"""FCMA data preparation.

Re-design of /root/reference/src/brainiak/fcma/preprocessing.py.  The
reference reads on rank 0 and broadcasts epoch-by-epoch over MPI
(preprocessing.py:210-229); in the single-controller JAX model every process
prepares host arrays directly and sharding happens when estimators place
data on a mesh, so the ``comm`` parameter disappears.

Epoch normalization runs on device
(:mod:`brainiak_tpu.ops.kernels.epoch_norm`: one jitted z-score
dispatch per distinct epoch shape, Pallas-tiled on TPU), retiring the
per-epoch host C++ ``native/epoch_norm`` round-trip that used to sit
on this ingest path; the NumPy fallback keeps toolchain-less hosts
working.
"""

import logging
from enum import Enum

import numpy as np
from scipy.stats import zscore

from ..image import mask_images, multimask_images
from ..ops.kernels.epoch_norm import normalize_epochs

logger = logging.getLogger(__name__)

__all__ = [
    "generate_epochs_info",
    "prepare_fcma_data",
    "prepare_mvpa_data",
    "prepare_searchlight_mvpa_data",
    "RandomType",
]


class RandomType(Enum):
    """Voxel-permutation null options (reference preprocessing.py:142-155):
    NORANDOM, REPRODUCIBLE (per-subject-index seed), UNREPRODUCIBLE."""
    NORANDOM = 0
    REPRODUCIBLE = 1
    UNREPRODUCIBLE = 2


def _randomize_single_subject(data, seed=None):
    """Shuffle the voxel dimension of [nVoxels, nTRs] data in place."""
    if seed is not None:
        np.random.seed(seed)
    np.random.shuffle(data)


def _randomize_subject_list(data_list, random):
    if random == RandomType.REPRODUCIBLE:
        for i, data in enumerate(data_list):
            _randomize_single_subject(data, seed=i)
    elif random == RandomType.UNREPRODUCIBLE:
        for data in data_list:
            _randomize_single_subject(data)


def _separate_epochs(activity_data, epoch_list):
    """Cut per-subject [nVoxels, nTRs] data into per-epoch [len, nVoxels]
    blocks, z-scored over time and scaled by 1/sqrt(len) so correlation is
    a plain matmul (reference preprocessing.py:41-92).

    Returns (raw_data list, labels list)."""
    raw_data = []
    labels = []
    for sid in range(len(epoch_list)):
        epoch = epoch_list[sid]
        for cond in range(epoch.shape[0]):
            sub_epoch = epoch[cond, :, :]
            for eid in range(epoch.shape[1]):
                r = np.sum(sub_epoch[eid, :])
                if r > 0:
                    mat = activity_data[sid][:, sub_epoch[eid, :] == 1]
                    raw_data.append(np.ascontiguousarray(
                        mat.T, dtype=np.float32))
                    labels.append(cond)
    # one device dispatch per distinct epoch shape (NumPy fallback
    # for tiny batches / forced-host operation)
    return normalize_epochs(raw_data), labels


def prepare_fcma_data(images, conditions, mask1, mask2=None,
                      random=RandomType.NORANDOM):
    """Mask images and cut them into normalized epochs for correlation
    analysis (reference preprocessing.py:156-232, sans MPI broadcast).

    Returns (raw_data1, raw_data2_or_None, labels)."""
    logger.info('start to apply masks and separate epochs')
    raw_data2 = None
    if mask2 is not None:
        activity_data1, activity_data2 = zip(
            *multimask_images(images, (mask1, mask2), np.float32))
        activity_data1 = list(activity_data1)
        activity_data2 = list(activity_data2)
        _randomize_subject_list(activity_data2, random)
        raw_data2, _ = _separate_epochs(activity_data2, conditions)
    else:
        activity_data1 = list(mask_images(images, mask1, np.float32))
    _randomize_subject_list(activity_data1, random)
    raw_data1, labels = _separate_epochs(activity_data1, conditions)
    return raw_data1, raw_data2, labels


def generate_epochs_info(epoch_list):
    """Flatten condition specs into (label, sid, start, end) tuples
    (reference preprocessing.py:235-271)."""
    epoch_info = []
    for sid, epoch in enumerate(epoch_list):
        for cond in range(epoch.shape[0]):
            sub_epoch = epoch[cond, :, :]
            for eid in range(epoch.shape[1]):
                r = np.sum(sub_epoch[eid, :])
                if r > 0:
                    start = np.nonzero(sub_epoch[eid, :])[0][0]
                    epoch_info.append((cond, sid, start, start + r))
    return epoch_info


def prepare_mvpa_data(images, conditions, mask):
    """Epoch-averaged, within-subject z-scored activity for MVPA
    (reference preprocessing.py:274-326).

    Returns (processed_data [num_voxels, num_epochs], labels)."""
    activity_data = list(mask_images(images, mask, np.float32))
    epoch_info = generate_epochs_info(conditions)
    num_epochs = len(epoch_info)
    d1, _ = activity_data[0].shape
    processed_data = np.empty([d1, num_epochs])
    labels = np.empty(num_epochs)
    subject_count = [0]
    cur_sid = -1
    for idx, epoch in enumerate(epoch_info):
        labels[idx] = epoch[0]
        if cur_sid != epoch[1]:
            subject_count.append(0)
            cur_sid = epoch[1]
        subject_count[-1] += 1
        processed_data[:, idx] = np.mean(
            activity_data[cur_sid][:, epoch[2]:epoch[3]], axis=1)
    cur_epoch = 0
    for i in subject_count:
        if i > 1:
            processed_data[:, cur_epoch:cur_epoch + i] = zscore(
                processed_data[:, cur_epoch:cur_epoch + i], axis=1, ddof=0)
        cur_epoch += i
    return np.nan_to_num(processed_data), labels


def prepare_searchlight_mvpa_data(images, conditions, data_type=np.float32,
                                  random=RandomType.NORANDOM):
    """Epoch-averaged, z-scored activity keeping the 3-D brain structure,
    processed subject by subject (reference preprocessing.py:328-414).

    Returns (processed_data [x, y, z, num_epochs], labels)."""
    epoch_info = generate_epochs_info(conditions)
    num_epochs = len(epoch_info)
    processed_data = None
    labels = np.empty(num_epochs)
    for idx, epoch in enumerate(epoch_info):
        labels[idx] = epoch[0]
    subject_count = np.zeros(len(conditions), dtype=np.int32)

    for sid, f in enumerate(images):
        data = f.get_fdata().astype(data_type)
        d1, d2, d3, d4 = data.shape
        if random != RandomType.NORANDOM:
            data = data.reshape((d1 * d2 * d3, d4))
            seed = sid if random == RandomType.REPRODUCIBLE else None
            _randomize_single_subject(data, seed=seed)
            data = data.reshape((d1, d2, d3, d4))
        if processed_data is None:
            processed_data = np.empty([d1, d2, d3, num_epochs],
                                      dtype=data_type)
        for idx, epoch in enumerate(epoch_info):
            if sid == epoch[1]:
                subject_count[sid] += 1
                processed_data[:, :, :, idx] = np.mean(
                    data[:, :, :, epoch[2]:epoch[3]], axis=3)

    cur_epoch = 0
    for i in subject_count:
        if i > 1:
            processed_data[:, :, :, cur_epoch:cur_epoch + i] = zscore(
                processed_data[:, :, :, cur_epoch:cur_epoch + i],
                axis=3, ddof=0)
        cur_epoch += i
    return np.nan_to_num(processed_data), labels
