"""Correlation-based voxel selection (FCMA stage 1), TPU-native.

Re-design of /root/reference/src/brainiak/fcma/voxelselector.py.  The
reference runs an MPI master-worker task farm handing 64-voxel blocks to
workers, each doing Cython sgemm + C++/OpenMP normalization + a
multiprocessing pool of sklearn SVC fits (voxelselector.py:176-282,
:284-516).  Here the whole per-block pipeline —

    per-epoch correlation (MXU einsum)
    -> Fisher-z within-subject normalization (fused elementwise)
    -> per-voxel linear-kernel Gram + magnitude shrink (batched matmul)
    -> batched dual-SVM k-fold cross validation (vmap)

— is ONE jitted XLA program; voxel blocks are a host loop (or sharded over
a mesh's ``voxel`` axis), and the dynamic master-worker load balancing
disappears because TPU chips are homogeneous.
"""

import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import profile as obs_profile
from ..obs import runtime as obs_runtime
from ..obs import spans as obs_spans
from ..ops import distla
from ..ops.correlation import resolve_precision
from ..ops.fisherz import within_subject_normalization
from ..ops.svm import svm_cv_accuracy
from ..parallel.compat import shard_map
from ..parallel.mesh import DEFAULT_VOXEL_AXIS
from jax.sharding import NamedSharding, PartitionSpec

logger = logging.getLogger(__name__)

__all__ = ["VoxelSelector"]


def _shrink(kernels):
    """The reference's magnitude shrink: scale so K[0,0] has at most 2
    integer digits for stable SVM duals (reference cython_blas.pyx
    compute_kernel_matrix + digit shrink, voxelselector.py:407-412)."""
    k00 = jnp.clip(kernels[:, 0, 0], 1.0, None)
    ndigits = jnp.floor(jnp.log10(k00)) + 1
    proportion = jnp.where(ndigits > 2, 10.0 ** (2 - ndigits), 1.0)
    return kernels * proportion[:, None, None]


def _gram_and_shrink(corr, precision=None):
    """Per-voxel linear-kernel Gram with the magnitude shrink."""
    kernels = jnp.einsum('bev,bfv->bef', corr, corr,
                         precision=resolve_precision(precision),
                         preferred_element_type=jnp.float32)
    return _shrink(kernels)


# the distla path's Grams come back raw (the psum-contraction program
# is FCMA-agnostic); one tiny jitted shrink applies the magnitude
# scaling without an eager per-element dispatch chain
_shrink_jit = jax.jit(_shrink)


@obs_runtime.counted_cache("fcma.sharded_gram")
def _sharded_gram_program(mesh, epochs_per_subj, interpret,
                          precision):
    """Mesh-sharded Pallas Gram program, built once per
    (mesh, config).  GSPMD cannot partition a pallas_call, so the
    Gram kernel runs per shard under shard_map; jit caches on
    function identity, so constructing the shard_map closure inside
    ``run()`` would rebuild (and retrace) it on every call.  Cache
    misses count as ``retrace_total{site=fcma.sharded_gram}``; with
    cost profiling active (BRAINIAK_TPU_OBS_PROFILE) the program's
    first run per shape captures a ``cost`` record under the same
    site, joined to ``fcma.block`` span durations by the report CLI.
    """
    return obs_profile.profile_program(jax.jit(shard_map(
        partial(_block_gram_pallas,
                epochs_per_subj=epochs_per_subj,
                interpret=interpret,
                precision=precision),
        mesh=mesh,
        in_specs=(PartitionSpec(None, None, DEFAULT_VOXEL_AXIS),
                  PartitionSpec()),
        out_specs=PartitionSpec(DEFAULT_VOXEL_AXIS, None, None),
        # pallas_call's out_shape carries no vma info
        check_vma=False)), "fcma.sharded_gram", span="fcma.block")


@obs_runtime.trace_signature("fcma.sharded_gram")
def _sharded_gram_trace_signature():
    from ..parallel.mesh import make_mesh

    mesh = make_mesh((DEFAULT_VOXEL_AXIS,), (-1,))
    e, t, v = 4, 5, 6
    b = mesh.shape[DEFAULT_VOXEL_AXIS]
    return [{"key": (mesh, 2, True, resolve_precision(None)),
             "args": (jax.ShapeDtypeStruct((e, t, b), jnp.float32),
                      jax.ShapeDtypeStruct((e, t, v), jnp.float32)),
             "mesh": mesh}]


@partial(jax.jit, static_argnames=("epochs_per_subj", "interpret",
                                   "precision"))
def _block_gram_pallas(blk, data2, epochs_per_subj, interpret=False,
                       precision=None):
    """Gram-only Pallas path: the [block, E, V] normalized-correlation
    tensor is reduced in VMEM and never reaches HBM (see
    :func:`brainiak_tpu.ops.pallas_kernels.fcma_gram`) — the SVM CV only
    needs the [block, E, E] kernels."""
    from ..ops.pallas_kernels import fcma_gram, pad_to_tiles

    n_b = blk.shape[2]
    blk_p, data_p, tile_b, tile_v, fits = pad_to_tiles(blk, data2)
    if not fits:
        # epoch x TR extent too large for VMEM tiles — use the XLA path
        return _block_gram_xla(blk, data2, epochs_per_subj,
                               precision=precision)
    kernels = fcma_gram(blk_p, data_p, epochs_per_subj, tile_b=tile_b,
                        tile_v=tile_v, interpret=interpret,
                        precision=precision)
    return _shrink(kernels[:n_b])


@partial(jax.jit, static_argnames=("epochs_per_subj", "interpret",
                                   "precision"))
def _block_kernel_matrices_pallas(blk, data2, epochs_per_subj,
                                  interpret=False, precision=None):
    """Pallas-fused variant of :func:`_block_kernel_matrices`: the
    correlation + Fisher-z + normalization tile never round-trips to HBM
    (see :mod:`brainiak_tpu.ops.pallas_kernels`)."""
    from ..ops.pallas_kernels import fcma_corr_normalize, pad_to_tiles

    n_b = blk.shape[2]
    n_v = data2.shape[2]
    blk_p, data_p, tile_b, tile_v, fits = pad_to_tiles(blk, data2)
    if not fits:
        # epoch x TR extent too large for VMEM tiles — use the XLA path
        return _block_kernel_matrices(blk, data2, epochs_per_subj,
                                      precision=precision)
    corr = fcma_corr_normalize(blk_p, data_p, epochs_per_subj,
                               tile_b=tile_b, tile_v=tile_v,
                               interpret=interpret, precision=precision)
    corr = corr[:n_b, :, :n_v]
    return _gram_and_shrink(corr, precision), corr


@partial(jax.jit, static_argnames=("epochs_per_subj", "precision"))
def _block_gram_xla(blk, data2, epochs_per_subj, precision=None):
    """Kernels-only XLA variant: not returning the [block, E, V]
    correlation tensor lets XLA fuse it away instead of shipping it out
    of the program for a caller that only needs the Grams."""
    corr = jnp.einsum('etb,etv->bev', blk, data2,
                      precision=resolve_precision(precision),
                      preferred_element_type=jnp.float32)
    corr = within_subject_normalization(corr, epochs_per_subj)
    return _gram_and_shrink(corr, precision)


# cost attribution for the unsharded Gram program (the sharded
# variant is profiled inside its builder above); under an ambient
# trace (_block_gram_pallas's VMEM-overflow fallback) the wrapper
# bypasses straight to the jitted function
_block_gram_xla = obs_profile.profile_program(
    _block_gram_xla, "fcma.block_gram", span="fcma.block")


@partial(jax.jit, static_argnames=("epochs_per_subj", "precision"))
def _block_kernel_matrices(blk, data2, epochs_per_subj, precision=None):
    """Correlate a voxel block against all voxels and build per-voxel SVM
    Gram matrices.

    blk : [E, T, block] the voxel block (sharded over a mesh's voxel axis
        when one is in use); data2 : [E, T, V] normalized epoch data.
    Returns (kernels [block, E, E], corr [block, E, V2]), both sharded
    over the leading (block) axis when ``blk`` is.
    """
    corr = jnp.einsum('etb,etv->bev', blk, data2,
                      precision=resolve_precision(precision),
                      preferred_element_type=jnp.float32)
    corr = within_subject_normalization(corr, epochs_per_subj)
    return _gram_and_shrink(corr, precision), corr


class VoxelSelector:
    """FCMA voxel selection by per-voxel correlation-pattern classification.

    Parameters (reference voxelselector.py:56-139)
    ----------
    labels : list/array of per-epoch condition labels
    epochs_per_subj : int (epochs of one subject are adjacent)
    num_folds : int, k for stratified CV
    raw_data : list of [epoch_len, n_voxels] normalized epoch arrays
        (from :func:`brainiak_tpu.fcma.preprocessing.prepare_fcma_data`)
    raw_data2 : optional second-mask epoch list for region×region FCMA
    voxel_unit : int, voxels per compiled block (default 256)
    mesh : optional jax.sharding.Mesh — blocks are additionally sharded
        over its ``voxel`` axis (the analog of adding MPI workers)
    svm_C, svm_iters : on-device dual-SVM hyperparameters.  The SMO step
        budget is ``svm_iters * n_epochs`` two-coordinate updates per
        dual.  Measured at the whole-brain bench config: the default
        (10) is bit-identical to a 50-iteration run on CPU fp32, and on
        a real v5e differs only by single near-boundary test samples on
        ~2% of voxels (max one sample per fold — the same noise band
        fp32 rounding already produces vs the sklearn f64 oracle).
        Each sequential SMO step is latency-bound, so CV wall time
        scales almost linearly with the budget; ``run`` checks the
        returned KKT gaps and warns when any dual needed more budget —
        raise ``svm_iters`` if that fires (or cross-check with
        ``ops.svm.svm_cv_accuracy(..., solver='ipm')``, the exact
        interior-point solver)
    use_distla : 'auto' | True | False — the pod-scale sharded-Gram
        path (:mod:`brainiak_tpu.ops.distla`): the "all voxels"
        operand is SHARDED over the mesh's voxel axis instead of
        replicated, each device contracts the block against its
        resident shard, and one psum completes the per-voxel Grams.
        'auto' engages it when replicating the stacked data2 would
        exceed ``replicated_budget_bytes`` — the whole-brain regime
        where the replicated path OOMs.  Requires ``mesh`` and the
        on-device SVM: under 'auto' a host-CV ``run(clf)`` falls
        back to the replicated layout for that call (with a
        warning); an explicit ``True`` raises instead.
    replicated_budget_bytes : per-device byte budget for replicating
        data2 under ``use_distla='auto'`` (default:
        :func:`brainiak_tpu.ops.distla.replicated_budget_bytes`).
    use_pallas : 'auto' (fused Pallas kernel on TPU) | True | False
    precision : 'highest' (fp32-equivalent, default) | 'high' (3-pass
        bf16 MXU, ~1e-3 correlation accuracy) | 'default', for the
        correlation/Gram matmuls.  Only the XLA paths
        (``use_pallas=False``) honor 'high': Mosaic lowers no 3-pass
        dot, so the Pallas kernels clamp it up to 'highest' (measured
        end-to-end on a v5e the two settings are within noise anyway —
        the pipeline is not MXU-bound at these epoch counts)
    """

    def __init__(self, labels, epochs_per_subj, num_folds, raw_data,
                 raw_data2=None, voxel_unit=256, mesh=None,
                 svm_C=1.0, svm_iters=10, process_num=None,
                 master_rank=0, use_pallas='auto', precision='highest',
                 use_distla='auto', replicated_budget_bytes=None):
        self.labels = np.asarray(labels)
        self.epochs_per_subj = epochs_per_subj
        self.num_folds = num_folds
        self.raw_data = raw_data
        self.raw_data2 = raw_data2
        self.voxel_unit = voxel_unit
        self.mesh = mesh
        self.svm_C = svm_C
        self.svm_iters = svm_iters
        # matmul precision for the correlation/Gram einsums: 'highest'
        # (fp32-equivalent, default) or 'high' (fewer bf16 MXU passes,
        # several-x throughput at ~1e-3 correlation accuracy) — the main
        # TPU throughput lever for voxel selection
        self.precision = resolve_precision(precision)
        # 'auto': the fused Pallas kernel on TPU, plain XLA elsewhere
        if use_pallas == 'auto':
            use_pallas = jax.default_backend() == 'tpu'
        self.use_pallas = bool(use_pallas)
        # process_num / master_rank accepted for API compatibility with the
        # reference's multiprocessing/MPI knobs; they have no effect here.
        self.num_voxels = raw_data[0].shape[1]
        self.num_voxels2 = raw_data2[0].shape[1] if raw_data2 is not None \
            else self.num_voxels
        if raw_data2 is not None and len(raw_data) != len(raw_data2):
            raise ValueError('The raw data lists must have the same number '
                             'of elements for computing the correlations '
                             'element by element')
        if self.num_voxels == 0 or self.num_voxels2 == 0:
            raise ValueError('Zero processed voxels')
        # distla (sharded-data2) path: decided at construction — the
        # input sizes are fixed here, and _stack()'s placement must
        # agree with the block-loop path in _run().  Whether the
        # engagement was automatic matters at run() time: the path
        # serves the on-device SVM only, and a budget-triggered auto
        # decision must degrade to the replicated path for host CV
        # instead of turning a previously-working call into an error.
        self._distla_auto = use_distla == 'auto'
        if use_distla == 'auto':
            budget = distla.replicated_budget_bytes() \
                if replicated_budget_bytes is None \
                else int(replicated_budget_bytes)
            data2_bytes = (len(raw_data) * raw_data[0].shape[0]
                           * self.num_voxels2 * 4)
            use_distla = mesh is not None and data2_bytes > budget
        elif use_distla and mesh is None:
            raise ValueError(
                "use_distla=True requires a mesh with a voxel axis "
                "(the sharded-Gram path shards data2 over it)")
        self.use_distla = bool(use_distla)

    def _stack(self):
        # cache the device-resident stack across run() calls — re-staging
        # ~100 MB of epoch data per call dominates wall time on a
        # tunneled device (the reference likewise keeps raw data resident
        # in worker memory across task assignments).  Keyed on the input
        # OBJECTS — the lists, their element arrays, and the mesh — held
        # alive in the key so an `is` match can never be a recycled id()
        # of a freed object.  Rebinding the lists OR replacing an element
        # (raw_data[0] = new_arr) invalidates; mutating an ndarray's
        # contents in place is not detected (no data hashing).
        def _key():
            elems = tuple(self.raw_data) + (
                tuple(self.raw_data2) if self.raw_data2 is not None
                else ())
            # use_distla participates: the auto path's host-CV
            # fallback flips it per run() call, and the sharded vs
            # replicated data2 placements must never be conflated
            return (self.raw_data, self.raw_data2, self.mesh,
                    self.use_distla) + elems

        key = _key()
        cached = getattr(self, "_stack_cache", None)
        if cached is not None and len(cached[0]) == len(key) and \
                all(a is b for a, b in zip(cached[0], key)):
            return cached[1]
        data1 = jnp.asarray(np.stack(self.raw_data),
                            dtype=jnp.float32)  # [E, T, V]
        if self.raw_data2 is not None:
            data2 = jnp.asarray(np.stack(self.raw_data2),
                                dtype=jnp.float32)
        else:
            data2 = data1
        if self.mesh is not None and self.use_distla:
            # distla path: data2 (the "all voxels" side) is SHARDED
            # over the voxel axis — the replicated-budget escape
            # hatch — zero-padded to the axis size (pad columns
            # normalize to zero and contribute nothing to the Gram);
            # blocks stay replicated and the contraction psums.
            n_shards = self.mesh.shape.get(DEFAULT_VOXEL_AXIS, 1)
            pad = (-data2.shape[2]) % n_shards
            if pad:
                data2 = jnp.pad(data2, ((0, 0), (0, 0), (0, pad)))
            data1 = jax.device_put(
                data1, NamedSharding(self.mesh, PartitionSpec()))
            data2 = jax.device_put(
                data2, NamedSharding(
                    self.mesh,
                    PartitionSpec(None, None, DEFAULT_VOXEL_AXIS)))
        elif self.mesh is not None:
            # data2 (the "all voxels" side) is replicated; each block of
            # data1 is sharded over the voxel axis below.
            data1 = jax.device_put(
                data1, NamedSharding(self.mesh, PartitionSpec()))
            data2 = jax.device_put(
                data2, NamedSharding(self.mesh, PartitionSpec()))
        self._stack_cache = (key, (data1, data2))
        return data1, data2

    def _slice_block(self, data1, start, block):
        """Take [E, T, block] starting at ``start`` (wrapping by tiling for
        a volume smaller than one block) and shard it over the mesh's
        voxel axis so correlation, Gram, and SVM CV all partition over
        the block dimension — the analog of handing the block to MPI
        workers (reference voxelselector.py:176-253)."""
        if self.num_voxels < block:
            reps = -(-block // self.num_voxels)
            blk = jnp.tile(data1, (1, 1, reps))[:, :, :block]
        else:
            blk = jax.lax.dynamic_slice_in_dim(data1, start, block, axis=2)
        if self.mesh is not None and not self.use_distla:
            # distla mode keeps the block replicated: the parallelism
            # is over data2's sharded voxel axis, not the block dim
            blk = jax.device_put(
                blk, NamedSharding(self.mesh,
                                   PartitionSpec(None, None,
                                                 DEFAULT_VOXEL_AXIS)))
        return blk

    def run(self, clf='svm'):
        """Score every voxel; returns [(voxel_id, accuracy)] sorted by
        accuracy descending (reference voxelselector.py:149-174).

        clf : 'svm' runs the batched on-device kernel-SVM CV; an sklearn
            estimator runs host cross-validation per voxel (parity path —
            SVC(kernel='precomputed') gets the Gram matrices, anything else
            gets raw correlation vectors).

        With :mod:`brainiak_tpu.obs` enabled the selection runs under a
        ``fcma.voxel_selection`` span with one ``fcma.block`` span per
        voxel block and a ``fcma.svm_cv`` span around the batched SMO
        solve; disabled (default) the spans are no-ops and introduce no
        host syncs — block dispatch stays fully asynchronous.
        """
        clf_label = clf if isinstance(clf, str) else type(clf).__name__
        with obs_spans.span("fcma.voxel_selection",
                            attrs={"clf": clf_label,
                                   "n_voxels": self.num_voxels}):
            return self._run(clf)

    def _run(self, clf):
        on_device_svm = isinstance(clf, str) and clf == 'svm'
        if self.use_distla and not on_device_svm:
            if not self._distla_auto:
                raise ValueError(
                    "the distla sharded-Gram path only supports the "
                    "on-device SVM (run('svm')); pass "
                    "use_distla=False for host cross-validation")
            # auto-engaged: the classifier is only known here.  Run
            # this call on the replicated path (the pre-distla
            # behavior — it may exceed the budget that triggered the
            # engagement) and restore the sharded path afterwards.
            logger.warning(
                "use_distla='auto' engaged (replicating data2 "
                "exceeds the budget) but host cross-validation "
                "needs the replicated layout; falling back for "
                "this run() call")
            self.use_distla = False
            try:
                return self._run(clf)
            finally:
                self.use_distla = True
        data1, data2 = self._stack()
        n_shards = 1
        if self.mesh is not None:
            n_shards = self.mesh.shape.get(DEFAULT_VOXEL_AXIS, 1)
        # distla mode parallelizes over data2's sharded voxel axis, so
        # the block extent is NOT multiplied by the shard count
        block = self.voxel_unit if self.use_distla \
            else self.voxel_unit * n_shards

        if self.use_pallas and on_device_svm and not self.use_distla:
            from ..ops.pallas_kernels import pick_tiles
            if pick_tiles(len(self.raw_data), self.raw_data[0].shape[0],
                          self.num_voxels, self.num_voxels2)[2]:
                # The fused Gram kernel never materializes the [B, E, V]
                # correlation tensor, so there is no memory reason to
                # block the voxel axis at all — one whole-volume dispatch
                # replaces num_voxels/voxel_unit round-trips of dispatch
                # latency (the [V, E, E] Grams are tiny; the kernel's
                # VMEM tiling is independent of the block extent).
                block = -(-self.num_voxels // n_shards) * n_shards

        # mesh + Pallas: the cached shard_map program (block shapes
        # are constant across iterations AND across run() calls, so
        # the builder is lru_cached at module scope — jaxlint JX001)
        sharded_gram = None
        if self.mesh is not None and self.use_pallas \
                and not self.use_distla:
            sharded_gram = _sharded_gram_program(
                self.mesh, self.epochs_per_subj,
                jax.default_backend() != 'tpu', self.precision)

        block_accs = []
        for start in range(0, self.num_voxels, block):
            # per-chunk span: times ENQUEUE, not compute — no sync
            # target on purpose, so observed runs keep the async block
            # pipeline (the compute lands in fcma.svm_cv, whose fetch
            # synchronizes); a no-op while obs is disabled
            with obs_spans.span("fcma.block",
                                attrs={"start": start}):
                cur = min(block, self.num_voxels - start)
                pad_start = min(start, self.num_voxels - block) \
                    if self.num_voxels >= block else 0
                offset = start - pad_start
                blk = self._slice_block(data1, pad_start, block)
                if self.use_distla and on_device_svm:
                    # sharded-data2 contraction (ops.distla): each
                    # device grams the block against its resident
                    # voxel shard; psum completes the kernels
                    kernels = _shrink_jit(distla.block_gram(
                        blk, data2, self.mesh, self.epochs_per_subj,
                        precision=self.precision))
                    corr = None
                elif self.use_pallas and on_device_svm:
                    # Gram-only fusion: the [block, E, V] tensor never
                    # round-trips through HBM
                    if sharded_gram is not None:
                        kernels = sharded_gram(blk, data2)
                    else:
                        kernels = _block_gram_pallas(
                            blk, data2, self.epochs_per_subj,
                            interpret=jax.default_backend() != 'tpu',
                            precision=self.precision)
                    corr = None
                elif on_device_svm:
                    kernels = _block_gram_xla(
                        blk, data2, self.epochs_per_subj,
                        precision=self.precision)
                    corr = None
                elif self.use_pallas and self.mesh is None:
                    kernels, corr = _block_kernel_matrices_pallas(
                        blk, data2, self.epochs_per_subj,
                        interpret=jax.default_backend() != 'tpu',
                        precision=self.precision)
                else:
                    # host-CV path (and any mesh-sharded non-svm path:
                    # a sharded block cannot feed a plain-jitted
                    # pallas_call, so use the partitionable XLA
                    # program)
                    kernels, corr = _block_kernel_matrices(
                        blk, data2, self.epochs_per_subj,
                        precision=self.precision)
                kernels = kernels[offset:offset + cur]
                if corr is not None:
                    corr = corr[offset:offset + cur]
                if on_device_svm:
                    # defer CV: collect the tiny [cur, E, E] Grams on
                    # device (blocks queue with no host sync) and solve
                    # ALL voxels' SVM duals in ONE batched SMO program
                    # after the loop — each SMO step is latency-bound,
                    # not FLOP-bound, so a 16x-larger problem batch
                    # costs nearly the same wall time as one block's
                    block_accs.append((start, cur, kernels))
                else:
                    accs = self._host_cv(clf, np.asarray(kernels),
                                         np.asarray(corr))
                    block_accs.append((start, cur, np.asarray(accs)))

        results = []
        if block_accs and on_device_svm:
            with obs_spans.span("fcma.svm_cv") as _svm_span:
                all_kernels = jnp.concatenate(
                    [k for _, _, k in block_accs])
                # svm_cv_accuracy fetches replicated: in a
                # multi-process run every process gets the full
                # per-voxel scores (the analog of the reference's MPI
                # score gather, voxelselector.py:208-238) — the fetch
                # synchronizes, so the span needs no explicit sync
                all_accs, gaps = svm_cv_accuracy(
                    all_kernels, self.labels, self.num_folds,
                    C=self.svm_C, n_iters=self.svm_iters,
                    return_gap=True)
                _svm_span.set("n_voxels", int(all_kernels.shape[0]))
            worst = float(np.max(gaps))
            if worst > 0.05:
                # Not libsvm's 1e-3 optimizer tolerance: measured on a
                # v5e, duals plateau near gap ~1e-2 for 10x the budget
                # while per-voxel accuracies stay within one test sample
                # of a converged run (boundary noise).  Gaps beyond ~5e-2
                # are where decision values start moving materially —
                # that is the silent-degradation regime worth flagging.
                logger.warning(
                    "SMO budget svm_iters=%d left %d/%d voxel duals "
                    "with a large KKT gap (worst %.2e); accuracies may "
                    "be degraded — raise svm_iters", self.svm_iters,
                    int(np.sum(gaps > 0.05)), len(gaps), worst)
            pos = 0
            for start, cur, _ in block_accs:
                results.extend((start + i, float(all_accs[pos + i]))
                               for i in range(cur))
                pos += cur
        else:
            # host-CV path: one fetch per block already happened
            for start, cur, accs in block_accs:
                results.extend(
                    (start + i, float(accs[i])) for i in range(cur))

        results.sort(key=lambda tup: tup[1], reverse=True)
        return results

    def _host_cv(self, clf, kernels, corr):
        """sklearn cross-validation parity path
        (reference voxelselector.py:41-53, :423-465)."""
        import sklearn.svm
        from sklearn import model_selection

        precomputed = isinstance(clf, sklearn.svm.SVC) and \
            clf.kernel == 'precomputed'
        data = kernels if precomputed else corr
        skf = model_selection.StratifiedKFold(n_splits=self.num_folds,
                                              shuffle=False)
        accs = np.empty(data.shape[0])
        for i in range(data.shape[0]):
            scores = model_selection.cross_val_score(
                clf, data[i], y=self.labels, cv=skf, n_jobs=1)
            accs[i] = scores.mean()
        return accs
