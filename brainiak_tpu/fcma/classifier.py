"""Correlation-based classification (FCMA stage 2), TPU-native.

Re-design of /root/reference/src/brainiak/fcma/classifier.py.  The feature
space is the flattened region1×region2 correlation pattern of each epoch;
the memory-bounded trick of accumulating the SVM Gram matrix voxel-portion
by voxel-portion without ever materializing the full correlation
(classifier.py:279-348) is kept, with each portion's
correlate→normalize→Gram-accumulate step as one jitted XLA program.
The final classifier fit runs on host sklearn — the Gram is only
[samples × samples].
"""

import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import sklearn
import sklearn.svm
from sklearn.base import BaseEstimator

from ..ops.correlation import PRECISION
from ..ops.fisherz import within_subject_normalization

logger = logging.getLogger(__name__)

__all__ = ["Classifier"]


@partial(jax.jit, static_argnames=("length", "norm_unit"))
def _chunk_features(x1, x2, start, length, norm_unit):
    """Correlation features for a voxel chunk of region 1 vs all of region 2.

    x1: [N, T, V1], x2: [N, T, V2] (already epoch-normalized)
    Returns [N, length, V2], within-subject normalized when norm_unit > 1.
    """
    blk = jax.lax.dynamic_slice_in_dim(x1, start, length, axis=2)
    corr = jnp.einsum('ntb,ntv->nbv', blk, x2, precision=PRECISION,
                      preferred_element_type=jnp.float32)
    if norm_unit > 1:
        n, b, v = corr.shape
        corr = within_subject_normalization(
            corr.reshape(1, n, b * v), norm_unit).reshape(n, b, v)
    return corr


@partial(jax.jit, static_argnames=("length", "norm_unit"))
def _chunk_gram_update(x1, x2, start, kernel, length, norm_unit):
    """Accumulate one voxel portion's contribution to the sample Gram."""
    corr = _chunk_features(x1, x2, start, length, norm_unit)
    feats = corr.reshape(corr.shape[0], -1)
    return kernel + jnp.matmul(feats, feats.T, precision=PRECISION), corr


class Classifier(BaseEstimator):
    """FCMA classifier over correlation features (reference
    classifier.py:37-690).

    Parameters
    ----------
    clf : an sklearn classifier; ``SVC(kernel='precomputed')`` activates the
        memory-bounded Gram path.
    num_processed_voxels : int, voxel-portion size for the Gram accumulation.
    epochs_per_subj : int, 0 disables within-subject normalization.
    """

    def __init__(self, clf, num_processed_voxels=2000, epochs_per_subj=0,
                 use_pallas='auto'):
        self.clf = clf
        self.num_processed_voxels = num_processed_voxels
        self.epochs_per_subj = epochs_per_subj
        self.num_digits_ = 0
        # 'auto': fused sample-Gram Pallas kernel on TPU when the
        # correlation features themselves are not needed
        if use_pallas == 'auto':
            use_pallas = jax.default_backend() == 'tpu'
        self.use_pallas = bool(use_pallas)

    # -- helpers ----------------------------------------------------------
    def _is_precomputed_svm(self):
        return isinstance(self.clf, sklearn.svm.SVC) and \
            self.clf.kernel == 'precomputed'

    @staticmethod
    def _stack_pairs(X):
        for x in X:
            assert len(x) == 2, \
                'there must be two parts for each correlation computation'
        X1, X2 = zip(*X)
        num_voxels1 = X1[0].shape[1]
        num_voxels2 = X2[0].shape[1]
        if num_voxels1 < num_voxels2:
            X1, X2 = X2, X1
            num_voxels1, num_voxels2 = num_voxels2, num_voxels1
        x1 = jnp.asarray(np.stack(X1), dtype=jnp.float32)
        x2 = jnp.asarray(np.stack(X2), dtype=jnp.float32)
        return x1, x2, num_voxels1, num_voxels2

    def _full_features(self, x1, x2, norm_unit):
        """Correlation features [N, V1*V2] computed in one portion."""
        corr = _chunk_features(x1, x2, 0, x1.shape[2], norm_unit)
        return np.asarray(corr).reshape(corr.shape[0], -1)

    def _pallas_sample_gram(self, x1, x2, norm_unit):
        """Fused in-VMEM sample Gram (no [N, V1*V2] feature matrix in
        HBM); returns the shrunk Gram, or None when the sample x TR
        extent exceeds the kernel's VMEM tiles."""
        from ..ops.pallas_kernels import fcma_sample_gram, pad_to_tiles

        x1_p, x2_p, tile_1, tile_2, fits = pad_to_tiles(x1, x2)
        if not fits:
            return None
        kernel = np.array(fcma_sample_gram(
            x1_p, x2_p, norm_unit, tile_1=tile_1, tile_2=tile_2,
            interpret=jax.default_backend() != 'tpu'))
        return self._digit_shrink(kernel)

    def _digit_shrink(self, kernel):
        """The reference's magnitude shrink, recorded in num_digits_
        so test similarity vectors scale identically
        (reference classifier.py:343-347)."""
        num_digits = len(str(int(kernel[0, 0])))
        self.num_digits_ = num_digits
        if num_digits > 2:
            kernel *= 10 ** (2 - num_digits)
        return kernel

    def _portioned_gram(self, x1, x2, norm_unit):
        """Gram matrix accumulated portion by portion
        (reference classifier.py:279-348)."""
        n = x1.shape[0]
        v1 = x1.shape[2]
        kernel = jnp.zeros((n, n), dtype=jnp.float32)
        last_corr = None
        portion = min(self.num_processed_voxels, v1)
        sr = 0
        while sr < v1:
            length = min(portion, v1 - sr)
            kernel, last_corr = _chunk_gram_update(
                x1, x2, sr, kernel, length, norm_unit)
            sr += length
        kernel = self._digit_shrink(np.array(kernel))
        # last_corr stays on device; only the single-portion fit path (which
        # stores training_data_) pays the host transfer.
        return kernel, last_corr

    # -- sklearn API ------------------------------------------------------
    def fit(self, X, y, num_training_samples=None):
        """Train on correlation features of (region1, region2) pairs
        (reference classifier.py:426-505)."""
        assert len(X) == len(y), \
            'the number of samples must be equal to the number of labels'
        x1, x2, num_voxels1, num_voxels2 = self._stack_pairs(X)
        if not self._is_precomputed_svm() and \
                num_training_samples is not None:
            num_training_samples = None
            logger.warning(
                'num_training_samples should not be set for classifiers '
                'other than SVM with precomputed kernels')
        self.num_voxels_ = num_voxels1
        self.num_features_ = num_voxels1 * num_voxels2
        self.num_samples_ = len(X)
        norm_unit = self.epochs_per_subj

        if not self._is_precomputed_svm():
            data = self._full_features(x1, x2, norm_unit)
            self.training_data_ = None
        else:
            if self.num_processed_voxels < self.num_voxels_:
                if num_training_samples is None:
                    raise RuntimeError(
                        'the kernel matrix will be computed portion by '
                        'portion, the test samples must be predefined by '
                        'specifying num_training_samples')
                if num_training_samples >= self.num_samples_:
                    raise ValueError('the number of training samples '
                                     'must be smaller than '
                                     'the number of total samples')
                data = None
                if self.use_pallas:
                    # features are discarded on this path, so the fused
                    # sample-Gram kernel applies
                    data = self._pallas_sample_gram(x1, x2, norm_unit)
                if data is None:
                    data, _ = self._portioned_gram(x1, x2, norm_unit)
                self.training_data_ = None
            else:
                data, corr = self._portioned_gram(x1, x2, norm_unit)
                self.training_data_ = np.asarray(corr).reshape(
                    self.num_samples_, self.num_features_)

        if num_training_samples is not None:
            self.test_raw_data_ = None
            self.test_data_ = data[num_training_samples:,
                                   0:num_training_samples]
            data = data[0:num_training_samples, 0:num_training_samples]
        else:
            self.test_raw_data_ = None
            self.test_data_ = None
        self.clf = self.clf.fit(data, y[0:num_training_samples])
        return self

    def _prepare_test_data(self, X):
        x1, x2, num_voxels1, num_voxels2 = self._stack_pairs(X)
        assert self.num_features_ == num_voxels1 * num_voxels2, \
            'the number of features does not match the model'
        num_test_samples = len(X)
        self.test_raw_data_ = X
        feats = self._full_features(x1, x2, num_test_samples)
        if self._is_precomputed_svm():
            assert self.training_data_ is not None, \
                'when using precomputed kernel of SVM, ' \
                'all training data must be provided'
            data = feats @ self.training_data_.T
            if self.num_digits_ > 2:
                data *= 10 ** (2 - self.num_digits_)
        else:
            data = feats
        self.test_data_ = data

    def _require_test_data(self, method):
        """X=None is only valid when fit() precomputed test
        similarity vectors (num_training_samples with a precomputed
        SVM kernel); otherwise sklearn would fail opaquely deep in
        ``clf.{method}`` on the None."""
        if getattr(self, "test_data_", None) is None:
            raise ValueError(
                f"{method}(X=None) requires test data prepared "
                "during fit (pass num_training_samples with a "
                "precomputed-kernel SVM), or pass X explicitly")

    def predict(self, X=None):
        """Predict labels; X=None reuses test data prepared during fit
        (reference classifier.py:507-570)."""
        if X is not None:
            self._prepare_test_data(X)
        else:
            self._require_test_data("predict")
        return self.clf.predict(self.test_data_)

    def _is_equal_to_test_raw_data(self, X):
        if self.test_raw_data_ is None or \
                len(X) != len(self.test_raw_data_):
            return False
        for new, old in zip(X, self.test_raw_data_):
            if not np.array_equal(new[0], old[0]) or \
                    not np.array_equal(new[1], old[1]):
                return False
        return True

    def decision_function(self, X=None):
        """Decision values (reference classifier.py:597-650)."""
        if X is not None and not self._is_equal_to_test_raw_data(X):
            self._prepare_test_data(X)
        elif X is None:
            self._require_test_data("decision_function")
        return self.clf.decision_function(self.test_data_)

    def score(self, X, y, sample_weight=None):
        """Mean accuracy; X is ignored when the Gram was portioned and test
        similarity vectors were precomputed in fit
        (reference classifier.py:652-690)."""
        from sklearn.metrics import accuracy_score
        if self._is_precomputed_svm() and self.training_data_ is None:
            return accuracy_score(y, self.predict(),
                                  sample_weight=sample_weight)
        return accuracy_score(y, self.predict(X),
                              sample_weight=sample_weight)
