"""Full Correlation Matrix Analysis (FCMA), TPU-native.

Correlation-based voxel selection and classification where the reference's
Cython BLAS + C++/OpenMP + MPI master-worker pipeline
(/root/reference/src/brainiak/fcma/) becomes fused XLA/Pallas kernels sharded
over a device mesh."""
