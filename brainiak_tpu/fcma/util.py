"""Public FCMA correlation routines (host-friendly API).

Re-design of /root/reference/src/brainiak/fcma/util.py: the normalize +
BLAS-sgemm pipeline is one jitted XLA computation on TPU
(:mod:`brainiak_tpu.ops.correlation`).
"""

import numpy as np

from ..ops import correlation as _corr_ops

__all__ = ["compute_correlation"]


def compute_correlation(matrix1, matrix2, return_nans=False):
    """Pearson correlation of the rows of matrix1 with the rows of matrix2.

    Accepts [r1, c] and [r2, c] arrays; returns float32 [r1, r2].
    Rows with zero variance yield 0 (or NaN when ``return_nans``).
    Contract: reference fcma/util.py:63-134.
    """
    matrix1 = np.asarray(matrix1)
    matrix2 = np.asarray(matrix2)
    if matrix1.ndim != 2 or matrix2.ndim != 2:
        raise ValueError("Input matrices must be 2D")
    if matrix1.shape[1] != matrix2.shape[1]:
        raise ValueError('Dimension discrepancy')
    return np.asarray(
        _corr_ops.compute_correlation(matrix1, matrix2,
                                      return_nans=return_nans))
