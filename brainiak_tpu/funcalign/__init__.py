"""Multi-subject functional alignment (SRM family), TPU-native.

The reference's MPI EM loops (/root/reference/src/brainiak/funcalign/) become
pure jitted JAX functions over stacked subject arrays, sharded over a device
mesh with XLA-inserted collectives."""
