"""FastSRM: atlas-accelerated deterministic SRM, TPU-native.

Re-design of /root/reference/src/brainiak/funcalign/fastsrm.py.  Pipeline
(reference fastsrm.py:592-1053): (1) optionally project each subject's data
onto an atlas (deterministic label averaging or probabilistic pseudo-inverse),
(2) run deterministic SRM in the reduced space on session-concatenated data,
(3) recover full-resolution per-subject bases from the SVD of
(shared response)ᵀ·(full data), (4) transform/inverse-transform via those
bases.  Data may be arrays or ``.npy`` paths; ``temp_dir``/``low_ram``
spill intermediates to disk; sessions may differ in length.

The reduced-space SRM is the jitted :class:`~brainiak_tpu.funcalign.srm.DetSRM`
program; basis SVDs and projections are jitted jnp ops.  ``n_jobs``
parallelizes only the host-side load+reduce stage over subjects with
joblib threads (useful for .npy path datasets where IO dominates); the
device math needs no process pool.
"""

import logging
import os
import uuid

import jax.numpy as jnp
import numpy as np
from sklearn.base import BaseEstimator, TransformerMixin
from sklearn.exceptions import NotFittedError

from .srm import DetSRM, _procrustes

logger = logging.getLogger(__name__)

__all__ = ["FastSRM"]


def _safe_load(data):
    if isinstance(data, str):
        return np.load(data)
    if hasattr(data, "load"):  # data.store.SubjectRef
        return data.load()
    return np.asarray(data)


def _canonicalize_imgs(imgs):
    """Accepts: array of paths [n_subjects, n_sessions]; list of arrays
    (one session each); list of lists of arrays/paths; or a
    :class:`~brainiak_tpu.data.store.SubjectStore` (one session per
    subject, ingested lazily through
    :class:`~brainiak_tpu.data.store.SubjectRef` handles).  Returns a
    list of lists: imgs[subject][session] (reference
    fastsrm.py:383-447)."""
    from ..data.store import SubjectStore

    if isinstance(imgs, SubjectStore):
        return [[imgs.ref(i)] for i in range(imgs.n_subjects)]
    if isinstance(imgs, np.ndarray) and imgs.dtype.kind in ("U", "S", "O") \
            and imgs.ndim == 2:
        return [[imgs[i, j] for j in range(imgs.shape[1])]
                for i in range(imgs.shape[0])]
    if isinstance(imgs, (list, tuple)):
        if len(imgs) == 0:
            raise ValueError("imgs is empty")
        if isinstance(imgs[0], (list, tuple)):
            return [list(subj) for subj in imgs]
        return [[subj] for subj in imgs]
    raise ValueError("imgs must be a list of arrays, a list of lists of "
                     "arrays, a 2D array of paths, or a SubjectStore")


def _shape_of(img):
    if isinstance(img, str):
        return np.load(img, mmap_mode="r").shape
    if hasattr(img, "iter_voxel_chunks"):  # SubjectRef: manifest shape
        return img.shape
    return np.asarray(img).shape


#: Voxel rows per streamed ingestion slab (:func:`_iter_voxel_chunks`).
REDUCE_CHUNK_VOXELS = 8192


def _iter_voxel_chunks(img, chunk_voxels=None):
    """Yield ``(start_row, block)`` voxel slabs of ``img`` without
    loading it whole: ``.npy`` paths are served off a read-only
    memmap (only the touched rows hit host memory),
    :class:`SubjectRef` handles stream from their store, and
    in-memory arrays are sliced in place — the ingestion primitive
    behind the streamed atlas reduction."""
    chunk = int(chunk_voxels or REDUCE_CHUNK_VOXELS)
    if hasattr(img, "iter_voxel_chunks"):
        yield from img.iter_voxel_chunks(chunk)
        return
    data = np.load(img, mmap_mode="r") if isinstance(img, str) \
        else np.asarray(img)
    for start in range(0, data.shape[0], chunk):
        # memmap slab -> host copy; no device is involved here
        block = np.asarray(data[start:start + chunk])  # jaxlint: disable=JX002
        yield start, block


def _check_imgs_consistency(imgs, atlas, n_components):
    """Shape validation mirroring the reference's check_imgs/check_atlas
    layer (reference fastsrm.py:256-446): every subject needs the same
    voxel count, sessions must agree in timeframes across subjects, the
    atlas must cover the data voxels with more regions than components,
    and the total timeframe count must reach n_components.  Only array
    shapes are touched (paths are probed with mmap, the raw atlas is
    inspected BEFORE any pseudo-inverse is built), so this stays cheap
    for on-disk datasets."""
    shapes = [[_shape_of(img) for img in subj] for subj in imgs]
    for i, subj in enumerate(shapes):
        for j, shp in enumerate(subj):
            if len(shp) != 2:
                raise ValueError(
                    f"imgs[{i}][{j}] should have exactly 2 axes "
                    f"(voxels, timeframes); got shape {shp}")
            if shp[0] != shapes[0][0][0]:
                raise ValueError(
                    f"imgs[{i}][{j}] has {shp[0]} voxels whereas "
                    f"imgs[0][0] has {shapes[0][0][0]}; all subjects "
                    "must share the voxel space")
            if shp[1] != shapes[0][j][1]:
                raise ValueError(
                    f"imgs[{i}][{j}] has {shp[1]} timeframes whereas "
                    f"imgs[0][{j}] has {shapes[0][j][1]}; sessions must "
                    "have the same length across subjects")
    n_voxels = shapes[0][0][0]
    total_t = sum(shp[1] for shp in shapes[0])
    if n_components is not None and total_t < n_components:
        raise ValueError(
            f"Total number of timeframes ({total_t}) is shorter than "
            f"the number of components ({n_components})")
    if atlas is not None:
        atlas = np.asarray(atlas)
        if atlas.ndim == 2:  # probabilistic [n_supervoxels, n_voxels]
            atlas_voxels, n_regions = atlas.shape[1], atlas.shape[0]
        else:
            atlas_voxels = len(atlas)
            n_regions = len(np.setdiff1d(np.unique(atlas), [0]))
        if atlas_voxels != n_voxels:
            raise ValueError(
                f"Atlas has {atlas_voxels} voxels but data have "
                f"{n_voxels}")
        if n_components is not None and n_regions <= n_components:
            raise ValueError(
                f"Atlas has {n_regions} regions which must exceed the "
                f"number of components ({n_components})")


def _check_indexes(indexes, n_max, name):
    """Index-list validation (reference fastsrm.py:103-113, 448-454)."""
    for idx in indexes:
        if not 0 <= int(idx) < n_max:
            raise ValueError(
                f"Index {int(idx)} of {name} is out of range "
                f"(0..{n_max - 1})")


def _reduce_one(data, atlas, inv_atlas, chunk_voxels=None):
    """Project [n_voxels, n_timeframes] data to the reduced space;
    returns [n_timeframes, n_supervoxels] (reference
    fastsrm.py:592-675).

    Ingestion STREAMS for lazy inputs: ``data`` may be an array, an
    ``.npy`` path, or a :class:`~brainiak_tpu.data.store.SubjectRef`.
    Path/store-backed subjects accumulate voxel slab by voxel slab
    (:func:`_iter_voxel_chunks`), so they are never fully
    host-resident — the [T, n_supervoxels] output is the only
    full-size allocation (float64 accumulators, cast back to the
    input's result type, matching the eager formulation to
    rounding).  In-memory arrays keep the original one-dispatch
    device formulation — chunking an already-resident operand would
    only trade the accelerator matmul for host BLAS.  An explicit
    ``chunk_voxels`` forces the streamed path (tests pin the
    chunked math against the eager one with it)."""
    lazy = isinstance(data, str) or hasattr(data, "iter_voxel_chunks")
    if inv_atlas is None and atlas is None:
        return _safe_load(data).T
    if not lazy and chunk_voxels is None:
        data_t = np.asarray(data).T  # [T, V]
        if inv_atlas is not None:
            return np.asarray(jnp.asarray(data_t)
                              @ jnp.asarray(inv_atlas))
        values = np.unique(atlas)
        values = values[values != 0]
        return np.stack([data_t[:, atlas == c].mean(axis=1)
                         for c in values], axis=1)
    n_voxels, n_frames = _shape_of(data)
    if inv_atlas is not None:
        inv_atlas = np.asarray(inv_atlas)
        out = np.zeros((n_frames, inv_atlas.shape[1]))
        for start, block in _iter_voxel_chunks(data, chunk_voxels):
            out += block.T.astype(np.float64) \
                @ inv_atlas[start:start + block.shape[0]]
        return out.astype(np.result_type(block.dtype,
                                         inv_atlas.dtype), copy=False)
    atlas = np.asarray(atlas)
    values = np.unique(atlas)
    values = values[values != 0]
    sums = np.zeros((n_frames, len(values)))
    counts = np.array([np.count_nonzero(atlas == c) for c in values],
                      dtype=np.float64)
    for start, block in _iter_voxel_chunks(data, chunk_voxels):
        onehot = (atlas[start:start + block.shape[0], None]
                  == values[None, :]).astype(np.float64)
        sums += block.T.astype(np.float64) @ onehot
    return (sums / counts).astype(block.dtype, copy=False)


class FastSRM(BaseEstimator, TransformerMixin):
    """FastSRM (reference fastsrm.py:1252-1767).

    Parameters
    ----------
    atlas : None, [n_voxels] deterministic labels (0 = ignore), or
        [n_supervoxels, n_voxels] probabilistic atlas
    n_components : int
    n_iter : int, reduced-space SRM iterations
    temp_dir : str or None — spill bases/reduced data as .npy
    low_ram : bool — with temp_dir, keep intermediates on disk
    seed : int
    n_jobs : joblib threads for the host-side load+reduce stage over
        subjects (IO-bound for .npy path datasets); device math is
        unaffected
    aggregate : 'mean' or None — transform returns the subject mean or
        per-subject projections
    """

    def __init__(self, atlas=None, n_components=20, n_iter=100,
                 temp_dir=None, low_ram=False, seed=0, n_jobs=1,
                 verbose="warn", aggregate="mean"):
        if aggregate is not None and aggregate != "mean":
            raise ValueError("aggregate can have only value mean or None")
        self.atlas = atlas
        self.n_components = n_components
        self.n_iter = n_iter
        self.low_ram = low_ram
        self.seed = seed
        self.n_jobs = n_jobs
        self.verbose = verbose
        self.aggregate = aggregate
        self.basis_list = None
        if temp_dir is None:
            self.temp_dir = None
            self.low_ram = False
        else:
            self.temp_dir = os.path.join(temp_dir,
                                         "fastsrm" + str(uuid.uuid4()))

    # -- internals --------------------------------------------------------
    def _atlas_parts(self):
        if self.atlas is None:
            return None, None
        atlas = np.asarray(self.atlas)
        if atlas.ndim == 2:
            return None, np.linalg.pinv(atlas)  # probabilistic
        return atlas, None

    def _maybe_spill(self, array, name, bases=False):
        # bases spill whenever temp_dir is set; reduced data only under
        # low_ram (reference fastsrm.py:592-676, :879-923)
        if self.temp_dir is not None and (bases or self.low_ram):
            os.makedirs(self.temp_dir, exist_ok=True)
            path = os.path.join(self.temp_dir, name + ".npy")
            np.save(path, array)
            return path
        return array

    def clean(self):
        """Remove temporary files (reference fastsrm.py:1368-1381)."""
        if self.temp_dir is not None and os.path.exists(self.temp_dir):
            for f in os.listdir(self.temp_dir):
                os.remove(os.path.join(self.temp_dir, f))
            os.rmdir(self.temp_dir)

    def _compute_basis(self, subject_sessions, shared_sessions):
        """Basis [n_components, n_voxels] from SVD of Σ_j S_jᵀ X_j
        (reference fastsrm.py:857-952).  Path/store-backed sessions
        accumulate the correlation voxel slab by voxel slab through
        :func:`_iter_voxel_chunks` (the [K, V] accumulator is the
        working set); in-memory arrays keep the one-dispatch device
        matmul."""
        corr = None
        for img, shared in zip(subject_sessions, shared_sessions):
            if isinstance(img, str) \
                    or hasattr(img, "iter_voxel_chunks"):
                n_voxels = _shape_of(img)[0]
                c = np.zeros((shared.shape[1], n_voxels))
                for start, block in _iter_voxel_chunks(img):
                    c[:, start:start + block.shape[0]] = \
                        (block @ shared).T  # block: [v, T]
            else:
                data = np.asarray(img)  # [V, T]
                c = np.asarray(jnp.asarray(shared.T)
                               @ jnp.asarray(data.T))
            corr = c if corr is None else corr + c
        basis = np.asarray(_procrustes(jnp.asarray(corr)))
        return basis

    # -- API --------------------------------------------------------------
    def fit(self, imgs, checkpoint_dir=None, checkpoint_every=5):
        """Fit bases from multi-subject (multi-session) data
        (reference fastsrm.py:1383-1466).

        With ``checkpoint_dir``, the iterative stage (the reduced-space
        deterministic SRM) checkpoints every ``checkpoint_every``
        iterations under the resilience guard and resumes after
        preemption; the surrounding projection/SVD stages are
        single-dispatch and recomputed deterministically.

        Example
        -------
        >>> fsrm = FastSRM(n_components=10, n_iter=100)
        >>> fsrm.fit(imgs, checkpoint_dir="/ckpts/fast1")  # resumable
        """
        imgs = _canonicalize_imgs(imgs)
        n_subjects = len(imgs)
        if n_subjects <= 1:
            raise ValueError("There are not enough subjects in the input "
                             "data to train the model.")
        n_sessions = len(imgs[0])
        for subj in imgs:
            if len(subj) != n_sessions:
                raise ValueError("All subjects must have the same number "
                                 "of sessions")

        _check_imgs_consistency(imgs, self.atlas, self.n_components)
        atlas, inv_atlas = self._atlas_parts()

        def reduce_subject(i):
            # hand _reduce_one the RAW entry (array, path, or
            # SubjectRef): lazy inputs then reduce voxel-slab by
            # voxel-slab off disk instead of loading eagerly
            return [self._maybe_spill(
                _reduce_one(imgs[i][j], atlas, inv_atlas),
                f"reduced_{i}_{j}") for j in range(n_sessions)]

        if self.n_jobs not in (None, 1):
            from joblib import Parallel, delayed

            # threads: the work is IO + NumPy/jnp releasing the GIL
            reduced = Parallel(n_jobs=self.n_jobs, prefer="threads")(
                delayed(reduce_subject)(i) for i in range(n_subjects))
        else:
            reduced = [reduce_subject(i) for i in range(n_subjects)]

        # Reduced-space deterministic SRM on session-concatenated data
        # (reference fast_srm, fastsrm.py:955-1021).
        first_subj = [_safe_load(r) for r in reduced[0]]
        session_lengths = [r.shape[0] for r in first_subj]
        X = [np.concatenate(first_subj, axis=0).T] + \
            [np.concatenate([_safe_load(r) for r in subj], axis=0).T
             for subj in reduced[1:]]
        srm = DetSRM(n_iter=self.n_iter, features=self.n_components,
                     rand_seed=self.seed)
        # the reduced-space SRM is the preemption-prone iterative stage;
        # forward the checkpoint contract so it runs under the
        # resilient loop (guard + rollback + resume)
        srm.fit(X,
                checkpoint_dir=None if checkpoint_dir is None else
                os.path.join(checkpoint_dir, "reduced_srm"),
                checkpoint_every=checkpoint_every)
        concatenated_s = np.mean(
            [s for s in srm.transform(X)], axis=0).T  # [T_total, K]
        shared_sessions = []
        start = 0
        for length in session_lengths:
            shared_sessions.append(concatenated_s[start:start + length])
            start += length

        # Full-resolution bases from the original data.
        self.basis_list = []
        for i in range(n_subjects):
            basis = self._compute_basis(imgs[i], shared_sessions)
            self.basis_list.append(
                self._maybe_spill(basis, f"basis_{i}", bases=True))
        return self

    def _check_against_basis(self, imgs):
        """Transform-time shape validation against the fitted basis
        voxel space (reference fastsrm.py:383-446 applies the same check
        layer on transform inputs)."""
        n_voxels = _safe_load(self.basis_list[0]).shape[1]
        for i, subj in enumerate(imgs):
            for j, img in enumerate(subj):
                shp = _shape_of(img)
                if len(shp) != 2 or shp[0] != n_voxels:
                    raise ValueError(
                        f"imgs[{i}][{j}] has shape {shp} but the fitted "
                        f"bases expect ({n_voxels}, n_timeframes)")

    def transform(self, imgs, subjects_indexes=None):
        """Project data into shared space (reference
        fastsrm.py:1513-1596)."""
        if self.basis_list is None:
            raise NotFittedError("The model fit has not been run yet.")
        imgs = _canonicalize_imgs(imgs)
        if subjects_indexes is None:
            subjects_indexes = list(range(len(imgs)))
        _check_indexes(subjects_indexes, len(self.basis_list),
                       "subjects_indexes")
        if len(imgs) != len(subjects_indexes):
            raise ValueError(
                f"imgs has {len(imgs)} subjects but subjects_indexes "
                f"has {len(subjects_indexes)} entries; they must match")
        self._check_against_basis(imgs)
        n_sessions = len(imgs[0])

        per_subject = []
        for pos, i in enumerate(subjects_indexes):
            basis = _safe_load(self.basis_list[i])
            sessions = [np.asarray(jnp.asarray(basis)
                                   @ jnp.asarray(_safe_load(
                                       imgs[pos][j])))
                        for j in range(n_sessions)]
            per_subject.append(sessions)

        if self.aggregate == "mean":
            out = [np.mean([subj[j] for subj in per_subject], axis=0)
                   for j in range(n_sessions)]
            return out[0] if n_sessions == 1 else out
        if n_sessions == 1:
            return [subj[0] for subj in per_subject]
        return per_subject

    def fit_transform(self, imgs, subjects_indexes=None):
        self.fit(imgs)
        return self.transform(imgs, subjects_indexes=subjects_indexes)

    def inverse_transform(self, shared_response, subjects_indexes=None,
                          sessions_indexes=None):
        """Reconstruct voxel-space data: basisᵀ · shared
        (reference fastsrm.py:1598-1679)."""
        if self.basis_list is None:
            raise NotFittedError("The model fit has not been run yet.")
        if subjects_indexes is None:
            subjects_indexes = list(range(len(self.basis_list)))
        _check_indexes(subjects_indexes, len(self.basis_list),
                       "subjects_indexes")
        single_session = isinstance(shared_response, np.ndarray)
        shared = [shared_response] if single_session else \
            list(shared_response)
        if sessions_indexes is None:
            sessions_indexes = list(range(len(shared)))
        _check_indexes(sessions_indexes, len(shared), "sessions_indexes")

        data = []
        for i in subjects_indexes:
            basis = _safe_load(self.basis_list[i])
            if single_session:
                data.append(basis.T @ shared[0])
            else:
                data.append([basis.T @ shared[j]
                             for j in sessions_indexes])
        return data

    def add_subjects(self, imgs, shared_response):
        """Fit bases for additional subjects against an existing shared
        response (reference fastsrm.py:1681-1766)."""
        if self.basis_list is None:
            self.basis_list = []
        imgs = _canonicalize_imgs(imgs)
        if self.basis_list:
            self._check_against_basis(imgs)
        single = isinstance(shared_response, np.ndarray)
        shared = [shared_response.T] if single else \
            [s.T for s in shared_response]
        for subj in imgs:
            basis = self._compute_basis(subj, shared)
            self.basis_list.append(
                self._maybe_spill(basis, f"basis_{len(self.basis_list)}",
                                  bases=True))
        return self
