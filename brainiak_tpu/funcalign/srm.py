"""Probabilistic and deterministic Shared Response Model (SRM), TPU-native.

Re-design of /root/reference/src/brainiak/funcalign/srm.py.  The model is
X_i ≈ W_i S with orthonormal per-subject maps W_i; the probabilistic variant
adds a Normal prior S ~ N(0, Σ_s) and per-subject noise ρ_i².

TPU-first architecture
----------------------
The reference distributes subjects over MPI ranks and stitches the EM loop
together with reduce/bcast/allreduce (srm.py:483-623).  Here the whole EM
loop is ONE jitted function over a stacked ``[subjects, voxels, TRs]`` array:

- subjects with differing voxel counts are zero-padded to a common voxel
  dimension — exact for every EM quantity (QR of a zero-padded matrix has
  zero rows; SVD of A with zero rows yields W with zero rows; traces and
  inner products are unaffected; per-subject voxel counts enter ρ² and the
  log-likelihood explicitly);
- placing the stacked array on a ``('subject',)``-sharded
  :class:`jax.sharding.Mesh` makes XLA insert the psum for
  ``Σ_i W_iᵀX_i/ρ_i²`` (the reference's comm.reduce at srm.py:571) and
  replicate the small Σ_s updates — no rank-0 special-casing;
- the per-iteration loop is a ``lax.fori_loop``, so the full fit is a single
  XLA program (one compile, no host round-trips per iteration).
"""

import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec
from sklearn.base import BaseEstimator, TransformerMixin
from sklearn.exceptions import NotFittedError
from sklearn.utils import assert_all_finite

from ..obs import profile as obs_profile
from ..ops import distla
from ..parallel.mesh import (DEFAULT_SUBJECT_AXIS, fetch_replicated,
                             place_on_mesh)
from ..resilience.guards import (array_digest, check_state,
                                 make_device_carry_chunk,
                                 run_resilient_loop)

__all__ = ["SRM", "DetSRM", "load"]

logger = logging.getLogger(__name__)

# Polar-factor algorithm for the tall W update: "eigh" (Gram
# eigendecomposition — exact, default) or "ns" (matmul-only
# Newton-Schulz — for accelerators where batched small eigh lowers to
# long sequential loops; see _polar_ns).  Baked in at TRACE time: the
# jitted EM programs do not key their cache on this flag, so flip it
# before the first fit in the process (or call jax.clear_caches()).
POLAR_METHOD = "eigh"


def _polar_ns(ap, n_iters=24):
    """Matmul-only polar factor via the coupled Newton-Schulz iteration
    on the K x K Gram: ``Y -> c^(1/2)``, ``Z -> c^(-1/2)`` for
    ``c = apᵀap / s`` (``s`` a row-sum bound on the spectral radius),
    then ``W = ap (c/s)^(-1/2) / sqrt(s)``.

    An alternative to the Gram-eigh path for accelerators where batched
    small-matrix eigh lowers to long sequential loops: every operation
    here is a K x K matmul.  Severely rank-deficient inputs (RSRM's
    perturbation=0 regime) should keep the eigh path.  The caller's
    Newton-Schulz orthogonality scrub runs after either path.

    Accuracy (measured, 600x20): the iteration converges well inside the
    default budget — more iterations do not move the result.  What
    limits accuracy is the working precision applied to the SQUARED
    condition number of the Gram: max error vs the SVD polar factor is
    ~eps * kappa(a)^2 (within ~10x).  float64: ~1e-11 at kappa=100,
    ~1e-9 at kappa=1000.  float32: ~6e-4 at kappa=30, ~6e-3 at
    kappa=100, ~3e-2 at kappa=300 — so in fp32 (the TPU production
    dtype) this path is only a faithful polar factor for
    kappa ≲ 30-100; beyond that the scrub restores orthogonality but
    not proximity to the true factor, and the eigh path (same Gram
    floor, but exact spectrum handling) or f64 should be used.
    """
    hp = jax.lax.Precision.HIGHEST
    k = ap.shape[1]
    c = jnp.einsum('vi,vj->ij', ap, ap, precision=hp)
    # spectral bound: max absolute row sum (>= lambda_max); guard zeros
    s = jnp.maximum(jnp.max(jnp.sum(jnp.abs(c), axis=1)),
                    jnp.asarray(jnp.finfo(ap.dtype).tiny, ap.dtype))
    eye = jnp.eye(k, dtype=ap.dtype)
    # RELATIVE spectrum floor (the analog of the eigh path's eigenvalue
    # floor): Gram eigenvalues that round NEGATIVE in floating point
    # diverge under the Newton-Schulz map p -> p(3-p)^2/4 instead of
    # converging slowly — a ridge of ~100 ulp pins them just inside
    # (0, 1] at accuracy cost far below fp32 noise.
    floor = 100.0 * jnp.finfo(ap.dtype).eps
    y, z = c / s + floor * eye, eye

    def body(_, carry):
        y, z = carry
        m = 0.5 * (3.0 * eye - jnp.einsum('ij,jk->ik', z, y,
                                          precision=hp))
        return (jnp.einsum('ij,jk->ik', y, m, precision=hp),
                jnp.einsum('ij,jk->ik', m, z, precision=hp))

    _, z = jax.lax.fori_loop(0, n_iters, body, (y, z))
    inv_sqrt = z / jnp.sqrt(s)
    return jnp.einsum('vk,kj->vj', ap, inv_sqrt, precision=hp)


def _procrustes(a, perturbation=0.001):
    """Orthogonal map closest to ``a`` ([voxels, features]): U Vᵀ from the
    thin SVD of ``a`` plus the reference's 0.001 diagonal perturbation
    (srm.py:595-601).  RSRM's updates use no perturbation
    (rsrm.py:182-236); pass ``perturbation=0``.

    For tall inputs (voxels >> features — the whole-brain SRM regime) the
    tall SVD is replaced by the Gram-eigh polar factor:
    ``U Vᵀ = A (AᵀA)^(-1/2)``, i.e. one [V,K]x[K,K] matmul plus a K x K
    eigendecomposition instead of an iterative [V,K] SVD — the SVD is the
    serial bottleneck of the whole-brain EM step on TPU.  Squaring the
    condition number in AᵀA costs ~half the working precision, so one
    Newton-Schulz step ``W(3I - WᵀW)/2`` scrubs the orthogonality error
    (quadratic convergence; the eigh-based W is already near-orthogonal).
    """
    eye = jnp.zeros_like(a)
    k = min(a.shape)
    eye = eye.at[jnp.arange(k), jnp.arange(k)].set(perturbation)
    ap = a + eye
    v, kk = a.shape
    if v >= 4 * kk:
        hp = jax.lax.Precision.HIGHEST
        # The "ns" path is gated to perturbation != 0 call sites (the
        # probabilistic/deterministic SRM W updates): RSRM's and
        # FastSRM's perturbation=0 calls can be severely rank-deficient,
        # where the eigh spectrum handling is the safer choice.
        if POLAR_METHOD == "ns" and perturbation != 0:
            w = _polar_ns(ap)
        else:
            c = jnp.einsum('vi,vj->ij', ap, ap, precision=hp)
            lam, q = jnp.linalg.eigh(c)
            # RELATIVE floor (plus a sqrt-tiny absolute guard for an
            # all-zero input): rank-deficient Grams — RSRM passes
            # perturbation=0 — have eigenvalues rounding to ~0 or
            # slightly negative, and an absolute tiny floor would send
            # lam**-0.5 to ~1e19 and overflow the Newton-Schulz
            # products to Inf/NaN
            floor = jnp.maximum(jnp.finfo(a.dtype).eps * jnp.max(lam),
                                jnp.asarray(jnp.finfo(a.dtype).tiny,
                                            a.dtype) ** 0.5)
            lam = jnp.clip(lam, floor)
            inv_sqrt = jnp.einsum('ik,k,jk->ij', q, lam ** -0.5, q,
                                  precision=hp)
            w = jnp.einsum('vk,kj->vj', ap, inv_sqrt, precision=hp)
        # Newton-Schulz orthogonality scrub, shared by both polar paths
        # (squaring the condition number in the Gram costs ~half the
        # working precision; two quadratically-convergent steps scrub
        # the near-orthogonal result).
        eye_k = jnp.eye(kk, dtype=a.dtype)
        for _ in range(2):
            wtw = jnp.einsum('vi,vj->ij', w, w, precision=hp)
            w = 0.5 * jnp.einsum('vk,kj->vj', w, 3.0 * eye_k - wtw,
                                 precision=hp)
        return w
    u, _, vt = jnp.linalg.svd(ap, full_matrices=False)
    return u @ vt


def _procrustes_batch(a, mesh, perturbation=0.001):
    """Per-subject Procrustes W updates for a stacked [S, V, K] batch.

    With a mesh, the batch is laid out along the mesh's subject axis
    through :func:`brainiak_tpu.ops.distla.shard_vmap`, so each
    device runs the eigh-based polar solve only for its resident
    subjects (the sharded-batched E-step solve layout of ISSUE 6;
    batched small eigh under plain GSPMD lowers to long sequential
    loops on some backends).  Falls back to a plain ``vmap`` without
    a mesh or when the subject count does not divide the axis —
    per-subject numerics are identical either way."""
    fn = partial(_procrustes, perturbation=perturbation)
    return distla.shard_vmap(fn, mesh, DEFAULT_SUBJECT_AXIS,
                             a.shape[0])(a)


def _init_w_from_keys(keys, voxels_pad, features, voxel_counts,
                      dtype=jnp.float32):
    """Per-subject orthonormal init from EXPLICIT per-subject keys —
    the body shared by the stacked init (:func:`_init_w`) and the
    streamed per-shard init (``data.streaming_fit``), so a shard's
    ``w0`` lanes are bit-identical to the stacked fit's.

    ``dtype`` pins the draw to the data dtype: a dtype-less
    ``random.uniform`` follows the x64 flag, and a float64 ``w0``
    would promote every downstream contraction (jaxlint-IR JP301).
    """
    rnd = jax.vmap(
        lambda k: jax.random.uniform(k, (voxels_pad, features),
                                     dtype=dtype))(keys)
    row = jnp.arange(voxels_pad)[None, :, None]
    rnd = jnp.where(row < voxel_counts[:, None, None], rnd, 0.0)
    q, _ = jnp.linalg.qr(rnd)
    return jnp.where(row < voxel_counts[:, None, None], q, 0.0)


def _init_w(key, voxels_pad, n_subjects, features, voxel_counts,
            dtype=jnp.float32):
    """Random orthonormal init per subject via QR, with rows beyond each
    subject's true voxel count zeroed (srm.py:53-107)."""
    keys = jax.random.split(key, n_subjects)
    return _init_w_from_keys(keys, voxels_pad, features, voxel_counts,
                             dtype=dtype)


def _em_iteration(x, w, rho2, sigma_s, trace_xtx, voxel_counts, samples,
                  mesh=None):
    """One probabilistic-SRM EM iteration on stacked data.

    Mirrors srm.py:536-620; the subject-summed quantities become reductions
    over the (possibly mesh-sharded) leading axis, and with ``mesh`` the
    per-subject polar solves of the W update run sharded-batched along
    the subject axis (:func:`_procrustes_batch`).
    """
    features = sigma_s.shape[0]
    eye = jnp.eye(features, dtype=x.dtype)

    rho0 = jnp.sum(1.0 / rho2)
    chol = jax.scipy.linalg.cho_factor(sigma_s)
    inv_sigma_s = jax.scipy.linalg.cho_solve(chol, eye)
    sigma_s_rhos = inv_sigma_s + eye * rho0
    chol_rhos = jax.scipy.linalg.cho_factor(sigma_s_rhos)
    inv_sigma_s_rhos = jax.scipy.linalg.cho_solve(chol_rhos, eye)

    # Σ_i W_iᵀ X_i / ρ_i²  — XLA inserts the cross-device psum when the
    # subject axis is sharded (reference: comm.reduce, srm.py:571).
    wt_invpsi_x = jnp.einsum('svk,svt->kt', w / rho2[:, None, None], x)

    shared = sigma_s @ (eye - rho0 * inv_sigma_s_rhos) @ wt_invpsi_x
    sigma_s = inv_sigma_s_rhos + shared @ shared.T / samples
    trace_sigma_s = samples * jnp.trace(sigma_s)

    a = jnp.einsum('svt,kt->svk', x, shared)
    w = _procrustes_batch(a, mesh)
    rho2 = (trace_xtx - 2.0 * jnp.sum(w * a, axis=(1, 2)) + trace_sigma_s) \
        / (samples * voxel_counts)
    return w, rho2, sigma_s, shared, wt_invpsi_x, inv_sigma_s_rhos


def _srm_log_likelihood(sigma_s, rho2, voxel_counts, wt_invpsi_x,
                        inv_sigma_s_rhos, trace_xt_invsigma2_x, samples):
    """Marginal log-likelihood up to a constant (srm.py:360-396)."""
    features = sigma_s.shape[0]
    eye = jnp.eye(features, dtype=sigma_s.dtype)
    rho0 = jnp.sum(1.0 / rho2)
    chol = jax.scipy.linalg.cho_factor(sigma_s)
    log_det_sigma_s = 2.0 * jnp.sum(jnp.log(jnp.diag(chol[0])))
    sigma_s_rhos = jax.scipy.linalg.cho_solve(chol, eye) + eye * rho0
    chol_rhos = jax.scipy.linalg.cho_factor(sigma_s_rhos)
    log_det_rhos = 2.0 * jnp.sum(jnp.log(jnp.diag(chol_rhos[0])))
    log_det_psi = jnp.sum(jnp.log(rho2) * voxel_counts)
    log_det = log_det_rhos + log_det_psi + log_det_sigma_s
    ll = -0.5 * samples * log_det - 0.5 * trace_xt_invsigma2_x
    ll += 0.5 * jnp.trace(wt_invpsi_x.T @ inv_sigma_s_rhos @ wt_invpsi_x)
    return ll


@partial(jax.jit, static_argnames=("n_steps", "mesh"))
def _em_chunk(x, trace_xtx, voxel_counts, w, rho2, sigma_s, shared,
              n_steps, mesh=None):
    """Run ``n_steps`` EM iterations from explicit state — the
    checkpointable unit for preemption-safe fits.  ``mesh`` (static;
    hashable) routes the per-subject W solves through the
    sharded-batched distla layout."""
    samples = x.shape[2]

    def body(_, carry):
        w, rho2, sigma_s, shared = carry
        w, rho2, sigma_s, shared, _, _ = _em_iteration(
            x, w, rho2, sigma_s, trace_xtx, voxel_counts, samples,
            mesh=mesh)
        return w, rho2, sigma_s, shared

    return jax.lax.fori_loop(0, n_steps, body,
                             (w, rho2, sigma_s, shared))


# cost attribution (schema-v2 `cost` records when profiling is on):
# the checkpointed fit path calls this program from the host, so the
# wrapper sees concrete arrays there; inside the one-shot
# _fit_prob_srm program it sees tracers and bypasses
_em_chunk = obs_profile.profile_program(
    _em_chunk, "srm.em_chunk", span="fit_chunk", estimator="SRM.fit")


def _final_log_likelihood(x, w, rho2, sigma_s, trace_xtx, voxel_counts,
                          mesh=None):
    """Marginal log-likelihood at the current EM state (shared by the
    plain and checkpointed fit paths)."""
    samples = x.shape[2]
    trace_xt_invsigma2_x = jnp.sum(trace_xtx / rho2)
    _, _, _, _, wt_invpsi_x, inv_sigma_s_rhos = _em_iteration(
        x, w, rho2, sigma_s, trace_xtx, voxel_counts, samples,
        mesh=mesh)
    return _srm_log_likelihood(sigma_s, rho2, voxel_counts, wt_invpsi_x,
                               inv_sigma_s_rhos, trace_xt_invsigma2_x,
                               samples)


def _fit_prob_srm(x, trace_xtx, voxel_counts, key, features, n_iter,
                  mesh=None):
    """Full probabilistic-SRM EM fit as one XLA program."""
    n_subjects, voxels_pad, samples = x.shape
    w = _init_w(key, voxels_pad, n_subjects, features, voxel_counts,
                dtype=x.dtype)
    rho2 = jnp.ones(n_subjects, dtype=x.dtype)
    sigma_s = jnp.eye(features, dtype=x.dtype)
    shared = jnp.zeros((features, samples), dtype=x.dtype)
    w, rho2, sigma_s, shared = _em_chunk(
        x, trace_xtx, voxel_counts, w, rho2, sigma_s, shared,
        n_steps=n_iter, mesh=mesh)
    ll = _final_log_likelihood(x, w, rho2, sigma_s, trace_xtx,
                               voxel_counts, mesh=mesh)
    return w, rho2, sigma_s, shared, ll


_fit_prob_srm_jit = obs_profile.profile_program(
    jax.jit(_fit_prob_srm,
            static_argnames=("features", "n_iter", "mesh")),
    "srm.fit_prob")



@partial(jax.jit, static_argnames=("n_steps", "mesh"))
def _det_chunk(x, w, shared, n_steps, mesh=None):
    """``n_steps`` deterministic-SRM BCD iterations from explicit
    state — the checkpointable unit for preemption-safe fits.
    ``mesh`` (static) lays the per-subject W solves out along the
    subject axis (:func:`_procrustes_batch`)."""
    n_subjects = x.shape[0]

    def body(_, carry):
        w, shared = carry
        a = jnp.einsum('svt,kt->svk', x, shared)
        w = _procrustes_batch(a, mesh)
        return w, jnp.einsum('svk,svt->kt', w, x) / n_subjects

    return jax.lax.fori_loop(0, n_steps, body, (w, shared))


_det_chunk = obs_profile.profile_program(
    _det_chunk, "srm.det_chunk", span="fit_chunk",
    estimator="DetSRM.fit")


@jax.jit
def _det_objective(x, w, shared):
    return jnp.sum(
        jnp.square(x - jnp.einsum('svk,kt->svt', w, shared))) / 2.0


def _fit_det_srm(x, voxel_counts, key, features, n_iter, mesh=None):
    """Deterministic SRM block-coordinate descent (srm.py:859-918):
    alternate Procrustes W updates with S = mean_i W_iᵀ X_i."""
    n_subjects, voxels_pad, samples = x.shape
    w = _init_w(key, voxels_pad, n_subjects, features, voxel_counts,
                dtype=x.dtype)
    shared = jnp.einsum('svk,svt->kt', w, x) / n_subjects
    w, shared = _det_chunk(x, w, shared, n_steps=n_iter, mesh=mesh)
    return w, shared, _det_objective(x, w, shared)


_fit_det_srm_jit = obs_profile.profile_program(
    jax.jit(_fit_det_srm,
            static_argnames=("features", "n_iter", "mesh")),
    "srm.fit_det")


def _stack_and_pad(X, dtype, demean=True):
    """Stack a list of [voxels_i, samples] arrays into
    ([S, V_max, T], voxel_counts, means, trace_xtx); optionally demeaned
    over samples (probabilistic SRM demeans, srm.py:330-348; DetSRM does
    not)."""
    voxel_counts = np.array([d.shape[0] for d in X], dtype=np.int64)
    samples = X[0].shape[1]
    v_max = int(voxel_counts.max())
    stacked = np.zeros((len(X), v_max, samples), dtype=dtype)
    mu = []
    trace_xtx = np.zeros(len(X), dtype=dtype)
    for i, d in enumerate(X):
        d = np.asarray(d, dtype=dtype)
        m = d.mean(axis=1)
        mu.append(m)
        # Matching the reference, the trace is of the RAW data even though
        # the EM runs on demeaned data (srm.py:339-342).
        trace_xtx[i] = np.sum(d ** 2)
        if demean:
            d = d - m[:, None]
        stacked[i, :d.shape[0]] = d
    return stacked, voxel_counts, mu, trace_xtx


def _as_subject_store(X):
    """The streamed-fit dispatch test: a
    :class:`~brainiak_tpu.data.store.SubjectStore` (or anything
    duck-typing its read/metadata surface) routes ``fit`` through
    the out-of-core data plane instead of :func:`_stack_and_pad`.
    Imported lazily — the data plane depends on this module."""
    from ..data.store import SubjectStore

    return X if isinstance(X, SubjectStore) else None


class _SRMBase(BaseEstimator, TransformerMixin):

    def __init__(self, n_iter=10, features=50, rand_seed=0, mesh=None,
                 shard_subjects=None):
        self.n_iter = n_iter
        self.features = features
        self.rand_seed = rand_seed
        self.mesh = mesh
        #: subjects per streamed shard batch when ``fit`` is handed a
        #: :class:`~brainiak_tpu.data.store.SubjectStore` (None: auto
        #: from the host budget — see ``data.prefetch``); ignored by
        #: the in-memory path.
        self.shard_subjects = shard_subjects

    # -- common checks ----------------------------------------------------
    def _validate(self, X):
        if len(X) <= 1:
            raise ValueError("There are not enough subjects "
                             "({0:d}) to train the model.".format(len(X)))
        samples = X[0].shape[1]
        for d in X:
            assert_all_finite(d)
            if d.shape[1] != samples:
                raise ValueError(
                    "Different number of samples between subjects.")
        if samples < self.features:
            raise ValueError(
                "There are not enough samples to train the model with "
                "{0:d} features.".format(self.features))

    def _device_place(self, stacked):
        if self.mesh is not None:
            spec = PartitionSpec(DEFAULT_SUBJECT_AXIS, None, None)
            return place_on_mesh(stacked,
                                 NamedSharding(self.mesh, spec))
        return jnp.asarray(stacked)

    # -- shared API -------------------------------------------------------
    def transform(self, X, y=None):
        """Project each subject's data into shared space: s_i = W_iᵀ X_i
        (srm.py:271-303)."""
        if not hasattr(self, 'w_'):
            raise NotFittedError("The model fit has not been run yet.")
        if len(X) != len(self.w_):
            raise ValueError("The number of subjects does not match the one"
                             " in the model.")
        return [None if x is None else self.w_[i].T.dot(x)
                for i, x in enumerate(X)]

    def transform_subject(self, X):
        """Procrustes map for a held-out subject against the fitted shared
        response (srm.py:397-449)."""
        if not hasattr(self, 'w_'):
            raise NotFittedError("The model fit has not been run yet.")
        if X.shape[1] != self.s_.shape[1]:
            raise ValueError("The number of timepoints(TRs) does not match "
                             "the one in the model.")
        a = jnp.asarray(X) @ jnp.asarray(self.s_).T
        u, _, vt = jnp.linalg.svd(a, full_matrices=False)
        return np.asarray(u @ vt)


class SRM(_SRMBase):
    """Probabilistic Shared Response Model (reference srm.py:145-623).

    Parameters
    ----------
    n_iter : int, default 10
        Number of EM iterations.
    features : int, default 50
        Shared-space dimensionality K.
    rand_seed : int, default 0
        Seed for the orthonormal W init.
    mesh : jax.sharding.Mesh, optional
        If given, the stacked subject data is sharded over the mesh's
        ``'subject'`` axis and XLA distributes the EM loop (the analog of
        passing ``comm=`` in the reference).

    Attributes (after fit)
    ----------------------
    w_ : list of [voxels_i, features] orthonormal maps
    s_ : [features, samples] shared response
    sigma_s_ : [features, features] shared-response covariance
    mu_ : list of [voxels_i] voxel means
    rho2_ : [subjects] noise variances
    logprob_ : final marginal log-likelihood (up to a constant)
    """

    def fit(self, X, y=None, checkpoint_dir=None, checkpoint_every=5):
        """Fit the model.  With ``checkpoint_dir``, EM state is saved
        every ``checkpoint_every`` iterations and a later call resumes
        from the latest checkpoint — mid-iteration resume the reference
        lacks (SURVEY.md §5.4).  The checkpointed loop runs under the
        resilience guard: non-finite EM state rolls back to the last
        good checkpoint and, if divergence persists, aborts with
        :class:`~brainiak_tpu.resilience.DivergenceError`.

        Example
        -------
        >>> srm = SRM(n_iter=20, features=10)
        >>> srm.fit(data, checkpoint_dir="/ckpts/srm_run1")  # preempted
        >>> srm.fit(data, checkpoint_dir="/ckpts/srm_run1")  # resumes

        ``X`` may also be a :class:`~brainiak_tpu.data.store.
        SubjectStore`: the fit then streams subject shards from disk
        (map-reduce EM, overlapped prefetch) and never materializes
        the ``[subjects, V, T]`` stack — the thousand-subject path.
        See docs/streaming_data.md.
        """
        logger.info('Starting Probabilistic SRM')
        store = _as_subject_store(X)
        if store is not None:
            return self._fit_streamed(store, checkpoint_dir,
                                      checkpoint_every)
        self._validate(X)
        dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
        stacked, voxel_counts, mu, trace_xtx = _stack_and_pad(X, dtype)
        # content digest on the HOST stack, before device placement:
        # bit-reproducible across restarts (float64 numpy) and not
        # degenerate for z-scored data the way sum-of-squares is.
        # The voxel means are part of the digest — the stack itself is
        # demeaned, so X and X+c would otherwise collide.
        data_digest = array_digest(stacked, *mu) if checkpoint_dir \
            else 0.0
        stacked = self._device_place(stacked)

        key = jax.random.PRNGKey(self.rand_seed)
        if checkpoint_dir is None:
            w, rho2, sigma_s, shared, ll = _fit_prob_srm_jit(
                stacked, jnp.asarray(trace_xtx),
                jnp.asarray(voxel_counts).astype(dtype), key,
                features=self.features, n_iter=self.n_iter,
                mesh=self.mesh)
        else:
            w, rho2, sigma_s, shared, ll = self._fit_checkpointed(
                stacked, trace_xtx, voxel_counts, key, dtype,
                data_digest, checkpoint_dir, checkpoint_every)

        # fetch_replicated on every leaf: under a multi-process mesh
        # the subject-sharded w/rho2 are not addressable for a plain
        # np.asarray, and shared/sigma_s are only replicated by GSPMD's
        # propagation CHOICE (no out_shardings pins it) — the helper is
        # a no-op when they already are
        w = fetch_replicated(w, self.mesh)
        self.w_ = [w[i, :voxel_counts[i]] for i in range(len(X))]
        self.s_ = fetch_replicated(shared, self.mesh)
        self.sigma_s_ = fetch_replicated(sigma_s, self.mesh)
        self.mu_ = mu
        self.rho2_ = fetch_replicated(rho2, self.mesh)
        self.logprob_ = float(ll)
        # non-finite guard on the fitted state (the checkpointed path
        # guards every chunk; the fused path is guarded here)
        check_state({"w": w, "rho2": self.rho2_, "sigma_s": self.sigma_s_,
                     "shared": self.s_, "logprob": self.logprob_},
                    iteration=self.n_iter, where="SRM.fit")
        logger.info('Objective function %f', self.logprob_)
        return self

    def _fit_streamed(self, store, checkpoint_dir, checkpoint_every):
        """Out-of-core fit over a :class:`SubjectStore`: subject
        shards stream through the prefetcher, the EM loop runs as
        map-reduce over them (``data.streaming_fit``), and the
        checkpoint fingerprint comes from the store's per-subject
        digests instead of a stacked-tensor digest."""
        from ..data.streaming_fit import stream_fit_srm

        w, shared, sigma_s, mu, rho2, ll = stream_fit_srm(
            store, features=self.features, n_iter=self.n_iter,
            rand_seed=self.rand_seed, mesh=self.mesh,
            shard_subjects=self.shard_subjects,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every)
        self.w_ = w
        self.s_ = shared
        self.sigma_s_ = sigma_s
        self.mu_ = mu
        self.rho2_ = rho2
        self.logprob_ = float(ll)
        check_state({"rho2": self.rho2_, "sigma_s": self.sigma_s_,
                     "shared": self.s_, "logprob": self.logprob_},
                    iteration=self.n_iter, where="SRM.fit")
        logger.info('Objective function %f', self.logprob_)
        return self

    def _fit_checkpointed(self, stacked, trace_xtx, voxel_counts, key,
                          dtype, data_digest, checkpoint_dir,
                          checkpoint_every):
        """Chunked EM under the resilient-loop driver: orbax/npz
        checkpoints between chunks, non-finite guard with rollback, and
        deterministic fault-injection hooks."""
        n_subjects, voxels_pad, samples = stacked.shape
        trace_j = jnp.asarray(trace_xtx)
        counts_j = jnp.asarray(voxel_counts).astype(dtype)

        # fingerprint ties a checkpoint to this (data, config); resuming
        # against different data or settings is an error, not a silent
        # wrong answer
        fingerprint = np.array(
            [data_digest, float(samples),
             float(voxels_pad), float(n_subjects),
             float(self.features), float(self.rand_seed)])
        template = {
            "w": np.zeros((n_subjects, voxels_pad, self.features),
                          dtype=dtype),
            "rho2": np.zeros(n_subjects, dtype=dtype),
            "sigma_s": np.zeros((self.features, self.features),
                                dtype=dtype),
            "shared": np.zeros((self.features, samples), dtype=dtype),
        }
        w0 = _init_w(key, voxels_pad, n_subjects, self.features,
                     counts_j, dtype=dtype)
        init_state = {
            "w": fetch_replicated(w0, self.mesh),
            "rho2": np.ones(n_subjects, dtype=dtype),
            "sigma_s": np.eye(self.features, dtype=dtype),
            "shared": np.zeros((self.features, samples), dtype=dtype),
        }

        run_chunk, final_leaves = make_device_carry_chunk(
            lambda dev, n: _em_chunk(stacked, trace_j, counts_j, *dev,
                                     n_steps=n, mesh=self.mesh),
            ("w", "rho2", "sigma_s", "shared"),
            fetch=lambda v: fetch_replicated(v, self.mesh),
            dtype=dtype)
        state, step = run_resilient_loop(
            run_chunk, init_state, self.n_iter,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            fingerprint=fingerprint, template=template, name="SRM.fit",
            progress_objective="rho2", progress_direction="min")
        w, rho2, sigma_s, shared = final_leaves(state, step)
        ll = _final_log_likelihood(stacked, w, rho2, sigma_s, trace_j,
                                   counts_j, mesh=self.mesh)
        return w, rho2, sigma_s, shared, ll

    def save(self, file):
        """Persist the fitted model as .npz (srm.py:451-481).

        Kept for reference-format compatibility; new code should
        prefer :func:`brainiak_tpu.serve.save_model`, whose
        versioned artifact schema covers every servable estimator
        and stays pickle-free even for mixed voxel counts (this
        format needs ``allow_pickle`` for the ragged path)."""
        if not hasattr(self, 'w_'):
            raise NotFittedError("The model fit has not been run yet.")
        if len({w.shape for w in self.w_}) == 1:
            # uniform voxel counts: save plain stacked arrays so the
            # file is readable WITHOUT allow_pickle — the reference's
            # load() (srm.py:126) calls np.load with pickle disabled,
            # and this is exactly what its own save() produces
            w_arr = np.stack(self.w_)
            mu_arr = np.stack(self.mu_)
        else:
            w_arr = np.empty(len(self.w_), dtype=object)
            mu_arr = np.empty(len(self.mu_), dtype=object)
            for i in range(len(self.w_)):
                w_arr[i] = self.w_[i]
                mu_arr[i] = self.mu_[i]
        np.savez_compressed(
            file,
            w_=w_arr,
            s_=self.s_,
            sigma_s_=self.sigma_s_,
            mu_=mu_arr,
            rho2_=self.rho2_,
            kwargs=np.array([self.features, self.n_iter, self.rand_seed]))


def load(file):
    """Load a fitted SRM saved by :meth:`SRM.save` (srm.py:110-142).

    Also reads the reference's npz format (pinned by its
    tests/funcalign/sr_v0_4.npz golden file).  For the uniform
    versioned artifact registry (every servable estimator, retry-
    wired reads) use :func:`brainiak_tpu.serve.load_model`."""
    loaded = np.load(file, allow_pickle=True)
    features, n_iter, rand_seed = (int(v) for v in loaded['kwargs'])
    srm = SRM(n_iter=n_iter, features=features, rand_seed=rand_seed)
    srm.w_ = [np.asarray(s) for s in loaded['w_']]
    srm.s_ = np.asarray(loaded['s_'])
    srm.sigma_s_ = np.asarray(loaded['sigma_s_'])
    srm.mu_ = [np.asarray(s) for s in loaded['mu_']]
    srm.rho2_ = np.asarray(loaded['rho2_'])
    return srm


class DetSRM(_SRMBase):
    """Deterministic SRM (reference srm.py:626-918): minimize
    Σ_i ||X_i − W_i S||²_F with orthonormal W_i by block-coordinate descent.
    """

    def fit(self, X, y=None, checkpoint_dir=None, checkpoint_every=5):
        """Fit the deterministic SRM.  With ``checkpoint_dir``, BCD
        state is saved every ``checkpoint_every`` iterations under the
        resilience guard and a later call resumes from the latest
        checkpoint.

        Example
        -------
        >>> det = DetSRM(n_iter=30, features=10)
        >>> det.fit(data, checkpoint_dir="/ckpts/det_run1")  # resumable

        ``X`` may also be a :class:`~brainiak_tpu.data.store.
        SubjectStore` — the fit streams subject shards from disk and
        never materializes the stacked tensor (see
        docs/streaming_data.md).
        """
        logger.info('Starting Deterministic SRM')
        store = _as_subject_store(X)
        if store is not None:
            return self._fit_streamed(store, checkpoint_dir,
                                      checkpoint_every)
        self._validate(X)
        dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
        stacked, voxel_counts, _, _ = _stack_and_pad(
            X, dtype, demean=False)
        data_digest = array_digest(stacked) if checkpoint_dir else 0.0
        stacked = self._device_place(stacked)

        key = jax.random.PRNGKey(self.rand_seed)
        if checkpoint_dir is None:
            w, shared, objective = _fit_det_srm_jit(
                stacked, jnp.asarray(voxel_counts).astype(dtype), key,
                features=self.features, n_iter=self.n_iter,
                mesh=self.mesh)
        else:
            w, shared, objective = self._fit_checkpointed(
                stacked, voxel_counts, key, dtype, data_digest,
                checkpoint_dir, checkpoint_every)

        w = fetch_replicated(w, self.mesh)
        self.w_ = [w[i, :voxel_counts[i]] for i in range(len(X))]
        self.s_ = fetch_replicated(shared, self.mesh)
        self.objective_ = float(objective)
        check_state({"w": w, "shared": self.s_,
                     "objective": self.objective_},
                    iteration=self.n_iter, where="DetSRM.fit")
        logger.info('Objective function %f', self.objective_)
        return self

    def _fit_streamed(self, store, checkpoint_dir, checkpoint_every):
        """Out-of-core BCD over a :class:`SubjectStore` (see
        :meth:`SRM._fit_streamed`)."""
        from ..data.streaming_fit import stream_fit_detsrm

        w, shared, objective = stream_fit_detsrm(
            store, features=self.features, n_iter=self.n_iter,
            rand_seed=self.rand_seed, mesh=self.mesh,
            shard_subjects=self.shard_subjects,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every)
        self.w_ = w
        self.s_ = shared
        self.objective_ = float(objective)
        check_state({"shared": self.s_,
                     "objective": self.objective_},
                    iteration=self.n_iter, where="DetSRM.fit")
        logger.info('Objective function %f', self.objective_)
        return self

    def _fit_checkpointed(self, stacked, voxel_counts, key, dtype,
                          data_digest, checkpoint_dir,
                          checkpoint_every):
        """Chunked BCD under the resilient-loop driver (same shape as
        :meth:`SRM._fit_checkpointed`)."""
        n_subjects, voxels_pad, samples = stacked.shape
        counts_j = jnp.asarray(voxel_counts).astype(dtype)
        fingerprint = np.array(
            [data_digest, float(samples),
             float(voxels_pad), float(n_subjects),
             float(self.features), float(self.rand_seed)])
        template = {
            "w": np.zeros((n_subjects, voxels_pad, self.features),
                          dtype=dtype),
            "shared": np.zeros((self.features, samples), dtype=dtype),
        }
        w0 = _init_w(key, voxels_pad, n_subjects, self.features,
                     counts_j, dtype=dtype)
        shared0 = jnp.einsum('svk,svt->kt', w0, stacked) / n_subjects
        init_state = {"w": fetch_replicated(w0, self.mesh),
                      "shared": fetch_replicated(shared0, self.mesh)}

        run_chunk, final_leaves = make_device_carry_chunk(
            lambda dev, n: _det_chunk(stacked, *dev, n_steps=n,
                                      mesh=self.mesh),
            ("w", "shared"),
            fetch=lambda v: fetch_replicated(v, self.mesh),
            dtype=dtype)
        state, step = run_resilient_loop(
            run_chunk, init_state, self.n_iter,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            fingerprint=fingerprint, template=template,
            name="DetSRM.fit")
        w, shared = final_leaves(state, step)
        return w, shared, _det_objective(stacked, w, shared)
