"""Semi-Supervised Shared Response Model (SS-SRM), TPU-native.

Re-design of /root/reference/src/brainiak/funcalign/sssrm.py.  The model
jointly optimizes functional alignment and a multinomial logistic-regression
(MLR) classifier in shared space:

    min  (1−α)·Loss_SRM(W, S; X) + (α/γ)·Loss_MLR(θ, b; WᵀZ, y) + ½‖θ‖²
    s.t. WᵢᵀWᵢ = I

by block-coordinate descent over W (Stiefel manifold), S (closed form) and
(θ, b) (convex MLR).

TPU-first: the reference drives TensorFlow costs through pymanopt's
conjugate gradient (sssrm.py:386-557); here the MLR update is a jitted
L-BFGS and the per-subject W update is a jitted Riemannian gradient descent
with QR retraction (:func:`brainiak_tpu.ops.optimize.stiefel_minimize`) —
no TensorFlow, gradients via autodiff.
"""

import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.base import BaseEstimator, ClassifierMixin, TransformerMixin
from sklearn.exceptions import NotFittedError
from sklearn.utils import assert_all_finite
from sklearn.utils.multiclass import unique_labels

from ..ops.optimize import minimize_lbfgs, stiefel_minimize
from ..utils.utils import concatenate_not_none

logger = logging.getLogger(__name__)

__all__ = ["SSSRM"]


@partial(jax.jit, static_argnames=("n_classes",))
def _fit_mlr(shared_data, labels, weights, alpha_gamma, n_classes,
             max_iters=200):
    """Weighted multinomial logistic regression (θ, b) update
    (reference sssrm.py:386-454): minimize
    -(α/γ)·Σ log softmax(xθ + b)[y] / weight + ½‖θ‖²."""
    features = shared_data.shape[1]

    def loss(params):
        theta = params[:features * n_classes].reshape(features, n_classes)
        bias = params[features * n_classes:]
        logits = shared_data @ theta + bias[None, :]
        logp = jax.nn.log_softmax(logits, axis=1)
        picked = jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        return (-alpha_gamma * jnp.sum(picked / weights)
                + 0.5 * jnp.sum(theta ** 2))

    x0 = jnp.zeros(features * n_classes + n_classes,
                   dtype=shared_data.dtype)
    x, _ = minimize_lbfgs(loss, x0, max_iters=max_iters)
    theta = x[:features * n_classes].reshape(features, n_classes)
    bias = x[features * n_classes:]
    return theta, bias


@partial(jax.jit, static_argnames=("max_iters",))
def _fit_w_subject(x_align, x_sup, labels, w0, s, theta, bias, const_align,
                   const_sup, max_iters=30):
    """Stiefel-manifold W update for one subject with supervised data
    (reference sssrm.py:456-557)."""

    def cost(w):
        diff = x_align - w @ s
        f1 = const_align * jnp.sum(diff ** 2)
        logits = (theta.T @ (w.T @ x_sup)).T + bias[None, :]
        logp = jax.nn.log_softmax(logits, axis=1)
        picked = jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        return f1 + const_sup * jnp.sum(picked)

    return stiefel_minimize(cost, w0, max_iters=max_iters)


@partial(jax.jit, static_argnames=("max_iters",))
def _fit_w_subject_unsup(x_align, w0, s, const_align, max_iters=30):
    def cost(w):
        diff = x_align - w @ s
        return const_align * jnp.sum(diff ** 2)

    return stiefel_minimize(cost, w0, max_iters=max_iters)


class SSSRM(BaseEstimator, ClassifierMixin, TransformerMixin):
    """Semi-Supervised SRM (reference sssrm.py:55-822).

    Parameters: n_iter, features, gamma (MLR scale), alpha in (0,1)
    (supervision mix), rand_seed.

    Attributes after fit: ``w_``, ``s_``, ``theta_``, ``bias_``,
    ``classes_``.
    """

    def __init__(self, n_iter=10, features=50, gamma=1.0, alpha=0.5,
                 rand_seed=0):
        self.n_iter = n_iter
        self.features = features
        self.gamma = gamma
        self.alpha = alpha
        self.rand_seed = rand_seed

    def fit(self, X, y, Z):
        """Fit from alignment data X, labels y, and classification data Z
        (reference sssrm.py:133-202)."""
        logger.info('Starting SS-SRM')
        if not 0.0 < self.alpha < 1.0:
            raise ValueError("Alpha parameter should be in range (0.0, "
                             "1.0)")
        if self.gamma <= 0.0:
            raise ValueError("Gamma parameter should be positive.")
        if len(X) <= 1 or len(y) <= 1 or len(Z) <= 1:
            raise ValueError("There are not enough subjects in the input "
                             "data to train the model.")
        if len(X) != len(y) or len(X) != len(Z):
            raise ValueError("Different number of subjects in data.")
        if X[0].shape[1] < self.features:
            raise ValueError(
                "There are not enough samples to train the model with "
                "{0:d} features.".format(self.features))
        number_trs = X[0].shape[1]
        for subject in range(len(X)):
            assert_all_finite(X[subject])
            if X[subject].shape[1] != number_trs:
                raise ValueError("Different number of alignment samples "
                                 "between subjects.")
            if Z[subject] is not None:
                assert_all_finite(Z[subject])
                if X[subject].shape[0] != Z[subject].shape[0]:
                    raise ValueError(
                        "Different number of voxels between alignment and "
                        "classification data (subject {0:d})."
                        .format(subject))
                if Z[subject].shape[1] != y[subject].size:
                    raise ValueError(
                        "Different number of samples and labels in subject "
                        "{0:d}.".format(subject))

        new_y = self._init_classes(y)
        self.w_, self.s_, self.theta_, self.bias_ = \
            self._sssrm(X, Z, new_y)
        return self

    def _init_classes(self, y):
        """Map labels to [0, C) (reference sssrm.py:204-227)."""
        self.classes_ = unique_labels(concatenate_not_none(y))
        return [np.digitize(yi, self.classes_) - 1 if yi is not None
                else None for yi in y]

    def _sssrm(self, data_align, data_sup, labels):
        """BCD main loop (reference sssrm.py:299-385)."""
        n_classes = self.classes_.size
        dtype = np.float64 if jax.config.jax_enable_x64 else np.float32

        rng = np.random.RandomState(self.rand_seed)
        w = []
        for subject in range(len(data_align)):
            q, _ = np.linalg.qr(
                rng.random_sample((data_align[subject].shape[0],
                                   self.features)))
            w.append(q.astype(dtype))

        s = self._compute_shared_response(data_align, w)
        theta, bias = self._update_classifier(data_sup, labels, w,
                                              n_classes)

        for iteration in range(self.n_iter):
            logger.info('Iteration %d', iteration + 1)
            w = self._update_w(data_align, data_sup, labels, w, s, theta,
                               bias)
            s = self._compute_shared_response(data_align, w)
            theta, bias = self._update_classifier(data_sup, labels, w,
                                                  n_classes)
        return w, s, theta, bias

    @staticmethod
    def _compute_shared_response(data, w):
        """S = mean_i Wᵢᵀ Xᵢ (reference sssrm.py:559-584)."""
        s = np.zeros((w[0].shape[1], data[0].shape[1]))
        for m in range(len(w)):
            s = s + w[m].T @ data[m]
        return s / len(w)

    def _update_classifier(self, data, labels, w, n_classes):
        data_stacked, labels_stacked, weights = self._stack_list(
            data, labels, w)
        data_j = jnp.asarray(data_stacked)
        theta, bias = _fit_mlr(data_j,
                               jnp.asarray(labels_stacked),
                               jnp.asarray(weights, dtype=data_j.dtype),
                               self.alpha / self.gamma, n_classes)
        return np.asarray(theta), np.asarray(bias)

    def _update_w(self, data_align, data_sup, labels, w, s, theta, bias):
        s_j = jnp.asarray(s)
        theta_j = jnp.asarray(theta)
        bias_j = jnp.asarray(bias)
        new_w = []
        for subject in range(len(data_align)):
            const_align = (1 - self.alpha) * 0.5 / \
                data_align[subject].shape[1]
            if data_sup[subject] is not None:
                const_sup = -self.alpha / self.gamma / \
                    data_sup[subject].shape[1]
                wi, _ = _fit_w_subject(
                    jnp.asarray(data_align[subject]),
                    jnp.asarray(data_sup[subject]),
                    jnp.asarray(labels[subject]),
                    jnp.asarray(w[subject]), s_j, theta_j, bias_j,
                    const_align, const_sup)
            else:
                wi, _ = _fit_w_subject_unsup(
                    jnp.asarray(data_align[subject]),
                    jnp.asarray(w[subject]), s_j, const_align)
            new_w.append(np.asarray(wi))
        return new_w

    @staticmethod
    def _stack_list(data, data_labels, w):
        """Stack per-subject shared-space samples, labels and per-sample
        weights (reference sssrm.py:775-822)."""
        labels_stacked = concatenate_not_none(data_labels)
        weights = np.empty((labels_stacked.size,))
        data_shared = [None] * len(data)
        curr = 0
        for s in range(len(data)):
            if data[s] is not None:
                n = data[s].shape[1]
                weights[curr:curr + n] = n
                data_shared[s] = w[s].T @ data[s]
                curr += n
        data_stacked = concatenate_not_none(data_shared, axis=1).T
        return data_stacked, labels_stacked, weights

    # -- inference --------------------------------------------------------
    def transform(self, X, y=None):
        """Project into shared space: sᵢ = Wᵢᵀ Xᵢ
        (reference sssrm.py:229-262)."""
        if not hasattr(self, 'w_'):
            raise NotFittedError("The model fit has not been run yet.")
        if len(X) != len(self.w_):
            raise ValueError("The number of subjects does not match the "
                             "one in the model.")
        return [None if x is None else self.w_[i].T @ x
                for i, x in enumerate(X)]

    def predict(self, X):
        """MLR prediction in shared space (reference sssrm.py:264-297)."""
        if not hasattr(self, 'w_'):
            raise NotFittedError("The model fit has not been run yet.")
        if len(X) != len(self.w_):
            raise ValueError("The number of subjects does not match the "
                             "one in the model.")
        preds = [None] * len(X)
        for i, x in enumerate(X):
            if x is not None:
                logits = (self.theta_.T @ (self.w_[i].T @ x)).T + \
                    self.bias_[None, :]
                preds[i] = self.classes_[np.argmax(logits, axis=1)]
        return preds
