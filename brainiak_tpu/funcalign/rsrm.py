"""Robust Shared Response Model (RSRM), TPU-native.

Re-design of /root/reference/src/brainiak/funcalign/rsrm.py: factorize each
subject's data as X_i ≈ W_i R + S_i with orthonormal W_i and an l1-sparse
individual term S_i, by block-coordinate descent (Procrustes W update,
soft-threshold S update, averaged shared response).

Like SRM, the whole BCD loop is one jitted program over a zero-padded
``[subjects, voxels, TRs]`` stack (padding is exact: zero data rows produce
zero W rows and zero S rows through every update), shardable over a
``('subject',)`` mesh axis.
"""

import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.base import BaseEstimator, TransformerMixin
from sklearn.exceptions import NotFittedError
from sklearn.utils import assert_all_finite

from ..obs import profile as obs_profile
from ..resilience.guards import (array_digest, check_state,
                                 make_device_carry_chunk,
                                 run_resilient_loop)
from .srm import _init_w, _procrustes, _stack_and_pad

logger = logging.getLogger(__name__)

__all__ = ["RSRM"]


@jax.jit
def _shrink(v, gamma):
    """Soft-thresholding operator (reference rsrm.py:537-561)."""
    return jnp.sign(v) * jnp.maximum(jnp.abs(v) - gamma, 0.0)


def _shared_response(x, s, w, n_subjects):
    return jnp.einsum('svk,svt->kt', w, x - s) / n_subjects


@partial(jax.jit, static_argnames=("n_steps",))
def _rsrm_chunk(x, w, s, r, gamma, n_steps):
    """``n_steps`` RSRM BCD iterations from explicit state — the
    checkpointable unit for preemption-safe fits."""
    n_subjects = x.shape[0]

    def body(_, carry):
        w, s, r = carry
        a = jnp.einsum('svt,kt->svk', x - s, r)
        w = jax.vmap(lambda m: _procrustes(m, 0.0))(a)
        s = _shrink(x - jnp.einsum('svk,kt->svt', w, r), gamma)
        r = _shared_response(x, s, w, n_subjects)
        return w, s, r

    return jax.lax.fori_loop(0, n_steps, body, (w, s, r))


# cost attribution: host-called by the checkpointed fit path; inside
# the one-shot _fit_rsrm program the wrapper sees tracers and bypasses
_rsrm_chunk = obs_profile.profile_program(
    _rsrm_chunk, "rsrm.chunk", span="fit_chunk", estimator="RSRM.fit")


@jax.jit
def _rsrm_objective(x, w, s, r, gamma):
    return 0.5 * jnp.sum(
        (x - jnp.einsum('svk,kt->svt', w, r) - s) ** 2) \
        + gamma * jnp.sum(jnp.abs(s))


@partial(jax.jit, static_argnames=("features", "n_iter"))
def _fit_rsrm(x, voxel_counts, key, gamma, features, n_iter):
    """Full RSRM BCD fit as one XLA program (reference rsrm.py:256-350)."""
    n_subjects, voxels_pad, trs = x.shape
    w = _init_w(key, voxels_pad, n_subjects, features, voxel_counts,
                dtype=x.dtype)
    s = jnp.zeros_like(x)
    r = _shared_response(x, s, w, n_subjects)
    w, s, r = _rsrm_chunk(x, w, s, r, gamma, n_steps=n_iter)
    return w, s, r, _rsrm_objective(x, w, s, r, gamma)


# cost attribution for the one-shot (non-checkpointed) fit program
_fit_rsrm = obs_profile.profile_program(_fit_rsrm, "rsrm.fit")


@partial(jax.jit, static_argnames=("n_iter",))
def _transform_new_data(x, w, gamma, n_iter):
    """Alternating projection/shrinkage for new data of a fitted subject
    (reference rsrm.py:193-220)."""
    s = jnp.zeros_like(x)

    def body(_, carry):
        r, s = carry
        r = w.T @ (x - s)
        s = _shrink(x - w @ r, gamma)
        return r, s

    r0 = jnp.zeros((w.shape[1], x.shape[1]), dtype=x.dtype)
    return jax.lax.fori_loop(0, n_iter, body, (r0, s))


@partial(jax.jit, static_argnames=("n_iter",))
def _transform_subject(x, r, gamma, n_iter):
    """Alternating Procrustes/shrinkage for a held-out subject
    (reference rsrm.py:222-254)."""
    s = jnp.zeros_like(x)
    w0 = jnp.zeros((x.shape[0], r.shape[0]), dtype=x.dtype)

    def body(_, carry):
        w, s = carry
        w = _procrustes((x - s) @ r.T, 0.0)
        s = _shrink(x - w @ r, gamma)
        return w, s

    return jax.lax.fori_loop(0, n_iter, body, (w0, s))


class RSRM(BaseEstimator, TransformerMixin):
    """Robust SRM (reference rsrm.py:39-561).

    Attributes after fit: ``w_`` (orthonormal maps), ``r_`` (shared
    response), ``s_`` (sparse individual terms).
    """

    def __init__(self, n_iter=10, features=50, gamma=1.0, rand_seed=0,
                 mesh=None):
        self.n_iter = n_iter
        self.features = features
        self.gamma = gamma
        self.rand_seed = rand_seed
        self.mesh = mesh

    def fit(self, X, y=None, checkpoint_dir=None, checkpoint_every=5):
        """Fit the robust SRM.  With ``checkpoint_dir``, BCD state is
        saved every ``checkpoint_every`` iterations under the
        resilience guard (non-finite rollback) and a later call resumes
        from the latest checkpoint.

        Example
        -------
        >>> rsrm = RSRM(n_iter=20, features=10, gamma=1.0)
        >>> rsrm.fit(data, checkpoint_dir="/ckpts/rsrm1")  # resumable
        """
        logger.info('Starting RSRM')
        if self.gamma <= 0.0:
            raise ValueError("Gamma parameter should be positive.")
        if len(X) <= 1:
            raise ValueError("There are not enough subjects in the input "
                             "data to train the model.")
        if X[0].shape[1] < self.features:
            raise ValueError(
                "There are not enough timepoints to train the model with "
                "{0:d} features.".format(self.features))
        number_trs = X[0].shape[1]
        for subject in range(len(X)):
            assert_all_finite(X[subject])
            if X[subject].shape[1] != number_trs:
                raise ValueError("Different number of alignment timepoints "
                                 "between subjects.")

        dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
        stacked, voxel_counts, _, _ = _stack_and_pad(X, dtype, demean=False)
        # host-side content digest (float64-reproducible; not
        # degenerate for z-scored data), taken before device placement
        data_digest = array_digest(stacked) if checkpoint_dir else 0.0
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from ..parallel.mesh import (DEFAULT_SUBJECT_AXIS,
                                         place_on_mesh)
            stacked = place_on_mesh(
                stacked, NamedSharding(
                    self.mesh,
                    PartitionSpec(DEFAULT_SUBJECT_AXIS, None, None)))

        key = jax.random.PRNGKey(self.rand_seed)
        stacked_j = jnp.asarray(stacked)
        counts_j = jnp.asarray(voxel_counts).astype(dtype)
        if checkpoint_dir is None:
            w, s, r, objective = _fit_rsrm(
                stacked_j, counts_j, key, self.gamma,
                features=self.features, n_iter=self.n_iter)
        else:
            w, s, r, objective = self._fit_checkpointed(
                stacked_j, counts_j, key, dtype, data_digest,
                checkpoint_dir, checkpoint_every)
        w = np.asarray(w)
        s = np.asarray(s)
        self.w_ = [w[i, :voxel_counts[i]] for i in range(len(X))]
        self.s_ = [s[i, :voxel_counts[i]] for i in range(len(X))]
        self.r_ = np.asarray(r)
        self.objective_ = float(objective)
        check_state({"w": w, "s": s, "r": self.r_,
                     "objective": self.objective_},
                    iteration=self.n_iter, where="RSRM.fit")
        return self

    def _fit_checkpointed(self, stacked, counts_j, key, dtype,
                          data_digest, checkpoint_dir,
                          checkpoint_every):
        """Chunked BCD under the resilient-loop driver (guard +
        rollback + checkpoint/resume + fault hooks)."""
        n_subjects, voxels_pad, trs = stacked.shape
        fingerprint = np.array(
            [data_digest, float(trs),
             float(voxels_pad), float(n_subjects),
             float(self.features), float(self.rand_seed),
             float(self.gamma)])
        template = {
            "w": np.zeros((n_subjects, voxels_pad, self.features),
                          dtype=dtype),
            "s": np.zeros((n_subjects, voxels_pad, trs), dtype=dtype),
            "r": np.zeros((self.features, trs), dtype=dtype),
        }
        w0 = _init_w(key, voxels_pad, n_subjects, self.features,
                     counts_j, dtype=dtype)
        s0 = jnp.zeros_like(stacked)
        r0 = _shared_response(stacked, s0, w0, n_subjects)
        init_state = {"w": np.asarray(w0), "s": np.asarray(s0),
                      "r": np.asarray(r0)}

        run_chunk, final_leaves = make_device_carry_chunk(
            lambda dev, n: _rsrm_chunk(stacked, *dev, self.gamma,
                                       n_steps=n),
            ("w", "s", "r"), dtype=dtype)
        state, step = run_resilient_loop(
            run_chunk, init_state, self.n_iter,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            fingerprint=fingerprint, template=template,
            name="RSRM.fit")
        w, s, r = final_leaves(state, step)
        return w, s, r, _rsrm_objective(stacked, w, s, r, self.gamma)

    def transform(self, X):
        """Returns (shared responses, individual terms) for new data
        (reference rsrm.py:157-191)."""
        if not hasattr(self, 'w_'):
            raise NotFittedError("The model fit has not been run yet.")
        if len(X) != len(self.w_):
            raise ValueError("The number of subjects does not match the one"
                             " in the model.")
        r = [None] * len(X)
        s = [None] * len(X)
        for subject in range(len(X)):
            if X[subject] is not None:
                rj, sj = _transform_new_data(
                    jnp.asarray(X[subject]), jnp.asarray(self.w_[subject]),
                    self.gamma, self.n_iter)
                r[subject] = np.asarray(rj)
                s[subject] = np.asarray(sj)
        return r, s

    def transform_subject(self, X):
        """Returns (w, s) for a held-out subject (reference
        rsrm.py:222-254)."""
        if not hasattr(self, 'w_'):
            raise NotFittedError("The model fit has not been run yet.")
        if X.shape[1] != self.r_.shape[1]:
            raise ValueError("The number of timepoints(TRs) does not match "
                             "the one in the model.")
        w, s = _transform_subject(jnp.asarray(X), jnp.asarray(self.r_),
                                  self.gamma, self.n_iter)
        return np.asarray(w), np.asarray(s)
