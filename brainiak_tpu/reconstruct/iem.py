"""Inverted encoding models (IEM), TPU-native.

Re-design of /root/reference/src/brainiak/reconstruct/iem.py: reconstruct a
1-D (circular/half-circular) or 2-D (spatial) stimulus feature from voxel
patterns via idealized basis-function channels.  B = W·C; W estimated by
least squares on training data, channel responses recovered by
pseudo-inverting W on test data.  The pinv/matmul cores run as jitted jnp
ops; everything else is light host orchestration.
"""

import logging
import warnings

import jax.numpy as jnp
import numpy as np
import scipy.stats
from sklearn.base import BaseEstimator
from sklearn.metrics.pairwise import cosine_distances, euclidean_distances

from ..utils.utils import circ_dist

logger = logging.getLogger(__name__)

__all__ = ["InvertedEncoding1D", "InvertedEncoding2D"]

MAX_CONDITION_CHECK = 9000


class InvertedEncoding1D(BaseEstimator):
    """1-D inverted encoding model over a circular or half-circular feature
    domain with half-wave-rectified exponentiated sinusoid channels
    (reference iem.py:67-462)."""

    def __init__(self, n_channels=6, channel_exp=5,
                 stimulus_mode='halfcircular', range_start=0.,
                 range_stop=180., channel_density=180,
                 stimulus_resolution=None):
        self.n_channels = n_channels
        self.channel_exp = channel_exp
        self.stimulus_mode = stimulus_mode
        self.range_start = range_start
        self.range_stop = range_stop
        self.channel_density = channel_density
        self.channel_domain = np.linspace(range_start, range_stop - 1,
                                          channel_density)
        self.stim_res = (channel_density if stimulus_resolution is None
                         else stimulus_resolution)
        self._check_params()

    def _check_params(self):
        if self.range_start >= self.range_stop:
            raise ValueError("range_start {} must be less than "
                             "{} range_stop.".format(self.range_start,
                                                     self.range_stop))
        span = self.range_stop - self.range_start
        if self.stimulus_mode == 'halfcircular' and span != 180.:
            raise ValueError("For half-circular feature spaces, the range "
                             "must be 180 degrees, not {}".format(span))
        if self.stimulus_mode == 'circular' and span != 360.:
            raise ValueError("For circular feature spaces, the range must "
                             "be 360 degrees, not {}".format(span))
        if self.n_channels < 2:
            raise ValueError("Insufficient number of channels.")
        if self.stimulus_mode not in ('circular', 'halfcircular'):
            raise ValueError("Stimulus mode must be one of these: "
                             "'circular', 'halfcircular'")

    def _define_channels(self):
        """Exponentiated-cosine channels over the domain
        (reference iem.py:340-365)."""
        channel_centers = np.linspace(np.deg2rad(self.range_start),
                                      np.deg2rad(self.range_stop),
                                      self.n_channels + 1)[:-1]
        if self.stimulus_mode == 'circular':
            domain = self.channel_domain * 0.5
            centers = channel_centers * 0.5
        else:
            domain = self.channel_domain
            centers = channel_centers
        channels = np.abs(np.asarray(
            [np.cos(np.deg2rad(domain) - cx) ** self.channel_exp
             for cx in centers]))
        return channels, channel_centers

    def _define_trial_activations(self, stimuli):
        """Predicted channel responses per trial (reference
        iem.py:367-404)."""
        stim_axis = np.linspace(self.range_start, self.range_stop - 1,
                                self.stim_res)
        stimuli = np.asarray(stimuli, dtype=float)
        if self.range_start > 0:
            stimuli = stimuli + self.range_start
        elif self.range_start < 0:
            stimuli = stimuli - self.range_start
        one_hot = np.eye(self.stim_res)
        indices = [np.argmin(abs(stim_axis - x)) for x in stimuli]
        stimulus_mask = one_hot[indices, :]
        if self.channel_density != self.stim_res:
            if self.channel_density % self.stim_res == 0:
                stimulus_mask = np.repeat(
                    stimulus_mask, self.channel_density // self.stim_res,
                    axis=1)
            else:
                raise NotImplementedError(
                    "Stimulus resolution must evenly divide the channel "
                    "density")
        C = stimulus_mask @ self.channels_.T
        if np.linalg.matrix_rank(C) < self.n_channels:
            warnings.warn("Stimulus matrix is {}, not full rank. May cause "
                          "issues with stimulus prediction/reconstruction."
                          .format(np.linalg.matrix_rank(C)),
                          RuntimeWarning)
        return C

    def fit(self, X, y):
        """Estimate W from training betas X [trials, voxels] and features y
        (reference iem.py:212-253)."""
        X = np.asarray(X)
        if np.linalg.cond(X) > MAX_CONDITION_CHECK:
            raise ValueError("Data matrix is nearly singular.")
        if X.shape[0] < self.n_channels:
            raise ValueError("Fewer observations (trials) than channels. "
                             "Cannot compute pseudoinverse.")
        if X.ndim != 2:
            raise ValueError("Data matrix has too many or too few "
                             "dimensions.")
        if X.shape[0] != np.shape(y)[0]:
            raise ValueError("Mismatched data samples and label samples")

        self.channels_, self.channel_centers_ = self._define_channels()
        C = self._define_trial_activations(y)
        self.W_ = np.asarray(
            jnp.asarray(X).T @ jnp.linalg.pinv(jnp.asarray(C).T))
        if np.linalg.cond(self.W_) > MAX_CONDITION_CHECK:
            raise ValueError("Weight matrix is nearly singular.")
        return self

    def _predict_channel_responses(self, X):
        return np.asarray(jnp.linalg.pinv(jnp.asarray(self.W_))
                          @ jnp.asarray(X).T)

    def _predict_feature_responses(self, X):
        return self.channels_.T @ self._predict_channel_responses(X)

    def _predict_features(self, X):
        pred_response = self._predict_feature_responses(X)
        return self.channel_domain[np.argmax(pred_response, 0)]

    def predict(self, X):
        """Predicted feature per observation (reference iem.py:255-276)."""
        X = np.asarray(X)
        if X.ndim != 2:
            raise ValueError("Data matrix has too many or too few "
                             "dimensions.")
        return self._predict_features(X)

    def score(self, X, y):
        """Circular R² of predictions (reference iem.py:278-309)."""
        pred_features = self.predict(X)
        y = np.asarray(y, dtype=float)
        if self.stimulus_mode == 'halfcircular':
            pred_features = pred_features * 2
            y = y * 2
        ssres = (circ_dist(np.deg2rad(y),
                           np.deg2rad(pred_features)) ** 2).sum()
        sstot = (circ_dist(np.deg2rad(y),
                           np.ones(y.size) * scipy.stats.circmean(
                               np.deg2rad(y))) ** 2).sum()
        return 1 - ssres / sstot

    def get_params(self, deep=True):
        return {"n_channels": self.n_channels,
                "channel_exp": self.channel_exp,
                "stimulus_mode": self.stimulus_mode,
                "range_start": self.range_start,
                "range_stop": self.range_stop,
                "channel_domain": self.channel_domain,
                "stim_res": self.stim_res}

    def set_params(self, **parameters):
        for parameter, value in parameters.items():
            setattr(self, parameter, value)
        self.channel_domain = np.linspace(
            self.range_start, self.range_stop - 1, self.channel_density)
        self._check_params()
        return self


class InvertedEncoding2D(BaseEstimator):
    """2-D spatial inverted encoding model with exponentiated-cosine
    channels on square or triangular grids (reference iem.py:464-1050)."""

    def __init__(self, stim_xlim, stim_ylim, stimulus_resolution,
                 stim_radius=None, chan_xlim=None, chan_ylim=None,
                 channels=None, channel_exp=7):
        if not (hasattr(stim_xlim, "__len__") and len(stim_xlim) == 2 and
                hasattr(stim_ylim, "__len__") and len(stim_ylim) == 2):
            raise ValueError("Stimulus limits should be a sequence, "
                             "2 values")
        if np.isscalar(stimulus_resolution):
            stimulus_resolution = [stimulus_resolution,
                                   stimulus_resolution]
        self.stim_fov = [list(stim_xlim), list(stim_ylim)]
        self.stim_pixels = [
            np.linspace(stim_xlim[0], stim_xlim[1],
                        int(stimulus_resolution[0])),
            np.linspace(stim_ylim[0], stim_ylim[1],
                        int(stimulus_resolution[1]))]
        self.xp, self.yp = np.meshgrid(self.stim_pixels[0],
                                       self.stim_pixels[1])
        self.stim_radius_px = stim_radius
        self.channels = channels
        self.n_channels = None if channels is None else channels.shape[0]
        self.channel_limits = [
            list(stim_xlim) if chan_xlim is None else list(chan_xlim),
            list(stim_ylim) if chan_ylim is None else list(chan_ylim)]
        self.channel_exp = channel_exp
        self._check_params()

    def _check_params(self):
        if self.stim_fov[0][0] >= self.stim_fov[0][1] or \
                self.stim_fov[1][0] >= self.stim_fov[1][1]:
            raise ValueError("Stimulus x or y limits should be ascending "
                             "values")
        if self.channels is not None and \
                self.channels.shape[1] != self.xp.size:
            raise ValueError(
                "Defined {} channels over {} pixels, but there are {} "
                "pixels in the stimulus space".format(
                    self.channels.shape[0], self.channels.shape[1],
                    self.xp.size))

    # -- basis construction ----------------------------------------------
    def _make_2d_cosine(self, x, y, x_center, y_center, s):
        """Exponentiated 2-D cosine bumps of radius s
        (reference iem.py:989-1020)."""
        x = np.asarray(x).reshape(-1)
        y = np.asarray(y).reshape(-1)
        x_center = np.asarray(x_center).reshape(-1)
        y_center = np.asarray(y_center).reshape(-1)
        r = np.sqrt((x[None, :] - x_center[:, None]) ** 2 +
                    (y[None, :] - y_center[:, None]) ** 2)
        zp = (0.5 * (1 + np.cos(np.minimum(r / s, 1.0) * np.pi))) \
            ** self.channel_exp
        return zp * (r <= s)

    def _2d_cosine_sz_to_fwhm(self, size_constant):
        return 2 * size_constant * np.arccos(
            (0.5 ** (1 / self.channel_exp) - 0.5) / 0.5) / np.pi

    def _2d_cosine_fwhm_to_sz(self, fwhm):
        return (0.5 * np.pi * fwhm) / np.arccos(
            (0.5 ** (1 / self.channel_exp) - 0.5) / 0.5)

    def define_basis_functions_sqgrid(self, nchannels, channel_size=None):
        """Square grid of channels (reference iem.py:1045-1090)."""
        if not isinstance(nchannels, list):
            nchannels = [nchannels, nchannels]
        cxs = np.linspace(self.channel_limits[0][0],
                          self.channel_limits[0][1], nchannels[0])
        cys = np.linspace(self.channel_limits[1][0],
                          self.channel_limits[1][1], nchannels[1])
        cx, cy = np.meshgrid(cxs, cys)
        cx = cx.reshape(-1)
        cy = cy.reshape(-1)
        if channel_size is None:
            channel_size = 1.2 * (cxs[1] - cxs[0])
        cos_width = self._2d_cosine_fwhm_to_sz(channel_size)
        self.channels = self._make_2d_cosine(self.xp, self.yp, cx, cy,
                                             cos_width)
        self.n_channels = self.channels.shape[0]
        return self.channels, np.column_stack([cx, cy])

    def define_basis_functions_trigrid(self, grid_radius,
                                       channel_size=None):
        """Triangular (hexagonal) grid of channels
        (reference iem.py:1092-1140)."""
        x_dist = np.diff(self.channel_limits[0]).item() / (grid_radius * 2)
        y_dist = x_dist * np.sqrt(3) * 0.5
        pts = []
        xbase = np.arange(self.channel_limits[0][0],
                          self.channel_limits[0][1], x_dist)
        for yi, y in enumerate(np.arange(self.channel_limits[1][0],
                                         self.channel_limits[1][1],
                                         y_dist)):
            xx = xbase.copy() if yi % 2 == 0 else xbase + x_dist / 2
            pts.append(np.column_stack([xx, np.full(xx.size, y)]))
        trigrid = np.vstack(pts)
        if channel_size is None:
            channel_size = 1.1 * x_dist
        cos_width = self._2d_cosine_fwhm_to_sz(channel_size)
        self.channels = self._make_2d_cosine(
            self.xp, self.yp, trigrid[:, 0], trigrid[:, 1], cos_width)
        self.n_channels = self.channels.shape[0]
        return self.channels, trigrid

    # -- design ----------------------------------------------------------
    def _define_trial_activations(self, stim_centers, stim_radius=None):
        """Channel responses of circular stimuli (reference
        iem.py:1127-1172)."""
        stim_centers = np.asarray(stim_centers)
        nstim = stim_centers.shape[0]
        if stim_radius is not None:
            self.stim_radius_px = stim_radius
        if self.stim_radius_px is None:
            raise ValueError("No defined stimulus radius. Please set.")
        radii = np.ones(nstim) * np.asarray(self.stim_radius_px)
        masks = np.zeros((nstim, self.xp.size))
        flat_x = self.xp.reshape(-1)
        flat_y = self.yp.reshape(-1)
        for i in range(nstim):
            r = np.sqrt((flat_x - stim_centers[i, 0]) ** 2 +
                        (flat_y - stim_centers[i, 1]) ** 2)
            masks[i] = (r <= radii[i]) * 1.0
        return masks @ self.channels.T

    # -- estimation ------------------------------------------------------
    def fit(self, X, y, C=None):
        """Estimate W from betas X [trials, voxels] and stimulus centers y
        [trials, 2] (or an explicit design C) (reference iem.py:667-710)."""
        self._check_params()  # channels may have changed (ref iem.py:810)
        X = np.asarray(X)
        if np.linalg.cond(X) > MAX_CONDITION_CHECK:
            raise ValueError("Data matrix is nearly singular.")
        if self.channels is None:
            raise ValueError("Must define channels (set of basis "
                             "functions).")
        if X.shape[0] < self.n_channels:
            raise ValueError("Fewer observations (trials) than channels. "
                             "Cannot compute pseudoinverse.")
        if C is None:
            C = self._define_trial_activations(y)
        if X.shape[0] != C.shape[0]:
            raise ValueError("Mismatched data samples and label samples")
        self.W_ = np.asarray(
            jnp.asarray(X).T @ jnp.linalg.pinv(jnp.asarray(C).T))
        if np.linalg.cond(self.W_) > MAX_CONDITION_CHECK:
            raise ValueError("Weight matrix is nearly singular.")
        return self

    def _predict_channel_responses(self, X):
        return np.asarray(jnp.linalg.pinv(jnp.asarray(self.W_))
                          @ jnp.asarray(X).T)

    def predict_feature_responses(self, X):
        """Reconstruction in the pixel domain [n_pixels, observations]
        (reference iem.py:1189-1206)."""
        return self.channels.T @ self._predict_channel_responses(X)

    def _predict_features(self, X):
        pred_response = self.predict_feature_responses(X)
        idx = np.argmax(pred_response, axis=0)
        return np.column_stack([self.xp.reshape(-1)[idx],
                                self.yp.reshape(-1)[idx]])

    def predict(self, X):
        """Predicted (x, y) per observation (reference iem.py:712-732)."""
        X = np.asarray(X)
        if X.ndim != 2:
            raise ValueError("Data matrix has too many or too few "
                             "dimensions.")
        return self._predict_features(X)

    def score(self, X, y):
        """Per-observation R² against expected maxima (reference
        iem.py:735-758)."""
        pred_features = self.predict(X)
        y = np.asarray(y, dtype=float)
        ssres = np.sum((pred_features - y) ** 2, axis=1)
        sstot = np.sum((y - np.mean(y)) ** 2, axis=1)
        return 1 - ssres / sstot

    def score_against_reconstructed(self, X, y, metric="euclidean"):
        """Distance between reconstructions and expected pixel-domain
        patterns (reference iem.py:760-790)."""
        yhat = self.predict_feature_responses(X)
        if metric == "euclidean":
            score_value = euclidean_distances(y.T, yhat.T)
        elif metric == "cosine":
            score_value = cosine_distances(y.T, yhat.T)
        else:
            raise ValueError("metric must be 'euclidean' or 'cosine'")
        return score_value[0, :]

    def get_params(self, deep=True):
        return {"n_channels": self.n_channels,
                "channel_exp": self.channel_exp,
                "stim_fov": self.stim_fov,
                "stim_pixels": self.stim_pixels,
                "stim_radius_px": self.stim_radius_px, "xp": self.xp,
                "yp": self.yp, "channels": self.channels,
                "channel_limits": self.channel_limits}

    def set_params(self, **parameters):
        for parameter, value in parameters.items():
            setattr(self, parameter, value)
        self._check_params()
        return self
