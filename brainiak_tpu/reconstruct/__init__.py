"""Inverted encoding models (1D and 2D feature reconstruction)."""

from .iem import InvertedEncoding1D, InvertedEncoding2D  # noqa: F401
