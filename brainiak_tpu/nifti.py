"""Minimal pure-NumPy NIfTI-1 codec.

The reference delegates NIfTI I/O to nibabel (reference io.py:28); this
framework ships a small self-contained codec instead so the data plane has no
external imaging dependency.  Supports single-file ``.nii`` / ``.nii.gz``
(magic ``n+1``) and header-pair magic ``ni1`` data read, the common dtypes,
scl_slope/scl_inter scaling, and sform/qform/pixdim affines.  Only the
features the framework needs — not a general neuroimaging library.
"""

import gzip
import struct
import zlib

import numpy as np

from .resilience import faults
from .resilience.retry import retry

__all__ = ["NiftiImage", "load", "save"]

_DTYPES = {
    2: np.dtype(np.uint8),
    4: np.dtype(np.int16),
    8: np.dtype(np.int32),
    16: np.dtype(np.float32),
    64: np.dtype(np.float64),
    256: np.dtype(np.int8),
    512: np.dtype(np.uint16),
    768: np.dtype(np.uint32),
    1024: np.dtype(np.int64),
    1280: np.dtype(np.uint64),
}
_DTYPE_CODES = {v: k for k, v in _DTYPES.items()}

_HDR_SIZE = 348


class NiftiImage:
    """In-memory NIfTI image: data array + 4x4 affine (+ raw header dict).

    API-compatible with the subset of nibabel's ``SpatialImage`` the
    framework uses: ``get_fdata()``, ``affine``, ``shape``, ``dataobj``.
    """

    def __init__(self, dataobj, affine=None, header=None):
        self.dataobj = np.asarray(dataobj)
        self.affine = (np.eye(4) if affine is None
                       else np.asarray(affine, dtype=np.float64))
        self.header = dict(header or {})

    @property
    def shape(self):
        return self.dataobj.shape

    def get_fdata(self):
        """Data as float64 with scl_slope/inter applied (nibabel semantics)."""
        data = self.dataobj.astype(np.float64)
        slope = self.header.get("scl_slope", 0.0)
        inter = self.header.get("scl_inter", 0.0)
        if slope not in (0.0, 1.0) and np.isfinite(slope):
            data = data * slope + inter
        elif slope == 1.0 and inter not in (0.0,) and np.isfinite(inter):
            data = data + inter
        return data


def _quaternion_to_rotation(b, c, d):
    a2 = 1.0 - (b * b + c * c + d * d)
    a = np.sqrt(max(a2, 0.0))
    return np.array([
        [a * a + b * b - c * c - d * d, 2 * (b * c - a * d),
         2 * (b * d + a * c)],
        [2 * (b * c + a * d), a * a + c * c - b * b - d * d,
         2 * (c * d - a * b)],
        [2 * (b * d - a * c), 2 * (c * d + a * b),
         a * a + d * d - b * b - c * c],
    ])


@retry(retries=3, backoff=0.25,
       retriable=(OSError, EOFError, zlib.error), name="nifti.read")
def _read_bytes(path):
    # Shared-filesystem reads of subject images are the transient-
    # failure hot spot of long multi-subject jobs; retry with backoff.
    # A truncated .nii.gz mid-restage surfaces as EOFError or
    # zlib.error (NOT OSError subclasses; only BadGzipFile is), so
    # those are retriable too.  The faults hook lets tests inject the
    # failure deterministically.
    faults.io_point(path, site="nifti.read")
    path = str(path)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        return f.read()


def load(path):
    """Load a ``.nii`` / ``.nii.gz`` file into a :class:`NiftiImage`.

    Reads retry transient failures (``OSError``, truncated-gzip
    ``EOFError``/``zlib.error``) with exponential backoff (see
    :mod:`brainiak_tpu.resilience.retry`), so a momentary shared-
    filesystem hiccup does not kill an hours-long multi-subject fit.
    """
    raw = _read_bytes(path)
    if len(raw) < _HDR_SIZE:
        raise ValueError(f"{path}: too short to be a NIfTI-1 file")

    # Endianness detection via sizeof_hdr.
    for endian in ("<", ">"):
        if struct.unpack(endian + "i", raw[0:4])[0] == _HDR_SIZE:
            break
    else:
        raise ValueError(f"{path}: not a NIfTI-1 file (bad sizeof_hdr)")

    magic = raw[344:348]
    if magic[:3] not in (b"n+1", b"ni1"):
        raise ValueError(f"{path}: unsupported NIfTI magic {magic!r}")

    dim = struct.unpack(endian + "8h", raw[40:56])
    ndim = dim[0]
    if not 1 <= ndim <= 7:
        raise ValueError(f"{path}: invalid ndim {ndim}")
    shape = tuple(int(d) for d in dim[1:1 + ndim])

    datatype, = struct.unpack(endian + "h", raw[70:72])
    if datatype not in _DTYPES:
        raise ValueError(f"{path}: unsupported NIfTI datatype {datatype}")
    dtype = _DTYPES[datatype].newbyteorder(endian)

    pixdim = struct.unpack(endian + "8f", raw[76:108])
    vox_offset, = struct.unpack(endian + "f", raw[108:112])
    scl_slope, scl_inter = struct.unpack(endian + "2f", raw[112:120])
    qform_code, sform_code = struct.unpack(endian + "2h", raw[252:256])

    if sform_code > 0:
        srow = np.frombuffer(raw[280:328], dtype=np.dtype(np.float32)
                             .newbyteorder(endian)).reshape(3, 4)
        affine = np.vstack([srow.astype(np.float64), [0, 0, 0, 1]])
    elif qform_code > 0:
        b, c, d = struct.unpack(endian + "3f", raw[256:268])
        offsets = struct.unpack(endian + "3f", raw[268:280])
        rot = _quaternion_to_rotation(b, c, d)
        qfac = -1.0 if pixdim[0] == -1.0 else 1.0
        zooms = np.array([pixdim[1], pixdim[2], pixdim[3] * qfac])
        affine = np.eye(4)
        affine[:3, :3] = rot * zooms
        affine[:3, 3] = offsets
    else:
        affine = np.diag([pixdim[1] or 1.0, pixdim[2] or 1.0,
                          pixdim[3] or 1.0, 1.0])

    offset = int(vox_offset) if magic[:3] == b"n+1" else _HDR_SIZE
    count = int(np.prod(shape))
    data = np.frombuffer(raw, dtype=dtype, count=count, offset=offset)
    # NIfTI stores Fortran order (x fastest).
    data = data.reshape(shape, order="F")

    header = {
        "scl_slope": float(scl_slope), "scl_inter": float(scl_inter),
        "pixdim": tuple(float(p) for p in pixdim),
        "datatype": int(datatype),
        "qform_code": int(qform_code), "sform_code": int(sform_code),
    }
    return NiftiImage(data, affine, header)


def save(img, path):
    """Save a :class:`NiftiImage` (or (data, affine)) as single-file NIfTI-1.

    Gzip-compresses when the filename ends in ``.gz``.
    """
    if not isinstance(img, NiftiImage):
        raise TypeError("save() expects a NiftiImage")
    data = np.asarray(img.dataobj)
    if data.dtype not in _DTYPE_CODES:
        data = data.astype(np.float32)
    datatype = _DTYPE_CODES[data.dtype]
    bitpix = data.dtype.itemsize * 8
    affine = np.asarray(img.affine, dtype=np.float64)

    hdr = bytearray(_HDR_SIZE)
    struct.pack_into("<i", hdr, 0, _HDR_SIZE)
    dim = [data.ndim] + list(data.shape) + [1] * (7 - data.ndim)
    struct.pack_into("<8h", hdr, 40, *dim)
    struct.pack_into("<h", hdr, 70, datatype)
    struct.pack_into("<h", hdr, 72, bitpix)
    zooms = np.sqrt((affine[:3, :3] ** 2).sum(axis=0))
    pixdim = [1.0] + list(zooms) + [1.0] * 4
    struct.pack_into("<8f", hdr, 76, *pixdim)
    struct.pack_into("<f", hdr, 108, 352.0)  # vox_offset
    struct.pack_into("<2f", hdr, 112, 1.0, 0.0)  # scl_slope/inter
    struct.pack_into("<2h", hdr, 252, 0, 2)  # qform_code=0, sform_code=2
    struct.pack_into("<4f", hdr, 280, *affine[0])
    struct.pack_into("<4f", hdr, 296, *affine[1])
    struct.pack_into("<4f", hdr, 312, *affine[2])
    hdr[344:348] = b"n+1\x00"

    payload = bytes(hdr) + b"\x00" * 4 + data.tobytes(order="F")
    path = str(path)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(payload)
