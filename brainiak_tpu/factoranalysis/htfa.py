"""Hierarchical Topographical Factor Analysis (HTFA), TPU-native.

Re-design of /root/reference/src/brainiak/factoranalysis/htfa.py.  A global
template over factor centers/widths (mean + covariance/variance) is
MAP-updated from per-subject TFA posteriors.

Distribution design: the reference scatters subjects over MPI ranks and
stitches posteriors with Bcast/Gatherv (htfa.py:515-558, :672-764).  Here
the per-subject inner TFA iteration (masked ridge weight solve + bounded
L-BFGS over centers/widths) is ONE vmapped XLA program over the subject
axis (:func:`_batched_subject_step`); with ``mesh=`` the subject axis is
sharded over the mesh so GSPMD runs each shard's subjects on its own
devices, and fetching the [S, prior_size] posterior output is the
all_gather.  The MAP update of the K·(n_dim+1)-sized template is tiny and
stays replicated on host, as SURVEY.md §2.2 row 4 prescribes.  Ragged
voxel counts batch via zero-masked padding (same recipe as SRM's exact
zero-padding).

Deviation noted: the reference's ``_assign_posterior`` (htfa.py:560-590)
reorders only the covariance/variance fields by the Hungarian assignment
while leaving centers/widths unpermuted — inconsistent with TFA's version;
here all four fields are reordered consistently.
"""

import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec
from ..obs import profile as obs_profile
from ..ops.optimize import minimize_bounded
from ..ops.rbf import (rbf_factors, rbf_residual_sum,
                       rbf_weight_products)
from ..parallel.mesh import DEFAULT_SUBJECT_AXIS, place_on_mesh
from ..resilience.guards import (array_digest, check_state,
                                 run_resilient_loop)
from ..utils.utils import from_sym_2_tri, from_tri_2_sym
from .tfa import TFA, _full_sym, _match_centers, _rho_sum

logger = logging.getLogger(__name__)

__all__ = ["HTFA"]


@partial(jax.jit, static_argnames=("K", "n_dim", "nlss_loss", "max_iters"))
def _batched_subject_step(data, R, vmask, tmask, centers, widths, lower,
                          upper, beta, data_sigma, sample_scaling,
                          tmpl_centers, tmpl_cov_inv, tmpl_widths,
                          tmpl_reci, *, K, n_dim, nlss_loss, max_iters):
    """One inner TFA iteration for ALL subjects as a single XLA program.

    Per subject: masked ridge solve for the weight matrix, then bounded
    L-BFGS over packed (centers, widths) with the template penalty —
    vmapped over the leading (mesh-shardable) subject axis.  Replaces the
    reference's per-rank subject loop (reference htfa.py:732-744).
    Padding rows/columns are zero-masked so ragged subsample sizes batch
    cleanly; the template fields are replicated across subjects.

    data [S, V, T]; R [S, V, n_dim]; vmask [S, V]; tmask [S, T];
    centers [S, K, n_dim]; widths [S, K]; lower/upper [S, K*(n_dim+1)];
    beta/data_sigma/sample_scaling [S].  Returns (x [S, K*(n_dim+1)],
    cost [S]).
    """

    def one(data_s, R_s, vmask_s, tmask_s, c_s, w_s, lo_s, hi_s,
            beta_s, sigma_s, scaling_s):
        mask2d = vmask_s[:, None] * tmask_s[None, :]
        x_m = data_s * mask2d
        # MTTKRP-style fused contractions (ops.rbf): the masked
        # factor matrix is reconstructed chunk-by-chunk inside the
        # FᵀF/FᵀX products and the residual reduction, never
        # materializing [V, K] per subject per L-BFGS iteration
        g, b = rbf_weight_products(R_s, c_s, w_s, x_m,
                                   vmask=vmask_s)
        W = jnp.linalg.solve(
            g + beta_s * jnp.eye(K, dtype=g.dtype), b)
        init = jnp.concatenate([c_s.ravel(), w_s])

        def objective(params):
            cc = params[:K * n_dim].reshape(K, n_dim)
            ww = params[K * n_dim:]
            total = rbf_residual_sum(R_s, cc, ww, x_m, W, sigma_s,
                                     vmask=vmask_s, tmask=tmask_s,
                                     nlss_loss=nlss_loss)
            diff = cc - tmpl_centers
            maha = jnp.einsum('kd,kde,ke->k', diff, tmpl_cov_inv, diff)
            total = total + _rho_sum(scaling_s * maha, nlss_loss)
            wdist = scaling_s * tmpl_reci * (ww - tmpl_widths) ** 2
            total = total + _rho_sum(wdist, nlss_loss)
            return 0.5 * total

        return minimize_bounded(objective, init, lo_s, hi_s,
                                max_iters=max_iters)

    return jax.vmap(
        one,
        in_axes=(0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0))(
            data, R, vmask, tmask, centers, widths, lower, upper,
            beta, data_sigma, sample_scaling)


# cost attribution for the all-subjects inner-step program
_batched_subject_step = obs_profile.profile_program(
    _batched_subject_step, "htfa.subject_step", span="fit_chunk",
    estimator="HTFA.fit")


class HTFA(TFA):
    """Hierarchical TFA over multiple subjects (reference htfa.py:62-841).

    Parameters follow the reference: K, n_subj, max_global_iter /
    max_local_iter, threshold, weight_method, bounds ratios, subsampling
    ratios/caps (voxel_ratio, tr_ratio, max_voxel, max_tr).

    Attributes after fit: ``global_prior_``, ``global_posterior_``,
    ``local_posterior_`` (concatenated per-subject centers+widths),
    ``local_weights_`` (concatenated per-subject weight matrices).
    """

    def __init__(self, K, n_subj, max_global_iter=10, max_local_iter=10,
                 threshold=0.01, nlss_method='trf', nlss_loss='soft_l1',
                 jac='2-point', x_scale='jac', tr_solver=None,
                 weight_method='rr', upper_ratio=1.8, lower_ratio=0.02,
                 voxel_ratio=0.25, tr_ratio=0.1, max_voxel=5000,
                 max_tr=500, verbose=False, lbfgs_iters=60, mesh=None,
                 shard_subjects=None):
        self.K = K
        self.n_subj = n_subj
        self.max_global_iter = max_global_iter
        self.max_local_iter = max_local_iter
        self.threshold = threshold
        self.nlss_method = nlss_method
        self.nlss_loss = nlss_loss
        self.jac = jac
        self.x_scale = x_scale
        self.tr_solver = tr_solver
        self.weight_method = weight_method
        self.upper_ratio = upper_ratio
        self.lower_ratio = lower_ratio
        self.voxel_ratio = voxel_ratio
        self.tr_ratio = tr_ratio
        self.max_voxel = max_voxel
        self.max_tr = max_tr
        self.verbose = verbose
        self.lbfgs_iters = lbfgs_iters
        self.mesh = mesh
        #: subjects per streamed shard when ``fit`` is handed a
        #: :class:`~brainiak_tpu.data.store.SubjectStore` (None: one
        #: mesh-subject-axis width, else 8); ignored for in-memory
        #: subject lists.
        self.shard_subjects = shard_subjects
        self._store = None

    # -- convergence over the global template -----------------------------
    def _converged(self):
        prior = self.global_prior_[0:self.prior_size]
        posterior = self.global_posterior_[0:self.prior_size]
        diff = prior - posterior
        max_diff = np.max(np.fabs(diff))
        if self.verbose:
            # the reference's verbose diagnostics (htfa.py:209-214)
            _, mse = self._mse_converged()
            diff_ratio = np.sum(diff ** 2) / np.sum(posterior ** 2)
            logger.info('htfa prior posterior max diff %f mse %f '
                        'diff_ratio %f', max_diff, mse, diff_ratio)
        return max_diff <= self.threshold, max_diff

    def _mse_converged(self):
        prior = self.global_prior_[0:self.prior_size]
        posterior = self.global_posterior_[0:self.prior_size]
        mse = np.mean((prior - posterior) ** 2)
        return mse <= self.threshold, mse

    # -- MAP update -------------------------------------------------------
    def _map_update(self, prior_mean, prior_cov, global_cov_scaled,
                    new_observation):
        """Gaussian MAP update of one factor's center parameters
        (reference htfa.py:246-288)."""
        common = np.linalg.inv(prior_cov + global_cov_scaled)
        observation_mean = np.mean(new_observation, axis=1)
        posterior_mean = prior_cov.dot(common.dot(observation_mean)) + \
            global_cov_scaled.dot(common.dot(prior_mean))
        posterior_cov = prior_cov.dot(common.dot(global_cov_scaled))
        return posterior_mean, posterior_cov

    def _map_update_posterior(self):
        """MAP-update the global template from gathered subject posteriors
        (reference htfa.py:290-341)."""
        self.global_posterior_ = self.global_prior_.copy()
        prior_centers = self.get_centers(self.global_prior_)
        prior_widths = self.get_widths(self.global_prior_)
        prior_centers_mean_cov = \
            self.get_centers_mean_cov(self.global_prior_)
        prior_widths_mean_var = \
            self.get_widths_mean_var(self.global_prior_)
        center_size = self.K * self.n_dim
        posterior_size = center_size + self.K
        gathered = self.gather_posterior.reshape(self.n_subj,
                                                 posterior_size)
        all_centers = gathered[:, :center_size].reshape(
            self.n_subj, self.K, self.n_dim)
        all_widths = gathered[:, center_size:]
        for k in np.arange(self.K):
            next_centers = all_centers[:, k, :].T  # [n_dim, n_subj]
            next_widths = all_widths[:, k]

            posterior_mean, posterior_cov = self._map_update(
                prior_centers[k].T.copy(),
                from_tri_2_sym(prior_centers_mean_cov[k], self.n_dim),
                self.global_centers_cov_scaled,
                next_centers)
            self.global_posterior_[k * self.n_dim:(k + 1) * self.n_dim] = \
                posterior_mean.T
            start_idx = self.map_offset[2] + k * self.cov_vec_size
            end_idx = self.map_offset[2] + (k + 1) * self.cov_vec_size
            self.global_posterior_[start_idx:end_idx] = \
                from_sym_2_tri(posterior_cov)

            pw_var = float(prior_widths_mean_var[k, 0])
            pw = float(prior_widths[k, 0])
            common = 1.0 / (pw_var + self.global_widths_var_scaled)
            observation_mean = np.mean(next_widths)
            tmp = common * self.global_widths_var_scaled
            self.global_posterior_[self.map_offset[1] + k] = \
                pw_var * common * observation_mean + tmp * pw
            self.global_posterior_[self.map_offset[3] + k] = pw_var * tmp
        return self

    def _assign_posterior(self):
        """Hungarian matching of global posterior factors to the prior,
        reordering all four fields consistently (see module docstring)."""
        prior_centers = self.get_centers(self.global_prior_)
        posterior_centers = self.get_centers(self.global_posterior_)
        posterior_widths = self.get_widths(self.global_posterior_)
        posterior_centers_mean_cov = \
            self.get_centers_mean_cov(self.global_posterior_)
        posterior_widths_mean_var = \
            self.get_widths_mean_var(self.global_posterior_)
        col_ind = _match_centers(prior_centers, posterior_centers)
        self.set_centers(self.global_posterior_,
                         posterior_centers[col_ind])
        self.set_widths(self.global_posterior_, posterior_widths[col_ind])
        self.set_centers_mean_cov(self.global_posterior_,
                                  posterior_centers_mean_cov[col_ind])
        self.set_widths_mean_var(self.global_posterior_,
                                 posterior_widths_mean_var[col_ind])
        return self

    # -- fitting ----------------------------------------------------------
    def _prepare_subject_batch(self, shapes, R):
        """Precompute per-subject subsample sizes, NLLS bounds, and the
        template-penalty scaling (reference htfa.py:697-713 clamps +
        tfa.py:995-999), stacked along the subject axis for batching.
        Only ``shapes`` (per-subject ``(voxels, trs)``) is needed — a
        :class:`SubjectStore` supplies them from its manifest without
        touching the data."""
        self.sub_nvox = [min(self.max_voxel,
                             int(self.voxel_ratio * shp[0]),
                             shp[0]) for shp in shapes]
        self.sub_ntr = [min(self.max_tr,
                            int(self.tr_ratio * shp[1]),
                            shp[1]) for shp in shapes]
        self.sub_scaling = np.array(
            [0.5 * float(nv * nt) / float(shp[0] * shp[1])
             for nv, nt, shp in zip(self.sub_nvox, self.sub_ntr,
                                    shapes)])
        bounds = [self.get_bounds(r) for r in R]
        self.sub_lower = np.stack([b[0] for b in bounds])
        self.sub_upper = np.stack([b[1] for b in bounds])
        # global batch extents: every shard pads to these, so the
        # batched subject-step program keeps ONE shape whether the
        # subjects arrive all at once or shard by shard
        self._vb = max(self.sub_nvox)
        self._tb = max(self.sub_ntr)

    def _gather_subsample_batch(self, data, R, rngs, indices):
        """Draw the stochastic voxel/TR subsample for the subjects in
        ``indices`` and pad to the GLOBAL batch shape.  ``data``/
        ``R``/``rngs`` are index-aligned with ``indices`` (a shard's
        slice); the per-subject draws depend only on that subject's
        own RNG stream, so shard-wise processing reproduces the
        all-subjects batch exactly.  The ragged gather stays on host;
        only the padded batch ships to device."""
        S = len(indices)
        vb, tb = self._vb, self._tb
        n_dim = R[0].shape[1]
        bdata = np.zeros((S, vb, tb))
        bR = np.zeros((S, vb, n_dim))
        vmask = np.zeros((S, vb))
        tmask = np.zeros((S, tb))
        beta = np.zeros(S)
        sigma = np.zeros(S)
        for pos, s in enumerate(indices):
            nv, nt = self.sub_nvox[s], self.sub_ntr[s]
            feat = rngs[pos].choice(data[pos].shape[0], nv,
                                    replace=False)
            samp = rngs[pos].choice(data[pos].shape[1], nt,
                                    replace=False)
            curr = data[pos][feat][:, samp]
            bdata[pos, :nv, :nt] = curr
            bR[pos, :nv] = R[pos][feat]
            vmask[pos, :nv] = 1.0
            tmask[pos, :nt] = 1.0
            beta[pos] = np.var(curr) if self.weight_method == 'rr' \
                else 0.0
            sigma[pos] = np.std(curr) / np.sqrt(2.0)
        return bdata, bR, vmask, tmask, beta, sigma

    def _dispatch_batched_step(self, bdata, bR, vmask, tmask, centers,
                               widths, beta, sigma, tmpl, indices):
        """Run the batched inner step, sharding the subject axis over the
        mesh when one is set.

        A subject count that does not divide the mesh axis is padded to
        the next multiple, with pad lanes ZERO-MASKED rather than
        repeated: data/coords/voxel/TR masks and the template-penalty
        scaling pad with zeros (so the pad objective is identically 0
        and its L-BFGS lane converges on the first iteration instead of
        re-running subject 0's full trajectory), the ridge coefficient
        pads with 1 (keeps the weight solve nonsingular: W = I⁻¹·0 = 0),
        and the box bounds/inits pad by repetition (any valid box).
        SPMD lockstep still executes ceil(S/shards) lanes per shard —
        that cost is forced by static shapes — but pad lanes no longer
        carry a duplicated subject's optimization, and their outputs are
        inert template values rather than copies of a real subject.
        Padded rows are discarded on fetch."""
        S = bdata.shape[0]
        # target lane count: the streamed path pins it to the shard
        # size so a SHORT final shard reuses the compiled program
        # (one batch shape for the whole fit), and a mesh rounds it
        # up to the subject-axis size either way
        target = max(S, getattr(self, "_pad_lanes_to", 0) or 0)
        if self.mesh is not None and \
                DEFAULT_SUBJECT_AXIS in self.mesh.shape:
            axis = self.mesh.shape[DEFAULT_SUBJECT_AXIS]
            target = -(-target // axis) * axis
        pad = target - S

        def prep(a, pad_mode):
            a = np.asarray(a)
            if pad:
                if pad_mode == "zero":
                    fill = np.zeros((pad,) + a.shape[1:], a.dtype)
                elif pad_mode == "one":
                    fill = np.ones((pad,) + a.shape[1:], a.dtype)
                else:  # "repeat": any valid value; bounds/inits
                    fill = np.repeat(a[:1], pad, axis=0)
                a = np.concatenate([a, fill])
            if self.mesh is not None:
                spec = PartitionSpec(DEFAULT_SUBJECT_AXIS,
                                     *([None] * (a.ndim - 1)))
                return place_on_mesh(a, NamedSharding(self.mesh, spec))
            return jnp.asarray(a)

        idx = np.asarray(indices, dtype=int)
        modes = ("zero", "zero", "zero", "zero", "repeat", "repeat",
                 "repeat", "repeat", "one", "repeat", "zero")
        batch = [prep(a, m) for a, m in zip(
                 (bdata, bR, vmask, tmask, centers, widths,
                  self.sub_lower[idx], self.sub_upper[idx], beta,
                  sigma, self.sub_scaling[idx]), modes)]
        if self.mesh is not None:
            tmpl = [place_on_mesh(
                np.asarray(t), NamedSharding(self.mesh, PartitionSpec()))
                for t in tmpl]
        x, cost = _batched_subject_step(
            *batch, *tmpl, K=self.K, n_dim=self.n_dim,
            nlss_loss=self.nlss_loss, max_iters=self.lbfgs_iters)
        # every process needs all subjects' posteriors for the (host,
        # replicated) MAP template update — the analog of the
        # reference's Gatherv+Bcast (htfa.py:746-764)
        from ..parallel.mesh import fetch_replicated
        return (fetch_replicated(x, self.mesh)[:S],
                fetch_replicated(cost, self.mesh)[:S])

    def _match_to_prior(self, prior_vec, posterior_vec):
        """Hungarian-match one subject's posterior factors to its prior
        (functional form of reference tfa.py:242-260)."""
        K, n_dim = self.K, self.n_dim
        pc = prior_vec[:K * n_dim].reshape(K, n_dim)
        qc = posterior_vec[:K * n_dim].reshape(K, n_dim)
        qw = posterior_vec[K * n_dim:]
        col = _match_centers(pc, qc)
        return np.concatenate([qc[col].ravel(), qw[col]])

    def _template_terms(self):
        """The replicated template-penalty terms every subject's inner
        objective shares for one global iteration."""
        K, n_dim = self.K, self.n_dim
        tmpl_centers = self.get_centers(self.global_prior_)
        tmpl_widths = self.get_widths(self.global_prior_).reshape(-1)
        tmpl_tri = self.get_centers_mean_cov(self.global_prior_)
        tmpl_reci = (
            1.0 / self.get_widths_mean_var(self.global_prior_)).reshape(-1)
        tmpl_cov_inv = np.stack(
            [np.linalg.inv(_full_sym(tmpl_tri[k], n_dim))
             for k in range(K)])
        return (tmpl_centers, tmpl_cov_inv, tmpl_widths, tmpl_reci)

    def _fit_subject_shard(self, data, R, indices, global_iter, tmpl):
        """Inner TFA fits for the subjects in ``indices`` (their raw
        arrays in ``data``, index-aligned): the per-shard map step of
        the streamed outer loop, also the whole batch when everything
        is in memory.  Subsampling RNGs are seeded per subject from
        the global iteration, so a subject's draw stream — and hence
        its posterior trajectory — is identical whether it is fitted
        in one all-subjects batch or inside a shard."""
        K, n_dim = self.K, self.n_dim
        B = len(indices)
        rngs = [np.random.RandomState(global_iter * self.max_local_iter)
                for _ in range(B)]
        prior = np.tile(self.global_prior_[:self.prior_size], (B, 1))
        posterior = prior.copy()
        converged = np.zeros(B, dtype=bool)
        for n in range(self.max_local_iter):
            bdata, bR, vmask, tmask, beta, sigma = \
                self._gather_subsample_batch(data, R, rngs, indices)
            centers = prior[:, :K * n_dim].reshape(B, K, n_dim)
            widths = prior[:, K * n_dim:]
            out, _ = self._dispatch_batched_step(
                bdata, bR, vmask, tmask, centers, widths, beta, sigma,
                tmpl, indices)
            for s in np.nonzero(~converged)[0]:
                post_s = self._match_to_prior(prior[s], out[s])
                posterior[s] = post_s
                if np.max(np.abs(prior[s] - post_s)) <= self.threshold:
                    converged[s] = True
                else:
                    prior[s] = post_s
            if converged.all():
                break
        return posterior

    def _fit_subjects(self, data, R, global_iter):
        """All subjects' inner TFA fits for one global iteration.

        Every inner iteration is ONE device dispatch over the batched
        (mesh-sharded) subject axis; the per-subject Hungarian reorder
        and convergence bookkeeping are tiny and stay on host.  The
        returned [n_subj, prior_size] array is the analog of the
        reference's posterior Gatherv (htfa.py:746-749); converged
        subjects are frozen, matching the per-subject early stop of
        TFA._fit_tfa.

        With a :class:`~brainiak_tpu.data.store.SubjectStore` input,
        subjects stream through the shard prefetcher instead: while
        one shard runs its inner L-BFGS rounds on device, the next
        shard's raw arrays load from disk in the background — the
        full subject list is never host-resident at once."""
        tmpl = self._template_terms()
        if self._store is None:
            return self._fit_subject_shard(
                data, R, list(range(self.n_subj)), global_iter, tmpl)

        from ..data.prefetch import ShardPrefetcher, subject_shards

        shards = subject_shards(self.n_subj, self._shard_size)
        posterior = np.zeros((self.n_subj, self.prior_size))
        with ShardPrefetcher(self._store, shards, raw=True,
                             dtype=np.float64) as pf:
            for batch in pf:
                indices = list(range(batch.lo, batch.hi))
                posterior[batch.lo:batch.hi] = self._fit_subject_shard(
                    batch.subjects, [R[s] for s in indices], indices,
                    global_iter, tmpl)
        return posterior

    def _fit_htfa(self, data, R, checkpoint_dir=None,
                  checkpoint_every=5):
        """Outer template loop (reference htfa.py:672-764): batched
        subject fits -> posterior gather -> replicated MAP update.

        Driven by the resilient loop: each global iteration runs under
        the non-finite guard (rollback to the last good template on
        divergence) and, with ``checkpoint_dir``, the template state is
        persisted every ``checkpoint_every`` global iterations for
        preemption-safe resume.  The inner subject fits re-seed their
        subsampling RNGs from the global iteration index, so a resumed
        fit reproduces the uninterrupted iterates exactly."""
        n_subj = len(R)
        shapes = [(int(c), int(self._store.samples))
                  for c in self._store.voxel_counts] \
            if self._store is not None \
            else [d.shape for d in data]
        self._prepare_subject_batch(shapes, R)
        self.local_posterior_ = np.zeros(n_subj * self.prior_size)

        # Template initialized from a random subject's coordinates
        # (reference htfa.py:475-513).  On resume the restored template
        # supersedes this init.
        idx = np.random.choice(n_subj, 1)[0]
        self.global_prior_, self.global_centers_cov, \
            self.global_widths_var = self.get_template(R[idx])
        self.global_posterior_ = self.global_prior_.copy()

        def pack(done):
            return {
                "global_prior": np.asarray(self.global_prior_, float),
                "global_posterior": np.asarray(self.global_posterior_,
                                               float),
                "local_posterior": np.asarray(self.local_posterior_,
                                              float),
                "centers_cov": np.asarray(self.global_centers_cov,
                                          float),
                "widths_var": np.array([self.global_widths_var],
                                       dtype=float),
                "done": np.array(float(done)),
            }

        def unpack(state):
            self.global_prior_ = np.array(state["global_prior"], float)
            self.global_posterior_ = np.array(state["global_posterior"],
                                              float)
            self.local_posterior_ = np.array(state["local_posterior"],
                                             float)
            self.global_centers_cov = np.array(state["centers_cov"],
                                               float)
            self.global_widths_var = float(
                np.asarray(state["widths_var"]).reshape(-1)[0])
            self.global_centers_cov_scaled = \
                self.global_centers_cov / float(self.n_subj)
            self.global_widths_var_scaled = \
                self.global_widths_var / float(self.n_subj)

        def run_chunk(state, step, n_steps):
            unpack(state)
            done = False
            for i in range(n_steps):
                m = step + i
                if self.verbose:
                    logger.info("HTFA global iter %d", m)
                posterior = self._fit_subjects(data, R, m)
                self.local_posterior_ = posterior.ravel()
                self.gather_posterior = self.local_posterior_.copy()
                self._map_update_posterior()
                self._assign_posterior()
                check_state(
                    {"global_posterior": self.global_posterior_,
                     "local_posterior": self.local_posterior_},
                    iteration=m + 1, where="HTFA.fit")
                done, max_diff = self._converged()
                if done:
                    logger.info("converged at %d outer iter", m)
                    break
                self.global_prior_ = self.global_posterior_
            return pack(done), done

        if self._store is not None:
            # the manifest's per-subject digests identify the data —
            # fingerprinting never needs the subjects host-resident
            fingerprint = np.concatenate(
                [self._store.fingerprint(), [float(self.K)]])
        else:
            fingerprint = np.array(
                [array_digest(*data),
                 float(sum(d.shape[0] for d in data)), float(n_subj),
                 float(self.K)])
        state, _ = run_resilient_loop(
            run_chunk, pack(False), self.max_global_iter,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            fingerprint=fingerprint, name="HTFA.fit")
        unpack(state)

        self._update_weight(data, R)
        return self

    def _update_weight(self, data, R):
        """Final per-subject factor + weight solves
        (reference htfa.py:626-670).  Store-backed fits read one
        subject at a time — the weight pass is O(one subject) in
        host memory too."""
        weights = []
        if self._store is not None:
            data = (self._store.read(s)
                    for s in range(self._store.n_subjects))
        for s, subj_data in enumerate(data):
            base = s * self.prior_size
            centers = self.local_posterior_[
                base:base + self.K * self.n_dim].reshape(self.K,
                                                         self.n_dim)
            widths = self.local_posterior_[
                base + self.K * self.n_dim:base + self.prior_size] \
                .reshape(self.K, 1)
            F = np.asarray(rbf_factors(jnp.asarray(R[s]),
                                       jnp.asarray(centers),
                                       jnp.asarray(widths)))
            weights.append(self.get_weights(subj_data, F).ravel())
        self.local_weights_ = np.concatenate(weights)
        return self

    def _check_input(self, X, R):
        from ..data.store import SubjectStore

        if isinstance(X, SubjectStore):
            if not isinstance(R, list):
                raise TypeError("Coordinates should be a list")
            if X.n_subjects != len(R):
                raise TypeError("Data and coordinates lists must "
                                "have equal length")
            for s, r in enumerate(R):
                if not isinstance(r, np.ndarray) or r.ndim != 2:
                    raise TypeError(
                        "Each coordinate matrix should be a 2D array")
                if int(X.voxel_counts[s]) != r.shape[0]:
                    raise TypeError(
                        "The numbers of voxels in data and "
                        "coordinates differ")
            return
        if not isinstance(X, list):
            raise TypeError("Input data should be a list")
        if not isinstance(R, list):
            raise TypeError("Coordinates should be a list")
        if len(X) != len(R):
            raise TypeError("Data and coordinates lists must have equal "
                            "length")
        for x, r in zip(X, R):
            if not isinstance(x, np.ndarray) or x.ndim != 2:
                raise TypeError("Each subject data should be a 2D array")
            if not isinstance(r, np.ndarray) or r.ndim != 2:
                raise TypeError("Each coordinate matrix should be a 2D "
                                "array")
            if x.shape[0] != r.shape[0]:
                raise TypeError("The numbers of voxels in data and "
                                "coordinates differ")

    def fit(self, X, R, checkpoint_dir=None, checkpoint_every=5):
        """Fit HTFA (reference htfa.py:766-841).

        X : list of [n_voxel, n_tr] per-subject data, or a
            :class:`~brainiak_tpu.data.store.SubjectStore` — the
            subjects then stream from disk shard by shard through
            the prefetcher (disk reads of shard *s+1* overlap the
            inner L-BFGS rounds of shard *s*) and the full subject
            list is never host-resident at once (the
            thousand-subject path; docs/streaming_data.md)
        R : list of [n_voxel, n_dim] per-subject coordinates

        With ``checkpoint_dir``, the global-template loop checkpoints
        every ``checkpoint_every`` global iterations under the
        resilience guard and a later call resumes after preemption.

        Example
        -------
        >>> htfa = HTFA(K=5, n_subj=len(X))
        >>> htfa.fit(X, R, checkpoint_dir="/ckpts/htfa1")  # resumable
        """
        from ..data.store import SubjectStore

        self._check_input(X, R)
        if isinstance(X, SubjectStore):
            self._store = X
            shard = self.shard_subjects
            if shard is None:
                shard = 8
                if self.mesh is not None and \
                        DEFAULT_SUBJECT_AXIS in self.mesh.shape:
                    shard = self.mesh.shape[DEFAULT_SUBJECT_AXIS]
            self._shard_size = int(shard)
            # every shard batch pads to the full shard size, so the
            # jitted inner step compiles ONE shape even when the
            # final shard is short
            self._pad_lanes_to = self._shard_size
            X = None  # streamed: never hold the subject list
        else:
            self._store = None
            self._pad_lanes_to = 0
        if self.weight_method not in ('rr', 'ols'):
            raise ValueError(
                "only 'rr' and 'ols' are accepted as weight_method!")
        if self.mesh is not None and \
                DEFAULT_SUBJECT_AXIS not in self.mesh.shape:
            raise ValueError(
                "HTFA shards subjects over the mesh's "
                f"'{DEFAULT_SUBJECT_AXIS}' axis, but the given mesh has "
                f"axes {tuple(self.mesh.shape)}")
        if self.verbose:
            logger.info("Start to fit HTFA")
        self.n_dim = R[0].shape[1]
        self.cov_vec_size = np.sum(np.arange(self.n_dim) + 1)
        self.map_offset = self.get_map_offset()
        self.prior_size = self.K * (self.n_dim + 1)
        self._fit_htfa(X, R, checkpoint_dir=checkpoint_dir,
                       checkpoint_every=checkpoint_every)
        return self
