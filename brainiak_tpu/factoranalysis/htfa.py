"""Hierarchical Topographical Factor Analysis (HTFA), TPU-native.

Re-design of /root/reference/src/brainiak/factoranalysis/htfa.py.  A global
template over factor centers/widths (mean + covariance/variance) is
MAP-updated from per-subject TFA posteriors.  The reference distributes
subjects over MPI ranks with Bcast/Gatherv stitching
(htfa.py:515-558, :672-764); in the single-controller design the per-subject
fits run locally (each one a jitted L-BFGS program) and the gather is a
plain array concatenation — on a pod slice the subject loop becomes a
sharded vmap with the same math.

Deviation noted: the reference's ``_assign_posterior`` (htfa.py:560-590)
reorders only the covariance/variance fields by the Hungarian assignment
while leaving centers/widths unpermuted — inconsistent with TFA's version;
here all four fields are reordered consistently.
"""

import logging

import numpy as np
from scipy.optimize import linear_sum_assignment
from scipy.spatial import distance

from ..utils.utils import from_sym_2_tri, from_tri_2_sym
from .tfa import TFA

logger = logging.getLogger(__name__)

__all__ = ["HTFA"]


class HTFA(TFA):
    """Hierarchical TFA over multiple subjects (reference htfa.py:62-841).

    Parameters follow the reference: K, n_subj, max_global_iter /
    max_local_iter, threshold, weight_method, bounds ratios, subsampling
    ratios/caps (voxel_ratio, tr_ratio, max_voxel, max_tr).

    Attributes after fit: ``global_prior_``, ``global_posterior_``,
    ``local_posterior_`` (concatenated per-subject centers+widths),
    ``local_weights_`` (concatenated per-subject weight matrices).
    """

    def __init__(self, K, n_subj, max_global_iter=10, max_local_iter=10,
                 threshold=0.01, nlss_method='trf', nlss_loss='soft_l1',
                 jac='2-point', x_scale='jac', tr_solver=None,
                 weight_method='rr', upper_ratio=1.8, lower_ratio=0.02,
                 voxel_ratio=0.25, tr_ratio=0.1, max_voxel=5000,
                 max_tr=500, verbose=False, lbfgs_iters=60):
        self.K = K
        self.n_subj = n_subj
        self.max_global_iter = max_global_iter
        self.max_local_iter = max_local_iter
        self.threshold = threshold
        self.nlss_method = nlss_method
        self.nlss_loss = nlss_loss
        self.jac = jac
        self.x_scale = x_scale
        self.tr_solver = tr_solver
        self.weight_method = weight_method
        self.upper_ratio = upper_ratio
        self.lower_ratio = lower_ratio
        self.voxel_ratio = voxel_ratio
        self.tr_ratio = tr_ratio
        self.max_voxel = max_voxel
        self.max_tr = max_tr
        self.verbose = verbose
        self.lbfgs_iters = lbfgs_iters

    # -- convergence over the global template -----------------------------
    def _converged(self):
        prior = self.global_prior_[0:self.prior_size]
        posterior = self.global_posterior_[0:self.prior_size]
        max_diff = np.max(np.fabs(prior - posterior))
        return max_diff <= self.threshold, max_diff

    def _mse_converged(self):
        prior = self.global_prior_[0:self.prior_size]
        posterior = self.global_posterior_[0:self.prior_size]
        mse = np.mean((prior - posterior) ** 2)
        return mse <= self.threshold, mse

    # -- MAP update -------------------------------------------------------
    def _map_update(self, prior_mean, prior_cov, global_cov_scaled,
                    new_observation):
        """Gaussian MAP update of one factor's center parameters
        (reference htfa.py:246-288)."""
        common = np.linalg.inv(prior_cov + global_cov_scaled)
        observation_mean = np.mean(new_observation, axis=1)
        posterior_mean = prior_cov.dot(common.dot(observation_mean)) + \
            global_cov_scaled.dot(common.dot(prior_mean))
        posterior_cov = prior_cov.dot(common.dot(global_cov_scaled))
        return posterior_mean, posterior_cov

    def _map_update_posterior(self):
        """MAP-update the global template from gathered subject posteriors
        (reference htfa.py:290-341)."""
        self.global_posterior_ = self.global_prior_.copy()
        prior_centers = self.get_centers(self.global_prior_)
        prior_widths = self.get_widths(self.global_prior_)
        prior_centers_mean_cov = \
            self.get_centers_mean_cov(self.global_prior_)
        prior_widths_mean_var = \
            self.get_widths_mean_var(self.global_prior_)
        center_size = self.K * self.n_dim
        posterior_size = center_size + self.K
        gathered = self.gather_posterior.reshape(self.n_subj,
                                                 posterior_size)
        all_centers = gathered[:, :center_size].reshape(
            self.n_subj, self.K, self.n_dim)
        all_widths = gathered[:, center_size:]
        for k in np.arange(self.K):
            next_centers = all_centers[:, k, :].T  # [n_dim, n_subj]
            next_widths = all_widths[:, k]

            posterior_mean, posterior_cov = self._map_update(
                prior_centers[k].T.copy(),
                from_tri_2_sym(prior_centers_mean_cov[k], self.n_dim),
                self.global_centers_cov_scaled,
                next_centers)
            self.global_posterior_[k * self.n_dim:(k + 1) * self.n_dim] = \
                posterior_mean.T
            start_idx = self.map_offset[2] + k * self.cov_vec_size
            end_idx = self.map_offset[2] + (k + 1) * self.cov_vec_size
            self.global_posterior_[start_idx:end_idx] = \
                from_sym_2_tri(posterior_cov)

            pw_var = float(prior_widths_mean_var[k, 0])
            pw = float(prior_widths[k, 0])
            common = 1.0 / (pw_var + self.global_widths_var_scaled)
            observation_mean = np.mean(next_widths)
            tmp = common * self.global_widths_var_scaled
            self.global_posterior_[self.map_offset[1] + k] = \
                pw_var * common * observation_mean + tmp * pw
            self.global_posterior_[self.map_offset[3] + k] = pw_var * tmp
        return self

    def _assign_posterior(self):
        """Hungarian matching of global posterior factors to the prior,
        reordering all four fields consistently (see module docstring)."""
        prior_centers = self.get_centers(self.global_prior_)
        posterior_centers = self.get_centers(self.global_posterior_)
        posterior_widths = self.get_widths(self.global_posterior_)
        posterior_centers_mean_cov = \
            self.get_centers_mean_cov(self.global_posterior_)
        posterior_widths_mean_var = \
            self.get_widths_mean_var(self.global_posterior_)
        cost = distance.cdist(prior_centers, posterior_centers,
                              'euclidean')
        _, col_ind = linear_sum_assignment(cost)
        self.set_centers(self.global_posterior_,
                         posterior_centers[col_ind])
        self.set_widths(self.global_posterior_, posterior_widths[col_ind])
        self.set_centers_mean_cov(self.global_posterior_,
                                  posterior_centers_mean_cov[col_ind])
        self.set_widths_mean_var(self.global_posterior_,
                                 posterior_widths_mean_var[col_ind])
        return self

    # -- fitting ----------------------------------------------------------
    def _fit_htfa(self, data, R):
        """Outer template loop over per-subject TFA fits
        (reference htfa.py:672-764)."""
        n_subj = len(R)
        tfa = []
        for s in range(n_subj):
            nvoxel, ntr = data[s].shape
            sub = TFA(max_iter=self.max_local_iter,
                      threshold=self.threshold,
                      K=self.K, nlss_method=self.nlss_method,
                      nlss_loss=self.nlss_loss,
                      weight_method=self.weight_method,
                      upper_ratio=self.upper_ratio,
                      lower_ratio=self.lower_ratio,
                      max_num_voxel=min(self.max_voxel,
                                        int(self.voxel_ratio * nvoxel)),
                      max_num_tr=min(self.max_tr,
                                     int(self.tr_ratio * ntr)),
                      verbose=self.verbose,
                      lbfgs_iters=self.lbfgs_iters)
            tfa.append(sub)

        self.local_posterior_ = np.zeros(n_subj * self.prior_size)
        # Template initialized from a random subject's coordinates
        # (reference htfa.py:475-513).
        idx = np.random.choice(n_subj, 1)[0]
        self.global_prior_, self.global_centers_cov, \
            self.global_widths_var = self.get_template(R[idx])
        self.global_centers_cov_scaled = \
            self.global_centers_cov / float(self.n_subj)
        self.global_widths_var_scaled = \
            self.global_widths_var / float(self.n_subj)

        m = 0
        outer_converged = False
        while m < self.max_global_iter and not outer_converged:
            if self.verbose:
                logger.info("HTFA global iter %d", m)
            for s in range(n_subj):
                tfa[s].set_seed(m * self.max_local_iter)
                tfa[s].fit(data[s], R[s],
                           template_prior=self.global_prior_.copy())
                start = s * self.prior_size
                self.local_posterior_[start:start + self.prior_size] = \
                    tfa[s].local_posterior_
            self.gather_posterior = self.local_posterior_.copy()
            self._map_update_posterior()
            self._assign_posterior()
            outer_converged, max_diff = self._converged()
            if outer_converged:
                logger.info("converged at %d outer iter", m)
            else:
                self.global_prior_ = self.global_posterior_
            m += 1

        self._update_weight(data, R)
        return self

    def _update_weight(self, data, R):
        """Final per-subject factor + weight solves
        (reference htfa.py:626-670)."""
        import jax.numpy as jnp

        from ..ops.rbf import rbf_factors

        weights = []
        for s, subj_data in enumerate(data):
            base = s * self.prior_size
            centers = self.local_posterior_[
                base:base + self.K * self.n_dim].reshape(self.K,
                                                         self.n_dim)
            widths = self.local_posterior_[
                base + self.K * self.n_dim:base + self.prior_size] \
                .reshape(self.K, 1)
            F = np.asarray(rbf_factors(jnp.asarray(R[s]),
                                       jnp.asarray(centers),
                                       jnp.asarray(widths)))
            weights.append(self.get_weights(subj_data, F).ravel())
        self.local_weights_ = np.concatenate(weights)
        return self

    def _check_input(self, X, R):
        if not isinstance(X, list):
            raise TypeError("Input data should be a list")
        if not isinstance(R, list):
            raise TypeError("Coordinates should be a list")
        if len(X) != len(R):
            raise TypeError("Data and coordinates lists must have equal "
                            "length")
        for x, r in zip(X, R):
            if not isinstance(x, np.ndarray) or x.ndim != 2:
                raise TypeError("Each subject data should be a 2D array")
            if not isinstance(r, np.ndarray) or r.ndim != 2:
                raise TypeError("Each coordinate matrix should be a 2D "
                                "array")
            if x.shape[0] != r.shape[0]:
                raise TypeError("The numbers of voxels in data and "
                                "coordinates differ")

    def fit(self, X, R):
        """Fit HTFA (reference htfa.py:766-841).

        X : list of [n_voxel, n_tr] per-subject data
        R : list of [n_voxel, n_dim] per-subject coordinates
        """
        self._check_input(X, R)
        if self.verbose:
            logger.info("Start to fit HTFA")
        self.n_dim = R[0].shape[1]
        self.cov_vec_size = np.sum(np.arange(self.n_dim) + 1)
        self.map_offset = self.get_map_offset()
        self.prior_size = self.K * (self.n_dim + 1)
        self._fit_htfa(X, R)
        return self
