"""Topographical Factor Analysis (TFA), TPU-native.

Re-design of /root/reference/src/brainiak/factoranalysis/tfa.py.  The model:
one subject's data X [n_voxel, n_tr] ≈ F(C, W) · Wmat where F is a Gaussian
RBF factor matrix over scanner coordinates.  Fitting alternates a ridge
solve for the weight matrix with a bounded nonlinear least-squares update of
centers/widths on stochastically subsampled voxels/TRs.

TPU-first: the RBF factor op and ridge solve are jitted XLA
(:mod:`brainiak_tpu.ops.rbf`), and the bounded NLLS is a jitted L-BFGS with
a sigmoid box transform and autodiff gradients
(:mod:`brainiak_tpu.ops.optimize`) instead of scipy ``least_squares`` +
finite-difference Jacobians calling C++ residual kernels
(reference tfa.py:738-821).  The ``nlss_method``/``jac``/``x_scale``/
``tr_solver`` knobs are accepted for API compatibility but the solver is
always the L-BFGS transform; ``nlss_loss`` supports 'linear' and 'soft_l1'.
"""

import logging
import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from scipy.optimize import linear_sum_assignment
from scipy.spatial import distance
from sklearn.base import BaseEstimator
from sklearn.cluster import KMeans

from ..obs import profile as obs_profile
from ..ops.optimize import minimize_bounded
from ..ops.rbf import (rbf_factors, rbf_residual_sum,
                       rbf_weight_products)
from ..resilience.guards import (array_digest, check_state,
                                 pack_rng_state, run_resilient_loop,
                                 unpack_rng_state)
from ..utils.utils import from_sym_2_tri, from_tri_2_sym

logger = logging.getLogger(__name__)

__all__ = ["TFA"]


@partial(jax.jit, static_argnames=("weight_method",))
def _solve_weights(data, F, weight_method="rr"):
    """W = (FᵀF + beta·I)⁻¹ Fᵀ X (ridge, beta = var(data)) or OLS
    (reference tfa.py:569-598), from a materialized factor matrix."""
    k = F.shape[1]
    beta = jnp.var(data) if weight_method == "rr" else 0.0
    return jnp.linalg.solve(F.T @ F + beta * jnp.eye(k, dtype=F.dtype),
                            F.T @ data)


@partial(jax.jit, static_argnames=("weight_method",))
def _solve_weights_fused(data, R, centers, widths,
                         weight_method="rr"):
    """The same ridge/OLS weight solve with ``FᵀF``/``FᵀX``
    accumulated by the MTTKRP-style chunked contraction
    (:func:`~brainiak_tpu.ops.rbf.rbf_weight_products`) — the factor
    matrix is reconstructed tile-by-tile fused with the products and
    never materializes at ``[V, K]``."""
    k = centers.shape[0]
    beta = jnp.var(data) if weight_method == "rr" else 0.0
    g, b = rbf_weight_products(R, centers, widths, data)
    return jnp.linalg.solve(g + beta * jnp.eye(k, dtype=g.dtype), b)


def _rho_sum(sq, nlss_loss):
    if nlss_loss == "soft_l1":
        return jnp.sum(2.0 * (jnp.sqrt(1.0 + sq) - 1.0))
    return jnp.sum(sq)


def _full_sym(tri, n_dim):
    """Full symmetric matrix from its packed upper-triangular vector."""
    u = from_tri_2_sym(tri, n_dim)
    return u + u.T - np.diag(np.diag(u))


def _match_centers(prior_centers, posterior_centers):
    """Hungarian assignment of posterior factors to prior factors by
    center distance; returns the posterior column order."""
    cost = distance.cdist(prior_centers, posterior_centers, 'euclidean')
    return linear_sum_assignment(cost)[1]


@partial(jax.jit, static_argnames=("K", "n_dim", "nlss_loss", "max_iters",
                                   "has_template"))
def _fit_centers_widths(init, lower, upper, R, X, W, data_sigma,
                        sample_scaling, tmpl_centers, tmpl_cov_inv,
                        tmpl_widths, tmpl_widths_var_reci, *, K, n_dim,
                        nlss_loss, max_iters, has_template):
    """Bounded NLLS over packed (centers, widths) as ONE jitted program.

    Objective 0.5·Σ rho(r_i²) matching the reference residual stack
    (tfa.py:652-736): data term sigma·(X − F·W), plus per-factor center
    Mahalanobis and width penalties when a template is present.  The
    data term runs the MTTKRP-style fused reconstruction
    (:func:`~brainiak_tpu.ops.rbf.rbf_residual_sum`): factor tiles are
    rebuilt chunk-by-chunk inside the reduction, so no ``[V, K]``
    factor matrix or ``[V, T]`` residual materializes per L-BFGS
    iteration."""

    def objective(params):
        centers = params[:K * n_dim].reshape(K, n_dim)
        widths = params[K * n_dim:]
        total = rbf_residual_sum(R, centers, widths, X, W,
                                 data_sigma, nlss_loss=nlss_loss)
        if has_template:
            diff = centers - tmpl_centers
            maha = jnp.einsum('kd,kde,ke->k', diff, tmpl_cov_inv, diff)
            total = total + _rho_sum(sample_scaling * maha, nlss_loss)
            wdist = sample_scaling * tmpl_widths_var_reci.reshape(-1) * \
                (widths - tmpl_widths.reshape(-1)) ** 2
            total = total + _rho_sum(wdist, nlss_loss)
        return 0.5 * total

    return minimize_bounded(objective, init, lower, upper,
                            max_iters=max_iters)


# cost attribution for the per-iteration NLLS program (schema-v2
# `cost` records while profiling is active)
_fit_centers_widths = obs_profile.profile_program(
    _fit_centers_widths, "tfa.fit_centers_widths", span="fit_chunk",
    estimator="TFA.fit")


class TFA(BaseEstimator):
    """Topographical Factor Analysis (reference tfa.py:52-1024).

    Parameters follow the reference: K factors, ``max_iter`` outer
    iterations with ``threshold`` max-abs-diff convergence,
    ``weight_method`` 'rr' (ridge) or 'ols', bounds from
    ``lower_ratio``/``upper_ratio`` of the coordinate spread, stochastic
    subsampling to ``max_num_voxel`` × ``max_num_tr`` per iteration with
    ``seed``.

    Attributes after fit: ``local_posterior_`` (packed centers+widths),
    ``F_`` [n_voxel, K], ``W_`` [K, n_tr].
    """

    def __init__(self, max_iter=10, threshold=1.0, K=50, nlss_method='trf',
                 nlss_loss='linear', jac='2-point', x_scale=1.0,
                 tr_solver=None, weight_method='rr', upper_ratio=1.8,
                 lower_ratio=0.02, max_num_tr=500, max_num_voxel=5000,
                 seed=100, verbose=False, lbfgs_iters=60):
        self.miter = max_iter
        self.threshold = threshold
        self.K = K
        self.nlss_method = nlss_method
        self.nlss_loss = nlss_loss
        self.jac = jac
        self.x_scale = x_scale
        self.tr_solver = tr_solver
        self.weight_method = weight_method
        self.upper_ratio = upper_ratio
        self.lower_ratio = lower_ratio
        self.max_num_tr = max_num_tr
        self.max_num_voxel = max_num_voxel
        self.seed = seed
        self.verbose = verbose
        self.lbfgs_iters = lbfgs_iters

    # -- configuration ----------------------------------------------------
    def set_K(self, K):
        self.K = K
        return self

    def set_prior(self, prior):
        self.local_prior = prior
        return self

    def set_seed(self, seed):
        self.seed = seed
        return self

    # -- packed parameter vector layout (reference tfa.py:309-523) --------
    def get_map_offset(self):
        nfield = 4
        self.map_offset = np.zeros(nfield).astype(int)
        field_size = self.K * np.array(
            [self.n_dim, 1, self.cov_vec_size, 1])
        for i in np.arange(nfield - 1) + 1:
            self.map_offset[i] = self.map_offset[i - 1] + field_size[i - 1]
        return self.map_offset

    def get_centers(self, estimation):
        return estimation[0:self.map_offset[1]].reshape(self.K, self.n_dim)

    def get_widths(self, estimation):
        return estimation[self.map_offset[1]:self.map_offset[2]] \
            .reshape(self.K, 1)

    def get_centers_mean_cov(self, estimation):
        return estimation[self.map_offset[2]:self.map_offset[3]] \
            .reshape(self.K, self.cov_vec_size)

    def get_widths_mean_var(self, estimation):
        return estimation[self.map_offset[3]:].reshape(self.K, 1)

    def set_centers(self, estimation, centers):
        estimation[0:self.map_offset[1]] = centers.ravel()

    def set_widths(self, estimation, widths):
        estimation[self.map_offset[1]:self.map_offset[2]] = widths.ravel()

    def set_centers_mean_cov(self, estimation, centers_mean_cov):
        estimation[self.map_offset[2]:self.map_offset[3]] = \
            centers_mean_cov.ravel()

    def set_widths_mean_var(self, estimation, widths_mean_var):
        estimation[self.map_offset[3]:] = widths_mean_var.ravel()

    # -- initialization ---------------------------------------------------
    def _get_max_sigma(self, R):
        """2 · (max per-dim std of coordinates)² (reference tfa.py:600-618)."""
        return 2.0 * math.pow(np.nanmax(np.std(R, axis=0)), 2)

    def init_centers_widths(self, R):
        """KMeans centers + max-sigma widths (reference tfa.py:328-350)."""
        kmeans = KMeans(init='k-means++', n_clusters=self.K, n_init=10,
                        random_state=100)
        kmeans.fit(R)
        centers = kmeans.cluster_centers_
        widths = self._get_max_sigma(R) * np.ones((self.K, 1))
        return centers, widths

    def init_prior(self, R):
        centers, widths = self.init_centers_widths(R)
        prior = np.zeros(self.K * (self.n_dim + 1))
        self.set_centers(prior, centers)
        self.set_widths(prior, widths)
        self.set_prior(prior)
        return self

    def get_template(self, R):
        """Template prior: KMeans centers/widths + constant covariance
        cov(R)·K^(-2/3) and width variance (reference tfa.py:352-385)."""
        centers, widths = self.init_centers_widths(R)
        template_prior = np.zeros(
            self.K * (self.n_dim + 2 + self.cov_vec_size))
        template_centers_cov = np.cov(R.T) * math.pow(self.K, -2 / 3.0)
        template_widths_var = self._get_max_sigma(R)
        self.set_centers(template_prior, centers)
        self.set_widths(template_prior, widths)
        self.set_centers_mean_cov(
            template_prior,
            np.tile(from_sym_2_tri(template_centers_cov), self.K))
        self.set_widths_mean_var(
            template_prior, np.tile(template_widths_var, self.K))
        return template_prior, template_centers_cov, template_widths_var

    def get_bounds(self, R):
        """Box bounds: centers within coordinate range, widths within
        [lower_ratio, upper_ratio]·max_sigma (reference tfa.py:620-650)."""
        max_sigma = self._get_max_sigma(R)
        lower = np.zeros(self.K * (self.n_dim + 1))
        lower[0:self.K * self.n_dim] = np.tile(np.nanmin(R, axis=0),
                                               self.K)
        lower[self.K * self.n_dim:] = self.lower_ratio * max_sigma
        upper = np.zeros(self.K * (self.n_dim + 1))
        upper[0:self.K * self.n_dim] = np.tile(np.nanmax(R, axis=0),
                                               self.K)
        upper[self.K * self.n_dim:] = self.upper_ratio * max_sigma
        return lower, upper

    # -- factor / weight computation --------------------------------------
    def get_unique_R(self, R):
        """Unique coordinate values per dim + inverse indices (kept for API
        parity; the TPU factor op does not need them,
        reference tfa.py:879-906)."""
        unique_R = []
        inds = []
        for d in np.arange(self.n_dim):
            tmp_unique, tmp_inds = np.unique(R[:, d], return_inverse=True)
            unique_R.append(tmp_unique)
            inds.append(tmp_inds)
        return unique_R, inds

    def get_factors(self, unique_R, inds, centers, widths):
        """RBF factor matrix [n_voxel, K] (reference tfa.py:525-567).

        Accepts the reference's (unique_R, inds) calling convention but
        reconstructs R and evaluates the fused broadcast op."""
        R = np.stack([u[i] for u, i in zip(unique_R, inds)], axis=1)
        return np.asarray(rbf_factors(jnp.asarray(R),
                                      jnp.asarray(centers),
                                      jnp.asarray(widths)))

    def get_weights(self, data, F):
        """Ridge/OLS weight solve (reference tfa.py:569-598)."""
        return np.asarray(_solve_weights(jnp.asarray(data),
                                         jnp.asarray(F),
                                         self.weight_method))

    # -- convergence ------------------------------------------------------
    def _assign_posterior(self):
        """Hungarian matching of posterior to prior centers
        (reference tfa.py:242-260)."""
        prior_centers = self.get_centers(self.local_prior)
        posterior_centers = self.get_centers(self.local_posterior_)
        posterior_widths = self.get_widths(self.local_posterior_)
        col_ind = _match_centers(prior_centers, posterior_centers)
        self.set_centers(self.local_posterior_, posterior_centers[col_ind])
        self.set_widths(self.local_posterior_, posterior_widths[col_ind])
        return self

    def _converged(self):
        diff = self.local_prior - self.local_posterior_
        max_diff = np.max(np.fabs(diff))
        if self.verbose:
            # the reference's verbose diagnostics (tfa.py:276-281)
            _, mse = self._mse_converged()
            diff_ratio = np.sum(diff ** 2) \
                / np.sum(self.local_posterior_ ** 2)
            logger.info('tfa prior posterior max diff %f mse %f '
                        'diff_ratio %f', max_diff, mse, diff_ratio)
        return max_diff <= self.threshold, max_diff

    def _mse_converged(self):
        mse = np.mean((self.local_prior - self.local_posterior_) ** 2)
        return mse <= self.threshold, mse

    # -- fitting ----------------------------------------------------------
    def _estimate_centers_widths(self, R, X, W, init_centers, init_widths,
                                 template_centers, template_widths,
                                 template_centers_mean_cov,
                                 template_widths_mean_var_reci):
        """Bounded NLLS over packed (centers, widths)
        (reference tfa.py:738-821)."""
        init = np.hstack((init_centers.ravel(), init_widths.ravel()))
        data_sigma = 1.0 / math.sqrt(2.0) * np.std(X)
        has_template = template_centers is not None
        if has_template:
            cov_inv = np.stack([
                np.linalg.inv(_full_sym(template_centers_mean_cov[k],
                                        self.n_dim))
                for k in range(self.K)])
            tmpl_centers = jnp.asarray(template_centers)
            tmpl_cov_inv = jnp.asarray(cov_inv)
            tmpl_widths = jnp.asarray(template_widths)
            tmpl_reci = jnp.asarray(template_widths_mean_var_reci)
        else:
            tmpl_centers = jnp.zeros((self.K, self.n_dim))
            tmpl_cov_inv = jnp.zeros((self.K, self.n_dim, self.n_dim))
            tmpl_widths = jnp.zeros((self.K, 1))
            tmpl_reci = jnp.zeros((self.K, 1))

        x, cost = _fit_centers_widths(
            jnp.asarray(init), jnp.asarray(self.bounds[0]),
            jnp.asarray(self.bounds[1]), jnp.asarray(R), jnp.asarray(X),
            jnp.asarray(W), data_sigma, self.sample_scaling,
            tmpl_centers, tmpl_cov_inv, tmpl_widths, tmpl_reci,
            K=self.K, n_dim=self.n_dim, nlss_loss=self.nlss_loss,
            max_iters=self.lbfgs_iters, has_template=has_template)
        return np.array(x), float(cost)

    def _fit_tfa_inner(self, data, R, template_centers, template_widths,
                       template_centers_mean_cov,
                       template_widths_mean_var_reci):
        """One stochastic subsample + W solve + bounded NLLS
        (reference tfa.py:908-969)."""
        nfeature, nsample = data.shape
        feature_indices = self._rng.choice(nfeature, self.max_num_voxel,
                                           replace=False)
        sample_indices = self._rng.choice(nsample, self.max_num_tr,
                                          replace=False)
        curr_data = data[feature_indices][:, sample_indices].copy()
        curr_R = R[feature_indices].copy()
        centers = self.get_centers(self.local_prior)
        widths = self.get_widths(self.local_prior)
        # fused MTTKRP weight solve: FᵀF/FᵀX accumulate chunk-wise,
        # the [V, K] factor matrix never materializes
        W = np.asarray(_solve_weights_fused(
            jnp.asarray(curr_data), jnp.asarray(curr_R),
            jnp.asarray(centers), jnp.asarray(widths),
            self.weight_method))
        self.local_posterior_, self.total_cost = \
            self._estimate_centers_widths(
                curr_R, curr_data, W, centers, widths, template_centers,
                template_widths, template_centers_mean_cov,
                template_widths_mean_var_reci)
        return self

    def _fit_tfa(self, data, R, template_prior=None,
                 checkpoint_dir=None, checkpoint_every=5):
        """Outer loop: subsample-fit until converged
        (reference tfa.py:824-877), driven by the resilient loop:
        per-iteration non-finite guard with checkpoint rollback and —
        with ``checkpoint_dir`` — preemption-safe resume including the
        subsampling RNG stream position."""
        if template_prior is None:
            template_centers = None
            template_widths = None
            template_centers_mean_cov = None
            template_widths_mean_var_reci = None
        else:
            template_centers = self.get_centers(template_prior)
            template_widths = self.get_widths(template_prior)
            template_centers_mean_cov = \
                self.get_centers_mean_cov(template_prior)
            template_widths_mean_var_reci = \
                1.0 / self.get_widths_mean_var(template_prior)
        self._rng = np.random.RandomState(self.seed)

        def pack(done):
            keys, meta = pack_rng_state(self._rng)
            return {
                "prior": np.asarray(self.local_prior, dtype=float),
                "posterior": np.asarray(
                    getattr(self, "local_posterior_", self.local_prior),
                    dtype=float),
                "rng_keys": keys, "rng_meta": meta,
                "done": np.array(float(done)),
            }

        def unpack(state):
            self.local_prior = np.array(state["prior"], dtype=float)
            self.local_posterior_ = np.array(state["posterior"],
                                             dtype=float)
            unpack_rng_state(self._rng, state["rng_keys"],
                             state["rng_meta"])

        def run_chunk(state, step, n_steps):
            unpack(state)
            done = False
            for i in range(n_steps):
                self._fit_tfa_inner(data, R, template_centers,
                                    template_widths,
                                    template_centers_mean_cov,
                                    template_widths_mean_var_reci)
                self._assign_posterior()
                check_state({"posterior": self.local_posterior_},
                            iteration=step + i + 1, where="TFA.fit")
                converged, max_diff = self._converged()
                if converged:
                    if self.verbose:
                        logger.info("TFA converged at %d iteration.",
                                    step + i)
                    done = True
                    break
                self.local_prior = self.local_posterior_
            return pack(done), done

        fingerprint = np.array(
            [array_digest(data), float(data.shape[0]),
             float(data.shape[1]), float(self.K), float(self.seed)])
        state, _ = run_resilient_loop(
            run_chunk, pack(False), self.miter,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            fingerprint=fingerprint, name="TFA.fit")
        unpack(state)
        return self

    def fit(self, X, R, template_prior=None, checkpoint_dir=None,
            checkpoint_every=5):
        """Fit TFA to one subject (reference tfa.py:971-1024).

        X: [n_voxel, n_tr] data; R: [n_voxel, n_dim] coordinates.

        With ``checkpoint_dir``, the outer subsample-fit loop
        checkpoints every ``checkpoint_every`` iterations (including
        the subsampling RNG stream) under the resilience guard, and a
        later call with the same directory resumes after preemption.

        Example
        -------
        >>> tfa = TFA(K=5, max_iter=10)
        >>> tfa.fit(X, R, checkpoint_dir="/ckpts/tfa_s01")  # resumable
        """
        if not isinstance(X, np.ndarray):
            raise TypeError("Input data should be an array")
        if X.ndim != 2:
            raise TypeError("Input data should be 2D array")
        if not isinstance(R, np.ndarray):
            raise TypeError("Input coordinate matrix should be an array")
        if R.ndim != 2:
            raise TypeError("Input coordinate matrix should be 2D array")
        if X.shape[0] != R.shape[0]:
            raise TypeError(
                "The number of voxels should be the same in X and R!")
        if self.weight_method not in ('rr', 'ols'):
            raise ValueError(
                "only 'rr' and 'ols' are accepted as weight_method!")

        self.n_dim = R.shape[1]
        self.cov_vec_size = np.sum(np.arange(self.n_dim) + 1)
        self.map_offset = self.get_map_offset()
        self.bounds = self.get_bounds(R)
        self.max_num_voxel = min(self.max_num_voxel, X.shape[0])
        self.max_num_tr = min(self.max_num_tr, X.shape[1])
        self.sample_scaling = 0.5 * float(
            self.max_num_voxel * self.max_num_tr) / \
            float(X.shape[0] * X.shape[1])
        if template_prior is None:
            self.init_prior(R)
        else:
            self.local_prior = template_prior[0:self.map_offset[2]].copy()
        self._fit_tfa(X, R, template_prior,
                      checkpoint_dir=checkpoint_dir,
                      checkpoint_every=checkpoint_every)
        if template_prior is None:
            centers = self.get_centers(self.local_posterior_)
            widths = self.get_widths(self.local_posterior_)
            self.F_ = np.asarray(rbf_factors(jnp.asarray(R),
                                             jnp.asarray(centers),
                                             jnp.asarray(widths)))
            self.W_ = self.get_weights(X, self.F_)
        return self
