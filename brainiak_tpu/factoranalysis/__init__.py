"""Topographic factor analysis (TFA/HTFA), TPU-native.

The reference's C++ RBF kernels + scipy bounded least squares + MPI
hierarchical gather (/root/reference/src/brainiak/factoranalysis/) become
fused XLA ops + a jitted L-BFGS with box reparameterization + host-side
hierarchical updates over stacked posteriors."""
