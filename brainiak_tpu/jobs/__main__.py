"""``python -m brainiak_tpu.jobs`` — the fleet-facing job client.

Subcommands (all speak to a live scheduler's telemetry port — the
:class:`~brainiak_tpu.obs.http.TelemetryServer` a
``Scheduler(http_port=...)`` attaches its control plane to):

- ``gen`` — write an npz job batch
  (:func:`~brainiak_tpu.jobs.spec.save_jobs`) from CLI parameters;
- ``submit`` — POST a job batch to ``<url>/jobs/submit``; prints the
  accepted/shed verdict as JSON;
- ``status`` — GET ``<url>/jobs`` and render the scheduler table
  (or ``--json`` for the raw payload);
- ``cancel`` — POST ``<url>/jobs/cancel?job_id=<id>``.

Exit codes: 0 success, 1 request-level failure (shed, unknown job),
2 usage / transport error.
"""

import argparse
import json
import sys
from urllib.error import URLError
from urllib.request import Request, urlopen

from .spec import KINDS, JobSpec, save_jobs

__all__ = ["main"]


def _fetch(url, data=None, timeout=10.0):
    req = Request(url, data=data,
                  method="POST" if data is not None else "GET")
    with urlopen(req, timeout=timeout) as resp:
        return resp.read().decode("utf-8")


def _gen(args):
    specs = []
    for i in range(args.n):
        specs.append(JobSpec(
            tenant=args.tenant, kind=args.kind,
            priority=args.priority, n_iter=args.n_iter,
            features=args.features, seed=args.seed + i,
            n_subjects=args.subjects, voxels=args.voxels,
            samples=args.samples, deadline_s=args.deadline_s))
    save_jobs(args.out, specs)
    print(json.dumps({"written": args.out,
                      "job_ids": [s.job_id for s in specs]},
                     indent=2))
    return 0


def _submit(args):
    with open(args.jobs, "rb") as fh:
        body = fh.read()
    try:
        text = _fetch(args.url.rstrip("/") + "/jobs/submit",
                      data=body, timeout=args.timeout)
    except (URLError, OSError) as exc:
        print(f"submit failed: {exc}", file=sys.stderr)
        return 2
    print(text.rstrip())
    verdict = json.loads(text)
    return 1 if verdict.get("shed") else 0


def _render_status(payload):
    scheduler = payload.get("scheduler")
    if not scheduler:
        return "no scheduler live (fits only: {} active)".format(
            len(payload.get("fits", [])))
    lines = ["{:<18} {:<10} {:>4} {:<9} {:>6} {:>8} {:>9}".format(
        "JOB", "TENANT", "PRI", "STATE", "CHUNK", "PREEMPT",
        "DEFICIT")]
    tenants = scheduler.get("tenants", {})
    for row in scheduler.get("jobs", []):
        deficit = tenants.get(row["tenant"], {}).get("deficit", 0.0)
        lines.append(
            "{:<18} {:<10} {:>4} {:<9} {:>6.0f} {:>8} {:>9.2f}"
            .format(row["job_id"][:16], row["tenant"][:10],
                    row["priority"], row["state"], row["chunks"],
                    row["n_preemptions"], deficit))
    counts = scheduler.get("counts", {})
    lines.append("states: " + ", ".join(
        f"{state}={n}" for state, n in sorted(counts.items())))
    return "\n".join(lines)


def _status(args):
    try:
        text = _fetch(args.url.rstrip("/") + "/jobs",
                      timeout=args.timeout)
    except (URLError, OSError) as exc:
        print(f"status failed: {exc}", file=sys.stderr)
        return 2
    payload = json.loads(text)
    if args.json:
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print(_render_status(payload))
    return 0


def _cancel(args):
    try:
        text = _fetch(
            args.url.rstrip("/")
            + f"/jobs/cancel?job_id={args.job_id}",
            data=b"", timeout=args.timeout)
    except (URLError, OSError) as exc:
        print(f"cancel failed: {exc}", file=sys.stderr)
        return 2
    print(text.rstrip())
    return 0 if json.loads(text).get("cancelled") else 1


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m brainiak_tpu.jobs",
        description="job client for the fit scheduler")
    sub = parser.add_subparsers(dest="command", required=True)

    gen = sub.add_parser("gen", help="write an npz job batch")
    gen.add_argument("--out", required=True)
    gen.add_argument("--tenant", required=True)
    gen.add_argument("--kind", choices=KINDS, default="srm")
    gen.add_argument("--n", type=int, default=1)
    gen.add_argument("--priority", type=int, default=0)
    gen.add_argument("--n-iter", type=int, default=6)
    gen.add_argument("--features", type=int, default=3)
    gen.add_argument("--seed", type=int, default=0)
    gen.add_argument("--subjects", type=int, default=3)
    gen.add_argument("--voxels", type=int, default=16)
    gen.add_argument("--samples", type=int, default=20)
    gen.add_argument("--deadline-s", type=float, default=None)
    gen.set_defaults(fn=_gen)

    submit = sub.add_parser("submit", help="POST a job batch")
    submit.add_argument("jobs", help="npz batch (see gen)")
    submit.add_argument("--url", required=True)
    submit.add_argument("--timeout", type=float, default=10.0)
    submit.set_defaults(fn=_submit)

    status = sub.add_parser("status", help="render /jobs")
    status.add_argument("--url", required=True)
    status.add_argument("--json", action="store_true")
    status.add_argument("--timeout", type=float, default=10.0)
    status.set_defaults(fn=_status)

    cancel = sub.add_parser("cancel", help="cancel one job")
    cancel.add_argument("job_id")
    cancel.add_argument("--url", required=True)
    cancel.add_argument("--timeout", type=float, default=10.0)
    cancel.set_defaults(fn=_cancel)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
