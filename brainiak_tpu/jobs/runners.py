"""Per-kind job runners: a JobSpec in, a finished fit out.

Each runner drives one estimator-kind's ``fit(...,
checkpoint_dir=)`` entry point — the universal resilient-fit
contract — so EVERY job the scheduler runs is resumable and parkable
at chunk granularity for free:

- ``srm`` — :class:`~brainiak_tpu.funcalign.srm.SRM` EM over a
  subject list (the streamed path when ``spec.data`` names a
  ``write_store`` directory);
- ``incremental_srm`` — :class:`~brainiak_tpu.data.streaming_fit.
  IncrementalSRM` epochs over a :class:`~brainiak_tpu.data.store.
  SubjectStore` (synthetic jobs materialize a small store under the
  job's workdir once, then reuse it across park/resume cycles);
- ``htfa`` — :class:`~brainiak_tpu.factoranalysis.htfa.HTFA` global
  MAP rounds;
- ``ridge_encoding`` — :class:`~brainiak_tpu.encoding.ridge.
  RidgeEncoder` CV sweep in per-lambda blocks.

Determinism is the load-bearing property: a runner invoked twice for
the same spec builds bit-identical data (seeded from ``spec.seed``)
and estimator config, so a parked job re-invoked with the same
``checkpoint_dir`` resumes the SAME fit (same ``fit_id``, cumulative
wall clock) and lands on bit-exact final parameters — the
preempt-park-resume parity the tests and the JOB001 gate assert.

The runner result is ``{"kind", "digest", "arrays"}`` where
``digest`` is :func:`~brainiak_tpu.resilience.guards.array_digest`
over the fitted parameters (the cheap cross-process parity probe)
and ``arrays`` holds the parameters themselves for in-process
bit-exact comparison.
"""

import os

import numpy as np

from .spec import KINDS

__all__ = ["checkpoint_dir_for", "run_job", "synthetic_subjects"]


def checkpoint_dir_for(spec, workdir):
    """The job's checkpoint directory — ``workdir/<job_id>``, stable
    across park/resume cycles (the preemption contract hinges on
    re-invoking the fit with this exact path)."""
    return os.path.join(workdir, spec.job_id)


def synthetic_subjects(spec):
    """Seeded per-subject data ``[voxels, samples]`` — bit-identical
    across invocations for the same spec (see module docstring)."""
    rng = np.random.RandomState(int(spec.seed) & 0x7FFFFFFF)
    return [rng.randn(int(spec.voxels), int(spec.samples))
            .astype(np.float64)
            for _ in range(int(spec.n_subjects))]


def _load_npz_subjects(path):
    with np.load(path, allow_pickle=False) as archive:
        xs = [archive[k] for k in sorted(
            (k for k in archive.files if k.startswith("X.")),
            key=lambda k: int(k.split(".", 1)[1]))]
        y = archive["Y"] if "Y" in archive.files else None
    return xs, y


def _subject_data(spec):
    """(subjects list, Y-or-None) from ``spec.data`` or synthesis."""
    if spec.data is not None and os.path.isfile(spec.data):
        return _load_npz_subjects(spec.data)
    return synthetic_subjects(spec), None


def _collect_arrays(model, names):
    out = {}
    for name in names:
        value = getattr(model, name, None)
        if value is None:
            continue
        if isinstance(value, (list, tuple)):
            for i, item in enumerate(value):
                out[f"{name}{i}"] = np.asarray(item)
        else:
            out[name] = np.asarray(value)
    return out


def _run_srm(spec, ckpt_dir):
    from ..funcalign.srm import SRM

    if spec.data is not None and os.path.isdir(spec.data):
        from ..data.store import open_store
        x = open_store(spec.data)  # streamed fit path
    else:
        x, _ = _subject_data(spec)
    model = SRM(n_iter=int(spec.n_iter),
                features=int(spec.features),
                rand_seed=int(spec.seed))
    model.fit(x, checkpoint_dir=ckpt_dir,
              checkpoint_every=int(spec.checkpoint_every))
    return _collect_arrays(model, ("w_", "s_", "rho2_"))


def _run_incremental_srm(spec, ckpt_dir):
    from ..data.store import open_store, write_store
    from ..data.streaming_fit import IncrementalSRM

    if spec.data is not None:
        store = open_store(spec.data)
    else:
        store_dir = ckpt_dir + "-data"
        if not os.path.isdir(store_dir):
            write_store(store_dir, synthetic_subjects(spec))
        store = open_store(store_dir)
    model = IncrementalSRM(n_iter=int(spec.n_iter),
                           features=int(spec.features),
                           rand_seed=int(spec.seed))
    model.fit(store, checkpoint_dir=ckpt_dir,
              checkpoint_every=int(spec.checkpoint_every))
    return _collect_arrays(model, ("s_",))


def _run_htfa(spec, ckpt_dir):
    from ..factoranalysis.htfa import HTFA

    x, _ = _subject_data(spec)
    rng = np.random.RandomState((int(spec.seed) + 1) & 0x7FFFFFFF)
    coords = [rng.uniform(0.0, 10.0, size=(arr.shape[0], 3))
              for arr in x]
    model = HTFA(K=int(spec.features), n_subj=len(x),
                 max_global_iter=int(spec.n_iter),
                 max_local_iter=2)
    model.fit(x, coords, checkpoint_dir=ckpt_dir,
              checkpoint_every=int(spec.checkpoint_every))
    return _collect_arrays(
        model, ("global_posterior_", "local_posterior_"))


def _run_ridge(spec, ckpt_dir):
    from ..encoding.ridge import RidgeEncoder

    if spec.data is not None:
        xs, y = _load_npz_subjects(spec.data)
        design, responses = xs[0], y
    else:
        rng = np.random.RandomState(int(spec.seed) & 0x7FFFFFFF)
        t = max(int(spec.samples), 4 * 2)
        design = rng.randn(t, int(spec.features))
        responses = rng.randn(t, int(spec.voxels))
    # one lambda per block: the sweep checkpoints (and parks) at
    # per-lambda granularity, n_iter lambdas = n_iter loop steps
    model = RidgeEncoder(
        lambdas=np.logspace(-2.0, 2.0, int(spec.n_iter)),
        n_folds=2, lambda_block=1)
    model.fit(design, responses, checkpoint_dir=ckpt_dir,
              checkpoint_every=int(spec.checkpoint_every))
    return _collect_arrays(model, ("W_", "lambda_"))


_RUNNERS = {
    "srm": _run_srm,
    "incremental_srm": _run_incremental_srm,
    "htfa": _run_htfa,
    "ridge_encoding": _run_ridge,
}
assert set(_RUNNERS) == set(KINDS)


def run_job(spec, workdir):
    """Run ``spec``'s fit to completion (or until parked — the
    ambient :func:`~brainiak_tpu.resilience.guards.park_scope`
    predicate applies, installed by the scheduler's worker).

    Returns ``{"kind", "digest", "arrays"}`` (see module docstring).
    Raises whatever the fit raises — :class:`~brainiak_tpu.
    resilience.guards.FitParked`, :class:`~brainiak_tpu.resilience.
    guards.DivergenceError`, injected faults — classification is the
    scheduler's job, not the runner's.
    """
    from ..resilience.guards import array_digest

    ckpt_dir = checkpoint_dir_for(spec, workdir)
    os.makedirs(ckpt_dir, exist_ok=True)
    arrays = _RUNNERS[spec.kind](spec, ckpt_dir)
    digest = array_digest(*(arrays[k] for k in sorted(arrays))) \
        if arrays else 0.0
    return {"kind": spec.kind, "digest": digest, "arrays": arrays}
