"""Job descriptions, the lifecycle state machine, and the npz codec.

A **job** is one tenant-attributed fit request: which estimator kind
to run (``srm`` / ``incremental_srm`` / ``htfa`` / ``ridge_encoding``
— the chunked fits :func:`~brainiak_tpu.resilience.guards.
run_resilient_loop` drives), its iteration budget, its data (a path,
or a seeded synthetic shape), a scheduling priority, and an optional
soft deadline.  :class:`JobSpec` is a frozen, JSON-serializable value
object; everything mutable (state, fit_id, chunk counts, outcomes)
lives in the scheduler's :class:`~brainiak_tpu.jobs.scheduler.
JobRecord`.

**Lifecycle state machine** (:data:`STATES` / :data:`TERMINAL_STATES`
/ :func:`can_transition`)::

    queued ──────► running ──────► done | failed
       │             │  ▲
       │   (preempt/ ▼  │ (resume)
       │    grant)  parked ──► cancelled | failed
       │             │
       └─────────────┴──► cancelled

plus ``running -> queued`` (a crashed worker requeues the job for a
bounded retry).  Every job reaches EXACTLY ONE terminal state —
``done``, ``failed`` or ``cancelled`` — which is what the JOB001 gate
and the replica-crash test assert.

**npz codec** (:func:`encode_jobs` / :func:`decode_jobs` /
:func:`save_jobs` / :func:`load_jobs`): job batches travel as an npz
archive — one ``job.<i>`` entry per spec (a JSON unicode scalar; no
pickling, so ``allow_pickle=False`` round-trips) — the same wire
idiom the serving tier uses for request payloads, so ``python -m
brainiak_tpu.jobs submit`` can POST a job file to a live fleet's
telemetry port.
"""

import dataclasses
import io
import json
import os
from typing import Optional

import numpy as np

__all__ = [
    "CODEC_SCHEMA",
    "KINDS",
    "STATES",
    "TERMINAL_STATES",
    "JobSpec",
    "can_transition",
    "decode_jobs",
    "encode_jobs",
    "load_jobs",
    "new_job_id",
    "save_jobs",
]

#: Fit kinds the scheduler knows how to drive (see
#: :mod:`brainiak_tpu.jobs.runners`).
KINDS = ("srm", "incremental_srm", "htfa", "ridge_encoding")

#: The lifecycle states (see module docstring for the machine).
STATES = ("queued", "running", "parked", "done", "failed",
          "cancelled")

#: States a job never leaves.  Exactly one per job, ever.
TERMINAL_STATES = frozenset({"done", "failed", "cancelled"})

_TRANSITIONS = {
    "queued": {"running", "cancelled", "failed"},
    # running -> queued: crashed-worker requeue (bounded retry);
    # running -> parked: preemption / chunk-grant exhaustion
    "running": {"parked", "queued", "done", "failed", "cancelled"},
    "parked": {"running", "cancelled", "failed"},
    "done": set(),
    "failed": set(),
    "cancelled": set(),
}

#: npz codec schema version (bumped on incompatible key changes).
CODEC_SCHEMA = 1


def new_job_id():
    """Mint a job id: 16 hex chars (the trace-/fit-id idiom)."""
    return os.urandom(8).hex()


def can_transition(old, new):
    """Whether ``old -> new`` is a legal lifecycle edge."""
    return new in _TRANSITIONS.get(old, set())


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One tenant's fit request (immutable; scheduler state lives in
    the :class:`~brainiak_tpu.jobs.scheduler.JobRecord`).

    Parameters
    ----------
    tenant : str
        Owning tenant — the fair-share / quota accounting unit.
    kind : str
        One of :data:`KINDS`.
    job_id : str
        Stable id (minted when omitted).  Distinct from the fit's
        ``fit_id``: the job id names the *request*, the fit id names
        the *checkpoint stream* (the scheduler joins them through
        :func:`brainiak_tpu.obs.progress.fit_context`).
    priority : int
        Higher runs first and may preempt lower (park via the
        checkpoint contract).  Default 0 (throughput tier).
    n_iter : int
        Iteration budget forwarded to the estimator.
    features : int
        Model dimensionality (SRM/HTFA K, ridge feature count).
    checkpoint_every : int
        Chunk size in iterations — also the park/preempt granularity.
    seed : int
        Synthetic-data and estimator-init seed (bit-exact parity
        between a preempted and an unpreempted run needs both pinned).
    n_subjects, voxels, samples : int
        Synthetic data shape (ignored when ``data`` is set).
    data : str, optional
        Path to the job's input — a ``write_store`` directory for
        store-backed kinds, or an ``.npz`` of ``X.<i>`` subject
        arrays (+ ``Y`` for ridge).  None = seeded synthetic data.
    deadline_s : float, optional
        Soft SLO: seconds from submit to a terminal state.  An
        overrun marks ``deadline_exceeded`` on the record and emits
        a ``job_deadline`` event; it never kills the fit.
    trace_id : str, optional
        Request-trace id propagated from the submitting client.
    """

    tenant: str
    kind: str
    job_id: str = dataclasses.field(default_factory=new_job_id)
    priority: int = 0
    n_iter: int = 6
    features: int = 3
    checkpoint_every: int = 1
    seed: int = 0
    n_subjects: int = 3
    voxels: int = 16
    samples: int = 20
    data: Optional[str] = None
    deadline_s: Optional[float] = None
    trace_id: Optional[str] = None

    def __post_init__(self):
        if not self.tenant or not isinstance(self.tenant, str):
            raise ValueError(
                f"tenant must be a non-empty string, got "
                f"{self.tenant!r}")
        if self.kind not in KINDS:
            raise ValueError(
                f"kind must be one of {KINDS}, got {self.kind!r}")
        if int(self.n_iter) < 1:
            raise ValueError(
                f"n_iter must be >= 1, got {self.n_iter}")
        if int(self.checkpoint_every) < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got "
                f"{self.checkpoint_every}")

    def to_dict(self):
        """Plain JSON-serializable dict (the codec payload)."""
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d):
        """Inverse of :meth:`to_dict`; unknown keys are rejected so
        a forward-incompatible job file fails loudly, not silently."""
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - fields
        if unknown:
            raise ValueError(
                f"unknown JobSpec keys: {sorted(unknown)}")
        return cls(**d)


# -- npz codec --------------------------------------------------------

def encode_jobs(specs):
    """Encode specs as npz bytes (``job.<i>`` JSON scalars; no
    pickling)."""
    arrays = {"codec_schema": np.array(CODEC_SCHEMA),
              "n_jobs": np.array(len(specs))}
    for i, spec in enumerate(specs):
        if not isinstance(spec, JobSpec):
            raise TypeError(f"expected JobSpec, got {type(spec)!r}")
        arrays[f"job.{i}"] = np.array(json.dumps(spec.to_dict()))
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def decode_jobs(data):
    """Decode :func:`encode_jobs` bytes back into a JobSpec list."""
    with np.load(io.BytesIO(data), allow_pickle=False) as archive:
        schema = int(archive["codec_schema"])
        if schema > CODEC_SCHEMA:
            raise ValueError(
                f"job archive codec_schema={schema} is newer than "
                f"supported ({CODEC_SCHEMA})")
        n = int(archive["n_jobs"])
        return [JobSpec.from_dict(
            json.loads(str(archive[f"job.{i}"])))
            for i in range(n)]


def save_jobs(path, specs):
    """Write a job batch to ``path`` (npz); returns the path."""
    data = encode_jobs(specs)
    with open(path, "wb") as fh:
        fh.write(data)
    return path


def load_jobs(path):
    """Read a :func:`save_jobs` archive."""
    with open(path, "rb") as fh:
        return decode_jobs(fh.read())
