"""Weighted fair-share accounting across chunk grants.

The scheduler's currency is the **chunk grant**: a running fit is
allowed some number of resilient-loop chunks before it must yield
(park via checkpoint) and requeue.  :class:`FairShare` keeps the
ledger — per-tenant chunks consumed, normalized by weight — and
answers the only question the scheduler asks: *of the tenants with
runnable work, who is furthest behind its fair share?*

The math is start-time fair queueing reduced to its virtual-time
core.  Tenant *t* with weight :math:`w_t` has consumed :math:`u_t`
chunks; its **virtual time** is :math:`v_t = u_t / w_t`.  The
scheduler always grants the runnable tenant with minimal :math:`v_t`,
which bounds any tenant's service lag behind its entitled share by
one grant per competitor — a light tenant can be delayed at most
``(n_tenants - 1) * grant_chunks`` chunks beyond its fair turn, never
starved (the starvation test asserts the bound).  The reported
**deficit** is entitlement minus consumption,

.. math:: d_t = \\frac{w_t}{\\sum_s w_s} \\cdot U - u_t

(:math:`U` = total chunks consumed): positive = under-served, and the
``obs watch`` scheduler column renders it directly.
"""

import threading

__all__ = ["FairShare"]


class FairShare:
    """Deficit ledger over chunk grants (thread-safe: the scheduler
    tick and worker threads both charge it).

    Parameters
    ----------
    weights : dict, optional
        Tenant -> relative weight (> 0).  Unlisted tenants get
        ``default_weight``.
    default_weight : float
        Weight for tenants without an explicit entry.
    """

    def __init__(self, weights=None, default_weight=1.0):
        if default_weight <= 0:
            raise ValueError(
                f"default_weight must be > 0, got {default_weight}")
        for tenant, w in (weights or {}).items():
            if w <= 0:
                raise ValueError(
                    f"weight for tenant {tenant!r} must be > 0, "
                    f"got {w}")
        self.weights = dict(weights or {})
        self.default_weight = float(default_weight)
        self._lock = threading.Lock()
        self._usage = {}  # guarded-by: _lock (tenant -> chunks)

    def weight(self, tenant):
        """The tenant's relative weight."""
        return float(self.weights.get(tenant, self.default_weight))

    def charge(self, tenant, chunks):
        """Account ``chunks`` consumed by ``tenant``."""
        if chunks < 0:
            raise ValueError(f"chunks must be >= 0, got {chunks}")
        with self._lock:
            self._usage[tenant] = \
                self._usage.get(tenant, 0.0) + float(chunks)

    def usage(self, tenant):
        """Raw chunks consumed by ``tenant``."""
        with self._lock:
            return self._usage.get(tenant, 0.0)

    def virtual_time(self, tenant):
        """``usage / weight`` — the quantity the scheduler
        minimizes."""
        return self.usage(tenant) / self.weight(tenant)

    def pick(self, tenants):
        """The tenant with minimal virtual time (deterministic
        lexical tie-break), or None for an empty candidate set."""
        candidates = sorted(set(tenants))
        if not candidates:
            return None
        return min(candidates,
                   key=lambda t: (self.virtual_time(t), t))

    def deficits(self, tenants=None):
        """Tenant -> entitlement-minus-consumption (see module
        docstring); positive = under-served.  ``tenants`` widens the
        answer to tenants that have not consumed anything yet."""
        with self._lock:
            usage = dict(self._usage)
        for t in tenants or ():
            usage.setdefault(t, 0.0)
        if not usage:
            return {}
        total = sum(usage.values())
        total_w = sum(self.weight(t) for t in usage)
        return {t: (self.weight(t) / total_w) * total - u
                for t, u in usage.items()}

    def summary(self):
        """The ledger as one JSON-serializable dict (the ``/jobs``
        ``tenants`` payload)."""
        with self._lock:
            usage = dict(self._usage)
        deficits = self.deficits()
        return {t: {"usage": usage[t],
                    "weight": self.weight(t),
                    "virtual_time": usage[t] / self.weight(t),
                    "deficit": deficits.get(t, 0.0)}
                for t in sorted(usage)}
