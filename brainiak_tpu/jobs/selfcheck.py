"""CI selfcheck for the fit scheduler (JOB001 gate).

Run as a subprocess child by ``tools/run_checks.py`` on the 8-device
CPU mesh: two tenants submit mixed-priority SRM fits to one
:class:`~brainiak_tpu.jobs.scheduler.Scheduler` co-scheduled with a
warm :class:`~brainiak_tpu.serve.service.ServeService`, and one
priority preemption is injected (the high-priority arrival parks the
running low-priority fit mid-run).  The gate proves:

1. **zero lost jobs** — every submitted job reaches terminal
   ``done`` (no failed/cancelled/zombie records);
2. **resume parity** — the preempted-then-resumed fit's result
   digest equals an uninterrupted solo run of the same spec
   (bit-exact park/resume through the universal ``checkpoint_dir=``
   contract);
3. **fair share** — with equal weights and equal per-tenant work,
   every tenant's deficit ends within tolerance (the
   starvation-freedom ledger);
4. **zero added serve retraces** — serving waves replayed after the
   fits reuse every compiled ``serve.*`` program
   (``serve_retrace_total`` delta stays 0): throughput fits must not
   trash the latency tier's warm cache.
"""

import numpy as np

__all__ = ["selfcheck"]


def selfcheck(out=None):
    """Prints a JSON verdict; returns 0 on pass, 1 on failure."""
    import json
    import os
    import sys
    import tempfile
    import time

    from ..serve import ModelResidency
    from ..serve.batching import BucketPolicy, Request
    from ..serve.service import ServeService, serve_retrace_total
    from ..serve.__main__ import build_demo_model
    from .runners import run_job
    from .scheduler import Scheduler
    from .spec import JobSpec

    stream = out or sys.stdout

    model = build_demo_model(n_subjects=2, voxels=24, samples=32,
                             features=4, n_iter=2, seed=0)
    counts = [w.shape[0] for w in model.w_]
    residency = ModelResidency(
        budget_bytes=1 << 30,
        policy=BucketPolicy(max_batch=8, max_wait_s=0.05))
    residency.register("m", model=model)

    def serve_wave(service, prefix):
        # fixed shapes each wave: any retrace after warmup is a real
        # cache loss, not a new bucket
        rng = np.random.RandomState(5)
        reqs = [Request(request_id=f"{prefix}{i}",
                        x=rng.randn(counts[i % 2], 16)
                        .astype(np.float32),
                        subject=i % 2, model="m")
                for i in range(4)]
        return [t.result(timeout=60.0)
                for t in service.submit_many(reqs)]

    fit_kwargs = dict(kind="srm", n_iter=24, features=3,
                      checkpoint_every=1, n_subjects=3, voxels=16,
                      samples=20)
    low_spec = JobSpec(tenant="hospital-a", priority=0, seed=7,
                       **fit_kwargs)
    hi_spec = JobSpec(tenant="hospital-b", priority=1, seed=11,
                      **fit_kwargs)

    lost = []
    serve_ok = True
    parity_ok = False
    preempt_ok = False
    n_preempt = 0
    max_deficit = float("inf")
    fair_tol = 1.0  # chunks; equal work -> deficits ~0

    with ServeService(residency, default_model="m") as service, \
            tempfile.TemporaryDirectory() as tmp:
        warm = serve_wave(service, "w")
        serve_ok = all(r.error is None for r in warm)
        retrace_warm = serve_retrace_total()

        sched = Scheduler(os.path.join(tmp, "jobs"), max_slots=1,
                          pressure_slots=1,
                          serve_pressure_depth=1 << 20,
                          tick_interval_s=0.01)
        try:
            low_ticket = sched.submit(low_spec)
            # wait for the low-priority fit to be mid-run, then
            # inject the preemption: a higher-priority arrival
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                row = sched.job(low_spec.job_id)
                if row["state"] == "running" and row["chunks"] >= 1:
                    break
                time.sleep(0.02)
            hi_ticket = sched.submit(hi_spec)
            # co-scheduled serving while both fits are in flight
            mid = serve_wave(service, "m")
            serve_ok = serve_ok and all(r.error is None
                                        for r in mid)
            hi_rec = hi_ticket.result(timeout=300.0)
            low_rec = low_ticket.result(timeout=300.0)

            lost = [r["job_id"] for r in (low_rec, hi_rec)
                    if r["state"] != "done"]
            n_preempt = low_rec["n_preemptions"]
            preempt_ok = n_preempt >= 1 \
                and hi_rec["n_preemptions"] == 0

            # parity: same fit params solo (fresh job_id, its own
            # checkpoint tree, never parked) must reach the same
            # digest as the preempted-and-resumed scheduled run
            base = run_job(
                JobSpec(tenant="solo", priority=0, seed=7,
                        **fit_kwargs),
                os.path.join(tmp, "solo"))
            parity_ok = (low_rec["digest"] is not None
                         and low_rec["digest"] == base["digest"])

            summary = sched.summary()
            deficits = [entry["deficit"]
                        for entry in summary["tenants"].values()]
            max_deficit = max(abs(d) for d in deficits) \
                if deficits else float("inf")
        finally:
            sched.close()

        after = serve_wave(service, "a")
        serve_ok = serve_ok and all(r.error is None for r in after)
        retrace_delta = serve_retrace_total() - retrace_warm

    fairshare_ok = max_deficit <= fair_tol
    ok = (not lost and parity_ok and preempt_ok and fairshare_ok
          and serve_ok and retrace_delta == 0)
    json.dump({"ok": bool(ok), "n_jobs": 2, "lost": lost,
               "parity_ok": bool(parity_ok),
               "preempt_ok": bool(preempt_ok),
               "n_preemptions": int(n_preempt),
               "max_deficit": float(max_deficit),
               "fair_tol": fair_tol,
               "fairshare_ok": bool(fairshare_ok),
               "serve_ok": bool(serve_ok),
               "serve_retrace_delta": float(retrace_delta)},
              stream)
    stream.write("\n")
    return 0 if ok else 1
