"""The multi-tenant fit scheduler: gang-scheduling throughput fits
alongside latency-bound serving on one pod.

:class:`Scheduler` owns a job table (:class:`JobRecord` per
submitted :class:`~brainiak_tpu.jobs.spec.JobSpec`) and a tick loop
(daemon thread, the :class:`~brainiak_tpu.serve.service.ServeService`
idiom) that makes all placement decisions:

- **admission** — ``submit()`` consults the shared
  :class:`~brainiak_tpu.serve.federation.admission.
  AdmissionController` (global depth bound + per-tenant quotas): a
  shed submission resolves its ticket immediately with a terminal
  ``failed`` record carrying the typed shed verdict
  (``shed_overload`` semantics, ``retry_after_s`` included) — never
  an unbounded queue;
- **fair share** — among runnable jobs of the top priority, the
  tenant with minimal weighted virtual time
  (:class:`~brainiak_tpu.jobs.quota.FairShare`) runs next; chunk
  consumption is charged from the fit-progress stream, so a heavy
  tenant's long fits push its virtual time up and a light tenant is
  never starved (the deficit column ``obs watch`` renders comes
  straight from this ledger);
- **chunk grants** — a worker may run ``grant_chunks`` resilient-loop
  chunks before it must yield: the park predicate
  (:func:`~brainiak_tpu.resilience.guards.park_scope`) counts chunk
  boundaries and parks the fit via its checkpoint — time-slicing
  without killing work;
- **priority preemption** — a higher-priority arrival parks the
  lowest-priority running fit at its next chunk boundary (the
  universal ``checkpoint_dir=`` contract: same ``fit_id``,
  cumulative wall clock — PR 19 semantics); the parked job resumes
  when capacity returns and lands on bit-exact final parameters;
- **capacity signals** — the same series the
  :class:`~brainiak_tpu.serve.federation.fleet.FleetSupervisor`
  reads (``serve_service_ingress_depth`` + ``serve_service_
  queue_depth`` gauges, ``serve_shed_total`` deltas,
  ``admission.burning()``): under serving pressure the slot count
  drops to ``pressure_slots`` and excess fits park until the burst
  passes;
- **outcome feedback** — a :func:`~brainiak_tpu.obs.progress.
  add_finish_listener` hook folds every fit's terminal
  ``FitProgress.finish(status)`` into the owning job record
  (``fit_status``), so a diverged or retry-exhausted fit becomes a
  terminal ``failed`` job with the flight-recorder snapshot path
  attached — never a zombie "running" entry;
- **crash containment** — a worker death
  (:class:`~brainiak_tpu.resilience.faults.ReplicaCrashError`,
  injectable at site ``jobs.worker``) requeues the job for a bounded
  number of retries (the checkpoint preserves its progress), then
  fails it terminally.  Every job reaches EXACTLY ONE terminal
  state.

State is published two ways: :func:`scheduler_state` (module-level,
merged over live schedulers) feeds the ``/jobs`` HTTP payload, and
``http_port=`` starts a :class:`~brainiak_tpu.obs.http.
TelemetryServer` with the POST control plane attached so ``python -m
brainiak_tpu.jobs submit|status|cancel`` works against the live
process.
"""

import logging
import threading
import time
from collections import deque

from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics
from ..obs import progress as obs_progress
from ..obs import sink as obs_sink
from ..resilience import faults
from ..resilience.guards import FitParked, park_scope
from .quota import FairShare
from .spec import (
    TERMINAL_STATES,
    JobSpec,
    can_transition,
    decode_jobs,
)

logger = logging.getLogger(__name__)

__all__ = ["JobRecord", "JobTicket", "Scheduler", "SchedulerClosed",
           "scheduler_state"]

#: Fault-injection site for worker crashes (see
#: :func:`brainiak_tpu.resilience.faults.crash_point`).
CRASH_SITE = "jobs.worker"

_active_lock = threading.Lock()
_active = []  # guarded-by: _active_lock (live Scheduler instances)


def scheduler_state():
    """Merged ``summary()`` of every live scheduler in this process
    (None when there is none) — the ``scheduler`` key of the
    ``/jobs`` payload."""
    with _active_lock:
        scheds = list(_active)
    if not scheds:
        return None
    merged = {"jobs": [], "tenants": {}, "counts": {}, "slots": 0,
              "pressure": False}
    for sched in scheds:
        summary = sched.summary()
        merged["jobs"].extend(summary["jobs"])
        merged["tenants"].update(summary["tenants"])
        for state, n in summary["counts"].items():
            merged["counts"][state] = \
                merged["counts"].get(state, 0) + n
        merged["slots"] += summary["slots"]
        merged["pressure"] = merged["pressure"] \
            or summary["pressure"]
    return merged


class SchedulerClosed(RuntimeError):
    """Submission to a closed scheduler."""


class JobTicket:
    """Submission handle: resolves exactly once with the job's
    terminal record dict (the :class:`~brainiak_tpu.serve.service.
    ServiceTicket` idiom)."""

    def __init__(self, job_id):
        self.job_id = job_id
        self._event = threading.Event()
        self._record = None

    def done(self):
        """Whether the job has reached its terminal state."""
        return self._event.is_set()

    def result(self, timeout=None):
        """Block for the terminal record dict."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"job {self.job_id} not terminal after "
                f"{timeout} s")
        return self._record

    def _resolve(self, record):
        self._record = record
        self._event.set()


class JobRecord:
    """One job's mutable scheduler state.  All fields are
    guarded-by the owning scheduler's ``_cond`` lock except
    ``park_event`` (a :class:`threading.Event`, safe lock-free) and
    ``result`` arrays (written once by the worker before the done
    outcome is queued)."""

    def __init__(self, spec, seq):
        self.spec = spec
        self.seq = seq                  # FIFO tie-break
        self.state = "queued"
        self.submitted_ts = time.time()
        self.started_ts = None
        self.finished_ts = None
        self.fit_id = None
        self.fit_status = None
        self.chunks = 0.0               # chunks charged to fair share
        self.grants = 0                 # worker launches
        self.n_preemptions = 0
        self.crash_retries = 0
        self.error = None
        self.shed = None
        self.snapshot_path = None
        self.deadline_exceeded = False
        self.result = None              # runner result (arrays incl.)
        self.digest = None
        self.park_event = threading.Event()
        self.park_reason = None
        self.cancel_requested = False
        self.ticket = JobTicket(spec.job_id)

    def to_dict(self):
        """JSON-safe record (no arrays) — the ``/jobs`` row and the
        ticket resolution payload."""
        spec = self.spec
        return {
            "job_id": spec.job_id,
            "tenant": spec.tenant,
            "kind": spec.kind,
            "priority": spec.priority,
            "state": self.state,
            "n_iter": spec.n_iter,
            "submitted_ts": self.submitted_ts,
            "started_ts": self.started_ts,
            "finished_ts": self.finished_ts,
            "fit_id": self.fit_id,
            "fit_status": self.fit_status,
            "chunks": self.chunks,
            "grants": self.grants,
            "n_preemptions": self.n_preemptions,
            "crash_retries": self.crash_retries,
            "error": self.error,
            "shed": self.shed,
            "snapshot_path": self.snapshot_path,
            "deadline_s": spec.deadline_s,
            "deadline_exceeded": self.deadline_exceeded,
            "digest": self.digest,
            "trace_id": spec.trace_id,
        }


class Scheduler:
    """The control plane (see module docstring).

    Parameters
    ----------
    workdir : str
        Root for per-job checkpoint directories
        (``workdir/<job_id>``) — the park/resume contract.
    max_slots : int
        Concurrent fit workers when serving is unpressured.
    pressure_slots : int
        Slot count while serving pressure holds (see
        ``serve_pressure_depth``); excess running fits park.
    grant_chunks : int or None
        Resilient-loop chunks a worker may run per grant before it
        yields (parks + requeues).  None = run to completion unless
        preempted.
    fair_share : :class:`~brainiak_tpu.jobs.quota.FairShare`, optional
        The tenant ledger (default: equal weights).
    admission : :class:`~brainiak_tpu.serve.federation.admission.
        AdmissionController`, optional
        Submission gate (global depth + per-tenant quotas) and the
        SLO-burn capacity sensor.
    serve_pressure_depth : int
        Serving queue depth (``serve_service_ingress_depth`` +
        ``serve_service_queue_depth`` gauge sum) at which the slot
        count drops to ``pressure_slots``.
    max_crash_retries : int
        Worker crashes tolerated per job before terminal failure.
    tick_interval_s : float
        Scheduling-loop cadence.
    http_port : int, optional
        Start a :class:`~brainiak_tpu.obs.http.TelemetryServer` on
        this port (0 = ephemeral) with the jobs control plane
        attached (``POST /jobs/submit``, ``POST /jobs/cancel``).
    name : str
        Label for logs/metrics.
    """

    def __init__(self, workdir, *, max_slots=1, pressure_slots=None,
                 grant_chunks=None, fair_share=None, admission=None,
                 serve_pressure_depth=64, max_crash_retries=1,
                 tick_interval_s=0.02, http_port=None, name="jobs"):
        if max_slots < 1:
            raise ValueError(
                f"max_slots must be >= 1, got {max_slots}")
        if grant_chunks is not None and grant_chunks < 1:
            raise ValueError(
                f"grant_chunks must be >= 1 or None, got "
                f"{grant_chunks}")
        self.workdir = workdir
        self.max_slots = int(max_slots)
        self.pressure_slots = int(
            pressure_slots if pressure_slots is not None
            else max(0, max_slots - 1))
        self.grant_chunks = grant_chunks
        self.fair = fair_share or FairShare()
        self.admission = admission
        self.serve_pressure_depth = int(serve_pressure_depth)
        self.max_crash_retries = int(max_crash_retries)
        self.tick_interval_s = float(tick_interval_s)
        self.name = name
        self._cond = threading.Condition()
        self._jobs = {}       # guarded-by: _cond (job_id -> record)
        self._order = []      # guarded-by: _cond (submission order)
        self._outcomes = deque()  # guarded-by: _cond
        self._seq = 0         # guarded-by: _cond
        self._closing = False  # guarded-by: _cond
        self._last_shed_total = self._serve_shed_total()
        self._pressure = False
        self._workers = {}    # guarded-by: _cond (job_id -> Thread)
        obs_progress.add_finish_listener(self._on_fit_finish)
        self._thread = threading.Thread(
            target=self._loop, name=f"{name}-scheduler", daemon=True)
        self._thread.start()
        self.http = None
        if http_port is not None:
            from ..obs.http import TelemetryServer
            self.http = TelemetryServer(
                port=http_port, control=self._control).start()
        with _active_lock:
            _active.append(self)

    # -- submission (any thread) --------------------------------------

    def submit(self, spec):
        """Admit one job; returns its :class:`JobTicket`.

        A shed verdict (global depth or tenant quota, see
        :class:`~brainiak_tpu.serve.federation.admission.
        AdmissionController`) resolves the ticket immediately with a
        terminal ``failed`` record carrying ``shed`` — callers back
        off ``retry_after_s`` and resubmit, exactly like a shed
        serving request.
        """
        if not isinstance(spec, JobSpec):
            raise TypeError(f"expected JobSpec, got {type(spec)!r}")
        with self._cond:
            if self._closing:
                raise SchedulerClosed(
                    f"scheduler {self.name!r} is closed")
            if spec.job_id in self._jobs:
                raise ValueError(
                    f"duplicate job_id {spec.job_id!r}")
            self._seq += 1
            record = JobRecord(spec, self._seq)
            shed = None
            if self.admission is not None:
                depth = sum(
                    1 for j in self._jobs.values()
                    if j.state not in TERMINAL_STATES)
                tenant_depth = sum(
                    1 for j in self._jobs.values()
                    if j.spec.tenant == spec.tenant
                    and j.state not in TERMINAL_STATES)
                shed = self.admission.evaluate(
                    depth, tenant=spec.tenant,
                    tenant_depth=tenant_depth)
            self._jobs[spec.job_id] = record
            self._order.append(spec.job_id)
            if shed is not None:
                record.shed = {
                    "reason": shed.reason,
                    "retry_after_s": shed.retry_after_s,
                    "depth": shed.depth, "bound": shed.bound,
                }
                record.error = f"shed:{shed.reason}"
                self._finalize_locked(record, "failed")
            else:
                obs_sink.event(
                    "job_submitted", job_id=spec.job_id,
                    tenant=spec.tenant, kind=spec.kind,
                    priority=spec.priority,
                    trace_id=spec.trace_id)
                obs_metrics.counter(
                    "jobs_submitted_total",
                    help="jobs admitted by the fit scheduler").inc(
                        tenant=spec.tenant)
            self._cond.notify_all()
            return record.ticket

    def submit_many(self, specs):
        """Admit a batch; returns tickets in order."""
        return [self.submit(spec) for spec in specs]

    def cancel(self, job_id):
        """Request cancellation; returns False for unknown/terminal
        jobs.  Queued and parked jobs cancel immediately; a running
        job parks at its next chunk boundary and then cancels (its
        checkpoint survives for forensics)."""
        with self._cond:
            record = self._jobs.get(job_id)
            if record is None or record.state in TERMINAL_STATES:
                return False
            record.cancel_requested = True
            if record.state in ("queued", "parked"):
                self._finalize_locked(record, "cancelled")
            else:
                record.park_reason = record.park_reason or "cancel"
                record.park_event.set()
            self._cond.notify_all()
            return True

    def job(self, job_id):
        """The job's current record dict, or None."""
        with self._cond:
            record = self._jobs.get(job_id)
            return record.to_dict() if record is not None else None

    def drain(self, timeout=None):
        """Block until every submitted job is terminal; returns
        whether that happened within ``timeout``."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._cond:
            while any(j.state not in TERMINAL_STATES
                      for j in self._jobs.values()):
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining
                                if remaining is not None else 0.5)
            return True

    def close(self, timeout=10.0):
        """Stop scheduling: park running fits, cancel whatever is
        not terminal, stop the loop (idempotent)."""
        with _active_lock:
            if self in _active:
                _active.remove(self)
        with self._cond:
            if self._closing:
                already = True
            else:
                already = False
                self._closing = True
                for record in self._jobs.values():
                    if record.state == "running":
                        record.park_reason = \
                            record.park_reason or "close"
                        record.park_event.set()
            self._cond.notify_all()
        self._thread.join(timeout)
        obs_progress.remove_finish_listener(self._on_fit_finish)
        if not already:
            with self._cond:
                for record in self._jobs.values():
                    if record.state not in TERMINAL_STATES:
                        self._finalize_locked(record, "cancelled")
        if self.http is not None:
            self.http.stop()
            self.http = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()

    # -- reporting (any thread) ---------------------------------------

    def summary(self):
        """The scheduler's full state as one JSON-safe dict (the
        ``/jobs`` ``scheduler`` payload and the watch feed)."""
        with self._cond:
            jobs = [self._jobs[j].to_dict() for j in self._order]
            pressure = self._pressure
        tenants = {t: dict(v) for t, v in self.fair.summary().items()}
        known = {row["tenant"] for row in jobs}
        deficits = self.fair.deficits(known)
        for tenant in known:
            entry = tenants.setdefault(tenant, {
                "usage": 0.0, "weight": self.fair.weight(tenant),
                "virtual_time": 0.0, "deficit": 0.0})
            entry["deficit"] = deficits.get(tenant, 0.0)
        counts = {}
        for row in jobs:
            counts[row["state"]] = counts.get(row["state"], 0) + 1
        return {"jobs": jobs, "tenants": tenants, "counts": counts,
                "slots": self._slots(pressure),
                "pressure": pressure}

    # -- the control plane (http handler threads) ---------------------

    def _control(self, action, payload):
        if action == "submit":
            try:
                specs = decode_jobs(payload)
            except Exception as exc:
                raise ValueError(f"bad job archive: {exc}") from exc
            verdict = {"accepted": [], "shed": []}
            for spec in specs:
                self.submit(spec)
                with self._cond:
                    shed = self._jobs[spec.job_id].shed
                (verdict["shed"] if shed is not None
                 else verdict["accepted"]).append(spec.job_id)
            return verdict
        if action == "cancel":
            return {"job_id": payload,
                    "cancelled": self.cancel(payload)}
        raise ValueError(f"unknown control action {action!r}")

    # -- capacity signals ---------------------------------------------

    @staticmethod
    def _serve_queue_depth():
        total = 0.0
        for gauge_name in ("serve_service_ingress_depth",
                           "serve_service_queue_depth"):
            for _, value in obs_metrics.gauge(gauge_name).samples():
                total += value
        return total

    @staticmethod
    def _serve_shed_total():
        total = 0.0
        for _, value in obs_metrics.counter(
                "serve_shed_total").samples():
            total += value
        return total

    def _poll_pressure(self):
        """One tick's serving-pressure verdict — the series the
        fleet supervisor reads: queue depth, shed delta, SLO burn."""
        shed_total = self._serve_shed_total()
        shed_delta = shed_total - self._last_shed_total
        self._last_shed_total = shed_total
        burning = self.admission.burning() \
            if self.admission is not None else False
        depth = self._serve_queue_depth()
        return (depth >= self.serve_pressure_depth
                or shed_delta > 0 or burning)

    def _slots(self, pressure):
        return self.pressure_slots if pressure else self.max_slots

    # -- fit-progress feedback (fit worker threads) -------------------

    def _on_fit_finish(self, snapshot):
        """:func:`~brainiak_tpu.obs.progress.add_finish_listener`
        hook: fold the fit outcome into the owning job record."""
        job_id = snapshot.get("job_id")
        if job_id is None:
            return
        with self._cond:
            record = self._jobs.get(job_id)
            if record is None:
                return
            self._sync_progress_locked(record, snapshot)
            record.fit_status = snapshot.get("status")

    def _sync_progress_locked(self, record, snapshot):
        if snapshot.get("fit_id"):
            record.fit_id = snapshot["fit_id"]
        chunks = snapshot.get("chunk")
        if chunks is not None and chunks > record.chunks:
            self.fair.charge(record.spec.tenant,
                             chunks - record.chunks)
            record.chunks = float(chunks)

    # -- the worker (one thread per running grant) --------------------

    def _worker(self, record):
        spec = record.spec
        grant = self.grant_chunks
        ran = {"chunks": 0}

        def should_park():
            # called once per persisted chunk (the park_scope
            # contract) — lock-free: an event read and a counter
            if record.park_event.is_set():
                return True
            ran["chunks"] += 1
            return grant is not None and ran["chunks"] >= grant

        outcome, info = "done", None
        try:
            faults.crash_point(record.grants, site=CRASH_SITE,
                               name=spec.job_id)
            from .runners import run_job
            with obs_progress.fit_context(
                    job_id=spec.job_id, tenant=spec.tenant,
                    trace_id=spec.trace_id), park_scope(should_park):
                info = run_job(spec, self.workdir)
        except FitParked as exc:
            outcome, info = "parked", exc
        except faults.ReplicaCrashError as exc:
            outcome, info = "crashed", exc
        except BaseException as exc:  # divergence, retry-exhausted...
            outcome, info = "failed", exc
        with self._cond:
            self._outcomes.append((record, outcome, info))
            self._cond.notify_all()

    # -- the tick loop (scheduler thread) -----------------------------

    def _loop(self):
        while True:
            with self._cond:
                self._cond.wait(self.tick_interval_s)
                self._drain_outcomes_locked()
                self._sync_running_locked()
                self._check_deadlines_locked()
                self._pressure = pressure = self._poll_pressure()
                if not self._closing:
                    self._schedule_locked(pressure)
                self._publish_gauges_locked()
                running = [j for j in self._jobs.values()
                           if j.state == "running"]
                if self._closing and not running:
                    break

    def _drain_outcomes_locked(self):  # requires-lock: _cond
        while self._outcomes:
            record, outcome, info = self._outcomes.popleft()
            thread = self._workers.pop(record.spec.job_id, None)
            if thread is not None and thread.is_alive() \
                    and thread is not threading.current_thread():
                pass  # the outcome was queued last; thread is exiting
            if record.state in TERMINAL_STATES:
                continue  # cancel raced completion; terminal stands
            if outcome == "done":
                record.result = info
                record.digest = info.get("digest")
                self._finalize_locked(record, "done")
            elif outcome == "parked":
                reason = record.park_reason or "grant"
                record.park_reason = None
                record.park_event.clear()
                if record.cancel_requested:
                    self._finalize_locked(record, "cancelled")
                elif self._closing:
                    self._finalize_locked(record, "cancelled")
                else:
                    self._transition_locked(record, "parked")
                    if reason in ("preempt", "pressure"):
                        record.n_preemptions += 1
                        obs_metrics.counter(
                            "jobs_preempted_total",
                            help="running fits parked by priority "
                                 "preemption or serving "
                                 "pressure").inc(
                                tenant=record.spec.tenant)
                    obs_sink.event(
                        "job_parked", job_id=record.spec.job_id,
                        tenant=record.spec.tenant, reason=reason,
                        fit_id=record.fit_id)
            elif outcome == "crashed":
                record.crash_retries += 1
                obs_sink.event(
                    "job_worker_crash",
                    job_id=record.spec.job_id,
                    tenant=record.spec.tenant,
                    attempt=record.crash_retries, error=str(info))
                if record.cancel_requested:
                    self._finalize_locked(record, "cancelled")
                elif record.crash_retries > self.max_crash_retries:
                    record.error = f"replica_crash: {info}"
                    self._finalize_locked(record, "failed")
                else:
                    # the checkpoint survives the crash: requeue and
                    # resume from it on the next grant
                    self._transition_locked(record, "queued")
            else:  # failed
                record.error = repr(info)
                dump = obs_flight.last_dump(
                    fit_id=record.fit_id,
                    since=record.started_ts)
                if dump is not None:
                    record.snapshot_path = dump["path"]
                self._finalize_locked(record, "failed")

    def _sync_running_locked(self):  # requires-lock: _cond
        running = {j.spec.job_id: j for j in self._jobs.values()
                   if j.state == "running"}
        if not running:
            return
        for snap in obs_progress.active_fits():
            record = running.get(snap.get("job_id"))
            if record is not None:
                self._sync_progress_locked(record, snap)

    def _check_deadlines_locked(self):  # requires-lock: _cond
        now = time.time()
        for record in self._jobs.values():
            deadline = record.spec.deadline_s
            if deadline is None or record.deadline_exceeded \
                    or record.state in TERMINAL_STATES:
                continue
            if now - record.submitted_ts > deadline:
                record.deadline_exceeded = True
                obs_sink.event(
                    "job_deadline", job_id=record.spec.job_id,
                    tenant=record.spec.tenant, deadline_s=deadline,
                    waited_s=now - record.submitted_ts)

    def _schedule_locked(self, pressure):  # requires-lock: _cond
        slots = self._slots(pressure)
        running = [j for j in self._jobs.values()
                   if j.state == "running"]
        # pressure park: shrink to the pressured slot count, lowest
        # priority first (FIFO tie-break: park the newest)
        excess = [j for j in running if not j.park_event.is_set()]
        while len(excess) > slots:
            victim = min(excess,
                         key=lambda j: (j.spec.priority, -j.seq))
            victim.park_reason = "pressure"
            victim.park_event.set()
            excess.remove(victim)
        runnable = sorted(
            (j for j in self._jobs.values()
             if j.state in ("queued", "parked")
             and not j.cancel_requested),
            key=lambda j: (-j.spec.priority,
                           self.fair.virtual_time(j.spec.tenant),
                           j.seq))
        free = slots - len(running)
        for record in runnable:
            if free <= 0:
                break
            self._start_locked(record)
            free -= 1
        if free <= 0 and runnable:
            # priority preemption: the best waiter outranks the
            # weakest running fit -> park it (one per tick: parks
            # complete at chunk granularity, not instantly)
            waiting = [j for j in runnable
                       if j.state in ("queued", "parked")]
            victims = [j for j in self._jobs.values()
                       if j.state == "running"
                       and not j.park_event.is_set()]
            if waiting and victims:
                best = waiting[0]
                victim = min(victims,
                             key=lambda j: (j.spec.priority,
                                            -j.seq))
                if best.spec.priority > victim.spec.priority:
                    victim.park_reason = "preempt"
                    victim.park_event.set()
                    obs_sink.event(
                        "job_preempt_requested",
                        job_id=victim.spec.job_id,
                        tenant=victim.spec.tenant,
                        by_job=best.spec.job_id,
                        by_priority=best.spec.priority)

    def _start_locked(self, record):  # requires-lock: _cond
        resumed = record.state == "parked"
        self._transition_locked(record, "running")
        record.park_event.clear()
        record.park_reason = None
        record.grants += 1
        if record.started_ts is None:
            record.started_ts = time.time()
        thread = threading.Thread(
            target=self._worker, args=(record,),
            name=f"{self.name}-worker-{record.spec.job_id[:6]}",
            daemon=True)
        self._workers[record.spec.job_id] = thread
        obs_sink.event(
            "job_resumed" if resumed else "job_started",
            job_id=record.spec.job_id, tenant=record.spec.tenant,
            grant=record.grants, fit_id=record.fit_id,
            trace_id=record.spec.trace_id)
        thread.start()

    def _transition_locked(self, record, new):  # requires-lock: _cond
        if not can_transition(record.state, new):
            raise RuntimeError(
                f"illegal job transition {record.state} -> {new} "
                f"for {record.spec.job_id}")
        record.state = new

    def _finalize_locked(self, record, state):  # requires-lock: _cond
        """The ONLY path into a terminal state: transition, stamp,
        resolve the ticket exactly once, count."""
        if record.state in TERMINAL_STATES:
            return  # exactly-one-terminal: first verdict stands
        self._transition_locked(record, state)
        record.finished_ts = time.time()
        obs_sink.event(
            f"job_{state}", job_id=record.spec.job_id,
            tenant=record.spec.tenant, fit_id=record.fit_id,
            error=record.error, fit_status=record.fit_status,
            snapshot_path=record.snapshot_path)
        obs_metrics.counter(
            "jobs_terminal_total",
            help="jobs that reached a terminal state").inc(
                tenant=record.spec.tenant, state=state)
        record.ticket._resolve(record.to_dict())
        self._cond.notify_all()

    def _publish_gauges_locked(self):  # requires-lock: _cond
        counts = {}
        for record in self._jobs.values():
            counts[record.state] = counts.get(record.state, 0) + 1
        gauge = obs_metrics.gauge(
            "jobs_state_depth",
            help="jobs per lifecycle state in the fit scheduler")
        for state in ("queued", "running", "parked"):
            gauge.set(counts.get(state, 0), state=state,
                      scheduler=self.name)
