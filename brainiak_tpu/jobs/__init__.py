"""Fit-as-a-service: the multi-tenant training control plane.

Serving is always-on; this package makes *fitting* always-on too.
Tenants describe fits as :class:`~brainiak_tpu.jobs.spec.JobSpec`
values (npz-codec batches over the wire), the
:class:`~brainiak_tpu.jobs.scheduler.Scheduler` gang-schedules them
as resumable chunk sequences through
:func:`~brainiak_tpu.resilience.guards.run_resilient_loop` —
priority preemption parks running fits via the universal
``checkpoint_dir=`` contract, weighted fair-share
(:class:`~brainiak_tpu.jobs.quota.FairShare`) keeps any one tenant
from starving the rest, and per-tenant quotas wire into the serving
tier's :class:`~brainiak_tpu.serve.federation.admission.
AdmissionController`.  Scheduler state feeds the ``/jobs`` endpoint
(rendered by ``python -m brainiak_tpu.obs watch``) and ``python -m
brainiak_tpu.jobs submit|status|cancel`` speaks to a live fleet.

See ``docs/jobs.md`` for the lifecycle state machine, the
scheduling policy, the fair-share math, and the preemption
contract.
"""

from .quota import FairShare  # noqa: F401
from .scheduler import (  # noqa: F401
    JobRecord,
    JobTicket,
    Scheduler,
    SchedulerClosed,
    scheduler_state,
)
from .spec import (  # noqa: F401
    KINDS,
    STATES,
    TERMINAL_STATES,
    JobSpec,
    decode_jobs,
    encode_jobs,
    load_jobs,
    save_jobs,
)

__all__ = [
    "KINDS",
    "STATES",
    "TERMINAL_STATES",
    "FairShare",
    "JobRecord",
    "JobSpec",
    "JobTicket",
    "Scheduler",
    "SchedulerClosed",
    "decode_jobs",
    "encode_jobs",
    "load_jobs",
    "save_jobs",
    "scheduler_state",
]
