"""Gaussian RBF factor matrices, MTTKRP-style.

TPU-native replacement for the reference's C++/OpenMP TFA extension
(/root/reference/src/brainiak/factoranalysis/tfa_extension.cpp:30-165).
The reference computes F[v,k] = exp(-||R_v - c_k||^2 / w_k) separably per
dimension over unique coordinate values plus a gather — a cache optimization
for CPUs.

The first TPU port broadcast the distance tensor directly, which
materializes a ``[V, K, n_dim]`` intermediate in HBM before the row
reduction — the obs cost records put every ``tfa.*``/``htfa.*`` site
well under the roofline with bytes-accessed dominated by exactly that
tensor.  Following the loop-reordering playbook of the sparse-MTTKRP
formulation (https://arxiv.org/pdf/1708.08976), the kernels here
restructure the contraction instead:

- :func:`rbf_factors` expands ``||R_v - c_k||² = ||R_v||² - 2 R_v·c_k
  + ||c_k||²`` so the distance matrix is one MXU matmul plus rank-1
  broadcasts — no ``[V, K, n_dim]`` tensor exists at any point.
- :func:`rbf_weight_products` and :func:`rbf_residual_sum` go one
  step further for the fit loops: the factor matrix is reconstructed
  **chunk-by-chunk over voxels, fused with the contraction that
  consumes it** (``FᵀF``/``FᵀX`` for the ridge weight solve, the
  masked residual reduction for the NLLS objective), so the full
  ``[V, K]`` factor matrix never materializes per iteration either.

Identical numerics to the naive broadcast form up to float summation
order (parity-tested in ``tests/factoranalysis`` and the KRN001
gate).
"""

import functools

import jax
import jax.numpy as jnp

__all__ = ["rbf_factors", "rbf_residual_sum", "rbf_weight_products",
           "reconstruction_residual"]

#: Voxel chunk for the fused factor-times-data contractions: big
#: enough to keep the MXU fed, small enough that the per-chunk
#: [chunk, K] factor tile and [chunk, T] residual tile stay cheap.
_CHUNK = 1024


@jax.jit
def rbf_factors(R, centers, widths):
    """F[v, k] = exp(-||R_v - centers_k||^2 / widths_k).

    R: [n_voxels, n_dim]; centers: [K, n_dim]; widths: [K] or [K, 1].
    Returns [n_voxels, K].  The squared distance is computed by the
    matmul decomposition (see module docstring) — one ``R @ centersᵀ``
    on the MXU instead of a broadcast ``[V, K, n_dim]`` intermediate.

    Distances are translation-invariant, so both operands are
    centered on the coordinate mean first: without it, real scanner
    coordinates (~200 mm offsets) make ``||R||² - 2R·c`` cancel
    catastrophically in float32 (~1e4x accuracy loss vs the
    broadcast form).  ``sq`` is clamped at zero — rounding could
    otherwise leave it slightly negative and factors above 1.
    """
    widths = widths.reshape(-1)
    mu = jnp.mean(R, axis=0, keepdims=True)
    Rc = R - mu
    Cc = centers - mu
    sq = (jnp.sum(Rc * Rc, axis=1)[:, None]
          - 2.0 * Rc @ Cc.T
          + jnp.sum(Cc * Cc, axis=1)[None, :])
    return jnp.exp(-jnp.maximum(sq, 0.0) / widths[None, :])


def _chunked(R, X, vmask, chunk):
    """Reshape the voxel axis into [n_chunks, chunk, ...] scan
    operands, zero-padding the tail; the mask (existing voxel mask
    times the pad mask) zeroes pad factor rows so they contribute
    nothing to any contraction."""
    v = R.shape[0]
    chunk = min(chunk, v) if chunk else v
    pad = (-v) % chunk
    mask = jnp.ones((v,), R.dtype) if vmask is None \
        else vmask.astype(R.dtype)
    if pad:
        R = jnp.pad(R, ((0, pad), (0, 0)))
        X = jnp.pad(X, ((0, pad), (0, 0)))
        mask = jnp.pad(mask, (0, pad))
    n_chunks = R.shape[0] // chunk
    return (R.reshape(n_chunks, chunk, -1),
            X.reshape(n_chunks, chunk, -1),
            mask.reshape(n_chunks, chunk))


@functools.partial(jax.jit, static_argnames=("chunk",))
def rbf_weight_products(R, centers, widths, X, vmask=None,
                        chunk=_CHUNK):
    """``(FᵀF [K, K], FᵀX [K, T])`` with the factor matrix
    reconstructed chunk-by-chunk, fused with the accumulation — the
    inputs of the ridge weight solve without ever materializing the
    full ``[V, K]`` F.  ``vmask`` (optional, [V]) zeroes masked
    voxels' factor rows (the HTFA ragged-padding convention).
    """
    Rc, Xc, mc = _chunked(R, X, vmask, chunk)

    def body(carry, operands):
        g, b = carry
        r, x, m = operands
        f = rbf_factors(r, centers, widths) * m[:, None]
        return (g + f.T @ f, b + f.T @ x), None

    k = centers.shape[0]
    init = (jnp.zeros((k, k), R.dtype),
            jnp.zeros((k, X.shape[1]), R.dtype))
    (g, b), _ = jax.lax.scan(body, init, (Rc, Xc, mc))
    return g, b


@functools.partial(jax.jit, static_argnames=("nlss_loss", "chunk"))
def rbf_residual_sum(R, centers, widths, X, W, sigma, vmask=None,
                     tmask=None, nlss_loss="linear", chunk=_CHUNK):
    """``Σ rho((sigma · (X - F W))²)`` with F reconstructed
    chunk-by-chunk fused with the residual reduction — the NLLS data
    term of the TFA/HTFA objective without the full ``[V, K]`` factor
    matrix or ``[V, T]`` residual in HBM.  ``rho`` is identity for
    ``nlss_loss="linear"`` and the soft-L1 transform otherwise; masks
    follow the HTFA padding convention (masked rows/columns
    contribute zero).
    """
    Rc, Xc, mc = _chunked(R, X, vmask, chunk)
    tm = None if tmask is None else tmask[None, :]

    def body(total, operands):
        r, x, m = operands
        f = rbf_factors(r, centers, widths) * m[:, None]
        recon = sigma * (x * m[:, None] - f @ W)
        if tm is not None:
            recon = recon * tm
        sq = recon * recon
        if nlss_loss == "soft_l1":
            # pad rows are exactly 0, and rho(0) = 0 for soft_l1
            # too, so padding stays inert under the transform
            return total + jnp.sum(2.0 * (jnp.sqrt(1.0 + sq) - 1.0)), \
                None
        return total + jnp.sum(sq), None

    total, _ = jax.lax.scan(body, jnp.zeros((), R.dtype),
                            (Rc, Xc, mc))
    return total


@jax.jit
def reconstruction_residual(X, F, W, sigma):
    """sigma * (X - F @ W) — the reference's ``recon`` kernel
    (tfa_extension.cpp:169-239)."""
    return sigma * (X - F @ W)
