"""Gaussian RBF factor matrices.

TPU-native replacement for the reference's C++/OpenMP TFA extension
(/root/reference/src/brainiak/factoranalysis/tfa_extension.cpp:30-165).
The reference computes F[v,k] = exp(-||R_v - c_k||^2 / w_k) separably per
dimension over unique coordinate values plus a gather — a cache optimization
for CPUs.  On TPU a plain broadcasted computation is one fused XLA kernel
feeding the MXU-bound downstream matmuls, so the unique-coords machinery
disappears.
"""

import jax
import jax.numpy as jnp

__all__ = ["rbf_factors", "reconstruction_residual"]


@jax.jit
def rbf_factors(R, centers, widths):
    """F[v, k] = exp(-||R_v - centers_k||^2 / widths_k).

    R: [n_voxels, n_dim]; centers: [K, n_dim]; widths: [K] or [K, 1].
    Returns [n_voxels, K].
    """
    widths = widths.reshape(-1)
    sq = jnp.sum((R[:, None, :] - centers[None, :, :]) ** 2, axis=-1)
    return jnp.exp(-sq / widths[None, :])


@jax.jit
def reconstruction_residual(X, F, W, sigma):
    """sigma * (X - F @ W) — the reference's ``recon`` kernel
    (tfa_extension.cpp:169-239)."""
    return sigma * (X - F @ W)
