"""Jittable statistical ops for on-device resampling.

JAX counterparts of host utilities in :mod:`brainiak_tpu.utils.utils`
(reference: utils/utils.py:720-872).  These take explicit ``jax.random`` keys
so resampling nulls (bootstrap/permutation/phase-shift in
:mod:`brainiak_tpu.isc`) can be built as ``vmap`` over keys instead of
Python ``for`` loops over a stateful RandomState (reference isc.py:739-787).
"""

from functools import partial

import jax
import jax.numpy as jnp

__all__ = ["phase_randomize", "p_from_null"]


@partial(jax.jit, static_argnames=("voxelwise",))
def phase_randomize(key, data, voxelwise=False):
    """Phase-randomize time series (axis 0 = time), preserving power spectra.

    data : [n_TRs, n_voxels, n_subjects] (or [n_TRs, n_subjects] — treated
    as one voxel).  Same phase shifts across voxels unless ``voxelwise``.
    Mirrors utils.phase_randomize (reference utils/utils.py:720-801) with a
    jax.random key instead of a RandomState.
    """
    squeeze = data.ndim == 2
    if squeeze:
        data = data[:, None, :]
    n_TRs, n_voxels, n_subjects = data.shape

    # Positive-frequency bins 1..ceil((n-1)/2); conjugate bins mirrored.
    n_pos = (n_TRs - 1) // 2 if n_TRs % 2 else n_TRs // 2 - 1
    pos = jnp.arange(1, n_pos + 1)
    neg = n_TRs - pos

    shift_vox = n_voxels if voxelwise else 1
    # dtype threaded from the input so an f32 program stays f32 even
    # under x64 tracing (the uniform default would promote to f64)
    shifts = jax.random.uniform(
        key, (n_pos, shift_vox, n_subjects),
        dtype=jnp.real(data).dtype) * 2 * jnp.pi

    f = jnp.fft.fft(data, axis=0)
    rot = jnp.exp(1j * shifts).astype(f.dtype)
    f = f.at[pos].multiply(rot)
    f = f.at[neg].multiply(jnp.conj(rot))
    out = jnp.real(jnp.fft.ifft(f, axis=0))
    if squeeze:
        out = out[:, 0, :]
    return out


@partial(jax.jit, static_argnames=("side", "exact"))
def p_from_null(observed, distribution, side="two-sided", exact=False):
    """p-value of observed vs a null distribution whose axis 0 indexes
    resampling iterations (broadcasting over remaining axes).

    Mirrors utils.p_from_null (reference utils/utils.py:804-872).
    """
    n = distribution.shape[0]
    if side == "two-sided":
        numerator = jnp.sum(
            jnp.abs(distribution) >= jnp.abs(observed), axis=0)
    elif side == "left":
        numerator = jnp.sum(distribution <= observed, axis=0)
    elif side == "right":
        numerator = jnp.sum(distribution >= observed, axis=0)
    else:
        raise ValueError("side must be 'two-sided', 'left' or 'right'")
    if exact:
        return numerator / n
    return (numerator + 1) / (n + 1)
