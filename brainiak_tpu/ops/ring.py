"""Ring-sharded pairwise correlation over the voxel dimension.

The framework's "long context" is the voxel axis (SURVEY.md §5.7): a full
V×V correlation (the FCMA feature space, ISFC matrices, RSA kernels) at
whole-brain V cannot replicate the data on every chip.  This module
computes it the way ring attention computes long-sequence scores: the
voxel axis is sharded over the mesh, each device keeps its local shard
resident, and the peer shards ROTATE around the ring via
``jax.lax.ppermute`` — after n_shards steps every [local × remote] block
of the correlation matrix has been produced with only nearest-neighbor
ICI traffic and O(V/n) memory per device, never materializing the full
data anywhere.

The ring program itself now lives in the pod-scale linear algebra layer
(:mod:`brainiak_tpu.ops.distla`) as the general SUMMA primitive — this
module is the stable single-axis entry point the ISC/ISFC slab loop and
RSA callers use; :func:`brainiak_tpu.ops.distla.summa_gram` additionally
rides multi-axis (2-D mesh) rings, uneven panel splits, and the
checkpointable :func:`~brainiak_tpu.ops.distla.panel_gram` variant.

For data that fits replicated, prefer the plain einsum
(:func:`brainiak_tpu.ops.correlation.correlate_epochs`) or the
budget-dispatching :func:`brainiak_tpu.ops.distla.gram`; the ring pays
communication to buy memory.
"""

from .distla import summa_gram

__all__ = ["ring_correlation"]


def ring_correlation(data, mesh, data_b=None, axis_name="voxel"):
    """All-pairs Pearson correlation of the columns of ``data`` (against
    the columns of ``data_b`` when given) with the voxel axis sharded
    around a ring.

    data : [T, V] float array (V divisible by the mesh axis size);
        columns are variables, rows observations.
    data_b : optional [T, V] second array — computes the
        cross-correlation corr[i, j] = r(data[:, i], data_b[:, j]) (the
        LOO-ISFC pattern); ``data``'s shard stays resident while
        ``data_b``'s shards rotate.
    mesh : jax.sharding.Mesh with ``axis_name``.
    Returns corr [V, V], sharded over its first axis.
    """
    n_shards = mesh.shape[axis_name]
    v = data.shape[1]
    assert v % n_shards == 0, \
        f"voxel count {v} must be divisible by the {axis_name} axis " \
        f"size ({n_shards})"
    if data_b is not None:
        assert data_b.shape == data.shape, \
            "data_b must have the same shape as data"
    return summa_gram(data, mesh, data_b=data_b,
                      axis_names=(axis_name,))
