"""Ring-sharded pairwise correlation over the voxel dimension.

The framework's "long context" is the voxel axis (SURVEY.md §5.7): a full
V×V correlation (the FCMA feature space, ISFC matrices, RSA kernels) at
whole-brain V cannot replicate the data on every chip.  This module
computes it the way ring attention computes long-sequence scores: the
voxel axis is sharded over the mesh, each device keeps its local shard
resident, and the peer shards ROTATE around the ring via
``jax.lax.ppermute`` — after n_shards steps every [local × remote] block
of the correlation matrix has been produced with only nearest-neighbor
ICI traffic and O(V/n) memory per device, never materializing the full
data anywhere.

For data that fits replicated, prefer the plain einsum
(:func:`brainiak_tpu.ops.correlation.correlate_epochs`); the ring pays
communication to buy memory.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec
from jax import shard_map

from ..parallel.mesh import place_on_mesh
from .correlation import PRECISION

__all__ = ["ring_correlation"]


def _zscore_cols(data):
    """Column z-score + 1/sqrt(T), zero for constant columns (matching
    compute_correlation) and NaN for NaN-containing columns (so missing
    data propagates instead of fabricating finite correlations), making a
    plain dot of two normalized columns their Pearson r."""
    t = data.shape[0]
    mean = data.mean(axis=0, keepdims=True)
    std = data.std(axis=0, keepdims=True)
    safe_std = jnp.where(std > 0, std, 1.0)
    z = jnp.where(std > 0, (data - mean) / (safe_std * np.sqrt(t)), 0.0)
    return jnp.where(jnp.isnan(std), jnp.nan, z)


@functools.lru_cache(maxsize=None)
def _ring_program(mesh, axis_name):
    """Build (once per mesh/axis) the jitted ring program; jit caching
    keeps repeated calls — e.g. per-subject ISFC — from re-tracing."""
    n_shards = mesh.shape[axis_name]

    def ring_fn(z_local, zb_local):
        # z_local stays resident; zb shards visit around the ring
        my_idx = jax.lax.axis_index(axis_name)
        block_cols = zb_local.shape[1]

        def step(rotating, _):
            # block of corr rows (local) x cols (the shard currently held)
            block = jax.lax.dot_general(
                z_local, rotating, (((0,), (0,)), ((), ())),
                precision=PRECISION,
                preferred_element_type=z_local.dtype)
            # pass the visiting shard to the next device on the ring
            rotating = jax.lax.ppermute(
                rotating, axis_name,
                [(i, (i + 1) % n_shards) for i in range(n_shards)])
            return rotating, block

        _, blocks = jax.lax.scan(step, zb_local, None, length=n_shards)
        # blocks[s] holds corr[local, owner] where the owner of the shard
        # seen at step s is (my_idx - s) mod n_shards; scatter into place
        owners = (my_idx - jnp.arange(n_shards)) % n_shards
        out = jnp.zeros((z_local.shape[1], n_shards, block_cols),
                        dtype=z_local.dtype)
        out = out.at[:, owners, :].set(
            jnp.transpose(blocks, (1, 0, 2)))
        return out.reshape(z_local.shape[1], n_shards * block_cols)

    return jax.jit(shard_map(
        ring_fn, mesh=mesh,
        in_specs=(PartitionSpec(None, axis_name),
                  PartitionSpec(None, axis_name)),
        out_specs=PartitionSpec(axis_name, None)))


def ring_correlation(data, mesh, data_b=None, axis_name="voxel"):
    """All-pairs Pearson correlation of the columns of ``data`` (against
    the columns of ``data_b`` when given) with the voxel axis sharded
    around a ring.

    data : [T, V] float array (V divisible by the mesh axis size);
        columns are variables, rows observations.
    data_b : optional [T, V] second array — computes the
        cross-correlation corr[i, j] = r(data[:, i], data_b[:, j]) (the
        LOO-ISFC pattern); ``data``'s shard stays resident while
        ``data_b``'s shards rotate.
    mesh : jax.sharding.Mesh with ``axis_name``.
    Returns corr [V, V], sharded over its first axis.
    """
    n_shards = mesh.shape[axis_name]
    v = data.shape[1]
    assert v % n_shards == 0, \
        f"voxel count {v} must be divisible by the {axis_name} axis " \
        f"size ({n_shards})"
    if data_b is not None:
        assert data_b.shape == data.shape, \
            "data_b must have the same shape as data"

    # shard FIRST, z-score after: the full [T, V] array is never resident
    # on one device (z-scoring is columnwise, so it runs shard-local)
    spec = NamedSharding(mesh, PartitionSpec(None, axis_name))
    z = _zscore_cols(place_on_mesh(data, spec))
    z_b = z if data_b is None else _zscore_cols(
        place_on_mesh(data_b, spec))
    return _ring_program(mesh, axis_name)(z, z_b)
