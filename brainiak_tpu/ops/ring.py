"""Ring-sharded pairwise correlation over the voxel dimension.

The framework's "long context" is the voxel axis (SURVEY.md §5.7): a full
V×V correlation (the FCMA feature space, ISFC matrices, RSA kernels) at
whole-brain V cannot replicate the data on every chip.  This module
computes it the way ring attention computes long-sequence scores: the
voxel axis is sharded over the mesh, each device keeps its local shard
resident, and the peer shards ROTATE around the ring via
``jax.lax.ppermute`` — after n_shards steps every [local × remote] block
of the correlation matrix has been produced with only nearest-neighbor
ICI traffic and O(V/n) memory per device, never materializing the full
data anywhere.

For data that fits replicated, prefer the plain einsum
(:func:`brainiak_tpu.ops.correlation.correlate_epochs`); the ring pays
communication to buy memory.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec
from jax.experimental.shard_map import shard_map

from .correlation import PRECISION

__all__ = ["ring_correlation"]


def ring_correlation(data, mesh, axis_name="voxel"):
    """All-pairs Pearson correlation of the columns of ``data`` with the
    voxel axis sharded around a ring.

    data : [T, V] float array (V divisible by the mesh axis size);
        columns are variables, rows observations.
    mesh : jax.sharding.Mesh with ``axis_name``.
    Returns corr [V, V], sharded over its first axis.
    """
    n_shards = mesh.shape[axis_name]
    t, v = data.shape
    assert v % n_shards == 0, \
        f"voxel count {v} must divide the {axis_name} axis ({n_shards})"

    # z-score + 1/sqrt(T) once, so each block is a plain matmul
    mean = data.mean(axis=0, keepdims=True)
    std = data.std(axis=0, keepdims=True)
    safe_std = jnp.where(std > 0, std, 1.0)
    z = jnp.where(std > 0, (data - mean) / (safe_std * np.sqrt(t)), 0.0)
    z = jax.device_put(
        z, NamedSharding(mesh, PartitionSpec(None, axis_name)))

    def ring_fn(z_local):
        # z_local: [T, V/n] — this device's resident shard
        my_idx = jax.lax.axis_index(axis_name)
        block_cols = z_local.shape[1]

        def step(rotating, _):
            # block of corr rows (local) x cols (the shard currently held)
            block = jax.lax.dot_general(
                z_local, rotating, (((0,), (0,)), ((), ())),
                precision=PRECISION,
                preferred_element_type=z_local.dtype)
            # pass the visiting shard to the next device on the ring
            rotating = jax.lax.ppermute(
                rotating, axis_name,
                [(i, (i + 1) % n_shards) for i in range(n_shards)])
            return rotating, block

        _, blocks = jax.lax.scan(step, z_local, None, length=n_shards)
        # blocks[s] holds corr[local, owner] where the owner of the shard
        # seen at step s is (my_idx - s) mod n_shards; scatter into place
        owners = (my_idx - jnp.arange(n_shards)) % n_shards
        out = jnp.zeros((z_local.shape[1], n_shards, block_cols),
                        dtype=z_local.dtype)
        out = out.at[:, owners, :].set(
            jnp.transpose(blocks, (1, 0, 2)))
        return out.reshape(z_local.shape[1], n_shards * block_cols)

    corr = shard_map(
        ring_fn, mesh=mesh,
        in_specs=PartitionSpec(None, axis_name),
        out_specs=PartitionSpec(axis_name, None))(z)
    return corr
