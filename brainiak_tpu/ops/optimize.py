"""Bounded smooth minimization in JAX (L-BFGS + box transform).

TPU-native replacement for the reference's use of
``scipy.optimize.least_squares(method='trf', bounds=...)`` inside TFA/HTFA
(reference factoranalysis/tfa.py:738-821): instead of a host trust-region
solver calling C++ residual kernels, the whole bounded nonlinear
least-squares problem is one jitted L-BFGS program — box constraints are
eliminated with a sigmoid reparameterization and gradients come from
autodiff, so the hand-coded Jacobian machinery disappears.  The acceptance
criterion is recovery quality, not iterate-level parity with scipy
(SURVEY.md §7 hard part #2).
"""

import jax
import jax.numpy as jnp
import optax

__all__ = ["minimize_lbfgs", "minimize_bounded", "stiefel_minimize"]


def minimize_lbfgs(fun, x0, max_iters=100, tol=1e-8):
    """Minimize ``fun`` from ``x0`` with optax L-BFGS (zoom linesearch).

    Returns (x, value).  The loop runs under jit via lax.while_loop with a
    gradient-norm stopping rule.
    """
    opt = optax.lbfgs()
    value_and_grad = optax.value_and_grad_from_state(fun)

    def cond(carry):
        _, state, it, gnorm = carry
        return (it < max_iters) & (gnorm > tol)

    def body(carry):
        x, state, it, _ = carry
        value, grad = value_and_grad(x, state=state)
        updates, state = opt.update(grad, state, x, value=value,
                                    grad=grad, value_fn=fun)
        x = optax.apply_updates(x, updates)
        return x, state, it + 1, jnp.linalg.norm(grad)

    state = opt.init(x0)
    x, state, _, _ = jax.lax.while_loop(
        cond, body, (x0, state, 0, jnp.asarray(jnp.inf, x0.dtype)))
    return x, fun(x)


def stiefel_minimize(fun, w0, max_iters=100, tol=1e-6, n_backtrack=10,
                     initial_step=1.0):
    """Minimize ``fun(W)`` over the Stiefel manifold {W : WᵀW = I}.

    Riemannian gradient descent: the Euclidean gradient is projected to the
    tangent space (G − W·sym(WᵀG)), the step is retracted with a
    sign-corrected QR factorization, and the step size is chosen by
    evaluating a geometric ladder of candidates in parallel (a vmapped
    backtracking line search — the TPU-friendly replacement for
    pymanopt's conjugate gradient used by the reference's SS-SRM,
    funcalign/sssrm.py:456-557).

    Returns (W, value).  Call from inside jit or eagerly.
    """
    value_and_grad = jax.value_and_grad(fun)
    steps = initial_step * (0.5 ** jnp.arange(n_backtrack,
                                              dtype=w0.dtype))

    def retract(w, d):
        q, r = jnp.linalg.qr(w + d)
        s = jnp.sign(jnp.diag(r))
        s = jnp.where(s == 0, 1.0, s)
        return q * s[None, :]

    def cond(carry):
        _, _, it, gnorm = carry
        return (it < max_iters) & (gnorm > tol)

    def body(carry):
        w, value, it, _ = carry
        _, g = value_and_grad(w)
        wtg = w.T @ g
        d = -(g - w @ ((wtg + wtg.T) / 2))
        gnorm = jnp.linalg.norm(d)

        candidates = jax.vmap(lambda t: retract(w, t * d))(steps)
        values = jax.vmap(fun)(candidates)
        values = jnp.where(jnp.isnan(values), jnp.inf, values)
        best = jnp.argmin(values)
        improved = values[best] < value
        w_new = jnp.where(improved, candidates[best], w)
        v_new = jnp.where(improved, values[best], value)
        # if no candidate improves, stop (gnorm -> 0)
        gnorm = jnp.where(improved, gnorm, 0.0)
        return w_new, v_new, it + 1, gnorm

    v0 = fun(w0)
    w, value, _, _ = jax.lax.while_loop(
        cond, body, (w0, v0, 0, jnp.asarray(jnp.inf, w0.dtype)))
    return w, value


def _to_unbounded(x, lo, hi, eps=1e-6):
    frac = jnp.clip((x - lo) / (hi - lo), eps, 1 - eps)
    return jnp.log(frac) - jnp.log1p(-frac)


def _to_bounded(z, lo, hi):
    return lo + (hi - lo) * jax.nn.sigmoid(z)


def minimize_bounded(fun, x0, lower, upper, max_iters=100, tol=1e-8):
    """Minimize ``fun`` subject to ``lower <= x <= upper``.

    The box is mapped to R^n by x = lo + (hi-lo)*sigmoid(z) and the
    unconstrained problem is solved with :func:`minimize_lbfgs`.
    Returns (x, value).  Call from inside a jitted function (it traces;
    it is not itself jitted so closures over device arrays are fine).
    """
    z0 = _to_unbounded(x0, lower, upper)
    z, value = minimize_lbfgs(lambda z: fun(_to_bounded(z, lower, upper)),
                              z0, max_iters=max_iters, tol=tol)
    return _to_bounded(z, lower, upper), value
