"""Fisher-z transform + within-subject epoch normalization.

TPU-native replacement for the reference's C++/OpenMP extension
(/root/reference/src/brainiak/fcma/src/fcma_extension.cc:29-92,
``normalization``).  The OpenMP parallel-for over (voxel, subject) becomes a
single fused elementwise+reduction XLA computation.
"""

from functools import partial

import jax
import jax.numpy as jnp

__all__ = ["fisher_z", "within_subject_normalization"]

_CLAMP = 1e-4


@jax.jit
def fisher_z(r):
    """Fisher z-transform ``0.5*log((1+r)/(1-r))`` with the reference's
    clamping: numerator/denominator floored at 1e-4 when non-positive
    (fcma_extension.cc:68-72)."""
    r = jnp.asarray(r, dtype=jnp.float32)
    num = 1.0 + r
    den = 1.0 - r
    num = jnp.where(num <= 0.0, _CLAMP, num)
    den = jnp.where(den <= 0.0, _CLAMP, den)
    return 0.5 * jnp.log(num / den)


@partial(jax.jit, static_argnames=("epochs_per_subj",))
def within_subject_normalization(corr, epochs_per_subj):
    """Fisher-z then z-score each correlation across a subject's epochs.

    Parameters
    ----------
    corr : [n_selected_voxels, n_epochs, n_voxels]
        Raw correlations; epochs of each subject are contiguous and
        ``n_epochs % epochs_per_subj == 0``.
    epochs_per_subj : int

    Returns
    -------
    Normalized array, same shape.  Population std computed as
    ``E[x^2] - mean^2``; non-positive variance yields zeros
    (fcma_extension.cc:74-84).
    """
    b, e, v = corr.shape
    if e % epochs_per_subj != 0:
        raise ValueError(
            f"number of epochs ({e}) must be a multiple of "
            f"epochs_per_subj ({epochs_per_subj}); check that data "
            "splits respect subject boundaries")
    n_subjs = e // epochs_per_subj
    z = fisher_z(corr).reshape(b, n_subjs, epochs_per_subj, v)
    mean = jnp.mean(z, axis=2, keepdims=True)
    var = jnp.mean(z * z, axis=2, keepdims=True) - mean * mean
    inv_std = jnp.where(var <= 0.0, 0.0, jax.lax.rsqrt(var))
    return ((z - mean) * inv_std).reshape(b, e, v)
