"""Fused rotate-multiply-accumulate step for the SUMMA ring.

The unfused ring step in :mod:`brainiak_tpu.ops.distla` is three
HBM-bound stages per rotation: the panel matmul writes its block to
the scan's stacked output, the post-scan transpose re-lays the whole
``[n_shards, V/n, B]`` stack out again, and the owner scatter copies
it a third time into the final ``[V/n, V]`` buffer.  The cost records
(`obs report`, site ``distla.summa``) put the site well under the
roofline with bytes-accessed dominated by exactly those relayouts.

Fused form: the output buffer is carried through the scan and each
step's panel product lands **directly** in its final column slice —
one write per element of C, no stack, no transpose, no scatter.  Two
implementations, selected by :func:`ring_step_mode`:

- ``"pallas"`` (TPU, when the working set fits the VMEM budget): a
  Pallas kernel tiles the local panel product on the MXU and uses a
  scalar-prefetched owner index to place each output tile at its
  dynamic column block (``PrefetchScalarGridSpec`` — the index map
  reads the owner before the kernel body runs, so the DMA writes the
  final location).  The carried output aliases the kernel output
  (``input_output_aliases``), so untouched blocks are never copied.
- ``"fused"`` (everywhere else, and the TPU fallback): one
  ``lax.dynamic_update_slice`` per step on the donated scan carry —
  XLA fuses the dot into the in-place update.

``"unfused"`` requests the original three-stage formulation; it is
kept as the measured reference for the ``kernels`` bench tier and
the parity tests, never auto-selected.  ``BRAINIAK_TPU_RING_STEP``
overrides the mode for experiments.

VMEM discipline follows :mod:`brainiak_tpu.ops.pallas_kernels`: tile
sizes are derived from a float budget under the 16 MB scoped-VMEM
limit, and callers fall back to the XLA path when the extents cannot
tile (:func:`pick_ring_tiles` returns ``fits=False``).
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["RING_STEP_ENV", "mma_update", "pick_ring_tiles",
           "ring_mma", "ring_step_mode"]

#: Env override for the ring-step implementation
#: (``pallas`` / ``fused`` / ``unfused``).
RING_STEP_ENV = "BRAINIAK_TPU_RING_STEP"

#: VMEM budget per program, in floats — shared with the FCMA
#: kernels (double-buffered I/O tiles under the 16 MB scoped-VMEM
#: limit) so a budget retune lands everywhere at once.
from ..pallas_kernels import _VMEM_BUDGET_FLOATS  # noqa: E402

_MODES = ("pallas", "fused", "unfused")


def pick_ring_tiles(n_trs, n_local, n_block):
    """Choose ``(tile_r, fits)`` for the Pallas ring step.

    Each program holds the rotating panel ``[T, B]``, one resident
    column tile ``[T, tile_r]``, and one output tile
    ``[tile_r, B]`` (double-buffered I/O).  ``fits`` is False when
    even the smallest Mosaic-alignable tile exceeds the budget or
    the extents cannot tile (callers take the XLA path then):
    ``tile_r`` must divide ``n_local`` and — as the last axis of the
    resident-operand block — stay a multiple of 128.
    """

    def used(tr):
        return 2 * n_trs * (n_block + tr) + 2 * tr * n_block

    tile_r = min(512, n_local)
    while tile_r > 128 and (used(tile_r) > _VMEM_BUDGET_FLOATS
                            or n_local % tile_r):
        tile_r //= 2
    fits = (tile_r >= 128 and n_local % tile_r == 0
            and n_block % 128 == 0 and n_trs % 8 == 0
            and used(tile_r) <= _VMEM_BUDGET_FLOATS)
    return tile_r, fits


def ring_step_mode(n_trs, n_local, n_block, backend=None):
    """The ring-step implementation for one (T, V/n, B) extent:
    ``"pallas"`` on TPU when :func:`pick_ring_tiles` fits, else
    ``"fused"``.  ``BRAINIAK_TPU_RING_STEP`` overrides (unknown
    values are ignored)."""
    env = os.environ.get(RING_STEP_ENV, "").strip().lower()
    if env in _MODES:
        return env
    if backend is None:
        try:
            backend = jax.default_backend()
        except Exception:  # pragma: no cover - backend init failure
            backend = "cpu"
    if backend == "tpu" and pick_ring_tiles(n_trs, n_local,
                                            n_block)[1]:
        return "pallas"
    return "fused"


def _mma_kernel(owner_ref, z_ref, rot_ref, out_in_ref, out_ref, *,
                precision):
    """One ``[tile_r, B]`` output tile: resident-columns x rotating
    panel on the MXU, written straight to its owner column block
    (the index maps already placed this tile; nothing else moves)."""
    del owner_ref, out_in_ref  # consumed by the index maps / aliasing
    out_ref[...] = jax.lax.dot_general(
        z_ref[...], rot_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=out_ref.dtype, precision=precision)


def ring_mma(out, z_local, rotating, owner, *, n_shards, tile_r=None,
             precision=None, interpret=False):
    """Fused multiply-place for one ring step (Pallas).

    out : [V_local, n_shards * B] carried output buffer
    z_local : [T, V_local] resident columns
    rotating : [T, B] the panel currently held
    owner : traced int32 — which column *block* of ``out`` this panel
        owns (the scalar-prefetch argument the output index map
        reads).

    Returns ``out`` with block ``owner`` overwritten by
    ``z_localᵀ @ rotating``; every other block is aliased through
    untouched.
    """
    n_trs, n_local = z_local.shape
    n_block = rotating.shape[1]
    if tile_r is None:
        tile_r, fits = pick_ring_tiles(n_trs, n_local, n_block)
        if not fits and not interpret:
            raise ValueError(
                f"ring extents (T={n_trs}, V/n={n_local}, B={n_block})"
                " do not tile for the Pallas ring step; use the "
                "'fused' XLA mode")
        tile_r = min(tile_r, n_local)
    assert n_local % tile_r == 0, \
        "V_local must be a multiple of tile_r"
    if precision is None:
        precision = jax.lax.Precision.HIGHEST
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_local // tile_r,),
        in_specs=[
            pl.BlockSpec((n_trs, tile_r), lambda i, o: (0, i)),
            pl.BlockSpec((n_trs, n_block), lambda i, o: (0, 0)),
            pl.BlockSpec((tile_r, n_block), lambda i, o: (i, o[0])),
        ],
        out_specs=pl.BlockSpec((tile_r, n_block),
                               lambda i, o: (i, o[0])),
    )
    return pl.pallas_call(
        functools.partial(_mma_kernel, precision=precision),
        out_shape=jax.ShapeDtypeStruct(
            (n_local, n_shards * n_block), out.dtype),
        grid_spec=grid_spec,
        input_output_aliases={3: 0},
        interpret=interpret,
    )(jnp.asarray(owner, jnp.int32).reshape(1), z_local, rotating,
      out)


def mma_update(out, z_local, rotating, col_start, precision=None):
    """Fused multiply-place for one ring step (XLA fallback): the
    panel product written in place at its final column offset on the
    donated scan carry — XLA fuses the dot into the update, so each
    element of C is written exactly once."""
    block = jax.lax.dot_general(
        z_local, rotating, (((0,), (0,)), ((), ())),
        precision=precision, preferred_element_type=out.dtype)
    # both indices pinned to one dtype: the literal 0 would otherwise
    # weak-type to int64 under x64 while the traced offset is int32
    return jax.lax.dynamic_update_slice(
        out, block, (jnp.int32(0), jnp.asarray(col_start, jnp.int32)))
