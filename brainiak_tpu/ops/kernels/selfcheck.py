"""CI selfcheck for the fused kernels (``kernels`` gate, KRN001).

Parity of every fused path against its unfused reference, plus the
retrace-stability contract, on a tiny fixture over the 8-device CPU
mesh the gate child pins:

- single-scan HMM forward-backward vs the two-scan reference
  (including the masked-log edge case where an event column is
  entirely ``-inf`` — the -inf/NaN masks must agree exactly);
- fused rotate-multiply-accumulate SUMMA ring step vs the unfused
  three-stage formulation and a NumPy dense Gram (even and uneven
  splits, NaN-column propagation);
- MTTKRP-style factor reconstruction (matmul-decomposed
  :func:`~brainiak_tpu.ops.rbf.rbf_factors`, chunked
  ``FᵀF``/``FᵀX`` products) vs the naive broadcast einsum;
- device-side epoch z-score vs the NumPy fallback.

Everything runs TWICE; the second pass must add zero program-builder
cache misses on any fused site (``retrace_total{site=...}`` stays
flat), which the verdict reports as ``retraces[site] == 1``.
Prints a JSON verdict; returns 0 on pass, 1 on failure.
"""

import json
import sys

import numpy as np

__all__ = ["selfcheck"]

#: Fused sites whose builder caches must be stable across the two
#: passes.
_SITES = ("eventseg.forward_backward", "distla.summa",
          "fcma.epoch_norm")


def _fb_diff(a, b):
    """Max abs difference of two log-domain arrays where mutual
    ``-inf``/NaN entries count as equal, plus a mask-mismatch flag
    (a fused path must not invent or lose zero-probability
    states)."""
    a, b = np.asarray(a), np.asarray(b)
    mismatch = bool(np.any(np.isneginf(a) != np.isneginf(b))
                    or np.any(np.isnan(a) != np.isnan(b)))
    same = np.isneginf(a) & np.isneginf(b)
    with np.errstate(invalid="ignore"):
        d = np.abs(a - b)
    d[same | np.isnan(a) | np.isnan(b)] = 0.0
    return float(np.max(d)) if d.size else 0.0, mismatch


def _run_once(mesh, errs, flags):
    import jax.numpy as jnp

    from ...eventseg import event as ev
    from .. import distla, rbf
    from . import epoch_norm, ring

    rng = np.random.RandomState(0)

    # -- single-scan HMM forward-backward vs two-scan reference ----
    t, k = 48, 6
    es = ev.EventSegment(k)
    log_P, log_p_start, log_p_end = es._build_transitions(t)
    lp = np.hstack([rng.randn(t, k), np.full((t, 1), -np.inf)])
    args = (jnp.asarray(log_P), jnp.asarray(log_p_start),
            jnp.asarray(log_p_end))
    for case in (lp, np.where(np.arange(k + 1) == 2, -np.inf, lp)):
        g1, l1 = ev._fb_program()(jnp.asarray(case), *args)
        g2, l2 = ev._fb_reference_program()(jnp.asarray(case), *args)
        d, mism = _fb_diff(g1, g2)
        errs.append(d)
        flags.append(("fb_mask", mism))
        ld, lmism = _fb_diff(np.asarray([l1]), np.asarray([l2]))
        errs.append(ld)
        flags.append(("fb_ll_mask", lmism))

    # -- fused SUMMA ring step -------------------------------------
    t2, v = 16, 64
    n = mesh.devices.size
    data = rng.randn(t2, v).astype(np.float32)
    z = (data - data.mean(0)) / (data.std(0) * np.sqrt(t2))
    dense = z.T @ z
    fused = np.asarray(distla.summa_gram(data, mesh,
                                         ring_step="fused"))
    unfused = np.asarray(distla.summa_gram(data, mesh,
                                           ring_step="unfused"))
    errs.append(float(np.max(np.abs(fused - dense))))
    errs.append(float(np.max(np.abs(fused - unfused))))
    got_u = np.asarray(distla.summa_gram(data[:, :v - n + 1], mesh,
                                         ring_step="fused"))
    errs.append(float(np.max(np.abs(
        got_u - dense[:v - n + 1, :v - n + 1]))))
    nan_data = data.copy()
    nan_data[:, 3] = np.nan
    got_n = np.asarray(distla.summa_gram(nan_data, mesh,
                                         ring_step="fused"))
    flags.append(("ring_nan",
                  not (np.all(np.isnan(got_n[3]))
                       and np.all(np.isnan(got_n[:, 3]))
                       and np.isnan(got_n).sum() == 2 * v - 1)))
    # the Pallas step body itself, interpreter-mode, vs the XLA step
    out0 = jnp.zeros((8, 4 * 16), jnp.float32)
    zl = jnp.asarray(rng.randn(8, 8).astype(np.float32))
    rot = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    via_pallas = np.asarray(ring.ring_mma(
        out0, zl, rot, 2, n_shards=4, tile_r=8, interpret=True))
    via_xla = np.asarray(ring.mma_update(out0, zl, rot, 2 * 16))
    errs.append(float(np.max(np.abs(via_pallas - via_xla))))

    # -- MTTKRP factor reconstruction ------------------------------
    vv, kk, dd, tt = 300, 4, 3, 20
    R = rng.randn(vv, dd)
    C = rng.randn(kk, dd)
    W = np.abs(rng.rand(kk, 1)) + 1.0
    X = rng.randn(vv, tt)
    naive = np.exp(
        -np.einsum('vkd->vk',
                   (R[:, None, :] - C[None]) ** 2) / W.T)
    got_f = np.asarray(rbf.rbf_factors(
        jnp.asarray(R), jnp.asarray(C), jnp.asarray(W)))
    errs.append(float(np.max(np.abs(got_f - naive))))
    g, b = rbf.rbf_weight_products(
        jnp.asarray(R), jnp.asarray(C), jnp.asarray(W),
        jnp.asarray(X), chunk=128)
    errs.append(float(np.max(np.abs(np.asarray(g)
                                    - naive.T @ naive))))
    errs.append(float(np.max(np.abs(np.asarray(b) - naive.T @ X))))

    # -- device epoch norm vs NumPy fallback -----------------------
    mats = [rng.randn(30, 25).astype(np.float32) for _ in range(3)]
    mats[1][:, 4] = 1.5  # constant column -> exact zeros
    import os
    prev = os.environ.get(epoch_norm.EPOCH_NORM_ENV)
    os.environ[epoch_norm.EPOCH_NORM_ENV] = "device"
    try:
        dev = epoch_norm.normalize_epochs(mats)
    finally:
        if prev is None:
            os.environ.pop(epoch_norm.EPOCH_NORM_ENV, None)
        else:
            os.environ[epoch_norm.EPOCH_NORM_ENV] = prev
    for mat, got in zip(mats, dev):
        ref = epoch_norm._numpy_epoch_zscore(mat)
        errs.append(float(np.max(np.abs(got - ref))))


def selfcheck(out=None):
    """Run the fused-kernel parity suite twice and print the KRN001
    JSON verdict (``ok``/``max_err``/``tol``/``retraces``/
    ``n_shards``); returns 0 on pass, 1 on failure."""
    from ...obs import metrics as obs_metrics
    from ...parallel.mesh import (DEFAULT_VOXEL_AXIS, make_mesh,
                                  max_divisible_shards)

    stream = out or sys.stdout
    n = max_divisible_shards(64)
    mesh = make_mesh((DEFAULT_VOXEL_AXIS,), (n,))
    errs, flags = [], []
    _run_once(mesh, errs, flags)
    retrace = obs_metrics.counter("retrace_total")
    before = {site: retrace.value(site=site) for site in _SITES}
    _run_once(mesh, errs, flags)
    # 1 = stable (the second pass rebuilt nothing); >1 = the excess
    # builder misses the repeat pass added
    retraces = {site: 1.0 + retrace.value(site=site) - before[site]
                for site in _SITES}
    bad_flags = sorted({name for name, bad in flags if bad})
    tol = 5e-4
    ok = (max(errs) < tol and not bad_flags
          and all(count <= 1.0 for count in retraces.values())
          and all(before[site] > 0 for site in _SITES))
    json.dump({"ok": bool(ok), "max_err": max(errs), "tol": tol,
               "n_shards": int(n), "mask_mismatch": bad_flags,
               "retraces": retraces}, stream)
    stream.write("\n")
    return 0 if ok else 1
