"""Roofline-guided fused kernels for the attributed memory-bound sites.

PR 4 built the attribution (``obs report`` joins XLA cost records with
span durations into achieved-FLOP/s and roofline ratios per site);
this package spends those numbers.  Each module fuses one hot site
the cost records showed to be HBM-bound, following the loop-reorder /
fusion playbook of the sparse-MTTKRP formulation
(https://arxiv.org/pdf/1708.08976) and the communication-avoiding
batch discipline of DrJAX (https://arxiv.org/pdf/2403.07128):

- :mod:`.ring` — the fused rotate-multiply-accumulate SUMMA ring
  step: each panel product lands directly in its final output slice
  (Pallas with dynamic block placement on TPU, one jit-fused
  ``dynamic_update_slice`` per step elsewhere) instead of the
  stack → transpose → scatter relayout of the unfused ring.
- :mod:`.epoch_norm` — the device-side FCMA ingest epoch z-score
  that retires the host C++ ``native/epoch_norm`` round-trip (the
  last native-extension dependency on a hot path).
- :mod:`.selfcheck` — the KRN001 CI gate body: fused-vs-reference
  parity (single-scan HMM forward-backward, fused ring step,
  MTTKRP factor reconstruction, epoch norm) plus the
  retrace-stability contract on every fused site.

The single-scan HMM forward-backward lives with its estimator
(:mod:`brainiak_tpu.eventseg.event`) and the MTTKRP-style factor
contractions in :mod:`brainiak_tpu.ops.rbf`; this package holds the
kernels that are not tied to one estimator.  The FCMA
correlation+Fisher-z fusion that seeded the pattern stays in
:mod:`brainiak_tpu.ops.pallas_kernels`.
"""

from .epoch_norm import epoch_zscore, normalize_epochs
from .selfcheck import selfcheck

__all__ = [
    "epoch_zscore",
    "mma_update",
    "normalize_epochs",
    "pick_ring_tiles",
    "ring_mma",
    "ring_step_mode",
    "selfcheck",
]

#: ring.py exports, resolved lazily (PEP 562): ring.py imports
#: jax + pallas at module scope, and the FCMA ingest path imports
#: this package — eager re-export would pull the whole jax/pallas
#: stack into host-only ingest consumers at import time.
_RING_EXPORTS = ("mma_update", "pick_ring_tiles", "ring_mma",
                 "ring_step_mode")


def __getattr__(name):
    if name in _RING_EXPORTS or name == "ring":
        import importlib
        # importlib, not `from . import ring`: the from-import form
        # re-enters this __getattr__ through _handle_fromlist
        ring = importlib.import_module(".ring", __name__)
        return ring if name == "ring" else getattr(ring, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
