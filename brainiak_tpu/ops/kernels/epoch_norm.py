"""Device-side FCMA ingest epoch normalization.

Retires the host C++/OpenMP ``native/epoch_norm`` round-trip (the
last native-extension dependency on a hot path): the per-epoch
column z-score + ``1/sqrt(T)`` scaling that makes correlation a
plain matmul now runs as one jitted device program per distinct
epoch shape — :func:`normalize_epochs` groups a subject's epochs by
shape and normalizes each group in ONE dispatch, instead of one
ctypes call per epoch.

Numerics match the native kernel (and its NumPy fallback) exactly:
population standard deviation, zero output for zero-variance
columns, and non-finite results mapped to zero
(``nan_to_num`` semantics — NaN inputs normalize to zero rather
than poisoning the epoch).

On TPU the z-score runs as a Pallas kernel over voxel tiles (the
:mod:`~brainiak_tpu.ops.pallas_kernels` VMEM-budget discipline)
when the extents tile; everywhere else it is plain fused XLA.  The
NumPy path is kept as the fallback for forced-host operation
(``BRAINIAK_TPU_EPOCH_NORM=numpy``), tiny batches where dispatch
overhead dominates, and hosts where the device path fails —
toolchain-less hosts keep working, now without needing g++ either.
"""

import logging
import math
import os

import numpy as np

from ...obs import profile as obs_profile
from ...obs import runtime as obs_runtime
from ...obs import spans as obs_spans

logger = logging.getLogger(__name__)

__all__ = ["EPOCH_NORM_ENV", "epoch_zscore", "normalize_epochs"]

#: Env override: ``numpy`` forces the host fallback, ``device``
#: forces the device path even for tiny batches.
EPOCH_NORM_ENV = "BRAINIAK_TPU_EPOCH_NORM"

#: Below this many elements per batch the host path wins (one jit
#: dispatch costs more than the BLAS-free normalization of a small
#: epoch group).
_MIN_DEVICE_ELEMS = 1 << 16

def _vmem_budget_floats():
    """The shared VMEM budget (``pallas_kernels``'s constant, so a
    budget retune lands everywhere at once) — imported lazily: this
    module must not pull jax/pallas in at import time (ingest code
    imports it before any device work)."""
    from ..pallas_kernels import _VMEM_BUDGET_FLOATS
    return _VMEM_BUDGET_FLOATS


def _numpy_epoch_zscore(mat):
    """Host-fallback column z-score (population) + ``1/sqrt(rows)``
    of one ``[rows, cols]`` epoch; zero-variance columns become
    zero.  Bit-compatible with the retired native kernel's own NumPy
    fallback."""
    rows = mat.shape[0]
    mean = mat.mean(axis=0)
    std = mat.std(axis=0)
    with np.errstate(divide="ignore", invalid="ignore"):
        out = (mat - mean) / (std * np.sqrt(rows))
    return np.nan_to_num(out, nan=0.0, posinf=0.0,
                         neginf=0.0).astype(mat.dtype, copy=False)


def _pick_tile_v(n_trs, n_vox):
    """Voxel tile width for the Pallas path, or 0 when the extents
    do not tile under the VMEM budget (callers fall back to XLA)."""

    budget = _vmem_budget_floats()

    def used(tv):
        return 5 * n_trs * tv

    tile_v = min(512, n_vox)
    while tile_v > 128 and (used(tile_v) > budget
                            or n_vox % tile_v):
        tile_v //= 2
    # tile_v % 128: the lane (last) dimension must stay aligned or
    # Mosaic rejects the block — same contract as ring.py's
    # n_block % 128 guard
    if tile_v >= 128 and tile_v % 128 == 0 and n_vox % tile_v == 0 \
            and n_trs % 8 == 0 and used(tile_v) <= budget:
        return tile_v
    return 0


def _zscore_block(x):
    """Shared normalization body: z-score over the (row) time axis
    of one ``[..., T, V]`` block, non-finite results zeroed.

    Constant columns are detected EXACTLY (max == min) rather than
    through a zero-variance test: XLA lowers the mean's division to
    a multiply-by-reciprocal, so a constant column's residual can be
    ±1 ulp instead of 0 and would otherwise normalize to ±1/sqrt(T)
    — the NumPy/native contract is that such columns come out
    zero."""
    import jax.numpy as jnp
    t = x.shape[-2]
    mean = jnp.mean(x, axis=-2, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=-2, keepdims=True)
    out = (x - mean) / (jnp.sqrt(var) * math.sqrt(t))
    constant = jnp.max(x, axis=-2, keepdims=True) == \
        jnp.min(x, axis=-2, keepdims=True)
    return jnp.where(constant | ~jnp.isfinite(out), 0.0, out)


def _zscore_kernel(x_ref, out_ref):
    out_ref[...] = _zscore_block(x_ref[...])


def _pallas_batch_zscore(batch, tile_v, interpret):
    import jax
    from jax.experimental import pallas as pl

    n, t, v = batch.shape
    return pl.pallas_call(
        _zscore_kernel,
        out_shape=jax.ShapeDtypeStruct((n, t, v), batch.dtype),
        grid_spec=pl.GridSpec(
            grid=(n, v // tile_v),
            in_specs=[pl.BlockSpec((1, t, tile_v),
                                   lambda i, j: (i, 0, j))],
            out_specs=pl.BlockSpec((1, t, tile_v),
                                   lambda i, j: (i, 0, j)),
        ),
        interpret=interpret,
    )(batch)


@obs_runtime.counted_cache("fcma.epoch_norm")
def _epoch_norm_program(use_pallas, interpret=False):
    """Build (once per mode) the jitted batched epoch z-score
    program for ``[N, T, V]`` stacks.  Cache misses count as
    ``retrace_total{site=fcma.epoch_norm}``; under cost profiling
    the program captures a ``cost`` record joined to the
    ``fcma.epoch_norm`` span."""
    import jax

    def fn(batch):
        if use_pallas:
            tile_v = _pick_tile_v(batch.shape[1], batch.shape[2])
            if tile_v:
                return _pallas_batch_zscore(batch, tile_v, interpret)
        return _zscore_block(batch)

    return obs_profile.profile_program(
        jax.jit(fn), "fcma.epoch_norm", span="fcma.epoch_norm")


@obs_runtime.trace_signature("fcma.epoch_norm")
def _epoch_norm_trace_signature():
    import jax
    import jax.numpy as jnp

    return [{"key": (False,),
             "args": (jax.ShapeDtypeStruct((2, 5, 7), jnp.float32),)}]


def _mode():
    return os.environ.get(EPOCH_NORM_ENV, "").strip().lower()


def _use_pallas():
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend init failure
        return False


def epoch_zscore(mat, interpret=False):
    """Column z-score (population) + ``1/sqrt(rows)`` scaling of one
    ``[rows, cols]`` epoch; zero-variance columns become zero.

    Returns a NEW array (the retired native kernel normalized in
    place; no caller relied on the aliasing).  Small epochs and
    ``BRAINIAK_TPU_EPOCH_NORM=numpy`` take the host path.
    """
    return normalize_epochs([mat], interpret=interpret)[0]


def normalize_epochs(mats, interpret=False):
    """Normalize a list of ``[rows, cols]`` epochs, grouped by shape
    so each distinct shape costs ONE device dispatch (FCMA datasets
    are usually uniform-length, so the whole ingest is one program
    on one stacked batch).  Order is preserved; dtype is preserved.

    The host fallback runs per epoch when forced
    (``BRAINIAK_TPU_EPOCH_NORM=numpy``), when the batch is too small
    to amortize a dispatch, or when the device path fails.
    """
    mats = list(mats)
    if not mats:
        return []
    mode = _mode()
    out = [None] * len(mats)
    groups = {}
    for i, mat in enumerate(mats):
        groups.setdefault(np.shape(mat), []).append(i)
    for shape, idxs in groups.items():
        # size from the shape alone — the stacked copy is only built
        # once a group is committed to the device path
        group_elems = len(idxs) * int(np.prod(shape))
        if mode == "numpy" or (mode != "device"
                               and group_elems < _MIN_DEVICE_ELEMS):
            for i in idxs:
                out[i] = _numpy_epoch_zscore(np.asarray(mats[i]))
            continue
        try:
            import jax.numpy as jnp
            batch = np.stack([np.asarray(mats[i]) for i in idxs])
            dev = jnp.asarray(batch)
            if dev.dtype != batch.dtype:
                # the backend would silently downcast (float64 in,
                # x64 off): the dtype-preservation contract wins —
                # take the exact host path for this group
                for i in idxs:
                    out[i] = _numpy_epoch_zscore(np.asarray(mats[i]))
                continue
            program = _epoch_norm_program(_use_pallas(),
                                          interpret=interpret)
            with obs_spans.span("fcma.epoch_norm",
                                attrs={"n_epochs": len(idxs),
                                       "n_trs": int(shape[0]),
                                       "n_voxels": int(shape[1])}):
                # the fetch is the point: ingest hands host arrays
                # to downstream estimator constructors
                res = np.asarray(  # jaxlint: disable=JX002
                    program(dev))
        except Exception as exc:  # device path unusable -> host
            logger.info("device epoch norm unavailable (%s); using "
                        "NumPy fallback", exc)
            for i in idxs:
                out[i] = _numpy_epoch_zscore(np.asarray(mats[i]))
            continue
        for j, i in enumerate(idxs):
            out[i] = res[j]
    return out
