"""Masked elementwise ops.

Replacement for the reference's Cython ``masked_log``
(/root/reference/src/brainiak/eventseg/_utils.pyx:27): elementwise log with
non-positive entries mapped to -inf, as one jittable op.
"""

import jax
import jax.numpy as jnp

__all__ = ["masked_log"]


@jax.jit
def masked_log(x):
    """log(x) with x<=0 mapped to -inf (no warnings), any shape."""
    x = jnp.asarray(x)
    return jnp.where(x > 0, jnp.log(jnp.where(x > 0, x, 1.0)), -jnp.inf)
