"""Pod-scale distributed linear algebra: SUMMA-sharded Grams and
sharded batched solves.

The scaling wall this layer removes (ROADMAP open item 2): the seed's
FCMA Gram and ISC slab loops replicate the "all voxels" operand on
every device and reduce on one chip, so a whole-brain (~50k-voxel)
[V, V] correlation matrix is only reachable by subsampling — the same
wall the reference package hit with MPI.  Following "Large Scale
Distributed Linear Algebra With Tensor Processing Units"
(https://arxiv.org/pdf/2112.09017), the answer is SUMMA-style panel
matmul on the device mesh: every operand panel and every output block
stays sharded, panels move between nearest neighbors over ICI
(``lax.ppermute``), and per-device memory is O(V/n) for the inputs
and O(V²/n) for the output.

Three compute primitives, one decomposition family:

- :func:`summa_gram` / :func:`summa_matmul` — the fused ring program
  (the :mod:`brainiak_tpu.ops.ring` pattern generalized): both
  operands column-sharded over one or more mesh axes (a 2-D
  ``('subject', 'voxel')`` mesh flattens into one ring, so the whole
  pod participates), output row-sharded, one ``lax.scan`` of
  matmul+ppermute steps.
- :func:`panel_gram` — the checkpointable variant: row panels are
  driven from the host through
  :func:`~brainiak_tpu.resilience.guards.run_resilient_loop`, so a
  preemption mid-Gram resumes at the last completed panel instead of
  recomputing hours of matmul.
- :func:`block_gram` — the FCMA contraction: a small replicated voxel
  block against the voxel-sharded "all voxels" operand, partial Grams
  reduced with one ``psum`` — the SUMMA inner reduction, used when
  replicating the full data exceeds :func:`replicated_budget_bytes`.

Plus the sharded batched small-matrix helpers SRM-family E-steps need
(https://arxiv.org/pdf/1608.04647): :func:`batched_eigh` and
:func:`batched_cholesky_solve` lay the per-subject solves out along
the mesh's subject axis via ``shard_map`` (:func:`shard_vmap`)
instead of relying on GSPMD to partition a ``vmap``-ed
decomposition.

Telemetry: every program builder is a
:func:`~brainiak_tpu.obs.runtime.counted_cache` under a ``distla.*``
site and its program is wrapped by
:func:`~brainiak_tpu.obs.profile.profile_program`, so retrace counts,
cost records (FLOPs/bytes), and span durations join in ``obs
report`` for achieved-FLOP/s per primitive.
"""

import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..obs import profile as obs_profile
from ..obs import runtime as obs_runtime
from ..obs import spans as obs_spans
from ..parallel.compat import shard_map
from ..parallel.mesh import (DEFAULT_SUBJECT_AXIS, DEFAULT_VOXEL_AXIS,
                             place_on_mesh)
from .correlation import resolve_precision

logger = logging.getLogger(__name__)

__all__ = [
    "BUDGET_ENV",
    "DEFAULT_REPLICATED_BUDGET",
    "batched_cholesky_solve",
    "batched_eigh",
    "block_gram",
    "gram",
    "panel_gram",
    "replicated_budget_bytes",
    "selfcheck",
    "shard_vmap",
    "summa_gram",
    "summa_matmul",
]

#: Env override for the per-device replicated-operand budget.
BUDGET_ENV = "BRAINIAK_TPU_DISTLA_BUDGET_BYTES"

#: Default per-device budget for REPLICATING an operand (bytes).
#: Half a v5e chip's 16 GiB HBM: beyond this, callers should shard
#: the operand and pay collectives instead of replication.
DEFAULT_REPLICATED_BUDGET = 8 << 30


def replicated_budget_bytes():
    """The per-device byte budget above which an operand should be
    sharded rather than replicated (``BRAINIAK_TPU_DISTLA_BUDGET_BYTES``
    overrides the 8 GiB default)."""
    env = os.environ.get(BUDGET_ENV)
    if env:
        try:
            return int(float(env))
        except ValueError:
            logger.warning("ignoring unparseable %s=%r", BUDGET_ENV, env)
    return DEFAULT_REPLICATED_BUDGET


def _zscore_cols(data):
    """Column z-score + 1/sqrt(T), zero for constant columns (matching
    compute_correlation) and NaN for NaN-containing columns (so missing
    data propagates instead of fabricating finite correlations), making
    a plain dot of two normalized columns their Pearson r.  Zero-pad
    columns come out zero (std 0), so padded Grams carry exact zeros in
    the pad rows/columns."""
    t = data.shape[0]
    mean = data.mean(axis=0, keepdims=True)
    std = data.std(axis=0, keepdims=True)
    safe_std = jnp.where(std > 0, std, 1.0)
    z = jnp.where(std > 0, (data - mean) / (safe_std * np.sqrt(t)), 0.0)
    return jnp.where(jnp.isnan(std), jnp.nan, z)


def _ring_axes(mesh, axis_names):
    """Normalize the SUMMA ring axes: ``None`` means every axis of the
    mesh (a 2-D ``('subject', 'voxel')`` mesh becomes one flattened
    ring over the full device grid).  Returns (names tuple, the
    ppermute axis argument, ring size)."""
    names = tuple(mesh.axis_names) if axis_names is None \
        else tuple(axis_names)
    missing = [a for a in names if a not in mesh.shape]
    if not names or missing:
        raise ValueError(
            f"ring axes {names} not all present in mesh axes "
            f"{tuple(mesh.axis_names)}")
    size = int(np.prod([mesh.shape[a] for a in names]))
    axis = names if len(names) > 1 else names[0]
    return names, axis, size


@obs_runtime.counted_cache("distla.summa")
def _summa_program(mesh, axis_names, precision, ring_step="fused"):
    """Build (once per mesh/axes/precision/step-mode) the SUMMA ring
    program: both operands column-sharded over the flattened ring,
    panels rotated with nearest-neighbor ``ppermute``, output
    row-sharded.  Cache misses count as
    ``retrace_total{site=distla.summa}``; under cost profiling the
    program's first run captures a ``cost`` record joined to
    ``distla.gram`` span durations by the report CLI.

    ``ring_step`` selects the per-rotation implementation (see
    :mod:`brainiak_tpu.ops.kernels.ring`): ``"fused"`` /
    ``"pallas"`` land each panel product directly in its final
    output slice on the scan-carried buffer (one HBM write per
    element of C); ``"unfused"`` is the original three-stage
    stack → transpose → scatter formulation, kept as the measured
    reference for the ``kernels`` bench tier and parity tests.
    """
    from .kernels import ring as kring

    names, axis, n_shards = _ring_axes(mesh, axis_names)
    prec = resolve_precision(precision)

    def summa_fn(z_local, zb_local):
        # z_local stays resident; zb panels visit around the ring
        my_idx = jax.lax.axis_index(axis)
        block_cols = zb_local.shape[1]
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

        if ring_step == "unfused":
            def step(rotating, _):
                # output block: rows (resident cols) x cols (the
                # panel currently held)
                block = jax.lax.dot_general(
                    z_local, rotating, (((0,), (0,)), ((), ())),
                    precision=prec,
                    preferred_element_type=z_local.dtype)
                # hand the visiting panel to the next device
                rotating = jax.lax.ppermute(rotating, axis, perm)
                return rotating, block

            _, blocks = jax.lax.scan(step, zb_local, None,
                                     length=n_shards)
            # blocks[s] holds out[local, owner] where the owner of
            # the panel seen at step s is (my_idx - s) mod n_shards
            owners = (my_idx - jnp.arange(n_shards)) % n_shards
            out = jnp.zeros((z_local.shape[1], n_shards, block_cols),
                            dtype=z_local.dtype)
            out = out.at[:, owners, :].set(
                jnp.transpose(blocks, (1, 0, 2)))
            return out.reshape(z_local.shape[1],
                               n_shards * block_cols)

        def fused_step(carry, s):
            rotating, out = carry
            owner = (my_idx - s) % n_shards
            if ring_step == "pallas":
                out = kring.ring_mma(out, z_local, rotating, owner,
                                     n_shards=n_shards,
                                     precision=prec)
            else:
                out = kring.mma_update(out, z_local, rotating,
                                       owner * block_cols, prec)
            rotating = jax.lax.ppermute(rotating, axis, perm)
            return (rotating, out), None

        out0 = jnp.zeros((z_local.shape[1], n_shards * block_cols),
                         dtype=z_local.dtype)
        (_, out), _ = jax.lax.scan(
            fused_step, (zb_local, out0),
            jnp.arange(n_shards, dtype=jnp.int32))
        return out

    spec = PartitionSpec(None, axis)
    return obs_profile.profile_program(jax.jit(shard_map(
        summa_fn, mesh, in_specs=(spec, spec),
        out_specs=PartitionSpec(axis, None))),
        "distla.summa", span="distla.gram")


@obs_runtime.trace_signature("distla.summa")
def _summa_trace_signature():
    from ..parallel.mesh import make_mesh

    mesh = make_mesh((DEFAULT_SUBJECT_AXIS, DEFAULT_VOXEL_AXIS),
                     (2, -1))
    names = (DEFAULT_SUBJECT_AXIS, DEFAULT_VOXEL_AXIS)
    ring = int(np.prod([mesh.shape[a] for a in names]))
    t, v = 3, 2 * ring
    args = (jax.ShapeDtypeStruct((t, v), jnp.float32),
            jax.ShapeDtypeStruct((t, v), jnp.float32))
    prec = resolve_precision(None)
    return [{"key": (mesh, names, prec, step), "args": args,
             "mesh": mesh, "label": f"ring_step={step}"}
            for step in ("fused", "unfused")]


def _ring_step_for(n_trs, padded_v, n_shards, ring_step=None):
    """The ring-step mode for one problem extent: the caller's
    explicit choice (validated — a typo must not silently run a
    different kernel AND mint a spurious builder-cache key), else
    :func:`ops.kernels.ring.ring_step_mode` (Pallas on TPU when the
    per-device tiles fit, jit-fused XLA everywhere else)."""
    from .kernels import ring as kring

    if ring_step is not None:
        if ring_step not in kring._MODES:
            raise ValueError(
                f"ring_step must be one of {kring._MODES}; got "
                f"{ring_step!r}")
        return ring_step
    local = padded_v // n_shards
    return kring.ring_step_mode(n_trs, local, local)


def _pad_cols(arr, multiple):
    """Zero-pad the last axis of a host array up to ``multiple``."""
    pad = (-arr.shape[-1]) % multiple
    if not pad:
        return np.asarray(arr), 0
    widths = [(0, 0)] * arr.ndim
    widths[-1] = (0, pad)
    return np.pad(np.asarray(arr), widths), pad


def summa_matmul(a, mesh, b=None, axis_names=None, precision=None,
                 ring_step=None):
    """``C = aᵀ @ b`` with both operands column-sharded around the
    mesh ring — the raw SUMMA primitive.

    a, b : [T, V] arrays (``b`` defaults to ``a``); the voxel axis is
        zero-padded up to the ring size, so uneven panel splits are
        handled (pad rows/cols of C are exact zeros and are sliced
        off).
    mesh : :class:`jax.sharding.Mesh`; ``axis_names`` selects the
        ring axes (default: ALL mesh axes, flattened row-major — on
        the standard ``('subject', 'voxel')`` mesh the whole device
        grid forms one ring).
    ring_step : per-rotation implementation override
        (``"pallas"``/``"fused"``/``"unfused"``; default: auto —
        see :func:`_ring_step_for`).
    Returns C [V, V] (row-sharded over the ring when V divides it).
    """
    names, _, n_shards = _ring_axes(mesh, axis_names)
    v = a.shape[1]
    if b is not None and b.shape != a.shape:
        raise ValueError(
            f"operand shapes differ: {a.shape} vs {b.shape}")
    a_p, pad = _pad_cols(a, n_shards)
    spec = NamedSharding(
        mesh, PartitionSpec(None, names if len(names) > 1 else names[0]))
    za = place_on_mesh(a_p, spec)
    zb = za if b is None else place_on_mesh(_pad_cols(b, n_shards)[0],
                                            spec)
    mode = _ring_step_for(a.shape[0], a_p.shape[1], n_shards,
                          ring_step)
    out = _summa_program(mesh, names, resolve_precision(precision),
                         ring_step=mode)(za, zb)
    return out[:v, :v] if pad else out


def summa_gram(data, mesh, data_b=None, axis_names=None,
               precision=None, normalize=True, ring_step=None):
    """All-pairs Pearson correlation of the columns of ``data``
    (against ``data_b`` when given) computed as a SUMMA ring over the
    mesh — O(V/n) per-device input memory, O(V²/n) output, only
    nearest-neighbor traffic.

    Column z-scoring runs shard-local after placement (the full
    [T, V] array is never resident on one device); NaN columns
    propagate NaN rows/columns (see :func:`_zscore_cols`).  With
    ``normalize=False`` the z-scoring is skipped and the result is
    the raw product ``dataᵀ @ data_b`` — the encoding tier's
    ``Xᵀ X`` path (zero pad columns still contribute exact zeros,
    so uneven splits stay exact).  For data small enough to
    replicate, prefer :func:`gram` which dispatches on the budget.
    ``ring_step`` overrides the per-rotation implementation
    (``"pallas"``/``"fused"``/``"unfused"``; default auto — the
    fused rotate-multiply-accumulate step, see
    :mod:`brainiak_tpu.ops.kernels.ring`).
    """
    names, _, n_shards = _ring_axes(mesh, axis_names)
    v = data.shape[1]
    if data_b is not None and data_b.shape != data.shape:
        raise ValueError(
            f"data_b shape {data_b.shape} != data shape {data.shape}")
    norm = _zscore_cols if normalize else (lambda z: z)
    with obs_spans.span("distla.gram",
                        attrs={"n_voxels": int(v),
                               "n_shards": int(n_shards),
                               "kind": "summa"}):
        spec = NamedSharding(
            mesh,
            PartitionSpec(None, names if len(names) > 1 else names[0]))
        # shard FIRST, z-score after: z-scoring is columnwise, so it
        # runs shard-local and the full array never lands on one chip
        padded = _pad_cols(data, n_shards)[0]
        z = norm(place_on_mesh(padded, spec))
        z_b = z if data_b is None else norm(
            place_on_mesh(_pad_cols(data_b, n_shards)[0], spec))
        mode = _ring_step_for(data.shape[0], padded.shape[1],
                              n_shards, ring_step)
        out = _summa_program(mesh, names, resolve_precision(precision),
                             ring_step=mode)(z, z_b)
    return out[:v, :v] if v % n_shards else out


def gram(data, mesh=None, data_b=None, axis_names=None, precision=None,
         budget_bytes=None, force=None, normalize=True):
    """Pearson Gram with budget-based dispatch.

    Small problems run the replicated einsum (no collectives); when
    the replicated working set — the [T, V] operands plus the [V, V]
    output on every device — exceeds ``budget_bytes`` (default
    :func:`replicated_budget_bytes`) and a mesh is available, the
    SUMMA ring computes the same result with O(1/n) per-device
    memory.  ``force='replicated'`` raises instead of silently
    exceeding the budget; ``force='summa'`` always takes the ring.
    ``normalize=False`` skips the column z-scoring on either path and
    returns the raw ``dataᵀ @ data_b`` product — how the encoding
    tier gets its ``Xᵀ X`` through the same dispatcher.
    """
    if force not in (None, "replicated", "summa"):
        raise ValueError(
            f"force must be None, 'replicated' or 'summa'; got "
            f"{force!r}")
    # one contract on every branch: without this, a mismatched
    # cross-Gram would silently matmul on the replicated path and
    # start raising only once the data grew past the budget
    if data_b is not None and data_b.shape != data.shape:
        raise ValueError(
            f"data_b shape {data_b.shape} != data shape {data.shape}")
    v = data.shape[1]
    # .dtype, never np.asarray: a device-resident operand must not be
    # gathered to host just to read its itemsize on the very dispatch
    # path that exists to avoid oversized transfers
    dtype = getattr(data, "dtype", None)
    itemsize = np.dtype(dtype).itemsize if dtype is not None \
        else np.asarray(data).dtype.itemsize
    need = (2 if data_b is not None else 1) * data.size * itemsize \
        + v * v * itemsize
    budget = replicated_budget_bytes() if budget_bytes is None \
        else int(budget_bytes)
    over = need > budget
    if force == "replicated":
        if over:
            raise ValueError(
                f"replicated Gram needs ~{need} bytes per device, "
                f"over the {budget}-byte budget; use the SUMMA path "
                "(pass a mesh) or raise the budget")
        use_summa = False
    else:
        use_summa = force == "summa" or (over and mesh is not None)
    if use_summa:
        if mesh is None:
            raise ValueError("the SUMMA path needs a mesh")
        return summa_gram(data, mesh, data_b=data_b,
                          axis_names=axis_names, precision=precision,
                          normalize=normalize)
    if over:
        logger.warning(
            "replicated Gram working set (~%d bytes) exceeds the "
            "%d-byte budget and no mesh was given; computing "
            "replicated anyway", need, budget)
    norm = _zscore_cols if normalize else (lambda z: z)
    with obs_spans.span("distla.gram",
                        attrs={"n_voxels": int(v), "n_shards": 1,
                               "kind": "replicated"}):
        z = norm(jnp.asarray(data))
        z_b = z if data_b is None else norm(jnp.asarray(data_b))
        return jnp.matmul(z.T, z_b,
                          precision=resolve_precision(precision),
                          preferred_element_type=z.dtype)


# -- checkpointable panel Gram ---------------------------------------

@obs_runtime.counted_cache("distla.panel")
def _panel_program(mesh, axis_name, precision):
    """Row-panel product, cached per (mesh, axis, precision): a small
    replicated z-scored panel against the column-sharded operand,
    output gathered replicated (one all-gather of [panel, V/n]
    partials).  Cache misses count as
    ``retrace_total{site=distla.panel}``."""
    prec = resolve_precision(precision)
    return obs_profile.profile_program(jax.jit(
        lambda zp, z: jnp.einsum('tp,tv->pv', zp, z, precision=prec,
                                 preferred_element_type=zp.dtype),
        out_shardings=NamedSharding(mesh, PartitionSpec())),
        "distla.panel", span="distla.panel_chunk")


@obs_runtime.trace_signature("distla.panel")
def _panel_trace_signature():
    from ..parallel.mesh import make_mesh

    mesh = make_mesh((DEFAULT_VOXEL_AXIS,), (-1,))
    t, p, v = 4, 2, 2 * mesh.shape[DEFAULT_VOXEL_AXIS]
    return [{"key": (mesh, DEFAULT_VOXEL_AXIS,
                     resolve_precision(None)),
             "args": (jax.ShapeDtypeStruct((t, p), jnp.float32),
                      jax.ShapeDtypeStruct((t, v), jnp.float32)),
             "mesh": mesh}]


def panel_gram(data, mesh, data_b=None, axis_name=DEFAULT_VOXEL_AXIS,
               panel_size=None, checkpoint_dir=None,
               checkpoint_every=1, precision=None,
               name="distla.panel_gram"):
    """Pearson Gram computed panel-by-panel under the resilient-loop
    driver — the checkpointable SUMMA variant.

    The column-sharded operand stays device-resident for the whole
    loop; each step z-scores one host row panel, multiplies it
    against the sharded operand, and lands the finished [panel, V]
    rows in host state.  With ``checkpoint_dir`` the accumulated rows
    are persisted every ``checkpoint_every`` panels and a preempted
    run resumes at the last completed panel (the mid-Gram resume the
    fused ring cannot offer).  Returns the full [V, V] host array.

    panel_size : rows per step (default: one shard width,
        ``V_padded / n_shards``).
    """
    from ..resilience.guards import array_digest, run_resilient_loop

    n_shards = mesh.shape[axis_name]
    data = np.asarray(data)
    data_b = data if data_b is None else np.asarray(data_b)
    if data_b.shape != data.shape:
        raise ValueError(
            f"data_b shape {data_b.shape} != data shape {data.shape}")
    t, v = data.shape
    padded, _ = _pad_cols(data_b, n_shards)
    if panel_size is None:
        panel_size = max(1, padded.shape[1] // n_shards)
    n_panels = -(-v // panel_size)
    dtype = data.dtype if data.dtype.kind == "f" else np.float32

    z_b = _zscore_cols(place_on_mesh(
        padded, NamedSharding(mesh, PartitionSpec(None, axis_name))))
    program = _panel_program(mesh, axis_name,
                             resolve_precision(precision))

    fingerprint = None
    if checkpoint_dir is not None:
        # data_b participates: a resume against the same data but a
        # different cross-correlation target must restart, not mix
        # rows of corr(data, X) with rows of corr(data, Y)
        fingerprint = np.array(
            [array_digest(data), array_digest(data_b), float(t),
             float(v), float(panel_size), float(n_shards)])

    def run_chunk(state, step, n_steps):
        # copy-on-write: run_resilient_loop keeps the previous state
        # as the rollback target, so the accumulator must not be
        # mutated in place.  Host syncs are the POINT of this loop
        # (finished rows must land in host state to be
        # checkpointable); the fused ring (summa_gram) is the
        # no-sync path.
        out = np.array(state["out"], copy=True)  # jaxlint: disable=JX002
        for p in range(step, step + n_steps):
            start = p * panel_size
            stop = min(start + panel_size, v)
            panel = np.zeros((t, panel_size), dtype=dtype)
            panel[:, :stop - start] = data[:, start:stop]
            with obs_spans.span("distla.panel_chunk",
                                attrs={"panel": p,
                                       "rows": stop - start}):
                rows = np.asarray(  # jaxlint: disable=JX002
                    program(_zscore_cols(jnp.asarray(panel)), z_b))
            out[start:stop, :] = rows[:stop - start, :v]
        return {"out": out}, False

    # guard_skip: NaN rows are the documented propagation semantics
    # for NaN voxels, not divergence — the driver is used here for
    # checkpoint/resume, not the non-finite guard
    state, _ = run_resilient_loop(
        run_chunk, {"out": np.zeros((v, v), dtype=dtype)}, n_panels,
        checkpoint_dir=checkpoint_dir,
        checkpoint_every=checkpoint_every,
        fingerprint=fingerprint,
        template={"out": np.zeros((v, v), dtype=dtype)},
        name=name, guard_skip=("out",))
    return state["out"]


# -- FCMA block x all-voxel contraction ------------------------------

@obs_runtime.counted_cache("distla.block_gram")
def _block_gram_program(mesh, axis_name, epochs_per_subj, precision):
    """FCMA per-voxel Gram with the "all voxels" operand SHARDED over
    the mesh's voxel axis (the replicated-data-budget escape hatch):
    each device correlates the small replicated block against its
    resident voxel shard, normalizes locally (Fisher-z within-subject
    normalization is voxel-local), accumulates a partial Gram, and
    one ``psum`` completes the contraction — SUMMA's inner reduction.
    Cache misses count as ``retrace_total{site=distla.block_gram}``.
    """
    from .fisherz import within_subject_normalization

    prec = resolve_precision(precision)

    def fn(blk, data2_local):
        corr = jnp.einsum('etb,etv->bev', blk, data2_local,
                          precision=prec,
                          preferred_element_type=jnp.float32)
        corr = within_subject_normalization(corr, epochs_per_subj)
        part = jnp.einsum('bev,bfv->bef', corr, corr, precision=prec,
                          preferred_element_type=jnp.float32)
        return jax.lax.psum(part, axis_name)

    return obs_profile.profile_program(jax.jit(shard_map(
        fn, mesh,
        in_specs=(PartitionSpec(),
                  PartitionSpec(None, None, axis_name)),
        out_specs=PartitionSpec())),
        "distla.block_gram", span="fcma.block")


@obs_runtime.trace_signature("distla.block_gram")
def _block_gram_trace_signature():
    from ..parallel.mesh import make_mesh

    mesh = make_mesh((DEFAULT_VOXEL_AXIS,), (-1,))
    e, t, b = 4, 5, 3
    v = 2 * mesh.shape[DEFAULT_VOXEL_AXIS]
    return [{"key": (mesh, DEFAULT_VOXEL_AXIS, 2,
                     resolve_precision(None)),
             "args": (jax.ShapeDtypeStruct((e, t, b), jnp.float32),
                      jax.ShapeDtypeStruct((e, t, v), jnp.float32)),
             "mesh": mesh}]


def block_gram(blk, data2, mesh, epochs_per_subj,
               axis_name=DEFAULT_VOXEL_AXIS, precision=None):
    """Per-voxel SVM Gram of a replicated voxel block against
    voxel-sharded epoch data (see :func:`_block_gram_program`).

    blk : [E, T, B] replicated block; data2 : [E, T, V] sharded over
    ``axis_name`` (V padded to the axis size; zero pad columns
    normalize to zero and contribute nothing to the Gram).  Returns
    kernels [B, E, E] replicated (unshrunk — FCMA's magnitude shrink
    is applied by the caller).
    """
    return _block_gram_program(mesh, axis_name, int(epochs_per_subj),
                               resolve_precision(precision))(blk, data2)


# -- sharded batched small-matrix solves -----------------------------

def shard_vmap(fn, mesh, axis_name, n_batch):
    """``vmap(fn)`` with the leading batch axis laid out along the
    mesh's ``axis_name`` via ``shard_map`` (each device runs the vmap
    over its resident batch slice), falling back to a plain ``vmap``
    when there is no mesh, the axis is absent or trivial, or the
    batch does not divide it.  Composable inside jitted programs
    (SRM's EM chunks call it per W-update)."""
    mapped = jax.vmap(fn)
    if mesh is None or axis_name not in getattr(mesh, "shape", {}) \
            or mesh.shape[axis_name] <= 1 \
            or n_batch % mesh.shape[axis_name]:
        return mapped
    return shard_map(mapped, mesh,
                     in_specs=PartitionSpec(axis_name),
                     out_specs=PartitionSpec(axis_name))


def batched_eigh(mats, mesh=None, axis_name=DEFAULT_SUBJECT_AXIS):
    """Eigendecomposition of a batch of symmetric matrices [S, K, K],
    the batch sharded over the mesh's subject axis when possible —
    the per-subject solve layout SRM's E-step W updates run on
    (batched small eigh under plain GSPMD lowers to long sequential
    loops on some backends).  Returns ``(eigenvalues [S, K],
    eigenvectors [S, K, K])``."""
    return shard_vmap(jnp.linalg.eigh, mesh, axis_name,
                      mats.shape[0])(mats)


def batched_cholesky_solve(mats, rhs, mesh=None,
                           axis_name=DEFAULT_SUBJECT_AXIS):
    """Solve ``mats[i] @ x[i] = rhs[i]`` for a batch of SPD matrices
    [S, K, K] against [S, K, M] right-hand sides via per-subject
    Cholesky, sharded over the mesh's subject axis when possible —
    the per-subject covariance-solve layout for subject-parallel
    estimators."""
    def solve(a, b):
        return jax.scipy.linalg.cho_solve(
            jax.scipy.linalg.cho_factor(a), b)

    return shard_vmap(solve, mesh, axis_name, mats.shape[0])(mats, rhs)


# -- CI selfcheck (tools/run_checks.py `distla` gate) ----------------

def selfcheck(out=None):
    """Smoke the layer on a tiny fixture for the ``distla`` CI gate
    (DLA001): SUMMA parity against a NumPy reference, sharded batched
    solves parity, and retrace stability (repeat calls must not
    rebuild programs — every ``distla.*`` site stays at one trace).
    Prints a JSON verdict; returns 0 on pass, 1 on failure."""
    import json
    import sys

    from ..obs import metrics as obs_metrics
    from ..parallel.mesh import make_mesh, max_divisible_shards

    stream = out or sys.stdout
    rng = np.random.RandomState(0)
    t, v = 16, 64
    data = rng.randn(t, v).astype(np.float32)
    z = (data - data.mean(0)) / (data.std(0) * np.sqrt(t))
    dense = z.T @ z

    n = max_divisible_shards(v)
    mesh = make_mesh((DEFAULT_VOXEL_AXIS,), (n,))
    errs = []
    for _ in range(2):  # second call must hit every program cache
        got = np.asarray(summa_gram(data, mesh))
        errs.append(float(np.max(np.abs(got - dense))))
        got_u = np.asarray(summa_gram(data[:, :v - n + 1], mesh))
        errs.append(float(np.max(np.abs(
            got_u - dense[:v - n + 1, :v - n + 1]))))
        errs.append(float(np.max(np.abs(
            panel_gram(data, mesh) - dense))))

    s, k = 8, 5
    base = rng.randn(s, k, k)
    spd = base @ np.transpose(base, (0, 2, 1)) + 3 * np.eye(k)
    rhs = rng.randn(s, k, 2)
    smesh = make_mesh((DEFAULT_SUBJECT_AXIS,),
                      (max_divisible_shards(s),))
    solved = np.asarray(batched_cholesky_solve(
        jnp.asarray(spd), jnp.asarray(rhs), mesh=smesh))
    errs.append(float(np.max(np.abs(
        solved - np.linalg.solve(spd, rhs)))))
    w, q = batched_eigh(jnp.asarray(spd), mesh=smesh)
    recon = np.asarray(
        jnp.einsum('sik,sk,sjk->sij', q, w, q))
    errs.append(float(np.max(np.abs(recon - spd))))

    retrace = obs_metrics.counter("retrace_total")
    sites = {site: retrace.value(site=site)
             for site in ("distla.summa", "distla.panel",
                          "distla.block_gram")
             if retrace.value(site=site)}
    tol = 5e-4
    ok = max(errs) < tol and all(c <= 1.0 for c in sites.values()) \
        and {"distla.summa", "distla.panel"} <= set(sites)
    json.dump({"ok": bool(ok), "max_err": max(errs), "tol": tol,
               "n_shards": int(n), "retraces": sites}, stream)
    stream.write("\n")
    return 0 if ok else 1
