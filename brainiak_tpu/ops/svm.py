"""Batched kernel SVM (C-SVC, precomputed kernel) in pure JAX.

TPU-native replacement for FCMA's per-voxel ``sklearn.svm.SVC`` cross
validation (reference fcma/voxelselector.py:41-53, :423-465): instead of a
multiprocessing pool running thousands of tiny independent SVC fits, the
dual problems for ALL voxels and ALL folds are solved simultaneously as one
vmapped projected-gradient program on the MXU.

The dual of C-SVC:  max_a  1ᵀa - ½ aᵀQa,  0 <= a_i <= C,  Q = yyᵀ∘K.
Cyclic dual coordinate descent (the liblinear update) solves each problem
exactly for the small epoch counts FCMA uses (tens of samples); fold
exclusion is expressed by zeroing each test sample's box constraint, which
keeps every (voxel, fold) problem the same static shape.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["svm_cv_accuracy", "svm_fit_dual", "svm_decision"]


@partial(jax.jit, static_argnames=("n_iters",))
def svm_fit_dual(kernel, y, box, n_iters=400):
    """Solve the C-SVC dual exactly by cyclic dual coordinate descent
    (the liblinear/SMO-style update, which converges to the optimum for
    PSD kernels).

    kernel : [n, n] symmetric PSD Gram matrix
    y : [n] labels in {-1, +1}
    box : [n] per-sample upper bounds (C, or 0 to exclude a sample)
    n_iters : number of full sweeps over the coordinates
    Returns (alpha [n], bias).
    """
    y = y.astype(kernel.dtype)
    box = box.astype(kernel.dtype)
    n = kernel.shape[0]
    q = (y[:, None] * y[None, :]) * kernel
    diag = jnp.clip(jnp.diag(q), 1e-12, None)

    def body(k, carry):
        alpha, qalpha = carry
        i = k % n
        grad = 1.0 - qalpha[i]
        new = jnp.clip(alpha[i] + grad / diag[i], 0.0, box[i])
        delta = new - alpha[i]
        alpha = alpha.at[i].set(new)
        qalpha = qalpha + q[:, i] * delta
        return alpha, qalpha

    zeros = jnp.zeros((n,), dtype=kernel.dtype)
    alpha, _ = jax.lax.fori_loop(0, n_iters * n, body, (zeros, zeros))

    # Bias from free support vectors (0 < alpha < C); fall back to all
    # bounded SVs when none are free.
    f = kernel @ (alpha * y)
    free = (alpha > 1e-8 * box) & (alpha < box * (1 - 1e-6)) & (box > 0)
    any_free = jnp.sum(free) > 0
    sv = (alpha > 1e-8) & (box > 0)
    sel = jnp.where(any_free, free, sv)
    denom = jnp.clip(jnp.sum(sel), 1, None)
    bias = jnp.sum(jnp.where(sel, y - f, 0.0)) / denom
    return alpha, bias


def svm_decision(train_test_kernel, alpha, y, bias):
    """Decision values for test samples: K_test,train @ (alpha*y) + b."""
    return train_test_kernel @ (alpha * y) + bias


@partial(jax.jit, static_argnames=("n_iters", "n_classes"))
def _cv_one_voxel(kernel, pair_y, pair_classes, truth, train_masks,
                  c, n_iters, n_classes):
    """Mean one-vs-one CV accuracy of one voxel's kernel over all folds.

    kernel : [n, n]
    pair_y : [P, n] ±1 labels per class pair (0 for samples outside it)
    pair_classes : [P, 2] int (positive-side class, negative-side class)
    truth : [n] int class indices
    train_masks : [F, n] (1 = train)

    Each of the P·F binary SVMs trains only on its pair's training
    samples (the box constraint is zero elsewhere); test samples collect
    one-vs-one votes and the predicted class is the vote argmax
    (sklearn SVC's multiclass scheme; see svm_cv_accuracy's note on
    tie-breaking).
    """
    def one_fold(train_mask):
        train_mask = train_mask.astype(kernel.dtype)

        def one_pair(y_p, classes_p):
            # |y_p| is the pair membership mask
            box = c * train_mask * jnp.abs(y_p)
            alpha, bias = svm_fit_dual(kernel, y_p, box,
                                       n_iters=n_iters)
            dec = svm_decision(kernel, alpha, y_p, bias)
            vote_class = jnp.where(dec >= 0, classes_p[0], classes_p[1])
            return jax.nn.one_hot(vote_class, n_classes)

        votes = jnp.sum(jax.vmap(one_pair)(pair_y, pair_classes), axis=0)
        pred = jnp.argmax(votes, axis=1)
        test_mask = 1.0 - train_mask
        correct = jnp.sum((pred == truth) * test_mask)
        return correct / jnp.clip(jnp.sum(test_mask), 1, None)

    return jnp.mean(jax.vmap(one_fold)(train_masks))


@partial(jax.jit, static_argnames=("n_iters", "n_classes"))
def _cv_batch(kernels, pair_y, pair_classes, truth, train_masks, c,
              n_iters, n_classes):
    return jax.vmap(lambda k: _cv_one_voxel(
        k, pair_y, pair_classes, truth, train_masks, c, n_iters,
        n_classes))(kernels)


def svm_cv_accuracy(kernels, labels, num_folds, C=1.0, n_iters=50):
    """Stratified k-fold CV accuracy for a batch of precomputed kernels.

    kernels : [B, n, n] per-voxel Gram matrices
    labels : [n] condition labels (two or more classes; multiclass uses
        one-vs-one voting like sklearn SVC)
    Returns [B] mean fold accuracies, matching
    ``cross_val_score(SVC(kernel='precomputed'), ...)`` semantics
    (StratifiedKFold without shuffling, unweighted fold mean).  For more
    than two classes, vote TIE-BREAKING differs from libsvm (argmax picks
    the lowest class index; libsvm uses training order and a strict
    dec > 0), so multiclass accuracies agree within the reference's
    per-epoch tolerance rather than exactly.
    """
    from itertools import combinations

    from sklearn.model_selection import StratifiedKFold

    labels = np.asarray(labels)
    classes = np.unique(labels)
    if len(classes) < 2:
        raise ValueError("Need at least two classes; got "
                         f"{len(classes)}")
    n = len(labels)
    class_idx = np.searchsorted(classes, labels)

    pair_y, pair_classes = [], []
    for a, b in combinations(range(len(classes)), 2):
        y = np.zeros(n)
        y[class_idx == a] = 1.0
        y[class_idx == b] = -1.0
        pair_y.append(y)
        pair_classes.append([a, b])

    skf = StratifiedKFold(n_splits=num_folds, shuffle=False)
    train_masks = np.zeros((num_folds, n))
    for f, (train_idx, _) in enumerate(skf.split(np.zeros(n), labels)):
        train_masks[f, train_idx] = 1.0

    out = _cv_batch(jnp.asarray(kernels), jnp.asarray(np.stack(pair_y)),
                    jnp.asarray(np.asarray(pair_classes)),
                    jnp.asarray(class_idx),
                    jnp.asarray(train_masks), float(C), int(n_iters),
                    len(classes))
    return np.asarray(out)
