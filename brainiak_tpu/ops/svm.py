"""Batched kernel SVM (C-SVC, precomputed kernel) in pure JAX.

TPU-native replacement for FCMA's per-voxel ``sklearn.svm.SVC`` cross
validation (reference fcma/voxelselector.py:41-53, :423-465): instead of a
multiprocessing pool running thousands of tiny independent SVC fits, the
dual problems for ALL voxels and ALL folds are solved simultaneously as one
vmapped projected-gradient program on the MXU.

The dual of C-SVC:  max_a  1ᵀa - ½ aᵀQa,  0 <= a_i <= C,  yᵀa = 0,
Q = yyᵀ∘K.  The equality constraint (from the bias term) means plain
coordinate descent solves the WRONG problem (the bias-free liblinear
dual); each problem is instead solved by SMO with maximal-violating-pair
working-set selection — libsvm's algorithm — expressed as a fixed-length
``fori_loop`` of two-coordinate updates with argmax/argmin selection, so
all (voxel, fold, pair) problems run as one vmapped program.  Fold and
class-pair exclusion are expressed by zeroing each excluded sample's box
constraint, which keeps every problem the same static shape.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["svm_cv_accuracy", "svm_fit_dual", "svm_decision"]


@partial(jax.jit, static_argnames=("n_iters",))
def svm_fit_dual(kernel, y, box, n_iters=400):
    """Solve the C-SVC dual (WITH the yᵀa = 0 equality constraint) by SMO
    with maximal-violating-pair working-set selection — the libsvm
    algorithm, so solutions match ``sklearn.svm.SVC`` to optimizer
    tolerance.

    kernel : [n, n] symmetric PSD Gram matrix
    y : [n] labels in {-1, +1} (0 allowed for excluded samples)
    box : [n] per-sample upper bounds (C, or 0 to exclude a sample)
    n_iters : SMO step budget is n_iters * n two-coordinate updates
        (converged problems keep selecting a non-violating pair, whose
        update is a no-op, so overshooting is safe)
    Returns (alpha [n], bias, gap) — ``gap`` is the final KKT violation
    (libsvm's stopping quantity; ~0 when the dual converged within the
    step budget).
    """
    y = y.astype(kernel.dtype)
    box = box.astype(kernel.dtype)
    n = kernel.shape[0]
    q = (y[:, None] * y[None, :]) * kernel
    active = box > 0
    inf = jnp.asarray(jnp.inf, dtype=kernel.dtype)

    def body(_, carry):
        # Gather/scatter-free SMO step: every indexed read (q rows,
        # yg[i], box[i], ...) is expressed as a one-hot contraction and
        # the alpha update as a dense axpy.  Batched dynamic gathers /
        # scatter-adds under the (voxel, fold, pair) vmap lower to
        # serialized scatter ops on TPU — measured ~8 ms per SMO step at
        # a 32k-problem batch vs microseconds for the dense form (n is
        # at most a few dozen epochs, so the dense work is trivial).
        alpha, grad = carry
        # working-set selection on -y*grad over the feasible direction
        # sets: I_up can increase alpha along +y, I_low along -y
        yg = -y * grad
        in_up = active & (((y > 0) & (alpha < box)) |
                          ((y < 0) & (alpha > 0)))
        in_low = active & (((y < 0) & (alpha < box)) |
                           ((y > 0) & (alpha > 0)))
        ei = jax.nn.one_hot(jnp.argmax(jnp.where(in_up, yg, -inf)), n,
                            dtype=kernel.dtype)
        ej = jax.nn.one_hot(jnp.argmin(jnp.where(in_low, yg, inf)), n,
                            dtype=kernel.dtype)
        qi = q @ ei
        qj = q @ ej

        def at_i(v):
            return jnp.sum(v * ei)

        def at_j(v):
            return jnp.sum(v * ej)

        # two-variable subproblem along the constraint-preserving
        # direction: d alpha_i = y_i * t, d alpha_j = -y_j * t
        quad = jnp.clip(at_i(qi) + at_j(qj)
                        - 2.0 * at_i(y) * at_j(y) * at_j(qi),
                        1e-12, None)
        t = (at_i(yg) - at_j(yg)) / quad
        # box clipping for both coordinates
        t_hi_i = jnp.where(at_i(y) > 0, at_i(box) - at_i(alpha),
                           at_i(alpha))
        t_hi_j = jnp.where(at_j(y) > 0, at_j(alpha),
                           at_j(box) - at_j(alpha))
        t = jnp.clip(t, 0.0, jnp.minimum(t_hi_i, t_hi_j))
        # only step when the pair actually violates optimality
        t = jnp.where((at_i(yg) - at_j(yg) > 1e-12)
                      & (at_i(in_up.astype(kernel.dtype)) > 0)
                      & (at_j(in_low.astype(kernel.dtype)) > 0),
                      t, 0.0)
        di = at_i(y) * t
        dj = -at_j(y) * t
        alpha = alpha + di * ei + dj * ej
        grad = grad + qi * di + qj * dj
        return alpha, grad

    zeros = jnp.zeros((n,), dtype=kernel.dtype)
    alpha, grad = jax.lax.fori_loop(0, n_iters * n, body,
                                    (zeros, -jnp.ones_like(zeros)))

    # Bias: average y - f over free SVs; with none free, the midpoint of
    # the remaining violating-pair interval (libsvm's rho rule).
    f = kernel @ (alpha * y)
    free = (alpha > 1e-8 * box) & (alpha < box * (1 - 1e-6)) & active
    any_free = jnp.sum(free) > 0
    yg = -y * grad
    in_up = active & (((y > 0) & (alpha < box)) | ((y < 0) & (alpha > 0)))
    in_low = active & (((y < 0) & (alpha < box)) | ((y > 0) & (alpha > 0)))
    mid = (jnp.max(jnp.where(in_up, yg, -inf)) +
           jnp.min(jnp.where(in_low, yg, inf))) / 2.0
    bias_free = jnp.sum(jnp.where(free, y - f, 0.0)) / \
        jnp.clip(jnp.sum(free), 1, None)
    bias = jnp.where(any_free, bias_free,
                     jnp.where(jnp.isfinite(mid), mid, 0.0))
    # KKT violation gap (libsvm's stopping quantity): 0 when converged.
    # Lets callers detect an under-budgeted fixed-length SMO loop instead
    # of silently returning a degraded dual.
    gap = (jnp.max(jnp.where(in_up, yg, -inf)) -
           jnp.min(jnp.where(in_low, yg, inf)))
    gap = jnp.where(jnp.isfinite(gap), jnp.clip(gap, 0.0, None), 0.0)
    return alpha, bias, gap


def svm_decision(train_test_kernel, alpha, y, bias):
    """Decision values for test samples: K_test,train @ (alpha*y) + b."""
    return train_test_kernel @ (alpha * y) + bias


@partial(jax.jit, static_argnames=("n_iters", "n_classes"))
def _cv_one_voxel(kernel, pair_y, pair_classes, truth, train_masks,
                  c, n_iters, n_classes):
    """Mean one-vs-one CV accuracy of one voxel's kernel over all folds.

    kernel : [n, n]
    pair_y : [P, n] ±1 labels per class pair (0 for samples outside it)
    pair_classes : [P, 2] int (positive-side class, negative-side class)
    truth : [n] int class indices
    train_masks : [F, n] (1 = train)

    Each of the P·F binary SVMs trains only on its pair's training
    samples (the box constraint is zero elsewhere); test samples collect
    one-vs-one votes and the predicted class is the vote argmax
    (sklearn SVC's multiclass scheme; libsvm vote conventions, see
    svm_cv_accuracy).
    """
    def one_fold(train_mask):
        train_mask = train_mask.astype(kernel.dtype)

        def one_pair(y_p, classes_p):
            # |y_p| is the pair membership mask
            box = c * train_mask * jnp.abs(y_p)
            alpha, bias, gap = svm_fit_dual(kernel, y_p, box,
                                            n_iters=n_iters)
            dec = svm_decision(kernel, alpha, y_p, bias)
            # libsvm votes the LATER class of the pair at exactly 0
            vote_class = jnp.where(dec > 0, classes_p[0], classes_p[1])
            return jax.nn.one_hot(vote_class, n_classes), gap

        votes, gaps = jax.vmap(one_pair)(pair_y, pair_classes)
        votes = jnp.sum(votes, axis=0)
        pred = jnp.argmax(votes, axis=1)
        test_mask = 1.0 - train_mask
        correct = jnp.sum((pred == truth) * test_mask)
        acc = correct / jnp.clip(jnp.sum(test_mask), 1, None)
        return acc, jnp.max(gaps)

    accs, gaps = jax.vmap(one_fold)(train_masks)
    return jnp.mean(accs), jnp.max(gaps)


@partial(jax.jit, static_argnames=("n_iters", "n_classes"))
def _cv_batch(kernels, pair_y, pair_classes, truth, train_masks, c,
              n_iters, n_classes):
    return jax.vmap(lambda k: _cv_one_voxel(
        k, pair_y, pair_classes, truth, train_masks, c, n_iters,
        n_classes))(kernels)


# Budget (in floats) for the live q = yy^T*K batch inside one _cv_batch
# dispatch: B_chunk * folds * pairs * n^2 floats (~256 MB).  Bounds peak
# memory for whole-brain voxel counts without a caller-visible knob.
_CV_CHUNK_BUDGET_FLOATS = 64_000_000


def svm_cv_accuracy(kernels, labels, num_folds, C=1.0, n_iters=50,
                    return_gap=False):
    """Stratified k-fold CV accuracy for a batch of precomputed kernels.

    kernels : [B, n, n] per-voxel Gram matrices
    labels : [n] condition labels (two or more classes; multiclass uses
        one-vs-one voting like sklearn SVC)
    Returns [B] mean fold accuracies (with ``return_gap=True``, a tuple
    ``(accs, gaps)`` where gaps[b] is the worst final KKT violation over
    that voxel's folds/pairs — ~0 when every dual converged within the
    ``n_iters * n`` SMO budget), matching
    ``cross_val_score(SVC(kernel='precomputed'), ...)`` semantics
    (StratifiedKFold without shuffling, unweighted fold mean).  The
    one-vs-one vote matches libsvm's conventions — strict dec > 0 votes
    the pair's first class, vote ties go to the first class — with
    classes in SORTED order (np.unique); libsvm orders classes by first
    appearance in the training labels, so exact vote-tie parity holds
    when labels first appear in sorted order (always true for FCMA's
    0..k-1 epoch labels).
    """
    from itertools import combinations

    from sklearn.model_selection import StratifiedKFold

    labels = np.asarray(labels)
    classes = np.unique(labels)
    if len(classes) < 2:
        raise ValueError("Need at least two classes; got "
                         f"{len(classes)}")
    n = len(labels)
    class_idx = np.searchsorted(classes, labels)

    pair_y, pair_classes = [], []
    for a, b in combinations(range(len(classes)), 2):
        y = np.zeros(n)
        y[class_idx == a] = 1.0
        y[class_idx == b] = -1.0
        pair_y.append(y)
        pair_classes.append([a, b])

    skf = StratifiedKFold(n_splits=num_folds, shuffle=False)
    train_masks = np.zeros((num_folds, n))
    for f, (train_idx, _) in enumerate(skf.split(np.zeros(n), labels)):
        train_masks[f, train_idx] = 1.0

    args = (jnp.asarray(np.stack(pair_y)),
            jnp.asarray(np.asarray(pair_classes)),
            jnp.asarray(class_idx),
            jnp.asarray(train_masks), float(C), int(n_iters),
            len(classes))
    kernels = jnp.asarray(kernels)
    n_problems_per_voxel = num_folds * len(pair_y)
    chunk = max(1, _CV_CHUNK_BUDGET_FLOATS // (n_problems_per_voxel
                                               * n * n))
    if kernels.shape[0] <= chunk:
        accs, gaps = _cv_batch(kernels, *args)
    else:
        parts = [_cv_batch(kernels[s:s + chunk], *args)
                 for s in range(0, kernels.shape[0], chunk)]
        accs = jnp.concatenate([a for a, _ in parts])
        gaps = jnp.concatenate([g for _, g in parts])
    if return_gap:
        return np.asarray(accs), np.asarray(gaps)
    return np.asarray(accs)
