"""Batched kernel SVM (C-SVC, precomputed kernel) in pure JAX.

TPU-native replacement for FCMA's per-voxel ``sklearn.svm.SVC`` cross
validation (reference fcma/voxelselector.py:41-53, :423-465): instead of a
multiprocessing pool running thousands of tiny independent SVC fits, the
dual problems for ALL voxels and ALL folds are solved simultaneously as one
vmapped projected-gradient program on the MXU.

The dual of C-SVC:  max_a  1ᵀa - ½ aᵀQa,  0 <= a_i <= C,  Q = yyᵀ∘K.
Cyclic dual coordinate descent (the liblinear update) solves each problem
exactly for the small epoch counts FCMA uses (tens of samples); fold
exclusion is expressed by zeroing each test sample's box constraint, which
keeps every (voxel, fold) problem the same static shape.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["svm_cv_accuracy", "svm_fit_dual", "svm_decision"]


@partial(jax.jit, static_argnames=("n_iters",))
def svm_fit_dual(kernel, y, box, n_iters=400):
    """Solve the C-SVC dual exactly by cyclic dual coordinate descent
    (the liblinear/SMO-style update, which converges to the optimum for
    PSD kernels).

    kernel : [n, n] symmetric PSD Gram matrix
    y : [n] labels in {-1, +1}
    box : [n] per-sample upper bounds (C, or 0 to exclude a sample)
    n_iters : number of full sweeps over the coordinates
    Returns (alpha [n], bias).
    """
    y = y.astype(kernel.dtype)
    box = box.astype(kernel.dtype)
    n = kernel.shape[0]
    q = (y[:, None] * y[None, :]) * kernel
    diag = jnp.clip(jnp.diag(q), 1e-12, None)

    def body(k, carry):
        alpha, qalpha = carry
        i = k % n
        grad = 1.0 - qalpha[i]
        new = jnp.clip(alpha[i] + grad / diag[i], 0.0, box[i])
        delta = new - alpha[i]
        alpha = alpha.at[i].set(new)
        qalpha = qalpha + q[:, i] * delta
        return alpha, qalpha

    zeros = jnp.zeros((n,), dtype=kernel.dtype)
    alpha, _ = jax.lax.fori_loop(0, n_iters * n, body, (zeros, zeros))

    # Bias from free support vectors (0 < alpha < C); fall back to all
    # bounded SVs when none are free.
    f = kernel @ (alpha * y)
    free = (alpha > 1e-8 * box) & (alpha < box * (1 - 1e-6)) & (box > 0)
    any_free = jnp.sum(free) > 0
    sv = (alpha > 1e-8) & (box > 0)
    sel = jnp.where(any_free, free, sv)
    denom = jnp.clip(jnp.sum(sel), 1, None)
    bias = jnp.sum(jnp.where(sel, y - f, 0.0)) / denom
    return alpha, bias


def svm_decision(train_test_kernel, alpha, y, bias):
    """Decision values for test samples: K_test,train @ (alpha*y) + b."""
    return train_test_kernel @ (alpha * y) + bias


@partial(jax.jit, static_argnames=("n_iters",))
def _cv_one_voxel(kernel, y_signed, train_masks, c, n_iters):
    """Mean CV accuracy of one voxel's kernel over all folds.

    kernel : [n, n]; y_signed : [n]; train_masks : [F, n] (1=train)
    """
    def one_fold(train_mask):
        train_mask = train_mask.astype(kernel.dtype)
        box = c * train_mask
        alpha, bias = svm_fit_dual(kernel, y_signed, box, n_iters=n_iters)
        dec = svm_decision(kernel, alpha, y_signed, bias)
        pred = jnp.where(dec >= 0, 1.0, -1.0)
        test_mask = 1.0 - train_mask
        correct = jnp.sum((pred == y_signed) * test_mask)
        return correct / jnp.clip(jnp.sum(test_mask), 1, None)

    return jnp.mean(jax.vmap(one_fold)(train_masks))


@partial(jax.jit, static_argnames=("n_iters",))
def _cv_batch(kernels, y_signed, train_masks, c, n_iters):
    return jax.vmap(lambda k: _cv_one_voxel(k, y_signed, train_masks, c,
                                            n_iters))(kernels)


def svm_cv_accuracy(kernels, labels, num_folds, C=1.0, n_iters=50):
    """Stratified k-fold CV accuracy for a batch of precomputed kernels.

    kernels : [B, n, n] per-voxel Gram matrices
    labels : [n] binary condition labels
    Returns [B] mean fold accuracies, matching
    ``cross_val_score(SVC(kernel='precomputed'), ...)`` semantics
    (StratifiedKFold without shuffling, unweighted fold mean).
    """
    from sklearn.model_selection import StratifiedKFold

    labels = np.asarray(labels)
    classes = np.unique(labels)
    if len(classes) != 2:
        raise ValueError("On-device SVM CV supports binary labels; got "
                         f"{len(classes)} classes")
    y_signed = np.where(labels == classes[0], -1.0, 1.0)

    skf = StratifiedKFold(n_splits=num_folds, shuffle=False)
    train_masks = np.zeros((num_folds, len(labels)))
    for f, (train_idx, _) in enumerate(skf.split(np.zeros(len(labels)),
                                                 labels)):
        train_masks[f, train_idx] = 1.0

    out = _cv_batch(jnp.asarray(kernels), jnp.asarray(y_signed),
                    jnp.asarray(train_masks), float(C), int(n_iters))
    return np.asarray(out)
