"""Batched kernel SVM (C-SVC, precomputed kernel) in pure JAX.

TPU-native replacement for FCMA's per-voxel ``sklearn.svm.SVC`` cross
validation (reference fcma/voxelselector.py:41-53, :423-465): instead of a
multiprocessing pool running thousands of tiny independent SVC fits, the
dual problems for ALL voxels and ALL folds are solved simultaneously as one
vmapped projected-gradient program on the MXU.

The dual of C-SVC:  max_a  1ᵀa - ½ aᵀQa,  0 <= a_i <= C,  yᵀa = 0,
Q = yyᵀ∘K.  The equality constraint (from the bias term) means plain
coordinate descent solves the WRONG problem (the bias-free liblinear
dual); each problem is instead solved by SMO with maximal-violating-pair
working-set selection — libsvm's algorithm — expressed as a fixed-length
``fori_loop`` of two-coordinate updates with argmax/argmin selection, so
all (voxel, fold, pair) problems run as one vmapped program.  Fold and
class-pair exclusion are expressed by zeroing each excluded sample's box
constraint, which keeps every problem the same static shape.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["svm_cv_accuracy", "svm_fit_dual", "svm_fit_dual_ipm",
           "svm_decision"]


@partial(jax.jit, static_argnames=("n_iters",))
def svm_fit_dual(kernel, y, box, n_iters=400):
    """Solve the C-SVC dual (WITH the yᵀa = 0 equality constraint) by SMO
    with maximal-violating-pair working-set selection — the libsvm
    algorithm, so solutions match ``sklearn.svm.SVC`` to optimizer
    tolerance.

    kernel : [n, n] symmetric PSD Gram matrix
    y : [n] labels in {-1, +1} (0 allowed for excluded samples)
    box : [n] per-sample upper bounds (C, or 0 to exclude a sample)
    n_iters : SMO step budget is n_iters * n two-coordinate updates
        (converged problems keep selecting a non-violating pair, whose
        update is a no-op, so overshooting is safe)
    Returns (alpha [n], bias, gap) — ``gap`` is the final KKT violation
    (libsvm's stopping quantity; ~0 when the dual converged within the
    step budget).
    """
    y = y.astype(kernel.dtype)
    box = box.astype(kernel.dtype)
    n = kernel.shape[0]
    q = (y[:, None] * y[None, :]) * kernel
    active = box > 0
    inf = jnp.asarray(jnp.inf, dtype=kernel.dtype)

    def body(_, carry):
        # Gather/scatter-free SMO step: every indexed read (q rows,
        # yg[i], box[i], ...) is expressed as a one-hot contraction and
        # the alpha update as a dense axpy.  Batched dynamic gathers /
        # scatter-adds under the (voxel, fold, pair) vmap lower to
        # serialized scatter ops on TPU — measured ~8 ms per SMO step at
        # a 32k-problem batch vs microseconds for the dense form (n is
        # at most a few dozen epochs, so the dense work is trivial).
        # The dozen per-step scalar reads are stacked into two one-hot
        # contractions (e2 @ vals, e2 @ q); measured wall-neutral vs
        # one op per read on the current platform (the step is bound by
        # its sequential dependency chain, not op count).
        alpha, grad = carry
        # working-set selection on -y*grad over the feasible direction
        # sets: I_up can increase alpha along +y, I_low along -y
        yg = -y * grad
        in_up = active & (((y > 0) & (alpha < box)) |
                          ((y < 0) & (alpha > 0)))
        in_low = active & (((y < 0) & (alpha < box)) |
                           ((y > 0) & (alpha > 0)))
        e2 = jnp.stack([
            jax.nn.one_hot(jnp.argmax(jnp.where(in_up, yg, -inf)), n,
                           dtype=kernel.dtype),
            jax.nn.one_hot(jnp.argmin(jnp.where(in_low, yg, inf)), n,
                           dtype=kernel.dtype)])          # [2, n]
        vals = jnp.stack([yg, y, box, alpha,
                          in_up.astype(kernel.dtype),
                          in_low.astype(kernel.dtype)])   # [6, n]
        # One-hot contractions are exact elementwise reads in disguise:
        # pin them to HIGHEST so the MXU's default bf16 truncation cannot
        # round the carried grad/alpha state each sequential step.
        hp = jax.lax.Precision.HIGHEST
        at = jnp.matmul(e2, vals.T, precision=hp)         # [2, 6]
        qij = jnp.matmul(e2, q, precision=hp)             # [2, n]
        yg_i, y_i, box_i, alpha_i, up_i = (at[0, 0], at[0, 1], at[0, 2],
                                           at[0, 3], at[0, 4])
        yg_j, y_j, alpha_j, low_j = (at[1, 0], at[1, 1], at[1, 3],
                                     at[1, 5])
        box_j = at[1, 2]
        qii = jnp.sum(qij[0] * e2[0])
        qjj = jnp.sum(qij[1] * e2[1])
        qij_cross = jnp.sum(qij[0] * e2[1])

        # two-variable subproblem along the constraint-preserving
        # direction: d alpha_i = y_i * t, d alpha_j = -y_j * t
        quad = jnp.clip(qii + qjj - 2.0 * y_i * y_j * qij_cross,
                        1e-12, None)
        t = (yg_i - yg_j) / quad
        # box clipping for both coordinates
        t_hi_i = jnp.where(y_i > 0, box_i - alpha_i, alpha_i)
        t_hi_j = jnp.where(y_j > 0, alpha_j, box_j - alpha_j)
        t = jnp.clip(t, 0.0, jnp.minimum(t_hi_i, t_hi_j))
        # only step when the pair actually violates optimality
        t = jnp.where((yg_i - yg_j > 1e-12) & (up_i > 0) & (low_j > 0),
                      t, 0.0)
        d2 = jnp.stack([y_i * t, -y_j * t])               # [2]
        alpha = alpha + jnp.matmul(d2, e2, precision=hp)
        grad = grad + jnp.matmul(d2, qij, precision=hp)
        return alpha, grad

    zeros = jnp.zeros((n,), dtype=kernel.dtype)
    alpha, grad = jax.lax.fori_loop(0, n_iters * n, body,
                                    (zeros, -jnp.ones_like(zeros)))

    # Bias: average y - f over free SVs; with none free, the midpoint of
    # the remaining violating-pair interval (libsvm's rho rule).
    f = kernel @ (alpha * y)
    free = (alpha > 1e-8 * box) & (alpha < box * (1 - 1e-6)) & active
    any_free = jnp.sum(free) > 0
    yg = -y * grad
    in_up = active & (((y > 0) & (alpha < box)) | ((y < 0) & (alpha > 0)))
    in_low = active & (((y < 0) & (alpha < box)) | ((y > 0) & (alpha > 0)))
    mid = (jnp.max(jnp.where(in_up, yg, -inf)) +
           jnp.min(jnp.where(in_low, yg, inf))) / 2.0
    bias_free = jnp.sum(jnp.where(free, y - f, 0.0)) / \
        jnp.clip(jnp.sum(free), 1, None)
    bias = jnp.where(any_free, bias_free,
                     jnp.where(jnp.isfinite(mid), mid, 0.0))
    # KKT violation gap (libsvm's stopping quantity): 0 when converged.
    # Lets callers detect an under-budgeted fixed-length SMO loop instead
    # of silently returning a degraded dual.
    gap = (jnp.max(jnp.where(in_up, yg, -inf)) -
           jnp.min(jnp.where(in_low, yg, inf)))
    gap = jnp.where(jnp.isfinite(gap), jnp.clip(gap, 0.0, None), 0.0)
    return alpha, bias, gap


def svm_decision(train_test_kernel, alpha, y, bias):
    """Decision values for test samples: K_test,train @ (alpha*y) + b."""
    return train_test_kernel @ (alpha * y) + bias


@partial(jax.jit, static_argnames=("n_iters",))
def svm_fit_dual_ipm(kernel, y, box, n_iters=30):
    """Solve the C-SVC dual by a primal-dual interior-point method.

    Same problem and return contract as :func:`svm_fit_dual` (alpha,
    bias, gap), different algorithm: where SMO is a chain of
    ``n_iters_smo * n`` sequential two-coordinate updates, the IPM runs
    ~``n_iters`` Newton steps (an n-independent count), each a dense
    [n, n] Cholesky solve over the vmapped problem batch.  Measured:
    duals match sklearn's SVC to ~1e-4 (f64) and CV accuracies match
    the SMO path exactly in f64 / to single near-boundary test samples
    in fp32; batched CV wall time on CPU is ~1.3x the SMO path's at
    n = 16 (the batched small-matrix Cholesky dominates), so SMO stays
    the default and the IPM serves as the independent exact
    cross-check (``svm_cv_accuracy(..., solver='ipm')``) for the SMO
    step budget.

      min_a 0.5 a'Qa - 1'a   s.t.  y'a = 0,  0 <= a <= C
      (Q = yy' o K; reference semantics: sklearn SVC precomputed)

    Excluded samples (box == 0, e.g. other folds' samples or epochs
    outside the class pair) are made non-degenerate instead of shrinking
    their box to a point: their Q row/column is masked out, their linear
    term flips to +1 (so the optimum pins them to 0), and their box is
    widened to 1 — a strictly-interior, separable dummy coordinate.

    The equality multiplier converges to the SVC bias directly (for a
    free SV, stationarity gives f_i + nu = y_i), so no post-hoc rho rule
    is needed.  ``gap`` reports the same KKT violating-pair quantity as
    the SMO path.
    """
    dt = kernel.dtype
    y = y.astype(dt)
    box = box.astype(dt)
    n = kernel.shape[0]
    active = box > 0
    m = active.astype(dt)
    q = (y[:, None] * y[None, :]) * kernel * (m[:, None] * m[None, :])
    c_lin = jnp.where(active, -1.0, 1.0).astype(dt)
    ub = jnp.where(active, box, 1.0)

    # Strictly interior, equality-feasible start: spread a small mass
    # over each side of the pair proportionally to 1/count so y'a = 0.
    # (y > 0).astype(dt), not where(y > 0, 1.0, 0.0): two weak Python
    # scalars under a bool condition default to f64 under x64 and the
    # promotion would poison the whole loop carry
    n_pos = jnp.clip(jnp.sum((y > 0).astype(dt)), 1, None)
    n_neg = jnp.clip(jnp.sum((y < 0).astype(dt)), 1, None)
    n_min = jnp.minimum(n_pos, n_neg)
    scale = 0.5 * jnp.min(jnp.where(active, ub, jnp.inf))
    a0 = jnp.where(y > 0, scale * n_min / n_pos,
                   jnp.where(y < 0, scale * n_min / n_neg, 0.5 * ub))
    a = jnp.clip(a0, 1e-6, ub * (1 - 1e-6))
    # the clip could break y'a = 0 only in pathological all-excluded
    # problems; those have no pair samples and report accuracy on an
    # empty test set anyway
    z_lo = jnp.ones_like(a)
    z_hi = jnp.ones_like(a)
    nu = jnp.zeros((), dt)
    eye = jnp.eye(n, dtype=dt)
    tau = jnp.asarray(0.95, dt)
    # Keep the iterate a dtype-scaled distance inside the box: as the
    # path converges, ``ub - a`` underflows to exactly 0 in fp32 (ulp
    # ~1e-7 at 1.0) and the barrier divisions produce NaNs.  The floor
    # caps attainable dual accuracy at ~100 ulp — far beyond what the
    # CV decisions need.
    floor = 100.0 * jnp.finfo(dt).eps * jnp.max(ub)

    def body(_, carry):
        a, nu, z_lo, z_hi = carry
        a = jnp.clip(a, floor, ub - floor)
        s_hi = ub - a
        mu = (jnp.sum(z_lo * a) + jnp.sum(z_hi * s_hi)) / (2.0 * n)
        sig_mu = 0.1 * mu
        rd = q @ a + c_lin + nu * y - z_lo + z_hi
        r1 = -rd + (sig_mu - z_lo * a) / a \
            - (sig_mu - z_hi * s_hi) / s_hi
        d = z_lo / a + z_hi / s_hi
        chol = jnp.linalg.cholesky(q + jnp.diag(d)
                                   + 1e-6 * eye)
        sol = jax.scipy.linalg.cho_solve(
            (chol, True), jnp.stack([y, r1], axis=1))
        u, v = sol[:, 0], sol[:, 1]
        dnu = jnp.sum(y * v) / jnp.clip(jnp.sum(y * u), 1e-12, None)
        da = v - dnu * u
        dz_lo = (sig_mu - z_lo * a - z_lo * da) / a
        dz_hi = (sig_mu - z_hi * s_hi + z_hi * da) / s_hi

        def max_step(x, dx):
            # largest s with x + s*dx >= (1-tau)*x for dx < 0
            ratio = jnp.where(dx < 0, -x / jnp.where(dx < 0, dx, -1.0),
                              jnp.inf)
            return jnp.minimum(1.0, tau * jnp.min(ratio))

        s_pri = jnp.minimum(max_step(a, da), max_step(s_hi, -da))
        s_dual = jnp.minimum(max_step(z_lo, dz_lo),
                             max_step(z_hi, dz_hi))
        a = a + s_pri * da
        nu = nu + s_dual * dnu
        z_lo = z_lo + s_dual * dz_lo
        z_hi = z_hi + s_dual * dz_hi
        return a, nu, z_lo, z_hi

    a, nu, z_lo, z_hi = jax.lax.fori_loop(0, n_iters, body,
                                          (a, nu, z_lo, z_hi))
    alpha = jnp.where(active, jnp.clip(a, 0.0, box), 0.0)

    # Bias: nu is the bias up to sign convention (stationarity for a
    # free SV gives f_i + nu = y_i); report the same KKT gap as SMO.
    # Unlike SMO, the interior path only reaches the bounds
    # asymptotically (alpha = C - O(mu)), so bound membership for the
    # violating-pair sets needs a tolerance — with exact comparisons a
    # converged bounded SV still counts as movable and inflates the
    # gap by its O(1) legitimate KKT slack.
    f = kernel @ (alpha * y)
    grad = q @ alpha - jnp.where(active, 1.0, 0.0)
    yg = -y * grad
    inf = jnp.asarray(jnp.inf, dt)
    tol = 1e-5 * jnp.maximum(box, 1.0)
    at_hi = alpha > box - tol
    at_lo = alpha < tol
    in_up = active & (((y > 0) & ~at_hi) | ((y < 0) & ~at_lo))
    in_low = active & (((y < 0) & ~at_hi) | ((y > 0) & ~at_lo))
    gap = (jnp.max(jnp.where(in_up, yg, -inf)) -
           jnp.min(jnp.where(in_low, yg, inf)))
    gap = jnp.where(jnp.isfinite(gap), jnp.clip(gap, 0.0, None), 0.0)
    free = ~at_hi & ~at_lo & active
    any_free = jnp.sum(free) > 0
    bias_free = jnp.sum(jnp.where(free, y - f, 0.0)) / \
        jnp.clip(jnp.sum(free), 1, None)
    bias = jnp.where(any_free, bias_free, nu)
    return alpha, bias, gap


@partial(jax.jit, static_argnames=("n_iters", "n_classes", "solver"))
def _cv_one_voxel(kernel, pair_y, pair_classes, truth, train_masks,
                  c, n_iters, n_classes, solver="smo"):
    """Mean one-vs-one CV accuracy of one voxel's kernel over all folds.

    kernel : [n, n]
    pair_y : [P, n] ±1 labels per class pair (0 for samples outside it)
    pair_classes : [P, 2] int (positive-side class, negative-side class)
    truth : [n] int class indices
    train_masks : [F, n] (1 = train)

    Each of the P·F binary SVMs trains only on its pair's training
    samples (the box constraint is zero elsewhere); test samples collect
    one-vs-one votes and the predicted class is the vote argmax
    (sklearn SVC's multiclass scheme; libsvm vote conventions, see
    svm_cv_accuracy).
    """
    def one_fold(train_mask):
        train_mask = train_mask.astype(kernel.dtype)

        def one_pair(y_p, classes_p):
            # |y_p| is the pair membership mask
            box = c * train_mask * jnp.abs(y_p)
            if solver == "ipm":
                alpha, bias, gap = svm_fit_dual_ipm(kernel, y_p, box,
                                                    n_iters=n_iters)
            else:
                alpha, bias, gap = svm_fit_dual(kernel, y_p, box,
                                                n_iters=n_iters)
            dec = svm_decision(kernel, alpha, y_p, bias)
            # libsvm votes the LATER class of the pair at exactly 0
            vote_class = jnp.where(dec > 0, classes_p[0], classes_p[1])
            return jax.nn.one_hot(vote_class, n_classes), gap

        votes, gaps = jax.vmap(one_pair)(pair_y, pair_classes)
        votes = jnp.sum(votes, axis=0)
        pred = jnp.argmax(votes, axis=1)
        test_mask = 1.0 - train_mask
        correct = jnp.sum((pred == truth) * test_mask)
        acc = correct / jnp.clip(jnp.sum(test_mask), 1, None)
        return acc, jnp.max(gaps)

    accs, gaps = jax.vmap(one_fold)(train_masks)
    return jnp.mean(accs), jnp.max(gaps)


@partial(jax.jit, static_argnames=("n_iters", "n_classes", "solver"))
def _cv_batch(kernels, pair_y, pair_classes, truth, train_masks, c,
              n_iters, n_classes, solver="smo"):
    return jax.vmap(lambda k: _cv_one_voxel(
        k, pair_y, pair_classes, truth, train_masks, c, n_iters,
        n_classes, solver))(kernels)


# Budget (in floats) for the live q = yy^T*K batch inside one _cv_batch
# dispatch: B_chunk * folds * pairs * n^2 floats (~256 MB).  Bounds peak
# memory for whole-brain voxel counts without a caller-visible knob.
_CV_CHUNK_BUDGET_FLOATS = 64_000_000


def svm_cv_accuracy(kernels, labels, num_folds, C=1.0, n_iters=50,
                    return_gap=False, solver="smo"):
    """Stratified k-fold CV accuracy for a batch of precomputed kernels.

    kernels : [B, n, n] per-voxel Gram matrices
    labels : [n] condition labels (two or more classes; multiclass uses
        one-vs-one voting like sklearn SVC)
    Returns [B] mean fold accuracies (with ``return_gap=True``, a tuple
    ``(accs, gaps)`` where gaps[b] is the worst final KKT violation over
    that voxel's folds/pairs — ~0 when every dual converged within the
    ``n_iters * n`` SMO budget), matching
    ``cross_val_score(SVC(kernel='precomputed'), ...)`` semantics
    (StratifiedKFold without shuffling, unweighted fold mean).  The
    one-vs-one vote matches libsvm's conventions — strict dec > 0 votes
    the pair's first class, vote ties go to the first class — with
    classes in SORTED order (np.unique); libsvm orders classes by first
    appearance in the training labels, so exact vote-tie parity holds
    when labels first appear in sorted order (always true for FCMA's
    0..k-1 epoch labels).
    """
    from itertools import combinations

    from sklearn.model_selection import StratifiedKFold

    labels = np.asarray(labels)
    classes = np.unique(labels)
    if len(classes) < 2:
        raise ValueError("Need at least two classes; got "
                         f"{len(classes)}")
    n = len(labels)
    class_idx = np.searchsorted(classes, labels)

    pair_y, pair_classes = [], []
    for a, b in combinations(range(len(classes)), 2):
        y = np.zeros(n)
        y[class_idx == a] = 1.0
        y[class_idx == b] = -1.0
        pair_y.append(y)
        pair_classes.append([a, b])

    skf = StratifiedKFold(n_splits=num_folds, shuffle=False)
    train_masks = np.zeros((num_folds, n))
    for f, (train_idx, _) in enumerate(skf.split(np.zeros(n), labels)):
        train_masks[f, train_idx] = 1.0

    args = (jnp.asarray(np.stack(pair_y)),
            jnp.asarray(np.asarray(pair_classes)),
            jnp.asarray(class_idx),
            jnp.asarray(train_masks), float(C), int(n_iters),
            len(classes), str(solver))
    kernels = jnp.asarray(kernels)
    n_problems_per_voxel = num_folds * len(pair_y)
    chunk = max(1, _CV_CHUNK_BUDGET_FLOATS // (n_problems_per_voxel
                                               * n * n))
    if kernels.shape[0] <= chunk:
        accs, gaps = _cv_batch(kernels, *args)
    else:
        parts = [_cv_batch(kernels[s:s + chunk], *args)
                 for s in range(0, kernels.shape[0], chunk)]
        accs = jnp.concatenate([a for a, _ in parts])
        gaps = jnp.concatenate([g for _, g in parts])
    # fetch_replicated: a mesh-sharded kernels batch in a multi-process
    # run yields cross-process-sharded outputs that np.asarray cannot
    # read; replicate them first (no-op single-process)
    from ..parallel.mesh import fetch_replicated
    if return_gap:
        return fetch_replicated(accs), fetch_replicated(gaps)
    return fetch_replicated(accs)
