"""Fused correlation kernels.

TPU-native replacement for the reference's Cython BLAS layer
(/root/reference/src/brainiak/fcma/cython_blas.pyx) and
``fcma.util.compute_correlation``
(/root/reference/src/brainiak/fcma/util.py:63).

Design notes (TPU-first):
- The reference normalizes with scipy zscore on host, then calls sgemm into
  preallocated strided buffers.  Here the z-score + 1/sqrt(n) scaling + matmul
  are one jitted function, so XLA fuses the elementwise work into the MXU
  matmul's operand load.  fp32 throughout (matching reference numerics);
  the MXU consumes fp32 matmuls natively via bf16x3 passes.
- The "write into a slice of a preallocated 3-D buffer" pattern disappears:
  batched epochs are a leading dimension handled by a single einsum
  (``[E, B, T] x [E, V, T] -> [B, E, V]``), which XLA tiles onto the MXU.
"""

from functools import partial

import jax
import jax.numpy as jnp

__all__ = [
    "compute_correlation",
    "correlate_epochs",
    "normalize_for_correlation",
    "resolve_precision",
]

# Matmul precision for correlation statistics.  HIGHEST (fp32-equivalent via
# bf16 passes on the MXU) keeps Pearson r within ~1e-6 of float64 references;
# 'high' (fewer bf16 passes) trades ~1e-3 correlation accuracy for several-x
# MXU throughput — the main FCMA perf lever on TPU.
PRECISION = jax.lax.Precision.HIGHEST

_PRECISION_NAMES = {
    "highest": jax.lax.Precision.HIGHEST,
    "high": jax.lax.Precision.HIGH,
    "default": jax.lax.Precision.DEFAULT,
}


def resolve_precision(precision):
    """Map 'highest' / 'high' / 'default' (or a jax.lax.Precision, or
    None for the module default) to a jax.lax.Precision."""
    if precision is None:
        return PRECISION
    if isinstance(precision, jax.lax.Precision):
        return precision
    try:
        return _PRECISION_NAMES[str(precision).lower()]
    except KeyError:
        raise ValueError(
            f"precision must be one of {sorted(_PRECISION_NAMES)} or a "
            f"jax.lax.Precision; got {precision!r}") from None


@partial(jax.jit, static_argnames=("axis", "return_nans"))
def normalize_for_correlation(data, axis, return_nans=False):
    """Z-score (population) and scale by 1/sqrt(n) along ``axis``.

    After this, a plain dot product of two normalized vectors is their
    Pearson correlation.  Zero-variance rows produce zeros unless
    ``return_nans``.  Contract: fcma/util.py:32-60.
    """
    data = jnp.asarray(data, dtype=jnp.float32)
    n = data.shape[axis]
    mean = jnp.mean(data, axis=axis, keepdims=True)
    std = jnp.std(data, axis=axis, keepdims=True)
    z = (data - mean) / std
    if not return_nans:
        z = jnp.where(jnp.isfinite(z), z, 0.0)
    return z / jnp.sqrt(jnp.float32(n))


@partial(jax.jit, static_argnames=("return_nans", "precision"))
def compute_correlation(matrix1, matrix2, return_nans=False,
                        precision=None):
    """Pearson correlation of the rows of ``matrix1`` with rows of ``matrix2``.

    Returns shape ``[r1, r2]`` in float32.  Contract: fcma/util.py:63-134
    (there: normalize + BLAS sgemm; here: one fused XLA computation).
    ``precision``: 'highest' (default) / 'high' / 'default' — see
    :func:`resolve_precision`.
    """
    matrix1 = jnp.asarray(matrix1, dtype=jnp.float32)
    matrix2 = jnp.asarray(matrix2, dtype=jnp.float32)
    if matrix1.shape[1] != matrix2.shape[1]:
        raise ValueError('Dimension discrepancy')
    m1 = normalize_for_correlation(matrix1, 1, return_nans=return_nans)
    m2 = normalize_for_correlation(matrix2, 1, return_nans=return_nans)
    return jnp.matmul(m1, m2.T, precision=resolve_precision(precision))


@partial(jax.jit, static_argnames=("precision",))
def correlate_epochs(block_data, all_data, precision=None):
    """Per-epoch correlation of a voxel block against all voxels.

    Parameters
    ----------
    block_data : [n_epochs, block_voxels, n_TRs] float32, pre-normalized
        (``normalize_for_correlation`` along the TR axis).
    all_data : [n_epochs, n_voxels, n_TRs] float32, pre-normalized.

    Returns
    -------
    corr : [block_voxels, n_epochs, n_voxels]
        The layout consumed by within-subject normalization — the analog of
        the strided writes in cython_blas.pyx:20-115
        (``compute_self_corr_for_voxel_sel``), produced directly by one
        einsum instead.
    """
    return jnp.einsum('ebt,evt->bev', block_data, all_data,
                      precision=resolve_precision(precision),
                      preferred_element_type=jnp.float32)
