"""Pallas TPU kernels for the FCMA hot path.

The FCMA stage-1 inner loop (reference fcma/cython_blas.pyx:20-115 +
fcma_extension.cc:29-92) computes, per voxel block: per-epoch correlations
against all voxels, then Fisher-z + within-subject epoch normalization.
The XLA path (:mod:`brainiak_tpu.ops.correlation` /
:mod:`brainiak_tpu.ops.fisherz`) materializes the [block, epochs, voxels]
correlation tensor in HBM between the two steps; this kernel fuses the
epoch-batched MXU matmuls with the normalization while the tile is still in
VMEM, writing the normalized tensor exactly once.

Grid: (block_tiles, voxel_tiles).  Each program loads the whole epoch/TR
extent of its two voxel tiles ([E, T, TB] and [E, T, TV]), runs ONE
E-batched matmul on the MXU producing [E, TB, TV], and applies the
clamped Fisher-z and per-subject epoch z-scoring on the VPU **with the
epoch axis leading**: Mosaic tiles the last two dims of a vector, so
group reshapes/reductions over the untiled leading axis are free, while
the [TB, E, TV] layout (epochs in the middle) forces a relayout per
reshape — measured 50x slower on a real v5e chip.  A single transpose to
the caller-facing [TB, E, ...] layout happens once, right before the
MXU-side Gram reduction / the output store.

On non-TPU backends the kernel runs in interpreter mode (tests), and
callers can always fall back to the XLA path.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .fisherz import _CLAMP

__all__ = ["fcma_corr_normalize", "fcma_gram", "fcma_sample_gram",
           "pick_tiles", "pad_to_tiles"]


def _mosaic_precision(precision):
    """Mosaic lowers only DEFAULT/HIGHEST dot precisions (a HIGH dot is
    a hard NotImplementedError at kernel compile); clamp the in-between
    setting up — the XLA paths keep the true 3-pass 'high' lever."""
    from .correlation import resolve_precision
    p = resolve_precision(precision)
    return jax.lax.Precision.HIGHEST if p == jax.lax.Precision.HIGH else p

# VMEM budget per program, in floats.  Leaves headroom under the 16 MB
# scoped-VMEM limit for the cost model below (double-buffered I/O tiles
# plus the normalization chain's live intermediates); exceeding the real
# limit is a hard Mosaic compile error on TPU (observed at round-2
# tile probing: (128, 512) tiles -> "17.64M > 16.00M" OOM).
_VMEM_BUDGET_FLOATS = 3_900_000


def pick_tiles(n_epochs, n_trs, n_b, n_v):
    """Choose (tile_b, tile_v, fits): tile sizes (multiples of 8/128 or
    the full extent when smaller) keeping the working set within the VMEM
    budget.  ``fits`` is False when even the smallest tiles exceed the
    budget (very large epoch x TR extents) — callers should fall back to
    the XLA path then."""

    def used(tb, tv):
        # Pipelined input tiles are double-buffered (2x); the Fisher-z /
        # z-score chain keeps ~3 [E, tb, tv]-sized vectors live at once,
        # and the worst-case output tile ([tb, E, tv], corr_normalize)
        # is double-buffered too.
        return (2 * n_epochs * n_trs * (tb + tv)
                + 5 * n_epochs * tb * tv)

    tile_b = min(128, n_b)
    tile_v = min(512, n_v)
    while tile_v > 128 and used(tile_b, tile_v) > _VMEM_BUDGET_FLOATS:
        tile_v //= 2
    tile_v = max(tile_v, min(128, n_v))
    while tile_b > 8 and used(tile_b, tile_v) > _VMEM_BUDGET_FLOATS:
        tile_b //= 2
    tile_b = max(tile_b, min(8, n_b))
    return tile_b, tile_v, used(tile_b, tile_v) <= _VMEM_BUDGET_FLOATS


def pad_to_tiles(blk, data2):
    """Shared Pallas preamble: pick VMEM tile sizes and zero-pad the two
    voxel axes to tile multiples (zero columns correlate/normalize to
    exactly zero, so they are inert downstream).  Returns
    (blk_p, data_p, tile_b, tile_v, fits); when ``fits`` is False the
    inputs are returned unpadded and callers should take the XLA path."""
    n_e, n_t, n_b = blk.shape
    n_v = data2.shape[2]
    tile_b, tile_v, fits = pick_tiles(n_e, n_t, n_b, n_v)
    if not fits:
        return blk, data2, tile_b, tile_v, False
    blk_p = jnp.pad(blk, ((0, 0), (0, 0), (0, (-n_b) % tile_b)))
    data_p = jnp.pad(data2, ((0, 0), (0, 0), (0, (-n_v) % tile_v)))
    return blk_p, data_p, tile_b, tile_v, True


def _corr_tile(blk_ref, data_ref, n_epochs, precision):
    """Raw per-epoch correlation tile: one E-batched MXU matmul
    [E, T, TB] x [E, T, TV] -> [E, TB, TV] (batch dim 0, the only batch
    position Mosaic lowers)."""
    del n_epochs  # shape-carried
    return jax.lax.dot_general(
        blk_ref[...], data_ref[...], (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=precision)


def _normalized_corr_tile(blk_ref, data_ref, n_epochs, epochs_per_subj,
                          precision):
    """Compute one (TB, TV) tile of normalized correlation in VMEM:
    E-batched MXU matmul, clamped Fisher-z, per-subject epoch z-score
    (fcma_extension.cc:68-84 semantics).  Returns [E, TB, TV] — epoch
    axis leading so the subject-group reshapes stay on the untiled dim."""
    n_subjs = n_epochs // epochs_per_subj

    corr = _corr_tile(blk_ref, data_ref, n_epochs, precision)
    # Fisher z with the reference's clamping (fcma_extension.cc:68-72)
    num = 1.0 + corr
    den = 1.0 - corr
    num = jnp.where(num <= 0.0, _CLAMP, num)
    den = jnp.where(den <= 0.0, _CLAMP, den)
    z = 0.5 * jnp.log(num / den)
    # z-score across each subject's epochs (population std, zero when
    # non-positive; fcma_extension.cc:74-84)
    _, tb, tv = z.shape
    zr = z.reshape(n_subjs, epochs_per_subj, tb, tv)
    mean = jnp.mean(zr, axis=1, keepdims=True)
    var = jnp.mean(zr * zr, axis=1, keepdims=True) - mean * mean
    inv = jnp.where(var <= 0.0, 0.0, jax.lax.rsqrt(var))
    return ((zr - mean) * inv).reshape(n_epochs, tb, tv)


def _kernel(blk_ref, data_ref, out_ref, *, n_epochs, epochs_per_subj,
            precision=jax.lax.Precision.HIGHEST):
    """One (TB, TV) tile: correlate, Fisher-z, normalize, store."""
    z = _normalized_corr_tile(
        blk_ref, data_ref, n_epochs, epochs_per_subj, precision)
    out_ref[:, :, :] = jnp.transpose(z, (1, 0, 2))


def _gram_kernel(blk_ref, data_ref, out_ref, *, n_epochs,
                 epochs_per_subj, precision=jax.lax.Precision.HIGHEST):
    """One (TB, TV) tile reduced straight into per-voxel Gram matrices.

    The voxel grid axis is a reduction: each program adds its tile's
    contribution z @ z^T to the [TB, E, E] accumulator, so the [B, E, V]
    normalized-correlation tensor never exists in HBM at all — the
    payoff of fusing, since for whole-brain V that tensor dominates
    memory traffic (the on-chip analog of the reference's portioned-Gram
    accumulation, classifier.py:279-348)."""
    z = _normalized_corr_tile(blk_ref, data_ref, n_epochs,
                              epochs_per_subj, precision)
    zt = jnp.transpose(z, (1, 0, 2))  # [TB, E, TV]; batch dim -> pos 0

    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[:, :, :] = jnp.zeros_like(out_ref)

    out_ref[:, :, :] += jax.lax.dot_general(
        zt, zt, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32, precision=precision)


@functools.partial(jax.jit,
                   static_argnames=("epochs_per_subj", "tile_b", "tile_v",
                                    "interpret", "precision"))
def fcma_corr_normalize(blk, data, epochs_per_subj, tile_b=None,
                        tile_v=None, interpret=False, precision=None):
    """Fused FCMA correlation + within-subject normalization.

    blk : [E, T, B] normalized epoch data for the voxel block
    data : [E, T, V] normalized epoch data for all voxels
    precision : matmul precision for the correlation dot (see
        :func:`brainiak_tpu.ops.correlation.resolve_precision`)
    Returns [B, E, V] float32 — identical (to fp32 tolerance) to
    ``within_subject_normalization(correlate_epochs(blk, data), eps)``.

    B and V must be multiples of tile_b/tile_v (callers pad).
    """
    n_epochs, n_trs, n_b = blk.shape
    n_v = data.shape[2]
    auto_b, auto_v, fits = pick_tiles(n_epochs, n_trs, n_b, n_v)
    if (tile_b is None or tile_v is None) and not fits:
        raise ValueError(
            "epoch x TR extent too large for VMEM tiles "
            f"(E={n_epochs}, T={n_trs}); use the XLA path "
            "(ops.correlation + ops.fisherz) instead")
    tile_b = auto_b if tile_b is None else tile_b
    tile_v = auto_v if tile_v is None else tile_v
    assert n_b % tile_b == 0 and n_v % tile_v == 0, \
        "block/voxel sizes must be multiples of the tile sizes"

    grid = (n_b // tile_b, n_v // tile_v)
    kernel = functools.partial(_kernel, n_epochs=n_epochs,
                               epochs_per_subj=epochs_per_subj,
                               precision=_mosaic_precision(precision))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n_b, n_epochs, n_v),
                                       jnp.float32),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[
                pl.BlockSpec((n_epochs, n_trs, tile_b),
                             lambda i, j: (0, 0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((n_epochs, n_trs, tile_v),
                             lambda i, j: (0, 0, j),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((tile_b, n_epochs, tile_v),
                                   lambda i, j: (i, 0, j),
                                   memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(jnp.asarray(blk, jnp.float32), jnp.asarray(data, jnp.float32))


@functools.partial(jax.jit,
                   static_argnames=("epochs_per_subj", "tile_b", "tile_v",
                                    "interpret", "precision"))
def fcma_gram(blk, data, epochs_per_subj, tile_b=None, tile_v=None,
              interpret=False, precision=None):
    """Fused FCMA correlation + normalization + per-voxel Gram reduction.

    Like :func:`fcma_corr_normalize` followed by
    ``einsum('bev,bfv->bef')``, but the [B, E, V] normalized-correlation
    tensor is reduced tile-by-tile in VMEM and never written to HBM —
    the voxel grid axis accumulates into the [B, E, E] output (TPU grids
    iterate the last axis innermost, so the accumulator tile stays
    resident).

    blk : [E, T, B]; data : [E, T, V]; returns [B, E, E] float32
    (un-shrunk — callers apply the digit shrink, which needs K[0,0]).
    B and V must be multiples of tile_b/tile_v (callers pad; zero
    padding on V contributes exactly zero to the Gram).
    """
    n_epochs, n_trs, n_b = blk.shape
    n_v = data.shape[2]
    auto_b, auto_v, fits = pick_tiles(n_epochs, n_trs, n_b, n_v)
    if (tile_b is None or tile_v is None) and not fits:
        raise ValueError(
            "epoch x TR extent too large for VMEM tiles "
            f"(E={n_epochs}, T={n_trs}); use the XLA path instead")
    tile_b = auto_b if tile_b is None else tile_b
    tile_v = auto_v if tile_v is None else tile_v
    assert n_b % tile_b == 0 and n_v % tile_v == 0, \
        "block/voxel sizes must be multiples of the tile sizes"

    grid = (n_b // tile_b, n_v // tile_v)
    kernel = functools.partial(_gram_kernel, n_epochs=n_epochs,
                               epochs_per_subj=epochs_per_subj,
                               precision=_mosaic_precision(precision))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n_b, n_epochs, n_epochs),
                                       jnp.float32),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[
                pl.BlockSpec((n_epochs, n_trs, tile_b),
                             lambda i, j: (0, 0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((n_epochs, n_trs, tile_v),
                             lambda i, j: (0, 0, j),
                             memory_space=pltpu.VMEM),
            ],
            # independent of j: the voxel axis reduces into this tile
            out_specs=pl.BlockSpec((tile_b, n_epochs, n_epochs),
                                   lambda i, j: (i, 0, 0),
                                   memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(jnp.asarray(blk, jnp.float32), jnp.asarray(data, jnp.float32))


def _sample_gram_kernel(x1_ref, x2_ref, out_ref, *, n_samples, norm_unit,
                        precision=jax.lax.Precision.HIGHEST):
    """One (V1, V2) feature tile reduced into the [N, N] sample Gram.

    BOTH grid axes are reductions: the correlation features of this
    voxel-pair tile (optionally within-subject normalized, matching
    Classifier's feature pipeline) contribute z·zᵀ over their flattened
    feature extent, so the [N, V1·V2] feature matrix never exists —
    the on-chip form of the reference's portion-by-portion Gram
    accumulation (classifier.py:279-348)."""
    if norm_unit > 1:
        z = _normalized_corr_tile(x1_ref, x2_ref, n_samples, norm_unit,
                                  precision)
    else:
        z = _corr_tile(x1_ref, x2_ref, n_samples, precision)

    @pl.when((pl.program_id(0) == 0) & (pl.program_id(1) == 0))
    def _init():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    # z: [N, T1, T2].  Mosaic lowers neither two contracting dims nor
    # non-leading batch dims, so batch over T1 (transpose to pos 0) and
    # reduce the T1-batched [T1, N, N] grams over the untiled lead axis.
    zt = jnp.transpose(z, (1, 0, 2))  # [T1, N, T2]
    g = jax.lax.dot_general(
        zt, zt, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32, precision=precision)
    out_ref[:, :] += jnp.sum(g, axis=0)


@functools.partial(jax.jit,
                   static_argnames=("norm_unit", "tile_1", "tile_2",
                                    "interpret", "precision"))
def fcma_sample_gram(x1, x2, norm_unit, tile_1=None, tile_2=None,
                     interpret=False, precision=None):
    """Fused correlation-feature sample Gram for the FCMA classifier.

    Equivalent to building the per-sample correlation features of
    region1 x region2 (with within-subject normalization when
    ``norm_unit > 1``) and computing features @ features.T, but the
    feature matrix is reduced tile-by-tile in VMEM.

    x1 : [N, T, V1]; x2 : [N, T, V2]; returns [N, N] float32 (un-shrunk).
    V1 and V2 must be multiples of the tile sizes (callers pad; zero
    columns contribute exactly zero).
    """
    n_samples, n_trs, v1 = x1.shape
    v2 = x2.shape[2]
    auto_1, auto_2, fits = pick_tiles(n_samples, n_trs, v1, v2)
    if (tile_1 is None or tile_2 is None) and not fits:
        raise ValueError(
            "sample x TR extent too large for VMEM tiles "
            f"(N={n_samples}, T={n_trs}); use the XLA path instead")
    tile_1 = auto_1 if tile_1 is None else tile_1
    tile_2 = auto_2 if tile_2 is None else tile_2
    assert v1 % tile_1 == 0 and v2 % tile_2 == 0, \
        "voxel counts must be multiples of the tile sizes"

    grid = (v1 // tile_1, v2 // tile_2)
    kernel = functools.partial(_sample_gram_kernel, n_samples=n_samples,
                               norm_unit=norm_unit,
                               precision=_mosaic_precision(precision))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n_samples, n_samples),
                                       jnp.float32),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[
                pl.BlockSpec((n_samples, n_trs, tile_1),
                             lambda i, j: (0, 0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((n_samples, n_trs, tile_2),
                             lambda i, j: (0, 0, j),
                             memory_space=pltpu.VMEM),
            ],
            # constant block index: both grid axes reduce into the Gram
            out_specs=pl.BlockSpec((n_samples, n_samples),
                                   lambda i, j: (0, 0),
                                   memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(jnp.asarray(x1, jnp.float32), jnp.asarray(x2, jnp.float32))
