"""Pure-JAX jittable kernels — the TPU-native analog of the reference's
native extensions (cython_blas.pyx, fcma_extension.cc, tfa_extension.cpp,
eventseg/_utils.pyx)."""
