"""Intersubject correlation (ISC/ISFC) with resampling statistics.

Re-design of /root/reference/src/brainiak/isc.py.  Public surface and
statistical semantics match the reference; the compute core is jitted JAX:

- leave-one-out / pairwise ISC and ISFC are batched einsums instead of
  per-voxel / per-pair Python loops (reference isc.py:164-192, 310-349);
- the resampling nulls (bootstrap, permutation, circular time-shift, phase
  randomization) route through the :mod:`brainiak_tpu.stats` engine
  (:class:`~brainiak_tpu.stats.engine.NullEngine`): whole surrogate
  families compiled as one vmapped program over ``jax.random`` keys
  instead of stateful RandomState chains (reference isc.py:739-787,
  1200-1247, 1344-1398, 1500-1547).  Seeds therefore produce different
  (but statistically equivalent) resamples than the reference.  Pass
  ``return_distribution=False`` to skip materializing the
  ``[n_resamples, V]`` null and read p/CI from the engine's mergeable
  accumulator instead (population-scale runs).

Deviation noted: in the pairwise bootstrap the reference censors resampled
same-subject pairs by testing ``isc == 1.0`` (isc.py:769); we censor by
resampled-index equality, which is equivalent except it cannot
accidentally censor a genuine ISC of exactly 1.0.
"""

import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec
from scipy.spatial.distance import squareform

from .obs import profile as obs_profile
from .obs import runtime as obs_runtime
from .obs import spans as obs_spans
from .parallel.mesh import (DEFAULT_VOXEL_AXIS, fetch_replicated,
                            place_on_mesh)
from .stats.pvalues import compute_summary_statistic, p_from_null
from .utils.utils import _check_timeseries_input

__all__ = [
    "bootstrap_isc",
    "compute_summary_statistic",
    "isc",
    "isfc",
    "permutation_isc",
    "phaseshift_isc",
    "squareform_isfc",
    "timeshift_isc",
]

logger = logging.getLogger(__name__)

MAX_RANDOM_SEED = 2 ** 32 - 1


# ---------------------------------------------------------------------------
# helpers

def _threshold_nans(data, tolerate_nans):
    """Exclude voxels exceeding the NaN threshold; returns (data, keep_mask).
    Contract: reference isc.py:592-647."""
    nans = np.all(np.any(np.isnan(data), axis=0), axis=1)
    if tolerate_nans is True:
        pass
    elif isinstance(tolerate_nans, float):
        if not 0.0 <= tolerate_nans <= 1.0:
            raise ValueError("If threshold to tolerate NaNs is a float, "
                             "it must be between 0.0 and 1.0; got {0}".format(
                                 tolerate_nans))
        nans += ~(np.sum(~np.any(np.isnan(data), axis=0), axis=1) >=
                  data.shape[-1] * tolerate_nans)
    mask = ~nans
    return data[:, mask, :], mask


def _check_isc_input(iscs, pairwise=False):
    """Standardize ISC stat-test input; returns (iscs, n_subjects, n_voxels).
    Contract: reference isc.py:373-428."""
    if isinstance(iscs, list):
        iscs = np.array(iscs)[:, np.newaxis]
    elif isinstance(iscs, np.ndarray) and iscs.ndim == 1:
        iscs = iscs[:, np.newaxis]
    if pairwise:
        try:
            test_square = squareform(iscs[:, 0], force='tomatrix')
            n_subjects = test_square.shape[0]
        except ValueError:
            raise ValueError("For pairwise input, ISCs must be the "
                             "vectorized triangle of a square matrix.")
    else:
        n_subjects = iscs.shape[0]
    return iscs, n_subjects, iscs.shape[1]


# compute_summary_statistic's canonical home is stats.pvalues (imported
# above and re-exported here for the long-standing isc surface).


def squareform_isfc(isfcs, iscs=None):
    """Square<->condensed ISFC conversion retaining diagonal ISCs
    (reference isc.py:529-590)."""
    if not isinstance(iscs, np.ndarray) and isfcs.shape[-2] == \
            isfcs.shape[-1]:
        if isfcs.ndim == 2:
            isfcs = isfcs[np.newaxis, ...]
        if isfcs.ndim == 3:
            iscs = np.diagonal(isfcs, axis1=1, axis2=2)
            isfcs = np.vstack([squareform(m, checks=False)[np.newaxis, :]
                               for m in isfcs])
        else:
            raise ValueError("Square (redundant) ISFCs must be square "
                             "with multiple subjects or pairs of subjects "
                             "indexed by the first dimension")
        if isfcs.shape[0] == iscs.shape[0] == 1:
            isfcs, iscs = isfcs[0], iscs[0]
        return isfcs, iscs
    else:
        if isfcs.ndim == iscs.ndim == 1:
            isfcs, iscs = isfcs[np.newaxis, :], iscs[np.newaxis, :]
        stack = []
        for isfc_v, isc_v in zip(isfcs, iscs):
            sq = squareform(isfc_v, checks=False)
            np.fill_diagonal(sq, isc_v)
            stack.append(sq[np.newaxis, ...])
        out = np.vstack(stack)
        return out[0] if out.shape[0] == 1 else out


def _shard_voxels(arr, mesh, axis):
    """Device-place ``arr`` with its voxel axis sharded over the mesh's
    ``'voxel'`` axis.  The voxel dimension is NaN-padded up to the next
    multiple of the axis size (every ISC computation is voxelwise
    independent and NaN-tolerant, so pad columns simply come back NaN);
    callers slice padded outputs with ``[..., :n]``.  Returns the placed
    array.  With ``mesh=None`` this is a plain ``jnp.asarray``.
    """
    if mesh is None:
        return jnp.asarray(arr)
    n_shards = mesh.shape[DEFAULT_VOXEL_AXIS]
    pad = (-arr.shape[axis]) % n_shards
    if pad:
        widths = [(0, 0)] * arr.ndim
        widths[axis] = (0, pad)
        arr = np.pad(np.asarray(arr, dtype=float), widths,
                     constant_values=np.nan)
    spec = [None] * arr.ndim
    spec[axis] = DEFAULT_VOXEL_AXIS
    return place_on_mesh(arr, NamedSharding(mesh, PartitionSpec(*spec)))


@obs_runtime.counted_cache("isc.slab")
def _slab_program(mesh, chunk):
    """Replicated row-slab fetch, cached per (mesh, chunk): jit
    caches on function identity, so a fresh lambda per
    ``_fetch_ring_matrix`` call would re-lower the broadcast on
    every fetch (jaxlint JX001).  Cache misses count as
    ``retrace_total{site=isc.slab}``; under cost profiling the
    program's first run captures a ``cost`` record joined to
    ``isc.ring_slab`` span durations."""
    return obs_profile.profile_program(jax.jit(
        lambda a, i: jax.lax.dynamic_slice_in_dim(a, i, chunk, 0),
        out_shardings=NamedSharding(mesh, PartitionSpec())),
        "isc.slab", span="isc.ring_slab")


@obs_runtime.trace_signature("isc.slab")
def _slab_trace_signature():
    """Canonical jaxlint-IR trace: one row-slab fetch on the
    voxel-axis mesh over every trace device."""
    from .parallel.mesh import make_mesh

    mesh = make_mesh((DEFAULT_VOXEL_AXIS,), (-1,))
    chunk = 2
    v = mesh.shape[DEFAULT_VOXEL_AXIS] * chunk
    f32 = jnp.float32
    return [{
        "key": (mesh, chunk),
        "args": (jax.ShapeDtypeStruct((v, v), f32),
                 jax.ShapeDtypeStruct((), jnp.int32)),
        "mesh": mesh,
    }]


def _fetch_ring_matrix(m, mesh):
    """Host-fetch the ring path's row-sharded [V, V] matrix on every
    process WITHOUT ever replicating it on a device: the ring exists
    precisely because V x V does not fit per device, so a blanket
    replicated relayout (fetch_replicated) would OOM at the scales the
    path is for.  Instead one shard's row slab is broadcast per
    dispatch — per-device memory stays O(V^2 / n_shards) and the host
    assembles the slabs.  Single-process: plain np.asarray (all shards
    addressable)."""
    if jax.process_count() == 1:
        return np.asarray(m)
    n_shards = mesh.shape[DEFAULT_VOXEL_AXIS]
    if m.shape[0] % n_shards:
        raise ValueError(
            "row count {} not divisible by {} shards; trailing rows "
            "would be lost".format(m.shape[0], n_shards))
    chunk = m.shape[0] // n_shards
    slab = _slab_program(mesh, chunk)
    out = np.empty(m.shape, dtype=m.dtype)
    for i in range(n_shards):
        # per-chunk span (no-op while obs is disabled); the
        # np.asarray fetch below synchronizes, so the span needs no
        # explicit sync target and adds none
        with obs_spans.span("isc.ring_slab",
                            attrs={"shard": i, "rows": chunk}):
            out[i * chunk:(i + 1) * chunk] = np.asarray(
                slab(m, jnp.asarray(i * chunk)))
    return out


# ---------------------------------------------------------------------------
# jitted cores

@partial(jax.jit, static_argnames=("tolerate_nans",))
def _loo_means_core(data, tolerate_nans=True):
    """Mean of all-but-subject-s along the last axis: [T, V, S] -> same."""
    if tolerate_nans:
        total = jnp.nansum(data, axis=2, keepdims=True)
        count = jnp.sum(~jnp.isnan(data), axis=2, keepdims=True)
        centered = jnp.where(jnp.isnan(data), 0.0, data)
    else:
        total = jnp.sum(data, axis=2, keepdims=True)
        count = jnp.full(data.shape[:2] + (1,), data.shape[2],
                         dtype=data.dtype)
        centered = data
    return (total - centered) / (count - 1)


@jax.jit
def _columnwise_corr(x, y):
    """Pearson r between matching columns of x and y over axis 0.

    x, y: [T, V, S] -> [S, V]
    """
    xd = x - jnp.mean(x, axis=0)
    yd = y - jnp.mean(y, axis=0)
    num = jnp.sum(xd * yd, axis=0)
    den = jnp.sqrt(jnp.sum(xd ** 2, axis=0) * jnp.sum(yd ** 2, axis=0))
    return (num / den).T


@partial(jax.jit, static_argnames=("tolerate_nans",))
def _isc_loo_core(data, tolerate_nans=True):
    """Leave-one-out ISC: corr(subject, mean-of-others) per voxel.

    data: [T, V, S] -> [S, V]
    """
    return _columnwise_corr(data, _loo_means_core(data, tolerate_nans))


@jax.jit
def _isc_pairwise_core(data):
    """Pairwise per-voxel subject-by-subject correlation matrix.

    data: [T, V, S] -> [S, S, V]
    """
    xd = data - jnp.mean(data, axis=0)
    norm = jnp.sqrt(jnp.sum(xd ** 2, axis=0))
    z = xd / norm
    return jnp.einsum('tvs,tvr->srv', z, z)


@jax.jit
def _pearson_rows(x, y):
    """Correlate rows of x [A, T] with rows of y [B, T] -> [A, B]."""
    xd = x - jnp.mean(x, axis=1, keepdims=True)
    yd = y - jnp.mean(y, axis=1, keepdims=True)
    xn = xd / jnp.sqrt(jnp.sum(xd ** 2, axis=1, keepdims=True))
    yn = yd / jnp.sqrt(jnp.sum(yd ** 2, axis=1, keepdims=True))
    return xn @ yn.T


@partial(jax.jit, static_argnames=("symmetric",))
def _isfc_loo_core(data, target_means, symmetric=True):
    """Leave-one-out ISFC matrices for all subjects in one program.

    data, target_means: [T, V, S] / [T, W, S] -> [V, W, S]
    """
    def per_subject(subj, tgt):
        m = _pearson_rows(subj.T, tgt.T)
        return (m + m.T) / 2 if symmetric else m

    return jnp.moveaxis(
        jax.vmap(per_subject, in_axes=(2, 2))(data, target_means), 0, 2)


@jax.jit
def _isfc_pairwise_core(data, idx_i, idx_j):
    """Pairwise symmetrized ISFC matrices, batched over pairs.

    data: [T, V, S]; idx_i/idx_j: [P] -> [V, V, P]
    """
    def per_pair(i, j):
        m = _pearson_rows(data[..., i].T, data[..., j].T)
        return (m + m.T) / 2

    return jnp.moveaxis(jax.vmap(per_pair)(idx_i, idx_j), 0, 2)


# ---------------------------------------------------------------------------
# public API

def isc(data, pairwise=False, summary_statistic=None, tolerate_nans=True,
        mesh=None):
    """Intersubject correlation per voxel (reference isc.py:81-210).

    Leave-one-out (default) or pairwise; optional 'mean'/'median' summary.

    mesh : optional :class:`jax.sharding.Mesh` with a ``'voxel'`` axis —
        the [T, V, S] stack is then sharded along voxels (every per-voxel
        correlation is independent, so XLA partitions the whole program
        with no collectives).  Ignored for the 2-subject host path.
    """
    data, n_TRs, n_voxels, n_subjects = _check_timeseries_input(data)
    if n_subjects == 2:
        summary_statistic = None
    data, mask = _threshold_nans(data, tolerate_nans)
    n_kept = data.shape[1]

    if n_subjects == 2:
        from .utils.utils import array_correlation
        iscs_stack = array_correlation(data[..., 0],
                                       data[..., 1])[np.newaxis, :]
    elif pairwise:
        corr = fetch_replicated(
            _isc_pairwise_core(_shard_voxels(data, mesh, 1)),
            mesh)[..., :n_kept]
        iu = np.triu_indices(n_subjects, k=1)
        iscs_stack = corr[iu[0], iu[1], :]
    else:
        iscs_stack = fetch_replicated(_isc_loo_core(
            _shard_voxels(data, mesh, 1), bool(tolerate_nans)),
            mesh)[:, :n_kept]

    iscs = np.full((iscs_stack.shape[0], n_voxels), np.nan)
    iscs[:, np.where(mask)[0]] = iscs_stack

    if summary_statistic:
        iscs = compute_summary_statistic(
            iscs, summary_statistic=summary_statistic, axis=0)[np.newaxis, :]
    if iscs.shape[0] == 1:
        iscs = iscs[0]
    return iscs


def _check_targets_input(targets, data):
    """Standardize optional ISFC targets (reference isc.py:430-481)."""
    if isinstance(targets, (np.ndarray, list)):
        targets, n_TRs, n_voxels, n_subjects = (
            _check_timeseries_input(targets))
        if data.shape[0] != n_TRs:
            raise ValueError("Targets array must have same number of "
                             "TRs as input data")
        if data.shape[2] != n_subjects:
            raise ValueError("Targets array must have same number of "
                             "subjects as input data")
        symmetric = False
    else:
        targets = data
        n_TRs, n_voxels, n_subjects = data.shape
        symmetric = True
    return targets, n_TRs, n_voxels, n_subjects, symmetric


def isfc(data, targets=None, pairwise=False, summary_statistic=None,
         vectorize_isfcs=True, tolerate_nans=True, mesh=None):
    """Intersubject functional correlation (reference isc.py:211-370).

    Correlates each subject's voxel time series with (a) the average of the
    other subjects' series (leave-one-out), or (b) each other subject's
    series (pairwise); optionally against a separate ``targets`` array.

    mesh : optional :class:`jax.sharding.Mesh` with a ``voxel`` axis — the
        leave-one-out V×V matrices are then computed by the SUMMA ring
        (:func:`brainiak_tpu.ops.distla.summa_gram`, the pod-scale
        primitive :func:`brainiak_tpu.ops.ring.ring_correlation` is also
        built on) with the voxel axis sharded around the ring (O(V/n)
        per-device memory), for voxel counts too large to replicate per
        device.  Requires > 2 subjects, leave-one-out mode, targets with
        the same voxel count as data, and the post-NaN-threshold voxel
        count divisible by the mesh axis.
    """
    data, n_TRs, n_voxels, n_subjects = _check_timeseries_input(data)
    targets, t_n_TRs, t_n_voxels, _, symmetric = (
        _check_targets_input(targets, data))
    if not symmetric:
        pairwise = False
    data, mask = _threshold_nans(data, tolerate_nans)
    targets, targets_mask = _threshold_nans(targets, tolerate_nans)

    if symmetric and n_subjects == 2:
        if mesh is not None:
            raise ValueError("mesh-sharded ISFC requires more than 2 "
                             "subjects (the 2-subject case has no "
                             "leave-one-out mean)")
        m = np.asarray(_pearson_rows(jnp.asarray(data[..., 0].T),
                                     jnp.asarray(data[..., 1].T)))
        isfcs = ((m + m.T) / 2)[..., np.newaxis]
        summary_statistic = None
    elif pairwise:
        if mesh is not None:
            raise ValueError("mesh-sharded ISFC only supports "
                             "leave-one-out (pairwise=False)")
        iu = np.triu_indices(n_subjects, k=1)
        isfcs = np.asarray(_isfc_pairwise_core(
            jnp.asarray(data), jnp.asarray(iu[0]), jnp.asarray(iu[1])))
    elif mesh is not None:
        from .ops.distla import summa_gram
        if data.shape[1] != targets.shape[1]:
            raise ValueError("mesh-sharded ISFC requires targets with the "
                             "same voxel count as data")
        n_shards = mesh.shape["voxel"]
        if data.shape[1] % n_shards != 0:
            raise ValueError(
                f"mesh-sharded ISFC requires the voxel count after NaN "
                f"thresholding ({data.shape[1]} of {n_voxels} input "
                f"voxels) to be divisible by the mesh 'voxel' axis "
                f"size ({n_shards})")
        target_means = _loo_means_core(jnp.asarray(targets),
                                       bool(tolerate_nans))
        data_j = jnp.asarray(data)
        per_subj = []
        for s in range(n_subjects):
            # the slab product itself is the distla SUMMA primitive:
            # one nearest-neighbor ring over the voxel axis, row-
            # sharded output that _fetch_ring_matrix assembles slab
            # by slab without ever replicating [V, V] on a device
            m = _fetch_ring_matrix(summa_gram(
                data_j[..., s], mesh, data_b=target_means[..., s],
                axis_names=(DEFAULT_VOXEL_AXIS,)),
                mesh)
            per_subj.append((m + m.T) / 2 if symmetric else m)
        isfcs = np.stack(per_subj, axis=2)
    else:
        target_means = _loo_means_core(jnp.asarray(targets),
                                       bool(tolerate_nans))
        isfcs = np.asarray(_isfc_loo_core(
            jnp.asarray(data), target_means, symmetric=symmetric))

    isfcs_all = np.full((n_voxels, t_n_voxels, isfcs.shape[2]), np.nan)
    isfcs_all[np.ix_(np.where(mask)[0], np.where(targets_mask)[0])] = isfcs
    isfcs = np.moveaxis(isfcs_all, 2, 0)

    if summary_statistic:
        isfcs = compute_summary_statistic(
            isfcs, summary_statistic=summary_statistic, axis=0)
    if isfcs.shape[0] == 1:
        isfcs = isfcs[0]
    if vectorize_isfcs and symmetric:
        return squareform_isfc(isfcs)
    return isfcs


# ---------------------------------------------------------------------------
# resampling statistics

def _reinsert_nan_voxels(observed, distribution, mask, n_voxels):
    """Restore NaN columns for voxels excluded by _threshold_nans so output
    stays positionally aligned with the input voxel axis."""
    if np.all(mask):
        return observed, distribution
    idx = np.where(mask)[0]
    obs_full = np.full(observed.shape[:-1] + (n_voxels,), np.nan)
    obs_full[..., idx] = observed
    dist_full = np.full(distribution.shape[:-1] + (n_voxels,), np.nan)
    dist_full[..., idx] = distribution
    return obs_full, dist_full


def _resolve_seed(random_state):
    if isinstance(random_state, np.random.RandomState):
        return int(random_state.randint(0, MAX_RANDOM_SEED))
    if random_state is None:
        return int(np.random.randint(0, MAX_RANDOM_SEED))
    return int(random_state)


# -- null distributions ---------------------------------------------------
# The resampling loops live in brainiak_tpu.stats: each family is ONE
# counted_cache'd vmapped program (stats.surrogates) driven chunked /
# resumable / mergeable by stats.engine.NullEngine.  The wrappers below
# keep the long-standing *_isc signatures and, at matched seeds, return
# bit-identical distributions to the pre-engine versions (same key
# schedule: split once over all planned resamples).


def _null_engine(mesh, null_batch_size):
    from .stats.engine import NullEngine
    return NullEngine(mesh=mesh, null_batch_size=null_batch_size)


def _reinsert_nan_p(observed, p, mask, n_voxels, n_samples):
    """Accumulator-mode counterpart of _reinsert_nan_voxels: excluded
    voxels get the legacy all-NaN-column p of ``1 / (n + 1)`` (every
    NaN comparison counts as a non-exceedance)."""
    if np.all(mask):
        return observed, p
    idx = np.where(mask)[0]
    obs_full = np.full(observed.shape[:-1] + (n_voxels,), np.nan)
    obs_full[..., idx] = observed
    p_full = np.full(np.shape(p)[:-1] + (n_voxels,),
                     1.0 / (n_samples + 1))
    p_full[..., idx] = p
    return obs_full, p_full


def bootstrap_isc(iscs, pairwise=False, summary_statistic='median',
                  n_bootstraps=1000, ci_percentile=95, side='right',
                  random_state=None, mesh=None, null_batch_size=None,
                  return_distribution=True):
    """Subject-wise bootstrap test for ISCs (reference isc.py:649-810).

    Resamples subjects with replacement, shifts the bootstrap distribution
    by the observed statistic (Hall & Wilson 1991), and returns
    (observed, ci, p, distribution).

    mesh : optional Mesh with a ``'voxel'`` axis — shards the voxel
        dimension of the resampling program.
    null_batch_size : resamples evaluated per device dispatch (the
        vmap-chunk size); default
        :func:`brainiak_tpu.stats.engine.default_null_batch`.
    return_distribution : when False the ``[n_bootstraps, V]`` null is
        never materialized — p and CI come from the engine's mergeable
        accumulator (CI to sketch accuracy) and the returned
        distribution is None.
    """
    iscs, n_subjects, n_voxels = _check_isc_input(iscs, pairwise=pairwise)
    if summary_statistic not in ('mean', 'median'):
        raise ValueError("Summary statistic must be 'mean' or 'median'")

    observed = compute_summary_statistic(
        iscs, summary_statistic=summary_statistic, axis=0)

    engine = _null_engine(mesh, null_batch_size)
    result = engine.run(
        iscs, "subject_bootstrap", n_bootstraps,
        statistic=summary_statistic, side=side,
        seed=_resolve_seed(random_state), pairwise=pairwise,
        observed=observed, center=observed,
        return_distribution=return_distribution)

    if return_distribution:
        distribution = result.distribution
        ci = (np.percentile(distribution, (100 - ci_percentile) / 2,
                            axis=0),
              np.percentile(distribution,
                            ci_percentile + (100 - ci_percentile) / 2,
                            axis=0))
        shifted = distribution - observed
        p = p_from_null(observed, shifted, side=side, exact=False,
                        axis=0)
        return observed, ci, p, distribution
    # accumulator mode: exceedance counts of (null - observed), i.e.
    # exactly the Hall & Wilson shifted comparison, without the array
    p = result.p_values(side=side, exact=False)
    ci = result.ci(ci_percentile)
    return observed, ci, p, None


def _check_group_assignment(group_assignment, n_subjects):
    if isinstance(group_assignment, list):
        group_assignment = np.array(group_assignment)
    if group_assignment is not None and \
            len(group_assignment) != n_subjects:
        raise ValueError("Group assignments ({0}) do not match number of "
                         "subjects ({1})!".format(len(group_assignment),
                                                  n_subjects))
    return group_assignment


def permutation_isc(iscs, group_assignment=None, pairwise=False,
                    summary_statistic='median', n_permutations=1000,
                    side='right', random_state=None, mesh=None,
                    null_batch_size=None, return_distribution=True):
    """Group-label permutation test for ISCs (reference isc.py:1057-1251).

    One group: sign-flipping (exact when 2**N <= n_permutations).  Two
    groups: group-assignment shuffling (exact when N! <= n_permutations).
    Returns (observed, p, distribution).

    mesh / null_batch_size / return_distribution : see
    :func:`bootstrap_isc`.
    """
    iscs, n_subjects, n_voxels = _check_isc_input(iscs, pairwise=pairwise)
    if summary_statistic not in ('mean', 'median'):
        raise ValueError("Summary statistic must be 'mean' or 'median'")
    group_assignment = _check_group_assignment(group_assignment, n_subjects)

    labels = (np.unique(group_assignment)
              if group_assignment is not None else np.array([0]))
    n_groups = len(labels)
    if n_groups > 2:
        raise ValueError("This test is not valid for more than "
                         "2 groups! (got {0})".format(n_groups))

    family = "sign_flip" if n_groups == 1 else "group_shuffle"
    engine = _null_engine(mesh, null_batch_size)
    result = engine.run(
        iscs, family, n_permutations, statistic=summary_statistic,
        side=side, seed=_resolve_seed(random_state), pairwise=pairwise,
        group_assignment=group_assignment,
        return_distribution=return_distribution)

    observed = result.observed
    if return_distribution:
        distribution = result.distribution
        p = p_from_null(observed, distribution, side=side,
                        exact=result.exact, axis=0)
        return observed, p, distribution
    return observed, result.p_values(side=side), None


def timeshift_isc(data, pairwise=False, summary_statistic='median',
                  n_shifts=1000, side='right', tolerate_nans=True,
                  random_state=None, mesh=None, null_batch_size=None,
                  return_distribution=True):
    """Circular time-shift null for ISC (reference isc.py:1253-1410).

    Returns (observed, p, distribution).
    mesh / null_batch_size / return_distribution : see
    :func:`bootstrap_isc`."""
    data, n_TRs, n_voxels, n_subjects = _check_timeseries_input(data)
    data, mask = _threshold_nans(data, tolerate_nans)

    observed = isc(data, pairwise=pairwise,
                   summary_statistic=summary_statistic,
                   tolerate_nans=tolerate_nans, mesh=mesh)

    engine = _null_engine(mesh, null_batch_size)
    result = engine.run(
        data, "circular_timeshift", n_shifts,
        statistic=summary_statistic, side=side,
        seed=_resolve_seed(random_state), pairwise=pairwise,
        tolerate_nans=tolerate_nans, observed=observed,
        return_distribution=return_distribution)

    if return_distribution:
        observed, distribution = _reinsert_nan_voxels(
            observed, result.distribution, mask, n_voxels)
        p = p_from_null(observed, distribution, side=side, exact=False,
                        axis=0)
        return observed, p, distribution
    observed, p = _reinsert_nan_p(
        observed, result.p_values(side=side), mask, n_voxels, result.n)
    return observed, p, None


def phaseshift_isc(data, pairwise=False, summary_statistic='median',
                   n_shifts=1000, voxelwise=False, side='right',
                   tolerate_nans=True, random_state=None, mesh=None,
                   null_batch_size=None, return_distribution=True):
    """Phase-randomization null for ISC (reference isc.py:1410-1551).

    Returns (observed, p, distribution).
    mesh / null_batch_size / return_distribution : see
    :func:`bootstrap_isc`."""
    data, n_TRs, n_voxels, n_subjects = _check_timeseries_input(data)
    data, mask = _threshold_nans(data, tolerate_nans)

    observed = isc(data, pairwise=pairwise,
                   summary_statistic=summary_statistic,
                   tolerate_nans=tolerate_nans, mesh=mesh)

    engine = _null_engine(mesh, null_batch_size)
    result = engine.run(
        data, "phase_randomize", n_shifts, statistic=summary_statistic,
        side=side, seed=_resolve_seed(random_state), pairwise=pairwise,
        voxelwise=voxelwise, tolerate_nans=tolerate_nans,
        observed=observed, return_distribution=return_distribution)

    if return_distribution:
        observed, distribution = _reinsert_nan_voxels(
            observed, result.distribution, mask, n_voxels)
        p = p_from_null(observed, distribution, side=side, exact=False,
                        axis=0)
        return observed, p, distribution
    observed, p = _reinsert_nan_p(
        observed, result.p_values(side=side), mask, n_voxels, result.n)
    return observed, p, None
