"""Resampling-statistics engine: surrogate families, mergeable null
accumulators, and the chunked/resumable/poolable :class:`NullEngine`.

Submodules
----------
- :mod:`.pvalues`    p-value / summary-statistic conventions (NumPy-only)
- :mod:`.surrogates` surrogate-family registry + vmapped programs
- :mod:`.accum`      mergeable null accumulators (counts/moments/quantiles)
- :mod:`.engine`     :class:`NullEngine` + :class:`NullDistribution`

Attribute access is lazy (PEP 562): importing
``brainiak_tpu.stats.pvalues`` alone never pulls in jax, so the host
shims in ``utils.utils`` stay light.
"""

__all__ = [
    "FAMILIES",
    "NullAccumulator",
    "NullDistribution",
    "NullEngine",
    "TRANSFORMS",
    "compute_summary_statistic",
    "default_null_batch",
    "fdr_threshold",
    "make_spec",
    "p_from_null",
    "stats_budget_bytes",
]

_EXPORTS = {
    "FAMILIES": ".surrogates",
    "TRANSFORMS": ".surrogates",
    "make_spec": ".surrogates",
    "NullAccumulator": ".accum",
    "fdr_threshold": ".accum",
    "NullDistribution": ".engine",
    "NullEngine": ".engine",
    "default_null_batch": ".engine",
    "stats_budget_bytes": ".engine",
    "compute_summary_statistic": ".pvalues",
    "p_from_null": ".pvalues",
}


def __getattr__(name):
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(
            "module {!r} has no attribute {!r}".format(__name__, name))
    from importlib import import_module
    return getattr(import_module(target, __name__), name)


def __dir__():
    return sorted(set(globals()) | set(__all__))
