"""Host-side p-value and summary-statistic conventions.

Canonical home of the resampling-inference scalar conventions that the
rest of the repo (``isc``, ``utils.utils``, :mod:`.accum`,
:mod:`.engine`, the served ``null_threshold`` op) must agree on
bit-for-bit:

- :func:`p_from_null` — the exact-test numerator uses the *raw*
  exceedance count (``numerator / n_samples``); the sampled test adds
  the observed statistic to both numerator and denominator
  (``(numerator + 1) / (n_samples + 1)``, Phipson & Smyth 2010).
- :func:`compute_summary_statistic` — 'mean' is the Fisher-z
  (arctanh) average mapped back through tanh; 'median' is the plain
  NaN-aware median.

Moved here from ``utils.utils`` / ``isc`` (which keep re-export
shims) so :mod:`brainiak_tpu.stats` can depend on them without
importing the heavier host modules.  Everything here is NumPy-only.
"""

import numpy as np

__all__ = [
    "compute_summary_statistic",
    "exceedance_counts",
    "p_from_counts",
    "p_from_null",
]


def compute_summary_statistic(iscs, summary_statistic='mean', axis=None):
    """'mean' (Fisher-z averaged) or 'median' of ISC values
    (reference isc.py:483-527)."""
    if summary_statistic not in ('mean', 'median'):
        raise ValueError("Summary statistic must be 'mean' or 'median'")
    if summary_statistic == 'mean':
        return np.tanh(np.nanmean(np.arctanh(iscs), axis=axis))
    return np.nanmedian(iscs, axis=axis)


def exceedance_counts(observed, distribution, axis=0):
    """Per-element exceedance counts of ``observed`` vs a null chunk.

    Returns ``(ge, le, abs_ge)`` — the three integer numerators
    :func:`p_from_null` can be rebuilt from for any ``side``.  Counts
    sum exactly over disjoint chunks of the null axis, which is the
    whole basis of the mergeable accumulator contract
    (:class:`brainiak_tpu.stats.accum.NullAccumulator`).
    """
    distribution = np.asarray(distribution)
    ge = np.sum(distribution >= observed, axis=axis)
    le = np.sum(distribution <= observed, axis=axis)
    abs_ge = np.sum(np.abs(distribution) >= np.abs(observed), axis=axis)
    return ge, le, abs_ge


def p_from_counts(numerator, n_samples, exact=False):
    """The shared count -> p-value map.

    ``exact`` uses the raw count over the full enumeration
    (``numerator / n_samples``); otherwise the observed statistic
    joins the null (``(numerator + 1) / (n_samples + 1)``).  This is
    the single definition both :func:`p_from_null` and the
    accumulators route through, so chunked counts reproduce the
    monolithic p-map bit-for-bit.
    """
    numerator = np.asarray(numerator)
    if exact:
        return numerator / n_samples
    return (numerator + 1) / (n_samples + 1)


def p_from_null(observed, distribution, side='two-sided', exact=False,
                axis=None):
    """p-value of an observed statistic under a resampling null distribution.

    Adjusts for the observed statistic unless ``exact`` (Phipson & Smyth
    2010).  Reference contract: utils/utils.py:804-872.
    """
    if side not in ('two-sided', 'left', 'right'):
        raise ValueError("The value for 'side' must be either "
                         "'two-sided', 'left', or 'right', got {0}".
                         format(side))
    distribution = np.asarray(distribution)
    n_samples = len(distribution)

    if side == 'two-sided':
        numerator = np.sum(np.abs(distribution) >= np.abs(observed),
                           axis=axis)
    elif side == 'left':
        numerator = np.sum(distribution <= observed, axis=axis)
    else:
        numerator = np.sum(distribution >= observed, axis=axis)

    return p_from_counts(numerator, n_samples, exact=exact)
