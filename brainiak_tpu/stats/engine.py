"""Population-scale resampling-statistics engine.

:class:`NullEngine` drives thousands of null resamples per dispatch
through the :mod:`.surrogates` family programs, folding each chunk
into the mergeable :class:`~brainiak_tpu.stats.accum.NullAccumulator`
instead of materializing the ``[n_resamples, V]`` null (unless the
small-N ``return_distribution=True`` path asks for it).  The null
axis is chunked whenever ``n_resamples * V`` exceeds the device
budget (``BRAINIAK_TPU_STATS_BUDGET_BYTES``), the loop runs under
:func:`~brainiak_tpu.resilience.guards.run_resilient_loop` so a
preempted run resumes at the last completed null chunk
(fingerprint = data digest + family + seed + grid), and every chunk
emits a ``stats.chunk`` span plus ``stats_surrogates_total``.

Disjoint-range pooling: two runs over disjoint ``index_range``s of
the SAME (data, family, seed) slice the same key schedule, so their
:class:`NullDistribution` results ``merge()`` to exactly the
single-run verdict — across the serialized wire format.
"""

import logging
import os
import zlib

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..resilience.guards import array_digest, run_resilient_loop
from .accum import NullAccumulator
from .surrogates import FAMILIES, make_spec

__all__ = [
    "DEFAULT_BUDGET_BYTES",
    "NullDistribution",
    "NullEngine",
    "default_null_batch",
    "stats_budget_bytes",
]

logger = logging.getLogger(__name__)

#: default per-run materialization budget (bytes) when
#: ``BRAINIAK_TPU_STATS_BUDGET_BYTES`` is unset: 256 MiB.
DEFAULT_BUDGET_BYTES = 1 << 28

#: state leaves that are NaN-bearing by design (uncovered resample
#: slots, NaN voxel columns) and must skip the non-finite guard.
_NAN_LEAVES = ("observed", "center", "max_stat", "dist")


def stats_budget_bytes():
    """The configured null-materialization budget in bytes."""
    return int(os.environ.get("BRAINIAK_TPU_STATS_BUDGET_BYTES",
                              DEFAULT_BUDGET_BYTES))


def default_null_batch(n_elements=None):
    """The unified ``null_batch_size`` default, sized from the device
    budget.

    ``n_elements`` is the per-resample working-set element count (the
    ISC stack's ``V`` for the ISC-resampling families, ``T * V * S``
    for the shift families).  The batch is the largest power of two
    whose f32 working set (``batch * n_elements * 4`` bytes) stays
    within 1/8 of :func:`stats_budget_bytes`, clamped to [16, 64] —
    reproducing the old per-function defaults (64 for cheap ISC
    resamples, 16 for the heavy shift families) from one rule.
    """
    if n_elements is None:
        return 64
    budget = stats_budget_bytes()
    per_resample = 4 * max(1, int(n_elements))
    lanes = budget // (8 * per_resample)
    if lanes >= 64:
        return 64
    if lanes <= 16:
        return 16
    return 1 << (int(lanes).bit_length() - 1)


def _chunk_length(n_voxels, batch, budget):
    """Resamples per chunk: the materialized per-chunk null block
    ``[chunk, V]`` (one f32 device copy + one f64 host copy + integer
    accumulator updates) is held to the budget, rounded down to a
    whole number of ``batch``-size dispatch lanes."""
    per_resample = 16 * max(1, int(n_voxels))
    chunk = int(budget) // per_resample
    chunk = (chunk // batch) * batch
    return max(batch, chunk)


class NullDistribution:
    """The engine's result: observed statistic + mergeable null
    summary, and the persistable ``serve_kind="null_distribution"``
    artifact (:mod:`brainiak_tpu.serve.artifacts`).

    ``distribution`` is the materialized ``[n_total, V]`` null (rows
    outside the run's covered index range are NaN) when the run asked
    for ``return_distribution=True``; ``None`` otherwise.
    """

    def __init__(self, family, statistic, seed, side, exact,
                 observed, accumulator, distribution=None,
                 thresholds=None):
        self.family = family
        self.statistic = statistic
        self.seed = None if seed is None else int(seed)
        self.side = side
        self.exact = bool(exact)
        self.observed = np.asarray(observed)
        self.accumulator = accumulator
        self.distribution = distribution
        self.thresholds = dict(thresholds or {})

    @property
    def n_total(self):
        return self.accumulator.n_total

    @property
    def n(self):
        return self.accumulator.n

    @property
    def complete(self):
        return self.accumulator.complete

    def p_values(self, side=None, exact=None):
        return self.accumulator.p_values(
            side=self.side if side is None else side,
            exact=self.exact if exact is None else exact)

    def ci(self, ci_percentile=95):
        return self.accumulator.ci(ci_percentile)

    def fwer_threshold(self, alpha=0.05):
        return self.accumulator.fwer_threshold(alpha)

    def fdr_threshold(self, alpha=0.05):
        return self.accumulator.fdr_threshold(
            alpha, side=self.side, exact=self.exact)

    def compute_thresholds(self, alphas=(0.05, 0.01)):
        """Precompute FWER/FDR thresholds (stored on the artifact so
        the served lookup never re-derives them)."""
        for alpha in alphas:
            self.thresholds["fwer_{:g}".format(alpha)] = \
                self.fwer_threshold(alpha)
            self.thresholds["fdr_{:g}".format(alpha)] = \
                self.fdr_threshold(alpha)
        return self.thresholds

    def merge(self, other):
        """Pool a disjoint-range run into this one, in place —
        counts, histograms, and max-statistic slots merge exactly
        (see :meth:`NullAccumulator.merge`)."""
        if (self.family, self.statistic, self.seed, self.side,
                self.exact) != (other.family, other.statistic,
                                other.seed, other.side, other.exact):
            raise ValueError("cannot merge null distributions from "
                             "different runs")
        before = self.accumulator.covered.astype(bool).copy()
        self.accumulator.merge(other.accumulator)
        if self.distribution is not None:
            if other.distribution is None:
                self.distribution = None
            else:
                rows = other.accumulator.covered.astype(bool) & ~before
                self.distribution[rows] = other.distribution[rows]
        return self


class NullEngine:
    """Chunked, resumable, poolable null-distribution runner.

    Parameters
    ----------
    mesh : optional Mesh with a ``'voxel'`` axis — surrogate programs
        run voxel-sharded (the ``_shard_voxels`` placement idiom).
    null_batch_size : resamples per ``lax.map`` dispatch lane inside a
        chunk; default :func:`default_null_batch`.
    budget_bytes : override of ``BRAINIAK_TPU_STATS_BUDGET_BYTES``.
    """

    def __init__(self, mesh=None, null_batch_size=None,
                 budget_bytes=None):
        self.mesh = mesh
        self.null_batch_size = null_batch_size
        self.budget_bytes = (stats_budget_bytes()
                             if budget_bytes is None
                             else int(budget_bytes))

    def run(self, data, family, n_resamples, statistic='median', *,
            side='right', seed=0, pairwise=False,
            group_assignment=None, voxelwise=False, tolerate_nans=True,
            observed=None, center=None, index_range=None,
            return_distribution=False, checkpoint_dir=None,
            checkpoint_every=1, quantile_accuracy=None):
        """Evaluate ``n_resamples`` nulls of ``family`` over ``data``.

        ``observed`` defaults to the family's own observed statistic;
        ``center`` (e.g. the observed value, for the Hall & Wilson
        bootstrap shift) is subtracted from every null before
        exceedance counting.  ``index_range=(lo, hi)`` restricts this
        run to a slice of the global resample index space — the
        pooling hook: disjoint-range results ``merge()`` exactly.
        ``checkpoint_dir`` / ``checkpoint_every`` (in chunks) persist
        the accumulator so a preempted run resumes at the last
        completed null chunk.
        """
        if family not in FAMILIES:
            raise ValueError(
                "Unknown surrogate family {!r}; registered families: "
                "{}".format(family, ", ".join(FAMILIES)))
        spec = make_spec(
            family, data, statistic=statistic,
            n_resamples=n_resamples, seed=seed, pairwise=pairwise,
            group_assignment=group_assignment, voxelwise=voxelwise,
            tolerate_nans=tolerate_nans, mesh=self.mesh,
            null_batch_size=self.null_batch_size)
        n_total = spec.n_total
        lo, hi = (0, n_total) if index_range is None else (
            int(index_range[0]), int(index_range[1]))
        if not 0 <= lo < hi <= n_total:
            raise ValueError("index_range {} outside [0, {}]".format(
                (lo, hi), n_total))

        if observed is None:
            observed = spec.compute_observed()
        observed = np.asarray(observed)

        batch = (self.null_batch_size
                 if self.null_batch_size is not None
                 else default_null_batch(spec.n_voxels))
        chunk_len = _chunk_length(spec.n_voxels, batch,
                                  self.budget_bytes)
        # never pad past the requested range: one whole-range chunk
        # (rounded up to full dispatch lanes) is the floor
        chunk_len = min(chunk_len, -(-(hi - lo) // batch) * batch)
        n_chunks = -(-(hi - lo) // chunk_len)
        acc_kwargs = {}
        if quantile_accuracy is not None:
            acc_kwargs["quantile_accuracy"] = float(quantile_accuracy)

        # Materialize the null at a dtype that stores the compiled
        # program's values EXACTLY (f64 under x64, f32 on device):
        # a lossy cast would let a tie round across ``observed`` and
        # flip an exceedance count between the counted and the
        # materialized p-map, and in exact enumeration would drop
        # the identity resample's self-tie (the p >= 1/n guarantee).
        dist_dtype = np.result_type(np.asarray(observed).dtype,
                                    np.float32)

        def fresh_state():
            acc = NullAccumulator(observed, n_total, center=center,
                                  shape=(spec.n_voxels,), **acc_kwargs)
            state = acc.to_state()
            if return_distribution:
                state["dist"] = np.full(
                    (n_total, spec.n_voxels), np.nan,
                    dtype=dist_dtype)
            return acc, state

        acc0, init_state = fresh_state()
        fingerprint = self._fingerprint(
            data, spec, statistic, seed, lo, hi, chunk_len, batch)

        carry = {}

        def run_chunk(state, step, n_steps):
            if carry.get("step") == step:
                acc = carry["acc"]
                dist = carry.get("dist")
            else:
                acc = NullAccumulator.from_state(state)
                # host-to-host copy (state leaves are numpy, fresh or
                # checkpoint-restored) so rollback keeps the prior
                # chunk's rows; astype(copy=True) rather than
                # np.array to keep the chunk body sync-free (JX002)
                dist = (state["dist"].astype(dist_dtype, copy=True)
                        if return_distribution else None)
            for i in range(step, step + n_steps):
                c_lo = lo + i * chunk_len
                c_hi = min(c_lo + chunk_len, hi)
                xs_chunk = spec.xs[c_lo:c_hi]
                pad = chunk_len - (c_hi - c_lo)
                if pad:
                    # pad to the compiled chunk extent (one program
                    # per family); pad rows are sliced off below
                    xs_chunk = np.concatenate(
                        [xs_chunk,
                         np.repeat(xs_chunk[:1], pad, axis=0)])
                with obs_spans.span(
                        "stats.chunk",
                        attrs={"family": family, "lo": c_lo,
                               "hi": c_hi}):
                    values = spec.run(xs_chunk)[:c_hi - c_lo]
                acc.update(values, (c_lo, c_hi))
                if dist is not None:
                    dist[c_lo:c_hi] = values
                obs_metrics.counter(
                    "stats_surrogates_total",
                    help="null surrogates evaluated").inc(
                        c_hi - c_lo, family=family)
            new_state = acc.to_state()
            if dist is not None:
                new_state["dist"] = dist
            carry["step"] = step + n_steps
            carry["acc"] = acc
            carry["dist"] = dist
            return new_state, False

        state, _ = run_resilient_loop(
            run_chunk, init_state, n_chunks,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            fingerprint=fingerprint, name="stats",
            guard_skip=_NAN_LEAVES)

        acc = NullAccumulator.from_state(state)
        dist = (np.array(state["dist"], dtype=dist_dtype)
                if return_distribution else None)
        result = NullDistribution(
            family, statistic, seed, side, spec.exact, observed, acc,
            distribution=dist)
        if result.complete:
            result.compute_thresholds()
        return result

    @staticmethod
    def _fingerprint(data, spec, statistic, seed, lo, hi, chunk_len,
                     batch):
        flat = np.nan_to_num(np.asarray(data, dtype=float))
        return np.asarray([
            array_digest(flat),
            array_digest(np.asarray(spec.xs, dtype=float)),
            float(zlib.crc32(spec.family.encode())),
            float(zlib.crc32(str(statistic).encode())),
            float(-1 if seed is None else int(seed)),
            float(spec.n_total), float(lo), float(hi),
            float(chunk_len), float(batch),
        ], dtype=float)
