"""Mergeable null-distribution accumulators.

Replaces the materialized ``[n_resamples, V]`` null array with
fixed-size per-voxel state that (a) reproduces
:func:`~brainiak_tpu.stats.pvalues.p_from_null` **bit-for-bit** from
integer exceedance counts, (b) carries streaming moments, (c) holds
per-voxel quantile state for CIs / cluster thresholds as a vectorized
log-bucket histogram following the exact-bucket-merge idiom of
:class:`brainiak_tpu.obs.sketch.QuantileSketch`, and (d) tracks the
per-resample max statistic for max-statistic FWER control.

The pooling contract: every piece of state merges by integer addition
(counts, histograms) or disjoint-slice fill (max statistic), so two
half-runs over disjoint resample-index ranges ``merge()`` to EXACTLY
the verdict — p-values, CI bounds, thresholds — of one full run.  The
wire format (:meth:`NullAccumulator.to_state` / ``save`` / ``to_json``)
is a flat dict of plain NumPy arrays, so it round-trips through
``np.savez(allow_pickle=False)``, JSON, and the resilient-loop
checkpointer unchanged.

Memory model: state is ``O((2 K + c) · V)`` integers with
``K = O(log(max_magnitude / min_magnitude) / quantile_accuracy)``
histogram buckets per sign — independent of ``n_resamples``.
"""

import json
import math

import numpy as np

__all__ = ["NullAccumulator", "fdr_threshold"]

#: wire-format version stamped into serialized state.
WIRE_VERSION = 1

#: quantile relative-accuracy default (DDSketch alpha); CI bounds from
#: the accumulator are exact-in-rank, alpha-relative in value.
DEFAULT_QUANTILE_ACCURACY = 0.01

#: magnitudes below this collapse into the single "zero" bucket; above
#: the max they clip into the top bucket.  Defaults cover correlation-
#: scale statistics (|r| <= 1, differences <= 2) with wide margin.
DEFAULT_MIN_MAGNITUDE = 1e-5
DEFAULT_MAX_MAGNITUDE = 8.0

_CONFIG_KEYS = ("quantile_accuracy", "min_magnitude", "max_magnitude")


def fdr_threshold(p_values, alpha=0.05):
    """Benjamini-Hochberg step-up p-value cutoff.

    Returns the largest p among the finite input p-values that
    survives the step-up criterion (``p_(k) <= k/m * alpha``), or
    ``0.0`` when nothing survives.  Voxels with ``p <=`` the returned
    cutoff are the FDR-controlled discoveries.
    """
    p = np.asarray(p_values, dtype=float).ravel()
    p = p[np.isfinite(p)]
    if p.size == 0:
        return 0.0
    p = np.sort(p)
    m = p.size
    crit = (np.arange(1, m + 1) / m) * alpha
    passing = np.nonzero(p <= crit)[0]
    if passing.size == 0:
        return 0.0
    return float(p[passing[-1]])


class NullAccumulator:
    """Streaming, mergeable summary of a null distribution.

    Parameters
    ----------
    observed : array
        Observed statistic; chunks are compared against it (after the
        optional ``center`` shift) exactly as ``p_from_null`` would.
    n_total : int
        Total planned resamples across all pooled runs; sizes the
        per-resample max-statistic track and defines merge coverage.
    center : array, optional
        Subtracted from each chunk before exceedance counting (the
        Hall & Wilson bootstrap shift).  Quantile state always tracks
        the RAW chunk values (bootstrap CIs are percentiles of the
        unshifted distribution).
    """

    def __init__(self, observed, n_total, center=None,
                 quantile_accuracy=DEFAULT_QUANTILE_ACCURACY,
                 min_magnitude=DEFAULT_MIN_MAGNITUDE,
                 max_magnitude=DEFAULT_MAX_MAGNITUDE, shape=None):
        observed = np.asarray(observed, dtype=np.float64)
        self.observed = observed
        self.center = (None if center is None
                       else np.asarray(center, dtype=np.float64))
        self.n_total = int(n_total)
        self.quantile_accuracy = float(quantile_accuracy)
        self.min_magnitude = float(min_magnitude)
        self.max_magnitude = float(max_magnitude)
        # per-resample statistic shape (chunk values are [n, *shape]).
        # When not given, derived from the observed statistic with
        # leading broadcast axes squeezed — pass it explicitly when the
        # observed carries a genuine leading axis of size 1.
        if shape is None:
            shape = tuple(observed.shape)
            while shape and shape[0] == 1:
                shape = shape[1:]
        self.shape = tuple(int(s) for s in shape)
        shape = self.shape

        self._gamma = ((1.0 + self.quantile_accuracy)
                       / (1.0 - self.quantile_accuracy))
        self._log_gamma = math.log(self._gamma)
        self.k_lo = int(math.ceil(
            math.log(self.min_magnitude) / self._log_gamma))
        self.k_hi = int(math.ceil(
            math.log(self.max_magnitude) / self._log_gamma))
        self.n_keys = self.k_hi - self.k_lo + 1

        self.n = 0
        self.ge = np.zeros(shape, dtype=np.int64)
        self.le = np.zeros(shape, dtype=np.int64)
        self.abs_ge = np.zeros(shape, dtype=np.int64)
        self.sum = np.zeros(shape, dtype=np.float64)
        self.sumsq = np.zeros(shape, dtype=np.float64)
        self.n_finite = np.zeros(shape, dtype=np.int64)
        self.pos = np.zeros((self.n_keys,) + shape, dtype=np.int64)
        self.neg = np.zeros((self.n_keys,) + shape, dtype=np.int64)
        self.small = np.zeros(shape, dtype=np.int64)
        self.max_stat = np.full(self.n_total, np.nan)
        self.covered = np.zeros(self.n_total, dtype=np.uint8)

    # -- update -----------------------------------------------------------

    def _bucket_hist(self, values, mask):
        """Per-voxel bucket counts of the selected ``values`` as one
        ``[n_keys, *shape]`` array via a fused bincount (bucket-major
        linear index), the vectorized form of the sketch's per-value
        bucket add."""
        flat_cols = int(np.prod(self.shape, dtype=np.int64)) or 1
        out = np.zeros((self.n_keys, flat_cols), dtype=np.int64)
        if np.any(mask):
            mags = np.abs(values[mask])
            keys = np.ceil(np.log(mags) / self._log_gamma)
            keys = np.clip(keys, self.k_lo, self.k_hi).astype(np.int64)
            cols = np.broadcast_to(
                np.arange(flat_cols).reshape((1,) + self.shape),
                values.shape)[mask].astype(np.int64)
            lin = (keys - self.k_lo) * flat_cols + cols
            out = np.bincount(
                lin, minlength=self.n_keys * flat_cols).reshape(
                    self.n_keys, flat_cols).astype(np.int64)
        return out.reshape((self.n_keys,) + self.shape)

    def update(self, values, index_range):
        """Fold one chunk of null statistics into the accumulator.

        values : ``[n, *shape]`` array of null statistics for resample
        indices ``index_range = (lo, hi)`` (``hi - lo == n``).  Indices
        must not already be covered (by this run or a merged one).
        """
        values = np.asarray(values, dtype=np.float64)
        lo, hi = int(index_range[0]), int(index_range[1])
        if hi - lo != values.shape[0]:
            raise ValueError(
                "index_range {} spans {} resamples but chunk has {}"
                .format((lo, hi), hi - lo, values.shape[0]))
        if lo < 0 or hi > self.n_total:
            raise ValueError("index_range {} outside [0, {})".format(
                (lo, hi), self.n_total))
        if np.any(self.covered[lo:hi]):
            raise ValueError(
                "resample indices [{}, {}) already accumulated".format(
                    lo, hi))

        shifted = values if self.center is None else values - self.center
        self.ge += np.sum(shifted >= self.observed, axis=0)
        self.le += np.sum(shifted <= self.observed, axis=0)
        self.abs_ge += np.sum(
            np.abs(shifted) >= np.abs(self.observed), axis=0)
        self.n += values.shape[0]

        finite = np.isfinite(values)
        self.n_finite += np.sum(finite, axis=0)
        zeroed = np.where(finite, values, 0.0)
        self.sum += np.sum(zeroed, axis=0)
        self.sumsq += np.sum(zeroed * zeroed, axis=0)

        bucketed = finite & (np.abs(values) >= self.min_magnitude)
        self.small += np.sum(finite & ~bucketed, axis=0)
        self.pos += self._bucket_hist(values, bucketed & (values > 0))
        self.neg += self._bucket_hist(values, bucketed & (values < 0))

        per_resample = shifted.reshape(values.shape[0], -1)
        row_finite = np.isfinite(per_resample)
        row_max = np.max(
            np.where(row_finite, per_resample, -np.inf), axis=1)
        self.max_stat[lo:hi] = np.where(
            np.any(row_finite, axis=1), row_max, np.nan)
        self.covered[lo:hi] = 1

    # -- merge / verdicts -------------------------------------------------

    def _config_tuple(self):
        return (self.n_total, self.shape,
                self.quantile_accuracy, self.min_magnitude,
                self.max_magnitude)

    def merge(self, other):
        """Fold a disjoint-range accumulator into this one, in place.

        Exactness: counts and histograms add as integers; the
        max-statistic track fills disjoint slices — so merged state is
        identical to single-run state over the union of ranges.
        """
        if self._config_tuple() != other._config_tuple():
            raise ValueError("cannot merge accumulators with different "
                             "configurations")
        if not np.array_equal(self.observed, other.observed,
                              equal_nan=True):
            raise ValueError("cannot merge accumulators with different "
                             "observed statistics")
        same_center = ((self.center is None) == (other.center is None)
                       and (self.center is None
                            or np.array_equal(self.center, other.center,
                                              equal_nan=True)))
        if not same_center:
            raise ValueError("cannot merge accumulators with different "
                             "center shifts")
        overlap = (self.covered.astype(bool)
                   & other.covered.astype(bool))
        if np.any(overlap):
            raise ValueError(
                "resample ranges overlap at {} indices; pooled runs "
                "must cover disjoint index ranges".format(
                    int(np.sum(overlap))))
        self.n += other.n
        self.ge += other.ge
        self.le += other.le
        self.abs_ge += other.abs_ge
        self.sum += other.sum
        self.sumsq += other.sumsq
        self.n_finite += other.n_finite
        self.pos += other.pos
        self.neg += other.neg
        self.small += other.small
        mask = other.covered.astype(bool)
        self.max_stat[mask] = other.max_stat[mask]
        self.covered |= other.covered
        return self

    @property
    def complete(self):
        return bool(np.all(self.covered))

    def p_values(self, side='right', exact=False):
        """p-map from the integer exceedance counts — bit-for-bit the
        value :func:`~brainiak_tpu.stats.pvalues.p_from_null` returns
        on the materialized distribution."""
        from .pvalues import p_from_counts
        if side == 'two-sided':
            numerator = self.abs_ge
        elif side == 'left':
            numerator = self.le
        elif side == 'right':
            numerator = self.ge
        else:
            raise ValueError("The value for 'side' must be either "
                             "'two-sided', 'left', or 'right', got {0}"
                             .format(side))
        return p_from_counts(numerator, self.n, exact=exact)

    def mean(self):
        with np.errstate(invalid='ignore', divide='ignore'):
            return np.where(self.n_finite > 0,
                            self.sum / np.maximum(self.n_finite, 1),
                            np.nan)

    def variance(self):
        with np.errstate(invalid='ignore', divide='ignore'):
            m = self.sum / np.maximum(self.n_finite, 1)
            v = self.sumsq / np.maximum(self.n_finite, 1) - m * m
            return np.where(self.n_finite > 1, np.maximum(v, 0.0),
                            np.nan)

    def _ordered_counts(self):
        """Histogram rows in ascending-value order with their
        representative values: most-negative bucket first, the
        near-zero bucket in the middle, largest positive last."""
        rep = (2.0 * np.exp(np.arange(self.k_lo, self.k_hi + 1)
                            * self._log_gamma) / (self._gamma + 1.0))
        counts = np.concatenate(
            [self.neg[::-1], self.small[None, ...], self.pos], axis=0)
        values = np.concatenate([-rep[::-1], [0.0], rep])
        return counts, values

    def quantile(self, q):
        """Per-voxel nearest-rank quantile from the bucket histogram
        (value accurate to the configured relative accuracy; rank
        exact, hence exactly merge-stable)."""
        counts, values = self._ordered_counts()
        cum = np.cumsum(counts, axis=0)
        total = self.n_finite
        rank = np.rint(q * np.maximum(total - 1, 0)).astype(np.int64)
        idx = np.sum(cum <= rank, axis=0)
        idx = np.minimum(idx, len(values) - 1)
        out = values[idx]
        return np.where(total > 0, out, np.nan)

    def ci(self, ci_percentile=95):
        """(lower, upper) per-voxel CI bounds at ``ci_percentile``."""
        lo_q = (100.0 - ci_percentile) / 200.0
        hi_q = (ci_percentile + (100.0 - ci_percentile) / 2.0) / 100.0
        return self.quantile(lo_q), self.quantile(hi_q)

    def fwer_threshold(self, alpha=0.05):
        """Max-statistic FWER threshold: the (1 - alpha) nearest-rank
        quantile of the per-resample max-statistic null."""
        vals = self.max_stat[self.covered.astype(bool)]
        vals = vals[np.isfinite(vals)]
        if vals.size == 0:
            return float('nan')
        vals = np.sort(vals)
        idx = min(vals.size - 1,
                  int(math.floor((1.0 - alpha) * vals.size)))
        return float(vals[idx])

    def fdr_threshold(self, alpha=0.05, side='right', exact=False):
        """Benjamini-Hochberg cutoff over this accumulator's p-map."""
        return fdr_threshold(self.p_values(side=side, exact=exact),
                             alpha=alpha)

    # -- wire format ------------------------------------------------------

    def to_state(self):
        """Flat dict of NumPy arrays — the canonical wire format,
        shared by ``np.savez``, JSON, and resilient-loop checkpoints."""
        state = {
            "wire_version": np.asarray(WIRE_VERSION, dtype=np.int64),
            "n_total": np.asarray(self.n_total, dtype=np.int64),
            "n": np.asarray(self.n, dtype=np.int64),
            "config": np.asarray([self.quantile_accuracy,
                                  self.min_magnitude,
                                  self.max_magnitude]),
            "observed": self.observed,
            "has_center": np.asarray(
                0 if self.center is None else 1, dtype=np.int64),
            "center": (np.zeros(1)
                       if self.center is None else self.center),
            "ge": self.ge, "le": self.le, "abs_ge": self.abs_ge,
            "sum": self.sum, "sumsq": self.sumsq,
            "n_finite": self.n_finite,
            "pos": self.pos, "neg": self.neg, "small": self.small,
            "max_stat": self.max_stat, "covered": self.covered,
        }
        return state

    @classmethod
    def from_state(cls, state):
        version = int(np.asarray(state["wire_version"]))
        if version > WIRE_VERSION:
            raise ValueError(
                "accumulator wire version {} is newer than supported "
                "version {}".format(version, WIRE_VERSION))
        cfg = np.asarray(state["config"], dtype=float).ravel()
        center = (np.asarray(state["center"])
                  if int(np.asarray(state["has_center"])) else None)
        acc = cls(np.asarray(state["observed"]),
                  int(np.asarray(state["n_total"])), center=center,
                  quantile_accuracy=float(cfg[0]),
                  min_magnitude=float(cfg[1]),
                  max_magnitude=float(cfg[2]),
                  shape=np.asarray(state["ge"]).shape)
        acc.n = int(np.asarray(state["n"]))
        for name in ("ge", "le", "abs_ge", "n_finite", "pos", "neg",
                     "small", "covered"):
            setattr(acc, name, np.array(
                state[name], dtype=getattr(acc, name).dtype))
        for name in ("sum", "sumsq", "max_stat"):
            setattr(acc, name, np.array(state[name],
                                        dtype=np.float64))
        return acc

    def save(self, path):
        """Persist to ``.npz`` (readable with ``allow_pickle=False``)."""
        np.savez(path, **self.to_state())

    @classmethod
    def load(cls, path):
        with np.load(path, allow_pickle=False) as z:
            return cls.from_state({k: z[k] for k in z.files})

    def to_json(self):
        """JSON wire form (exact: integer state verbatim, float state
        via hex floats) for transports where npz is awkward."""
        payload = {}
        for key, arr in self.to_state().items():
            arr = np.asarray(arr)
            if arr.dtype.kind in "iu":
                data = arr.ravel().tolist()
            else:
                data = [float.hex(float(v)) for v in arr.ravel()]
            payload[key] = {"dtype": arr.dtype.name,
                            "shape": list(arr.shape), "data": data}
        return json.dumps(payload)

    @classmethod
    def from_json(cls, text):
        payload = json.loads(text)
        state = {}
        for key, rec in payload.items():
            dtype = np.dtype(rec["dtype"])
            if dtype.kind in "iu":
                arr = np.asarray(rec["data"], dtype=dtype)
            else:
                arr = np.asarray([float.fromhex(v)
                                  for v in rec["data"]], dtype=dtype)
            state[key] = arr.reshape(rec["shape"])
        return cls.from_state(state)
