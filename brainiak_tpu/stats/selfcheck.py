"""CI selfcheck for the resampling-statistics engine (STA001 gate).

Run as a subprocess child by ``tools/run_checks.py`` on the 8-device
CPU mesh: proves (1) count-vs-materialized parity — the accumulator's
integer exceedance counts reproduce ``p_from_null`` on the
materialized distribution bit-for-bit, (2) chunk invariance — a
starved ``BRAINIAK_TPU_STATS_BUDGET_BYTES`` run (many small chunks)
returns the bitwise-identical null to a one-chunk run, (3) pooling —
two disjoint half-range runs, each round-tripped through a DIFFERENT
wire format (JSON hex-floats / npz), ``merge()`` to EXACTLY the full
run's verdicts, (4) resume — an injected preemption mid-run, then a
resumed run that reproduces the uninterrupted p-map bitwise, and (5)
retrace stability: all of the above reuses ONE compiled program per
``stats.*`` builder key (every counted site stays at <= 1 trace).
"""

import numpy as np

__all__ = ["selfcheck"]


def selfcheck(out=None):
    """Prints a JSON verdict; returns 0 on pass, 1 on failure."""
    import json
    import os
    import sys
    import tempfile

    from ..obs import metrics as obs_metrics
    from ..resilience import faults
    from .accum import NullAccumulator
    from .engine import NullEngine
    from .pvalues import p_from_null

    stream = out or sys.stdout
    rng = np.random.RandomState(0)
    # 8 subjects x 5 voxels of ISC-scale values; 64 resamples over a
    # 16-lane batch so the starved-budget run below spans 4 chunks.
    iscs = 0.2 + 0.1 * rng.randn(8, 5)
    n_resamples, batch = 64, 16
    run_kwargs = dict(statistic="median", side="two-sided", seed=3)

    errs = []
    merge_ok = True
    resume_ok = True

    # (1) count-vs-materialized parity: accumulator counts must
    # reproduce p_from_null on the materialized null bit-for-bit
    engine = NullEngine(null_batch_size=batch)
    full = engine.run(iscs, "sign_flip", n_resamples,
                      return_distribution=True, **run_kwargs)
    p_ref = p_from_null(full.observed, full.distribution,
                        side="two-sided", exact=full.exact, axis=0)
    errs.append(float(np.max(np.abs(full.p_values() - p_ref))))

    # (2) chunk invariance: a starved budget (chunk == one dispatch
    # lane, 4 chunks here) must return the bitwise-identical null
    starved = NullEngine(null_batch_size=batch, budget_bytes=1)
    small = starved.run(iscs, "sign_flip", n_resamples,
                        return_distribution=True, **run_kwargs)
    chunk_exact = (
        np.array_equal(small.distribution, full.distribution,
                       equal_nan=True)
        and np.array_equal(small.p_values(), full.p_values()))
    errs.append(0.0 if chunk_exact else float(np.max(np.abs(
        np.nan_to_num(small.distribution)
        - np.nan_to_num(full.distribution)))))

    with tempfile.TemporaryDirectory() as tmp:
        # (3) pooling: disjoint half-ranges, each through a different
        # wire format, merge to EXACTLY the full run
        half = n_resamples // 2
        lo_half = engine.run(iscs, "sign_flip", n_resamples,
                             index_range=(0, half), **run_kwargs)
        hi_half = engine.run(iscs, "sign_flip", n_resamples,
                             index_range=(half, n_resamples),
                             **run_kwargs)
        acc_a = NullAccumulator.from_json(
            lo_half.accumulator.to_json())
        npz = os.path.join(tmp, "half.npz")
        hi_half.accumulator.save(npz)
        acc_b = NullAccumulator.load(npz)
        merged = acc_a.merge(acc_b)
        ref = full.accumulator
        merge_ok = (
            merged.complete
            and np.array_equal(merged.p_values(side="two-sided"),
                               ref.p_values(side="two-sided"))
            and np.array_equal(merged.quantile(0.975),
                               ref.quantile(0.975))
            and merged.fwer_threshold() == ref.fwer_threshold())

        # (4) resume at the last completed chunk after an injected
        # preemption; the resumed p-map must be bitwise identical
        ckpt = os.path.join(tmp, "ckpt")
        try:
            with faults.inject("preempt", at_step=2):
                starved.run(iscs, "sign_flip", n_resamples,
                            checkpoint_dir=ckpt, **run_kwargs)
            resume_ok = False  # the fault must fire
        except faults.PreemptionError:
            pass
        resumed = starved.run(iscs, "sign_flip", n_resamples,
                              checkpoint_dir=ckpt, **run_kwargs)
        if not np.array_equal(resumed.p_values(), full.p_values()):
            resume_ok = False

    # (5) retrace stability: one compiled program per builder key —
    # every run above shares (stat, batch, sampled, n_subjects,
    # pairwise), so each counted stats.* site must read <= 1
    retrace = obs_metrics.counter("retrace_total")
    sites = {}
    for labels, value in retrace.samples():
        site = labels.get("site", "")
        if site.startswith("stats."):
            sites[site] = value
    tol = 0.0
    ok = (max(errs) <= tol and merge_ok and resume_ok
          and all(c <= 1.0 for c in sites.values())
          and "stats.sign_flip" in sites)
    json.dump({"ok": bool(ok), "max_err": max(errs), "tol": tol,
               "merge_ok": bool(merge_ok),
               "resume_ok": bool(resume_ok), "retraces": sites},
              stream)
    stream.write("\n")
    return 0 if ok else 1
