"""Surrogate-family registry: whole null families as ONE vmapped program.

Each family is a pure jittable transform ``(key, data) -> surrogate``
(or index-resample) listed in :data:`TRANSFORMS`, and a compiled
program that evaluates an entire chunk of surrogates in one dispatch
(``lax.map`` over split PRNG keys — or over enumerated resamples for
exact tests).  Program builders are ``counted_cache``'d under
``stats.*`` sites with ``trace_signature`` factories, so the JPR001
IR audit covers every family and ``retrace_total{site=stats.*}``
stays <= 1 per family across repeat runs.

Voxel sharding rides on input placement: the engine places inputs via
the ``_shard_voxels`` idiom (``brainiak_tpu.isc``), and every program
here is voxelwise-independent, so XLA partitions the whole map with
no collectives.

The statistic compositions are verbatim the pre-refactor ``isc.py``
null maps (bit-for-bit parity at matched seeds is load-bearing: the
four ``*_isc`` resampling entry points now route through these
programs).
"""

import math
from itertools import permutations, product

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import profile as obs_profile
from ..obs import runtime as obs_runtime
from ..ops.stats import phase_randomize as _phase_randomize_jax

__all__ = [
    "FAMILIES",
    "TRANSFORMS",
    "NullSpec",
    "make_spec",
    "sign_flip",
    "group_shuffle",
    "subject_bootstrap",
    "circular_timeshift",
    "phase_randomize",
]

#: the registered surrogate families, in registry order.
FAMILIES = ("sign_flip", "group_shuffle", "subject_bootstrap",
            "circular_timeshift", "phase_randomize")

#: families whose input is an ISC matrix (vs a [T, V, S] time series).
ISC_INPUT_FAMILIES = ("sign_flip", "group_shuffle", "subject_bootstrap")


# ---------------------------------------------------------------------------
# pure per-surrogate transforms (the registry's canonical forms)

def sign_flip(key, iscs):
    """Random per-subject sign flips applied to an [S, V] ISC stack."""
    flips = jax.random.choice(
        key, jnp.asarray([-1.0, 1.0], dtype=iscs.dtype),
        (iscs.shape[0],))
    return iscs * flips[:, None]


def group_shuffle(key, selector):
    """Random permutation of a per-subject group-label vector."""
    return selector[jax.random.permutation(key, selector.shape[0])]


def subject_bootstrap(key, n_subjects):
    """With-replacement subject index resample (an index-resample
    family: the gather happens inside the statistic program)."""
    return jax.random.choice(key, n_subjects, (n_subjects,))


def circular_timeshift(key, data):
    """Independent circular time shift per subject of [T, V, S] data."""
    n_trs, _, n_subjects = data.shape
    shifts = jax.random.choice(key, n_trs, (n_subjects,))
    return jax.vmap(
        lambda s, shift: jnp.roll(s, shift, axis=0),
        in_axes=(2, 0), out_axes=2)(data, shifts)


def phase_randomize(key, data, voxelwise=False):
    """Phase randomization preserving power spectra
    (:func:`brainiak_tpu.ops.stats.phase_randomize`)."""
    return _phase_randomize_jax(key, data, voxelwise=voxelwise)


TRANSFORMS = {
    "sign_flip": sign_flip,
    "group_shuffle": group_shuffle,
    "subject_bootstrap": subject_bootstrap,
    "circular_timeshift": circular_timeshift,
    "phase_randomize": phase_randomize,
}


# ---------------------------------------------------------------------------
# shared statistic helpers (traced inside the family programs)

def _nanmedian(x, axis=0):
    """NaN-excluding median in the INPUT dtype.  ``jnp.nanmedian``
    routes through ``nanquantile``, whose rank-interpolation
    constants are float64 under x64 — a dtype-promotion leak in
    every f32 surrogate program (JP301).  Sorting with NaNs pushed
    to +inf and averaging the two middle ranks is the same
    arithmetic as ``np.median``'s ``(a + b) / 2``."""
    x = jnp.moveaxis(x, axis, 0)
    valid = ~jnp.isnan(x)
    count = jnp.sum(valid, axis=0, dtype=jnp.int32)
    ordered = jnp.sort(jnp.where(valid, x, jnp.inf), axis=0)
    hi_rank = count // 2
    lo_rank = jnp.maximum(count - 1, 0) // 2
    lo = jnp.take_along_axis(ordered, lo_rank[None], axis=0)[0]
    hi = jnp.take_along_axis(ordered, hi_rank[None], axis=0)[0]
    return jnp.where(count > 0, (lo + hi) / 2.0, jnp.nan)


def _jnp_summary(iscs, summary_statistic, axis=0):
    if summary_statistic == 'mean':
        return jnp.tanh(jnp.nanmean(jnp.arctanh(iscs), axis=axis))
    return _nanmedian(iscs, axis=axis)


def _group_diff_stat(iscs_j, sel, labels_j, stat):
    """summary(group0) - summary(group1) for per-row labels ``sel``
    (rows labeled NaN are excluded from both summaries).  Single source
    of the two-group statistic for BOTH the observed value and the
    permutation nulls."""
    s0 = _jnp_summary(
        jnp.where((sel == labels_j[0])[:, None], iscs_j, jnp.nan),
        stat, axis=0)
    s1 = _jnp_summary(
        jnp.where((sel == labels_j[1])[:, None], iscs_j, jnp.nan),
        stat, axis=0)
    return s0 - s1


# ---------------------------------------------------------------------------
# family program builders (one compiled vmapped program per family)

@obs_runtime.counted_cache("stats.subject_bootstrap")
def subject_bootstrap_program(stat, batch, pairwise):
    """Subject-wise bootstrap null chunk: [n_keys] -> [n_keys, V]."""
    if pairwise:
        def run(sq_j, keys, iu0, iu1):
            n_subj = sq_j.shape[0]

            def one(key):
                sample = jnp.sort(subject_bootstrap(key, n_subj))
                resq = sq_j[sample][:, sample]
                same = sample[:, None] == sample[None, :]
                resq = jnp.where(same[..., None], jnp.nan, resq)
                return _jnp_summary(resq[iu0, iu1], stat, axis=0)

            return jax.lax.map(one, keys, batch_size=batch)
    else:
        def run(iscs_j, keys):
            n_subj = iscs_j.shape[0]

            def one(key):
                sample = subject_bootstrap(key, n_subj)
                return _jnp_summary(iscs_j[sample], stat, axis=0)

            return jax.lax.map(one, keys, batch_size=batch)

    return obs_profile.profile_program(
        jax.jit(run), "stats.subject_bootstrap", span="stats.chunk")


@obs_runtime.counted_cache("stats.sign_flip")
def sign_flip_program(stat, batch, sampled, n_subjects, pairwise):
    """One-group sign-flip permutation null chunk.  ``xs`` is split
    keys when ``sampled`` else the enumerated [-1, 1]^S flip matrix."""
    if pairwise:
        def run(iscs_j, xs, iu0, iu1):
            def apply_flips(flips):
                pairflip = flips[iu0] * flips[iu1]
                return _jnp_summary(iscs_j * pairflip[:, None], stat,
                                    axis=0)

            if sampled:
                def one(key):
                    flips = jax.random.choice(
                        key,
                        jnp.asarray([-1.0, 1.0], dtype=iscs_j.dtype),
                        (n_subjects,))
                    return apply_flips(flips)

                return jax.lax.map(one, xs, batch_size=batch)
            return jax.lax.map(apply_flips, xs, batch_size=batch)
    else:
        def run(iscs_j, xs):
            def apply_flips(flips):
                return _jnp_summary(iscs_j * flips[:, None], stat,
                                    axis=0)

            if sampled:
                def one(key):
                    flips = jax.random.choice(
                        key,
                        jnp.asarray([-1.0, 1.0], dtype=iscs_j.dtype),
                        (n_subjects,))
                    return apply_flips(flips)

                return jax.lax.map(one, xs, batch_size=batch)
            return jax.lax.map(apply_flips, xs, batch_size=batch)

    return obs_profile.profile_program(
        jax.jit(run), "stats.sign_flip", span="stats.chunk")


@obs_runtime.counted_cache("stats.group_shuffle")
def group_shuffle_program(stat, batch, sampled, pairwise):
    """Two-group label-shuffle permutation null chunk.  ``xs`` is
    split keys when ``sampled`` else enumerated permutations."""
    if pairwise:
        def run(iscs_j, sq_labels_j, labels_j, iu0, iu1, xs):
            def permute_stat(perm):
                shuffled = sq_labels_j[perm][:, perm]
                return _group_diff_stat(iscs_j, shuffled[iu0, iu1],
                                        labels_j, stat)

            n_subjects = sq_labels_j.shape[0]
            if sampled:
                def one(key):
                    return permute_stat(
                        jax.random.permutation(key, n_subjects))

                return jax.lax.map(one, xs, batch_size=batch)
            return jax.lax.map(permute_stat, xs, batch_size=batch)
    else:
        def run(iscs_j, sel_j, labels_j, xs):
            n_subjects = sel_j.shape[0]
            if sampled:
                def one(key):
                    return _group_diff_stat(
                        iscs_j, group_shuffle(key, sel_j), labels_j,
                        stat)

                return jax.lax.map(one, xs, batch_size=batch)
            return jax.lax.map(
                lambda perm: _group_diff_stat(iscs_j, sel_j[perm],
                                              labels_j, stat),
                xs, batch_size=batch)

    return obs_profile.profile_program(
        jax.jit(run), "stats.group_shuffle", span="stats.chunk")


@obs_runtime.counted_cache("stats.circular_timeshift")
def circular_timeshift_program(stat, batch, pairwise):
    """Circular time-shift null chunk over [T, V, S] data.  ``others``
    is the unshifted leave-one-out means (loo mode; unread in the
    pairwise trace — callers pass the data as a free placeholder)."""
    def run(data_j, others, keys, iu0, iu1):
        from ..isc import _columnwise_corr, _isc_pairwise_core

        def one_shift(key):
            rolled = circular_timeshift(key, data_j)
            if pairwise:
                corr = _isc_pairwise_core(rolled)
                return _jnp_summary(corr[iu0, iu1, :], stat, axis=0)
            return _jnp_summary(_columnwise_corr(rolled, others), stat,
                                axis=0)

        return jax.lax.map(one_shift, keys, batch_size=batch)

    return obs_profile.profile_program(
        jax.jit(run), "stats.circular_timeshift", span="stats.chunk")


@obs_runtime.counted_cache("stats.phase_randomize")
def phase_randomize_program(stat, batch, pairwise, voxelwise):
    """Phase-randomization null chunk over [T, V, S] data."""
    def run(data_j, others, keys, iu0, iu1):
        from ..isc import _columnwise_corr, _isc_pairwise_core

        def one_shift(key):
            shifted = phase_randomize(key, data_j,
                                      voxelwise=voxelwise)
            if pairwise:
                corr = _isc_pairwise_core(shifted)
                return _jnp_summary(corr[iu0, iu1, :], stat, axis=0)
            return _jnp_summary(_columnwise_corr(shifted, others),
                                stat, axis=0)

        return jax.lax.map(one_shift, keys, batch_size=batch)

    return obs_profile.profile_program(
        jax.jit(run), "stats.phase_randomize", span="stats.chunk")


# ---------------------------------------------------------------------------
# canonical jaxlint-IR trace signatures (one spec per program branch)

def _key_aval(n):
    return jax.ShapeDtypeStruct((n, 2), jnp.uint32)


def _iu_avals(n_pairs):
    return (jax.ShapeDtypeStruct((n_pairs,), jnp.int32),
            jax.ShapeDtypeStruct((n_pairs,), jnp.int32))


@obs_runtime.trace_signature("stats.subject_bootstrap")
def _subject_bootstrap_signature():
    f32 = jnp.float32
    iu0, iu1 = _iu_avals(3)
    return [
        {"key": ("median", 2, False), "label": "loo",
         "args": (jax.ShapeDtypeStruct((3, 8), f32), _key_aval(4))},
        {"key": ("mean", 2, True), "label": "pairwise",
         "args": (jax.ShapeDtypeStruct((3, 3, 8), f32), _key_aval(4),
                  iu0, iu1)},
    ]


@obs_runtime.trace_signature("stats.sign_flip")
def _sign_flip_signature():
    f32 = jnp.float32
    iu0, iu1 = _iu_avals(3)
    return [
        {"key": ("median", 2, True, 3, False), "label": "loo-sampled",
         "args": (jax.ShapeDtypeStruct((3, 8), f32), _key_aval(4))},
        {"key": ("median", 2, False, 3, False), "label": "loo-exact",
         "args": (jax.ShapeDtypeStruct((3, 8), f32),
                  jax.ShapeDtypeStruct((8, 3), f32))},
        {"key": ("mean", 2, True, 3, True), "label": "pairwise",
         "args": (jax.ShapeDtypeStruct((3, 8), f32), _key_aval(4),
                  iu0, iu1)},
    ]


@obs_runtime.trace_signature("stats.group_shuffle")
def _group_shuffle_signature():
    f32 = jnp.float32
    iu0, iu1 = _iu_avals(6)
    labels = jax.ShapeDtypeStruct((2,), f32)
    return [
        {"key": ("median", 2, True, False), "label": "loo-sampled",
         "args": (jax.ShapeDtypeStruct((4, 8), f32),
                  jax.ShapeDtypeStruct((4,), f32), labels,
                  _key_aval(4))},
        {"key": ("median", 2, False, False), "label": "loo-exact",
         "args": (jax.ShapeDtypeStruct((4, 8), f32),
                  jax.ShapeDtypeStruct((4,), f32), labels,
                  jax.ShapeDtypeStruct((4, 4), jnp.int32))},
        {"key": ("mean", 2, True, True), "label": "pairwise",
         "args": (jax.ShapeDtypeStruct((6, 8), f32),
                  jax.ShapeDtypeStruct((4, 4), f32), labels,
                  iu0, iu1, _key_aval(4))},
    ]


@obs_runtime.trace_signature("stats.circular_timeshift")
def _circular_timeshift_signature():
    f32 = jnp.float32
    iu0, iu1 = _iu_avals(3)
    data = jax.ShapeDtypeStruct((6, 8, 3), f32)
    return [
        {"key": ("median", 2, False), "label": "loo",
         "args": (data, data, _key_aval(4), iu0, iu1)},
        {"key": ("mean", 2, True), "label": "pairwise",
         "args": (data, data, _key_aval(4), iu0, iu1)},
    ]


@obs_runtime.trace_signature("stats.phase_randomize")
def _phase_randomize_signature():
    f32 = jnp.float32
    iu0, iu1 = _iu_avals(3)
    data = jax.ShapeDtypeStruct((6, 8, 3), f32)
    return [
        {"key": ("median", 2, False, False), "label": "loo",
         "args": (data, data, _key_aval(4), iu0, iu1)},
        {"key": ("mean", 2, True, True), "label": "pairwise-voxelwise",
         "args": (data, data, _key_aval(4), iu0, iu1)},
    ]


# ---------------------------------------------------------------------------
# family specs: everything the engine needs to drive one null run

class NullSpec:
    """One prepared resampling family: the full resample descriptor
    array ``xs`` (split PRNG keys, or the enumerated resamples of an
    exact test) plus a ``run(xs_chunk) -> [n, V] ndarray`` closure over
    the device-placed inputs.  Slicing ``xs`` by global resample index
    is what makes chunking, resume, and disjoint-range pooling all
    yield the same per-index surrogate."""

    def __init__(self, family, xs, run, n_voxels, n_total, exact,
                 sampled, statistic, compute_observed):
        self.family = family
        self.xs = xs
        self.run = run
        self.n_voxels = n_voxels
        self.n_total = n_total
        self.exact = exact
        self.sampled = sampled
        self.statistic = statistic
        self.compute_observed = compute_observed


def _sampled_xs(seed, n_resamples):
    """The canonical key schedule: split once over ALL planned
    resamples, sliced per chunk — key i is a pure function of
    (seed, i), independent of chunk boundaries."""
    return np.asarray(jax.random.split(
        jax.random.PRNGKey(int(seed)), int(n_resamples)))


def make_spec(family, data, *, statistic='median', n_resamples=1000,
              seed=0, pairwise=False, group_assignment=None,
              voxelwise=False, tolerate_nans=True, mesh=None,
              null_batch_size=None):
    """Build the :class:`NullSpec` for one family over prepared data.

    ``data`` is the family's input: an ISC stack (``[S, V]``
    leave-one-out, or the condensed pairwise form) for the
    ISC-resampling families, a ``[T, V, S]`` time-series stack for the
    shift families.  Placement (voxel sharding over ``mesh``) happens
    here, once, outside the chunk loop.
    """
    from .engine import default_null_batch

    if family not in FAMILIES:
        raise ValueError("Unknown surrogate family {!r}; registered "
                         "families: {}".format(family,
                                               ", ".join(FAMILIES)))
    if statistic not in ('mean', 'median'):
        raise ValueError("Summary statistic must be 'mean' or 'median'")

    from ..isc import _check_isc_input, _loo_means_core, _shard_voxels
    from ..parallel.mesh import fetch_replicated
    from .pvalues import compute_summary_statistic

    if family in ISC_INPUT_FAMILIES:
        iscs, n_subjects, n_voxels = _check_isc_input(
            np.asarray(data) if not isinstance(data, list) else data,
            pairwise=pairwise)
        iu = np.triu_indices(n_subjects, k=1)
        iu0, iu1 = jnp.asarray(iu[0]), jnp.asarray(iu[1])

        if family == "subject_bootstrap":
            batch = (null_batch_size if null_batch_size is not None
                     else default_null_batch(n_voxels))
            if pairwise:
                from scipy.spatial.distance import squareform
                sq = np.stack([squareform(v, force='tomatrix')
                               for v in iscs.T], axis=-1)  # [S, S, V]
                for v in range(sq.shape[-1]):
                    np.fill_diagonal(sq[..., v], 1.0)
                sq_j = _shard_voxels(sq, mesh, 2)

                def run(xs_chunk):
                    program = subject_bootstrap_program(
                        statistic, batch, True)
                    return np.asarray(fetch_replicated(
                        program(sq_j, jnp.asarray(xs_chunk), iu0,
                                iu1), mesh))[:, :n_voxels]
            else:
                iscs_j = _shard_voxels(iscs, mesh, 1)

                def run(xs_chunk):
                    program = subject_bootstrap_program(
                        statistic, batch, False)
                    return np.asarray(fetch_replicated(
                        program(iscs_j, jnp.asarray(xs_chunk)),
                        mesh))[:, :n_voxels]

            def compute_observed():
                return compute_summary_statistic(
                    iscs, summary_statistic=statistic, axis=0)

            return NullSpec(family, _sampled_xs(seed, n_resamples),
                            run, n_voxels, int(n_resamples), False,
                            True, statistic, compute_observed)

        if family == "sign_flip":
            batch = (null_batch_size if null_batch_size is not None
                     else default_null_batch(n_voxels))
            exact = n_resamples >= 2 ** n_subjects
            if exact:
                n_total = 2 ** n_subjects
                xs = np.asarray(list(product([-1.0, 1.0],
                                             repeat=n_subjects)))
            else:
                n_total = int(n_resamples)
                xs = _sampled_xs(seed, n_total)
            iscs_j = _shard_voxels(iscs, mesh, 1)

            if pairwise:
                def run(xs_chunk):
                    program = sign_flip_program(
                        statistic, batch, not exact, n_subjects, True)
                    return np.asarray(fetch_replicated(
                        program(iscs_j, jnp.asarray(xs_chunk), iu0,
                                iu1), mesh))[:, :n_voxels]
            else:
                def run(xs_chunk):
                    program = sign_flip_program(
                        statistic, batch, not exact, n_subjects,
                        False)
                    return np.asarray(fetch_replicated(
                        program(iscs_j, jnp.asarray(xs_chunk)),
                        mesh))[:, :n_voxels]

            def compute_observed():
                return compute_summary_statistic(
                    iscs, summary_statistic=statistic,
                    axis=0)[np.newaxis, :]

            return NullSpec(family, xs, run, n_voxels, n_total, exact,
                            not exact, statistic, compute_observed)

        # group_shuffle
        if group_assignment is None:
            raise ValueError("group_shuffle requires group_assignment")
        batch = (null_batch_size if null_batch_size is not None
                 else default_null_batch(n_voxels))
        group_selector = np.asarray(group_assignment)
        labels = np.unique(group_selector)
        if len(labels) != 2:
            raise ValueError("group_shuffle requires exactly 2 groups "
                             "(got {0})".format(len(labels)))
        labels_j = jnp.asarray(labels.astype(float))
        exact = n_resamples >= math.factorial(n_subjects)
        if exact:
            n_total = math.factorial(n_subjects)
            xs = np.asarray(list(permutations(np.arange(n_subjects))))
        else:
            n_total = int(n_resamples)
            xs = _sampled_xs(seed, n_total)
        iscs_j = _shard_voxels(iscs, mesh, 1)

        if pairwise:
            from scipy.spatial.distance import squareform
            sq_labels = np.full((n_subjects, n_subjects), np.nan)
            for g in labels:
                idx = np.where(group_selector == g)[0]
                sq_labels[np.ix_(idx, idx)] = g
            np.fill_diagonal(sq_labels, np.nan)
            pair_labels = squareform(sq_labels, checks=False)
            sq_labels_j = jnp.asarray(sq_labels)

            def run(xs_chunk):
                program = group_shuffle_program(statistic, batch,
                                                not exact, True)
                return np.asarray(fetch_replicated(
                    program(iscs_j, sq_labels_j, labels_j, iu0, iu1,
                            jnp.asarray(xs_chunk)),
                    mesh))[:, :n_voxels]

            def compute_observed():
                return np.asarray(fetch_replicated(_group_diff_stat(
                    iscs_j, jnp.asarray(pair_labels), labels_j,
                    statistic), mesh))[:n_voxels]
        else:
            sel_j = jnp.asarray(group_selector)

            def run(xs_chunk):
                program = group_shuffle_program(statistic, batch,
                                                not exact, False)
                return np.asarray(fetch_replicated(
                    program(iscs_j, sel_j, labels_j,
                            jnp.asarray(xs_chunk)),
                    mesh))[:, :n_voxels]

            def compute_observed():
                return np.asarray(fetch_replicated(_group_diff_stat(
                    iscs_j, sel_j, labels_j, statistic),
                    mesh))[:n_voxels]

        return NullSpec(family, xs, run, n_voxels, n_total, exact,
                        not exact, statistic, compute_observed)

    # shift families: data is a prepared [T, V, S] stack
    data = np.asarray(data)
    if data.ndim != 3:
        raise ValueError("shift families expect [TRs, voxels, "
                         "subjects] data (got ndim={})".format(
                             data.ndim))
    n_trs, n_voxels, n_subjects = data.shape
    batch = (null_batch_size if null_batch_size is not None
             else default_null_batch(n_trs * n_voxels * n_subjects))
    data_j = _shard_voxels(data, mesh, 1)
    tol = bool(tolerate_nans)
    iu = np.triu_indices(n_subjects, k=1)
    iu0, iu1 = jnp.asarray(iu[0]), jnp.asarray(iu[1])
    # loo: shift all subjects, correlate each against the UNSHIFTED
    # others' mean.  The pairwise trace never reads ``others``; pass
    # data_j as a free placeholder instead of computing dead LOO means.
    others = data_j if pairwise else _loo_means_core(data_j, tol)

    if family == "circular_timeshift":
        def run(xs_chunk):
            program = circular_timeshift_program(
                statistic, batch, bool(pairwise))
            return np.asarray(fetch_replicated(
                program(data_j, others, jnp.asarray(xs_chunk), iu0,
                        iu1), mesh))[:, :n_voxels]
    else:
        def run(xs_chunk):
            program = phase_randomize_program(
                statistic, batch, bool(pairwise), bool(voxelwise))
            return np.asarray(fetch_replicated(
                program(data_j, others, jnp.asarray(xs_chunk), iu0,
                        iu1), mesh))[:, :n_voxels]

    def compute_observed():
        from ..isc import isc
        return isc(data, pairwise=pairwise,
                   summary_statistic=statistic,
                   tolerate_nans=tolerate_nans, mesh=mesh)

    return NullSpec(family, _sampled_xs(seed, n_resamples), run,
                    n_voxels, int(n_resamples), False, True,
                    statistic, compute_observed)
