"""Massive voxel-wise encoding models: batched ridge / banded ridge.

The canonical heavy-read fMRI workload the reference package never
had (ROADMAP open item 5): tens of thousands of independent per-voxel
ridge regressions fit once against a stimulus/feature design, then
scored against thousands of held-out scans — the massive-individual-
dataset setting of "Scaling up ridge regression for brain encoding in
a massive individual fMRI dataset"
(https://arxiv.org/pdf/2403.19421).

The solver is the eigendecomposition trick that makes a lambda sweep
nearly free: with ``G = Xᵀ X = Q Λ Qᵀ`` computed ONCE (through
:func:`brainiak_tpu.ops.distla.gram`, so the budget dispatcher picks
the replicated einsum or the SUMMA-sharded ring automatically, the
feature axis sharded over the mesh when over budget), every ridge
solution is a diagonal rescale in the eigenbasis::

    W(λ) = Q diag(1 / (Λ + λ)) Qᵀ Xᵀ Y

K-fold cross-validation reuses the same algebra per fold: the train
Gram of fold ``f`` is ``G - G_f`` (one small per-fold Gram each), so
one batched ``eigh`` over the K train Grams prepares the whole sweep,
and the sweep itself is a ``vmap`` over the lambda grid inside ONE
jitted program — no host round-trip per lambda, no recompile per
lambda (``retrace_total{site=encoding.*}`` counts one trace per
distinct program, not per grid point).

:class:`BandedRidgeEncoder` generalizes to per-feature-band lambdas
via the scaling trick: solving ridge at ``λ = 1`` on the column-scaled
design ``X·diag(s)`` with ``s = 1/sqrt(λ_band)`` is exactly banded
ridge, so each candidate (one lambda per band) costs one scaled
``eigh`` — batched over a candidate block in one program.

Resilience: the sweep is driven block-by-block through
:func:`~brainiak_tpu.resilience.guards.run_resilient_loop` — with
``checkpoint_dir=`` the accumulated per-voxel CV scores persist every
``checkpoint_every`` blocks and a preempted fit resumes at the last
completed lambda/candidate block.  Blocks are equal-sized (the last
one padded), so chunking never adds program shapes.

Telemetry: every program builder is a
:func:`~brainiak_tpu.obs.runtime.counted_cache` under an
``encoding.*`` site and the programs are
:func:`~brainiak_tpu.obs.profile.profile_program`-wrapped, so
retraces, cost records and span durations join in ``obs report`` like
every other estimator.

Memory model of the sweep (see docs/encoding.md): the peak transient
is the predicted held-out block ``[block, K, T/K, V]`` — bound it
with ``lambda_block=`` (ridge) / ``candidate_block=`` (banded)
instead of shrinking the grid.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import profile as obs_profile
from ..obs import runtime as obs_runtime
from ..obs import spans as obs_spans
from ..ops import distla
from ..ops.correlation import resolve_precision
from ..resilience.guards import array_digest, run_resilient_loop

logger = logging.getLogger(__name__)

__all__ = [
    "BandedRidgeEncoder",
    "DEFAULT_LAMBDAS",
    "RidgeEncoder",
    "selfcheck",
]

#: Default lambda grid (log-spaced; sorted ascending, so per-voxel
#: argmax ties resolve to the SMALLEST adequate lambda).
DEFAULT_LAMBDAS = (0.01, 0.1, 1.0, 10.0, 100.0, 1000.0, 10000.0)


def _fold_scores(pred, y):
    """Per-voxel Pearson r between predictions and held-out data over
    the time axis (axis -2); zero where either side is constant (a
    huge lambda drives predictions to a constant — score it neutral,
    never NaN)."""
    pc = pred - pred.mean(axis=-2, keepdims=True)
    yc = y - y.mean(axis=-2, keepdims=True)
    cov = (pc * yc).sum(axis=-2)
    den = jnp.sqrt((pc * pc).sum(axis=-2) * (yc * yc).sum(axis=-2))
    return jnp.where(den > 0, cov / jnp.where(den > 0, den, 1.0), 0.0)


# -- jitted program builders ------------------------------------------
#
# One builder per program family, lru-keyed on every extent that
# shapes the traced arrays (plus trace-time statics), so counted_cache
# misses == distinct compiled programs.  The acceptance contract:
# a full fit compiles at most one program per family — the lambda
# sweep is ONE program ranging over the grid, never one per lambda.

def _fold_algebra(k, t_f, prec):
    """The fold decomposition both prepare programs share: slice the
    contiguous folds out of the (already device-resident) full
    arrays — so X and Y each cross the host-device boundary exactly
    once per fit — and subtract per-fold Grams/cross-products from
    the totals (``G_train = G - G_f``)."""

    def fn(x, y, g_total):
        x_folds = x[:k * t_f].reshape(k, t_f, x.shape[1])
        y_folds = y[:k * t_f].reshape(k, t_f, y.shape[1])
        b_total = jnp.einsum('tf,tv->fv', x, y, precision=prec,
                             preferred_element_type=x.dtype)
        g_folds = jnp.einsum('ktf,ktg->kfg', x_folds, x_folds,
                             precision=prec,
                             preferred_element_type=x.dtype)
        b_folds = jnp.einsum('ktf,ktv->kfv', x_folds, y_folds,
                             precision=prec,
                             preferred_element_type=x.dtype)
        return (x_folds, y_folds, g_total[None] - g_folds,
                b_total[None] - b_folds, b_total)

    return fn


@obs_runtime.counted_cache("encoding.prepare")
def _prepare_program(t, k, t_f, f, v, precision):
    """Ridge sweep preparation: the shared fold algebra plus the
    batched train-Gram eigendecompositions and the eigenbasis
    projections the lambda sweep consumes.  Cache misses count as
    ``retrace_total{site=encoding.prepare}``.  Both the total ``t``
    (the full-T x/y arrays are traced inputs) and the fold length
    ``t_f`` key the cache — T values sharing a fold length still
    compile distinct programs."""
    prec = resolve_precision(precision)
    algebra = _fold_algebra(k, t_f, prec)

    def fn(x, y, g_total):
        x_folds, y_folds, g_tr, b_tr, b_total = algebra(x, y,
                                                        g_total)
        evals, q = jnp.linalg.eigh(g_tr)
        evals = jnp.maximum(evals, 0.0)  # f32 noise on a PSD matrix
        a = jnp.einsum('kfg,kfv->kgv', q, b_tr, precision=prec,
                       preferred_element_type=x.dtype)
        p = jnp.einsum('ktf,kfg->ktg', x_folds, q, precision=prec,
                       preferred_element_type=x.dtype)
        return evals, a, p, y_folds, b_total

    return obs_profile.profile_program(
        jax.jit(fn), "encoding.prepare", span="encoding.fit")


# canonical trace extents shared by every encoding.* signature:
# T=8 TRs in k=2 folds of t_f=4, F=3 features, V=5 voxels,
# lambda/candidate blocks of 2
_TRACE_T, _TRACE_K, _TRACE_TF, _TRACE_F, _TRACE_V, _TRACE_BLOCK = \
    8, 2, 4, 3, 5, 2


def _enc_aval(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _prepare_trace_specs():
    t, f, v = _TRACE_T, _TRACE_F, _TRACE_V
    return [{"key": (t, _TRACE_K, _TRACE_TF, f, v,
                     resolve_precision(None)),
             "args": (_enc_aval(t, f), _enc_aval(t, v),
                      _enc_aval(f, f))}]


@obs_runtime.trace_signature("encoding.prepare")
def _prepare_trace_signature():
    return _prepare_trace_specs()


@obs_runtime.counted_cache("encoding.banded_prepare")
def _banded_prepare_program(t, k, t_f, f, v, precision):
    """Banded sweep preparation: the shared fold algebra only — the
    eigendecomposition is per-candidate (scaled Gram), so it lives in
    the sweep program instead."""
    prec = resolve_precision(precision)
    algebra = _fold_algebra(k, t_f, prec)
    return obs_profile.profile_program(
        jax.jit(algebra), "encoding.banded_prepare",
        span="encoding.fit")


@obs_runtime.trace_signature("encoding.banded_prepare")
def _banded_prepare_trace_signature():
    return _prepare_trace_specs()


@obs_runtime.counted_cache("encoding.sweep")
def _sweep_program(k, t_f, f, v, block, precision):
    """The ridge CV sweep: ONE jitted program scoring a whole lambda
    block — ``vmap`` over lambdas of (diagonal rescale in the
    eigenbasis, held-out prediction, per-voxel correlation), folds
    reduced inside.  Cache misses count as
    ``retrace_total{site=encoding.sweep}`` — one per block SHAPE,
    never one per lambda."""
    prec = resolve_precision(precision)

    def fn(evals, a, p, y_folds, lambdas):
        def one(lam):
            w = a / (evals[..., None] + lam)
            pred = jnp.einsum('ktf,kfv->ktv', p, w, precision=prec,
                              preferred_element_type=p.dtype)
            return _fold_scores(pred, y_folds).mean(axis=0)

        return jax.vmap(one)(lambdas)

    return obs_profile.profile_program(
        jax.jit(fn), "encoding.sweep", span="encoding.sweep_chunk")


@obs_runtime.trace_signature("encoding.sweep")
def _sweep_trace_signature():
    k, t_f, f, v, block = (_TRACE_K, _TRACE_TF, _TRACE_F, _TRACE_V,
                           _TRACE_BLOCK)
    return [{"key": (k, t_f, f, v, block, resolve_precision(None)),
             "args": (_enc_aval(k, f), _enc_aval(k, f, v),
                      _enc_aval(k, t_f, f), _enc_aval(k, t_f, v),
                      _enc_aval(block))}]


@obs_runtime.counted_cache("encoding.banded_sweep")
def _banded_sweep_program(k, t_f, f, v, block, precision):
    """The banded CV sweep: per candidate (one per-feature scale row
    ``s = 1/sqrt(λ_band)``), scale the train Grams, eigendecompose,
    solve at λ=1, score held-out predictions — ``vmap`` over the
    candidate block in one program."""
    prec = resolve_precision(precision)

    def fn(g_tr, b_tr, x_folds, y_folds, scales):
        def one(s):
            g_s = g_tr * s[None, :, None] * s[None, None, :]
            evals, q = jnp.linalg.eigh(g_s)
            evals = jnp.maximum(evals, 0.0)
            a = jnp.einsum('kfg,kfv->kgv', q,
                           b_tr * s[None, :, None], precision=prec,
                           preferred_element_type=s.dtype)
            p = jnp.einsum('ktf,kfg->ktg',
                           x_folds * s[None, None, :], q,
                           precision=prec,
                           preferred_element_type=s.dtype)
            pred = jnp.einsum('ktf,kfv->ktv', p,
                              a / (evals[..., None] + 1.0),
                              precision=prec,
                              preferred_element_type=s.dtype)
            return _fold_scores(pred, y_folds).mean(axis=0)

        return jax.vmap(one)(scales)

    return obs_profile.profile_program(
        jax.jit(fn), "encoding.banded_sweep",
        span="encoding.sweep_chunk")


@obs_runtime.trace_signature("encoding.banded_sweep")
def _banded_sweep_trace_signature():
    k, t_f, f, v, block = (_TRACE_K, _TRACE_TF, _TRACE_F, _TRACE_V,
                           _TRACE_BLOCK)
    return [{"key": (k, t_f, f, v, block, resolve_precision(None)),
             "args": (_enc_aval(k, f, f), _enc_aval(k, f, v),
                      _enc_aval(k, t_f, f), _enc_aval(k, t_f, v),
                      _enc_aval(block, f))}]


@obs_runtime.counted_cache("encoding.refit")
def _refit_program(f, v, precision):
    """Final full-data refit at the per-voxel selected lambdas: one
    eigendecomposition of the total Gram, then a per-voxel diagonal
    rescale — every voxel gets its own lambda in one program."""
    prec = resolve_precision(precision)

    def fn(g_total, b_total, lam_sel):
        evals, q = jnp.linalg.eigh(g_total)
        evals = jnp.maximum(evals, 0.0)
        a = jnp.einsum('fg,fv->gv', q, b_total, precision=prec,
                       preferred_element_type=b_total.dtype)
        w = a / (evals[:, None] + lam_sel[None, :])
        return jnp.einsum('fg,gv->fv', q, w, precision=prec,
                          preferred_element_type=b_total.dtype)

    return obs_profile.profile_program(
        jax.jit(fn), "encoding.refit", span="encoding.fit")


@obs_runtime.trace_signature("encoding.refit")
def _refit_trace_signature():
    f, v = _TRACE_F, _TRACE_V
    return [{"key": (f, v, resolve_precision(None)),
             "args": (_enc_aval(f, f), _enc_aval(f, v),
                      _enc_aval(v))}]


@obs_runtime.counted_cache("encoding.banded_refit")
def _banded_refit_program(f, v, block, precision):
    """Banded full-data refit for one candidate block: per candidate,
    eigendecompose the scaled total Gram, solve at λ=1, map back to
    the unscaled basis (``w = s ∘ w_s``), and keep only the voxel
    columns whose CV selected this candidate (the one-hot mask);
    summing over candidates assembles the mixed-candidate [F, V]
    coefficient block-by-block."""
    prec = resolve_precision(precision)

    def fn(g_total, b_total, scales, mask):
        def one(s, m):
            g_s = g_total * s[:, None] * s[None, :]
            evals, q = jnp.linalg.eigh(g_s)
            evals = jnp.maximum(evals, 0.0)
            a = jnp.einsum('fg,fv->gv', q, b_total * s[:, None],
                           precision=prec,
                           preferred_element_type=s.dtype)
            w = jnp.einsum('fg,gv->fv', q,
                           a / (evals[:, None] + 1.0),
                           precision=prec,
                           preferred_element_type=s.dtype)
            return (s[:, None] * w) * m[None, :]

        return jax.vmap(one)(scales, mask).sum(axis=0)

    return obs_profile.profile_program(
        jax.jit(fn), "encoding.banded_refit", span="encoding.fit")


@obs_runtime.trace_signature("encoding.banded_refit")
def _banded_refit_trace_signature():
    f, v, block = _TRACE_F, _TRACE_V, _TRACE_BLOCK
    return [{"key": (f, v, block, resolve_precision(None)),
             "args": (_enc_aval(f, f), _enc_aval(f, v),
                      _enc_aval(block, f), _enc_aval(block, v))}]


# -- estimators -------------------------------------------------------

class RidgeEncoder:
    """Voxel-wise ridge encoding model with an on-device CV lambda
    sweep.

    Fits ``V`` independent ridge regressions ``y_v ≈ X w_v`` sharing
    one design ``X [T, F]``, selecting a per-voxel lambda from
    ``lambdas`` by contiguous k-fold cross-validation (held-out
    per-voxel Pearson r, averaged over folds; ties take the smallest
    lambda), then refitting on all data at the selected lambdas.

    Parameters
    ----------
    lambdas : sequence of positive floats, default DEFAULT_LAMBDAS
        Candidate regularization grid (sorted ascending internally).
    n_folds : int, default 5
        Contiguous CV folds over the first ``K * (T // K)`` samples;
        remainder rows stay in every training fold.
    fit_intercept : bool, default True
        Center ``X`` and ``Y`` (the usual ridge intercept handling);
        predictions add the stored means back.
    standardize : bool, default False
        Additionally scale design columns to unit std before fitting
        (zero-variance columns keep scale 1).
    lambda_block : int, optional
        Sweep the grid in equal blocks of this many lambdas (default:
        the whole grid in one block).  Bounds the sweep's transient
        memory and sets the checkpoint granularity.
    mesh : jax.sharding.Mesh, optional
        Passed to :func:`brainiak_tpu.ops.distla.gram`: the ``Xᵀ X``
        Gram shards the feature axis over the mesh when the
        replicated working set exceeds the distla budget.
    precision : jax.lax.Precision, optional
        Matmul precision (default: the ops-layer default, HIGHEST).

    Attributes (after fit)
    ----------------------
    W_ : [F, V] per-voxel coefficients (standardized design space).
    lambda_ : [V] the CV-selected lambda per voxel.
    cv_scores_ : [L, V] mean held-out correlation per (lambda, voxel).
    lambdas_ : [L] the ascending grid actually swept.
    x_mean_, x_scale_, y_mean_ : the preprocessing parameters
        ``predict`` applies (zeros/ones when disabled).
    """

    def __init__(self, lambdas=None, n_folds=5, fit_intercept=True,
                 standardize=False, lambda_block=None, mesh=None,
                 precision=None):
        self.lambdas = tuple(DEFAULT_LAMBDAS if lambdas is None
                             else lambdas)
        self.n_folds = int(n_folds)
        self.fit_intercept = bool(fit_intercept)
        self.standardize = bool(standardize)
        self.lambda_block = lambda_block
        self.mesh = mesh
        self.precision = precision

    # -- shared plumbing ----------------------------------------------
    def _validate_grid(self):
        grid = np.asarray(self.lambdas, dtype=np.float32)
        if grid.ndim != 1 or grid.size == 0:
            raise ValueError("lambdas must be a non-empty 1-D grid")
        if not np.all(np.isfinite(grid)) or np.any(grid <= 0):
            raise ValueError(
                "lambdas must be finite and positive "
                f"(got {self.lambdas!r})")
        return np.sort(grid)

    def _prepare_data(self, X, Y):
        x = np.asarray(X, dtype=np.float32)
        y = np.asarray(Y, dtype=np.float32)
        if x.ndim != 2 or y.ndim != 2 or x.shape[0] != y.shape[0]:
            raise ValueError(
                f"expected X [T, F] and Y [T, V] with matching T; "
                f"got {x.shape} and {y.shape}")
        if not (np.all(np.isfinite(x)) and np.all(np.isfinite(y))):
            raise ValueError(
                "X/Y contain NaN/Inf; encoding fits require finite "
                "data (mask or impute missing voxels first)")
        if self.n_folds < 2:
            raise ValueError(
                f"n_folds must be >= 2, got {self.n_folds}")
        t_f = x.shape[0] // self.n_folds
        if t_f < 2:
            raise ValueError(
                f"{x.shape[0]} samples cannot form {self.n_folds} "
                "folds of >= 2 samples (correlation scoring needs "
                "at least 2 held-out rows per fold)")
        self.x_mean_ = (x.mean(axis=0) if self.fit_intercept
                        else np.zeros(x.shape[1], np.float32))
        self.y_mean_ = (y.mean(axis=0) if self.fit_intercept
                        else np.zeros(y.shape[1], np.float32))
        xs = x - self.x_mean_
        if self.standardize:
            scale = xs.std(axis=0)
            self.x_scale_ = np.where(scale > 0, scale,
                                     1.0).astype(np.float32)
            xs = xs / self.x_scale_
        else:
            self.x_scale_ = np.ones(x.shape[1], np.float32)
        yc = y - self.y_mean_
        return xs, yc, t_f

    def _gram(self, xs):
        """``Xᵀ X`` through the distla budget dispatcher (replicated
        einsum under the budget; SUMMA ring with the feature axis
        mesh-sharded over it)."""
        return distla.gram(xs, mesh=self.mesh,
                           precision=self.precision,
                           normalize=False)

    def _sweep_blocks(self, program, fixed_args, grid, block, n_vox,
                      checkpoint_dir, checkpoint_every, fingerprint,
                      name):
        """Drive ``program(*fixed_args, block_rows)`` over
        equal-sized blocks of ``grid`` rows under the resilient-loop
        driver, filling the host [n_grid, V] score matrix.  Blocks
        are padded (repeating the last row) so every call shares one
        program shape; with ``checkpoint_dir`` a preempted sweep
        resumes at the last completed block.  ``block`` must already
        be normalized (the caller built the program with it — the
        padded rows must match its traced static shape)."""
        n = grid.shape[0]
        n_blocks = -(-n // block)

        def run_chunk(state, step, n_steps):
            # copy-on-write: the previous state is the rollback
            # target.  Host syncs are the contract here — finished
            # block scores must land in host state to be
            # checkpointable (the sweep program itself is sync-free).
            out = np.array(state["scores"],  # jaxlint: disable=JX002
                           copy=True)
            for b in range(step, step + n_steps):
                start = b * block
                stop = min(start + block, n)
                rows = grid[start:start + block]
                if rows.shape[0] < block:
                    pad = np.repeat(rows[-1:],
                                    block - rows.shape[0], axis=0)
                    rows = np.concatenate([rows, pad], axis=0)
                with obs_spans.span("encoding.sweep_chunk",
                                    attrs={"block": b,
                                           "rows": stop - start}):
                    got = np.asarray(  # jaxlint: disable=JX002
                        program(*fixed_args, jnp.asarray(rows)))
                out[start:stop] = got[:stop - start]
            return {"scores": out}, False

        zeros = np.zeros((n, n_vox), dtype=np.float32)
        state, _ = run_resilient_loop(
            run_chunk, {"scores": zeros}, n_blocks,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            fingerprint=fingerprint,
            template={"scores": np.zeros_like(zeros)}, name=name,
            progress_objective="scores", progress_direction="max")
        return state["scores"]

    def _fingerprint(self, checkpoint_dir, xs, yc, grid, block):
        if checkpoint_dir is None:
            return None
        # the grid AND the block size participate: resilient-loop
        # steps are counted in blocks, so a resume against the same
        # data but a different grid or block size must restart, not
        # mix (or silently skip) score rows
        return np.array(
            [array_digest(xs), array_digest(yc), array_digest(grid),
             float(self.n_folds), float(grid.shape[0]),
             float(block)])

    def _check_fitted(self):
        if not hasattr(self, "W_"):
            raise ValueError(
                f"this {type(self).__name__} is not fitted yet; "
                "call fit(X, Y) first")

    # -- fit / predict ------------------------------------------------
    def fit(self, X, Y, checkpoint_dir=None, checkpoint_every=1):
        """Fit per-voxel ridge with CV lambda selection.

        X : [T, F] design (stimulus/feature embedding per TR).
        Y : [T, V] responses (voxels).
        checkpoint_dir, checkpoint_every : persist the accumulated
            CV scores every ``checkpoint_every`` lambda blocks and
            resume a preempted sweep at the last completed block
            (the resilient fit contract every estimator honors).
        """
        self.lambdas_ = self._validate_grid()
        xs, yc, t_f = self._prepare_data(X, Y)
        f = xs.shape[1]
        v = yc.shape[1]
        with obs_spans.span("encoding.fit",
                            attrs={"estimator": "RidgeEncoder",
                                   "n_voxels": int(v),
                                   "n_features": int(f),
                                   "n_lambdas":
                                       int(self.lambdas_.size)}):
            g_total = self._gram(xs)
            prep = _prepare_program(
                xs.shape[0], self.n_folds, t_f, f, v,
                resolve_precision(self.precision))
            # X and Y cross the host-device boundary ONCE: the fold
            # tensors are sliced out of the full arrays inside the
            # program, and the sweep consumes its device outputs
            evals, a, p, y_folds_d, b_total = prep(
                jnp.asarray(xs), jnp.asarray(yc), g_total)
            n_lam = int(self.lambdas_.size)
            block = n_lam if self.lambda_block is None \
                else max(1, min(int(self.lambda_block), n_lam))
            sweep = _sweep_program(
                self.n_folds, t_f, f, v, block,
                resolve_precision(self.precision))
            scores = self._sweep_blocks(
                sweep, (evals, a, p, y_folds_d),
                self.lambdas_, block, v, checkpoint_dir,
                checkpoint_every,
                self._fingerprint(checkpoint_dir, xs, yc,
                                  self.lambdas_, block),
                name="encoding.fit")
            self.cv_scores_ = scores
            best = np.argmax(scores, axis=0)
            self.lambda_ = self.lambdas_[best]
            refit = _refit_program(
                f, v, resolve_precision(self.precision))
            self.W_ = np.asarray(refit(g_total, b_total,
                                       jnp.asarray(self.lambda_)))
        return self

    def predict(self, X):
        """Predicted responses [T, V] for a new design [T, F]."""
        self._check_fitted()
        x = np.asarray(X, dtype=np.float32)
        if x.ndim != 2 or x.shape[1] != self.W_.shape[0]:
            raise ValueError(
                f"expected X [T, {self.W_.shape[0]}], got {x.shape}")
        xs = (x - self.x_mean_) / self.x_scale_
        return xs @ self.W_ + self.y_mean_

    def score(self, X, Y):
        """Per-voxel Pearson r [V] between ``predict(X)`` and ``Y``
        — the serve engine's scoring semantics on host."""
        pred = self.predict(X)
        y = np.asarray(Y, dtype=np.float32)
        if y.shape != pred.shape:
            raise ValueError(
                f"expected Y {pred.shape}, got {y.shape}")
        pc = pred - pred.mean(axis=0)
        yc = y - y.mean(axis=0)
        den = np.sqrt((pc * pc).sum(axis=0) * (yc * yc).sum(axis=0))
        cov = (pc * yc).sum(axis=0)
        return np.where(den > 0, cov / np.where(den > 0, den, 1.0),
                        0.0).astype(np.float32)


class BandedRidgeEncoder(RidgeEncoder):
    """Banded ridge: one lambda per feature *band* (feature grouping
    — e.g. motion-energy vs. semantic embeddings), selected jointly
    per voxel over a candidate grid.

    Parameters (beyond :class:`RidgeEncoder`)
    -----------------------------------------
    bands : int array [F]
        Band id (0..n_bands-1) of every design column.
    candidates : [C, n_bands] array, optional
        Per-band lambda rows to sweep.  Default: the full Cartesian
        grid of ``lambdas`` over the bands — refused above
        ``max_candidates`` (pass explicit candidates, e.g. a random
        search, for many bands).
    candidate_block : int, default 8
        Candidates scored per program call (each costs one scaled
        ``eigh`` per fold; the block bounds transient memory and
        sets the checkpoint granularity).
    max_candidates : int, default 4096
        Cap on the default Cartesian grid.

    After fit, ``lambda_`` is [V, n_bands] (the selected candidate
    row per voxel) and ``cv_scores_`` is [C, V].
    """

    def __init__(self, bands, lambdas=None, candidates=None,
                 n_folds=5, fit_intercept=True, standardize=False,
                 candidate_block=8, mesh=None, precision=None,
                 max_candidates=4096):
        super().__init__(lambdas=lambdas, n_folds=n_folds,
                         fit_intercept=fit_intercept,
                         standardize=standardize, mesh=mesh,
                         precision=precision)
        self.bands = np.asarray(bands, dtype=np.int32)
        self.candidates = candidates
        self.candidate_block = int(candidate_block)
        self.max_candidates = int(max_candidates)

    def _candidate_grid(self):
        if self.bands.ndim != 1 or np.any(self.bands < 0):
            raise ValueError(
                "bands must be a 1-D array of non-negative band ids")
        n_bands = int(self.bands.max()) + 1
        if not np.array_equal(np.unique(self.bands),
                              np.arange(n_bands)):
            # sparse ids would silently inflate the Cartesian grid
            # (bands=[0, 5] -> a 6-band product of duplicates)
            raise ValueError(
                "bands ids must be dense 0..n_bands-1; got "
                f"{sorted(set(self.bands.tolist()))}")
        if self.candidates is not None:
            cand = np.asarray(self.candidates, dtype=np.float32)
            if cand.ndim != 2 or cand.shape[1] != n_bands:
                raise ValueError(
                    f"candidates must be [C, {n_bands}] for "
                    f"{n_bands} bands; got {cand.shape}")
            if not np.all(np.isfinite(cand)) or np.any(cand <= 0):
                raise ValueError(
                    "candidates must be finite and positive")
            return cand
        grid = self._validate_grid()
        n = grid.size ** n_bands
        if n > self.max_candidates:
            raise ValueError(
                f"the full {grid.size}^{n_bands} = {n} candidate "
                f"grid exceeds max_candidates={self.max_candidates}"
                "; pass an explicit candidates array")
        mesh_axes = np.meshgrid(*([grid] * n_bands), indexing="ij")
        return np.stack([m.ravel() for m in mesh_axes],
                        axis=1).astype(np.float32)

    def fit(self, X, Y, checkpoint_dir=None, checkpoint_every=1):
        """Fit banded ridge with joint per-voxel candidate selection
        (same resilient contract as :meth:`RidgeEncoder.fit`, chunked
        over candidate blocks)."""
        self.lambdas_ = self._validate_grid()
        xs, yc, t_f = self._prepare_data(X, Y)
        f = xs.shape[1]
        v = yc.shape[1]
        if self.bands.shape[0] != f:
            raise ValueError(
                f"bands has {self.bands.shape[0]} entries for "
                f"{f} design columns")
        cand = self._candidate_grid()
        self.candidates_ = cand
        scales = (1.0 / np.sqrt(cand[:, self.bands])).astype(
            np.float32)
        block = max(1, min(self.candidate_block, cand.shape[0]))
        with obs_spans.span("encoding.fit",
                            attrs={"estimator": "BandedRidgeEncoder",
                                   "n_voxels": int(v),
                                   "n_features": int(f),
                                   "n_candidates":
                                       int(cand.shape[0])}):
            g_total = self._gram(xs)
            prep = _banded_prepare_program(
                xs.shape[0], self.n_folds, t_f, f, v,
                resolve_precision(self.precision))
            # one transfer per operand; folds slice out on device
            x_folds_d, y_folds_d, g_tr, b_tr, b_total = prep(
                jnp.asarray(xs), jnp.asarray(yc), g_total)
            sweep = _banded_sweep_program(
                self.n_folds, t_f, f, v, block,
                resolve_precision(self.precision))
            scores = self._sweep_blocks(
                sweep, (g_tr, b_tr, x_folds_d, y_folds_d),
                scales, block, v, checkpoint_dir, checkpoint_every,
                self._fingerprint(checkpoint_dir, xs, yc, scales,
                                  block),
                name="encoding.fit")
            self.cv_scores_ = scores
            best = np.argmax(scores, axis=0)
            self.lambda_ = cand[best]
            self.W_ = self._banded_refit(
                g_total, b_total, scales, best, block, f, v)
        return self

    def _banded_refit(self, g_total, b_total, scales, best, block,
                      f, v):
        """Assemble the mixed-candidate [F, V] coefficient block by
        block: each program call refits one candidate block on all
        data and masks in exactly the voxel columns that selected a
        candidate of the block (blocks nobody selected are skipped
        host-side — no device work for unused candidates)."""
        refit = _banded_refit_program(
            f, v, block, resolve_precision(self.precision))
        w = np.zeros((f, v), dtype=np.float32)
        n = scales.shape[0]
        for start in range(0, n, block):
            stop = min(start + block, n)
            onehot = (best[None, :]
                      == np.arange(start, stop)[:, None])
            if not onehot.any():
                continue
            rows = scales[start:start + block]
            if rows.shape[0] < block:
                pad = block - rows.shape[0]
                rows = np.concatenate(
                    [rows, np.repeat(rows[-1:], pad, axis=0)],
                    axis=0)
                onehot = np.concatenate(
                    [onehot, np.zeros((pad, v), dtype=bool)],
                    axis=0)
            # host accumulation is the point: each block's masked
            # [F, V] contribution lands in the host coefficient
            # (bounded memory for any candidate count)
            w += np.asarray(refit(  # jaxlint: disable=JX002
                jnp.asarray(g_total), b_total, jnp.asarray(rows),
                jnp.asarray(onehot.astype(np.float32))))
        return w


# -- CI selfcheck (tools/run_checks.py `encoding` gate) ---------------

def selfcheck(out=None):
    """Smoke the encoding tier for the ``encoding`` CI gate (ENC001):
    sklearn-``Ridge`` per-voxel prediction parity at the CV-selected
    lambdas, the sharded raw-product Gram path on the CPU mesh, a
    banded fit, and retrace stability (a repeat fit must not rebuild
    any ``encoding.*`` program).  Prints a JSON verdict; returns 0 on
    pass, 1 on failure."""
    import json
    import sys

    from sklearn.linear_model import Ridge

    from ..obs import metrics as obs_metrics
    from ..parallel.mesh import (DEFAULT_VOXEL_AXIS, make_mesh,
                                 max_divisible_shards)

    stream = out or sys.stdout
    rng = np.random.RandomState(0)
    t, f, v = 48, 12, 32
    x = rng.randn(t, f).astype(np.float32)
    w0 = rng.randn(f, v).astype(np.float32)
    y = (x @ w0 + 0.5 * rng.randn(t, v)).astype(np.float32)
    lambdas = (1.0, 10.0, 100.0)

    errs = []
    # sharded raw-product Gram parity (the encoding Xᵀ X path over
    # the CPU mesh ring)
    mesh = make_mesh((DEFAULT_VOXEL_AXIS,),
                     (max_divisible_shards(f),))
    g_ring = np.asarray(distla.gram(x, mesh=mesh, force="summa",
                                    normalize=False))
    errs.append(float(np.max(np.abs(g_ring - x.T @ x)))
                / max(1.0, float(np.max(np.abs(x.T @ x)))))

    enc = None
    for _ in range(2):  # second fit must hit every program cache
        enc = RidgeEncoder(lambdas=lambdas, n_folds=3,
                           mesh=mesh).fit(x, y)
    pred = enc.predict(x)
    sk = np.empty_like(pred)
    for lam in np.unique(enc.lambda_):
        cols = enc.lambda_ == lam
        model = Ridge(alpha=float(lam)).fit(x, y[:, cols])
        sk[:, cols] = model.predict(x).reshape(t, -1)
    errs.append(float(np.max(np.abs(pred - sk)))
                / max(1.0, float(np.max(np.abs(sk)))))

    bands = np.repeat(np.arange(2), f // 2)
    for _ in range(2):
        banded = BandedRidgeEncoder(
            bands, lambdas=(1.0, 100.0), n_folds=3,
            candidate_block=2).fit(x, y)
    r = banded.score(x, y)
    ok_banded = bool(np.all(np.isfinite(r))) and r.shape == (v,)

    retrace = obs_metrics.counter("retrace_total")
    sites = {site: retrace.value(site=site)
             for site in ("encoding.prepare", "encoding.sweep",
                          "encoding.refit",
                          "encoding.banded_prepare",
                          "encoding.banded_sweep",
                          "encoding.banded_refit")
             if retrace.value(site=site)}
    tol = 1e-3
    sites_ok = {"encoding.prepare", "encoding.sweep",
                "encoding.refit"} <= set(sites)
    ok = (max(errs) < tol and ok_banded and sites_ok
          and all(c <= 1.0 for c in sites.values()))
    json.dump({"ok": bool(ok), "max_err": max(errs), "tol": tol,
               "banded_finite": ok_banded,
               "sites_present": sites_ok, "retraces": sites},
              stream)
    stream.write("\n")
    return 0 if ok else 1
