"""brainiak_tpu.encoding: massive voxel-wise encoding models.

The framework's heavy-read workload tier (ROADMAP open item 5):
batched per-voxel ridge regression with an on-device cross-validated
lambda sweep (:class:`RidgeEncoder`) and its per-feature-band
generalization (:class:`BandedRidgeEncoder`), built on the
eigendecomposition solver of "Scaling up ridge regression for brain
encoding in a massive individual fMRI dataset"
(https://arxiv.org/pdf/2403.19421).

The ``Xᵀ X`` Gram runs through :func:`brainiak_tpu.ops.distla.gram`
(budget-dispatched replicated vs. SUMMA-sharded), the sweep is one
jitted program per lambda/candidate block driven resiliently
(``fit(..., checkpoint_dir=)`` resumes mid-sweep), and fitted models
persist through :mod:`brainiak_tpu.serve.artifacts`
(``serve_kind="ridge_encoding"``) for batched held-out-scan scoring
in the serve engine.

See docs/encoding.md.
"""

from .ridge import (  # noqa: F401
    DEFAULT_LAMBDAS,
    BandedRidgeEncoder,
    RidgeEncoder,
    selfcheck,
)

__all__ = [
    "DEFAULT_LAMBDAS",
    "BandedRidgeEncoder",
    "RidgeEncoder",
    "selfcheck",
]
