"""Real-time closed-loop tier: per-TR streaming analysis.

Every other workload in the framework is throughput-bound; this tier
is **latency-bound** — a TR arrives every ~1–2 s and the subject must
see feedback well inside that window (the neurofeedback scenario,
ROADMAP item 4).  The pieces:

- :mod:`~brainiak_tpu.realtime.ingest` — the TR-source protocol
  (:class:`MemoryFeed`, :class:`DirectoryWatcher` over the fmrisim
  real-time generator's stream, :class:`StoreReplay` off a
  ``data/`` SubjectStore), with arrival-jitter metrics;
- :mod:`~brainiak_tpu.realtime.online` — incremental estimators with
  O(1)-per-TR state (:class:`OnlineZScore`, :class:`OnlineISC`,
  :class:`IncrementalEventSegment`), each one cached jitted step
  program (retraces <= 1 per scan, online == batch at every prefix);
- :mod:`~brainiak_tpu.realtime.loop` — :class:`RealtimeSession`, the
  deadline-driven closed-loop driver with checkpoint/resume and
  optional warm :class:`~brainiak_tpu.serve.service.ServeService`
  scoring through the ``low_latency=True`` submit path.

Gated by RT001 (``tools/run_checks.py``: online-vs-batch parity,
preempt/resume parity, retrace stability) and the ``realtime`` bench
tier (per-TR p99 + deadline-miss ratio, both lower-is-better).  See
docs/realtime.md.
"""

from .ingest import (DirectoryWatcher, MemoryFeed, StoreReplay,
                     TRSample, TRSource)
from .loop import RealtimeSession
from .online import IncrementalEventSegment, OnlineISC, OnlineZScore

__all__ = [
    "DirectoryWatcher",
    "IncrementalEventSegment",
    "MemoryFeed",
    "OnlineISC",
    "OnlineZScore",
    "RealtimeSession",
    "StoreReplay",
    "TRSample",
    "TRSource",
]
