"""The closed-loop driver: ingest → estimators → serve, per TR.

:class:`RealtimeSession` pipelines one TR at a time from a
:class:`~brainiak_tpu.realtime.ingest.TRSource` through an optional
online preprocessor, a set of incremental estimators
(:mod:`brainiak_tpu.realtime.online`), and optionally a warm
classifier/SRM scoring hop through a running
:class:`~brainiak_tpu.serve.service.ServeService` (submitted
``low_latency=True`` so a singleton request dispatches on the next
tick instead of waiting out the batch window), against a **hard
per-TR deadline**:

- every TR runs under a ``realtime.tr`` span; each stage's wall time
  feeds a per-stage :class:`~brainiak_tpu.obs.sketch.QuantileSketch`
  AND the ``realtime_stage_seconds{stage=}`` histogram, so ``/metrics``
  serves live per-stage p50/p99;
- a TR whose total latency (arrival → all outputs on host) exceeds
  ``deadline_s`` emits a ``deadline_exceeded`` record naming the TR
  and its stage breakdown and increments
  ``realtime_deadline_miss_total`` — the closed-loop SLO is the miss
  ratio plus the per-TR p99, both gated ``lower_is_better`` by the
  ``realtime`` bench tier;
- with ``checkpoint_dir`` the estimator states checkpoint every
  ``checkpoint_every`` TRs through
  :func:`~brainiak_tpu.resilience.guards.run_resilient_loop`; a
  preempted session re-run with the same arguments **resumes
  mid-scan**: the source seeks to the first unprocessed TR and the
  resumed states match an uninterrupted scan (the RT001 resume-parity
  gate).

Steady-state contract: every estimator advances through ONE cached
jitted step program, so a whole scan — any length — runs at
``retrace_total{site=realtime.*} <= 1`` per estimator
(:meth:`RealtimeSession.summary` reports the live counts).
"""

import time

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import sink as obs_sink
from ..obs import spans as obs_spans
from ..obs.sketch import QuantileSketch
from ..resilience.guards import run_resilient_loop

__all__ = ["RealtimeSession"]

#: state-dict key separator between estimator name and leaf name
_KEY_SEP = "."

#: stage names owned by the session itself — estimator names must
#: not shadow them (outputs, latency sketches, and checkpoint state
#: are all keyed by stage name)
_RESERVED_STAGES = frozenset({"preprocess", "serve", "total"})


class RealtimeSession:
    """Drive a closed-loop per-TR analysis over one scan.

    Parameters
    ----------
    source : :class:`~brainiak_tpu.realtime.ingest.TRSource`
        Per-TR volume source (in-memory feed, directory watcher over
        the fmrisim generator's stream, or a SubjectStore replay).
        Must support ``seek`` for checkpoint/resume.
    estimators : dict of name -> online estimator
        Incremental estimators (the :mod:`~brainiak_tpu.realtime
        .online` protocol: ``init_state``/``step``).  Names label
        stages, metrics, and checkpoint state leaves — so they must
        not contain ``"."``.
    preprocess : online estimator, optional
        Runs before the estimators each TR; its first output (e.g.
        :class:`~brainiak_tpu.realtime.online.OnlineZScore`'s ``z``)
        replaces the volume the estimators see.  Stage name:
        ``"preprocess"``.
    deadline_s : float
        Hard per-TR latency budget, measured from the sample's host
        arrival stamp to all outputs fetched.  A miss never aborts
        the scan — neurofeedback skips a frame, it does not stop the
        scanner — it is *recorded* (``deadline_exceeded`` event +
        miss counter) and the loop moves on.
    service, service_model : optional
        A started :class:`~brainiak_tpu.serve.service.ServeService`
        plus the model name to score each TR against (stage
        ``"serve"``; requests go ``low_latency=True`` with the TR's
        remaining deadline budget as both the request deadline and
        the ticket wait).  ``service_request`` customizes the
        request: a callable ``(tr_index, volume) -> Request``;
        the default sends ``volume[:, None]`` (one-TR scan) for
        subject ``service_subject``.
    name : str
        Label for checkpoints, spans, and the resilient loop.
    keep_outputs : int, optional
        Retain only the most recent N per-TR output dicts (None —
        the default — keeps the whole scan).  Set for long or
        open-ended live sessions: the aggregates (``summary()``,
        the metric histograms) are O(1) regardless, but the raw
        per-TR outputs are ~the volume size each and would
        otherwise grow without bound.
    """

    def __init__(self, source, estimators, preprocess=None,
                 deadline_s=1.0, service=None, service_model=None,
                 service_subject=0, service_request=None,
                 name="realtime", keep_outputs=None):
        for key in estimators:
            if _KEY_SEP in key:
                raise ValueError(
                    f"estimator name {key!r} must not contain "
                    f"{_KEY_SEP!r} (it separates checkpoint state "
                    "leaves)")
            if key in _RESERVED_STAGES:
                raise ValueError(
                    f"estimator name {key!r} is reserved (built-in "
                    "stage names: "
                    f"{', '.join(sorted(_RESERVED_STAGES))}) — it "
                    "would collide with that stage's outputs, "
                    "timings, and checkpoint state")
        self.source = source
        self.estimators = dict(estimators)
        self.preprocess = preprocess
        self.deadline_s = float(deadline_s)
        self.service = service
        self.service_model = service_model
        self.service_subject = service_subject
        self.service_request = service_request
        self.name = name
        if keep_outputs is not None and int(keep_outputs) < 1:
            raise ValueError(
                f"keep_outputs must be >= 1 or None, got "
                f"{keep_outputs}")
        self.keep_outputs = None if keep_outputs is None \
            else int(keep_outputs)
        self._outputs = {}       # tr -> output dict (re-runs overwrite)
        self._sketches = {}      # stage -> QuantileSketch
        self._n_processed = 0
        self._n_misses = 0
        self._source_pos = 0
        self._slo_snapshot = None  # (step, counts, sketches)
        # retrace reporting is a DELTA from construction (the
        # InferenceEngine idiom): a later session in the same
        # process must not be charged the programs an earlier one
        # legitimately built
        self._retrace_base = self._retrace_counts()
        obs_metrics.gauge(
            "realtime_deadline_budget_seconds", unit="s",
            help="per-TR latency budget of the running "
                 "session").set(self.deadline_s, session=self.name)
        # pre-register the miss series at 0: a healthy scan must
        # expose realtime_deadline_miss_total{session=} == 0 on
        # /metrics (an absent series cannot be alerted on)
        obs_metrics.counter(
            "realtime_deadline_miss_total",
            help="TRs whose processing exceeded the per-TR "
                 "deadline").inc(0, session=self.name)

    # -- state plumbing -----------------------------------------------
    def _stages(self):
        names = []
        if self.preprocess is not None:
            names.append("preprocess")
        names.extend(self.estimators)
        if self.service is not None:
            names.append("serve")
        return names

    def _init_state(self):
        state = {}
        if self.preprocess is not None:
            for leaf, value in self.preprocess.init_state().items():
                state[f"preprocess{_KEY_SEP}{leaf}"] = value
        for est_name, est in self.estimators.items():
            for leaf, value in est.init_state().items():
                state[f"{est_name}{_KEY_SEP}{leaf}"] = value
        return state

    @staticmethod
    def _slice_state(state, prefix):
        head = prefix + _KEY_SEP
        return {key[len(head):]: value
                for key, value in state.items()
                if key.startswith(head)}

    @staticmethod
    def _merge_state(state, prefix, sub):
        for leaf, value in sub.items():
            state[f"{prefix}{_KEY_SEP}{leaf}"] = value

    def _fingerprint(self, n_trs):
        names = sorted(self.estimators)
        base = [float(n_trs), float(len(names)),
                float(sum((i + 1) * sum(map(ord, n))
                          for i, n in enumerate(names))),
                float(0 if self.preprocess is None else 1),
                float(0 if self.service is None else 1)]
        # per-estimator configuration digests (sorted by name):
        # same shapes + names but DIFFERENT parameters (reference
        # group, event patterns) must refuse a checkpoint, not
        # silently mix runs.  An estimator without config_digest
        # contributes 0 (checked by name/count only).
        for name in names:
            digest = getattr(self.estimators[name],
                             "config_digest", None)
            base.append(float(digest()) if callable(digest)
                        else 0.0)
        pre = getattr(self.preprocess, "config_digest", None)
        base.append(float(pre()) if callable(pre) else 0.0)
        return np.array(base)

    # -- instrumentation ----------------------------------------------
    def _restore_or_snapshot_slo(self, step):
        """Chunk-entry SLO-accounting snapshot: a guard rollback
        re-runs the chunk deterministically, and the replayed TRs
        must not inflate the gated numbers (n_trs, miss ratio, the
        latency percentiles).  The process-global ``realtime_*``
        metric counters stay monotonic (Prometheus semantics — a
        rollback shows up there as the extra work it really was);
        only this session's summary() is de-duplicated."""
        if self._slo_snapshot is not None \
                and self._slo_snapshot[0] == step:
            _, n_processed, n_misses, sketches = self._slo_snapshot
            self._n_processed = n_processed
            self._n_misses = n_misses
            self._sketches = {
                stage: QuantileSketch.from_dict(payload)
                for stage, payload in sketches.items()}
        self._slo_snapshot = (
            step, self._n_processed, self._n_misses,
            {stage: sketch.to_dict()
             for stage, sketch in self._sketches.items()})

    def _observe_stage(self, stage, seconds):
        self._sketches.setdefault(stage, QuantileSketch()).observe(
            max(seconds, 0.0))
        obs_metrics.histogram(
            "realtime_stage_seconds", unit="s",
            help="per-TR wall time of each closed-loop "
                 "stage").observe(max(seconds, 0.0), stage=stage,
                                  session=self.name)

    # -- the per-TR pipeline ------------------------------------------
    def _process_tr(self, sample, state):
        tr = sample.index
        stage_s = {}
        with obs_spans.span("realtime.tr",
                            attrs={"tr": int(tr),
                                   "session": self.name}) as frame:
            out = {"tr": int(tr)}
            volume = sample.volume
            t0 = time.perf_counter()
            if self.preprocess is not None:
                sub = self._slice_state(state, "preprocess")
                sub, pre_out = self.preprocess.step(sub, volume)
                # first output is the transformed volume; fetch it
                # (the fetch is the sync that makes the stage time
                # real, not an async-dispatch enqueue time)
                first = next(iter(pre_out.values()))
                volume = np.asarray(first)
                self._merge_state(state, "preprocess", sub)
                stage_s["preprocess"] = time.perf_counter() - t0
            for est_name, est in self.estimators.items():
                t1 = time.perf_counter()
                sub = self._slice_state(state, est_name)
                sub, est_out = est.step(sub, volume)
                out[est_name] = {key: np.asarray(value)
                                 for key, value in est_out.items()}
                self._merge_state(state, est_name, sub)
                stage_s[est_name] = time.perf_counter() - t1
            if self.service is not None:
                stage_s["serve"] = self._serve_stage(
                    sample, volume, out)
            latency = time.monotonic() - sample.t_arrival
            out["latency_s"] = latency
            miss = latency > self.deadline_s
            out["deadline_miss"] = miss
            frame.set("latency_s", round(latency, 6))
            frame.set("deadline_miss", miss)
        for stage, seconds in stage_s.items():
            self._observe_stage(stage, seconds)
        self._observe_stage("total", latency)
        obs_metrics.histogram(
            "realtime_tr_latency_seconds", unit="s",
            help="arrival-to-outputs latency per TR").observe(
                latency, session=self.name)
        if miss:
            self._n_misses += 1
            obs_metrics.counter(
                "realtime_deadline_miss_total",
                help="TRs whose processing exceeded the per-TR "
                     "deadline").inc(session=self.name)
            obs_sink.event(
                "deadline_exceeded", session=self.name, tr=int(tr),
                latency_s=round(latency, 6),
                deadline_s=self.deadline_s,
                stages={stage: round(seconds, 6)
                        for stage, seconds in stage_s.items()})
        self._n_processed += 1
        self._outputs[tr] = out
        if self.keep_outputs is not None:
            while len(self._outputs) > self.keep_outputs:
                self._outputs.pop(min(self._outputs))
        return state

    def _serve_stage(self, sample, volume, out):
        from ..serve.batching import Request

        t2 = time.perf_counter()
        remaining = self.deadline_s - (time.monotonic()
                                       - sample.t_arrival)
        budget = max(remaining, 1e-3)
        if self.service_request is not None:
            request = self.service_request(sample.index, volume)
        else:
            request = Request(
                request_id=f"{self.name}-tr{sample.index}",
                x=np.asarray(volume)[:, None],
                subject=self.service_subject,
                model=self.service_model)
        request.deadline_s = budget
        ticket = self.service.submit(request,
                                     model=self.service_model,
                                     low_latency=True)
        try:
            record = ticket.result(timeout=budget)
        except TimeoutError:
            # the deadline accounting below records the miss; the
            # abandoned ticket still resolves (exactly-one-record
            # contract) — it is just too late to matter
            out["serve"] = None
            out["serve_timeout"] = True
        else:
            out["serve"] = record.result if record.ok else None
            if not record.ok:
                out["serve_error"] = record.error
        return time.perf_counter() - t2

    # -- driving ------------------------------------------------------
    def run(self, n_trs=None, checkpoint_dir=None,
            checkpoint_every=25):
        """Process the scan; returns :meth:`summary`.

        ``n_trs`` defaults to ``len(source)``; a source that ends
        early simply ends the scan.  With ``checkpoint_dir`` the
        estimator states are persisted every ``checkpoint_every``
        TRs and a later call with the same arguments resumes at the
        first unprocessed TR (the source is ``seek``-ed there) —
        outputs before the resume point are not re-emitted, but the
        resumed states (and every output after) match an
        uninterrupted scan.
        """
        if n_trs is None:
            n_trs = len(self.source)
        n_trs = int(n_trs)
        self._source_pos = None  # force the first seek

        def run_chunk(state, step, n_steps):
            # shallow-copy: _process_tr rebinds leaves on this dict,
            # and the resilient loop's rollback snapshot must keep
            # the chunk-entry state intact
            state = dict(state)
            # a guard rollback re-runs this chunk from the same
            # step; restore the SLO accounting (TR/miss counts,
            # latency sketches) to its chunk-entry snapshot so the
            # replayed TRs are not double-counted in summary()
            self._restore_or_snapshot_slo(step)
            if self._source_pos != step:
                self.source.seek(step)
                self._source_pos = step
            for _ in range(n_steps):
                sample = self.source.next()
                if sample is None:
                    return state, True  # scan ended early
                state = self._process_tr(sample, state)
                self._source_pos = sample.index + 1
            return state, False

        state, _ = run_resilient_loop(
            run_chunk, self._init_state(), n_trs,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            fingerprint=self._fingerprint(n_trs),
            name=self.name, guard_nan_only=True)
        self._final_state = state
        return self.summary()

    # -- reporting ----------------------------------------------------
    @property
    def outputs(self):
        """Per-TR output dicts, in TR order (this process's TRs —
        a resumed session holds the TRs after the resume point)."""
        return [self._outputs[tr] for tr in sorted(self._outputs)]

    def estimator_state(self, name):
        """Final state leaves of one estimator after :meth:`run`
        (host arrays)."""
        return {leaf: np.asarray(value) for leaf, value
                in self._slice_state(self._final_state,
                                     name).items()}

    @staticmethod
    def _retrace_counts():
        sites = {}
        for labels, value in obs_metrics.counter(
                "retrace_total").samples():
            site = str(labels.get("site", ""))
            if site.startswith("realtime."):
                sites[site] = value
        return sites

    def retraces(self):
        """``retrace_total{site=realtime.*}`` growth SINCE this
        session was constructed — the steady-state zero-retrace
        contract, readable mid-scan.  A delta, not the process
        total: programs an earlier session in the same process
        built (one per shape, by design) are not charged to this
        one."""
        return {site: value - self._retrace_base.get(site, 0.0)
                for site, value in self._retrace_counts().items()}

    def summary(self):
        """Scan-level aggregate: TRs processed, per-stage and total
        latency percentiles, deadline misses, and the realtime
        retrace counts."""
        stages = {}
        for stage, sketch in self._sketches.items():
            stages[stage] = {
                "count": sketch.count,
                "p50_s": sketch.quantile(0.50),
                "p99_s": sketch.quantile(0.99),
                "max_s": sketch.max,
            }
        return {
            "session": self.name,
            "n_trs": self._n_processed,
            "n_deadline_misses": self._n_misses,
            "deadline_miss_ratio": (
                self._n_misses / self._n_processed
                if self._n_processed else 0.0),
            "deadline_s": self.deadline_s,
            "stages": stages,
            "p99_latency_s": (
                self._sketches["total"].quantile(0.99)
                if "total" in self._sketches else None),
            "retraces": self.retraces(),
        }
