"""Per-TR ingest: the TR-source protocol of the closed-loop tier.

A **TR source** delivers one flattened ``[V]`` volume per tick of a
scan, stamped with its host arrival time — the latency clock every
downstream deadline measures from.  Three sources cover the
closed-loop lifecycles:

- :class:`MemoryFeed` — an in-memory ``[T, V]`` array (or an
  iterable of volumes, e.g. the fmrisim generator's
  :class:`~brainiak_tpu.utils.fmrisim_real_time_generator
  .RealtimeStream` with a mask), optionally paced at one volume per
  ``tr_s`` — the simulation/bench mode;
- :class:`DirectoryWatcher` — polls a directory for the
  ``rt_<TR>.npy`` files the fmrisim real-time generator CLI writes,
  yielding each volume as it lands (half-written files are retried,
  never surfaced) — the scanner-adjacent mode;
- :class:`StoreReplay` — replays one subject of a
  :class:`~brainiak_tpu.data.store.SubjectStore` column by column —
  the archived-scan replay mode.

Every source shares the instrumentation of :class:`TRSource`: a
``realtime_trs_total{source=}`` counter, and **arrival jitter**
(observed inter-arrival time minus the nominal TR period) into the
``realtime_arrival_jitter_seconds`` histogram — the scanner-clock
health signal a closed-loop operator watches next to the processing
deadline.  All sources support :meth:`~TRSource.seek`, which is what
lets a checkpointed :class:`~brainiak_tpu.realtime.RealtimeSession`
resume mid-scan: the resumed loop seeks the source to the first
unprocessed TR.
"""

import os
import time

import numpy as np

from ..obs import metrics as obs_metrics
from ..utils.utils import MonotonicPacer

__all__ = ["DirectoryWatcher", "MemoryFeed", "StoreReplay",
           "TRSample", "TRSource"]


class TRSample:
    """One ingested TR: the flattened ``[V]`` volume, its scan
    index, and the host arrival stamp (``time.monotonic`` — the
    deadline clock's zero for this TR)."""

    __slots__ = ("index", "volume", "t_arrival")

    def __init__(self, index, volume, t_arrival):
        self.index = int(index)
        self.volume = volume
        self.t_arrival = float(t_arrival)

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"TRSample(index={self.index}, "
                f"volume[{self.volume.shape[0]}])")


class TRSource:
    """Base TR source: iteration protocol + arrival instrumentation.

    Subclasses implement :meth:`_read` (volume for one index, or
    None when the scan is over) and set ``n_trs`` (None for
    unbounded live streams) and ``tr_s`` (the nominal TR period the
    jitter metric is measured against; 0 disables jitter — an
    unpaced replay has no scanner clock to be late against).
    """

    #: label stamped on this source's metrics
    source_name = "source"

    def __init__(self, tr_s=0.0, n_trs=None):
        self.tr_s = float(tr_s)
        self.n_trs = n_trs
        self._pos = 0
        self._last_arrival = None
        self._pacer = MonotonicPacer(self.tr_s)

    # -- the protocol -------------------------------------------------
    def _read(self, index):
        """Volume ``[V]`` for TR ``index``; None = end of scan.
        Blocking (a live watcher waits for the file) is allowed —
        the wait is the arrival time the sample stamps."""
        raise NotImplementedError

    def seek(self, index):
        """Position the source so the next sample is TR ``index``
        (the resume contract: a restored session seeks to its
        checkpoint step).  Forgets the jitter baseline and the
        pacing schedule — the gap across a preemption is downtime,
        not scanner jitter."""
        self._pos = int(index)
        self._last_arrival = None
        self._pacer.reset()
        return self

    def _pace(self):
        """Hold replayed sources to the scanner period (the shared
        :class:`~brainiak_tpu.utils.utils.MonotonicPacer` absolute
        schedule — consumer time counts against the period, pacing
        never drifts).  No-op for ``tr_s == 0``."""
        self._pacer.wait()

    def __len__(self):
        if self.n_trs is None:
            raise TypeError(f"{type(self).__name__} is unbounded")
        return int(self.n_trs)

    def next(self):
        """The next :class:`TRSample`, or None at end of scan."""
        volume = self._read(self._pos)
        if volume is None:
            return None
        sample = TRSample(self._pos, volume, time.monotonic())
        self._pos += 1
        self._observe_arrival(sample)
        return sample

    def __iter__(self):
        while True:
            sample = self.next()
            if sample is None:
                return
            yield sample

    # -- instrumentation ----------------------------------------------
    def _observe_arrival(self, sample):
        obs_metrics.counter(
            "realtime_trs_total",
            help="TRs ingested by realtime sources").inc(
                source=self.source_name)
        last = self._last_arrival
        self._last_arrival = sample.t_arrival
        if last is None or self.tr_s <= 0.0:
            return
        # jitter = how late (positive) or early (negative) this TR
        # arrived vs the nominal scanner period; the histogram keeps
        # the magnitude (sketch-backed quantiles need positives) and
        # the signed value rides the gauge
        jitter = (sample.t_arrival - last) - self.tr_s
        obs_metrics.gauge(
            "realtime_arrival_jitter_last_seconds", unit="s",
            help="signed arrival jitter of the latest TR "
                 "(inter-arrival minus nominal TR)").set(
                jitter, source=self.source_name)
        obs_metrics.histogram(
            "realtime_arrival_jitter_seconds", unit="s",
            help="absolute arrival jitter per TR").observe(
                abs(jitter), source=self.source_name)


class MemoryFeed(TRSource):
    """In-memory TR source over a ``[T, V]`` array.

    ``data`` may be a ``[T, V]`` array, a list of ``[V]`` volumes,
    or an fmrisim :class:`~brainiak_tpu.utils
    .fmrisim_real_time_generator.RealtimeStream` together with
    ``mask`` (volumes are flattened through ``mask > 0``).
    ``tr_s > 0`` paces delivery at one volume per period (sleeping
    in :meth:`_read`), simulating the scanner clock — and giving the
    jitter metric something real to measure.
    """

    source_name = "memory"

    def __init__(self, data, mask=None, tr_s=0.0):
        if hasattr(data, "brain"):  # RealtimeStream
            brain = np.asarray(data.brain)
            if mask is None:
                mask = np.asarray(data.mask)
            flat = brain[mask > 0]          # [V, T]
            rows = np.ascontiguousarray(flat.T)
        else:
            rows = np.asarray(data)
            if rows.ndim != 2:
                raise ValueError(
                    "MemoryFeed expects [T, V] data (or a "
                    f"RealtimeStream); got shape {rows.shape}")
            if mask is not None:
                rows = rows[:, np.asarray(mask).ravel() > 0]
        self.rows = rows
        super().__init__(tr_s=tr_s, n_trs=rows.shape[0])

    def _read(self, index):
        if index >= self.rows.shape[0]:
            return None
        self._pace()
        return self.rows[index]


class DirectoryWatcher(TRSource):
    """Watch a directory for the fmrisim generator's ``rt_<TR>.npy``
    stream, yielding each volume as it lands.

    ``mask`` (array, or the directory's ``mask.npy`` — resolved
    lazily at the first volume read, so a watcher started before
    the producer wrote its metadata still picks the mask up)
    flattens the 3-D volumes to ``[V]``.  A file that exists but
    fails to load
    (half-written by the producer) is retried until ``timeout_s``
    (counted in ``realtime_ingest_retries_total``); timing out —
    no file, no producer progress — ends the scan when ``n_trs`` is
    None, or raises :class:`TimeoutError` for a bounded scan that
    goes quiet mid-way.
    """

    source_name = "directory"

    def __init__(self, path, mask=None, tr_s=0.0, n_trs=None,
                 timeout_s=30.0, poll_s=0.02):
        super().__init__(tr_s=tr_s, n_trs=n_trs)
        self.path = str(path)
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s)
        # mask=None defers resolution to the FIRST volume read: a
        # watcher started before the producer finished simulating
        # must not silently lock in "unmasked" — the generator
        # writes mask.npy before any rt_*.npy, so once a volume
        # exists the mask question is settled
        self._mask_pending = mask is None
        self.mask = None if mask is None \
            else (np.asarray(mask) > 0)

    def _resolve_mask(self):
        if self._mask_pending:
            mask_path = os.path.join(self.path, "mask.npy")
            if os.path.exists(mask_path):
                self.mask = np.load(mask_path) > 0
            self._mask_pending = False

    def _file_for(self, index):
        return os.path.join(self.path, f"rt_{index:0>3}.npy")

    def _read(self, index):
        if self.n_trs is not None and index >= int(self.n_trs):
            return None
        deadline = time.monotonic() + self.timeout_s
        path = self._file_for(index)
        while True:
            if os.path.exists(path):
                try:
                    vol = np.load(path, allow_pickle=False)
                except (OSError, ValueError):
                    # half-written by the producer: retry until the
                    # write completes (numpy writes the header last
                    # on some paths, so a partial file raises)
                    obs_metrics.counter(
                        "realtime_ingest_retries_total",
                        help="half-written volume reads retried "
                             "by the directory watcher").inc(
                            source=self.source_name)
                else:
                    self._resolve_mask()
                    if self.mask is not None:
                        vol = np.asarray(vol)[self.mask]
                    return np.asarray(vol).ravel()
            if time.monotonic() >= deadline:
                if self.n_trs is None:
                    return None  # open-ended scan: quiet = over
                raise TimeoutError(
                    f"TR {index} ({path}) did not arrive within "
                    f"{self.timeout_s}s (scan of {self.n_trs} TRs "
                    "went quiet)")
            time.sleep(self.poll_s)


class StoreReplay(TRSource):
    """Replay one subject of an on-disk
    :class:`~brainiak_tpu.data.store.SubjectStore` TR by TR.

    The subject's ``[V, T]`` array is memmap-friendly
    (:meth:`SubjectStore.open`), so the replay reads one column per
    tick rather than the whole scan.  ``tr_s > 0`` paces the replay
    at the scanner period.
    """

    source_name = "store"

    def __init__(self, store, subject=0, tr_s=0.0):
        self._data = store.open(int(subject))  # [V, T]
        super().__init__(tr_s=tr_s, n_trs=self._data.shape[1])

    def _read(self, index):
        if index >= self._data.shape[1]:
            return None
        self._pace()
        # one column off the (possibly memmapped) subject
        return np.asarray(self._data[:, index])
