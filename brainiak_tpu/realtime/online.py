"""Incremental per-TR estimators with O(1)-per-TR state.

The closed-loop tier cannot re-run a batch estimator per TR — at TR
``t`` that costs O(t) and the per-TR latency grows through the scan
until the deadline breaks.  Every estimator here advances **constant
state** by one jitted step per TR, and the step is built exactly once
per (shape, config) through a ``counted_cache`` builder, so a whole
scan runs at **retraces <= 1 per estimator** (the RT001 gate's
runtime contract):

- :class:`OnlineZScore` — per-voxel running z-scoring via Welford
  moments (count, mean ``[V]``, M2 ``[V]``); at TR ``t`` emits the
  volume standardized against the running prefix moments — exactly
  ``(x_t - mean(X[:t+1])) / std(X[:t+1], ddof=1)``;
- :class:`OnlineISC` — intersubject correlation of the live subject
  against a reference group from rolling sufficient statistics
  (sums, squares, cross-products): leave-one-out (vs the reference
  mean time course — row 0 of :func:`brainiak_tpu.isc.isc` on the
  stacked prefix) or pairwise (vs each reference subject),
  cumulative and optionally windowed (ring buffer of the last ``W``
  TRs, still O(V·R) work per TR);
- :class:`IncrementalEventSegment` — forward-only HMM event
  segmentation carrying ONLY the scaled log-alpha row ``[K+1]`` from
  the fused batch scan's :func:`~brainiak_tpu.eventseg.event
  .forward_step` (no backward pass, nothing O(T)); each TR emits the
  current-event posterior given the data so far, equal to the batch
  forward pass's scaled alpha at every prefix.

Shared protocol (duck-typed; :class:`~brainiak_tpu.realtime
.RealtimeSession` drives it): ``init_state() -> dict`` of named
arrays (flat — checkpointable by
:func:`~brainiak_tpu.resilience.guards.run_resilient_loop`),
``step(state, volume) -> (state, outputs)`` with ``outputs`` a dict
of device arrays, and ``state_nbytes`` for capacity planning (the
state-size table in docs/realtime.md).
"""

import numpy as np

from ..obs import profile as obs_profile
from ..obs import runtime as obs_runtime

__all__ = ["IncrementalEventSegment", "OnlineISC", "OnlineZScore"]


def _canonical_dtype(dtype):
    """The estimator state dtype: ``None`` means jax's canonical
    float (float32, or float64 under ``jax_enable_x64`` — so parity
    tests run at full precision and the TPU path stays fp32 without
    a silent downcast)."""
    import jax.numpy as jnp
    if dtype is None:
        return jnp.zeros(0).dtype
    return jnp.asarray(np.zeros(0, dtype=dtype)).dtype


# ---------------------------------------------------------------------------
# online z-scoring (Welford moments)

def _zscore_step_core(n, mean, m2, x):
    import jax.numpy as jnp
    n1 = n + 1.0
    delta = x - mean
    mean1 = mean + delta / n1
    m21 = m2 + delta * (x - mean1)
    var = m21 / jnp.maximum(n1 - 1.0, 1.0)
    std = jnp.sqrt(var)
    z = jnp.where(std > 0, (x - mean1) / std, 0.0)
    return n1, mean1, m21, z


@obs_runtime.counted_cache("realtime.zscore_step")
def _zscore_program(v, dtype):
    """The jitted Welford step for one (V, dtype) — built once per
    scan shape; misses count as
    ``retrace_total{site=realtime.zscore_step}``."""
    import jax
    del v, dtype  # cache key only: shapes specialize inside jit
    return obs_profile.profile_program(
        jax.jit(_zscore_step_core), "realtime.zscore_step",
        span="realtime.tr")


@obs_runtime.trace_signature("realtime.zscore_step")
def _zscore_trace_signature():
    import jax
    import jax.numpy as jnp

    v = 5

    def a(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.float32)

    return [{"key": (v, "float32"),
             "args": (a(), a(v), a(v), a(v))}]


class OnlineZScore:
    """Per-voxel running z-score: Welford moments in O(V) state.

    At TR ``t`` the emitted volume equals the batch
    ``(x_t - mean(X[:t+1])) / std(X[:t+1], ddof=1)`` (constant
    voxels emit 0; the first TR emits 0 everywhere — a 1-sample
    std is undefined).  The state is 2 ``[V]`` arrays + a scalar.
    """

    def __init__(self, n_voxels, dtype=None):
        self.n_voxels = int(n_voxels)
        self.dtype = _canonical_dtype(dtype)

    def init_state(self):
        v = self.n_voxels
        return {"n": np.zeros((), dtype=np.float64),
                "mean": np.zeros(v, dtype=self.dtype),
                "m2": np.zeros(v, dtype=self.dtype)}

    def config_digest(self):
        """Configuration digest folded into the session checkpoint
        fingerprint (resuming under a different configuration must
        refuse, not silently mix)."""
        return float(self.n_voxels)

    @property
    def state_nbytes(self):
        return 8 + 2 * self.n_voxels * self.dtype.itemsize

    def step(self, state, volume):
        import jax.numpy as jnp
        program = _zscore_program(self.n_voxels, str(self.dtype))
        n, mean, m2, z = program(
            jnp.asarray(np.asarray(state["n"]), dtype=self.dtype),
            jnp.asarray(state["mean"], dtype=self.dtype),
            jnp.asarray(state["m2"], dtype=self.dtype),
            jnp.asarray(volume, dtype=self.dtype))
        return ({"n": n, "mean": mean, "m2": m2},
                {"z": z})


# ---------------------------------------------------------------------------
# online ISC (rolling sufficient statistics)

def _pearson_from_sums(n, sx, sy, sxx, syy, sxy):
    """Pearson r per (voxel, reference) from running sums.

    sx/sxx: [V]; sy/syy/sxy: [V, R] -> [V, R].  Undefined
    correlations (fewer than 2 samples, constant series) are NaN —
    the same convention as the batch :func:`brainiak_tpu.isc.isc`.
    """
    import jax.numpy as jnp
    num = n * sxy - sx[:, None] * sy
    den_x = n * sxx - sx * sx
    den_y = n * syy - sy * sy
    den = jnp.sqrt(jnp.maximum(den_x[:, None], 0.0)
                   * jnp.maximum(den_y, 0.0))
    return jnp.where((den > 0) & (n > 1), num / den, jnp.nan)


def _isc_step_cum_core(n, x0, y0, sx, sxx, sy, syy, sxy, x, y):
    """Advance the cumulative sufficient statistics by one TR.

    The sums are of SHIFTED samples ``x - x0`` / ``y - y0`` with the
    first TR as the anchor: Pearson r is shift-invariant, and the
    raw-moment formula ``n*sxx - sx*sx`` on unshifted fMRI
    intensities (mean >> std) would cancel catastrophically in
    float32 — the anchored moments keep the subtraction at the
    signal's own scale.
    """
    import jax.numpy as jnp
    first = n == 0
    x01 = jnp.where(first, x, x0)
    y01 = jnp.where(first, y, y0)
    xs = x - x01
    ys = y - y01
    n1 = n + 1.0
    sx1 = sx + xs
    sxx1 = sxx + xs * xs
    sy1 = sy + ys
    syy1 = syy + ys * ys
    sxy1 = sxy + xs[:, None] * ys
    corr = _pearson_from_sums(n1, sx1, sy1, sxx1, syy1, sxy1)
    return (n1, x01, y01, sx1, sxx1, sy1, syy1, sxy1), corr


def _make_isc_step_core(window):
    """Step core for one static window size (0 = cumulative only).

    The windowed half keeps a ring buffer of the subject's last
    ``window`` volumes (anchor-shifted, like every moment here —
    see :func:`_isc_step_cum_core`); the reference rows leaving the
    window are supplied by the host (the estimator holds the full
    reference array), so the windowed sufficient statistics
    subtract the outgoing (x, y) pair exactly.
    """
    import jax.numpy as jnp

    if not window:
        def core(n, x0, y0, sx, sxx, sy, syy, sxy, x, y):
            state, corr = _isc_step_cum_core(
                n, x0, y0, sx, sxx, sy, syy, sxy, x, y)
            return state + (corr,)
        return core

    w = int(window)

    def core(n, x0, y0, sx, sxx, sy, syy, sxy, xbuf,
             wsx, wsxx, wsy, wsyy, wsxy, x, y, y_out, t):
        (n1, x01, y01, sx1, sxx1, sy1, syy1, sxy1), corr = \
            _isc_step_cum_core(n, x0, y0, sx, sxx, sy, syy, sxy,
                               x, y)
        xs = x - x01
        ys = y - y01
        slot = jnp.mod(t, w)
        full = t >= w
        x_out = jnp.where(full, xbuf[slot], 0.0)
        yo = jnp.where(full, y_out - y01, 0.0)
        wsx1 = wsx + xs - x_out
        wsxx1 = wsxx + xs * xs - x_out * x_out
        wsy1 = wsy + ys - yo
        wsyy1 = wsyy + ys * ys - yo * yo
        wsxy1 = wsxy + xs[:, None] * ys - x_out[:, None] * yo
        xbuf1 = xbuf.at[slot].set(xs)
        wn = jnp.minimum(n1, float(w))
        wcorr = _pearson_from_sums(wn, wsx1, wsy1, wsxx1, wsyy1,
                                   wsxy1)
        return (n1, x01, y01, sx1, sxx1, sy1, syy1, sxy1, xbuf1,
                wsx1, wsxx1, wsy1, wsyy1, wsxy1, corr, wcorr)

    return core


@obs_runtime.counted_cache("realtime.isc_step")
def _isc_program(v, r, window, dtype):
    """The jitted ISC sufficient-statistics step for one
    (V, R, window, dtype) — built once per scan configuration."""
    import jax
    del v, r, dtype  # cache key only
    return obs_profile.profile_program(
        jax.jit(_make_isc_step_core(window)), "realtime.isc_step",
        span="realtime.tr")


@obs_runtime.trace_signature("realtime.isc_step")
def _isc_trace_signature():
    import jax
    import jax.numpy as jnp

    v, r, w = 5, 2, 3

    def a(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.float32)

    cumulative = (a(), a(v), a(v, r), a(v), a(v), a(v, r), a(v, r),
                  a(v, r))
    windowed = (a(w, v), a(v), a(v), a(v, r), a(v, r), a(v, r))
    return [
        {"key": (v, r, 0, "float32"),
         "args": cumulative + (a(v), a(v, r)),
         "label": "cumulative"},
        {"key": (v, r, w, "float32"),
         "args": cumulative + windowed
         + (a(v), a(v, r), a(v, r),
            jax.ShapeDtypeStruct((), jnp.int32)),
         "label": f"window={w}"},
    ]


class OnlineISC:
    """Streaming intersubject correlation against a reference group.

    Parameters
    ----------
    references : array
        Reference group time courses, ``[T, V, R]`` (brainiak's
        time-major convention) or ``[T, V]`` for a single reference.
        Held in full by the estimator (the references are a fitted
        artifact, not streaming state); the per-TR state is the
        rolling sufficient statistics only.
    pairwise : bool
        False (default): leave-one-out — correlate the live subject
        with the MEAN reference time course; at every prefix this
        equals row 0 of the batch ``isc(stack([subject] + refs))``.
        True: one correlation per reference subject — the
        ``(0, j)`` rows of the batch pairwise ISC.
    window : int
        0 (default): cumulative only.  ``W > 0`` additionally
        maintains a rolling window of the last ``W`` TRs
        (``isc_windowed`` output) — the recency-sensitive signal a
        neurofeedback display shows.

    Per-TR outputs: ``isc`` (``[V]`` leave-one-out, ``[V, R]``
    pairwise) and, with a window, ``isc_windowed``.
    """

    def __init__(self, references, pairwise=False, window=0,
                 dtype=None):
        import jax.numpy as jnp
        refs = np.asarray(references, dtype=float)
        if refs.ndim == 2:
            refs = refs[:, :, None]
        if refs.ndim != 3:
            raise ValueError(
                "references must be [T, V, R] or [T, V]; got shape "
                f"{refs.shape}")
        self.pairwise = bool(pairwise)
        self.window = int(window or 0)
        if self.window < 0:
            raise ValueError(f"window must be >= 0, got {window}")
        # leave-one-out reduces the references to their mean time
        # course once, up front — the per-TR y row is then [V, 1]
        self._y_rows = refs if self.pairwise \
            else refs.mean(axis=2, keepdims=True)
        self.n_trs, self.n_voxels, self.n_refs = self._y_rows.shape
        self.dtype = _canonical_dtype(dtype)
        self._y_dev = jnp.asarray(self._y_rows, dtype=self.dtype)

    def init_state(self):
        v, r = self.n_voxels, self.n_refs
        dt = self.dtype
        state = {"n": np.zeros((), dtype=np.float64),
                 "x0": np.zeros(v, dtype=dt),
                 "y0": np.zeros((v, r), dtype=dt),
                 "sx": np.zeros(v, dtype=dt),
                 "sxx": np.zeros(v, dtype=dt),
                 "sy": np.zeros((v, r), dtype=dt),
                 "syy": np.zeros((v, r), dtype=dt),
                 "sxy": np.zeros((v, r), dtype=dt)}
        if self.window:
            state.update({
                "xbuf": np.zeros((self.window, v), dtype=dt),
                "wsx": np.zeros(v, dtype=dt),
                "wsxx": np.zeros(v, dtype=dt),
                "wsy": np.zeros((v, r), dtype=dt),
                "wsyy": np.zeros((v, r), dtype=dt),
                "wsxy": np.zeros((v, r), dtype=dt)})
        return state

    def config_digest(self):
        """Content digest of the reference group + mode knobs: a
        resumed session over DIFFERENT references (same shapes)
        must refuse the checkpoint, not mix two groups' sufficient
        statistics."""
        from ..resilience.guards import array_digest
        return (array_digest(self._y_rows)
                + 7.0 * self.window
                + (13.0 if self.pairwise else 0.0))

    @property
    def state_nbytes(self):
        v, r, item = self.n_voxels, self.n_refs, self.dtype.itemsize
        n = 8 + (3 * v + 4 * v * r) * item
        if self.window:
            n += (self.window * v + 2 * v + 3 * v * r) * item
        return n

    def _squeeze(self, corr):
        return corr[:, 0] if not self.pairwise else corr

    def step(self, state, volume):
        import jax.numpy as jnp
        program = _isc_program(self.n_voxels, self.n_refs,
                               self.window, str(self.dtype))
        t = int(np.asarray(state["n"]))
        if t >= self.n_trs:
            raise ValueError(
                f"OnlineISC was built for {self.n_trs} reference "
                f"TRs; TR {t} is past the end")
        dt = self.dtype
        x = jnp.asarray(volume, dtype=dt)
        y = self._y_dev[t]
        args = [jnp.asarray(np.asarray(state["n"]), dtype=dt)] + [
            jnp.asarray(state[k], dtype=dt)
            for k in ("x0", "y0", "sx", "sxx", "sy", "syy", "sxy")]
        if not self.window:
            out = program(*args, x, y)
            n1, x0, y0, sx, sxx, sy, syy, sxy, corr = out
            new_state = {"n": n1, "x0": x0, "y0": y0, "sx": sx,
                         "sxx": sxx, "sy": sy, "syy": syy,
                         "sxy": sxy}
            return new_state, {"isc": self._squeeze(corr)}
        args += [jnp.asarray(state[k], dtype=dt)
                 for k in ("xbuf", "wsx", "wsxx", "wsy", "wsyy",
                           "wsxy")]
        y_out = self._y_dev[t - self.window] if t >= self.window \
            else jnp.zeros_like(y)
        out = program(*args, x, y, y_out,
                      jnp.asarray(t, dtype=jnp.int32))
        (n1, x0, y0, sx, sxx, sy, syy, sxy, xbuf, wsx, wsxx, wsy,
         wsyy, wsxy, corr, wcorr) = out
        new_state = {"n": n1, "x0": x0, "y0": y0, "sx": sx,
                     "sxx": sxx, "sy": sy, "syy": syy, "sxy": sxy,
                     "xbuf": xbuf, "wsx": wsx, "wsxx": wsxx,
                     "wsy": wsy, "wsyy": wsyy, "wsxy": wsxy}
        return new_state, {"isc": self._squeeze(corr),
                           "isc_windowed": self._squeeze(wcorr)}


# ---------------------------------------------------------------------------
# incremental event segmentation (forward pass only)

def _zscore_columns(mat):
    """Column-wise spatial z-scoring, the exact normalization the
    batch ``_logprob_obs_core`` applies to the event patterns."""
    import jax.numpy as jnp
    return (mat - jnp.mean(mat, axis=0)) \
        / jnp.std(mat, axis=0, ddof=1)


def _evseg_step_core(alpha, t, ll, x, mp_z, mp_sq, var, log_P,
                     log_p_start):
    import jax
    import jax.numpy as jnp

    from ..eventseg.event import forward_step

    v = x.shape[0]
    # per-TR spatial z-scoring: identical to the batch
    # _logprob_obs_core, whose column-wise mean/std make every TR's
    # observation row independent of the rest of the scan.  A
    # constant volume (TR 0 of an online-z-scored stream is all
    # zeros) z-scores to zeros instead of NaN: the posterior then
    # follows the prior for that TR rather than poisoning the
    # forward row for the rest of the scan.  The patterns' z-score
    # (``mp_z``) and squared norms (``mp_sq``) are scan constants,
    # precomputed once by the estimator — not re-derived per TR on
    # the deadline-bound path.
    x_std = jnp.std(x, ddof=1)
    xz = jnp.where(x_std > 0, (x - jnp.mean(x)) / x_std, 0.0)
    sq = jnp.sum(xz ** 2) - 2.0 * xz @ mp_z + mp_sq
    lp = (-0.5 * v * jnp.log(2 * jnp.pi * var)
          - 0.5 * sq / var) / v
    lp_ext = jnp.concatenate(
        [lp, jnp.full((1,), -jnp.inf, lp.dtype)])
    stepped, step_scale = forward_step(alpha, lp_ext, log_P)
    # TR 0 starts the chain from the start prior instead of a
    # transition out of a previous row (one program for both cases:
    # is_first is a traced predicate, never a retrace)
    first = log_p_start + lp_ext
    first_scale = jax.nn.logsumexp(first)
    is_first = t == 0
    new_alpha = jnp.where(is_first, first - first_scale, stepped)
    scale = jnp.where(is_first, first_scale, step_scale)
    return (new_alpha, t + 1, ll + scale,
            jnp.exp(new_alpha))


@obs_runtime.counted_cache("realtime.evseg_step")
def _evseg_program(v, k, dtype):
    """The jitted forward-only event-segmentation step for one
    (V, K, dtype) — built once per scan configuration."""
    import jax
    del v, k, dtype  # cache key only
    return obs_profile.profile_program(
        jax.jit(_evseg_step_core), "realtime.evseg_step",
        span="realtime.tr")


@obs_runtime.trace_signature("realtime.evseg_step")
def _evseg_trace_signature():
    import jax
    import jax.numpy as jnp

    v, k = 5, 3

    def a(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.float32)

    return [{"key": (v, k, "float32"),
             "args": (a(k + 1), jax.ShapeDtypeStruct((), jnp.int32),
                      a(), a(v), a(v, k), a(k), a(k), a(k + 1, k + 1),
                      a(k + 1))}]


class IncrementalEventSegment:
    """Forward-only streaming event segmentation.

    Wraps a fitted (or pattern-set)
    :class:`~brainiak_tpu.eventseg.event.EventSegment`: per TR it
    advances ONLY the scaled log-alpha row of the batch model's
    fused forward scan (through the shared
    :func:`~brainiak_tpu.eventseg.event.forward_step`) and emits the
    current-event posterior given the data so far.  No backward
    pass, no ``[T, K]`` arrays — O(K) state, O(V·K) work per TR.

    ``n_trs`` fixes the expected scan length: the left-to-right
    transition probability is ``(K-1)/T``, so the batch model's
    transitions — and therefore prefix-parity with its forward pass
    — are defined by the full scan length, not the prefix.

    Per-TR outputs: ``log_alpha`` (``[K+1]`` scaled — equal to the
    batch forward pass's row at this prefix), ``posterior``
    (``exp(log_alpha)``; entry K is the past-the-last-event sink),
    and the running forward log-evidence rides the state (``ll`` —
    the batch log-likelihood without the end-state prior).
    """

    def __init__(self, model, n_trs, var=None, dtype=None):
        import jax.numpy as jnp
        if not hasattr(model, "event_pat_"):
            raise ValueError(
                "model has no event patterns; fit() it or call "
                "set_event_patterns() first")
        if var is None:
            if not hasattr(model, "event_var_"):
                raise ValueError(
                    "var= is required when the model was not "
                    "fit() (set_event_patterns sets no variance)")
            var = model.event_var_
        self.n_trs = int(n_trs)
        self.n_events = int(model.n_events)
        pat = np.asarray(model.event_pat_, dtype=float)
        self.n_voxels = pat.shape[0]
        var = np.broadcast_to(
            np.asarray(var, dtype=float), (self.n_events,))
        log_P, log_p_start, _ = model._build_transitions(self.n_trs)
        self.dtype = _canonical_dtype(dtype)
        dt = self.dtype
        self._mean_pat = jnp.asarray(pat, dtype=dt)
        # scan constants: z-scored patterns + their squared norms
        # (the same jnp ops the batch path applies, so prefix
        # parity is preserved bit-for-bit)
        self._mp_z = _zscore_columns(self._mean_pat)
        self._mp_sq = jnp.sum(self._mp_z ** 2, axis=0)
        self._var = jnp.asarray(var, dtype=dt)
        self._log_P = jnp.asarray(log_P, dtype=dt)
        self._log_p_start = jnp.asarray(log_p_start, dtype=dt)

    def init_state(self):
        k = self.n_events
        return {"alpha": np.zeros(k + 1, dtype=self.dtype),
                "t": np.zeros((), dtype=np.int32),
                "ll": np.zeros((), dtype=self.dtype)}

    def config_digest(self):
        """Content digest of the event patterns + variance + scan
        length: resuming against a differently-parameterized model
        must refuse the checkpoint."""
        from ..resilience.guards import array_digest
        return (array_digest(np.asarray(self._mean_pat),
                             np.asarray(self._var))
                + 7.0 * self.n_trs)

    @property
    def state_nbytes(self):
        return (self.n_events + 1) * self.dtype.itemsize + 4 \
            + self.dtype.itemsize

    def step(self, state, volume):
        import jax.numpy as jnp
        program = _evseg_program(self.n_voxels, self.n_events,
                                 str(self.dtype))
        dt = self.dtype
        alpha, t, ll, posterior = program(
            jnp.asarray(np.asarray(state["alpha"]), dtype=dt),
            jnp.asarray(np.asarray(state["t"]), dtype=jnp.int32),
            jnp.asarray(np.asarray(state["ll"]), dtype=dt),
            jnp.asarray(volume, dtype=dt),
            self._mp_z, self._mp_sq, self._var, self._log_P,
            self._log_p_start)
        return ({"alpha": alpha, "t": t, "ll": ll},
                {"log_alpha": alpha, "posterior": posterior})
