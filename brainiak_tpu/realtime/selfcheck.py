"""CI selfcheck for the realtime closed-loop tier (RT001 gate).

Run as a subprocess child by ``tools/run_checks.py``; proves the
tier's three contracts:

1. **online == batch** — :class:`~brainiak_tpu.realtime.OnlineISC`'s
   cumulative correlation matches :func:`brainiak_tpu.isc.isc` on the
   stacked prefix at EVERY TR, and
   :class:`~brainiak_tpu.realtime.IncrementalEventSegment`'s scaled
   log-alpha matches the fused batch forward pass at every prefix
   (both ~1e-6);
2. **resume-mid-scan parity** — a session preempted by an injected
   fault, then resumed from its checkpoint, ends with the same
   estimator states as the uninterrupted scan;
3. **retrace stability** — a full scan (including a REPEAT session in
   the same process, and a warm low-latency ServeService scoring hop)
   keeps every ``retrace_total{site=realtime.*}`` at <= 1.
"""

import numpy as np

__all__ = ["selfcheck"]


def selfcheck(out=None):
    """Prints a JSON verdict; returns 0 on pass, 1 on failure."""
    import json
    import os
    import sys
    import tempfile

    import jax

    jax.config.update("jax_enable_x64", True)

    from ..eventseg.event import (EventSegment, _forward_pass,
                                  _logprob_obs_core)
    from ..isc import isc
    from ..obs import metrics as obs_metrics
    from ..resilience import faults
    from ..serve import ModelResidency
    from ..serve.batching import BucketPolicy
    from ..serve.service import ServeService
    from ..serve.__main__ import build_demo_model
    from . import (IncrementalEventSegment, MemoryFeed, OnlineISC,
                   OnlineZScore, RealtimeSession)

    import jax.numpy as jnp

    stream = out or sys.stdout
    rng = np.random.RandomState(0)
    n_trs, n_voxels, n_refs, n_events = 48, 40, 3, 5
    subj = rng.randn(n_trs, n_voxels)
    refs = rng.randn(n_trs, n_voxels, n_refs)
    pat = rng.randn(n_voxels, n_events)
    var = 2.0

    errs = []
    resume_ok = True
    serve_ok = True

    # (1a) OnlineISC vs the batch isc() at every prefix
    online = OnlineISC(refs)
    state = online.init_state()
    for t in range(n_trs):
        state, out_t = online.step(state, subj[t])
        if t >= 2:
            stacked = np.concatenate(
                [subj[:t + 1, :, None], refs[:t + 1]], axis=2)
            batch = isc(stacked)  # [S, V]; row 0 = subj vs mean-refs
            errs.append(float(np.nanmax(np.abs(
                np.asarray(out_t["isc"]) - batch[0]))))

    # (1b) incremental event segmentation vs the fused batch forward
    # pass at every prefix (shared forward_step — RT001's core claim)
    model = EventSegment(n_events=n_events)
    model.set_event_patterns(pat)
    log_P, log_p_start, _ = model._build_transitions(n_trs)
    logprob = np.asarray(_logprob_obs_core(
        jnp.asarray(subj.T), jnp.asarray(pat),
        jnp.asarray(np.full(n_events, var))))
    lp_ext = np.hstack([logprob, np.full((n_trs, 1), -np.inf)])
    batch_alpha = np.asarray(_forward_pass(
        jnp.asarray(lp_ext), jnp.asarray(log_P),
        jnp.asarray(log_p_start))[0])
    inc = IncrementalEventSegment(model, n_trs=n_trs, var=var)
    state = inc.init_state()
    for t in range(n_trs):
        state, out_t = inc.step(state, subj[t])
        row = np.asarray(out_t["log_alpha"])
        ref_row = batch_alpha[t]
        finite = np.isfinite(ref_row)
        if not np.array_equal(np.isfinite(row), finite):
            errs.append(float("inf"))
        elif finite.any():
            errs.append(float(np.max(np.abs(
                row[finite] - ref_row[finite]))))

    # (2 + 3) full closed-loop sessions: uninterrupted, preempted +
    # resumed (state parity), and a repeat (retrace stability), each
    # with online z-scoring and a warm low-latency ServeService hop
    srm = build_demo_model(n_subjects=2, voxels=n_voxels,
                           samples=32, features=4, n_iter=2, seed=0)
    residency = ModelResidency(
        budget_bytes=1 << 30,
        policy=BucketPolicy(max_batch=16, max_wait_s=2.0))
    residency.register("m", model=srm)

    def run_session(service, checkpoint_dir=None):
        session = RealtimeSession(
            MemoryFeed(subj),
            {"isc": OnlineISC(refs),
             "evseg": IncrementalEventSegment(model, n_trs=n_trs,
                                              var=var)},
            preprocess=OnlineZScore(n_voxels), deadline_s=5.0,
            service=service, service_model="m",
            name="rt-selfcheck")
        session.run(checkpoint_dir=checkpoint_dir,
                    checkpoint_every=8)
        return session

    with ServeService(residency, default_model="m") as service, \
            tempfile.TemporaryDirectory() as tmp:
        base = run_session(service)
        if any(o.get("serve") is None for o in base.outputs):
            serve_ok = False
        ckpt = os.path.join(tmp, "ckpt")
        try:
            with faults.inject("preempt", at_step=16):
                run_session(service, checkpoint_dir=ckpt)
            resume_ok = False  # the fault must fire
        except faults.PreemptionError:
            pass
        resumed = run_session(service, checkpoint_dir=ckpt)
        if not resumed.outputs or resumed.outputs[0]["tr"] != 16:
            resume_ok = False  # did not resume at the checkpoint
        for est in ("isc", "evseg"):
            a_state = base.estimator_state(est)
            b_state = resumed.estimator_state(est)
            for leaf in a_state:
                a, b = a_state[leaf], b_state[leaf]
                finite = np.isfinite(a)
                if not np.array_equal(np.isfinite(b), finite):
                    resume_ok = False
                elif finite.any():
                    err = float(np.max(np.abs(
                        a[finite] - b[finite])))
                    errs.append(err)
                    if err > 1e-6:
                        resume_ok = False
        # repeat scan: every realtime.* program must already be built
        repeat = run_session(service)

    sites = repeat.retraces()
    retrace = obs_metrics.counter("retrace_total")
    for labels, value in retrace.samples():
        if str(labels.get("site", "")).startswith("serve."):
            sites[labels["site"]] = value

    tol = 1e-6
    expected = {"realtime.zscore_step", "realtime.isc_step",
                "realtime.evseg_step"}
    ok = (max(errs) < tol and resume_ok and serve_ok
          and all(count <= 1.0 for count in sites.values())
          and expected <= set(sites))
    json.dump({"ok": bool(ok), "max_err": max(errs), "tol": tol,
               "resume_ok": bool(resume_ok),
               "serve_ok": bool(serve_ok),
               "n_misses": int(base.summary()["n_deadline_misses"]),
               "retraces": sites}, stream)
    stream.write("\n")
    return 0 if ok else 1
