"""Generic image functionality: masking and condition specs.

Re-design of /root/reference/src/brainiak/image.py with the same public
surface, independent of nibabel (works with any object exposing
``get_fdata()`` — e.g. :class:`brainiak_tpu.nifti.NiftiImage` — or a plain
ndarray).
"""

import itertools
from typing import Iterable, Optional, Sequence, Type, TypeVar

import numpy as np

__all__ = [
    "ConditionSpec",
    "MaskedMultiSubjectData",
    "mask_image",
    "mask_images",
    "multimask_images",
    "SingleConditionSpec",
]

T = TypeVar("T", bound="MaskedMultiSubjectData")


class MaskedMultiSubjectData(np.ndarray):
    """Array with shape (n_TRs, n_voxels, n_subjects).

    Contract: reference image.py:37-81.
    """

    @classmethod
    def from_masked_images(cls: Type[T], masked_images: Iterable[np.ndarray],
                           n_subjects: int) -> T:
        """Stack per-subject (n_voxels, n_TRs) masked images into
        (n_TRs, n_voxels, n_subjects); raises ValueError on shape mismatch
        or a count different from ``n_subjects``."""
        images = iter(masked_images)
        try:
            first = next(images)
        except StopIteration:
            raise ValueError("n_subjects != number of images: {} != 0"
                             .format(n_subjects))
        expected = first.T.shape
        result = np.empty(expected + (n_subjects,))
        count = 0
        for image in itertools.chain([first], images):
            image = image.T
            if image.shape != expected:
                raise ValueError(
                    "Image {} has different shape from first image: "
                    "{} != {}".format(count, image.shape, expected))
            if count < n_subjects:
                result[:, :, count] = image
            count += 1
        if count != n_subjects:
            raise ValueError("n_subjects != number of images: {} != {}"
                             .format(n_subjects, count))
        return result.view(cls)


class ConditionSpec(np.ndarray):
    """One-hot representation of conditions across epochs and TRs;
    shape (n_conditions, n_epochs, n_TRs)."""


class SingleConditionSpec(ConditionSpec):
    """ConditionSpec with exactly one active condition per epoch."""

    def extract_labels(self) -> np.ndarray:
        """Condition label of each epoch (reference image.py:91-105)."""
        condition_idxs, epoch_idxs, _ = np.where(self)
        _, unique_epoch_idxs = np.unique(epoch_idxs, return_index=True)
        return condition_idxs[unique_epoch_idxs]


def _image_data(image) -> np.ndarray:
    if hasattr(image, "get_fdata"):
        return image.get_fdata()
    return np.asarray(image)


def mask_image(image, mask: np.ndarray,
               data_type: Optional[type] = None) -> np.ndarray:
    """Apply a boolean spatial mask to an image (time may be last dim).

    Returns array of shape (n_mask_voxels[, n_TRs]).
    Contract: reference image.py:107-140.
    """
    image_data = _image_data(image)
    if image_data.shape[:3] != mask.shape:
        raise ValueError("Image data and mask have different shapes.")
    if data_type is not None:
        image_data = image_data.astype(data_type)
    return image_data[mask]


def multimask_images(images, masks: Sequence[np.ndarray],
                     image_type: Optional[type] = None
                     ) -> Iterable[Sequence[np.ndarray]]:
    """For each image, yield the list of maskings by each mask.

    Contract: reference image.py:143-165.
    """
    for image in images:
        yield [mask_image(image, mask, image_type) for mask in masks]


def mask_images(images, mask: np.ndarray,
                image_type: Optional[type] = None) -> Iterable[np.ndarray]:
    """Yield each image masked by ``mask`` (reference image.py:168-187)."""
    for masked in multimask_images(images, (mask,), image_type):
        yield masked[0]
