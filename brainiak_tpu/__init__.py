"""brainiak_tpu: a TPU-native brain imaging analysis framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of BrainIAK
(reference: /root/reference, brainiak/brainiak): scalable fMRI analysis with
device-mesh parallelism (pjit/shard_map over ICI/DCN) replacing MPI, fused
XLA/Pallas kernels replacing C++/Cython extensions, and pure-JAX optimization
replacing TensorFlow/pymanopt components.

Layout
------
- ``ops``            pure-JAX jittable kernels (correlation, Fisher-z, RBF
                     factors, masked log, Gram accumulation, phase
                     randomization) — the analog of the reference's native
                     extensions (cython_blas.pyx, fcma_extension.cc,
                     tfa_extension.cpp, _utils.pyx).
- ``parallel``       device-mesh / sharding / collective helpers — the analog
                     of the reference's mpi4py layer.
- ``io`` / ``image`` host-side data plane (NIfTI, masking, condition specs).
- ``data``           out-of-core streaming data plane: on-disk per-subject
                     stores, the double-buffered host-to-device shard
                     prefetcher, and streamed/minibatch SRM fits that never
                     materialize the [subjects, V, T] stack.
- domain packages    ``fcma``, ``funcalign``, ``factoranalysis``,
                     ``eventseg``, ``searchlight``, ``isc``, ``reprsimil``,
                     ``matnormal``, ``reconstruct``, ``hyperparamopt``,
                     ``encoding``, ``utils`` — sklearn-style estimators and
                     free functions matching (and extending) the reference
                     API surface.
"""

__version__ = "0.1.0"
