"""Searchlight analysis engine, TPU-native.

Re-design of /root/reference/src/brainiak/searchlight/searchlight.py.  The
reference scatters halo'd volume blocks over MPI ranks and runs a pickled
Python ``voxel_fn`` in a per-node process pool (searchlight.py:284-489).
Here the engine is two-tier:

- **generic tier** (`run_searchlight`): the same arbitrary-Python
  ``voxel_fn`` API — every active voxel's halo'd neighborhood is visited in
  a host loop (optionally a process pool).  Needed for user functions that
  cannot be traced (e.g. sklearn classifiers in MVPA selection).
- **traced tier** (`run_searchlight_jax`): a jittable ``voxel_fn`` is
  ``vmap``-ed over ALL active-voxel neighborhoods at once — the
  neighborhoods are materialized with one advanced-indexing gather
  ([n_centers, subjects, shape_voxels, TRs]) and the whole sweep compiles
  to a single batched XLA program, optionally sharded over a mesh's
  ``voxel`` axis.  This replaces block scatter + halo exchange: on TPU the
  volume fits in HBM replicated, and the shard dimension is the CENTER
  list, which needs no halo at all.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
from multiprocessing import Pool

from ..utils.utils import usable_cpu_count

logger = logging.getLogger(__name__)

__all__ = ["Ball", "Cube", "Diamond", "Searchlight", "Shape"]


def _apply_voxel_fn(args):
    """Top-level worker wrapper so Pool.imap can stream tasks lazily."""
    voxel_fn = args[0]
    return voxel_fn(*args[1:])


class Shape:
    """Searchlight shape in a (2*rad+1)^3 cube (reference
    searchlight.py:34-56)."""

    def __init__(self, rad):
        self.rad = rad


class Cube(Shape):
    def __init__(self, rad):
        super().__init__(rad)
        self.mask_ = np.ones((2 * rad + 1,) * 3, dtype=bool)


class Diamond(Shape):
    """Manhattan-distance ball (reference searchlight.py:76-100)."""

    def __init__(self, rad):
        super().__init__(rad)
        g = np.abs(np.arange(-rad, rad + 1))
        dist = g[:, None, None] + g[None, :, None] + g[None, None, :]
        self.mask_ = dist <= rad


class Ball(Shape):
    """Euclidean ball (reference searchlight.py:102-126)."""

    def __init__(self, rad):
        super().__init__(rad)
        g = np.arange(-rad, rad + 1) ** 2
        dist = g[:, None, None] + g[None, :, None] + g[None, None, :]
        self.mask_ = np.sqrt(dist) <= rad


class Searchlight:
    """Run a user function over every active voxel's neighborhood
    (reference searchlight.py:128-540).

    Parameters
    ----------
    sl_rad : neighborhood radius in voxels
    max_blk_edge : kept for API compatibility (block decomposition is not
        needed in the single-controller design)
    shape : Shape subclass (Cube/Diamond/Ball)
    min_active_voxels_proportion : skip centers whose (mask ∩ shape)
        neighborhood has at most this active fraction
    pool_size : processes for the generic tier's host loop
    mesh : optional jax.sharding.Mesh for the traced tier
    """

    def __init__(self, sl_rad=1, max_blk_edge=10, shape=Cube,
                 min_active_voxels_proportion=0, pool_size=None, mesh=None):
        assert sl_rad >= 0, 'sl_rad should not be negative'
        assert max_blk_edge > 0, 'max_blk_edge should be positive'
        self.sl_rad = sl_rad
        self.max_blk_edge = max_blk_edge
        self.min_active_voxels_proportion = min_active_voxels_proportion
        self.shape = shape(sl_rad).mask_
        self.bcast_var = None
        self.pool_size = pool_size
        self.mesh = mesh

    # -- data staging ----------------------------------------------------
    def distribute(self, subjects, mask):
        """Stage subject volumes + mask.  The reference scatters blocks over
        MPI ranks here (searchlight.py:327-379); in the single-controller
        model the volumes are simply kept (and later placed on device for
        the traced tier)."""
        self.subjects = [np.asarray(s) if s is not None else None
                         for s in subjects]
        self.mask = np.asarray(mask).astype(bool)
        for s in self.subjects:
            if s is not None and s.shape[:3] != self.mask.shape:
                raise ValueError("Subject volume and mask shapes differ")
        # re-staging data is the one supported way to change it: drop the
        # traced tier's device cache so in-place-mutated buffers (which
        # an identity key cannot detect) can't be served stale
        self._jax_tier_cache = None

    def broadcast(self, bcast_var):
        """Make shared variables available to the voxel function
        (reference searchlight.py:381-391)."""
        self.bcast_var = bcast_var

    # -- center enumeration ----------------------------------------------
    def _centers(self):
        """Active centers at least sl_rad from every border, plus the
        min-active-proportion filter (reference semantics:
        searchlight.py:542-578)."""
        rad = self.sl_rad
        mask = self.mask
        interior = np.zeros_like(mask)
        if rad > 0:
            interior[rad:-rad, rad:-rad, rad:-rad] = \
                mask[rad:-rad, rad:-rad, rad:-rad]
        else:
            interior = mask
        centers = np.argwhere(interior)
        if self.min_active_voxels_proportion > 0 and len(centers):
            keep = []
            size = self.shape.size
            for (i, j, k) in centers:
                patch = mask[i - rad:i + rad + 1, j - rad:j + rad + 1,
                             k - rad:k + rad + 1] * self.shape
                if np.count_nonzero(patch) / size > \
                        self.min_active_voxels_proportion:
                    keep.append((i, j, k))
            centers = np.asarray(keep).reshape(-1, 3)
        return centers

    # -- generic tier -----------------------------------------------------
    def run_searchlight(self, voxel_fn, pool_size=None):
        """Apply an arbitrary Python voxel_fn(subj_patches, mask_patch,
        rad, bcast_var) at every active voxel; returns an object-dtype
        volume (None where skipped) (reference searchlight.py:491-540)."""
        rad = self.sl_rad
        centers = self._centers()
        outmat = np.empty(self.mask.shape, dtype=object)

        def patch_args(c):
            i, j, k = c
            sl = np.s_[i - rad:i + rad + 1, j - rad:j + rad + 1,
                       k - rad:k + rad + 1]
            subj = [s[sl] if s is not None else None
                    for s in self.subjects]
            return subj, self.mask[sl] * self.shape, rad, self.bcast_var

        if pool_size is None:
            pool_size = self.pool_size
        processes = usable_cpu_count() if pool_size is None else \
            min(pool_size, usable_cpu_count())

        if processes > 1 and len(centers) > 1:
            # Lazy chunked submission keeps memory bounded by
            # processes x chunksize patches instead of the full center list.
            args_iter = ((voxel_fn,) + patch_args(c) for c in centers)
            with Pool(processes) as pool:
                for c, value in zip(
                        centers,
                        pool.imap(_apply_voxel_fn, args_iter,
                                  chunksize=8)):
                    outmat[tuple(c)] = value
        else:
            for c in centers:
                outmat[tuple(c)] = voxel_fn(*patch_args(c))
        return outmat

    def run_block_function(self, block_fn, extra_block_fn_params=None,
                           pool_size=None):
        """Apply a block function to the whole (single) halo'd block.

        The reference cuts the volume into max_blk_edge^3 blocks purely to
        spread work over ranks/processes (searchlight.py:393-489); with one
        logical device the entire volume is one block.
        """
        result = block_fn(self.subjects, self.mask, self.sl_rad,
                          self.bcast_var, extra_block_fn_params)
        outmat = np.empty(self.mask.shape, dtype=object)
        rad = self.sl_rad
        if rad > 0:
            outmat[rad:-rad, rad:-rad, rad:-rad] = result
        else:
            outmat[:] = result
        return outmat

    # -- traced tier ------------------------------------------------------
    def run_searchlight_jax(self, voxel_fn, batch_size=1024,
                            fill_value=np.nan):
        """Apply a JITTABLE voxel_fn over all active voxels as one batched
        XLA program.

        voxel_fn(patches, mask_patch, rad, bcast_var) -> scalar, where
        ``patches`` is [n_subjects, shape_voxels, n_TRs] (already masked by
        the shape: entries outside the shape or brain mask are zero, and
        ``mask_patch`` [shape_voxels] bool marks valid ones).

        Returns a float volume (fill_value where skipped).
        """
        rad = self.sl_rad
        centers = self._centers()
        if len(centers) == 0:
            return np.full(self.mask.shape, fill_value, dtype=np.float64)

        if any(s is None for s in self.subjects):
            raise ValueError(
                "run_searchlight_jax requires all subject volumes; None "
                "placeholders are only supported by the generic tier")

        # Device-resident state and the COMPILED sweep are cached across
        # calls: a fresh @jax.jit wrapper per call retraces and
        # recompiles every time (~seconds), which used to dwarf the
        # actual sweep (milliseconds).  Patches are gathered through a
        # single flattened voxel axis — one-axis gathers lower ~3x
        # faster on TPU than triple-coordinate fancy indexing.
        # key holds the OBJECTS (not bare ids) so an `is` match can never
        # be a recycled id() of freed inputs; mask/bcast_var invalidate too
        key = (self.subjects, self.mask, self.bcast_var) \
            + tuple(self.subjects)
        cache = getattr(self, "_jax_tier_cache", None)
        if cache is None or len(cache["key"]) != len(key) or \
                not all(a is b for a, b in zip(cache["key"], key)):
            data = np.stack(self.subjects)  # [S, x, y, z, T]
            s, dx, dy, dz, t = data.shape
            cache = {
                "key": key,
                "dims": (dx, dy, dz),
                "flat": jnp.asarray(data.reshape(s, dx * dy * dz, t)),
                "mflat": jnp.asarray(self.mask.reshape(-1)),
                "sweeps": {},
            }
            self._jax_tier_cache = cache
        dx, dy, dz = cache["dims"]
        flat, mflat = cache["flat"], cache["mflat"]
        bcast = self.bcast_var

        # the [N, P] flattened patch-index matrix is determined entirely
        # by cached state (mask + instance-fixed shape/rad/mesh) — build
        # and upload it once per staged dataset, not per call
        if "idx" not in cache:
            offs = np.argwhere(self.shape) - rad  # [P, 3]
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                from ..parallel.mesh import DEFAULT_VOXEL_AXIS
                n_shards = self.mesh.shape.get(DEFAULT_VOXEL_AXIS, 1)
                pad = (-len(centers)) % n_shards
                centers_padded = np.concatenate(
                    [centers, np.repeat(centers[-1:], pad, axis=0)])
            else:
                pad = 0
                centers_padded = centers
            idx3 = centers_padded[:, None, :] + offs[None, :, :]
            idx1 = np.ascontiguousarray(
                (idx3[..., 0] * dy + idx3[..., 1]) * dz + idx3[..., 2])
            idx_dev = jnp.asarray(idx1)
            if self.mesh is not None:
                idx_dev = jax.device_put(
                    idx_dev,
                    NamedSharding(self.mesh,
                                  PartitionSpec(DEFAULT_VOXEL_AXIS,
                                                None)))
            cache["idx"] = (idx_dev, pad)
        idx_dev, pad = cache["idx"]

        sweep = cache["sweeps"].get((voxel_fn, batch_size))
        if sweep is None:
            # bound the compiled-sweep cache: fresh lambdas per call
            # would otherwise pin every compiled executable forever
            if len(cache["sweeps"]) >= 8:
                cache["sweeps"].pop(next(iter(cache["sweeps"])))
            @jax.jit
            def sweep(idx_arr):
                def one_center(i1):
                    patch = flat[:, i1, :]  # [S, P, T]
                    mpatch = mflat[i1]
                    patch = jnp.where(mpatch[None, :, None], patch, 0.0)
                    return voxel_fn(patch, mpatch, rad, bcast)

                return jax.lax.map(one_center, idx_arr,
                                   batch_size=batch_size)

            cache["sweeps"][(voxel_fn, batch_size)] = sweep

        # fetch_replicated: per-center scalars are tiny, and in a
        # multi-process run the center-sharded output is not
        # addressable for a plain np.asarray
        from ..parallel.mesh import fetch_replicated
        values = fetch_replicated(sweep(idx_dev), self.mesh)
        if pad:
            values = values[:len(centers)]
        outmat = np.full(self.mask.shape, fill_value, dtype=values.dtype)
        outmat[centers[:, 0], centers[:, 1], centers[:, 2]] = values
        return outmat
