from .searchlight import (  # noqa: F401
    Ball,
    Cube,
    Diamond,
    Searchlight,
    Shape,
)
