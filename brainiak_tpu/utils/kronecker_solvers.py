"""Kronecker-structured triangular solves and products.

Re-design of /root/reference/src/brainiak/utils/kronecker_solvers.py.  The
reference implements recursive blockwise TF loops
(kronecker_solvers.py:6-102); in JAX the unmasked solves collapse to
axis-wise ``solve_triangular`` over the reshaped operand, since
(L₁⊗…⊗L_k)⁻¹ = L₁⁻¹⊗…⊗L_k⁻¹ acts independently along each tensor axis —
one fused XLA program, no recursion.

Masked variants solve the principal submatrix of the Kronecker factor
restricted to valid indices (a principal submatrix of a triangular matrix
is triangular).  They materialize the masked factor densely — exact, and
fine for the moderate masked sizes these are used at; the reference's
implicit recursion (kronecker_solvers.py:150-330) trades memory for a
TF graph that TPUs no longer need.
"""

from functools import reduce

import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular

__all__ = [
    "kron_mult",
    "masked_triangular_solve",
    "solve_lower_triangular_kron",
    "solve_lower_triangular_masked_kron",
    "solve_upper_triangular_kron",
    "solve_upper_triangular_masked_kron",
]

# Naming note: the reference exports these with a ``tf_`` prefix
# (tf_solve_lower_triangular_kron etc.); the prefix is dropped here since
# there is no TensorFlow.


def _axiswise(Ls, y, op):
    """Apply ``op(L, mat)`` along each Kronecker axis of y [prod(n), p]."""
    sizes = [L.shape[0] for L in Ls]
    p = y.shape[1] if y.ndim == 2 else 1
    x = y.reshape(sizes + [p])
    k = len(Ls)
    for i, L in enumerate(Ls):
        x = jnp.moveaxis(x, i, 0)
        flat = x.reshape(sizes[i], -1)
        flat = op(L, flat)
        x = flat.reshape([sizes[i]] + [s for j, s in enumerate(sizes)
                                       if j != i] + [p])
        x = jnp.moveaxis(x, 0, i)
    out = x.reshape(-1, p)
    return out if y.ndim == 2 else out[:, 0]


def solve_lower_triangular_kron(Ls, y):
    """x with (L₀⊗…⊗L_{k-1}) x = y, each L_i lower triangular."""
    return _axiswise(Ls, y, lambda L, m: solve_triangular(L, m,
                                                          lower=True))


def solve_upper_triangular_kron(Ls, y):
    """x with (L₀⊗…⊗L_{k-1})ᵀ x = y, each L_i lower triangular."""
    return _axiswise(Ls, y,
                     lambda L, m: solve_triangular(L.T, m, lower=False))


def kron_mult(Ls, x):
    """(L₀⊗…⊗L_{k-1}) x."""
    return _axiswise(Ls, x, lambda L, m: L @ m)


def _dense_kron(Ls):
    return reduce(jnp.kron, Ls)


def _masked_solve(Ls, y, mask, upper):
    """Solve the mask-restricted triangular Kronecker system via the
    single-matrix primitive; masked rows of the output are zero."""
    return masked_triangular_solve(_dense_kron(Ls), y, mask,
                                   lower=True, adjoint=upper)


def masked_triangular_solve(L, y, mask, lower=True, adjoint=False):
    """Triangular solve restricted to the masked principal submatrix
    (masked rows of the output are zero) — the single-matrix primitive
    underlying the masked Kronecker solves (reference
    kronecker_solvers.py:150-267, ``tf_masked_triangular_solve``)."""
    mask = jnp.asarray(mask, bool)
    idx = jnp.where(mask)[0]
    sub = L[jnp.ix_(idx, idx)]
    y2 = y if y.ndim == 2 else y[:, None]
    rhs = y2[idx]
    use_lower = lower != adjoint
    mat = sub.T if adjoint else sub
    out = solve_triangular(mat, rhs, lower=use_lower)
    full = jnp.zeros_like(y2)
    full = full.at[idx].set(out)
    return full if y.ndim == 2 else full[:, 0]


def solve_lower_triangular_masked_kron(Ls, y, mask):
    return _masked_solve(Ls, y, mask, upper=False)


def solve_upper_triangular_masked_kron(Ls, y, mask):
    return _masked_solve(Ls, y, mask, upper=True)
