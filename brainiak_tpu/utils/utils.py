"""Shared statistical / utility layer.

TPU-native re-design of the reference's ``brainiak.utils.utils``
(/root/reference/src/brainiak/utils/utils.py).  Host-side helpers stay NumPy;
everything on a hot path (correlation, phase randomization, p-values) also has
a pure-JAX jittable counterpart in :mod:`brainiak_tpu.ops` so resampling loops
can be ``vmap``-ed on device.

Behavior contracts follow the reference (cited per function) but the
implementations are new.
"""

import logging
import os
import re
import warnings

import numpy as np

__all__ = [
    "array_correlation",
    "center_mass_exp",
    "circ_dist",
    "concatenate_not_none",
    "cov2corr",
    "from_sym_2_tri",
    "from_tri_2_sym",
    "gen_design",
    "MonotonicPacer",
    "p_from_null",
    "phase_randomize",
    "ReadDesign",
    "sumexp_stable",
    "usable_cpu_count",
]

logger = logging.getLogger(__name__)


class MonotonicPacer:
    """Absolute-monotonic period scheduler: tick ``t`` is due at
    ``start + t * period_s``.

    The shared pacing primitive of the real-time paths (the fmrisim
    :class:`~brainiak_tpu.utils.fmrisim_real_time_generator
    .RealtimeStream` iterator and the
    :class:`brainiak_tpu.realtime.ingest.TRSource` replays):
    consumer time between :meth:`wait` calls counts against the
    period — pacing never drifts — and the monotonic clock is
    immune to wall-clock steps (NTP, DST).  ``period_s <= 0``
    disables pacing.  :meth:`reset` forgets the schedule (a resumed
    replay restarts its clock; the gap was downtime, not lateness).
    """

    def __init__(self, period_s):
        import time as _time  # late: keep this module numpy-light
        self._time = _time
        self.period_s = float(period_s)
        self._next_due = None

    def reset(self):
        self._next_due = None
        return self

    def wait(self):
        """Sleep until the next tick is due, then advance the
        schedule.  Returns the seconds slept."""
        if self.period_s <= 0.0:
            return 0.0
        now = self._time.monotonic()
        if self._next_due is None:
            self._next_due = now
        delay = self._next_due - now
        if delay > 0:
            self._time.sleep(delay)
        self._next_due += self.period_s
        return max(delay, 0.0)


def circ_dist(x, y):
    """Pairwise circular distance (radians) between two equal-size vectors.

    Reference contract: utils/utils.py:48-66.
    """
    x = np.asarray(x)
    y = np.asarray(y)
    if x.size != y.size:
        raise ValueError("Input sizes must match to compute pairwise "
                         "comparisons.")
    return np.angle(np.exp(1j * (x - y)))


def from_tri_2_sym(tri, dim):
    """Expand an upper-triangular 1-D vector into a dim×dim symmetric matrix.

    Only the upper triangle of the result is populated (matching the
    reference, utils/utils.py:69-92, which leaves the strict lower triangle
    zero).
    """
    symm = np.zeros((dim, dim), dtype=np.asarray(tri).dtype)
    symm[np.triu_indices(dim)] = tri
    return symm


def from_sym_2_tri(symm):
    """Extract the upper triangle (incl. diagonal) of a symmetric matrix
    as 1-D.

    Reference contract: utils/utils.py:95-115.
    """
    symm = np.asarray(symm)
    return symm[np.triu_indices_from(symm)]


def sumexp_stable(data):
    """Stable sum of exponentials over axis 0.

    Returns ``(result_sum, max_value, result_exp)`` with
    ``result_exp = exp(data - max)``, ``result_sum = sum(result_exp, axis=0)``.
    Reference contract: utils/utils.py:118-151.
    """
    data = np.asarray(data)
    max_value = data.max(axis=0)
    result_exp = np.exp(data - max_value)
    result_sum = np.sum(result_exp, axis=0)
    return result_sum, max_value, result_exp


def concatenate_not_none(data, axis=0):
    """Concatenate the non-None entries of a list of arrays.

    Reference contract: utils/utils.py:154-182.
    """
    return np.concatenate([d for d in data if d is not None], axis=axis)


def cov2corr(cov):
    """Convert a covariance matrix to a correlation matrix.

    Reference contract: utils/utils.py:185-206.
    """
    cov = np.asarray(cov)
    assert cov.ndim == 2, 'covariance matrix should be 2D array'
    inv_sd = 1.0 / np.sqrt(np.diag(cov))
    return cov * inv_sd[None, :] * inv_sd[:, None]


def center_mass_exp(interval, scale=1.0):
    """Center of mass of an exponential distribution on an interval.

    Reference contract: utils/utils.py:657-697.
    """
    assert isinstance(interval, tuple), 'interval must be a tuple'
    assert len(interval) == 2, 'interval must be length two'
    left, right = interval
    assert left >= 0, 'interval_left must be non-negative'
    assert right > left, 'interval_right must be bigger than interval_left'
    assert scale > 0, 'scale must be positive'
    if not np.isfinite(right):
        return left + scale
    el = np.exp(-left / scale)
    er = np.exp(-right / scale)
    return ((left + scale) * el - (right + scale) * er) / (el - er)


def usable_cpu_count():
    """Number of CPUs usable by the current process (cpuset-aware).

    Reference contract: utils/utils.py:700-717.
    """
    try:
        return len(os.sched_getaffinity(0))
    except AttributeError:
        return os.cpu_count() or 1


def _check_timeseries_input(data):
    """Standardize time-series input to (data3d, n_TRs, n_voxels, n_subjects).

    Accepts a list of per-subject (n_TRs, n_voxels) arrays, a 2-D array
    (n_TRs, n_subjects), or a 3-D array (n_TRs, n_voxels, n_subjects).
    Reference contract: utils/utils.py:875-935.
    """
    if isinstance(data, list):
        shape0 = data[0].shape
        arrays = []
        for d in data:
            d = np.asarray(d)
            if d.shape != shape0:
                raise ValueError("All ndarrays in input list "
                                 "must be the same shape!")
            arrays.append(d[:, np.newaxis] if d.ndim == 1 else d)
        data = np.dstack(arrays)
    else:
        data = np.asarray(data)
        if data.ndim == 2:
            data = data[:, np.newaxis, :]
        elif data.ndim != 3:
            raise ValueError("Input ndarray should have 2 "
                             "or 3 dimensions (got {0})!".format(data.ndim))

    n_TRs, n_voxels, n_subjects = data.shape
    logger.debug(
        "Assuming %d subjects with %d time points and %d voxel(s) or ROI(s)",
        n_subjects, n_TRs, n_voxels)
    return data, n_TRs, n_voxels, n_subjects


def array_correlation(x, y, axis=0):
    """Column- (axis=0) or row-wise (axis=1) Pearson correlation of two arrays.

    Reference contract: utils/utils.py:938-996.
    """
    x = np.asarray(x)
    y = np.asarray(y)
    if x.shape != y.shape:
        raise ValueError("Input arrays must be the same shape")
    if axis == 1:
        x, y = x.T, y.T
    xd = x - x.mean(axis=0)
    yd = y - y.mean(axis=0)
    num = np.sum(xd * yd, axis=0)
    den = np.sqrt(np.sum(xd ** 2, axis=0) * np.sum(yd ** 2, axis=0))
    return num / den


def phase_randomize(data, voxelwise=False, random_state=None):
    """Randomize the phase of time series, preserving the power spectrum.

    .. deprecated::
        This host-NumPy twin now delegates to the single jax
        implementation, :func:`brainiak_tpu.ops.stats.phase_randomize`
        (which also backs the ``"phase_randomize"`` surrogate family in
        :mod:`brainiak_tpu.stats`).  ``random_state`` seeds a
        ``jax.random`` key, so surrogates differ draw-for-draw from the
        old RandomState chain while remaining distribution-identical
        (uniform phases; power spectra preserved exactly).

    Same phase shift across voxels by default; per-voxel shifts when
    ``voxelwise=True``.  Accepts 2-D (TR × subject) or 3-D
    (TR × voxel × subject) input.  Reference contract:
    utils/utils.py:720-801.
    """
    warnings.warn(
        "brainiak_tpu.utils.utils.phase_randomize is deprecated; use "
        "brainiak_tpu.ops.stats.phase_randomize (explicit jax.random "
        "key) or the 'phase_randomize' surrogate family in "
        "brainiak_tpu.stats", DeprecationWarning, stacklevel=2)
    import jax

    from ..ops.stats import phase_randomize as _phase_randomize_jax

    data_ndim = np.ndim(data)
    data, n_TRs, n_voxels, n_subjects = _check_timeseries_input(data)
    if isinstance(random_state, np.random.RandomState):
        seed = int(random_state.randint(0, 2 ** 32 - 1))
    elif random_state is None:
        seed = int(np.random.randint(0, 2 ** 32 - 1))
    else:
        seed = int(random_state)
    shifted_data = np.asarray(_phase_randomize_jax(
        jax.random.PRNGKey(seed), data, voxelwise=voxelwise))
    if data_ndim == 2:
        shifted_data = shifted_data[:, 0, :]
    return shifted_data


# p_from_null's canonical home is brainiak_tpu.stats.pvalues (one
# NumPy-only source for the exceedance-count -> p conventions shared
# with the streaming NullAccumulator); re-exported here for the
# long-standing utils surface.
from ..stats.pvalues import p_from_null  # noqa: E402,F401


class ReadDesign:
    """Reader for AFNI 3dDeconvolve design matrices (``.1D``/``.1d``/``.txt``).

    Parses the ``ni_type``, ``ColumnGroups`` and ``StimLabels`` header
    comments to classify columns into task (>0), orthogonal/motion (0) and
    polynomial-drift (-1) regressors.  Reference contract:
    utils/utils.py:208-363.
    """

    _RE_NCOL = re.compile(r'^#\s+ni_type\s+=\s+"(\d+)[*]', re.MULTILINE)
    _RE_GROUPS = re.compile(r'^#\s+ColumnGroups\s+=\s+"(.+)"', re.MULTILINE)
    _RE_LABELS = re.compile(r'^#\s+StimLabels\s+=\s+"(.+)"', re.MULTILINE)

    def __init__(self, fname=None, include_orth=True, include_pols=True):
        self.design = np.zeros([0, 0])
        self.n_col = 0
        self.column_types = np.ones(0)
        self.n_basis = 0
        self.n_stim = 0
        self.n_orth = 0
        self.StimLabels = []

        if fname is not None:
            _, ext = os.path.splitext(fname)
            if ext in ('.1D', '.1d', '.txt'):
                self.read_afni(fname)

        self.include_orth = include_orth
        self.include_pols = include_pols

        self.cols_task = np.where(self.column_types == 1)[0]
        self.design_task = self.design[:, self.cols_task]
        self.n_TR = self.design_task.shape[0]

        nuisance_cols = []
        if self.include_orth:
            nuisance_cols.append(np.where(self.column_types == 0)[0])
        if self.include_pols:
            nuisance_cols.append(np.where(self.column_types == -1)[0])
        self.cols_nuisance = np.intp(np.sort(np.concatenate(nuisance_cols))) \
            if nuisance_cols else np.array([], dtype=np.intp)
        if self.cols_nuisance.size > 0:
            self.reg_nuisance = self.design[:, self.cols_nuisance]
        else:
            self.reg_nuisance = None

    def read_afni(self, fname):
        self.design = np.loadtxt(fname, ndmin=2)
        with open(fname) as f:
            text = f.read()

        m = self._RE_NCOL.search(text)
        if m:
            self.n_col = int(m.group(1))
            if self.n_col != self.design.shape[1]:
                warnings.warn('The number of columns in the design matrix'
                              'does not match the header information')
                self.n_col = self.design.shape[1]
        else:
            self.n_col = self.design.shape[1]

        self.column_types = np.ones(self.n_col)
        m = self._RE_GROUPS.search(text)
        if m:
            idx = 0
            for group in m.group(1).split(','):
                parts = group.split('@')
                if len(parts) == 2:
                    # "<count>@<type>": count columns of the given type
                    count, ctype = int(parts[0]), int(parts[1])
                    self.column_types[idx:idx + count] = ctype
                    idx += count
                elif len(parts) == 1 and not re.search(r'\..', parts[0]):
                    self.column_types[idx] = int(parts[0])
                    idx += 1
                else:
                    # "<label>..<count>": a run of stimulus columns
                    count = int(group.split('..')[1])
                    self.column_types[idx:idx + count] = 1
                    idx += count
            self.n_basis = int(np.sum(self.column_types == -1))
            self.n_stim = int(np.sum(self.column_types > 0))
            self.n_orth = int(np.sum(self.column_types == 0))

        m = self._RE_LABELS.search(text)
        self.StimLabels = re.split(r'[ ;]+', m.group(1)) if m else []


def gen_design(stimtime_files, scan_duration, TR, style='FSL',
               temp_res=0.01, hrf_para=None):
    """Generate design matrix columns from stimulus timing files.

    Convolves boxcar (or parametrically modulated) event trains with a
    double-gamma HRF at high temporal resolution, then downsamples to TR
    grid.  Supports FSL 3-column and AFNI stimtime formats, and multiple
    runs via list-of-files (concatenated along time).

    Reference contract: utils/utils.py:365-655.

    Parameters
    ----------
    stimtime_files : str or list of str
        One file (or a list of per-condition files).  FSL style: three
        columns (onset, duration, weight); AFNI style: one row per run of
        onsets, ``*`` for empty runs, optionally ``onset*weight`` or
        ``onset:duration`` annotations.
    scan_duration : float or list/array of float
        Duration (s) of each fMRI run; scalar for a single run.
    TR : float
        Repetition time (s).
    style : 'FSL' or 'AFNI'
    temp_res : float
        Temporal resolution (s) at which convolution is performed.
    hrf_para : dict or None
        Double-gamma parameters: keys ``response_delay``,
        ``undershoot_delay``, ``response_dispersion``,
        ``undershoot_dispersion``, ``undershoot_scale``.

    Returns
    -------
    design : ndarray, shape (n_TRs_total, n_conditions)
    """
    if hrf_para is None:
        hrf_para = {'response_delay': 6, 'undershoot_delay': 12,
                    'response_dispersion': 0.9, 'undershoot_dispersion': 0.9,
                    'undershoot_scale': 0.035}
    if style not in ('FSL', 'AFNI'):
        raise ValueError("style must be 'FSL' or 'AFNI'")
    if isinstance(stimtime_files, str):
        stimtime_files = [stimtime_files]
    scan_duration = np.atleast_1d(np.asarray(scan_duration, dtype=float))
    if TR <= 0:
        raise ValueError("TR must be positive")
    if np.any(scan_duration <= TR):
        raise ValueError("scan_duration must exceed TR for every run")
    n_runs = scan_duration.size
    run_TRs = np.round(scan_duration / TR).astype(int)

    # High-resolution double-gamma HRF (same parameterization family as the
    # reference / SPM): gamma-pdf response minus scaled gamma-pdf undershoot.
    from scipy.stats import gamma as gamma_dist
    hrf_len = int(np.round(32.0 / temp_res))
    t = np.arange(hrf_len) * temp_res
    response = gamma_dist.pdf(
        t, hrf_para['response_delay'] / hrf_para['response_dispersion'],
        scale=hrf_para['response_dispersion'])
    undershoot = gamma_dist.pdf(
        t, hrf_para['undershoot_delay'] / hrf_para['undershoot_dispersion'],
        scale=hrf_para['undershoot_dispersion'])
    hrf = response - hrf_para['undershoot_scale'] * undershoot
    hrf = hrf / np.max(hrf)

    run_starts = np.concatenate([[0.0], np.cumsum(scan_duration)])

    def parse_events(fname):
        """Return per-run lists of (onset, duration, weight).

        FSL: one event per line, columns onset[, duration[, weight]],
        onsets on the concatenated-run timeline; events outside every run
        are dropped.  AFNI: one line per run, tokens
        ``onset[*weight][:duration]``; ``*`` marks an empty run; events
        with onset < 0 or beyond the run duration are dropped.  Defaults:
        duration 1.0, weight 1.0.  (Reference utils/utils.py:500-655.)
        """
        events = [[] for _ in range(n_runs)]
        if style == 'FSL':
            with open(fname) as f:
                for line in f:
                    cols = line.split()
                    if not cols:
                        continue
                    onset = float(cols[0])
                    duration = float(cols[1]) if len(cols) >= 2 else 1.0
                    weight = float(cols[2]) if len(cols) >= 3 else 1.0
                    run = int(np.searchsorted(run_starts, onset,
                                              side='right')) - 1
                    if 0 <= run < n_runs:
                        events[run].append((onset - run_starts[run],
                                            duration, weight))
        else:  # AFNI
            with open(fname) as f:
                lines = [ln.strip() for ln in f if ln.strip() != '']
            if len(lines) != n_runs:
                raise ValueError(
                    'Number of lines does not match number of runs!')
            for run, line in enumerate(lines):
                toks = line.split()
                if toks and toks[0] == '*':
                    continue
                for tok in toks:
                    duration, weight = 1.0, 1.0
                    if ':' in tok:
                        tok, dur_s = tok.rsplit(':', 1)
                        duration = float(dur_s)
                    if '*' in tok:
                        tok, weight_s = tok.split('*')
                        weight = float(weight_s)
                    onset = float(tok)
                    if 0 <= onset < scan_duration[run]:
                        events[run].append((onset, duration, weight))
        return events

    n_cond = len(stimtime_files)
    design = np.zeros((int(run_TRs.sum()), n_cond))
    for c, fname in enumerate(stimtime_files):
        events = parse_events(fname)
        col_runs = []
        stride = int(round(TR / temp_res))
        for run in range(n_runs):
            n_hi = int(np.round(scan_duration[run] / temp_res))
            boxcar = np.zeros(n_hi)
            for onset, duration, weight in events[run]:
                lo = int(np.round(onset / temp_res))
                hi = int(np.round((onset + duration) / temp_res))
                boxcar[lo:min(hi, n_hi)] += weight
            # Scale by temp_res so the amplitude approximates the integral
            # of weight x HRF (reference utils/utils.py:136-138); sample at
            # mid-TR (slice-time-corrected convention, fmrisim convolve_hrf).
            conv = np.convolve(boxcar, hrf)[:n_hi] * temp_res
            idx = stride // 2 + np.arange(run_TRs[run]) * stride
            col_runs.append(conv[np.minimum(idx, n_hi - 1)])
        design[:, c] = np.concatenate(col_runs)
    return design
