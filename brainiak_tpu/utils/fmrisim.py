"""fMRI data simulator.

Re-design of /root/reference/src/brainiak/utils/fmrisim.py (Ellis et al.):
generate task signal volumes, stimulus time courses, HRF-convolved signal
functions, and realistic scanner noise (system/drift/ARMA/physiological/task
components scaled to target SNR/SFNR), plus noise-parameter estimation from
real data and receptive-field generators.

This is a host-side data generator (NumPy), as in the reference — it feeds
the TPU analysis pipelines rather than running on device.  Spatial noise
is a power-law spectral Gaussian random field with a self-calibrated
FWHM→exponent map (reference fmrisim.py:1890-1971), and the
``cos_power_drop`` drift is the DCT ladder with a 99%-power cutoff
(reference fmrisim.py:1546-1693).  ARMA coefficients are exact
per-voxel Gaussian MLEs from a batched Kalman-filter likelihood on a
zooming grid (an own estimator with the same contract as the
reference's statsmodels ARIMA MLE, fmrisim.py:1205-1289).  Documented
deviation from the reference internals:

- ``mask_brain`` without ``mask_self`` loads a PACKAGED brain template
  (``sim_parameters/brain_template.npz``, zoomed to the volume) through
  the same pipeline the reference uses for its grey-matter atlas
  (fmrisim.py:2230-2366).  The packaged template is procedurally
  generated once on the MNI-like grid (hemispheres, cortical shell,
  ventricles, smooth falloff; ``tools/gen_brain_template.py``) — gross
  statistical structure matches the atlas, voxel-level anatomical
  provenance does not.
"""

import logging
import os

import numpy as np
from scipy import ndimage, signal, stats

logger = logging.getLogger(__name__)

__all__ = [
    "apply_signal",
    "calc_noise",
    "compute_signal_change",
    "convolve_hrf",
    "export_3_column",
    "export_epoch_file",
    "generate_1d_gaussian_rfs",
    "generate_1d_rf_responses",
    "generate_noise",
    "generate_signal",
    "generate_stimfunction",
    "mask_brain",
]


# ---------------------------------------------------------------------------
# signal generation

def _insert_idxs(feature_centre, feature_size, dimensions):
    """Clip a feature's bounding box to the volume
    (reference fmrisim.py:283-308)."""
    def axis_idx(centre, dim):
        lo = int(centre - feature_size / 2) + 1
        hi = int(centre - feature_size / 2 + feature_size) + 1
        return [max(lo, 0), min(hi, int(dim))]

    x_idx = axis_idx(feature_centre[0], dimensions[0])
    y_idx = axis_idx(feature_centre[1], dimensions[1])
    z_idx = axis_idx(feature_centre[2], dimensions[2])
    return x_idx, y_idx, z_idx


def _generate_feature(feature_type, feature_size, signal_magnitude,
                      thickness=1):
    """One cube/loop/cavity/sphere feature (reference fmrisim.py:171-264)."""
    if feature_size <= 2:
        feature_type = 'cube'

    if feature_type == 'cube':
        sig = np.ones((feature_size,) * 3)
    elif feature_type == 'loop':
        sig = np.zeros((feature_size,) * 3)
        seq = np.linspace(0, feature_size - 1, feature_size)
        xx, yy = np.meshgrid(seq, seq)
        disk = (xx - (feature_size - 1) / 2) ** 2 + \
            (yy - (feature_size - 1) / 2) ** 2
        outer_lim = disk[int((feature_size - 1) / 2), 0]
        inner_lim = disk[int((feature_size - 1) / 2), thickness]
        loop = (disk <= outer_lim) != (disk <= inner_lim)
        if not loop.any():
            loop = disk <= outer_lim
        sig[:, :, int(np.round(feature_size / 2))] = loop
    elif feature_type in ('sphere', 'cavity'):
        seq = np.linspace(0, feature_size - 1, feature_size)
        xx, yy, zz = np.meshgrid(seq, seq, seq)
        dist = ((xx - (feature_size - 1) / 2) ** 2 +
                (yy - (feature_size - 1) / 2) ** 2 +
                (zz - (feature_size - 1) / 2) ** 2)
        c = int((feature_size - 1) / 2)
        outer_lim = dist[c, c, 0]
        inner_lim = dist[c, c, thickness]
        if feature_type == 'sphere':
            sig = dist <= outer_lim
        else:
            sig = (dist <= outer_lim) != (dist <= inner_lim)
        sig = sig.astype(float)
    else:
        raise ValueError("Unknown feature type: {}".format(feature_type))
    return np.asarray(sig, dtype=float) * signal_magnitude


def generate_signal(dimensions, feature_coordinates, feature_size,
                    feature_type, signal_magnitude=[1], signal_constant=1):
    """A single signal volume with cube/loop/cavity/sphere features
    (reference fmrisim.py:310-413)."""
    volume_signal = np.zeros(dimensions)
    feature_coordinates = np.asarray(feature_coordinates)
    if feature_coordinates.ndim == 1:
        feature_coordinates = feature_coordinates[np.newaxis]
    n = feature_coordinates.shape[0]
    feature_size = list(feature_size) * n if len(feature_size) == 1 \
        else list(feature_size)
    feature_type = list(feature_type) * n if len(feature_type) == 1 \
        else list(feature_type)
    signal_magnitude = list(signal_magnitude) * n \
        if len(signal_magnitude) == 1 else list(signal_magnitude)

    for i in range(n):
        centre = np.asarray(feature_coordinates[i])
        sig = _generate_feature(feature_type[i], feature_size[i],
                                signal_magnitude[i])
        if signal_constant == 0:
            sig = sig * np.random.random([feature_size[i]] * 3)
        x_idx, y_idx, z_idx = _insert_idxs(centre, feature_size[i],
                                           dimensions)
        volume_signal[x_idx[0]:x_idx[1], y_idx[0]:y_idx[1],
                      z_idx[0]:z_idx[1]] = \
            sig[:x_idx[1] - x_idx[0], :y_idx[1] - y_idx[0],
                :z_idx[1] - z_idx[0]]
    return volume_signal


def generate_stimfunction(onsets, event_durations, total_time, weights=[1],
                          timing_file=None, temporal_resolution=100.0):
    """Boxcar stimulus time course at the given temporal resolution
    (reference fmrisim.py:415-533)."""
    if timing_file is not None:
        onsets, event_durations, weights = [], [], []
        with open(timing_file) as f:
            for line in f:
                onset, duration, weight = line.strip().split()
                upsampled = float(onset) * temporal_resolution
                if not np.allclose(upsampled, np.round(upsampled)):
                    logger.warning(
                        'Onset %s has more decimal points than the '
                        'specified temporal resolution can resolve.', onset)
                onsets.append(float(onset))
                event_durations.append(float(duration))
                weights.append(float(weight))

    if len(event_durations) == 1:
        event_durations = list(event_durations) * len(onsets)
    if len(weights) == 1:
        weights = list(weights) * len(onsets)
    if len(onsets) and np.max(onsets) > total_time:
        raise ValueError('Onsets outside of range of total time.')

    stimfunction = np.zeros((int(round(total_time * temporal_resolution)),
                             1))
    for i in range(len(onsets)):
        onset_idx = int(np.floor(onsets[i] * temporal_resolution))
        offset_idx = int(np.floor((onsets[i] + event_durations[i])
                                  * temporal_resolution))
        stimfunction[onset_idx:offset_idx, 0] = weights[i]
    return stimfunction


def export_3_column(stimfunction, filename, temporal_resolution=100.0):
    """Write an FSL-style 3-column (onset, duration, weight) file
    (reference fmrisim.py:536-602)."""
    i = 0
    with open(filename, "a") as f:
        while i < stimfunction.shape[0]:
            if stimfunction[i, 0] != 0:
                onset = i / temporal_resolution
                weight = stimfunction[i, 0]
                duration = 0
                while i < stimfunction.shape[0] and \
                        stimfunction[i, 0] != 0:
                    duration += 1
                    i += 1
                f.write("{}\t{}\t{}\n".format(
                    onset, duration / temporal_resolution, weight))
            i += 1


def export_epoch_file(stimfunction, filename, tr_duration,
                      temporal_resolution=100.0):
    """Write a BrainIAK-style epoch file (list of condition × epoch × TR
    one-hot arrays) as .npy (reference fmrisim.py:605-721)."""
    epoch_file = [0] * len(stimfunction)
    for ppt_counter, ppt_stim in enumerate(stimfunction):
        ppt_stim = np.asarray(ppt_stim)
        n_conditions = ppt_stim.shape[1]
        trs = int(ppt_stim.shape[0] / (tr_duration * temporal_resolution))
        stride = int(tr_duration * temporal_resolution)
        epochs = []  # (condition, start_tr, end_tr)
        for cond in range(n_conditions):
            course = ppt_stim[::stride, cond][:trs]
            in_epoch = False
            start = 0
            for tr in range(trs):
                if course[tr] != 0 and not in_epoch:
                    in_epoch = True
                    start = tr
                elif course[tr] == 0 and in_epoch:
                    in_epoch = False
                    epochs.append((cond, start, tr))
            if in_epoch:
                epochs.append((cond, start, trs))
        arr = np.zeros((n_conditions, len(epochs), trs), dtype=np.int8)
        for e_idx, (cond, start, end) in enumerate(epochs):
            arr[cond, e_idx, start:end] = 1
        epoch_file[ppt_counter] = arr.astype(bool)
    # Same-shaped subjects stack into a plain bool array (the reference's
    # np.save(filename, epoch_file) behavior, fmrisim.py:720) which
    # io.load_labels reads back WITHOUT allow_pickle; only genuinely
    # ragged subjects need the pickled object-array form.
    shapes = {a.shape for a in epoch_file}
    if len(shapes) == 1:
        np.save(filename, np.stack(epoch_file))
    else:
        # ragged: build the object array explicitly (np.asarray on
        # partially-matching shapes attempts a broadcast and raises)
        obj = np.empty(len(epoch_file), dtype=object)
        for i, arr in enumerate(epoch_file):
            obj[i] = arr
        np.save(filename, obj)


def _double_gamma_hrf(response_delay=6, undershoot_delay=12,
                      response_dispersion=0.9, undershoot_dispersion=0.9,
                      response_scale=1, undershoot_scale=0.035,
                      temporal_resolution=100.0):
    """Double-gamma HRF sampled at the given resolution over 30 s
    (reference fmrisim.py:723-802)."""
    hrf_length = 30
    t = np.arange(int(hrf_length * temporal_resolution)) \
        / temporal_resolution
    response_peak = response_delay * response_dispersion
    undershoot_peak = undershoot_delay * undershoot_dispersion
    with np.errstate(divide='ignore', invalid='ignore'):
        resp = response_scale * (t / response_peak) ** response_delay * \
            np.exp(-(t - response_peak) / response_dispersion)
        under = undershoot_scale * (t / undershoot_peak) ** \
            undershoot_delay * \
            np.exp(-(t - undershoot_peak / undershoot_dispersion))
    hrf = np.nan_to_num(resp) - np.nan_to_num(under)
    hrf[-1] = 0
    return list(hrf)


def convolve_hrf(stimfunction, tr_duration, hrf_type='double_gamma',
                 scale_function=True, temporal_resolution=100.0):
    """Convolve stimulus time courses with the HRF and downsample to TRs
    (reference fmrisim.py:804-900)."""
    stimfunction = np.asarray(stimfunction)
    if stimfunction.ndim == 1:
        stimfunction = stimfunction[:, np.newaxis]
    if stimfunction.shape[0] < stimfunction.shape[1]:
        logger.warning('Stimfunction may be the wrong shape')

    stride = int(temporal_resolution * tr_duration)
    duration = int(stimfunction.shape[0] / stride)

    if isinstance(hrf_type, str):
        if hrf_type != 'double_gamma':
            # An unrecognized string (e.g. the typo 'double-gamma')
            # would otherwise coerce to a 0-d string array and fail
            # opaquely inside np.convolve; name the problem here.
            raise ValueError(
                f"Unrecognized hrf_type {hrf_type!r}: expected "
                "'double_gamma' or an array-like HRF kernel")
        hrf = _double_gamma_hrf(temporal_resolution=temporal_resolution)
    else:
        # user-supplied kernel (reference fmrisim.py:869-872 takes a
        # list; an ndarray would crash BOTH implementations at the
        # string comparison above without the isinstance guard)
        hrf = np.asarray(hrf_type)

    signal_function = np.zeros((duration, stimfunction.shape[1]))
    for col in range(stimfunction.shape[1]):
        conv = np.convolve(stimfunction[:, col], hrf)
        conv = conv[:duration * stride]
        vox = conv[int(stride / 2)::stride]
        if scale_function and np.max(np.abs(vox)) > 0:
            vox = vox / np.max(vox)
        signal_function[:, col] = vox
    return signal_function


def apply_signal(signal_function, volume_signal):
    """Combine a [TR, voxel] signal function with a signal volume into a
    4-D time series (reference fmrisim.py:903-966)."""
    signal_function = np.asarray(signal_function)
    if signal_function.ndim == 1:
        signal_function = signal_function[:, np.newaxis]
    dims = volume_signal.shape
    n_trs = signal_function.shape[0]
    signal = np.zeros(list(dims) + [n_trs])
    sig_coords = np.where(volume_signal != 0)
    n_sig_vox = len(sig_coords[0])
    if signal_function.shape[1] == 1:
        signal_function = np.tile(signal_function, (1, n_sig_vox))
    elif signal_function.shape[1] != n_sig_vox:
        raise IndexError("The number of columns in signal_function does "
                         "not match the number of signal voxels")
    for i in range(n_sig_vox):
        x, y, z = sig_coords[0][i], sig_coords[1][i], sig_coords[2][i]
        signal[x, y, z, :] = signal_function[:, i] * volume_signal[x, y, z]
    return signal


# ---------------------------------------------------------------------------
# brain mask / template

def _synthetic_brain_template(dims):
    """Procedural stand-in for the packaged grey-matter atlas: union of
    two hemisphere ellipsoids with a bright cortical shell, darker
    interior, and central ventricles, smoothed to scanner-like
    spatial continuity.  Values in [0, 1]."""
    grids = np.meshgrid(*[np.linspace(-1, 1, d) for d in dims],
                        indexing='ij')
    if len(dims) != 3:
        # non-3-D volumes: dims-agnostic radial falloff
        r = np.sqrt(sum((g / 0.8) ** 2 for g in grids))
        t = np.clip(1.2 - r, 0, None)
        return t / t.max() if t.max() > 0 else t
    gx, gy, gz = grids

    def ellipsoid_dist(cx, cy, cz, rx, ry, rz):
        return np.sqrt(((gx - cx) / rx) ** 2 + ((gy - cy) / ry) ** 2
                       + ((gz - cz) / rz) ** 2)

    # two hemispheres, slightly separated along x
    left = ellipsoid_dist(-0.22, 0.0, 0.0, 0.52, 0.72, 0.62)
    right = ellipsoid_dist(0.22, 0.0, 0.0, 0.52, 0.72, 0.62)
    d_brain = np.minimum(left, right)
    template = np.zeros(dims)
    interior = d_brain < 1.0
    # mid-intensity interior (white-matter-like)
    template[interior] = 0.75
    # bright cortical shell: the outer ~15% of the radial profile
    shell = (d_brain >= 0.85) & (d_brain < 1.0)
    template[shell] = 1.0
    # dark central ventricles, one per hemisphere
    vent = np.minimum(
        ellipsoid_dist(-0.12, 0.05, 0.05, 0.12, 0.22, 0.15),
        ellipsoid_dist(0.12, 0.05, 0.05, 0.12, 0.22, 0.15))
    template[vent < 1.0] = 0.3
    # smooth to scanner-like continuity (also softens the inter-
    # hemispheric gap) and renormalize
    sigma = max(1.0, min(dims) / 24.0)
    template = ndimage.gaussian_filter(template, sigma)
    if template.max() > 0:
        template = template / template.max()
    return template


_PACKAGED_TEMPLATE_CACHE = {}


def _load_packaged_template():
    """The packaged brain template (91 x 109 x 91 uint8 -> [0, 1]),
    generated once by ``_synthetic_brain_template`` on the MNI152-like
    grid via ``tools/gen_brain_template.py`` and stored as package data
    — the analog of the reference's grey-matter atlas loading
    (reference fmrisim.py:2288-2292)."""
    if "template" not in _PACKAGED_TEMPLATE_CACHE:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "sim_parameters", "brain_template.npz")
        with np.load(path) as payload:
            _PACKAGED_TEMPLATE_CACHE["template"] = \
                payload["template"].astype(np.float64) / 255.0
    return _PACKAGED_TEMPLATE_CACHE["template"]


def mask_brain(volume, template_name=None, mask_threshold=None,
               mask_self=True):
    """Produce a binary mask + continuous template for a volume
    (reference fmrisim.py:2230-2366).

    With ``mask_self`` the template comes from the volume itself; with
    ``template_name`` from that ``.npy`` file (reference
    fmrisim.py:2292-2294); otherwise from the PACKAGED brain template
    (``sim_parameters/brain_template.npz``), zoomed to the volume shape
    exactly like the reference zooms its grey-matter atlas.  The
    packaged template is procedurally generated (documented deviation:
    the reference's atlas is derived from MNI152 anatomy; voxel-level
    provenance differs, gross structure matches) — two hemispheres, a
    bright cortical shell around a mid-intensity interior, dark central
    ventricles, and a smooth falloff — so template-scaled noise
    components (SFNR maps, spatial scaling) exhibit realistic spatial
    heterogeneity and the histogram stays bimodal for the automatic
    mask threshold."""
    volume = np.asarray(volume, dtype=float)
    if volume.ndim == 1:
        volume = np.ones(volume.astype(int))

    if mask_self:
        mask_raw = volume
    elif template_name is not None:
        mask_raw = np.load(template_name)
    else:
        if volume.ndim < 3:
            # the packaged template is 3-D and the zoom below maps it
            # onto volume.shape[:3]; a 2-D volume has no meaningful
            # target shape (the reference unconditionally loads its
            # 3-D atlas and would fail the same way, just later)
            raise ValueError(
                "mask_brain with mask_self=False and no template_name "
                f"requires a >=3-D volume, got shape {volume.shape}")
        mask_raw = _load_packaged_template()

    if mask_raw.ndim == 4:
        mask_raw = mask_raw[..., 0] if mask_raw.shape[3] == 1 \
            else np.mean(mask_raw, 3)
    template = mask_raw / mask_raw.max()

    if volume.ndim == 3:
        volume = volume[..., np.newaxis]
    if template.shape != volume.shape[:3]:
        zoom_factor = tuple(volume.shape[i] / template.shape[i]
                            for i in range(3))
        template = ndimage.zoom(template, zoom_factor, order=2)
        template[template < 0] = 0

    if mask_threshold is None:
        # bimodal histogram: threshold at the minimum between the first
        # two peaks (reference fmrisim.py:2322-2342)
        order = 5
        hist, bins = np.histogram(template.reshape(-1), 100)
        binval = np.concatenate([np.zeros(order), hist])
        bins = np.concatenate([np.zeros(order), bins])
        peaks = signal.argrelmax(binval, order=order)[0][0:2]
        if len(peaks) == 2:
            minima = binval[peaks[0]:peaks[1]].min()
            minima_idx = (np.where(binval[peaks[0]:peaks[1]] == minima)
                          + peaks[0])[-1]
            mask_threshold = bins[minima_idx][0]
        else:
            mask_threshold = 0.5
    mask = (template > mask_threshold).astype(float)
    return mask, template


# ---------------------------------------------------------------------------
# noise components

def _noise_dict_update(noise_dict):
    """Fill missing noise parameters with defaults
    (reference fmrisim.py:2368-2440)."""
    default_dict = {'task_sigma': 0, 'drift_sigma': 0, 'auto_reg_sigma': 1,
                    'auto_reg_rho': [0.5], 'ma_rho': [0.0],
                    'physiological_sigma': 0, 'sfnr': 90, 'snr': 50,
                    'max_activity': 1000, 'voxel_size': [1.0, 1.0, 1.0],
                    'fwhm': 4, 'matched': 1}
    for key, value in default_dict.items():
        noise_dict.setdefault(key, value)
    return noise_dict


def _spectral_field(dimensions, exponent, white):
    """Filter a white-noise volume to a |k|^(-exponent/2) power-law
    spectrum (the standard spectral Gaussian-random-field recipe, as the
    reference adopts at fmrisim.py:1890-1971).  Wavenumbers are in
    cycles per VOXEL (plain fftfreq), so the weighting is isotropic in
    voxel units on non-cubic grids — per-box integer wavenumbers would
    make the short axis rougher per voxel."""
    freqs = np.meshgrid(*[np.fft.fftfreq(d) for d in dimensions],
                        indexing="ij")
    k = np.sqrt(sum(f ** 2 for f in freqs))
    amplitude = np.zeros_like(k)
    amplitude[k > 0] = k[k > 0] ** (-exponent / 2.0)
    return np.real(np.fft.ifftn(np.fft.fftn(white) * amplitude))


_SPECTRAL_CALIBRATION = {}


def _spectral_exponent_for_fwhm(dimensions, fwhm):
    """Spectral exponent realizing the requested FWHM on THIS grid.

    A pure power-law field is scale-free, so a fixed exponent yields a
    smoothness proportional to the box size (the reference's empirical
    FWHM→sigma map admits the same grid dependence,
    fmrisim.py:1923-1934).  Instead of a fixed fit, calibrate at
    runtime: measured FWHM is monotone in the exponent, so bisect on
    trial fields measured with :func:`_calc_fwhm`.  Results are cached
    per (grid, fwhm); a private RNG keeps the global NumPy stream
    untouched by calibration."""
    key = (tuple(dimensions), round(float(fwhm), 3))
    if key in _SPECTRAL_CALIBRATION:
        return _SPECTRAL_CALIBRATION[key]
    rng = np.random.default_rng(1234)
    ones = np.ones(dimensions)

    def measure(exponent, reps=3):
        vals = []
        for _ in range(reps):
            f = _spectral_field(dimensions, exponent,
                                rng.standard_normal(dimensions))
            f = (f - f.mean()) / (f.std() + 1e-12)
            vals.append(_calc_fwhm(f, ones))
        return float(np.mean(vals))

    lo, hi = 0.0, 10.0
    if measure(lo) >= fwhm:
        result = lo
    elif measure(hi) <= fwhm:
        result = hi
    else:
        for _ in range(7):
            mid = 0.5 * (lo + hi)
            if measure(mid) < fwhm:
                lo = mid
            else:
                hi = mid
        result = 0.5 * (lo + hi)
    _SPECTRAL_CALIBRATION[key] = result
    return result


def _generate_noise_spatial(dimensions, template=None, mask=None, fwhm=4.0):
    """Gaussian random field with a power-law spatial spectrum whose
    exponent is calibrated so the realized smoothness matches ``fwhm``
    on this grid.  Masked voxels are z-scored within the mask."""
    dimensions = tuple(int(d) for d in dimensions[:3])
    exponent = _spectral_exponent_for_fwhm(dimensions, fwhm)
    field = _spectral_field(dimensions, exponent,
                            np.random.randn(*dimensions))
    if mask is not None:
        field = field * mask
        inside = mask > 0
        field[inside] = stats.zscore(field[inside])
    else:
        field = (field - field.mean()) / (field.std() + 1e-12)
    return field


def _generate_noise_temporal_task(stimfunction_tr, motion_noise='gaussian'):
    """Task-locked noise (reference fmrisim.py:1502-1544)."""
    stimfunction_tr = (np.asarray(stimfunction_tr) != 0)
    if motion_noise == 'gaussian':
        noise = stimfunction_tr * np.random.normal(
            0, 1, size=stimfunction_tr.shape)
    elif motion_noise == 'rician':
        noise = stimfunction_tr * stats.rice.rvs(
            0, 1, size=stimfunction_tr.shape)
    else:
        raise ValueError("motion_noise must be gaussian or rician")
    noise_task = stimfunction_tr + noise
    return np.nan_to_num(stats.zscore(noise_task)).flatten()


def _drift_power_drop_rate(duration, period, tr_duration,
                           retained=0.99):
    """Per-basis geometric weight decay r solving the reference's
    power-drop criterion (1 - r^(2L/F)) / (1 - r^(2L/tr)) = retained,
    by bisection on (0, 1) — the ratio decreases monotonically from 1
    (r->0) to tr/F (r->1), so the root is unique.  Reproduces reference
    fmrisim.py:1634-1680 exactly; note its exponents index the basis
    whose PERIOD is 2F (DCT basis b has period 2L/b), so the realized
    cutoff is stronger than a literal 99%-of-power-below-F reading —
    drift comes out at least as smooth as requested."""
    if period < tr_duration:
        raise ValueError(
            'Drift period (%0.0f s) must be at least the TR duration '
            '(%0.0f s)' % (period, tr_duration))

    def ratio(r):
        return (1 - r ** (2 * duration / period)) / \
            (1 - r ** (2 * duration / tr_duration))

    lo, hi = 1e-12, 1 - 1e-12
    for _ in range(200):
        mid = 0.5 * (lo + hi)
        if ratio(mid) > retained:
            lo = mid
        else:
            hi = mid
    return 0.5 * (lo + hi)


def _generate_noise_temporal_drift(trs, tr_duration, basis="cos_power_drop",
                                   period=150):
    """Slow scanner drift (reference fmrisim.py:1546-1693).

    ``cos_power_drop`` (default) is a full DCT ladder (one basis per TR,
    frequency proportional to the basis index) with geometrically
    decaying weights chosen so 99% of the power sits below the requested
    period; ``discrete_cos`` is the equal-power harmonic ladder;
    ``sine`` a single randomized-phase sinusoid."""
    timepoints = np.linspace(0, trs - 1, trs) * tr_duration
    duration = trs * tr_duration
    if basis == "discrete_cos":
        rad = (timepoints / period) * 2 * np.pi
        basis_funcs = int(np.floor(duration / period))
        if basis_funcs == 0:
            logger.warning('Too few timepoints (%d) to accurately model '
                           'drift', trs)
            basis_funcs = 1
        b = np.arange(1, basis_funcs + 1)
        phases = np.random.rand(basis_funcs) * np.pi * 2
        ladder = np.cos(rad[:, None] / b[None, :] + phases[None, :])
        noise_drift = ladder.mean(axis=1)
    elif basis == "cos_power_drop":
        r = _drift_power_drop_rate(duration, period, tr_duration)
        # geometric weights vanish quickly: keep only bases above 1e-8
        # weight (identical output after the z-score; avoids an
        # O(trs^2) ladder on long runs)
        n_keep = trs if r >= 1.0 - 1e-12 else \
            min(trs, int(np.ceil(1 - 8 * np.log(10) / np.log(r))))
        b = np.arange(1, n_keep + 1)
        phases = np.random.rand(n_keep) * np.pi * 2
        ladder = np.cos(timepoints[:, None] / duration * np.pi *
                        b[None, :] + phases[None, :])
        noise_drift = (ladder * r ** (b - 1)[None, :]).mean(axis=1)
    elif basis == "sine":
        phase = np.random.rand() * np.pi * 2
        noise_drift = np.sin(timepoints / period * 2 * np.pi + phase)
    else:
        raise ValueError("Unknown drift basis: {}".format(basis))
    return np.nan_to_num(stats.zscore(noise_drift))


def _generate_noise_temporal_phys(timepoints, resp_freq=0.2,
                                  heart_freq=1.17):
    """Respiration + cardiac oscillations (reference fmrisim.py:1630-1674)."""
    timepoints = np.asarray(timepoints, dtype=float)
    resp_phase = np.random.rand() * 2 * np.pi
    heart_phase = np.random.rand() * 2 * np.pi
    noise_phys = np.cos(timepoints * resp_freq * 2 * np.pi + resp_phase) + \
        np.sin(timepoints * heart_freq * 2 * np.pi + heart_phase)
    return np.nan_to_num(stats.zscore(noise_phys))


def _generate_noise_temporal_autoregression(timepoints, noise_dict,
                                            dimensions, mask):
    """Spatially-varying ARMA noise: per-TR smooth spatial fields combined
    with AR and MA recursions (reference fmrisim.py:1676-1780)."""
    auto_reg_rho = list(noise_dict['auto_reg_rho'])
    ma_rho = list(noise_dict['ma_rho'])
    trs = len(timepoints)
    fields = np.stack([
        _generate_noise_spatial(dimensions, mask=mask,
                                fwhm=noise_dict['fwhm'])
        for _ in range(trs)], axis=3)
    noise = np.zeros_like(fields)
    for tr in range(trs):
        value = fields[..., tr].copy()
        for p, rho in enumerate(auto_reg_rho):
            if tr - (p + 1) >= 0:
                value += rho * noise[..., tr - (p + 1)]
        for q, theta in enumerate(ma_rho):
            if tr - (q + 1) >= 0:
                value += theta * fields[..., tr - (q + 1)]
        noise[..., tr] = value
    return np.nan_to_num(stats.zscore(noise, axis=3))


def _generate_noise_temporal(stimfunction_tr, tr_duration, dimensions,
                             template, mask, noise_dict):
    """Mix the brain-specific temporal noise components
    (reference fmrisim.py:1782-1906)."""
    trs = len(stimfunction_tr)
    timepoints = list(np.linspace(0, (trs - 1) * tr_duration, trs))
    noise_volume = np.zeros(tuple(dimensions[:3]) + (trs,))

    if noise_dict['physiological_sigma'] != 0:
        noise = _generate_noise_temporal_phys(timepoints)
        volume = _generate_noise_spatial(dimensions, mask=mask,
                                         fwhm=noise_dict['fwhm'])
        noise_volume += np.multiply.outer(volume, noise) * \
            noise_dict['physiological_sigma']

    if noise_dict['auto_reg_sigma'] != 0:
        noise = _generate_noise_temporal_autoregression(
            timepoints, noise_dict, dimensions, mask)
        noise_volume += noise * noise_dict['auto_reg_sigma']

    if noise_dict['task_sigma'] != 0 and np.sum(stimfunction_tr) > 0:
        noise = _generate_noise_temporal_task(stimfunction_tr)
        volume = _generate_noise_spatial(dimensions, mask=mask,
                                         fwhm=noise_dict['fwhm'])
        noise_volume += np.multiply.outer(volume, noise) * \
            noise_dict['task_sigma']

    noise_volume = stats.zscore(noise_volume, 3)
    return np.nan_to_num(noise_volume)


def _generate_noise_system(dimensions_tr, spatial_sd, temporal_sd,
                           spatial_noise_type='gaussian',
                           temporal_noise_type='gaussian'):
    """Scanner noise: a stable spatial pattern plus temporal jitter
    (reference fmrisim.py:1908-2010)."""
    def noise_volume(dimensions, noise_type):
        if noise_type == 'rician':
            return stats.rice.rvs(b=0, loc=0, scale=1.527, size=dimensions)
        if noise_type == 'exponential':
            return stats.expon.rvs(0, scale=1, size=dimensions)
        return np.random.normal(0, 1, size=dimensions)

    spatial = noise_volume(dimensions_tr[:3], spatial_noise_type)
    temporal = noise_volume(dimensions_tr, temporal_noise_type)
    # the temporal component is demeaned per voxel over time — exact,
    # not a distribution-mean constant — while the spatial pattern
    # keeps its raw location (reference fmrisim.py:1440-1482: a rician/
    # exponential spatial mean is part of the scanner's stable pattern)
    temporal = temporal - temporal.mean(axis=3, keepdims=True)
    return temporal * temporal_sd + \
        np.broadcast_to(spatial[..., np.newaxis] * spatial_sd,
                        dimensions_tr)


# ---------------------------------------------------------------------------
# noise estimation

def _calc_sfnr(volume, mask):
    """Mean over 2nd-order-detrended std per brain voxel
    (reference fmrisim.py:1079-1130)."""
    brain_voxels = volume[mask > 0]
    mean_voxels = np.nanmean(brain_voxels, 1)
    seq = np.linspace(1, brain_voxels.shape[1], brain_voxels.shape[1])
    detrend_poly = np.polyfit(seq, brain_voxels.T, 2)
    trend = (detrend_poly[0][:, None] * seq ** 2 +
             detrend_poly[1][:, None] * seq + detrend_poly[2][:, None])
    std_voxels = np.nanstd(brain_voxels - trend, 1)
    with np.errstate(divide='ignore', invalid='ignore'):
        sfnr = mean_voxels / std_voxels
    return float(np.mean(sfnr[np.isfinite(sfnr)]))


def _calc_snr(volume, mask, dilation=5, reference_tr=None):
    """Mean brain voxel / std of non-brain voxels
    (reference fmrisim.py:1132-1203)."""
    if reference_tr is None:
        reference_tr = list(range(volume.shape[3]))
    mask_dilated = ndimage.binary_dilation(mask, iterations=dilation) \
        if dilation > 0 else mask
    brain = volume[mask > 0][:, reference_tr]
    nonbrain = volume[:, :, :, reference_tr].astype('float64')
    if brain.ndim > 1:
        brain = np.mean(brain, 1)
        nonbrain = np.mean(nonbrain, 3)
    nonbrain = nonbrain[mask_dilated == 0]
    return float(np.nanmean(brain) / np.nanstd(nonbrain))


def _arma11_loglik_grid(x, rhos, thetas):
    """Concentrated exact Gaussian log-likelihood of ARMA(1,1) models,
    evaluated for every voxel and every (rho, theta) candidate at once.

    Uses the Kalman filter on the 2-state Harvey state-space form
    ``alpha_t = [x_t, theta*e_t]``, ``T = [[rho, 1], [0, 0]]``,
    ``R = [1, theta]``, with the innovation variance scale concentrated
    out.  For this 2-state model the filter collapses to scalar
    recursions:
    the second state component ``theta*e_{t+1}`` has zero conditional
    mean given the past, the cross/e-covariances freeze at
    ``p12 = theta``, ``p22 = theta**2`` after one step, and the
    stationary init is ``p11 = (1 + 2*rho*theta + theta**2) /
    (1 - rho**2)``.  Only the one-step prediction ``a1`` and its
    variance ``p11`` evolve, so every update is an elementwise op on
    the ``[n_voxels, n_candidates]`` batch (the time loop is the only
    Python loop).

    Parameters
    ----------
    x : [B, T] centered voxel time courses
    rhos, thetas : [B, C] candidate AR / MA coefficients per voxel

    Returns
    -------
    ll : [B, C] concentrated log-likelihoods
    """
    t = x.shape[1]
    rho = rhos
    theta = thetas
    p12 = theta
    p22 = theta * theta
    # Stationary variance of x_t (sigma2 = 1 scale, concentrated out).
    p11 = (1.0 + 2.0 * rho * theta + p22) / (1.0 - rho * rho)
    a1 = np.zeros_like(rho)                               # x one-step pred
    sum_log_f = np.zeros_like(rho)
    sum_sq = np.zeros_like(rho)
    for step in range(t):
        v = x[:, step, None] - a1                         # innovation
        f = np.maximum(p11, 1e-12)                        # its variance
        sum_log_f += np.log(f)
        sum_sq += v * v / f
        g = rho * p11 + p12                               # gain * f
        a1 = rho * a1 + g / f * v
        p11 = rho * rho * p11 + 2.0 * rho * p12 + p22 + 1.0 - g * g / f
    # Concentrate the innovation scale: sigma2_hat = sum_sq / t.
    return -0.5 * (t * np.log(np.maximum(sum_sq, 1e-300) / t)
                   + sum_log_f + t * (1.0 + np.log(2.0 * np.pi)))


def _arma11_mle(x, n_pts=13, n_zooms=3, half=0.94, clip=0.97):
    """Exact ARMA(1,1) Gaussian MLEs for every row of the centered batch
    ``x`` [B, T]: zooming grid search over (rho, theta) on the Kalman
    likelihood (:func:`_arma11_loglik_grid`) — coarse sweep of the
    invertible region, then refinements around each row's best cell.

    The single source of the grid recipe: used by ``_calc_ARMA_noise``
    (with the white-noise LRT gate on top) and by the parity suite's
    statsmodels-ARIMA stand-in (tests/parity/conftest.py), which must
    share the estimator exactly.

    Returns (rho [B], theta [B], ll_best [B]).
    """
    n_sampled = x.shape[0]
    centers_r = np.zeros(n_sampled)
    centers_t = np.zeros(n_sampled)
    ll_best = np.full(n_sampled, -np.inf)
    for _zoom in range(n_zooms):
        offs = np.linspace(-half, half, n_pts)
        rr, tt = np.meshgrid(offs, offs, indexing='ij')
        cand_r = np.clip(centers_r[:, None] + rr.ravel()[None], -clip,
                         clip)
        cand_t = np.clip(centers_t[:, None] + tt.ravel()[None], -clip,
                         clip)
        ll = _arma11_loglik_grid(x, cand_r, cand_t)
        best = np.argmax(ll, axis=1)
        rows = np.arange(n_sampled)
        centers_r = cand_r[rows, best]
        centers_t = cand_t[rows, best]
        ll_best = ll[rows, best]
        half /= (n_pts - 1) / 2.0
    return centers_r, centers_t, ll_best


# chi2(2).ppf(0.95)/2 nats: the 95% likelihood-ratio bar for the two
# extra ARMA(1,1) parameters over the white-noise model.
_ARMA_LRT_GATE = 3.0


def _calc_ARMA_noise(volume, mask, auto_reg_order=1, ma_order=1,
                     sample_num=100):
    """Exact per-voxel ARMA(1,1) maximum-likelihood estimates averaged
    over sampled brain voxels.

    Matches the reference's estimator contract (statsmodels ARIMA MLE
    per sampled voxel, then average — fmrisim.py:1205-1289) with an own
    estimator: the exact Kalman-filter likelihood is evaluated on a
    zooming (rho, theta) grid, batched over all sampled voxels in one
    vectorized recursion instead of a per-voxel optimizer loop.

    ARMA(1,1) is unidentified on white data — every point of the
    ``rho = -theta`` ridge is exactly the white-noise model, so the
    per-voxel argmax lands at an arbitrary (often extreme) near-ridge
    point there.  Each voxel therefore passes a likelihood-ratio gate
    against the white model: when the MLE improves on (0, 0) by less
    than ``_ARMA_LRT_GATE`` nats (the chi-square(2) 95% bar — the
    autocorrelation is statistically undetectable), that voxel reports
    (0, 0).  Identified coefficients are untouched pure MLEs.
    """
    if volume.ndim > 1:
        brain_timecourse = volume[mask > 0]
    else:
        brain_timecourse = volume.reshape(1, len(volume))
    n_vox = brain_timecourse.shape[0]
    idxs = np.random.permutation(n_vox)[:min(sample_num, n_vox)]
    x = brain_timecourse[idxs].astype('float64')
    x = x - x.mean(axis=1, keepdims=True)
    sd = x.std(axis=1)
    x = x[sd > 0] / sd[sd > 0][:, None]
    if x.shape[0] == 0 or x.shape[1] < 3:
        return [0.0] * auto_reg_order, [0.0] * ma_order

    centers_r, centers_t, ll_best = _arma11_mle(x)
    n_sampled = x.shape[0]
    # White-model likelihood-ratio gate (see docstring).
    ll_white = _arma11_loglik_grid(x, np.zeros((n_sampled, 1)),
                                   np.zeros((n_sampled, 1)))[:, 0]
    undetectable = ll_best - ll_white < _ARMA_LRT_GATE
    centers_r[undetectable] = 0.0
    centers_t[undetectable] = 0.0
    ar = float(np.nanmean(centers_r))
    ma = float(np.nanmean(centers_t))
    return [ar] * auto_reg_order, [ma] * ma_order


def _calc_fwhm(volume, mask, voxel_size=[1.0, 1.0, 1.0]):
    """Estimate smoothness from gradient variance (AFNI-style FWHM
    estimator, reference fmrisim.py:985-1077)."""
    v = volume * mask
    fwhm = []
    for axis, vs in enumerate(voxel_size):
        d = np.diff(v, axis=axis)
        valid = np.minimum(np.take(mask, range(1, mask.shape[axis]),
                                   axis=axis),
                           np.take(mask, range(0, mask.shape[axis] - 1),
                                   axis=axis)) > 0
        diffs = d[valid]
        inside = v[mask > 0]
        var_diff = np.var(diffs)
        var_all = np.var(inside)
        if var_diff <= 0 or var_all <= 0:
            continue
        r = 1 - var_diff / (2 * var_all)
        if r <= 0:
            continue
        fwhm.append(np.sqrt(-2 * np.log(2) / np.log(r)) * vs)
    return float(np.mean(fwhm)) if fwhm else float(np.mean(voxel_size))


def calc_noise(volume, mask, template, noise_dict=None):
    """Estimate the noise parameters of a real dataset
    (reference fmrisim.py:1291-1387)."""
    if template.max() > 1.1:
        raise ValueError('Template out of range')
    if mask is None:
        raise ValueError('Mask not supplied')
    if noise_dict is None:
        noise_dict = {'voxel_size': [1.0, 1.0, 1.0]}
    elif 'voxel_size' not in noise_dict:
        noise_dict['voxel_size'] = [1.0, 1.0, 1.0]
    noise_dict['max_activity'] = np.nanmax(np.mean(volume, 3))
    noise_dict['auto_reg_rho'], noise_dict['ma_rho'] = \
        _calc_ARMA_noise(volume, mask)
    noise_dict['auto_reg_sigma'] = 1
    noise_dict['physiological_sigma'] = 0
    noise_dict['task_sigma'] = 0
    noise_dict['drift_sigma'] = 0
    noise_dict['sfnr'] = _calc_sfnr(volume, mask)
    if volume.shape[3] > 100:
        trs = np.random.choice(volume.shape[3], size=100, replace=False)
    else:
        trs = list(range(volume.shape[3]))
    noise_dict['fwhm'] = float(np.mean(
        [_calc_fwhm(volume[:, :, :, tr], mask, noise_dict['voxel_size'])
         for tr in trs]))
    noise_dict['snr'] = _calc_snr(volume, mask)
    return noise_dict


# ---------------------------------------------------------------------------
# noise generation

def _fit_spatial(noise, noise_temporal, drift_noise, mask, template,
                 spatial_sd, temporal_sd, noise_dict, fit_thresh, fit_delta,
                 iterations):
    """Iteratively rescale the system spatial noise to hit the target SNR
    (reference fmrisim.py:2443-2611)."""
    dim_tr = noise.shape
    base = template * noise_dict['max_activity']
    base = base.reshape(dim_tr[0], dim_tr[1], dim_tr[2], 1)
    mean_signal = (base[mask > 0]).mean()
    target_snr = noise_dict['snr']
    spat_sd_orig = np.copy(spatial_sd)
    for iteration in range(iterations):
        new_snr = _calc_snr(noise, mask)
        if abs(new_snr - target_snr) / target_snr < fit_thresh:
            logger.info('Terminated SNR fit after %d iterations.',
                        iteration)
            break
        spat_sd_new = mean_signal / new_snr
        spatial_sd -= (spat_sd_new - spat_sd_orig) * fit_delta
        if spatial_sd < 0 or np.isnan(spatial_sd):
            spatial_sd = 10e-3
        noise_system = _generate_noise_system(
            dimensions_tr=dim_tr, spatial_sd=spatial_sd,
            temporal_sd=temporal_sd)
        noise = base + drift_noise + noise_system
        noise += noise_temporal * temporal_sd
        noise[noise < 0] = 0
    return noise, spatial_sd


def _fit_temporal(noise, mask, template, stimfunction_tr, tr_duration,
                  spatial_sd, temporal_proportion, temporal_sd, drift_noise,
                  noise_dict, fit_thresh, fit_delta, iterations):
    """Iteratively rescale the brain temporal noise to hit the target SFNR
    (reference fmrisim.py:2613-2831)."""
    dim_tr = noise.shape
    dimensions = np.asarray(dim_tr[:3])
    base = template * noise_dict['max_activity']
    base = base.reshape(dim_tr[0], dim_tr[1], dim_tr[2], 1)
    mean_signal = (base[mask > 0]).mean()
    target_sfnr = noise_dict['sfnr']
    temp_sd_orig = np.copy(temporal_sd)
    for iteration in range(iterations):
        new_sfnr = _calc_sfnr(noise, mask)
        if abs(new_sfnr - target_sfnr) / target_sfnr < fit_thresh:
            logger.info('Terminated SFNR fit after %d iterations.',
                        iteration)
            break
        temp_sd_new = mean_signal / new_sfnr
        temporal_sd -= (temp_sd_new - temp_sd_orig) * fit_delta
        if temporal_sd < 0 or np.isnan(temporal_sd):
            temporal_sd = 10e-3
        temporal_sd_system = np.sqrt(temporal_sd ** 2
                                     * temporal_proportion)
        noise_temporal = _generate_noise_temporal(
            stimfunction_tr, tr_duration, dimensions, template, mask,
            noise_dict)
        noise_system = _generate_noise_system(
            dimensions_tr=dim_tr, spatial_sd=spatial_sd,
            temporal_sd=temporal_sd_system)
        noise = base + drift_noise + noise_system
        noise += noise_temporal * temporal_sd
        noise[noise < 0] = 0
    return noise


def generate_noise(dimensions, stimfunction_tr, tr_duration, template,
                   mask=None, noise_dict=None, temporal_proportion=0.5,
                   iterations=None, fit_thresh=0.05, fit_delta=0.5):
    """Generate realistic fMRI noise matched to the target noise_dict
    (reference fmrisim.py:2833-3070)."""
    if noise_dict is None:
        noise_dict = {}
    noise_dict = _noise_dict_update(dict(noise_dict))

    if iterations is None:
        iterations = [20, 20] if noise_dict['matched'] == 1 else [0, 0]

    if abs(noise_dict['auto_reg_rho'][0]) - \
            abs(noise_dict['ma_rho'][0]) < 0.1:
        logger.warning('ARMA coefs are close, may have trouble fitting')

    dimensions = np.asarray(dimensions)
    dimensions_tr = (int(dimensions[0]), int(dimensions[1]),
                     int(dimensions[2]), len(stimfunction_tr))
    if mask is None:
        mask = np.ones(dimensions[:3])

    base = template * noise_dict['max_activity']
    base = base.reshape(dimensions_tr[0], dimensions_tr[1],
                        dimensions_tr[2], 1)
    base = np.ones(dimensions_tr) * base
    mean_signal = (base[mask > 0]).mean()

    noise_temporal = _generate_noise_temporal(
        stimfunction_tr, tr_duration, dimensions, template, mask,
        noise_dict)

    if noise_dict['drift_sigma'] != 0:
        drift = _generate_noise_temporal_drift(len(stimfunction_tr),
                                               tr_duration)
        drift_noise = np.multiply.outer(np.ones(dimensions_tr[:3]),
                                        drift) * noise_dict['drift_sigma']
    else:
        drift_noise = np.zeros(dimensions_tr)

    temporal_sd = mean_signal / noise_dict['sfnr']
    temporal_sd_system = np.sqrt(temporal_sd ** 2 * temporal_proportion)
    spat_sd = mean_signal / noise_dict['snr']
    spatial_sd = np.sqrt(spat_sd ** 2 * (1 - temporal_proportion))

    noise_system = _generate_noise_system(
        dimensions_tr=dimensions_tr, spatial_sd=spatial_sd,
        temporal_sd=temporal_sd_system)

    noise = base + drift_noise + noise_system
    noise += noise_temporal * temporal_sd
    noise[noise < 0] = 0

    noise, spatial_sd = _fit_spatial(
        noise, noise_temporal, drift_noise, mask, template, spatial_sd,
        temporal_sd_system, noise_dict, fit_thresh, fit_delta,
        iterations[0])
    noise = _fit_temporal(
        noise, mask, template, stimfunction_tr, tr_duration, spatial_sd,
        temporal_proportion, temporal_sd, drift_noise, noise_dict,
        fit_thresh, fit_delta, iterations[1])
    return noise


def compute_signal_change(signal_function, noise_function, noise_dict,
                          magnitude, method='PSC'):
    """Rescale a signal function to a desired effect-size metric
    (reference fmrisim.py:3072-3271)."""
    assert type(magnitude) is list, '"magnitude" should be a list of floats'
    signal_function = np.array(signal_function, dtype=float)
    noise_function = np.asarray(noise_function, dtype=float)
    if len(magnitude) == 1:
        magnitude = magnitude * signal_function.shape[1]
    if signal_function.shape != noise_function.shape:
        raise ValueError('noise_function is not the same size as '
                         'signal_function')

    overall_max = np.max(np.abs(signal_function))
    if overall_max == 0:
        # no events: nothing to scale
        return np.zeros(signal_function.shape)
    signal_function /= overall_max
    out = np.zeros(signal_function.shape)
    for v in range(signal_function.shape[1]):
        sig = signal_function[:, v]
        noise = noise_function[:, v]
        mag = magnitude[v]
        max_amp = np.max(np.abs(sig))
        if method == 'SFNR':
            new_sig = sig * ((noise.mean() / noise_dict['sfnr']) * mag)
        elif method == 'CNR_Amp/Noise-SD':
            new_sig = sig * (mag * np.std(noise))
        elif method == 'CNR_Amp2/Noise-Var_dB':
            scale = (10 ** (mag / 20)) * np.std(noise) / max_amp
            new_sig = sig * scale
        elif method == 'CNR_Signal-SD/Noise-SD':
            new_sig = sig * ((mag / max_amp) * np.std(noise)
                             / np.std(sig))
        elif method == 'CNR_Signal-Var/Noise-Var_dB':
            scale = (10 ** (mag / 20)) * np.std(noise) / (max_amp
                                                          * np.std(sig))
            new_sig = sig * scale
        elif method == 'PSC':
            new_sig = sig * ((noise.mean() / 100) * mag)
        else:
            raise ValueError("Unknown method: {}".format(method))
        out[:, v] = new_sig
    return out


# ---------------------------------------------------------------------------
# 1-D receptive fields

def generate_1d_gaussian_rfs(n_voxels, feature_resolution, feature_range,
                             rf_size=15, random_tuning=True, rf_noise=0.):
    """Gaussian voxel receptive fields along one feature dimension
    (reference fmrisim.py:3273-3336)."""
    range_start, range_stop = feature_range
    if random_tuning:
        voxel_tuning = np.floor(np.random.rand(n_voxels) * range_stop
                                + range_start).astype(int)
    else:
        voxel_tuning = np.linspace(range_start, range_stop,
                                   n_voxels + 1)[:-1]
        voxel_tuning = np.floor(voxel_tuning).astype(int)
    gaussian = signal.windows.gaussian(feature_resolution, rf_size)
    voxel_rfs = np.zeros((n_voxels, feature_resolution))
    for i in range(n_voxels):
        voxel_rfs[i, :] = np.roll(
            gaussian, voxel_tuning[i] - (feature_resolution // 2 - 1))
    voxel_rfs += np.random.rand(n_voxels, feature_resolution) * rf_noise
    voxel_rfs = voxel_rfs / np.max(voxel_rfs, axis=1)[:, None]
    return voxel_rfs, voxel_tuning


def generate_1d_rf_responses(rfs, trial_list, feature_resolution,
                             feature_range, trial_noise=0.25):
    """Trial-wise responses of the given receptive fields
    (reference fmrisim.py:3338-3388)."""
    range_start, range_stop = feature_range
    stim_axis = np.linspace(range_start, range_stop, feature_resolution)
    trial_list = np.asarray(trial_list, dtype=float)
    if range_start > 0:
        trial_list = trial_list + range_start
    elif range_start < 0:
        trial_list = trial_list - range_start
    one_hot = np.eye(feature_resolution)
    indices = [np.argmin(abs(stim_axis - x)) for x in trial_list]
    stimulus_mask = one_hot[:, indices]
    trial_data = rfs @ stimulus_mask
    trial_data += np.random.rand(rfs.shape[0], trial_list.size) * \
        (trial_noise * np.max(trial_data))
    return trial_data
