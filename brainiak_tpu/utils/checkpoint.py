"""Checkpoint / resume for iterative fits.

The reference has model-level persistence only (SRM.save/load npz,
FastSRM temp_dir spill — SURVEY.md §5.4) and no mid-iteration resume.
This module is the strict superset the TPU design calls for: any pytree of
EM/BCD state can be checkpointed every k iterations through orbax and a
fit resumed after preemption — the standard discipline for long TPU jobs.
"""

import logging
import os

import numpy as np

from ..resilience import faults
from ..resilience.retry import retry

logger = logging.getLogger(__name__)

__all__ = ["CheckpointManager"]

# Set BRAINIAK_TPU_CHECKPOINT_NPZ=1 to force the npz fallback even when
# orbax is importable (used by tests to cover both persistence paths).
FORCE_NPZ_ENV_VAR = "BRAINIAK_TPU_CHECKPOINT_NPZ"


class CheckpointManager:
    """Thin orbax-backed manager for (step, state-pytree) checkpoints.

    Falls back to ``np.savez`` of flattened leaves when orbax is
    unavailable (the state pytrees used here are flat dicts of arrays).

    ``save`` and ``restore`` retry transient ``OSError`` with
    exponential backoff (:func:`brainiak_tpu.resilience.retry.retry`) —
    a checkpoint writer on a shared filesystem must survive the
    transient faults it exists to protect against.
    """

    def __init__(self, directory, max_to_keep=2):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        try:
            if os.environ.get(FORCE_NPZ_ENV_VAR):
                raise ImportError(
                    f"{FORCE_NPZ_ENV_VAR} set; forcing npz checkpoints")
            import orbax.checkpoint as ocp
            self._ocp = ocp
            self._mngr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, create=True))
        except Exception as exc:
            logger.info("orbax unavailable (%s); using npz checkpoints",
                        exc)
            self._ocp = None
            self._mngr = None

    @retry(retries=2, backoff=0.2, retriable=(OSError,),
           name="checkpoint.save")
    def save(self, step, state):
        """Persist ``state`` (a pytree of arrays) at ``step``."""
        faults.io_point(self.directory, site="checkpoint.save")
        if self._mngr is not None:
            self._mngr.save(step, args=self._ocp.args.StandardSave(state))
            self._mngr.wait_until_finished()
        else:
            path = os.path.join(self.directory, f"ckpt_{step}.npz")
            # savez appends ".npz" to bare filenames, so write through an
            # open handle to keep the tmp name exact for the atomic rename.
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                np.savez(f, **{k: np.asarray(v) for k, v in state.items()})
            os.replace(tmp, path)  # atomic: survive preemption mid-save
            self._prune_npz()

    def _prune_npz(self):
        # None or <=0 mean keep everything (orbax convention).
        if not self.max_to_keep or self.max_to_keep <= 0:
            return
        steps = sorted(s for s in (self._npz_step(f)
                                   for f in os.listdir(self.directory))
                       if s is not None)
        for s in steps[:-self.max_to_keep]:
            try:
                os.remove(os.path.join(self.directory, f"ckpt_{s}.npz"))
            except OSError:  # pragma: no cover - concurrent cleanup
                pass

    @staticmethod
    def _npz_step(fname):
        if fname.startswith("ckpt_") and fname.endswith(".npz"):
            try:
                return int(fname[5:-4])
            except ValueError:
                return None
        return None

    def latest_step(self):
        if self._mngr is not None:
            return self._mngr.latest_step()
        steps = [s for s in (self._npz_step(f)
                             for f in os.listdir(self.directory))
                 if s is not None]
        return max(steps) if steps else None

    @retry(retries=2, backoff=0.2, retriable=(OSError,),
           name="checkpoint.restore")
    def restore(self, step=None, template=None):
        """Load the checkpoint at ``step`` (default latest); returns
        (step, state) or (None, None) when nothing exists."""
        faults.io_point(self.directory, site="checkpoint.restore")
        if step is None:
            step = self.latest_step()
        if step is None:
            return None, None
        if self._mngr is not None:
            # StandardRestore() without a template restores the raw
            # saved tree (needed for states whose leaf shapes are not
            # known a priori, e.g. BRSA's round-dependent nuisance
            # design); a bare restore(step) would require a handler
            # registry in a fresh process.
            state = self._mngr.restore(
                step, args=self._ocp.args.StandardRestore(template))
            return step, state
        path = os.path.join(self.directory, f"ckpt_{step}.npz")
        loaded = np.load(path)
        return step, {k: loaded[k] for k in loaded.files}
