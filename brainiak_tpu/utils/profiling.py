"""Deprecated shim over :mod:`brainiak_tpu.obs` (PR 3).

The 71-line ad-hoc timing registry that used to live here grew into
the ``brainiak_tpu/obs/`` subsystem: hierarchical spans
(:func:`brainiak_tpu.obs.span`), a typed metric registry, JSONL sinks,
and the ``python -m brainiak_tpu.obs report`` CLI — see
docs/observability.md.

These names keep working exactly as before (``stage_timer`` always
records into the thread-safe in-process registry and always honors
``sync``, no sink required) but new code should import from
``brainiak_tpu.obs`` directly — importing this shim emits a
``DeprecationWarning`` saying so.
"""

import warnings

warnings.warn(
    "brainiak_tpu.utils.profiling is deprecated: import "
    "stage_timer/stage_times/reset_stage_times/device_trace from "
    "brainiak_tpu.obs instead (see docs/observability.md)",
    DeprecationWarning, stacklevel=2)

from ..obs.runtime import device_trace  # noqa: E402,F401
from ..obs.spans import (  # noqa: E402,F401
    reset_stage_times,
    stage_timer,
    stage_times,
)

__all__ = ["stage_timer", "stage_times", "reset_stage_times",
           "device_trace"]
