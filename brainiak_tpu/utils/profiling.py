"""Structured timing and device profiling hooks.

The reference's observability is ad-hoc ``time.time()`` pairs around
pipeline stages logged at DEBUG (SURVEY.md §5.1, e.g.
fcma/voxelselector.py:299-328).  Here the same intent is a reusable
context manager with an inspectable registry, plus a wrapper around
``jax.profiler`` traces for device-level analysis (the TPU-native
replacement for wall-clock-only timing).
"""

import contextlib
import logging
import time
from collections import defaultdict

logger = logging.getLogger(__name__)

__all__ = ["stage_timer", "stage_times", "reset_stage_times",
           "device_trace"]

_times = defaultdict(list)


@contextlib.contextmanager
def stage_timer(name, sync=None):
    """Time a pipeline stage; ``sync`` may be an array (or pytree) to
    block on before stopping the clock (remember: dispatch is async).

    Results accumulate in a process-wide registry readable with
    :func:`stage_times`.
    """
    t0 = time.perf_counter()
    holder = {}
    try:
        yield holder
    finally:
        target = holder.get("sync", sync)
        if target is not None:
            try:
                import jax
            except ImportError:
                jax = None
            if jax is not None:
                # computation errors surfaced here must propagate — a
                # swallowed failure would record a bogus (unsynced) time
                jax.block_until_ready(target)
        dt = time.perf_counter() - t0
        _times[name].append(dt)
        logger.debug("stage %s took %.3fs", name, dt)


def stage_times():
    """Mapping of stage name -> list of durations (seconds)."""
    return {k: list(v) for k, v in _times.items()}


def reset_stage_times():
    _times.clear()


@contextlib.contextmanager
def device_trace(log_dir):
    """Capture a jax.profiler trace (TensorBoard-viewable) around a block
    of device work."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
