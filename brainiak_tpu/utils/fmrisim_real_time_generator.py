"""Real-time fMRI data generator (the framework's one CLI).

Re-design of /root/reference/src/brainiak/utils/fmrisim_real_time_generator.py:
streams simulated TR-by-TR volumes to disk for testing real-time analysis
pipelines.  Differences from the reference: inputs that the reference ships
as packaged files (ROIs, template, noise dict) are synthesized when not
provided; DICOM output requires pydicom and raises a clear error when it is
absent (it is an optional dependency there too).

Two consumption modes share one simulation path (:func:`_simulate`):

- :func:`generate_data` — the on-disk CLI path: mask/labels/ROI volumes
  plus one ``rt_<TR>.npy`` (or ``.dcm``) per TR, optionally paced at one
  volume per ``trDuration`` (``save_realtime``).  Under a fixed ``rng``
  seed the written bytes are deterministic across runs.
- :func:`generate_stream` — the in-memory mode: returns a
  :class:`RealtimeStream` whose iterator yields one ``[x, y, z]`` volume
  per TR with the mask/ROIs/labels as attributes, so a closed-loop
  consumer (:mod:`brainiak_tpu.realtime`) never round-trips through
  disk.

Randomness: ``rng`` accepts a seed or a ``numpy.random.Generator`` and
threads through every draw this module makes; because the underlying
:mod:`fmrisim` synthesis routines draw from global NumPy state, a
seeded call also pins that stream (from the generator) for the
duration of the simulation, making the whole volume sequence
reproducible.  ``rng=None`` keeps the legacy behavior (global state,
non-deterministic).

Run as ``python -m brainiak_tpu.utils.fmrisim_real_time_generator -o DIR``.
"""

import argparse
import logging
import os
import time
from pathlib import Path

import numpy as np

from . import fmrisim as sim

logger = logging.getLogger(__name__)

__all__ = ["RealtimeStream", "default_settings", "generate_data",
           "generate_stream"]

default_settings = {
    'ROI_A_file': None,
    'ROI_B_file': None,
    'template_path': None,
    'noise_dict_file': None,
    'numTRs': 200,
    'event_duration': 10,
    'scale_percentage': 0.5,
    'multivariate_pattern': False,
    'different_ROIs': False,
    'save_dicom': False,
    'save_realtime': False,
    'trDuration': 2,
    'isi': 6,
    'burn_in': 6,
}


class _GlobalStateRNG:
    """Legacy draw source for ``rng=None``: the module's own draws
    come from process-global NumPy state exactly as they always did
    (``np.random.randint``/``rand``), so a caller that seeded the
    global stream keeps its pre-``rng=`` reproducibility."""

    @staticmethod
    def integers(low, high):
        return np.random.randint(low, high)

    @staticmethod
    def random(shape):
        return np.random.rand(*shape)


def _resolve_rng(rng):
    """``(generator, seeded)``: the draw source for this module's
    own draws, plus whether the caller asked for determinism (a
    seed or an explicit Generator) — in which case the global NumPy
    stream the fmrisim internals read is pinned too (and restored
    afterwards)."""
    if rng is None:
        return _GlobalStateRNG(), False
    return np.random.default_rng(rng), True


def _default_inputs(data_dict):
    """Synthesize template/ROIs/noise parameters when not supplied
    (the reference loads packaged files, fmrisim_real_time_generator
    .py:117-186)."""
    dims = np.array([24, 24, 16])
    if data_dict['template_path'] is None:
        _, template = sim.mask_brain(dims, mask_self=False)
        template = template * 1000
    else:
        template = np.load(data_dict['template_path'])
        dims = np.array(template.shape[:3])

    def roi(center):
        vol = sim.generate_signal(dimensions=dims,
                                  feature_coordinates=np.array([center]),
                                  feature_type=['cube'],
                                  feature_size=[4],
                                  signal_magnitude=[1])
        return vol

    roi_a = np.load(data_dict['ROI_A_file']) \
        if data_dict['ROI_A_file'] else roi([8, 8, 8])
    roi_b = np.load(data_dict['ROI_B_file']) \
        if data_dict['ROI_B_file'] else roi([16, 16, 8])

    if data_dict['noise_dict_file']:
        with open(data_dict['noise_dict_file']) as f:
            noise_dict = eval(f.read())  # reference behavior
    else:
        noise_dict = {'snr': 30, 'sfnr': 70, 'max_activity': 1000,
                      'matched': 0}
    return roi_a, roi_b, template, noise_dict, dims


def _save_volume(volume, out_file, save_dicom):
    if save_dicom:
        try:
            import pydicom  # noqa: F401
        except ImportError:
            raise ImportError(
                "DICOM output requires pydicom, which is not installed; "
                "use save_dicom=False for .npy output")
        _write_dicom(volume, out_file)
    else:
        np.save(out_file, volume.astype(np.int16))


def _write_dicom(volume, out_file):
    """Minimal secondary-capture DICOM writer (reference
    fmrisim_real_time_generator.py:187-265)."""
    import pydicom
    from pydicom.dataset import FileDataset, FileMetaDataset

    meta = FileMetaDataset()
    meta.MediaStorageSOPClassUID = \
        pydicom.uid.SecondaryCaptureImageStorage
    meta.MediaStorageSOPInstanceUID = pydicom.uid.generate_uid()
    meta.TransferSyntaxUID = pydicom.uid.ImplicitVRLittleEndian
    ds = FileDataset(out_file, {}, file_meta=meta, preamble=b"\0" * 128)
    ds.NumberOfFrames = volume.shape[2]
    ds.Rows = volume.shape[0]
    ds.Columns = volume.shape[1]
    ds.SamplesPerPixel = 1
    ds.BitsAllocated = 16
    ds.BitsStored = 16
    ds.HighBit = 15
    ds.PixelRepresentation = 0
    ds.PhotometricInterpretation = "MONOCHROME2"
    ds.PixelData = volume.astype(np.uint16).tobytes()
    ds.save_as(out_file, write_like_original=False)


def _simulate(data_dict, rng):
    """The simulation shared by the on-disk and in-memory modes:
    synthesizes (or loads) the inputs, generates noise + evoked
    signal, and returns the whole-scan arrays as a dict with keys
    ``brain`` [x, y, z, T], ``mask``, ``roi_a``, ``roi_b`` (binary
    uint8 volumes), ``labels`` [T*tr, 1], and ``dims``.

    ``rng`` is this module's draw stream (onset coin flips, the
    multivariate pattern); when the caller seeded it, the global
    NumPy stream the fmrisim internals use is pinned from it too, so
    the full volume sequence is reproducible.
    """
    rng, seeded = _resolve_rng(rng)
    if not seeded:
        return _simulate_body(data_dict, rng)
    # fmrisim's synthesis (generate_noise et al.) draws from global
    # NumPy state; pin it from the caller's generator so a seeded
    # run is reproducible end to end — and restore the caller's
    # global stream afterwards (the pin lasts only for the
    # duration of the simulation)
    saved_state = np.random.get_state()
    np.random.seed(int(rng.integers(0, 2 ** 32)))
    try:
        return _simulate_body(data_dict, rng)
    finally:
        np.random.set_state(saved_state)


def _simulate_body(data_dict, rng):
    roi_a, roi_b, template, noise_dict, dims = _default_inputs(data_dict)
    mask, template = sim.mask_brain(volume=template, mask_self=True)

    noise_dict['matched'] = 0
    num_trs = data_dict['numTRs']
    tr_dur = data_dict['trDuration']
    logger.info('Generating noise')
    noise = sim.generate_noise(
        dimensions=dims,
        stimfunction_tr=np.zeros((num_trs, 1)),
        tr_duration=int(tr_dur),
        template=template,
        mask=mask,
        noise_dict=noise_dict)

    total_time = int(num_trs * tr_dur)
    onsets_a, onsets_b = [], []
    curr_time = data_dict['burn_in']
    while curr_time < total_time - data_dict['event_duration']:
        (onsets_a if int(rng.integers(0, 2)) == 1
         else onsets_b).append(curr_time)
        curr_time += data_dict['event_duration'] + data_dict['isi']

    temporal_res = 1 / tr_dur
    stimfunc_a = sim.generate_stimfunction(
        onsets=onsets_a, event_durations=[data_dict['event_duration']],
        total_time=total_time, temporal_resolution=temporal_res)
    stimfunc_b = sim.generate_stimfunction(
        onsets=onsets_b, event_durations=[data_dict['event_duration']],
        total_time=total_time, temporal_resolution=temporal_res)
    labels = stimfunc_a + stimfunc_b * 2

    def roi_signal(roi_vol, stimfunc, scale):
        """Evoked signal within an ROI scaled as percent signal change."""
        sf = sim.convolve_hrf(stimfunc, tr_dur,
                              temporal_resolution=temporal_res)
        n_vox = int((roi_vol > 0).sum())
        if data_dict['multivariate_pattern']:
            pattern = rng.random((1, n_vox))
            sf = sf @ pattern
        sig_func = np.tile(sf, (1, n_vox)) if sf.shape[1] == 1 else sf
        noise_fn = noise[roi_vol > 0].T
        sig_func = sim.compute_signal_change(
            sig_func, noise_fn, noise_dict, [scale], 'PSC')
        return sim.apply_signal(sig_func, roi_vol)

    scale = data_dict['scale_percentage']
    signal_a = roi_signal(roi_a, stimfunc_a, scale)
    if data_dict['different_ROIs']:
        signal_b = roi_signal(roi_b, stimfunc_b, scale)
    elif data_dict['multivariate_pattern']:
        signal_b = roi_signal(roi_a, stimfunc_b, scale)
    else:
        signal_b = roi_signal(roi_a, stimfunc_b, scale * 0.5)

    return {
        'brain': noise + signal_a + signal_b,
        'mask': mask.astype(np.uint8),
        'roi_a': (roi_a > 0).astype(np.uint8),
        'roi_b': (roi_b > 0).astype(np.uint8),
        'labels': labels,
        'dims': dims,
    }


class RealtimeStream:
    """In-memory realtime scan: iterate for one ``[x, y, z]`` volume
    per TR (no disk round-trip).

    Attributes mirror the files :func:`generate_data` writes:
    ``mask`` / ``roi_a`` / ``roi_b`` (binary uint8 volumes),
    ``labels`` (per-stimulus-sample condition vector), ``n_trs``,
    ``tr_duration_s``, plus the full ``brain`` [x, y, z, T] array
    for batch-parity checks.  ``paced=True`` sleeps the iterator to
    one volume per TR (the ``save_realtime`` analog); the default
    yields as fast as the consumer pulls.
    """

    def __init__(self, sim_out, tr_duration_s, paced=False):
        self.brain = sim_out['brain']
        self.mask = sim_out['mask']
        self.roi_a = sim_out['roi_a']
        self.roi_b = sim_out['roi_b']
        self.labels = sim_out['labels']
        self.tr_duration_s = float(tr_duration_s)
        self.paced = bool(paced)

    @property
    def n_trs(self):
        return int(self.brain.shape[3])

    def __len__(self):
        return self.n_trs

    def volume(self, tr):
        """The ``[x, y, z]`` volume at ``tr`` (random access — what
        lets a resumed closed-loop session seek mid-scan)."""
        return self.brain[:, :, :, int(tr)]

    def __iter__(self):
        # the shared absolute-monotonic scheduler (also used by the
        # realtime ingest replays): TR t is due at
        # start + t*trDuration, so consumer processing time between
        # pulls counts against the period and pacing never drifts —
        # and a wall-clock step (NTP, DST) cannot stall or burst
        # the simulated scanner
        from .utils import MonotonicPacer

        pacer = MonotonicPacer(self.tr_duration_s
                               if self.paced else 0.0)
        for tr in range(self.n_trs):
            pacer.wait()
            yield self.brain[:, :, :, tr]


def generate_stream(user_settings=None, rng=None, paced=False):
    """Simulate a realtime scan fully in memory; returns a
    :class:`RealtimeStream` (the generator-function mode — no disk
    round-trip, same volumes the on-disk path would write, before
    the int16 save cast).

    ``user_settings`` updates :data:`default_settings`; ``rng`` is a
    seed or ``numpy.random.Generator`` (a seeded call is
    reproducible end to end, see the module docstring).
    """
    data_dict = default_settings.copy()
    data_dict.update(user_settings or {})
    out = _simulate(data_dict, rng)
    return RealtimeStream(out, data_dict['trDuration'], paced=paced)


def generate_data(outputDir, user_settings, rng=None):
    """Generate and stream simulated realtime data to ``outputDir``
    (reference fmrisim_real_time_generator.py:349-533).

    Writes mask.npy, labels.npy, and one rt_<TR>.npy (or .dcm) per TR.
    ``rng`` (seed or ``numpy.random.Generator``): a fixed seed makes
    the written bytes deterministic across runs; None keeps the
    legacy global-state behavior.
    """
    data_dict = default_settings.copy()
    data_dict.update(user_settings)
    Path(outputDir).mkdir(parents=True, exist_ok=True)

    out = _simulate(data_dict, rng)
    np.save(os.path.join(outputDir, 'mask.npy'), out['mask'])
    # the analysis side needs the ROI geometry (the reference ships its
    # ROI volumes as package data next to the generated stream)
    np.save(os.path.join(outputDir, 'roi_a.npy'), out['roi_a'])
    np.save(os.path.join(outputDir, 'roi_b.npy'), out['roi_b'])
    np.save(os.path.join(outputDir, 'labels.npy'), out['labels'])

    num_trs = data_dict['numTRs']
    tr_dur = data_dict['trDuration']
    brain = out['brain']
    for tr in range(num_trs):
        start = time.time()
        vol = brain[:, :, :, tr]
        ext = 'dcm' if data_dict['save_dicom'] else 'npy'
        out_file = os.path.join(outputDir, 'rt_{0:0>3}.{1}'.format(tr, ext))
        _save_volume(vol, out_file, data_dict['save_dicom'])
        if data_dict['save_realtime']:
            elapsed = time.time() - start
            time.sleep(max(0.0, tr_dur - elapsed))
    logger.info('Generated %d volumes in %s', num_trs, outputDir)


def main():
    p = argparse.ArgumentParser(description="Generate simulated realtime "
                                            "fMRI data")
    p.add_argument('--output-dir', '-o', required=True, type=str)
    p.add_argument('--ROI-A-file', default=None, type=str)
    p.add_argument('--ROI-B-file', default=None, type=str)
    p.add_argument('--template-path', default=None, type=str)
    p.add_argument('--noise-dict-file', default=None, type=str)
    p.add_argument('--numTRs', '-n', default=200, type=int)
    p.add_argument('--event-duration', '-d', default=10, type=int)
    p.add_argument('--trDuration', default=2, type=int)
    p.add_argument('--isi', default=6, type=int)
    p.add_argument('--burn-in', default=6, type=int)
    p.add_argument('--scale-percentage', '-s', default=0.5, type=float)
    p.add_argument('--multivariate-pattern', '-m', action='store_true')
    p.add_argument('--different-ROIs', '-r', action='store_true')
    p.add_argument('--save-dicom', action='store_true')
    p.add_argument('--save-realtime', action='store_true')
    p.add_argument('--seed', default=None, type=int,
                   help="seed the simulation (deterministic output "
                        "bytes for a fixed seed)")
    args = p.parse_args()
    settings = {
        'ROI_A_file': args.ROI_A_file,
        'ROI_B_file': args.ROI_B_file,
        'template_path': args.template_path,
        'noise_dict_file': args.noise_dict_file,
        'numTRs': args.numTRs,
        'event_duration': args.event_duration,
        'trDuration': args.trDuration,
        'isi': args.isi,
        'burn_in': args.burn_in,
        'scale_percentage': args.scale_percentage,
        'multivariate_pattern': args.multivariate_pattern,
        'different_ROIs': args.different_ROIs,
        'save_dicom': args.save_dicom,
        'save_realtime': args.save_realtime,
    }
    generate_data(args.output_dir, settings, rng=args.seed)


if __name__ == "__main__":
    main()
