"""Real-time fMRI data generator (the framework's one CLI).

Re-design of /root/reference/src/brainiak/utils/fmrisim_real_time_generator.py:
streams simulated TR-by-TR volumes to disk for testing real-time analysis
pipelines.  Differences from the reference: inputs that the reference ships
as packaged files (ROIs, template, noise dict) are synthesized when not
provided; DICOM output requires pydicom and raises a clear error when it is
absent (it is an optional dependency there too).

Run as ``python -m brainiak_tpu.utils.fmrisim_real_time_generator -o DIR``.
"""

import argparse
import logging
import os
import time
from pathlib import Path

import numpy as np

from . import fmrisim as sim

logger = logging.getLogger(__name__)

__all__ = ["generate_data", "default_settings"]

default_settings = {
    'ROI_A_file': None,
    'ROI_B_file': None,
    'template_path': None,
    'noise_dict_file': None,
    'numTRs': 200,
    'event_duration': 10,
    'scale_percentage': 0.5,
    'multivariate_pattern': False,
    'different_ROIs': False,
    'save_dicom': False,
    'save_realtime': False,
    'trDuration': 2,
    'isi': 6,
    'burn_in': 6,
}


def _default_inputs(data_dict):
    """Synthesize template/ROIs/noise parameters when not supplied
    (the reference loads packaged files, fmrisim_real_time_generator
    .py:117-186)."""
    dims = np.array([24, 24, 16])
    if data_dict['template_path'] is None:
        _, template = sim.mask_brain(dims, mask_self=False)
        template = template * 1000
    else:
        template = np.load(data_dict['template_path'])
        dims = np.array(template.shape[:3])

    def roi(center):
        vol = sim.generate_signal(dimensions=dims,
                                  feature_coordinates=np.array([center]),
                                  feature_type=['cube'],
                                  feature_size=[4],
                                  signal_magnitude=[1])
        return vol

    roi_a = np.load(data_dict['ROI_A_file']) \
        if data_dict['ROI_A_file'] else roi([8, 8, 8])
    roi_b = np.load(data_dict['ROI_B_file']) \
        if data_dict['ROI_B_file'] else roi([16, 16, 8])

    if data_dict['noise_dict_file']:
        with open(data_dict['noise_dict_file']) as f:
            noise_dict = eval(f.read())  # reference behavior
    else:
        noise_dict = {'snr': 30, 'sfnr': 70, 'max_activity': 1000,
                      'matched': 0}
    return roi_a, roi_b, template, noise_dict, dims


def _save_volume(volume, out_file, save_dicom):
    if save_dicom:
        try:
            import pydicom  # noqa: F401
        except ImportError:
            raise ImportError(
                "DICOM output requires pydicom, which is not installed; "
                "use save_dicom=False for .npy output")
        _write_dicom(volume, out_file)
    else:
        np.save(out_file, volume.astype(np.int16))


def _write_dicom(volume, out_file):
    """Minimal secondary-capture DICOM writer (reference
    fmrisim_real_time_generator.py:187-265)."""
    import pydicom
    from pydicom.dataset import FileDataset, FileMetaDataset

    meta = FileMetaDataset()
    meta.MediaStorageSOPClassUID = \
        pydicom.uid.SecondaryCaptureImageStorage
    meta.MediaStorageSOPInstanceUID = pydicom.uid.generate_uid()
    meta.TransferSyntaxUID = pydicom.uid.ImplicitVRLittleEndian
    ds = FileDataset(out_file, {}, file_meta=meta, preamble=b"\0" * 128)
    ds.NumberOfFrames = volume.shape[2]
    ds.Rows = volume.shape[0]
    ds.Columns = volume.shape[1]
    ds.SamplesPerPixel = 1
    ds.BitsAllocated = 16
    ds.BitsStored = 16
    ds.HighBit = 15
    ds.PixelRepresentation = 0
    ds.PhotometricInterpretation = "MONOCHROME2"
    ds.PixelData = volume.astype(np.uint16).tobytes()
    ds.save_as(out_file, write_like_original=False)


def generate_data(outputDir, user_settings):
    """Generate and stream simulated realtime data to ``outputDir``
    (reference fmrisim_real_time_generator.py:349-533).

    Writes mask.npy, labels.npy, and one rt_<TR>.npy (or .dcm) per TR.
    """
    data_dict = default_settings.copy()
    data_dict.update(user_settings)
    Path(outputDir).mkdir(parents=True, exist_ok=True)

    roi_a, roi_b, template, noise_dict, dims = _default_inputs(data_dict)
    mask, template = sim.mask_brain(volume=template, mask_self=True)
    np.save(os.path.join(outputDir, 'mask.npy'), mask.astype(np.uint8))
    # the analysis side needs the ROI geometry (the reference ships its
    # ROI volumes as package data next to the generated stream)
    np.save(os.path.join(outputDir, 'roi_a.npy'),
            (roi_a > 0).astype(np.uint8))
    np.save(os.path.join(outputDir, 'roi_b.npy'),
            (roi_b > 0).astype(np.uint8))

    noise_dict['matched'] = 0
    num_trs = data_dict['numTRs']
    tr_dur = data_dict['trDuration']
    logger.info('Generating noise')
    noise = sim.generate_noise(
        dimensions=dims,
        stimfunction_tr=np.zeros((num_trs, 1)),
        tr_duration=int(tr_dur),
        template=template,
        mask=mask,
        noise_dict=noise_dict)

    total_time = int(num_trs * tr_dur)
    onsets_a, onsets_b = [], []
    curr_time = data_dict['burn_in']
    while curr_time < total_time - data_dict['event_duration']:
        (onsets_a if np.random.randint(0, 2) == 1
         else onsets_b).append(curr_time)
        curr_time += data_dict['event_duration'] + data_dict['isi']

    temporal_res = 1 / tr_dur
    stimfunc_a = sim.generate_stimfunction(
        onsets=onsets_a, event_durations=[data_dict['event_duration']],
        total_time=total_time, temporal_resolution=temporal_res)
    stimfunc_b = sim.generate_stimfunction(
        onsets=onsets_b, event_durations=[data_dict['event_duration']],
        total_time=total_time, temporal_resolution=temporal_res)
    np.save(os.path.join(outputDir, 'labels.npy'),
            stimfunc_a + stimfunc_b * 2)

    def roi_signal(roi_vol, stimfunc, scale):
        """Evoked signal within an ROI scaled as percent signal change."""
        sf = sim.convolve_hrf(stimfunc, tr_dur,
                              temporal_resolution=temporal_res)
        n_vox = int((roi_vol > 0).sum())
        if data_dict['multivariate_pattern']:
            pattern = np.random.rand(1, n_vox)
            sf = sf @ pattern
        sig_func = np.tile(sf, (1, n_vox)) if sf.shape[1] == 1 else sf
        noise_fn = noise[roi_vol > 0].T
        sig_func = sim.compute_signal_change(
            sig_func, noise_fn, noise_dict, [scale], 'PSC')
        return sim.apply_signal(sig_func, roi_vol)

    scale = data_dict['scale_percentage']
    signal_a = roi_signal(roi_a, stimfunc_a, scale)
    if data_dict['different_ROIs']:
        signal_b = roi_signal(roi_b, stimfunc_b, scale)
    elif data_dict['multivariate_pattern']:
        signal_b = roi_signal(roi_a, stimfunc_b, scale)
    else:
        signal_b = roi_signal(roi_a, stimfunc_b, scale * 0.5)

    brain = noise + signal_a + signal_b
    for tr in range(num_trs):
        start = time.time()
        vol = brain[:, :, :, tr]
        ext = 'dcm' if data_dict['save_dicom'] else 'npy'
        out_file = os.path.join(outputDir, 'rt_{0:0>3}.{1}'.format(tr, ext))
        _save_volume(vol, out_file, data_dict['save_dicom'])
        if data_dict['save_realtime']:
            elapsed = time.time() - start
            time.sleep(max(0.0, tr_dur - elapsed))
    logger.info('Generated %d volumes in %s', num_trs, outputDir)


def main():
    p = argparse.ArgumentParser(description="Generate simulated realtime "
                                            "fMRI data")
    p.add_argument('--output-dir', '-o', required=True, type=str)
    p.add_argument('--ROI-A-file', default=None, type=str)
    p.add_argument('--ROI-B-file', default=None, type=str)
    p.add_argument('--template-path', default=None, type=str)
    p.add_argument('--noise-dict-file', default=None, type=str)
    p.add_argument('--numTRs', '-n', default=200, type=int)
    p.add_argument('--event-duration', '-d', default=10, type=int)
    p.add_argument('--trDuration', default=2, type=int)
    p.add_argument('--isi', default=6, type=int)
    p.add_argument('--burn-in', default=6, type=int)
    p.add_argument('--scale-percentage', '-s', default=0.5, type=float)
    p.add_argument('--multivariate-pattern', '-m', action='store_true')
    p.add_argument('--different-ROIs', '-r', action='store_true')
    p.add_argument('--save-dicom', action='store_true')
    p.add_argument('--save-realtime', action='store_true')
    args = p.parse_args()
    settings = {
        'ROI_A_file': args.ROI_A_file,
        'ROI_B_file': args.ROI_B_file,
        'template_path': args.template_path,
        'noise_dict_file': args.noise_dict_file,
        'numTRs': args.numTRs,
        'event_duration': args.event_duration,
        'trDuration': args.trDuration,
        'isi': args.isi,
        'burn_in': args.burn_in,
        'scale_percentage': args.scale_percentage,
        'multivariate_pattern': args.multivariate_pattern,
        'different_ROIs': args.different_ROIs,
        'save_dicom': args.save_dicom,
        'save_realtime': args.save_realtime,
    }
    generate_data(args.output_dir, settings)


if __name__ == "__main__":
    main()
