"""On-disk per-subject array store for out-of-core fits.

A :class:`SubjectStore` is a directory of one array file per subject
plus a ``manifest.json`` describing them: shapes, dtype, format, and
a per-subject content digest.  Estimators stream subjects from it
shard by shard (:mod:`brainiak_tpu.data.prefetch`) instead of
stacking a ``[subjects, V, T]`` tensor on the host, and the
manifest's digests give the resilient-loop checkpoint fingerprint
without touching the data — a resumed fit refuses a store whose
contents changed (:meth:`SubjectStore.fingerprint`).

Layout::

    store_dir/
      manifest.json      # version, format, dtype, samples,
                         # voxel_counts, files, digests
      subject_00000.npy  # [voxels_i, samples], one per subject

Formats: ``npy`` (default — readable with ``mmap_mode="r"`` for
voxel-chunked access), ``npz`` (single ``data`` member), and
``nifti`` (the in-repo :mod:`brainiak_tpu.nifti` codec; a [V, T]
array is stored as a (V, 1, 1, T) volume, so external neuroimaging
tools can open it).  All reads go through
:func:`brainiak_tpu.resilience.retry.retry` with the shared fault-
injection hook, matching the ``nifti.load``/``io`` loaders.
"""

import json
import os

import numpy as np

from ..resilience import faults
from ..resilience.guards import array_digest
from ..resilience.retry import retry

__all__ = ["STORE_FORMATS", "SubjectRef", "SubjectStore", "open_store",
           "write_store"]

MANIFEST_NAME = "manifest.json"
STORE_VERSION = 1
STORE_FORMATS = ("npy", "npz", "nifti")


def _subject_filename(i, fmt):
    ext = {"npy": "npy", "npz": "npz", "nifti": "nii.gz"}[fmt]
    return f"subject_{i:05d}.{ext}"


@retry(retries=3, backoff=0.25, retriable=(OSError,), name="data.read")
def _read_array(path, fmt):
    """One subject array from disk (full read).  Shared-filesystem
    reads are the transient-failure hot spot of long streaming fits;
    retry with backoff, and let tests inject the failure
    deterministically (the same contract as ``nifti.load``)."""
    faults.io_point(path, site="data.read")
    if fmt == "npy":
        return np.load(path, allow_pickle=False)
    if fmt == "npz":
        with np.load(path, allow_pickle=False) as zf:
            return zf["data"]
    from .. import nifti

    img = nifti.load(path)
    v, t = img.shape[0], img.shape[-1]
    return np.asarray(img.dataobj).reshape(v, t, order="F")


@retry(retries=3, backoff=0.25, retriable=(OSError,), name="data.open")
def _open_npy_memmap(path):
    faults.io_point(path, site="data.open")
    return np.load(path, mmap_mode="r", allow_pickle=False)


class SubjectRef:
    """A lazy handle on one subject of a :class:`SubjectStore`.

    Duck-types the subset of an array the ingestion helpers need
    (``.shape``) while deferring the actual read: ``load()`` pulls
    the full ``[voxels, samples]`` array, ``iter_voxel_chunks()``
    yields ``(start, block)`` row slabs without materializing the
    whole subject (memmap-backed for ``npy`` stores), which is how
    FastSRM's atlas reduction streams.
    """

    def __init__(self, store, index):
        self.store = store
        self.index = int(index)

    @property
    def shape(self):
        return (int(self.store.voxel_counts[self.index]),
                int(self.store.samples))

    def load(self):
        return self.store.read(self.index)

    def iter_voxel_chunks(self, chunk_voxels=2048):
        """Yield ``(start_row, block)`` host slabs of at most
        ``chunk_voxels`` rows.  ``npy`` stores serve slabs straight
        off a memmap (only the touched rows are read); other formats
        fall back to one full read sliced in place."""
        data = self.store.open(self.index)
        n = data.shape[0]
        for start in range(0, n, int(chunk_voxels)):
            stop = start + int(chunk_voxels)
            # memmap slab -> host copy; no device is involved here
            block = np.asarray(data[start:stop])  # jaxlint: disable=JX002
            yield start, block

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"SubjectRef({self.store.root!r}, {self.index})"


class SubjectStore:
    """Read-side view of an on-disk subject store (see module doc).

    Construct through :func:`open_store` / :func:`write_store`.
    Metadata (subject count, per-subject voxel counts, samples,
    digests) comes from the manifest — no data file is touched until
    :meth:`read`/:meth:`open`.
    """

    def __init__(self, root, manifest):
        self.root = str(root)
        if int(manifest.get("version", -1)) != STORE_VERSION:
            raise ValueError(
                f"unsupported subject-store version "
                f"{manifest.get('version')!r} in {self.root} "
                f"(this build reads version {STORE_VERSION})")
        fmt = manifest["format"]
        if fmt not in STORE_FORMATS:
            raise ValueError(
                f"unknown subject-store format {fmt!r}; expected one "
                f"of {STORE_FORMATS}")
        self.format = fmt
        self.dtype = np.dtype(manifest["dtype"])
        self.samples = int(manifest["samples"])
        self.voxel_counts = np.asarray(manifest["voxel_counts"],
                                       dtype=np.int64)
        self.files = list(manifest["files"])
        self.digests = [float(d) for d in manifest["digests"]]
        if not (len(self.files) == len(self.digests)
                == len(self.voxel_counts)):
            raise ValueError(
                f"corrupt manifest in {self.root}: files/digests/"
                "voxel_counts lengths differ")

    # -- metadata ---------------------------------------------------------
    @property
    def n_subjects(self):
        return len(self.files)

    def __len__(self):
        return self.n_subjects

    @property
    def v_max(self):
        return int(self.voxel_counts.max())

    @property
    def total_nbytes(self):
        """Bytes of subject data on disk-equivalent terms (sum of
        ragged arrays at the stored dtype)."""
        return int(self.voxel_counts.sum()) * self.samples \
            * self.dtype.itemsize

    @property
    def stack_nbytes(self):
        """Bytes the in-memory path would allocate for the padded
        ``[subjects, v_max, samples]`` stack at the stored dtype —
        what streaming avoids."""
        return self.n_subjects * self.v_max * self.samples \
            * self.dtype.itemsize

    def path(self, i):
        return os.path.join(self.root, self.files[i])

    def ref(self, i):
        return SubjectRef(self, i)

    def refs(self):
        return [SubjectRef(self, i) for i in range(self.n_subjects)]

    # -- reads ------------------------------------------------------------
    def read(self, i, verify=False):
        """Subject ``i`` as a ``[voxels_i, samples]`` array (full
        read, retry-wrapped).  ``verify=True`` additionally recomputes
        the content digest and refuses a file that no longer matches
        the manifest (stale manifest after an out-of-band rewrite)."""
        arr = np.asarray(_read_array(self.path(i), self.format),
                         dtype=self.dtype)
        if arr.shape != (int(self.voxel_counts[i]), self.samples):
            raise ValueError(
                f"{self.path(i)}: shape {arr.shape} does not match "
                f"manifest ({int(self.voxel_counts[i])}, "
                f"{self.samples})")
        if verify and not np.isclose(array_digest(arr),
                                     self.digests[i],
                                     rtol=1e-10, atol=0.0):
            raise ValueError(
                f"{self.path(i)}: content digest does not match the "
                "manifest; the store was modified after it was "
                "written (regenerate it with write_store)")
        return arr

    def open(self, i):
        """Lazy array handle for subject ``i``: a read-only memmap for
        ``npy`` stores (voxel-chunked access reads only the touched
        rows), a full read otherwise."""
        if self.format == "npy":
            return _open_npy_memmap(self.path(i))
        return self.read(i)

    # -- identity ---------------------------------------------------------
    def digest(self, i):
        """Manifest content digest of subject ``i`` (computed at
        write time by :func:`write_store`)."""
        return self.digests[i]

    def fingerprint(self):
        """1-D float digest of the whole store — per-subject content
        digests folded with the shape metadata.  The streamed fits
        put this in their resilient-loop checkpoint fingerprint, so a
        resume against a store whose contents changed raises instead
        of silently mixing runs — without ever stacking the data the
        way ``array_digest(stacked)`` required."""
        ramp = np.cos(np.arange(len(self.digests), dtype=float))
        dig = np.asarray(self.digests, dtype=float)
        return np.array([
            float(dig @ ramp) + float(dig @ dig),
            float(self.n_subjects), float(self.samples),
            float(self.v_max), float(self.voxel_counts.sum()),
        ])


def write_store(path, subjects, fmt="npy", dtype=None):
    """Write an in-memory list of ``[voxels_i, samples]`` arrays as a
    subject store and return the opened :class:`SubjectStore` — the
    migration path for existing ``fit(X)`` call sites (``fit(
    write_store(d, X))`` is the whole change).

    Arrays are cast to ``dtype`` (default: a common float dtype —
    float64 only if every input already is) before the digest is
    computed, so :meth:`SubjectStore.read` returns bit-identical
    content to what was digested.
    """
    if fmt not in STORE_FORMATS:
        raise ValueError(
            f"format must be one of {STORE_FORMATS}; got {fmt!r}")
    subjects = [np.asarray(s) for s in subjects]
    if not subjects:
        raise ValueError("cannot write an empty subject store")
    samples = subjects[0].shape[1] if subjects[0].ndim == 2 else None
    for i, s in enumerate(subjects):
        if s.ndim != 2:
            raise ValueError(
                f"subjects[{i}] must be 2-D [voxels, samples]; got "
                f"shape {s.shape}")
        if s.shape[1] != samples:
            raise ValueError(
                f"subjects[{i}] has {s.shape[1]} samples; subject 0 "
                f"has {samples} (sessions must be concatenated "
                "before storing)")
    if dtype is None:
        dtype = np.float64 if all(s.dtype == np.float64
                                  for s in subjects) else np.float32
    dtype = np.dtype(dtype)

    os.makedirs(path, exist_ok=True)
    files, digests, counts = [], [], []
    for i, s in enumerate(subjects):
        arr = np.ascontiguousarray(s, dtype=dtype)
        name = _subject_filename(i, fmt)
        target = os.path.join(path, name)
        if fmt == "npy":
            np.save(target, arr)
        elif fmt == "npz":
            np.savez(target, data=arr)
        else:
            from .. import nifti

            vol = arr.reshape(arr.shape[0], 1, 1, arr.shape[1],
                              order="F")
            nifti.save(nifti.NiftiImage(vol), target)
        files.append(name)
        digests.append(float(array_digest(arr)))
        counts.append(int(arr.shape[0]))

    manifest = {
        "version": STORE_VERSION,
        "format": fmt,
        "dtype": dtype.name,
        "samples": int(samples),
        "voxel_counts": counts,
        "files": files,
        "digests": digests,
    }
    tmp = os.path.join(path, MANIFEST_NAME + ".tmp")
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(manifest, fh, indent=1)
    # atomic publish: a crashed writer must not leave a store whose
    # manifest half-describes the files next to it
    os.replace(tmp, os.path.join(path, MANIFEST_NAME))
    return SubjectStore(path, manifest)


def open_store(path):
    """Open an existing store directory written by
    :func:`write_store`."""
    manifest_path = os.path.join(str(path), MANIFEST_NAME)
    try:
        with open(manifest_path, encoding="utf-8") as fh:
            manifest = json.load(fh)
    except FileNotFoundError:
        raise FileNotFoundError(
            f"{path} is not a subject store (no {MANIFEST_NAME}); "
            "create one with brainiak_tpu.data.write_store")
    return SubjectStore(path, manifest)
