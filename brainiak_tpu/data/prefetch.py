"""Double-buffered async host-to-device subject-shard pipeline.

:class:`ShardPrefetcher` walks a :class:`~brainiak_tpu.data.store.
SubjectStore` shard by shard on a background thread: while the
consumer computes on shard *s*, the loader reads shard *s+1* from
disk, stacks/pads it, and (in device mode) starts its
``jax.device_put`` onto the mesh's ``'subject'`` axis — the layout
:func:`brainiak_tpu.ops.distla.shard_vmap` expects — so the H2D copy
overlaps compute instead of serializing with it.  The buffer is a
bounded queue (``depth``, default 2 = classic double buffering):
when the consumer falls behind, the loader blocks instead of racing
ahead of the host budget.

Failure contract: an exception in the loader thread (a bad subject
file, an injected ``io_error`` past its retry budget) is captured
and re-raised — the original exception — from the consumer's next
``__next__``; the fit fails loudly, never hangs.

Telemetry (no-ops while obs is disabled, and the pipeline performs
**zero** device syncs in that state): per-shard
``data_prefetch_seconds`` histograms and ``data.prefetch_shard``
spans from the loader thread, ``data_h2d_bytes_total`` for bytes
placed, ``data_buffer_occupancy`` for queue depth, and stall
accounting (``data_prefetch_stall_seconds_total``) on the consumer
side so the overlap ratio is measurable (the ``streaming`` bench
tier gates it).
"""

import os
import queue
import threading
import time

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import sink as obs_sink
from ..obs import spans as obs_spans
from ..parallel.mesh import DEFAULT_SUBJECT_AXIS

__all__ = ["DATA_BUDGET_ENV", "DEFAULT_HOST_BUDGET", "ShardBatch",
           "ShardPrefetcher", "host_budget_bytes", "subject_shards"]

#: Env override for the streaming host working-set budget (bytes).
DATA_BUDGET_ENV = "BRAINIAK_TPU_DATA_BUDGET_BYTES"

#: Default host budget for the streamed working set: the stacked
#: tensor a shard pass may hold live at once (shard batch plus the
#: double buffer), NOT the dataset size.  1 GiB keeps thousand-
#: subject stores streamable on modest hosts.
DEFAULT_HOST_BUDGET = 1 << 30


def host_budget_bytes():
    """The per-process byte budget for the streamed working set
    (``BRAINIAK_TPU_DATA_BUDGET_BYTES`` overrides the 1 GiB
    default).  The streamed fits size their default subject shard so
    ``depth + 1`` in-flight shard batches fit inside it."""
    env = os.environ.get(DATA_BUDGET_ENV)
    if env:
        try:
            return int(float(env))
        except ValueError:
            pass
    return DEFAULT_HOST_BUDGET


def subject_shards(n_subjects, shard_size):
    """Split ``range(n_subjects)`` into contiguous ``(lo, hi)``
    shards of at most ``shard_size`` subjects (the last may be
    short; the prefetcher zero-pads it back to ``shard_size`` lanes
    so every shard batch has ONE program shape)."""
    shard_size = int(shard_size)
    if shard_size < 1:
        raise ValueError(f"shard_size must be >= 1, got {shard_size}")
    return [(lo, min(lo + shard_size, n_subjects))
            for lo in range(0, int(n_subjects), shard_size)]


class ShardBatch:
    """One prefetched subject shard.

    Attributes
    ----------
    index : int
        Position of this shard in the pass (0-based).
    lo, hi : int
        Subject range ``[lo, hi)`` this shard covers; ``hi - lo`` may
        be smaller than the lane count (the pad lanes have
        ``mask == 0``).
    x : array or None
        Stacked ``[lanes, v_pad, samples]`` batch (device-placed in
        device mode), demeaned when requested.  ``None`` in raw mode.
    counts, mask, trace_xtx : float arrays ``[lanes]``
        Per-lane voxel counts, real-subject mask, and raw-data
        sum-of-squares (computed BEFORE demeaning, matching
        ``_stack_and_pad``; zeros in raw mode, whose consumers
        never read it).
    means : list of arrays or None
        Per-real-subject voxel means (``want_means=True`` only).
    subjects : list of arrays or None
        Raw ragged per-subject host arrays (raw mode only —
        HTFA's host-side subsampling path).
    """

    __slots__ = ("index", "lo", "hi", "x", "counts", "mask",
                 "trace_xtx", "means", "subjects")

    def __init__(self, index, lo, hi, x=None, counts=None, mask=None,
                 trace_xtx=None, means=None, subjects=None):
        self.index = index
        self.lo = lo
        self.hi = hi
        self.x = x
        self.counts = counts
        self.mask = mask
        self.trace_xtx = trace_xtx
        self.means = means
        self.subjects = subjects


class _End:
    """Queue sentinel: normal exhaustion or a captured loader error."""

    __slots__ = ()


_DONE = _End()


class ShardPrefetcher:
    """Iterate a store's subject shards with background loading (see
    module docstring).  Use as an iterator or context manager::

        with ShardPrefetcher(store, shards, dtype=dt) as pf:
            for batch in pf:
                ...  # compute on batch while the next one loads

    Parameters
    ----------
    store : :class:`~brainiak_tpu.data.store.SubjectStore`
    shards : list of (lo, hi) subject ranges (:func:`subject_shards`)
    dtype : numpy dtype the batch is cast to (the fit dtype)
    lanes : lane count every batch is padded to (default: the widest
        shard) — one program shape across the whole pass
    pad_voxels : voxel padding (default: ``store.v_max``)
    demean : subtract each subject's voxel mean (probabilistic SRM's
        convention; ``trace_xtx`` stays raw either way)
    mesh, axis_name : place each batch sharded over the mesh axis
        (``lanes`` must divide the axis size)
    to_device : place batches on device (False: host numpy batches)
    raw : yield ragged host subject lists instead of stacked batches
        (HTFA's subsampling path; implies host placement)
    want_means : collect per-subject voxel means
    depth : buffered shards (2 = double buffering)
    verify : forward to :meth:`SubjectStore.read` (digest check)
    """

    def __init__(self, store, shards, *, dtype=np.float32, lanes=None,
                 pad_voxels=None, demean=False, mesh=None,
                 axis_name=DEFAULT_SUBJECT_AXIS, to_device=True,
                 raw=False, want_means=False, depth=2, verify=False):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.store = store
        self.shards = list(shards)
        self.dtype = np.dtype(dtype)
        self.lanes = int(lanes) if lanes is not None else (
            max((hi - lo for lo, hi in self.shards), default=0))
        self.pad_voxels = int(pad_voxels) if pad_voxels is not None \
            else store.v_max
        self.demean = bool(demean)
        self.mesh = mesh
        self.axis_name = axis_name
        self.to_device = bool(to_device) and not raw
        self.raw = bool(raw)
        self.want_means = bool(want_means)
        self.verify = bool(verify)
        if mesh is not None and self.to_device:
            axis = mesh.shape.get(axis_name, 1)
            if self.lanes % axis:
                raise ValueError(
                    f"shard lane count {self.lanes} is not a "
                    f"multiple of the mesh '{axis_name}' axis "
                    f"({axis}); pad the shard size up to a multiple")
        self._queue = queue.Queue(maxsize=depth)
        self._lock = threading.Lock()
        self._error = None        # guarded-by: _lock
        self._stop = False        # guarded-by: _lock
        self._stall_s = 0.0       # guarded-by: _lock
        self._bytes_placed = 0    # guarded-by: _lock
        self._consumed = 0        # consumer thread only
        self._thread = threading.Thread(
            target=self._run, name="data-prefetch", daemon=True)
        self._thread.start()

    # -- loader thread ----------------------------------------------------
    def _should_stop(self):  # requires-lock: _lock
        return self._stop

    def _put(self, item):
        """Bounded put that aborts promptly when the consumer closed
        (close() drains the queue, so the timeout loop re-checks the
        stop flag instead of blocking forever on a full buffer)."""
        while True:
            with self._lock:
                if self._should_stop():
                    return False
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue

    def _run(self):
        occupancy = obs_metrics.gauge(
            "data_buffer_occupancy",
            help="prefetched subject shards currently buffered")
        try:
            for index, (lo, hi) in enumerate(self.shards):
                with self._lock:
                    if self._should_stop():
                        return
                t0 = time.perf_counter()
                with obs_spans.span(
                        "data.prefetch_shard",
                        attrs={"shard": index, "lo": lo, "hi": hi}):
                    batch = self._load(index, lo, hi)
                    if self.to_device and batch.x is not None:
                        batch.x = self._place(batch.x)
                        nbytes = batch.x.size \
                            * self.dtype.itemsize
                        with self._lock:
                            self._bytes_placed += nbytes
                        obs_metrics.counter(
                            "data_h2d_bytes_total", unit="bytes",
                            help="subject-shard bytes placed on "
                                 "device by the prefetcher").inc(
                                nbytes)
                        if obs_sink.enabled():
                            # charge the H2D copy to THIS span (the
                            # whole point of prefetching is that this
                            # wait runs on the loader thread, not the
                            # consumer); obs disabled → no sync, the
                            # copy completes asynchronously under the
                            # consumer's first use
                            import jax

                            jax.block_until_ready(batch.x)
                obs_metrics.histogram(
                    "data_prefetch_seconds", unit="s",
                    help="disk read + stack + device placement per "
                         "prefetched shard").observe(
                        time.perf_counter() - t0)
                if not self._put(batch):
                    return
                occupancy.set(self._queue.qsize())
        except BaseException as exc:  # noqa: BLE001 - re-raised below
            with self._lock:
                self._error = exc
        finally:
            self._put(_DONE)

    def _load(self, index, lo, hi):
        reads = [self.store.read(i, verify=self.verify)
                 for i in range(lo, hi)]
        counts = np.zeros(self.lanes, dtype=self.dtype)
        mask = np.zeros(self.lanes, dtype=self.dtype)
        trace = np.zeros(self.lanes, dtype=self.dtype)
        means = [] if self.want_means else None
        subjects = [] if self.raw else None
        x = None if self.raw else np.zeros(
            (self.lanes, self.pad_voxels, self.store.samples),
            dtype=self.dtype)
        for lane, arr in enumerate(reads):
            d = np.asarray(arr, dtype=self.dtype)
            counts[lane] = d.shape[0]
            mask[lane] = 1.0
            if self.raw:
                # raw consumers (HTFA subsampling, IncrementalSRM)
                # never read trace_xtx — skip the O(V*T) reduction
                subjects.append(d)
                continue
            # raw-data sum of squares, matching _stack_and_pad: the
            # reference's trace is of the data BEFORE demeaning
            trace[lane] = np.sum(d ** 2)
            if self.want_means or self.demean:
                m = d.mean(axis=1)
                if self.want_means:
                    means.append(m)
                if self.demean:
                    d = d - m[:, None]
            x[lane, :d.shape[0]] = d
        return ShardBatch(index, lo, hi, x=x, counts=counts,
                          mask=mask, trace_xtx=trace, means=means,
                          subjects=subjects)

    def _place(self, x):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from ..parallel.mesh import place_on_mesh

        if self.mesh is not None \
                and self.axis_name in self.mesh.shape:
            spec = PartitionSpec(self.axis_name, None, None)
            return place_on_mesh(
                x, NamedSharding(self.mesh, spec))
        return jax.device_put(x)

    # -- consumer side ----------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        item = self._queue.get()
        stall = time.perf_counter() - t0
        with self._lock:
            self._stall_s += stall
            err = self._error
        obs_metrics.counter(
            "data_prefetch_stall_seconds_total", unit="s",
            help="consumer time spent waiting on the prefetch "
                 "buffer").inc(stall)
        obs_metrics.gauge(
            "data_buffer_occupancy",
            help="prefetched subject shards currently buffered").set(
                self._queue.qsize())
        if isinstance(item, _End):
            self._thread.join(timeout=10.0)
            if err is not None:
                raise err
            raise StopIteration
        self._consumed += 1
        return item

    def close(self):
        """Stop the loader and release the buffer (safe to call
        multiple times; also runs on context exit).  A consumer that
        abandons a pass mid-way (an exception in its compute) must
        not leave the loader blocked on a full queue."""
        with self._lock:
            self._stop = True
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=10.0)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    # -- stats ------------------------------------------------------------
    @property
    def stall_seconds(self):
        """Consumer seconds spent blocked on the buffer this pass
        (≈0 when prefetch fully overlaps compute)."""
        with self._lock:
            return self._stall_s

    @property
    def bytes_placed(self):
        """Bytes of shard batches placed on device this pass."""
        with self._lock:
            return self._bytes_placed
