"""brainiak_tpu.data: the out-of-core streaming data plane.

Every funcalign/factoranalysis fit used to materialize the full
``[subjects, T, V]`` tensor on the host before anything ran, so "fit
the whole dataset" was an OOM at thousand-subject scale — the exact
setting of "Enabling Factor Analysis on Thousand-Subject Neuroimaging
Datasets" (arXiv:1608.04647).  This package is the missing data
plane (ROADMAP open item 1), following DrJAX's map-over-a-placed-axis
discipline (arXiv:2403.07128):

- :mod:`~brainiak_tpu.data.store` — :class:`SubjectStore`, a
  manifest-described directory of per-subject arrays on disk
  (``.npy`` memmap, ``.npz``, or NIfTI through the in-repo codec),
  with per-subject content digests so resilient-loop fingerprints no
  longer need the stacked tensor; :func:`write_store` converts
  in-memory subject lists so existing call sites migrate trivially.
- :mod:`~brainiak_tpu.data.prefetch` — :class:`ShardPrefetcher`, a
  double-buffered background-thread loader that overlaps the disk
  read + host-to-device copy of subject shard *s+1* with compute on
  shard *s*, placing each batch directly onto the mesh's
  ``'subject'`` axis (the layout ``ops.distla.shard_vmap`` expects);
  instrumented with ``data_prefetch_seconds`` /
  ``data_h2d_bytes_total`` / ``data_buffer_occupancy``.
- :mod:`~brainiak_tpu.data.streaming_fit` — SRM/DetSRM outer loops
  restructured as map-reduce over subject shards (per-shard E-step
  feeding streaming sufficient-statistic reductions; peak memory
  O(shard · V·T + K²), never the full stack), plus
  :class:`IncrementalSRM`, the minibatch variant whose memory is
  O(K) in subjects.  ``SRM.fit``/``DetSRM.fit``/``HTFA.fit`` route
  here automatically when handed a :class:`SubjectStore`.

See docs/streaming_data.md for the store layout, the pipeline
diagram, and the memory-model table.
"""

from .prefetch import (  # noqa: F401
    DATA_BUDGET_ENV,
    DEFAULT_HOST_BUDGET,
    ShardBatch,
    ShardPrefetcher,
    host_budget_bytes,
    subject_shards,
)
from .store import (  # noqa: F401
    STORE_FORMATS,
    SubjectRef,
    SubjectStore,
    open_store,
    write_store,
)
from .streaming_fit import (  # noqa: F401
    IncrementalSRM,
    stream_fit_detsrm,
    stream_fit_srm,
)

__all__ = [
    "DATA_BUDGET_ENV",
    "DEFAULT_HOST_BUDGET",
    "STORE_FORMATS",
    "IncrementalSRM",
    "ShardBatch",
    "ShardPrefetcher",
    "SubjectRef",
    "SubjectStore",
    "host_budget_bytes",
    "open_store",
    "stream_fit_detsrm",
    "stream_fit_srm",
    "subject_shards",
    "write_store",
]
