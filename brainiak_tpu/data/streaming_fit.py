"""Streamed SRM/DetSRM fits: map-reduce over subject shards.

The stacked fits (:mod:`brainiak_tpu.funcalign.srm`) hold the full
``[subjects, V, T]`` tensor resident.  Mathematically, though, each
EM/BCD iteration touches the data only through per-subject terms and
two kinds of small reductions:

- probabilistic SRM: the shared-response statistic
  ``Σ_i W_iᵀ X_i / ρ_i²`` ([K, T]) plus per-subject scalars
  (``ρ_i²``, ``tr X_iᵀX_i``);
- deterministic SRM: ``Σ_i W_iᵀ X_i`` ([K, T]).

So the outer loops restructure as a **map over subject shards**
(per-shard Procrustes W updates — :func:`~brainiak_tpu.funcalign.
srm._procrustes_batch`, sharded over the mesh subject axis) feeding
**streaming sufficient-statistic reductions**, with one key
observation: the W update of iteration *t+1* needs only the shared
response of iteration *t*, so W is never persisted — it is
recomputed inside each pass while that shard's data is resident.
Peak memory is O(shard · V·T + K·T + K² + S), never
O(subjects · V·T).  One fit costs ``n_iter + 2`` passes over the
store (an init pass for ``W₀ᵀX`` accumulation, one pass per
iteration, and an output pass that materializes the per-subject maps
of the final iteration).

Checkpoint/resume rides :func:`~brainiak_tpu.resilience.guards.
run_resilient_loop` with the [K,T]-sized statistics as the state —
a preempted fit resumes at the last completed shard round (= one
full pass over the shards), and the checkpoint fingerprint comes
from the store's per-subject digests
(:meth:`~brainiak_tpu.data.store.SubjectStore.fingerprint`), so it
never needs the stacked tensor either.

:class:`IncrementalSRM` is the minibatch variant whose state is
O(K·T) regardless of subject count: it keeps only the running
shared response (online averaging over minibatch block updates) and
computes per-subject bases on demand.
"""

import logging
from functools import partial

import numpy as np

from ..obs import runtime as obs_runtime
from ..obs import spans as obs_spans
from ..parallel.mesh import DEFAULT_SUBJECT_AXIS
from ..resilience.guards import run_resilient_loop
from .prefetch import ShardPrefetcher, host_budget_bytes, subject_shards

logger = logging.getLogger(__name__)

__all__ = ["IncrementalSRM", "stream_fit_detsrm", "stream_fit_srm"]


# -- jitted per-shard / global programs ------------------------------
#
# Builders are counted_cache'd under srm.stream_* sites: across
# repeat shard rounds (and repeat fits in one process) every site
# must stay at <= 1 retrace — the DAT001 gate's contract.  All shard
# batches in a pass share ONE shape (the prefetcher pads the last
# shard), so the jit caches inside never grow either.

@obs_runtime.counted_cache("srm.stream_init")
def _init_program(mesh):
    """``Σ_lane W₀ᵀ X`` for one shard from per-subject PRNG keys —
    shared by the probabilistic init (ρ²=1) and the deterministic
    init (divide by S on the host)."""
    import jax
    import jax.numpy as jnp

    from ..funcalign.srm import _init_w_from_keys

    @partial(jax.jit, static_argnames=("features",))
    def init_fn(keys, counts, x, mask, *, features):
        w0 = _init_w_from_keys(keys, x.shape[1], features, counts,
                               dtype=x.dtype)
        w0 = w0 * mask[:, None, None]
        return jnp.einsum('svk,svt->kt', w0, x)

    return init_fn


def _stream_mesh():
    """Canonical subject-axis trace mesh for the srm.stream_* sites."""
    from ..parallel.mesh import DEFAULT_SUBJECT_AXIS, make_mesh
    return make_mesh((DEFAULT_SUBJECT_AXIS,), (-1,))


def _stream_extents(mesh):
    """(S, V, T, K) canonical extents: S fills the subject axis so
    sharded Procrustes batches divide it."""
    from ..parallel.mesh import DEFAULT_SUBJECT_AXIS
    return mesh.shape[DEFAULT_SUBJECT_AXIS], 4, 6, 2


def _aval(*shape, dtype=None):
    import jax
    import jax.numpy as jnp
    return jax.ShapeDtypeStruct(shape, dtype or jnp.float32)


@obs_runtime.trace_signature("srm.stream_init")
def _init_trace_signature():
    import jax.numpy as jnp

    mesh = _stream_mesh()
    s, v, t, k = _stream_extents(mesh)
    return [{"key": (mesh,),
             "args": (_aval(s, 2, dtype=jnp.uint32), _aval(s),
                      _aval(s, v, t), _aval(s)),
             "kwargs": {"features": k}, "mesh": mesh}]


@obs_runtime.counted_cache("srm.stream_prob_shard")
def _prob_shard_program(mesh):
    """One probabilistic-EM shard step: per-lane Procrustes W update
    (mesh-sharded over the subject axis when available), ρ² update,
    and this shard's contribution to ``Σ W'ᵀX/ρ'²`` — the map side
    of the round's map-reduce."""
    import jax
    import jax.numpy as jnp

    from ..funcalign.srm import _procrustes_batch

    @jax.jit
    def shard_fn(x, trace_xtx, counts, mask, shared, trace_sigma_s,
                 samples):
        a = jnp.einsum('svt,kt->svk', x, shared)
        w = _procrustes_batch(a, mesh)
        # pad lanes: counts=0 would divide by zero and their W is
        # meaningless — mask them to inert values (ρ²=1, W=0) so
        # the reductions below stay exact
        safe_counts = jnp.where(mask > 0, counts, 1.0)
        rho2 = (trace_xtx - 2.0 * jnp.sum(w * a, axis=(1, 2))
                + trace_sigma_s) / (samples * safe_counts)
        rho2 = jnp.where(mask > 0, rho2, 1.0)
        wm = w * mask[:, None, None]
        wt_part = jnp.einsum('svk,svt->kt',
                             wm / rho2[:, None, None], x)
        return w, rho2, wt_part

    return shard_fn


@obs_runtime.trace_signature("srm.stream_prob_shard")
def _prob_shard_trace_signature():
    mesh = _stream_mesh()
    s, v, t, k = _stream_extents(mesh)
    return [{"key": (mesh,),
             "args": (_aval(s, v, t), _aval(s), _aval(s), _aval(s),
                      _aval(k, t), _aval(), _aval()),
             "mesh": mesh}]


@obs_runtime.counted_cache("srm.stream_global")
def _prob_global_program(mesh):
    """The replicated top half of ``_em_iteration``: shared response
    and Σ_s update from the reduced statistic — O(K²), the reduce
    side of the round."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def global_fn(wt_invpsi_x, rho2, sigma_s, samples):
        features = sigma_s.shape[0]
        eye = jnp.eye(features, dtype=sigma_s.dtype)
        rho0 = jnp.sum(1.0 / rho2)
        chol = jax.scipy.linalg.cho_factor(sigma_s)
        inv_sigma_s = jax.scipy.linalg.cho_solve(chol, eye)
        sigma_s_rhos = inv_sigma_s + eye * rho0
        chol_rhos = jax.scipy.linalg.cho_factor(sigma_s_rhos)
        inv_sigma_s_rhos = jax.scipy.linalg.cho_solve(chol_rhos, eye)
        shared = sigma_s @ (eye - rho0 * inv_sigma_s_rhos) \
            @ wt_invpsi_x
        sigma_s_new = inv_sigma_s_rhos + shared @ shared.T / samples
        trace_sigma_s = samples * jnp.trace(sigma_s_new)
        return shared, sigma_s_new, trace_sigma_s

    return global_fn


@obs_runtime.trace_signature("srm.stream_global")
def _prob_global_trace_signature():
    mesh = _stream_mesh()
    s, v, t, k = _stream_extents(mesh)
    return [{"key": (mesh,),
             "args": (_aval(k, t), _aval(s), _aval(k, k), _aval()),
             "mesh": mesh}]


@obs_runtime.counted_cache("srm.stream_ll")
def _ll_program(mesh):
    """Marginal log-likelihood at the final EM state from the
    streamed statistics (the streamed analog of
    ``_final_log_likelihood``: the ``Σ WᵀX/ρ²`` it needs is exactly
    the accumulator left by the final round)."""
    import jax
    import jax.numpy as jnp

    from ..funcalign.srm import _srm_log_likelihood

    @jax.jit
    def ll_fn(sigma_s, rho2, counts, trace_xtx, wt_invpsi_x, samples):
        features = sigma_s.shape[0]
        eye = jnp.eye(features, dtype=sigma_s.dtype)
        rho0 = jnp.sum(1.0 / rho2)
        chol = jax.scipy.linalg.cho_factor(sigma_s)
        sigma_s_rhos = jax.scipy.linalg.cho_solve(chol, eye) \
            + eye * rho0
        inv_sigma_s_rhos = jax.scipy.linalg.cho_solve(
            jax.scipy.linalg.cho_factor(sigma_s_rhos), eye)
        trace_xt_invsigma2_x = jnp.sum(trace_xtx / rho2)
        return _srm_log_likelihood(
            sigma_s, rho2, counts, wt_invpsi_x, inv_sigma_s_rhos,
            trace_xt_invsigma2_x, samples)

    return ll_fn


@obs_runtime.trace_signature("srm.stream_ll")
def _ll_trace_signature():
    mesh = _stream_mesh()
    s, v, t, k = _stream_extents(mesh)
    return [{"key": (mesh,),
             "args": (_aval(k, k), _aval(s), _aval(s), _aval(s),
                      _aval(k, t), _aval()),
             "mesh": mesh}]


@obs_runtime.counted_cache("srm.stream_det_shard")
def _det_shard_program(mesh):
    """One deterministic-BCD shard step: Procrustes W update and this
    shard's ``Σ WᵀX`` contribution."""
    import jax
    import jax.numpy as jnp

    from ..funcalign.srm import _procrustes_batch

    @jax.jit
    def shard_fn(x, mask, shared):
        a = jnp.einsum('svt,kt->svk', x, shared)
        w = _procrustes_batch(a, mesh)
        wm = w * mask[:, None, None]
        return w, jnp.einsum('svk,svt->kt', wm, x)

    return shard_fn


@obs_runtime.trace_signature("srm.stream_det_shard")
def _det_shard_trace_signature():
    mesh = _stream_mesh()
    s, v, t, k = _stream_extents(mesh)
    return [{"key": (mesh,),
             "args": (_aval(s, v, t), _aval(s), _aval(k, t)),
             "mesh": mesh}]


# -- shard-size policy ------------------------------------------------

def _resolve_lanes(store, shard_subjects, mesh, dtype, depth):
    """Subjects per shard batch: the caller's choice, else the
    largest shard whose ``depth + 1`` in-flight padded batches fit
    the host budget (:func:`~brainiak_tpu.data.prefetch.
    host_budget_bytes`) — the knob that makes a store bigger than
    host memory stream instead of OOM.  Rounded up to the mesh
    subject-axis size so placed batches divide it."""
    per_subject = store.v_max * store.samples * np.dtype(dtype).itemsize
    if shard_subjects is None:
        budget = host_budget_bytes()
        lanes = max(1, int(budget // (max(per_subject, 1)
                                      * (depth + 1))))
        lanes = min(lanes, store.n_subjects)
    else:
        lanes = int(shard_subjects)
        if lanes < 1:
            raise ValueError(
                f"shard_subjects must be >= 1, got {lanes}")
    if mesh is not None and DEFAULT_SUBJECT_AXIS in mesh.shape:
        axis = mesh.shape[DEFAULT_SUBJECT_AXIS]
        lanes = -(-lanes // axis) * axis
    return lanes


def _pad_lanes(arr, lanes):
    """Pad a leading-axis host array up to ``lanes`` rows by
    repeating row 0 (used for PRNG keys of pad lanes, whose outputs
    are masked out)."""
    arr = np.asarray(arr)
    if arr.shape[0] == lanes:
        return arr
    reps = np.repeat(arr[:1], lanes - arr.shape[0], axis=0)
    return np.concatenate([arr, reps], axis=0)


def _validate_store(store, features):
    if store.n_subjects <= 1:
        raise ValueError(
            "There are not enough subjects ({0:d}) to train the "
            "model.".format(store.n_subjects))
    if store.samples < features:
        raise ValueError(
            "There are not enough samples to train the model with "
            "{0:d} features.".format(features))


# -- probabilistic SRM ------------------------------------------------

def stream_fit_srm(store, *, features, n_iter, rand_seed=0, mesh=None,
                   shard_subjects=None, prefetch_depth=2,
                   checkpoint_dir=None, checkpoint_every=5,
                   name="SRM.fit_stream"):
    """Probabilistic-SRM EM over a :class:`SubjectStore`, never
    materializing the stacked tensor.

    Returns ``(w_list, shared, sigma_s, mu_list, rho2, logprob)`` —
    the attribute set ``SRM.fit`` publishes.  Numerics match the
    stacked fit at the same iteration schedule up to floating-point
    reduction order (the per-shard partial sums replace one big
    einsum); the per-subject W trajectories are otherwise identical
    because the init is key-exact (``_init_w_from_keys``) and each
    round consumes exactly the statistics the stacked
    ``_em_iteration`` does.
    """
    import jax
    import jax.numpy as jnp

    _validate_store(store, features)
    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    n_subjects, samples = store.n_subjects, store.samples
    v_max = store.v_max
    lanes = _resolve_lanes(store, shard_subjects, mesh, dtype,
                           prefetch_depth)
    shards = subject_shards(n_subjects, lanes)
    samples_f = float(samples)
    keys = np.asarray(jax.random.split(
        jax.random.PRNGKey(rand_seed), n_subjects))

    init_p = _init_program(mesh)
    shard_p = _prob_shard_program(mesh)
    global_p = _prob_global_program(mesh)

    def prefetcher(want_means=False):
        return ShardPrefetcher(
            store, shards, dtype=dtype, lanes=lanes,
            pad_voxels=v_max, demean=True, mesh=mesh,
            depth=prefetch_depth, want_means=want_means)

    def init_pass():
        wt = jnp.zeros((features, samples), dtype=dtype)
        with obs_spans.span("data.stream_pass",
                            attrs={"estimator": name,
                                   "stage": "init"}):
            with prefetcher() as pf:
                for batch in pf:
                    kb = jnp.asarray(_pad_lanes(keys[batch.lo:batch.hi],
                                                lanes))
                    wt = wt + init_p(kb, jnp.asarray(batch.counts),
                                     batch.x,
                                     jnp.asarray(batch.mask),
                                     features=features)
        return wt

    def round_pass(shared, trace_sigma_s, round_idx):
        """One EM round's map-reduce: returns the NEXT iteration's
        ``Σ WᵀX/ρ²`` statistic, the updated per-subject ρ², and the
        final shard's W handles (unused except by the output pass,
        which replays this with the final shared response)."""
        wt_next = jnp.zeros((features, samples), dtype=dtype)
        rho2_parts = []
        with obs_spans.span("data.stream_pass",
                            attrs={"estimator": name,
                                   "round": round_idx}):
            with prefetcher() as pf:
                for batch in pf:
                    _, rho2_s, wt_part = shard_p(
                        batch.x, jnp.asarray(batch.trace_xtx),
                        jnp.asarray(batch.counts),
                        jnp.asarray(batch.mask), shared,
                        trace_sigma_s, samples_f)
                    wt_next = wt_next + wt_part
                    rho2_parts.append((batch.lo, batch.hi, rho2_s))
        rho2 = np.empty(n_subjects, dtype=dtype)
        for lo, hi, part in rho2_parts:
            # host landing of the per-subject scalars is the point:
            # they are loop state the next round (and the checkpoint)
            # needs on host  # jaxlint: disable=JX002
            rho2[lo:hi] = np.asarray(part)[:hi - lo]
        return wt_next, rho2

    def run_chunk(state, step, n_steps):
        # host round trips below are the chunked-fit checkpoint
        # contract: the streamed statistics are [K,T]-sized loop
        # state run_resilient_loop guards/persists (the per-shard
        # [B,V,T] work stays on device inside round_pass)
        wt = jnp.asarray(np.asarray(  # jaxlint: disable=JX002
            state["wt_invpsi_x"], dtype=dtype))
        sigma_s = jnp.asarray(np.asarray(  # jaxlint: disable=JX002
            state["sigma_s"], dtype=dtype))
        rho2 = np.asarray(  # jaxlint: disable=JX002
            state["rho2"], dtype=dtype)
        shared = state["shared"]
        started = np.asarray(  # jaxlint: disable=JX002
            state["initialized"]).reshape(-1)[0]
        if not float(started):  # jaxlint: disable=JX002
            wt = init_pass()
            rho2 = np.ones(n_subjects, dtype=dtype)
        for i in range(n_steps):
            shared, sigma_s, trace_sigma_s = global_p(
                wt, jnp.asarray(rho2), sigma_s, samples_f)
            # the per-subject rho2 land on host once per ROUND (one
            # [S] vector per pass over the store) — checkpoint state,
            # not a per-dispatch sync
            wt, rho2 = round_pass(  # jaxlint: disable=JX010
                shared, trace_sigma_s, step + i)
        return {
            "wt_invpsi_x": np.asarray(wt),  # jaxlint: disable=JX002
            "sigma_s": np.asarray(sigma_s),  # jaxlint: disable=JX002
            "rho2": np.asarray(rho2),  # jaxlint: disable=JX002
            "shared": np.asarray(shared),  # jaxlint: disable=JX002
            "initialized": np.ones(1, dtype=dtype),
        }, False

    zeros = partial(np.zeros, dtype=dtype)
    init_state = {
        "wt_invpsi_x": zeros((features, samples)),
        "sigma_s": np.eye(features, dtype=dtype),
        "rho2": np.ones(n_subjects, dtype=dtype),
        "shared": zeros((features, samples)),
        "initialized": zeros(1),
    }
    fingerprint = None
    template = None
    if checkpoint_dir is not None:
        fingerprint = np.concatenate([
            store.fingerprint(),
            [float(features), float(rand_seed), float(lanes),
             float(np.dtype(dtype).itemsize)]])
        template = {k: np.zeros_like(np.asarray(v))
                    for k, v in init_state.items()}

    state, _ = run_resilient_loop(
        run_chunk, init_state, n_iter,
        checkpoint_dir=checkpoint_dir,
        checkpoint_every=checkpoint_every,
        fingerprint=fingerprint, template=template, name=name,
        progress_objective="rho2", progress_direction="min")

    # -- output pass: materialize the final-iteration W per subject
    # (recomputed from the final shared response — bit-identical to
    # the last round's update), per-subject means, and the raw
    # traces the log-likelihood needs.
    shared = jnp.asarray(np.asarray(state["shared"], dtype=dtype))
    sigma_s = np.asarray(state["sigma_s"], dtype=dtype)
    trace_sigma_s = dtype(samples_f) * np.trace(sigma_s)
    w_list = [None] * n_subjects
    mu_list = [None] * n_subjects
    trace_all = np.zeros(n_subjects, dtype=dtype)
    counts = store.voxel_counts
    with obs_spans.span("data.stream_pass",
                        attrs={"estimator": name, "stage": "output"}):
        with prefetcher(want_means=True) as pf:
            for batch in pf:
                w, _, _ = shard_p(
                    batch.x, jnp.asarray(batch.trace_xtx),
                    jnp.asarray(batch.counts),
                    jnp.asarray(batch.mask), shared,
                    jnp.asarray(trace_sigma_s), samples_f)
                wn = np.asarray(w)  # jaxlint: disable=JX002
                for j, subj in enumerate(range(batch.lo, batch.hi)):
                    w_list[subj] = wn[j, :int(counts[subj])].copy()
                    mu_list[subj] = batch.means[j]
                trace_all[batch.lo:batch.hi] = \
                    batch.trace_xtx[:batch.hi - batch.lo]

    ll = _ll_program(mesh)(
        jnp.asarray(sigma_s), jnp.asarray(state["rho2"], dtype=dtype),
        jnp.asarray(counts.astype(dtype)), jnp.asarray(trace_all),
        jnp.asarray(np.asarray(state["wt_invpsi_x"], dtype=dtype)),
        samples_f)
    return (w_list, np.asarray(state["shared"], dtype=dtype), sigma_s,
            mu_list, np.asarray(state["rho2"], dtype=dtype),
            float(ll))


# -- deterministic SRM ------------------------------------------------

def stream_fit_detsrm(store, *, features, n_iter, rand_seed=0,
                      mesh=None, shard_subjects=None, prefetch_depth=2,
                      checkpoint_dir=None, checkpoint_every=5,
                      name="DetSRM.fit_stream"):
    """Deterministic-SRM BCD over a :class:`SubjectStore` (see
    :func:`stream_fit_srm`; the carried statistic here is just
    ``S = Σ WᵀX / n``).  Returns ``(w_list, shared, objective)``.

    The objective needs no extra pass: with ``S = Σ WᵀX / n`` by
    construction, ``Σ‖X_i − W_i S‖² = Σ‖X_i‖² − n·‖S‖²`` (W has
    orthonormal columns), both terms of which the final round
    already produced.
    """
    import jax
    import jax.numpy as jnp

    _validate_store(store, features)
    dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    n_subjects, samples = store.n_subjects, store.samples
    v_max = store.v_max
    lanes = _resolve_lanes(store, shard_subjects, mesh, dtype,
                           prefetch_depth)
    shards = subject_shards(n_subjects, lanes)
    keys = np.asarray(jax.random.split(
        jax.random.PRNGKey(rand_seed), n_subjects))

    init_p = _init_program(mesh)
    shard_p = _det_shard_program(mesh)

    def prefetcher():
        return ShardPrefetcher(
            store, shards, dtype=dtype, lanes=lanes,
            pad_voxels=v_max, demean=False, mesh=mesh,
            depth=prefetch_depth)

    def init_pass():
        ssum = jnp.zeros((features, samples), dtype=dtype)
        with obs_spans.span("data.stream_pass",
                            attrs={"estimator": name,
                                   "stage": "init"}):
            with prefetcher() as pf:
                for batch in pf:
                    kb = jnp.asarray(_pad_lanes(keys[batch.lo:batch.hi],
                                                lanes))
                    ssum = ssum + init_p(
                        kb, jnp.asarray(batch.counts), batch.x,
                        jnp.asarray(batch.mask), features=features)
        return ssum / n_subjects

    def round_pass(shared, round_idx):
        ssum = jnp.zeros((features, samples), dtype=dtype)
        with obs_spans.span("data.stream_pass",
                            attrs={"estimator": name,
                                   "round": round_idx}):
            with prefetcher() as pf:
                for batch in pf:
                    _, part = shard_p(batch.x,
                                      jnp.asarray(batch.mask), shared)
                    ssum = ssum + part
        return ssum / n_subjects

    def run_chunk(state, step, n_steps):
        shared = jnp.asarray(np.asarray(state["shared"], dtype=dtype))
        if not float(np.asarray(state["initialized"]).reshape(-1)[0]):
            shared = init_pass()
        prev = shared
        for i in range(n_steps):
            prev = shared
            shared = round_pass(shared, step + i)
        # host state is the checkpoint/guard contract
        # jaxlint: disable=JX002
        return {"shared": np.asarray(shared),
                "prev_shared": np.asarray(prev),
                "initialized": np.ones(1, dtype=dtype)}, False

    init_state = {
        "shared": np.zeros((features, samples), dtype=dtype),
        "prev_shared": np.zeros((features, samples), dtype=dtype),
        "initialized": np.zeros(1, dtype=dtype),
    }
    fingerprint = None
    template = None
    if checkpoint_dir is not None:
        fingerprint = np.concatenate([
            store.fingerprint(),
            [float(features), float(rand_seed), float(lanes),
             float(np.dtype(dtype).itemsize)]])
        template = {k: np.zeros_like(v)
                    for k, v in init_state.items()}

    state, _ = run_resilient_loop(
        run_chunk, init_state, n_iter,
        checkpoint_dir=checkpoint_dir,
        checkpoint_every=checkpoint_every,
        fingerprint=fingerprint, template=template, name=name)

    # -- output pass: the final-iteration W comes from the shared
    # response that ENTERED the final round (the stacked BCD body
    # updates W before S), so replay the final round's map with
    # ``prev_shared`` and collect W + the raw traces the objective
    # needs.
    prev_shared = jnp.asarray(np.asarray(state["prev_shared"],
                                         dtype=dtype))
    w_list = [None] * n_subjects
    trace_total = 0.0
    counts = store.voxel_counts
    with obs_spans.span("data.stream_pass",
                        attrs={"estimator": name, "stage": "output"}):
        with prefetcher() as pf:
            for batch in pf:
                w, _ = shard_p(batch.x, jnp.asarray(batch.mask),
                               prev_shared)
                wn = np.asarray(w)  # jaxlint: disable=JX002
                for j, subj in enumerate(range(batch.lo, batch.hi)):
                    w_list[subj] = wn[j, :int(counts[subj])].copy()
                trace_total += float(
                    batch.trace_xtx[:batch.hi - batch.lo].sum())

    shared_out = np.asarray(state["shared"], dtype=dtype)
    objective = 0.5 * (trace_total
                       - n_subjects * float(np.sum(shared_out ** 2)))
    return w_list, shared_out, float(objective)


# -- incremental / minibatch SRM --------------------------------------

@obs_runtime.counted_cache("srm.incremental_step")
def _incremental_program(mesh):
    """Local BCD alternation for one minibatch against the running
    shared response: ``inner_iter`` rounds of (W | S) block updates
    confined to the batch — O(batch · V·K) working memory."""
    import jax
    import jax.numpy as jnp

    from ..funcalign.srm import _procrustes_batch

    @partial(jax.jit, static_argnames=("inner_iter",))
    def step_fn(x, mask, shared, *, inner_iter):
        n_real = jnp.maximum(jnp.sum(mask), 1.0)

        def body(_, s):
            a = jnp.einsum('svt,kt->svk', x, s)
            w = _procrustes_batch(a, mesh)
            wm = w * mask[:, None, None]
            return jnp.einsum('svk,svt->kt', wm, x) / n_real

        return jax.lax.fori_loop(0, inner_iter, body, shared)

    return step_fn


@obs_runtime.trace_signature("srm.incremental_step")
def _incremental_trace_signature():
    mesh = _stream_mesh()
    s, v, t, k = _stream_extents(mesh)
    return [{"key": (mesh,),
             "args": (_aval(s, v, t), _aval(s), _aval(k, t)),
             "kwargs": {"inner_iter": 2}, "mesh": mesh}]


class IncrementalSRM:
    """Minibatch deterministic SRM whose memory is O(K) in subjects.

    Where :func:`stream_fit_detsrm` keeps exact BCD semantics at the
    cost of one pass per iteration, this variant trades exactness
    for constant state: it holds only the running shared response
    ``s_`` ([features, samples]) and folds each subject minibatch in
    with online averaging —

    ``s ← s + (b / n_seen) · (s_batch − s)``

    where ``s_batch`` is ``inner_iter`` local BCD rounds of the
    minibatch against the current ``s``.  Because every batch's W is
    solved *against the current shared frame*, there is no rotation
    ambiguity between batches (the first batch bootstraps the
    frame).  Per-subject maps are not retained; compute them on
    demand with :meth:`subject_basis` / :meth:`transform`.

    ``fit`` accepts either a list of arrays or a
    :class:`~brainiak_tpu.data.store.SubjectStore` (minibatches then
    stream through the prefetcher); ``partial_fit`` ingests one
    minibatch at a time for fully external loops.  With
    ``checkpoint_dir`` the rounds run under
    :func:`run_resilient_loop` and resume after preemption.
    """

    def __init__(self, n_iter=3, features=50, rand_seed=0,
                 batch_subjects=8, inner_iter=3, mesh=None,
                 prefetch_depth=2):
        self.n_iter = n_iter
        self.features = features
        self.rand_seed = rand_seed
        self.batch_subjects = int(batch_subjects)
        self.inner_iter = int(inner_iter)
        self.mesh = mesh
        self.prefetch_depth = prefetch_depth
        self.s_ = None
        self.n_seen_ = 0
        self._v_pad = 0

    # -- internals --------------------------------------------------------
    def _dtype(self):
        import jax

        return np.float64 if jax.config.jax_enable_x64 \
            else np.float32

    def _stack_batch(self, X, lanes=None):
        dtype = self._dtype()
        lanes = len(X) if lanes is None else lanes
        v_max = max(max(d.shape[0] for d in X), self._v_pad)
        x = np.zeros((lanes, v_max, X[0].shape[1]), dtype=dtype)
        mask = np.zeros(lanes, dtype=dtype)
        counts = np.zeros(lanes, dtype=dtype)
        for i, d in enumerate(X):
            x[i, :d.shape[0]] = np.asarray(d, dtype=dtype)
            mask[i] = 1.0
            counts[i] = d.shape[0]
        return x, mask, counts, v_max

    def _bootstrap(self, x, mask, counts, n_real):
        """First minibatch defines the shared frame: start from the
        key-exact W₀ init (same recipe as the full fits) and take
        its mean projection as the seed shared response."""
        import jax
        import jax.numpy as jnp

        keys = jnp.asarray(np.asarray(jax.random.split(
            jax.random.PRNGKey(self.rand_seed), x.shape[0])))
        ssum = _init_program(self.mesh)(
            keys, jnp.asarray(counts), jnp.asarray(x),
            jnp.asarray(mask), features=self.features)
        return ssum / n_real

    def partial_fit(self, X, lanes=None):
        """Fold one minibatch (list of ``[voxels_i, samples]``
        arrays) into the running shared response.  ``lanes`` pads
        the batch to a fixed lane count (``fit`` pins it so a short
        final minibatch reuses the same compiled shape)."""
        import jax.numpy as jnp

        if not X:
            return self
        x, mask, counts, v_pad = self._stack_batch(X, lanes=lanes)
        self._v_pad = v_pad
        if self.s_ is None:
            shared = self._bootstrap(x, mask, counts, float(len(X)))
        else:
            if x.shape[2] != self.s_.shape[1]:
                raise ValueError(
                    f"batch has {x.shape[2]} samples; the running "
                    f"shared response has {self.s_.shape[1]}")
            shared = jnp.asarray(self.s_)
        shared = _incremental_program(self.mesh)(
            jnp.asarray(x), jnp.asarray(mask), shared,
            inner_iter=self.inner_iter)
        b = len(X)
        self.n_seen_ += b
        eta = b / float(self.n_seen_)
        new = np.asarray(shared)
        self.s_ = new if self.s_ is None or eta >= 1.0 \
            else (1.0 - eta) * self.s_ + eta * new
        return self

    def fit(self, X, y=None, checkpoint_dir=None, checkpoint_every=1):
        """Rounds of minibatch updates over a subject list or a
        :class:`SubjectStore`.  Each round is one pass over all
        minibatches; with ``checkpoint_dir`` the rounds checkpoint
        and resume under the resilience guard."""
        from .store import SubjectStore

        is_store = isinstance(X, SubjectStore)
        n = X.n_subjects if is_store else len(X)
        if n <= 1:
            raise ValueError(
                "There are not enough subjects ({0:d}) to train "
                "the model.".format(n))
        dtype = self._dtype()
        lanes = min(self.batch_subjects, n)
        if self.mesh is not None \
                and DEFAULT_SUBJECT_AXIS in self.mesh.shape:
            axis = self.mesh.shape[DEFAULT_SUBJECT_AXIS]
            lanes = -(-lanes // axis) * axis
        shards = subject_shards(n, lanes)
        # pin the padded voxel width up front (the store manifest —
        # or one pass over the list shapes — knows it), so a ragged
        # store with growing voxel counts compiles ONE batch shape
        # instead of retracing per new widest subject
        self._v_pad = max(
            self._v_pad,
            X.v_max if is_store else max(d.shape[0] for d in X))

        def batches():
            if is_store:
                pf = ShardPrefetcher(
                    X, shards, dtype=dtype, lanes=lanes, raw=True,
                    depth=self.prefetch_depth)
                with pf:
                    for batch in pf:
                        yield batch.subjects
            else:
                for lo, hi in shards:
                    yield [np.asarray(d, dtype=dtype)
                           for d in X[lo:hi]]

        def run_chunk(state, step, n_steps):
            self.s_ = None if not float(
                np.asarray(state["initialized"]).reshape(-1)[0]) \
                else np.asarray(state["shared"], dtype=dtype)
            self.n_seen_ = int(
                np.asarray(state["n_seen"]).reshape(-1)[0])
            for i in range(n_steps):
                with obs_spans.span(
                        "data.stream_pass",
                        attrs={"estimator": "IncrementalSRM.fit",
                               "round": step + i}):
                    for subj_batch in batches():
                        # partial_fit lands the [K,T] running shared
                        # response on host per minibatch — that IS
                        # the O(K)-in-subjects state contract
                        self.partial_fit(  # jaxlint: disable=JX010
                            subj_batch, lanes=lanes)
            return {"shared": np.asarray(self.s_),
                    "n_seen": np.array([float(self.n_seen_)]),
                    "initialized": np.ones(1, dtype=dtype)}, False

        samples = X.samples if is_store else X[0].shape[1]
        init_state = {
            "shared": np.zeros((self.features, samples), dtype=dtype),
            "n_seen": np.zeros(1),
            "initialized": np.zeros(1, dtype=dtype),
        }
        fingerprint = None
        template = None
        if checkpoint_dir is not None:
            if not is_store:
                raise ValueError(
                    "checkpoint_dir requires a SubjectStore input "
                    "(per-subject digests make the resume "
                    "fingerprint; wrap the list with write_store)")
            fingerprint = np.concatenate([
                X.fingerprint(),
                [float(self.features), float(self.rand_seed),
                 float(lanes), float(self.inner_iter)]])
            template = {k: np.zeros_like(v)
                        for k, v in init_state.items()}
        state, _ = run_resilient_loop(
            run_chunk, init_state, self.n_iter,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            fingerprint=fingerprint, template=template,
            name="IncrementalSRM.fit")
        self.s_ = np.asarray(state["shared"], dtype=dtype)
        self.n_seen_ = int(np.asarray(state["n_seen"]).reshape(-1)[0])
        return self

    # -- on-demand subject maps ------------------------------------------
    def subject_basis(self, x):
        """Orthonormal ``[voxels, features]`` map for one subject's
        data against the fitted shared response (computed on demand —
        the O(K)-in-subjects contract means no ``w_`` list)."""
        import jax.numpy as jnp

        from ..funcalign.srm import _procrustes

        if self.s_ is None:
            raise RuntimeError(
                "The model fit has not been run yet.")
        a = jnp.asarray(np.asarray(x, dtype=self._dtype())) \
            @ jnp.asarray(self.s_).T
        return np.asarray(_procrustes(a))

    def transform(self, X, y=None):
        """Project each subject into shared space via its on-demand
        basis: ``s_i = W_iᵀ X_i``."""
        return [None if x is None
                else self.subject_basis(x).T @ np.asarray(x)
                for x in X]
