"""CI selfcheck for the streaming data plane (DAT001 gate).

Run as a subprocess child by ``tools/run_checks.py`` on the 8-device
CPU mesh: proves (1) streamed-vs-in-memory SRM parity over a real
on-disk :class:`~brainiak_tpu.data.store.SubjectStore`, (2)
resume-at-shard-round — an injected preemption mid-stream, then a
resumed fit that matches the uninterrupted one, and (3) retrace
stability: a REPEAT streamed fit (second full set of shard rounds in
the same process) must not rebuild any ``data.*``/``srm.*`` program
— every counted site stays at <= 1 trace.
"""

import numpy as np

__all__ = ["selfcheck"]


def selfcheck(out=None):
    """Prints a JSON verdict; returns 0 on pass, 1 on failure."""
    import json
    import os
    import sys
    import tempfile

    from ..funcalign.srm import SRM, DetSRM
    from ..obs import metrics as obs_metrics
    from ..parallel.mesh import DEFAULT_SUBJECT_AXIS, make_mesh
    from ..resilience import faults
    from .store import write_store

    stream = out or sys.stdout
    rng = np.random.RandomState(0)
    # 10 subjects over shards of 4: the final shard is SHORT (2 real
    # + 2 masked pad lanes), so the zero-pad reduction path runs
    # under the mesh.  One mesh for every fit below — each counted
    # builder must be constructed exactly once process-wide.
    n_subjects, samples, features = 10, 30, 3
    shared = rng.randn(features, samples)
    subjects = []
    for i in range(n_subjects):
        v = 20 + i  # ragged: the zero-pad path must stay exact
        q, _ = np.linalg.qr(rng.randn(v, features))
        subjects.append((q @ shared
                         + 0.1 * rng.randn(v, samples)).astype(
                             np.float32))

    mesh = make_mesh((DEFAULT_SUBJECT_AXIS,), (4,))
    errs = []
    resume_ok = True
    with tempfile.TemporaryDirectory() as tmp:
        store = write_store(os.path.join(tmp, "store"), subjects)

        # (1) streamed vs in-memory parity over mesh-sharded shards
        inmem = SRM(n_iter=4, features=features).fit(subjects)
        streamed = SRM(n_iter=4, features=features, mesh=mesh,
                       shard_subjects=4).fit(store)
        errs.append(max(
            float(np.max(np.abs(a - b)))
            for a, b in zip(inmem.w_, streamed.w_)))
        errs.append(float(np.max(np.abs(inmem.s_ - streamed.s_))))
        errs.append(float(np.max(np.abs(inmem.rho2_
                                        - streamed.rho2_))))

        det_in = DetSRM(n_iter=4, features=features).fit(subjects)
        det_st = DetSRM(n_iter=4, features=features, mesh=mesh,
                        shard_subjects=4).fit(store)
        errs.append(max(
            float(np.max(np.abs(a - b)))
            for a, b in zip(det_in.w_, det_st.w_)))
        errs.append(float(np.max(np.abs(det_in.s_ - det_st.s_))))

        # (2) resume at the last completed shard round after an
        # injected preemption
        ckpt = os.path.join(tmp, "ckpt")
        try:
            with faults.inject("preempt", at_step=2):
                SRM(n_iter=4, features=features, mesh=mesh,
                    shard_subjects=4).fit(
                        store, checkpoint_dir=ckpt,
                        checkpoint_every=2)
            resume_ok = False  # the fault must fire
        except faults.PreemptionError:
            pass
        resumed = SRM(n_iter=4, features=features, mesh=mesh,
                      shard_subjects=4).fit(
                          store, checkpoint_dir=ckpt,
                          checkpoint_every=2)
        resume_err = max(
            float(np.max(np.abs(a - b)))
            for a, b in zip(streamed.w_, resumed.w_))
        errs.append(resume_err)
        if resume_err > 1e-5:
            resume_ok = False

        # (3) repeat shard rounds: a second full streamed fit must
        # hit every program cache (counted below)
        SRM(n_iter=2, features=features, mesh=mesh,
            shard_subjects=4).fit(store)

    retrace = obs_metrics.counter("retrace_total")
    sites = {}
    for labels, value in retrace.samples():
        site = labels.get("site", "")
        if site.startswith(("data.", "srm.stream",
                            "srm.incremental")):
            sites[site] = value
    tol = 5e-4
    ok = max(errs) < tol and resume_ok \
        and all(c <= 1.0 for c in sites.values()) \
        and {"srm.stream_init", "srm.stream_prob_shard",
             "srm.stream_det_shard"} <= set(sites)
    json.dump({"ok": bool(ok), "max_err": max(errs), "tol": tol,
               "resume_ok": bool(resume_ok), "retraces": sites},
              stream)
    stream.write("\n")
    return 0 if ok else 1
