"""Event segmentation (HMM with left-to-right event chains), TPU-native.

Re-design of /root/reference/src/brainiak/eventseg/: the Python
forward-backward loops become ``lax.scan`` programs.

:func:`~brainiak_tpu.eventseg.event.forward_step` is the exposed
single-step forward recursion — the shared kernel of the batch scan
and the per-TR streaming estimator
(:class:`brainiak_tpu.realtime.IncrementalEventSegment`)."""

from .event import EventSegment, forward_step

__all__ = ["EventSegment", "forward_step"]
