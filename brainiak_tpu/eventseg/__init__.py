"""Event segmentation (HMM with left-to-right event chains), TPU-native.

Re-design of /root/reference/src/brainiak/eventseg/: the Python
forward-backward loops become ``lax.scan`` programs."""
