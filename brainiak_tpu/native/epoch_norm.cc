// Host-side data-plane kernels for brainiak_tpu.
//
// The TPU compute path is JAX/XLA/Pallas; these C++ routines cover the
// host runtime's hot loops, the niche the reference fills with
// C++/OpenMP+Cython (fcma_extension.cc, cython_blas.pyx): epoch
// normalization during data ingest, which runs on CPU while staging data
// for the device and benefits from multithreading across voxels.
//
// Built as a plain shared library and bound with ctypes (no pybind11).

#include <cmath>
#include <cstdint>

extern "C" {

// Z-score each column of a row-major [rows, cols] float32 matrix over the
// row (time) axis with population variance, scale by 1/sqrt(rows), and
// map zero-variance columns to zero — the exact semantics of FCMA epoch
// preparation (reference fcma/preprocessing.py:41-92).
void epoch_zscore_f32(float* mat, int64_t rows, int64_t cols) {
  const float inv_rows = 1.0f / static_cast<float>(rows);
  const float scale = 1.0f / std::sqrt(static_cast<float>(rows));
#pragma omp parallel for schedule(static)
  for (int64_t c = 0; c < cols; ++c) {
    // two-pass variance with double accumulators: raw BOLD intensities
    // have means ~1e4, where single-pass float32 E[x^2]-mean^2 suffers
    // catastrophic cancellation
    double mean_acc = 0.0;
    for (int64_t r = 0; r < rows; ++r) {
      mean_acc += static_cast<double>(mat[r * cols + c]);
    }
    const float mean = static_cast<float>(mean_acc * inv_rows);
    double var_acc = 0.0;
    for (int64_t r = 0; r < rows; ++r) {
      const double d = static_cast<double>(mat[r * cols + c]) - mean;
      var_acc += d * d;
    }
    const float var = static_cast<float>(var_acc * inv_rows);
    if (var <= 0.0f || !std::isfinite(var)) {
      for (int64_t r = 0; r < rows; ++r) mat[r * cols + c] = 0.0f;
    } else {
      const float inv_std = scale / std::sqrt(var);
      for (int64_t r = 0; r < rows; ++r) {
        mat[r * cols + c] = (mat[r * cols + c] - mean) * inv_std;
      }
    }
  }
}

// Mean over the time axis for a row-major [rows, cols] float32 matrix —
// the epoch-averaging loop of MVPA preparation
// (reference fcma/preprocessing.py:274-326).
void column_mean_f32(const float* mat, int64_t rows, int64_t cols,
                     float* out) {
  const float inv_rows = 1.0f / static_cast<float>(rows);
#pragma omp parallel for schedule(static)
  for (int64_t c = 0; c < cols; ++c) {
    float acc = 0.0f;
    for (int64_t r = 0; r < rows; ++r) acc += mat[r * cols + c];
    out[c] = acc * inv_rows;
  }
}

}  // extern "C"
