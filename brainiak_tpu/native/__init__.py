"""DEPRECATED native (C++/OpenMP) host-runtime kernels.

The FCMA ingest path no longer calls these: epoch normalization runs
on device via :mod:`brainiak_tpu.ops.kernels.epoch_norm` (one jitted
dispatch per distinct epoch shape, Pallas-tiled on TPU, NumPy
fallback kept), which retired the last native-extension dependency
on a hot path.  This package remains as a shim for out-of-tree
callers — importing it emits a ``DeprecationWarning`` (the same
retirement protocol ``utils/profiling`` followed in PR 5) — and will
be removed once downstream code has migrated.

The original behavior is preserved: the shared library is compiled
on demand with the system g++ and cached next to the sources, and
every entry point has a NumPy fallback, so the shim works without a
toolchain too.
"""

import ctypes
import logging
import os
import subprocess
import sysconfig
import warnings

import numpy as np

warnings.warn(
    "brainiak_tpu.native is deprecated: the FCMA ingest path now "
    "normalizes epochs on device via "
    "brainiak_tpu.ops.kernels.epoch_norm (normalize_epochs / "
    "epoch_zscore), which keeps a NumPy fallback for hosts without "
    "an accelerator; this C++/ctypes shim will be removed",
    DeprecationWarning, stacklevel=2)

logger = logging.getLogger(__name__)

__all__ = ["epoch_zscore", "column_mean", "native_available"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "epoch_norm.cc")
_LIB_PATH = os.path.join(_HERE, "_epoch_norm" +
                         (sysconfig.get_config_var("EXT_SUFFIX") or ".so"))
_lib = None
_tried = False


def _build():
    # compile to a unique temp name and rename into place so concurrent
    # processes (e.g. the distributed test harness) never load a
    # partially written library
    tmp = _LIB_PATH + f".tmp{os.getpid()}"
    # no -march=native: the cached .so may travel with the repo across
    # heterogeneous hosts (the OpenMP threading is the dominant win)
    cmd = ["g++", "-O3", "-fPIC", "-shared", "-fopenmp",
           _SRC, "-o", tmp]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, _LIB_PATH)


def _load():
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    try:
        if not os.path.exists(_LIB_PATH) or \
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC):
            _build()
        lib = ctypes.CDLL(_LIB_PATH)
        lib.epoch_zscore_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.c_int64]
        lib.column_mean_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_float)]
        _lib = lib
    except Exception as exc:  # toolchain missing / build failure
        logger.info("native kernels unavailable (%s); using NumPy "
                    "fallbacks", exc)
        _lib = None
    return _lib


def native_available():
    return _load() is not None


def epoch_zscore(mat):
    """In-place column z-score (population) + 1/sqrt(rows) scaling of a
    C-contiguous float32 [rows, cols] array; zero-variance columns become
    zero.  Returns ``mat``."""
    assert mat.dtype == np.float32 and mat.flags.c_contiguous
    lib = _load()
    if lib is None:
        rows = mat.shape[0]
        mean = mat.mean(axis=0)
        std = mat.std(axis=0)
        with np.errstate(divide='ignore', invalid='ignore'):
            out = (mat - mean) / (std * np.sqrt(rows))
        mat[:] = np.nan_to_num(out, nan=0.0, posinf=0.0, neginf=0.0)
        return mat
    lib.epoch_zscore_f32(
        mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        mat.shape[0], mat.shape[1])
    return mat


def column_mean(mat):
    """Column means of a C-contiguous float32 [rows, cols] array."""
    assert mat.dtype == np.float32 and mat.flags.c_contiguous
    lib = _load()
    if lib is None:
        return mat.mean(axis=0)
    out = np.empty(mat.shape[1], dtype=np.float32)
    lib.column_mean_f32(
        mat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        mat.shape[0], mat.shape[1],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    return out
