#!/usr/bin/env python3
"""Regenerate the committed serve-gate fixture (tools/serve_fixture/).

The ``serve`` gate of ``tools/run_checks.py`` (SRV001) smoke-runs the
serving CLI on a tiny committed model + request file; this script is
how those artifacts were produced — deterministic (fixed seeds, CPU
backend) so a regeneration diff means the artifact schema or the
demo-model numerics changed, both of which SHOULD be a reviewed
change.

Run from the repo root:  python tools/gen_serve_fixture.py
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "tools", "serve_fixture")


def main():
    from brainiak_tpu.serve import save_model, save_requests
    from brainiak_tpu.serve.__main__ import (build_demo_model,
                                             build_mixed_requests)

    os.makedirs(OUT, exist_ok=True)
    # mixed voxel counts (ragged=True) so the gate also exercises the
    # indexed-key list packing; tiny sizes keep CI fast
    model = build_demo_model(n_subjects=3, voxels=12, samples=24,
                             features=4, n_iter=3, seed=7)
    save_model(model, os.path.join(OUT, "model.npz"))
    requests = build_mixed_requests(model, 10, seed=7,
                                    tr_choices=(6, 11, 18))
    save_requests(
        os.path.join(OUT, "requests.npz"),
        [r.x for r in requests],
        subjects=[r.subject for r in requests],
        ids=[r.request_id for r in requests])
    print(f"wrote {OUT}/model.npz and {OUT}/requests.npz")


if __name__ == "__main__":
    main()
