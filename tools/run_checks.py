#!/usr/bin/env python3
"""CI-grade static analysis gate, rule-plugin edition.

The analog of the reference's ``run-checks.sh:19-24`` (flake8 + mypy),
grown into a gate registry sharing ONE file walk and ONE output path
with the jaxlint TPU-correctness analyzer
(:mod:`brainiak_tpu.analysis`):

========== ===================================================
gate       what it enforces
========== ===================================================
external   ruff/flake8 + mypy when installed (full CI hosts)
stdlib     hermetic fallback: syntax (CHK001), 79-col lines
           (CHK002), unused imports (CHK003)
doc-defaults   docs/*.md ``name= (default X)`` claims match a
           signature default (CHK101)
resilient-fits every public iterative fit honors the
           checkpoint_dir/run_resilient_loop contract (CHK102)
jaxlint    TPU-readiness file rules JX001-JX006 over the
           configured scope, with the [tool.jaxlint] baseline
           applied
jaxlint-deep project-wide semantic analysis over the same scope:
           interprocedural dataflow (JX010-JX012 — transitive
           host syncs in hot loops, jit-per-call through the
           call graph, cross-function PRNG key reuse),
           mesh/collective axis checking (JX101-JX103), and the
           guarded-by lock-discipline race detector for the
           serve loop (JX201-JX205); same baseline, own section
           conventions (see docs/static_analysis.md)
jaxlint-ir traced-IR audit (JPR001): a child pinned to an
           8-device CPU backend traces every registered
           jitted-program builder at its canonical abstract
           signature and runs the JP301-JP305 rules over the
           actual jaxpr/executable (dtype promotion, donation,
           host callbacks, collective axes, retrace surface);
           surviving findings keep their own JP codes, and the
           gate itself fails on builder coverage below 90% of
           the static census or a crashed/hung audit child
obs        smoke-runs ``python -m brainiak_tpu.obs report
           --format=json`` on tools/obs_fixture.jsonl and
           fails on schema violations (OBS001)
obs-live   live telemetry plane (OBS002): a child process drives
           a tiny ServeService with SLO tracking and the HTTP
           exposition on an ephemeral port, scrapes /metrics +
           /healthz + /readyz, validates the Prometheus text with
           the in-repo parser, and requires the serve_*/slo_*
           series present and in agreement with the JSON summary
obs-fit    fit-progress plane (OBS003): a child process drives a
           chunked resilient fit through a preemption/resume
           cycle and then a NaN-divergence incident under
           ``BRAINIAK_TPU_OBS_DIR``, and requires one stable
           fit_id with monotone chunk indices across the resume,
           a divergence_precursor timestamped before the guard's
           rollback, exactly one auto-dumped flight-recorder
           snapshot naming the aborting fit, and a clean
           ``obs postmortem`` render of it
regress    runs ``python -m brainiak_tpu.obs regress`` on the
           committed tools/bench_fixture/ history and fails on
           a regression verdict (REG001) — the bench gate runs
           fixture-driven in CI, no TPU required
serve      smoke-runs ``python -m brainiak_tpu.serve run`` on
           the committed tools/serve_fixture/ model + request
           files and fails on CLI errors, request-level error
           records, or per-request recompiles (SRV001)
service    smoke-runs ``python -m brainiak_tpu.serve service``
           TWICE on the committed serve fixture over one temp
           AOT cache and fails unless the second run reports
           aot hits > 0 and ZERO serve retraces — the
           restart-without-compile-stall contract (SRV002)
federation serving federation gate (SRV003): two ``serve service
           --replicas 2`` fleets over ONE temp AOT cache — the
           second fleet must report aot hits > 0, zero serve
           retraces, and the router must have routed the mixed
           wave across BOTH replicas; then the federation
           selfcheck child on the 8-device CPU mesh proves
           sharded over-budget serving parity, per-device
           residency accounting, and load shedding with
           retry_after
fleet      elastic-fleet chaos gate (SRV004): the fleet
           selfcheck child on the 8-device CPU mesh runs one
           deterministic chaos soak — heavy-tailed traffic
           triples mid-run while a replica is stalled and then
           killed under injected faults — and fails on a lost
           ticket (a request that never resolves), a missing
           failover to survivors, or ANY serve retrace on the
           mid-run scaled-up replicas over the shared AOT cache
distla     smoke-runs the pod-scale linear algebra selfcheck
           (``brainiak_tpu.ops.distla.selfcheck``) on a tiny
           fixture over an 8-device CPU mesh and fails on
           parity error or program rebuilds — every
           ``retrace_total{site=distla.*}`` must stay at 1
           across repeat calls (DLA001)
encoding   smoke-runs the encoding-tier selfcheck
           (``brainiak_tpu.encoding.selfcheck``) on the
           8-device CPU mesh and fails on sklearn-Ridge parity
           error, a broken banded fit, or program rebuilds —
           every ``retrace_total{site=encoding.*}`` must stay
           at 1 across repeat fits (ENC001)
kernels    smoke-runs the fused-kernels selfcheck
           (``brainiak_tpu.ops.kernels.selfcheck``) on the
           8-device CPU mesh and fails on fused-vs-reference
           parity error (single-scan HMM forward-backward,
           fused SUMMA ring step, MTTKRP factor reconstruction,
           device epoch norm), a -inf/NaN mask mismatch, or
           program rebuilds across the repeat pass (KRN001)
realtime   smoke-runs the closed-loop tier selfcheck
           (``brainiak_tpu.realtime.selfcheck``): online-vs-
           batch parity (OnlineISC vs ``isc()``, incremental
           event segmentation vs the fused batch forward pass,
           at every prefix, ~1e-6), resume-mid-scan parity
           after an injected preemption, and retrace stability
           across repeat sessions incl. the warm low-latency
           ServeService hop (RT001)
stats      smoke-runs the resampling-statistics selfcheck
           (``brainiak_tpu.stats.selfcheck``) on the 8-device
           CPU mesh: count-vs-materialized p-value parity,
           chunk invariance, exact pooling over both wire
           formats, resume-at-chunk after an injected
           preemption, and stats.* retrace stability (STA001)
jobs       smoke-runs the fit-scheduler selfcheck
           (``brainiak_tpu.jobs.selfcheck``) on the 8-device
           CPU mesh: two tenants' mixed-priority fits
           co-scheduled with warm serving, one injected
           priority preemption — fails on a lost job, broken
           park/resume parity, a fair-share deficit outside
           tolerance (starvation), or any added serve.*
           retrace (JOB001)
========== ===================================================

``# noqa`` suppresses stdlib/doc findings on a line; jaxlint uses
``# jaxlint: disable=JX00N`` plus the justification baseline.  Run
``python -m tools.run_checks --only=jaxlint`` for one gate,
``--format=json`` for machine-readable output (including per-gate
wall time in ``gate_seconds``, so gate-runtime creep is visible as
the registry grows), ``--format=sarif`` for CI hosts that render
findings as inline annotations; exits non-zero on any finding.
``tests/test_static_checks.py`` wires the full gate into the pytest
suite.
"""

import argparse
import ast
import json
import os
import re
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from brainiak_tpu.analysis import (  # noqa: E402
    Baseline, FileRule, Finding, JAXLINT_RULES,
    iter_python_files, load_config, to_sarif)
from brainiak_tpu.analysis.cli import (  # noqa: E402
    ALL_RULES, DEEP_RULES)
from brainiak_tpu.analysis.core import (  # noqa: E402,F401
    SKIP_DIRS, build_context, run_project_rules)

MAX_COLS = 79
GATES = ("external", "stdlib", "doc-defaults", "resilient-fits",
         "jaxlint", "jaxlint-deep", "jaxlint-ir", "obs", "obs-live",
         "obs-fit", "regress", "serve", "service", "federation",
         "fleet", "distla", "encoding", "kernels", "data",
         "realtime", "stats", "jobs")


def python_sources():
    yield from iter_python_files([REPO])


def _rel(path):
    return os.path.relpath(path, REPO).replace(os.sep, "/")


# -- stdlib gate (hermetic ruff/flake8 subset) ------------------------

class LineLength(FileRule):
    """CHK002: pycodestyle E501 analog (79 columns)."""

    code = "CHK002"
    name = "line-too-long"
    gate = "stdlib"
    pragma = "noqa"
    needs_tree = False

    def check(self, ctx):
        for i, line in enumerate(ctx.lines, 1):
            n = len(line.rstrip("\n"))
            if n > MAX_COLS:
                yield ctx.finding(
                    self, i, f"line too long ({n} > {MAX_COLS})")


class UnusedImports(FileRule):
    """CHK003: pyflakes F401 analog."""

    code = "CHK003"
    name = "unused-import"
    gate = "stdlib"
    pragma = "noqa"

    def check(self, ctx):
        # __init__.py re-export lists are conventionally exempt
        # (F401 in per-file-ignores of every major config).
        if os.path.basename(ctx.path) == "__init__.py":
            return
        imports = []
        used = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    imports.append((node.lineno, bound))
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    imports.append((node.lineno, bound))
            elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load):
                used.add(node.id)
            elif isinstance(node, ast.Constant) and isinstance(
                    node.value, str):
                used.add(node.value)  # __all__ strings count as use
        for lineno, name in imports:
            if name.startswith("_"):
                continue
            if name not in used:
                yield ctx.finding(
                    self, lineno, f"'{name}' imported but unused")


# -- doc-defaults gate ------------------------------------------------

def _code_defaults():
    """(global, by_owner): parameter name -> set of repr'd default
    values across every function/method signature in the package,
    plus the same map scoped per owning symbol — the function name,
    and for methods also the enclosing class name (so docs can anchor
    a claim to either ``fit`` or ``SRM``)."""
    defaults = {}
    by_owner = {}

    def record(owner_names, param, value):
        defaults.setdefault(param, set()).add(value)
        for owner in owner_names:
            by_owner.setdefault(owner, {}).setdefault(
                param, set()).add(value)

    def visit_fn(node, owners):
        args = node.args
        pos = args.posonlyargs + args.args
        for arg, dflt in zip(pos[len(pos) - len(args.defaults):],
                             args.defaults):
            if isinstance(dflt, ast.Constant):
                record(owners, arg.arg, repr(dflt.value))
        for arg, dflt in zip(args.kwonlyargs, args.kw_defaults):
            if dflt is not None and isinstance(dflt, ast.Constant):
                record(owners, arg.arg, repr(dflt.value))

    pkg = os.path.join(REPO, "brainiak_tpu")
    for path in iter_python_files([pkg]):
        with open(path, encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read(), filename=path)
            except SyntaxError:
                continue
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        visit_fn(sub, (node.name, sub.name))
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                visit_fn(node, (node.name,))
    return defaults, by_owner


_DOC_DEFAULT_RE = re.compile(
    r"`(?P<name>[A-Za-z_][A-Za-z0-9_]*)=?`\*{0,2}\s*"
    r"\(\s*(?:`)?default(?:s to)?[\s:`]+(?P<value>[^)`\s,;]+)")
_TOKEN_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def check_doc_defaults(findings):
    """Docs-vs-code default drift gate (CHK101): every
    ``**`name=`** (default X)`` claim in docs/*.md must match at
    least one signature default for a parameter of that name (the
    round-2 ``svm_iters`` 20-vs-10 drift is the motivating case)."""
    docs_dir = os.path.join(REPO, "docs")
    if not os.path.isdir(docs_dir):
        return
    defaults = by_owner = None
    md_files = []
    for root, dirs, files in os.walk(docs_dir):
        dirs[:] = [d for d in dirs if d not in SKIP_DIRS]
        md_files.extend(os.path.join(root, f)
                        for f in sorted(files) if f.endswith(".md"))
    for path in md_files:
        heading = ""
        in_fence = False
        with open(path, encoding="utf-8") as fh:
            for i, line in enumerate(fh, 1):
                if line.lstrip().startswith("```"):
                    in_fence = not in_fence
                # markdown heading, not a comment inside a fenced
                # code example
                if not in_fence and re.match(r"^#{1,6} ", line):
                    heading = line
                if "# noqa" in line:
                    continue
                for m in _DOC_DEFAULT_RE.finditer(line):
                    if defaults is None:
                        defaults, by_owner = _code_defaults()
                    name = m.group("name")
                    doc_val = m.group("value").strip("'\"")
                    code_vals = defaults.get(name)
                    if not code_vals:
                        continue  # not a signature param (knob alias)
                    # Scope to the owning symbol when the line or the
                    # nearest heading names one that defines this
                    # parameter — a claim must not be "confirmed" by
                    # an unrelated function's coincidentally matching
                    # default.
                    owners = [t for t in _TOKEN_RE.findall(
                                  line + " " + heading)
                              if t != name and name in
                              by_owner.get(t, ())]
                    if owners:
                        code_vals = set().union(
                            *(by_owner[o][name] for o in owners))
                    elif len(code_vals) > 1:
                        findings.append(Finding(
                            _rel(path), i, "CHK101",
                            f"documented default `{name}={doc_val}` "
                            f"is ambiguous — {len(code_vals)} "
                            "distinct signature defaults "
                            f"({', '.join(sorted(code_vals))}) "
                            "exist; name the owning function/class "
                            "on the line or heading, or # noqa",
                            line.strip()))
                        continue
                    normalized = {v.strip("'\"") for v in code_vals}
                    if doc_val not in normalized:
                        opts = ", ".join(sorted(code_vals))
                        findings.append(Finding(
                            _rel(path), i, "CHK101",
                            f"documented default `{name}={doc_val}` "
                            "does not match a signature default of "
                            f"{'/'.join(owners) or name} ({opts})",
                            line.strip()))


# -- resilient-fits gate ----------------------------------------------

# Public iterative estimators required to honor the resilience
# contract: fit() accepts checkpoint_dir, and the module either
# drives its loop through resilience.run_resilient_loop (which
# applies the non-finite guard) or delegates by forwarding
# checkpoint_dir= to another estimator's fit (FastSRM ->
# reduced-space DetSRM).  An entry may name the guarded loop method
# explicitly as "Class.method" for stateful drivers whose loop is
# not a fit() (the realtime closed-loop session's run()).
RESILIENT_FITS = {
    "brainiak_tpu/data/streaming_fit.py": ("IncrementalSRM",),
    "brainiak_tpu/encoding/ridge.py": ("RidgeEncoder",
                                       "BandedRidgeEncoder"),
    "brainiak_tpu/funcalign/srm.py": ("SRM", "DetSRM"),
    "brainiak_tpu/funcalign/rsrm.py": ("RSRM",),
    "brainiak_tpu/funcalign/fastsrm.py": ("FastSRM",),
    "brainiak_tpu/factoranalysis/tfa.py": ("TFA",),
    "brainiak_tpu/factoranalysis/htfa.py": ("HTFA",),
    "brainiak_tpu/reprsimil/brsa.py": ("BRSA",),
    "brainiak_tpu/eventseg/event.py": ("EventSegment",),
    "brainiak_tpu/realtime/loop.py": ("RealtimeSession.run",),
    "brainiak_tpu/stats/engine.py": ("NullEngine.run",),
}


def check_resilient_fits(findings):
    """Static resilience gate (CHK102): every public iterative
    ``fit`` must accept ``checkpoint_dir`` and run its loop under the
    non-finite guard (via ``run_resilient_loop``) or forward the
    contract to a guarded estimator."""
    for relpath, classes in sorted(RESILIENT_FITS.items()):
        path = os.path.join(REPO, *relpath.split("/"))
        try:
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            findings.append(Finding(
                relpath, 1, "CHK102",
                "unparseable (resilience gate)"))
            continue
        uses_driver = any(
            (isinstance(n, ast.Name) and n.id == "run_resilient_loop")
            or (isinstance(n, ast.Attribute)
                and n.attr == "run_resilient_loop")
            for n in ast.walk(tree))
        delegates = any(
            isinstance(n, ast.Call) and any(
                kw.arg == "checkpoint_dir" for kw in n.keywords)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "fit"
            for n in ast.walk(tree))
        if not (uses_driver or delegates):
            findings.append(Finding(
                relpath, 1, "CHK102",
                "no run_resilient_loop use (or checkpointed fit "
                "delegation); iterative fits must run under the "
                "resilience guard"))
        class_methods = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        class_methods[(node.name, sub.name)] = sub
        for cls in classes:
            cls, _, method = cls.partition(".")
            method = method or "fit"
            fit = class_methods.get((cls, method))
            if fit is None:
                findings.append(Finding(
                    relpath, 1, "CHK102",
                    f"class {cls} defines no {method}() "
                    "(resilience gate)"))
                continue
            args = [a.arg for a in (fit.args.posonlyargs
                                    + fit.args.args
                                    + fit.args.kwonlyargs)]
            for required in ("checkpoint_dir", "checkpoint_every"):
                if required not in args:
                    findings.append(Finding(
                        relpath, fit.lineno, "CHK102",
                        f"{cls}.{method}() does not accept "
                        f"{required}= (resilience contract)"))


# -- obs gate ---------------------------------------------------------

OBS_FIXTURE = os.path.join(REPO, "tools", "obs_fixture.jsonl")


def check_obs(findings):
    """Obs telemetry gate (OBS001): smoke-run the report CLI
    (``python -m brainiak_tpu.obs report --format=json``) on the
    fixture JSONL.  Fails when the CLI errors, emits schema
    violations, or its summary is not the JSON shape downstream
    tooling parses — so a schema drift in
    :mod:`brainiak_tpu.obs.sink` breaks CI instead of silently
    corrupting the next round's traces."""
    rel = _rel(OBS_FIXTURE)
    if not os.path.exists(OBS_FIXTURE):
        findings.append(Finding(
            rel, 1, "OBS001", "obs fixture JSONL missing"))
        return
    proc = subprocess.run(
        [sys.executable, "-m", "brainiak_tpu.obs", "report",
         "--format=json", OBS_FIXTURE],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    # rc=1 with parseable output means schema violations: the CLI
    # still prints its JSON summary, so report them one Finding per
    # violation rather than a generic stderr tail
    try:
        summary = json.loads(proc.stdout)
    except ValueError:
        summary = None
    if summary is None:
        tail = (proc.stderr or proc.stdout or "").strip()
        tail = "; ".join(tail.splitlines()[-3:])
        findings.append(Finding(
            rel, 1, "OBS001",
            f"obs report CLI failed (rc={proc.returncode}): "
            f"{tail or 'no JSON summary'}"))
        return
    for key in ("n_records", "spans", "events", "metrics",
                "schema_errors"):
        if key not in summary:
            findings.append(Finding(
                rel, 1, "OBS001",
                f"obs report summary missing key {key!r}"))
    for err in summary.get("schema_errors", []):
        findings.append(Finding(
            rel, 1, "OBS001", f"schema violation: {err}"))
    if proc.returncode != 0 and not summary.get("schema_errors"):
        findings.append(Finding(
            rel, 1, "OBS001",
            f"obs report CLI exited rc={proc.returncode} with no "
            "reported schema errors"))


# -- obs-live gate ----------------------------------------------------

_OBS_LIVE_CHILD = """\
import jax
jax.config.update("jax_platforms", "cpu")
import sys
from brainiak_tpu.obs.livecheck import selfcheck
sys.exit(selfcheck())
"""


def check_obs_live(findings):
    """Live telemetry gate (OBS002): run
    :func:`brainiak_tpu.obs.livecheck.selfcheck` in a CPU-pinned
    child — a real ``ServeService`` drive with SLO tracking and the
    HTTP exposition on an ephemeral port, scraped over real HTTP.
    Fails when the scrape does not parse as Prometheus text (the
    minimal in-repo parser), a required ``serve_*``/``slo_*`` series
    is missing, the scraped ok-count disagrees with the JSON
    summary, or health/readiness misreport."""
    rel = _rel(os.path.join(REPO, "brainiak_tpu", "obs",
                            "livecheck.py"))
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _OBS_LIVE_CHILD],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu",
                     BENCH_FORCE_CPU="1"),
            timeout=420)
    except subprocess.TimeoutExpired:
        findings.append(Finding(
            rel, 1, "OBS002",
            "obs-live selfcheck timed out after 420s (hung "
            "backend init?)"))
        return
    try:
        verdict = json.loads(proc.stdout)
    except ValueError:
        verdict = None
    if verdict is None or proc.returncode not in (0, 1):
        tail = (proc.stderr or proc.stdout or "").strip()
        tail = "; ".join(tail.splitlines()[-3:])
        findings.append(Finding(
            rel, 1, "OBS002",
            f"obs-live selfcheck failed (rc={proc.returncode}): "
            f"{tail or 'no JSON verdict'}"))
        return
    if verdict.get("ok"):
        return
    if verdict.get("error"):
        findings.append(Finding(
            rel, 1, "OBS002",
            f"obs-live drive crashed: {verdict['error']}"))
        return
    if verdict.get("parse_errors"):
        for err in verdict["parse_errors"][:5]:
            findings.append(Finding(
                rel, 1, "OBS002",
                f"/metrics is not valid Prometheus text: {err}"))
        return
    if verdict.get("missing"):
        findings.append(Finding(
            rel, 1, "OBS002",
            "/metrics scrape is missing required series: "
            + ", ".join(verdict["missing"])))
        return
    if not verdict.get("counts_agree", True):
        findings.append(Finding(
            rel, 1, "OBS002",
            f"scraped serve_requests_total ok-count "
            f"({verdict.get('scraped_ok')}) disagrees with the "
            f"service summary n_ok ({verdict.get('n_ok')}) for "
            f"{verdict.get('n_requested')} requests"))
        return
    findings.append(Finding(
        rel, 1, "OBS002",
        "obs-live selfcheck failed: "
        f"healthz_ok={verdict.get('healthz_ok')} "
        f"readyz_ready={verdict.get('readyz_ready')} "
        f"metrics_status={verdict.get('metrics_status')}"))


# -- obs-fit gate -----------------------------------------------------

_OBS_FIT_CHILD = """\
import jax
jax.config.update("jax_platforms", "cpu")
import sys
from brainiak_tpu.obs.fitcheck import selfcheck
sys.exit(selfcheck())
"""


def check_obs_fit(findings):
    """Fit-progress gate (OBS003): run
    :func:`brainiak_tpu.obs.fitcheck.selfcheck` in a CPU-pinned
    child — a chunked resilient fit preempted and resumed, then a
    NaN-divergence incident.  Fails when the fit_id does not
    survive the resume, chunk indices break monotonicity, the
    divergence precursor is not timestamped before the guard's
    rollback, the abort does not auto-dump exactly one
    flight-recorder snapshot naming the fit, or the postmortem CLI
    cannot render that snapshot."""
    rel = _rel(os.path.join(REPO, "brainiak_tpu", "obs",
                            "fitcheck.py"))
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _OBS_FIT_CHILD],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu",
                     BENCH_FORCE_CPU="1"),
            timeout=420)
    except subprocess.TimeoutExpired:
        findings.append(Finding(
            rel, 1, "OBS003",
            "obs-fit selfcheck timed out after 420s (hung "
            "backend init?)"))
        return
    try:
        verdict = json.loads(proc.stdout.splitlines()[-1])
    except (ValueError, IndexError):
        verdict = None
    if verdict is None or proc.returncode not in (0, 1):
        tail = (proc.stderr or proc.stdout or "").strip()
        tail = "; ".join(tail.splitlines()[-3:])
        findings.append(Finding(
            rel, 1, "OBS003",
            f"obs-fit selfcheck failed (rc={proc.returncode}): "
            f"{tail or 'no JSON verdict'}"))
        return
    if verdict.get("ok"):
        return
    if verdict.get("error"):
        findings.append(Finding(
            rel, 1, "OBS003",
            f"obs-fit drive crashed: {verdict['error']}"))
        return
    if verdict.get("schema_errors"):
        for err in verdict["schema_errors"][:5]:
            findings.append(Finding(
                rel, 1, "OBS003",
                f"progress stream is not schema-clean: {err}"))
        return
    if not verdict.get("fit_id_stable", True) \
            or not verdict.get("chunks_monotone", True) \
            or not verdict.get("wall_cumulative", True):
        findings.append(Finding(
            rel, 1, "OBS003",
            "resume parity broke: "
            f"fit_id_stable={verdict.get('fit_id_stable')} "
            f"chunks={verdict.get('chunks')} "
            f"wall_cumulative={verdict.get('wall_cumulative')}"))
        return
    if not verdict.get("precursor_before_guard", True):
        findings.append(Finding(
            rel, 1, "OBS003",
            "divergence precursor did not fire before the guard "
            f"(fired={verdict.get('precursor_fired')})"))
        return
    findings.append(Finding(
        rel, 1, "OBS003",
        "incident snapshot/postmortem failed: "
        f"aborted={verdict.get('aborted')} "
        f"n_snapshots={verdict.get('n_snapshots')} "
        f"snapshot_ok={verdict.get('snapshot_ok')} "
        f"postmortem_rc={verdict.get('postmortem_rc')}"))


# -- regress gate -----------------------------------------------------

BENCH_FIXTURE_DIR = os.path.join(REPO, "tools", "bench_fixture")


def check_regress(findings):
    """Bench regression gate (REG001): run the regression detector
    (``python -m brainiak_tpu.obs regress``) over the committed
    fixture history in self-gating mode (each tier's newest record
    vs. the records before it).  The fixture pins the detector's
    behavior on the repo's real BENCH_r* trajectory; a code change
    that flips its verdict — or breaks the CLI — fails CI without
    needing TPU hardware or a live bench run."""
    rel = _rel(BENCH_FIXTURE_DIR)
    if not os.path.isdir(BENCH_FIXTURE_DIR):
        findings.append(Finding(
            rel, 1, "REG001", "bench fixture directory missing"))
        return
    proc = subprocess.run(
        [sys.executable, "-m", "brainiak_tpu.obs", "regress",
         "--history", BENCH_FIXTURE_DIR, "--format=json"],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    try:
        verdict = json.loads(proc.stdout)
    except ValueError:
        verdict = None
    if verdict is None:
        tail = (proc.stderr or proc.stdout or "").strip()
        tail = "; ".join(tail.splitlines()[-3:])
        findings.append(Finding(
            rel, 1, "REG001",
            f"obs regress CLI failed (rc={proc.returncode}): "
            f"{tail or 'no JSON verdict'}"))
        return
    for check in verdict.get("checks", []):
        if check.get("status") == "regression":
            findings.append(Finding(
                rel, 1, "REG001",
                f"regression: {check.get('metric')} "
                f"[tier {check.get('tier')}] at "
                f"{check.get('ratio', 0):.2f}x of baseline "
                f"{check.get('baseline_median')}"))
    if verdict.get("verdict") not in ("pass", "skip") \
            and not any(c.get("status") == "regression"
                        for c in verdict.get("checks", [])):
        findings.append(Finding(
            rel, 1, "REG001",
            f"obs regress verdict {verdict.get('verdict')!r} with "
            "no named regression"))
    # a fixture that cannot gate must fail loudly rather than
    # silently passing forever — that covers both zero checks
    # (verdict "skip") and a gutted history where every tier reports
    # insufficient_history (verdict "pass" with nothing gated)
    if not any(c.get("status") in ("ok", "regression")
               for c in verdict.get("checks", [])):
        findings.append(Finding(
            rel, 1, "REG001",
            "fixture history produced no gating regression checks "
            "(all skipped or insufficient history)"))


# -- serve gate -------------------------------------------------------

SERVE_FIXTURE_DIR = os.path.join(REPO, "tools", "serve_fixture")


def check_serve(findings):
    """Serving gate (SRV001): smoke-run the serve CLI
    (``python -m brainiak_tpu.serve run --format=json``) on the
    committed tiny model + request fixture
    (``tools/gen_serve_fixture.py`` regenerates).  Fails when the
    CLI errors, any request yields an error record, the summary
    loses the keys downstream tooling parses, or the engine
    recompiled more than once per bucket (the no-per-request-
    recompiles contract)."""
    rel = _rel(SERVE_FIXTURE_DIR)
    model = os.path.join(SERVE_FIXTURE_DIR, "model.npz")
    requests = os.path.join(SERVE_FIXTURE_DIR, "requests.npz")
    for path in (model, requests):
        if not os.path.exists(path):
            findings.append(Finding(
                rel, 1, "SRV001",
                f"serve fixture missing: {_rel(path)}"))
            return
    # unlike the obs/regress gate children this one initializes a
    # JAX backend; BENCH_FORCE_CPU makes the child pin the platform
    # in-process before backend init (the JAX_PLATFORMS env var
    # alone can hang on a wedged tunnel PJRT plugin,
    # docs/performance.md rule 4) — the timeout stays as a backstop
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "brainiak_tpu.serve", "run",
             "--model", model, "--requests", requests,
             "--format=json"],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu",
                     BENCH_FORCE_CPU="1"),
            timeout=420)
    except subprocess.TimeoutExpired:
        findings.append(Finding(
            rel, 1, "SRV001",
            "serve CLI timed out after 420s (hung backend init?)"))
        return
    try:
        summary = json.loads(proc.stdout)
    except ValueError:
        summary = None
    # rc=1 with a parseable summary means request-level error
    # records — report those as their own finding below; anything
    # without a summary is a hard CLI failure
    if summary is None or proc.returncode not in (0, 1):
        tail = (proc.stderr or proc.stdout or "").strip()
        tail = "; ".join(tail.splitlines()[-3:])
        findings.append(Finding(
            rel, 1, "SRV001",
            f"serve CLI failed (rc={proc.returncode}): "
            f"{tail or 'no JSON summary'}"))
        return
    for key in ("n_requests", "n_ok", "n_errors", "buckets",
                "retrace_total", "padding_waste"):
        if key not in summary:
            findings.append(Finding(
                rel, 1, "SRV001",
                f"serve summary missing key {key!r}"))
            return
    if summary["n_errors"]:
        findings.append(Finding(
            rel, 1, "SRV001",
            f"{summary['n_errors']} fixture request(s) produced "
            f"error records: {summary.get('errors_by_code')}"))
    if summary["n_ok"] + summary["n_errors"] != summary["n_requests"]:
        findings.append(Finding(
            rel, 1, "SRV001",
            f"{summary['n_ok']} ok + {summary['n_errors']} error "
            f"record(s) for {summary['n_requests']} fixture "
            "requests: records were silently dropped"))
    if summary["retrace_total"] > len(summary["buckets"]):
        findings.append(Finding(
            rel, 1, "SRV001",
            f"engine compiled {summary['retrace_total']:.0f} "
            f"programs for {len(summary['buckets'])} bucket(s): "
            "per-request recompiles"))


# -- service gate -----------------------------------------------------

def _run_service_cli(aot_dir):
    """One ``serve service`` child over the committed fixture with a
    shared AOT cache; returns (rc, summary-or-None, stderr tail)."""
    model = os.path.join(SERVE_FIXTURE_DIR, "model.npz")
    requests = os.path.join(SERVE_FIXTURE_DIR, "requests.npz")
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "brainiak_tpu.serve", "service",
             "--model", f"fixture={model}", "--requests", requests,
             "--aot-cache", aot_dir, "--waves", "1",
             "--format=json"],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu",
                     BENCH_FORCE_CPU="1"),
            timeout=420)
    except subprocess.TimeoutExpired:
        return None, None, "timed out after 420s"
    try:
        summary = json.loads(proc.stdout)
    except ValueError:
        summary = None
    tail = "; ".join((proc.stderr or proc.stdout or "")
                     .strip().splitlines()[-3:])
    return proc.returncode, summary, tail


def check_service(findings):
    """Always-on service gate (SRV002): run the ``service`` CLI
    TWICE on the committed serve fixture over one fresh temp AOT
    cache (``--waves 1`` — atomic submission, deterministic bucket
    shapes).  The first run may compile (and must persist what it
    compiled); the second run is the restart contract: every
    request ok, ``aot.hits > 0``, and ``retrace_total`` (the
    process-wide ``retrace_total{site=serve.*}``) exactly 0 — a
    restarted service must serve without a compile stall."""
    import tempfile

    rel = _rel(SERVE_FIXTURE_DIR)
    for name in ("model.npz", "requests.npz"):
        if not os.path.exists(os.path.join(SERVE_FIXTURE_DIR,
                                           name)):
            findings.append(Finding(
                rel, 1, "SRV002",
                f"serve fixture missing: {rel}/{name}"))
            return
    with tempfile.TemporaryDirectory(prefix="srv002-aot-") as tmp:
        for attempt in (1, 2):
            rc, summary, tail = _run_service_cli(tmp)
            if rc is None or summary is None or rc not in (0, 1):
                findings.append(Finding(
                    rel, 1, "SRV002",
                    f"service CLI run {attempt} failed "
                    f"(rc={rc}): {tail or 'no JSON summary'}"))
                return
            if summary.get("n_errors"):
                findings.append(Finding(
                    rel, 1, "SRV002",
                    f"run {attempt}: {summary['n_errors']} "
                    "request(s) produced error records: "
                    f"{summary.get('errors_by_code')}"))
                return
    aot = summary.get("aot") or {}
    if not aot.get("hits"):
        findings.append(Finding(
            rel, 1, "SRV002",
            "second service run over the warm AOT cache reported "
            f"no aot hits ({aot}): programs are not being "
            "persisted or not being found"))
    if summary.get("retrace_total", 1) != 0:
        findings.append(Finding(
            rel, 1, "SRV002",
            "second service run compiled "
            f"{summary.get('retrace_total'):.0f} serve program(s) "
            "despite the warm AOT cache: the restart "
            "zero-compile contract is broken"))


# -- federation gate --------------------------------------------------

def _run_federation_cli(aot_dir):
    """One ``serve service --replicas 2`` fleet over the committed
    fixture with a shared AOT cache; returns (rc, summary-or-None,
    stderr tail)."""
    model = os.path.join(SERVE_FIXTURE_DIR, "model.npz")
    requests = os.path.join(SERVE_FIXTURE_DIR, "requests.npz")
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "brainiak_tpu.serve", "service",
             "--model", f"fixture={model}", "--requests", requests,
             "--aot-cache", aot_dir, "--waves", "1",
             "--replicas", "2", "--format=json"],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu",
                     BENCH_FORCE_CPU="1"),
            timeout=420)
    except subprocess.TimeoutExpired:
        return None, None, "timed out after 420s"
    try:
        summary = json.loads(proc.stdout)
    except ValueError:
        summary = None
    tail = "; ".join((proc.stderr or proc.stdout or "")
                     .strip().splitlines()[-3:])
    return proc.returncode, summary, tail


_FEDERATION_CHILD = """\
import jax
jax.config.update("jax_platforms", "cpu")
import sys
from brainiak_tpu.serve.federation.selfcheck import selfcheck
sys.exit(selfcheck())
"""


def check_federation(findings):
    """Serving-federation gate (SRV003), two halves.

    Process granularity: run ``serve service --replicas 2`` TWICE
    over one fresh temp AOT cache — fleet 1 may compile (and must
    persist what it compiled); fleet 2 is the warm-fleet contract:
    every request ok, ``aot.hits > 0``, ``retrace_total`` exactly
    0, and the router routed the mixed wave across BOTH replicas
    (every per-replica routed count > 0).

    Mesh granularity: the federation selfcheck child on the
    8-device CPU mesh — sharded over-budget serving parity vs the
    host reference, per-device residency accounting, router
    placement, and overload sheds carrying ``retry_after`` (every
    shed request still resolving exactly one ticket)."""
    import tempfile

    rel = _rel(SERVE_FIXTURE_DIR)
    for name in ("model.npz", "requests.npz"):
        if not os.path.exists(os.path.join(SERVE_FIXTURE_DIR,
                                           name)):
            findings.append(Finding(
                rel, 1, "SRV003",
                f"serve fixture missing: {rel}/{name}"))
            return
    with tempfile.TemporaryDirectory(prefix="srv003-aot-") as tmp:
        for attempt in (1, 2):
            rc, summary, tail = _run_federation_cli(tmp)
            if rc is None or summary is None or rc not in (0, 1):
                findings.append(Finding(
                    rel, 1, "SRV003",
                    f"federation CLI run {attempt} failed "
                    f"(rc={rc}): {tail or 'no JSON summary'}"))
                return
            if summary.get("n_errors"):
                findings.append(Finding(
                    rel, 1, "SRV003",
                    f"run {attempt}: {summary['n_errors']} "
                    "request(s) produced error records: "
                    f"{summary.get('errors_by_code')}"))
                return
    routed = (summary.get("federation") or {}).get("routed") or {}
    if len(routed) < 2 or not all(v > 0 for v in routed.values()):
        findings.append(Finding(
            rel, 1, "SRV003",
            f"router did not spread the wave across both replicas "
            f"(routed={routed})"))
    aot = summary.get("aot") or {}
    if not aot.get("hits"):
        findings.append(Finding(
            rel, 1, "SRV003",
            "second replica fleet over the warm shared AOT cache "
            f"reported no aot hits ({aot}): warm fleet start is "
            "broken"))
    if summary.get("retrace_total", 1) != 0:
        findings.append(Finding(
            rel, 1, "SRV003",
            "second replica fleet compiled "
            f"{summary.get('retrace_total'):.0f} serve program(s) "
            "despite the warm shared AOT cache: replicas 2..N "
            "must warm-start with zero serve retraces"))

    def classify(verdict):
        if not verdict.get("all_resolved", True):
            return ("federation selfcheck lost tickets under "
                    "overload: a shed request must still resolve "
                    "exactly one ticket")
        if verdict.get("n_shed", 0) == 0 \
                or not verdict.get("retry_after_ok", True):
            return ("overload produced no usable sheds "
                    f"(n_shed={verdict.get('n_shed')}, "
                    f"retry_after_ok="
                    f"{verdict.get('retry_after_ok')}): the "
                    "bounded-ingress shed path is broken")
        routed = verdict.get("routed") or {}
        if routed and not all(v > 0 for v in routed.values()):
            return (f"router starved a replica (routed={routed})")
        if not verdict.get("per_device_ok", True):
            return ("per-device residency accounting did not "
                    "charge every mesh device within budget: "
                    f"{verdict.get('per_device')}")
        return (f"sharded-serving parity failure: max_err="
                f"{verdict.get('max_err')} over tol="
                f"{verdict.get('tol')} "
                f"(n_devices={verdict.get('n_devices')})")

    _run_selfcheck_gate(
        findings, _FEDERATION_CHILD, "SRV003",
        _rel(os.path.join(REPO, "brainiak_tpu", "serve",
                          "federation", "selfcheck.py")),
        "federation", classify)


# -- elastic-fleet gate -----------------------------------------------

_FLEET_CHILD = """\
import jax
jax.config.update("jax_platforms", "cpu")
import sys
from brainiak_tpu.serve.federation.fleet_selfcheck import selfcheck
sys.exit(selfcheck())
"""


def check_fleet(findings):
    """Elastic-fleet chaos gate (SRV004): the fleet selfcheck child
    on the 8-device CPU mesh runs one deterministic chaos soak —
    fmrisim heavy-tailed traffic triples mid-run while replica
    ``r1`` is degraded by an injected ``slow_replica`` fault and
    killed by an injected ``replica_crash`` fault with a wave still
    queued in its ingress.  Verified, in failure-class order:

    - **lost tickets** — every submitted request resolves exactly
      one ticket (``delivered`` / ``shed_overload`` / typed
      ``replica_lost``), never silence;
    - **failover** — the supervisor declared the killed replica
      dead and the router re-placed its stranded work onto
      survivors, with the survivor actually routed;
    - **scale-up retraces** — the surge grew the fleet and the
      mid-run joiners served off the shared AOT cache with ZERO
      new serve programs (classified generically by the selfcheck
      harness, like every gate)."""

    def classify(verdict):
        if not verdict.get("all_resolved", True):
            return (f"fleet chaos soak LOST "
                    f"{verdict.get('n_unresolved')} ticket(s): a "
                    "request on a killed replica must still "
                    "resolve exactly one ticket (delivered, shed, "
                    "or a typed replica_lost record) — silent "
                    "loss is the invariant violation "
                    f"(by_code={verdict.get('by_code')})")
        if not verdict.get("failover_ok", True) \
                or not verdict.get("survivor_routed_ok", True):
            return ("replica death did not fail over to "
                    "survivors: crash_fired="
                    f"{verdict.get('crash_fired')}, failover="
                    f"{verdict.get('failover')}, routed="
                    f"{verdict.get('routed')}")
        if not verdict.get("degraded_seen", True):
            return ("the stalled replica was never marked "
                    "degraded: the supervisor's slow-replica "
                    "hysteresis is broken (states="
                    f"{verdict.get('states')})")
        if not verdict.get("scale_up_ok", True):
            return ("the mid-run traffic surge did not scale the "
                    "fleet up (or the joiners served nothing): "
                    f"scaled={verdict.get('scaled_replicas')}, "
                    f"n_scaled_up_served="
                    f"{verdict.get('n_scaled_up_served')}")
        return ("fleet chaos soak failed: "
                f"warm_retraces={verdict.get('warm_retraces')}, "
                f"final_retraces={verdict.get('final_retraces')}, "
                f"by_code={verdict.get('by_code')}")

    _run_selfcheck_gate(
        findings, _FLEET_CHILD, "SRV004",
        _rel(os.path.join(REPO, "brainiak_tpu", "serve",
                          "federation", "fleet_selfcheck.py")),
        "fleet", classify)


# -- selfcheck-child gates (distla, encoding) -------------------------
#
# Shared harness: run a module selfcheck in a child pinned to an
# 8-device CPU mesh (platform pinned IN-PROCESS by the child code,
# not the JAX_PLATFORMS env var alone, which can hang on a wedged
# tunnel PJRT plugin — docs/performance.md rule 4; the timeout stays
# as a backstop), parse its JSON verdict, and classify failures.

def _run_selfcheck_gate(findings, child_src, code, rel, label,
                        classify):
    """One selfcheck-child gate run.  ``classify(verdict)`` maps a
    failed (ok=false) verdict to a finding message; retrace
    instability (a repeat call rebuilt a program — the
    no-per-call-retrace contract, jaxlint JX001's runtime twin) is
    classified here, identically for every gate."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", child_src],
            capture_output=True, text=True, cwd=REPO, env=env,
            timeout=420)
    except subprocess.TimeoutExpired:
        findings.append(Finding(
            rel, 1, code,
            f"{label} selfcheck timed out after 420s (hung backend "
            "init?)"))
        return
    try:
        verdict = json.loads(proc.stdout)
    except ValueError:
        verdict = None
    if verdict is None or proc.returncode not in (0, 1):
        tail = (proc.stderr or proc.stdout or "").strip()
        tail = "; ".join(tail.splitlines()[-3:])
        findings.append(Finding(
            rel, 1, code,
            f"{label} selfcheck failed (rc={proc.returncode}): "
            f"{tail or 'no JSON verdict'}"))
        return
    if verdict.get("ok"):
        return
    retraces = {site: count for site, count
                in verdict.get("retraces", {}).items()
                if count > 1}
    if retraces:
        findings.append(Finding(
            rel, 1, code,
            f"{label} programs rebuilt on repeat calls: "
            + ", ".join(f"{site}={count:.0f}"
                        for site, count in sorted(
                            retraces.items()))))
    else:
        findings.append(Finding(rel, 1, code, classify(verdict)))


_DISTLA_CHILD = """\
import jax
jax.config.update("jax_platforms", "cpu")
import sys
from brainiak_tpu.ops.distla import selfcheck
sys.exit(selfcheck())
"""


def check_distla(findings):
    """Distla gate (DLA001): smoke-run the pod-scale linear algebra
    selfcheck (``brainiak_tpu.ops.distla.selfcheck``) on the
    8-device CPU mesh: the SUMMA Gram (even and uneven splits), the
    checkpointable panel Gram, and the sharded batched solves, twice
    each against NumPy references, plus the retrace-stability
    contract (``retrace_total{site=distla.*}`` stays at 1 across
    repeat calls)."""

    def classify(verdict):
        return (f"distla parity failure: max_err="
                f"{verdict.get('max_err')} over tol="
                f"{verdict.get('tol')} "
                f"(n_shards={verdict.get('n_shards')})")

    _run_selfcheck_gate(
        findings, _DISTLA_CHILD, "DLA001",
        _rel(os.path.join(REPO, "brainiak_tpu", "ops", "distla.py")),
        "distla", classify)


# -- encoding gate ----------------------------------------------------

_ENCODING_CHILD = """\
import jax
jax.config.update("jax_platforms", "cpu")
import sys
from brainiak_tpu.encoding import selfcheck
sys.exit(selfcheck())
"""


def check_encoding(findings):
    """Encoding gate (ENC001): smoke-run the encoding-tier selfcheck
    (``brainiak_tpu.encoding.selfcheck``) on the 8-device CPU mesh:
    per-voxel prediction parity against sklearn Ridge at the
    CV-selected lambdas, the sharded raw-product Gram over the mesh
    ring, a banded fit, and the retrace-stability contract — a
    repeat fit must not rebuild any program (the lambda sweep is ONE
    jitted program, not one per lambda)."""

    def classify(verdict):
        if not verdict.get("banded_finite", True):
            return "banded encoding fit produced non-finite scores"
        if not verdict.get("sites_present", True):
            return ("encoding selfcheck missing expected "
                    "retrace sites (a program builder no longer "
                    "routes through counted_cache?): saw "
                    + (", ".join(sorted(verdict.get("retraces", {})))
                       or "none"))
        return (f"encoding sklearn-parity failure: max_err="
                f"{verdict.get('max_err')} over tol="
                f"{verdict.get('tol')}")

    _run_selfcheck_gate(
        findings, _ENCODING_CHILD, "ENC001",
        _rel(os.path.join(REPO, "brainiak_tpu", "encoding",
                          "ridge.py")),
        "encoding", classify)


# -- kernels gate -----------------------------------------------------

_KERNELS_CHILD = """\
import jax
jax.config.update("jax_platforms", "cpu")
import sys
from brainiak_tpu.ops.kernels import selfcheck
sys.exit(selfcheck())
"""


def check_kernels(findings):
    """Fused-kernels gate (KRN001): smoke-run the fused-kernel
    parity selfcheck (``brainiak_tpu.ops.kernels.selfcheck``) on the
    8-device CPU mesh: single-scan HMM forward-backward vs the
    two-scan reference (incl. the masked-log edge cases), the fused
    rotate-multiply-accumulate SUMMA ring step vs the unfused
    formulation and a NumPy dense Gram (even/uneven splits, NaN
    propagation), MTTKRP factor reconstruction vs the naive
    broadcast einsum, and the device epoch norm vs its NumPy
    fallback — everything twice, with the retrace-stability contract
    (the repeat pass must rebuild no fused-site program)."""

    def classify(verdict):
        if verdict.get("mask_mismatch"):
            return ("fused kernels changed -inf/NaN masks vs the "
                    "references: "
                    + ", ".join(verdict["mask_mismatch"]))
        return (f"fused-kernel parity failure: max_err="
                f"{verdict.get('max_err')} over tol="
                f"{verdict.get('tol')} "
                f"(n_shards={verdict.get('n_shards')})")

    _run_selfcheck_gate(
        findings, _KERNELS_CHILD, "KRN001",
        _rel(os.path.join(REPO, "brainiak_tpu", "ops", "kernels",
                          "selfcheck.py")),
        "kernels", classify)


# -- data gate --------------------------------------------------------

_DATA_CHILD = """\
import jax
jax.config.update("jax_platforms", "cpu")
import sys
from brainiak_tpu.data.selfcheck import selfcheck
sys.exit(selfcheck())
"""


def check_data(findings):
    """Streaming-data-plane gate (DAT001): smoke-run the out-of-core
    selfcheck (``brainiak_tpu.data.selfcheck``) on the 8-device CPU
    mesh: streamed-vs-in-memory SRM/DetSRM parity over a real
    on-disk SubjectStore (mesh-sharded shards, a short masked final
    shard), resume-at-shard-round after an injected preemption, and
    the retrace-stability contract — repeat shard rounds (and a
    repeat fit) must keep every ``data.*``/``srm.*`` streamed
    program at <= 1 trace."""

    def classify(verdict):
        if not verdict.get("resume_ok", True):
            return ("streamed fit did not resume at the last "
                    "completed shard round after the injected "
                    "preemption (or the preempt fault never fired)")
        return (f"streamed-vs-in-memory SRM parity failure: "
                f"max_err={verdict.get('max_err')} over tol="
                f"{verdict.get('tol')}")

    _run_selfcheck_gate(
        findings, _DATA_CHILD, "DAT001",
        _rel(os.path.join(REPO, "brainiak_tpu", "data",
                          "selfcheck.py")),
        "data", classify)


# -- realtime gate ----------------------------------------------------

_REALTIME_CHILD = """\
import jax
jax.config.update("jax_platforms", "cpu")
import sys
from brainiak_tpu.realtime.selfcheck import selfcheck
sys.exit(selfcheck())
"""


def check_realtime(findings):
    """Closed-loop tier gate (RT001): smoke-run the realtime
    selfcheck (``brainiak_tpu.realtime.selfcheck``): online-vs-batch
    parity at every prefix (OnlineISC vs ``isc()``, incremental
    event segmentation's scaled forward row vs the fused batch
    forward pass), resume-mid-scan parity after an injected
    preemption, and the retrace-stability contract — repeat sessions
    (with a warm low-latency ServeService scoring hop) must keep
    every ``realtime.*`` step program at <= 1 trace."""

    def classify(verdict):
        if not verdict.get("resume_ok", True):
            return ("realtime session did not resume mid-scan with "
                    "parity after the injected preemption (or the "
                    "preempt fault never fired)")
        if not verdict.get("serve_ok", True):
            return ("realtime low-latency ServeService scoring hop "
                    "returned error/empty records")
        return (f"realtime online-vs-batch parity failure: "
                f"max_err={verdict.get('max_err')} over tol="
                f"{verdict.get('tol')}")

    _run_selfcheck_gate(
        findings, _REALTIME_CHILD, "RT001",
        _rel(os.path.join(REPO, "brainiak_tpu", "realtime",
                          "selfcheck.py")),
        "realtime", classify)


# -- stats gate -------------------------------------------------------

_STATS_CHILD = """\
import jax
jax.config.update("jax_platforms", "cpu")
import sys
from brainiak_tpu.stats.selfcheck import selfcheck
sys.exit(selfcheck())
"""


def check_stats(findings):
    """Resampling-statistics gate (STA001): smoke-run the stats
    selfcheck (``brainiak_tpu.stats.selfcheck``) on the 8-device CPU
    mesh: accumulator-counts-vs-materialized-null p-value parity
    (bit-for-bit), chunk invariance under a starved
    ``BRAINIAK_TPU_STATS_BUDGET_BYTES``, exact pooling of disjoint
    half-range runs round-tripped through BOTH wire formats
    (JSON/npz), resume-at-chunk after an injected preemption, and
    the retrace-stability contract — every counted ``stats.*``
    surrogate program stays at <= 1 trace across all of the above."""

    def classify(verdict):
        if not verdict.get("merge_ok", True):
            return ("pooled half-range null runs did not merge to "
                    "EXACTLY the full-run verdicts (wire-format or "
                    "accumulator merge drift)")
        if not verdict.get("resume_ok", True):
            return ("null run did not resume at the last completed "
                    "chunk with a bit-identical p-map after the "
                    "injected preemption (or the preempt fault "
                    "never fired)")
        return (f"null-engine p-value parity failure: max_err="
                f"{verdict.get('max_err')} over tol="
                f"{verdict.get('tol')} (accumulator counts vs "
                "materialized distribution, or chunk-size "
                "dependence)")

    _run_selfcheck_gate(
        findings, _STATS_CHILD, "STA001",
        _rel(os.path.join(REPO, "brainiak_tpu", "stats",
                          "selfcheck.py")),
        "stats", classify)


# -- jobs gate --------------------------------------------------------

_JOBS_CHILD = """\
import jax
jax.config.update("jax_platforms", "cpu")
import sys
from brainiak_tpu.jobs.selfcheck import selfcheck
sys.exit(selfcheck())
"""


def check_jobs(findings):
    """Fit-scheduler gate (JOB001): smoke-run the jobs selfcheck
    (``brainiak_tpu.jobs.selfcheck``) on the 8-device CPU mesh: two
    tenants submit mixed-priority SRM fits co-scheduled with a warm
    ServeService, one priority preemption is injected, and the
    verdict must show zero lost jobs (every job terminal ``done``),
    bit-exact park/resume parity against an unpreempted solo run,
    per-tenant fair-share deficits within tolerance (starvation
    freedom), and zero added ``serve.*`` retraces (the throughput
    fits must not evict the latency tier's compiled programs)."""

    def classify(verdict):
        lost = verdict.get("lost") or []
        if lost:
            return ("scheduler lost job(s) " + ", ".join(lost)
                    + ": submitted fits did not reach terminal "
                      "done (zombie/failed/cancelled records)")
        if not verdict.get("parity_ok", True):
            return ("preempted fit did not resume to bit-exact "
                    "parity with the unpreempted solo run (the "
                    "park/resume checkpoint contract drifted)")
        if not verdict.get("preempt_ok", True):
            return ("injected priority preemption never fired "
                    f"(n_preemptions="
                    f"{verdict.get('n_preemptions')}): the "
                    "high-priority arrival did not park the "
                    "running low-priority fit")
        if not verdict.get("fairshare_ok", True):
            return ("fair-share starvation: tenant deficit "
                    f"{verdict.get('max_deficit')} exceeds "
                    f"tolerance {verdict.get('fair_tol')} chunks "
                    "under equal weights and equal work")
        return ("co-scheduled serving regressed: "
                f"serve retrace delta="
                f"{verdict.get('serve_retrace_delta')} "
                f"(serve_ok={verdict.get('serve_ok')}) — fits must "
                "add zero serve.* retraces")

    _run_selfcheck_gate(
        findings, _JOBS_CHILD, "JOB001",
        _rel(os.path.join(REPO, "brainiak_tpu", "jobs",
                          "selfcheck.py")),
        "jobs", classify)


# -- external gate ----------------------------------------------------

def run_external(findings):
    """Run ruff/flake8 + mypy when available (full CI hosts).

    Each failing tool contributes one EXT001 finding carrying its
    output block."""
    ran = []
    if shutil.which("ruff"):
        ran.append("ruff")
        r = subprocess.run(["ruff", "check", REPO],
                           capture_output=True, text=True)
        if r.returncode:
            findings.append(Finding(
                ".", 1, "EXT001", "ruff: " + r.stdout.strip()))
    elif shutil.which("flake8"):
        ran.append("flake8")
        r = subprocess.run(
            ["flake8", os.path.join(REPO, "brainiak_tpu")],
            capture_output=True, text=True)
        if r.returncode:
            findings.append(Finding(
                ".", 1, "EXT001", "flake8: " + r.stdout.strip()))
    if shutil.which("mypy"):
        ran.append("mypy")
        r = subprocess.run(
            ["mypy", os.path.join(REPO, "brainiak_tpu")],
            capture_output=True, text=True)
        if r.returncode:
            findings.append(Finding(
                ".", 1, "EXT001", "mypy: " + r.stdout.strip()))
    return ran


# -- driver -----------------------------------------------------------

# -- jaxlint-ir gate --------------------------------------------------

#: Minimum traced fraction of the static builder census the gate
#: accepts; below this every skipped site's reason is surfaced.
_IR_MIN_COVERAGE = 0.90


def check_jaxlint_ir(findings, ir_stale):
    """jaxlint-IR gate (JPR001): the traced-IR audit in a child.

    Runs ``python -m brainiak_tpu.analysis.cli --ir --format=json``
    pinned to an 8-device CPU backend (the audit traces collective
    programs against a real mesh) and folds the verdict in:

    * surviving JP3xx findings are re-emitted under their OWN rule
      codes — a JP301 dtype leak and a JP302 donation break stay
      distinguishable in gate output and SARIF;
    * builder coverage below ``_IR_MIN_COVERAGE`` of the static
      census, a crashed or hung child, or malformed JSON raise a
      gate-level JPR001 with the skip reasons attached;
    * the audit's stale-baseline entries (already scoped to the JP
      rules it ran) are appended to ``ir_stale`` so jaxlint-ir
      participates in the shared staleness report.
    """
    rel = _rel(os.path.join(REPO, "brainiak_tpu", "analysis", "ir",
                            "audit.py"))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    cmd = [sys.executable, "-m", "brainiak_tpu.analysis.cli",
           "--ir", "--format=json"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              cwd=REPO, env=env, timeout=420)
    except subprocess.TimeoutExpired:
        findings.append(Finding(
            rel, 1, "JPR001",
            "jaxlint-ir audit timed out after 420s (hung backend "
            "init?)"))
        return
    try:
        verdict = json.loads(proc.stdout)
    except ValueError:
        verdict = None
    if verdict is None or proc.returncode not in (0, 1):
        tail = (proc.stderr or proc.stdout or "").strip()
        tail = "; ".join(tail.splitlines()[-3:])
        findings.append(Finding(
            rel, 1, "JPR001",
            f"jaxlint-ir audit failed (rc={proc.returncode}): "
            f"{tail or 'no JSON verdict'}"))
        return
    for item in verdict.get("findings", []):
        findings.append(Finding(
            item["path"], item["line"], item["code"],
            item["message"], item.get("snippet", "")))
    coverage = verdict.get("coverage", 0.0)
    if coverage < _IR_MIN_COVERAGE:
        skipped = verdict.get("skipped", [])
        detail = "; ".join(f"{s['site']}: {s['reason']}"
                           for s in skipped[:5])
        if len(skipped) > 5:
            detail += f"; … {len(skipped) - 5} more"
        findings.append(Finding(
            rel, 1, "JPR001",
            f"builder coverage {coverage:.0%} is below the "
            f"{_IR_MIN_COVERAGE:.0%} contract — every builder "
            f"needs a canonical trace signature or an explicit "
            f"fix: {detail or 'no skip reasons reported'}"))
    ir_stale.extend(verdict.get("stale_baseline", []))


def _jaxlint_scope(config):
    """(include_abs_paths, exclude_prefixes) for the jaxlint gate."""
    include = [os.path.abspath(p) for p in config.include_paths()]
    prefixes = tuple(e.rstrip("/") + "/" for e in config.exclude)
    return include, prefixes


def _in_scope(path, include, prefixes):
    ap = os.path.abspath(path)
    if not any(ap == base or ap.startswith(base + os.sep)
               for base in include):
        return False
    rel = _rel(path)
    return not (rel + "/").startswith(prefixes) \
        and not rel.startswith(prefixes)


def _apply_rules(ctx, rules, findings):
    """File-rule application over one built context (the CHK001
    syntax finding is emitted once by the walk, not per group)."""
    for rule in rules:
        if rule.needs_tree and ctx.tree is None:
            continue
        for finding in rule.check(ctx):
            if not ctx.suppressed(finding, rule.pragma):
                findings.append(finding)


def run_gates(only=None):
    """Run the selected gates; returns a result dict.

    ``only``: iterable of gate names (default: all).  One file walk
    (and one parse per file) feeds the stdlib file rules, the
    jaxlint file rules, and the jaxlint-deep project analysis;
    repo-level gates run after.  Every gate's wall time is recorded
    in ``gate_seconds``.
    """
    selected = set(only or GATES)
    unknown = selected - set(GATES)
    if unknown:
        raise SystemExit(
            f"run_checks: unknown gate(s): {', '.join(sorted(unknown))}"
            f" (choose from {', '.join(GATES)})")
    findings = []
    stale = []
    ran = []
    gate_seconds = {gate: 0.0 for gate in sorted(selected)}

    def timed(gate, fn, *args):
        t0 = time.perf_counter()
        out = fn(*args)
        gate_seconds[gate] += time.perf_counter() - t0
        return out

    if "external" in selected:
        ran = timed("external", run_external, findings)

    config = load_config(REPO, os.path.join(REPO, "pyproject.toml"))
    known = {r.code: r for r in ALL_RULES}
    deep_codes = {r.code for r in DEEP_RULES}
    if "jaxlint" in selected or "jaxlint-deep" in selected:
        bad = [c for c in config.select if c not in known]
        if bad:
            raise SystemExit(
                "run_checks: unknown jaxlint rule code(s) in "
                f"[tool.jaxlint] select: {', '.join(bad)} "
                f"(known: {', '.join(sorted(known))})")
    std_rules = ([LineLength(), UnusedImports()]
                 if "stdlib" in selected else [])
    jax_rules = []
    deep_rules = []
    baseline = None
    if "jaxlint" in selected:
        jax_rules = [known[c]() for c in config.select
                     if c not in deep_codes]
    if "jaxlint-deep" in selected:
        deep_rules = [known[c]() for c in config.select
                      if c in deep_codes]
    if jax_rules or deep_rules:
        bl_path = config.baseline_path()
        if bl_path:
            baseline = Baseline.load(bl_path)
    include, prefixes = _jaxlint_scope(config)

    n = 0
    contexts = {}
    if std_rules or jax_rules or deep_rules:
        parse_gate = "stdlib" if std_rules else "jaxlint" \
            if jax_rules else "jaxlint-deep"
        for path in python_sources():
            in_scope = _in_scope(path, include, prefixes)
            if not (std_rules or (in_scope
                                  and (jax_rules or deep_rules))):
                continue
            n += 1
            ctx = timed(parse_gate, build_context, path, REPO)
            if ctx.parse_error is not None:
                exc = ctx.parse_error
                findings.append(Finding(
                    ctx.relpath, exc.lineno or 1, "CHK001",
                    f"syntax error: {exc.msg}",
                    ctx.src_line(exc.lineno or 1)))
            if std_rules:
                timed("stdlib", _apply_rules, ctx, std_rules,
                      findings)
            if in_scope:
                if jax_rules:
                    timed("jaxlint", _apply_rules, ctx, jax_rules,
                          findings)
                if deep_rules:
                    contexts[ctx.relpath] = ctx
    if deep_rules:
        findings.extend(timed("jaxlint-deep", run_project_rules,
                              contexts, deep_rules))

    ir_stale = []
    if "jaxlint-ir" in selected:
        timed("jaxlint-ir", check_jaxlint_ir, findings, ir_stale)

    if "doc-defaults" in selected:
        timed("doc-defaults", check_doc_defaults, findings)
    if "resilient-fits" in selected:
        timed("resilient-fits", check_resilient_fits, findings)
    if "obs" in selected:
        timed("obs", check_obs, findings)
    if "obs-live" in selected:
        timed("obs-live", check_obs_live, findings)
    if "obs-fit" in selected:
        timed("obs-fit", check_obs_fit, findings)
    if "regress" in selected:
        timed("regress", check_regress, findings)
    if "serve" in selected:
        timed("serve", check_serve, findings)
    if "service" in selected:
        timed("service", check_service, findings)
    if "federation" in selected:
        timed("federation", check_federation, findings)
    if "fleet" in selected:
        timed("fleet", check_fleet, findings)
    if "distla" in selected:
        timed("distla", check_distla, findings)
    if "encoding" in selected:
        timed("encoding", check_encoding, findings)
    if "kernels" in selected:
        timed("kernels", check_kernels, findings)
    if "data" in selected:
        timed("data", check_data, findings)
    if "realtime" in selected:
        timed("realtime", check_realtime, findings)
    if "stats" in selected:
        timed("stats", check_stats, findings)
    if "jobs" in selected:
        timed("jobs", check_jobs, findings)

    if baseline is not None:
        findings, stale = baseline.filter(findings)
        # JP-rule entries are judged by the jaxlint-ir child (which
        # applies the same baseline to the traced findings), never
        # by the AST families — they always look unmatched here.
        stale = [e for e in stale
                 if not str(e.get("rule", "")).startswith("JP")]
        if not {"jaxlint", "jaxlint-deep"} <= selected:
            # a partial rule run cannot judge staleness: entries
            # for the unselected family would all look unmatched
            stale = []
    if "jaxlint-ir" in selected:
        stale = list(stale) + ir_stale
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    label = "+".join(
        (["stdlib"] if "stdlib" in selected else []) + ran
        + [g for g in ("doc-defaults", "resilient-fits", "jaxlint",
                       "jaxlint-deep", "jaxlint-ir", "obs",
                       "obs-live", "obs-fit", "regress", "serve",
                       "service", "federation", "fleet", "distla",
                       "encoding", "kernels", "data", "realtime",
                       "stats", "jobs")
           if g in selected])
    return {
        "ok": not findings,
        "label": label or "none",
        "files": n,
        "gates": sorted(selected),
        "gate_seconds": {g: round(s, 3)
                         for g, s in gate_seconds.items()},
        "findings": findings,
        "stale_baseline": stale,
    }


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="run_checks",
        description="repo static-analysis gates "
                    "(see docs/static_analysis.md)")
    parser.add_argument(
        "--only", metavar="GATE[,GATE...]",
        help=f"run a subset of gates ({', '.join(GATES)})")
    parser.add_argument("--format",
                        choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--list", action="store_true",
                        help="list gate names and exit")
    args = parser.parse_args(argv)
    if args.list:
        for gate in GATES:
            print(gate)
        return 0
    only = ([g.strip() for g in args.only.split(",")]
            if args.only else None)
    result = run_gates(only)
    if args.format == "sarif":
        from brainiak_tpu.analysis import IR_RULES
        rules_by_code = {r.code: r
                         for r in (*ALL_RULES, *IR_RULES)}
        print(json.dumps(to_sarif(
            result["findings"], rules_by_code,
            tool_name="run_checks"), indent=2))
        return 0 if result["ok"] else 1
    if args.format == "json":
        payload = dict(result)
        payload["findings"] = [f.to_dict()
                               for f in result["findings"]]
        print(json.dumps(payload, indent=2))
        return 0 if result["ok"] else 1
    findings = result["findings"]
    for entry in result["stale_baseline"]:
        print(f"warning: stale jaxlint baseline entry "
              f"{entry['rule']} {entry['path']}; delete it")
    if findings:
        print(f"run_checks [{result['label']}]: "
              f"{len(findings)} finding(s) over "
              f"{result['files']} files")
        for item in findings:
            print(" ", item)
        return 1
    print(f"run_checks [{result['label']}]: OK "
          f"({result['files']} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
