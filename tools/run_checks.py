#!/usr/bin/env python3
"""CI-grade static analysis gate.

The analog of the reference's ``run-checks.sh:19-24`` (flake8 + mypy):
runs ruff/flake8 and mypy when they are installed, and ALWAYS runs a
hermetic stdlib fallback so the gate is enforced even in environments
without the linters:

1. byte-compilation of every Python source (syntax gate);
2. AST-based unused-import detection (pyflakes F401 analog);
3. the 79-column line limit (pycodestyle E501 analog).

``# noqa`` on a line suppresses findings for that line.  Exits non-zero
on any finding; ``tests/test_static_checks.py`` wires this into the
pytest suite so the gate runs with the tests.
"""

import ast
import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SKIP_DIRS = {".git", "__pycache__", ".claude", "build", "dist",
             ".pytest_cache", "node_modules", ".venv", "venv", ".tox",
             ".eggs", ".ruff_cache", ".mypy_cache"}
MAX_COLS = 79


def python_sources():
    for root, dirs, files in os.walk(REPO):
        dirs[:] = [d for d in dirs if d not in SKIP_DIRS]
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


def _noqa_lines(source_lines):
    return {i for i, line in enumerate(source_lines, 1)
            if "# noqa" in line}


def check_syntax(path, source, findings):
    try:
        compile(source, path, "exec")
    except SyntaxError as exc:
        findings.append(f"{path}:{exc.lineno}: syntax error: {exc.msg}")


def check_line_length(path, lines, noqa, findings):
    for i, line in enumerate(lines, 1):
        if i in noqa:
            continue
        n = len(line.rstrip("\n"))
        if n > MAX_COLS:
            findings.append(
                f"{path}:{i}: line too long ({n} > {MAX_COLS})")


class _ImportCollector(ast.NodeVisitor):
    """Record imported bindings and every referenced identifier."""

    def __init__(self):
        self.imports = []     # (lineno, bound_name)
        self.used = set()

    def visit_Import(self, node):
        for alias in node.names:
            bound = alias.asname or alias.name.split(".")[0]
            self.imports.append((node.lineno, bound))

    def visit_ImportFrom(self, node):
        for alias in node.names:
            if alias.name == "*":
                continue
            bound = alias.asname or alias.name
            self.imports.append((node.lineno, bound))

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


def check_unused_imports(path, tree, noqa, findings):
    # __init__.py re-export lists are conventionally exempt (F401 in
    # per-file-ignores of every major config).
    if os.path.basename(path) == "__init__.py":
        return
    col = _ImportCollector()
    col.visit(tree)
    # names referenced via __all__ strings count as used
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            col.used.add(node.value)
    for lineno, name in col.imports:
        if lineno in noqa or name.startswith("_"):
            continue
        if name not in col.used:
            findings.append(
                f"{path}:{lineno}: '{name}' imported but unused")


def _code_defaults():
    """(global, by_owner): parameter name -> set of repr'd default
    values across every function/method signature in the package, plus
    the same map scoped per owning symbol — the function name, and for
    methods also the enclosing class name (so docs can anchor a claim
    to either ``fit`` or ``SRM``)."""
    defaults = {}
    by_owner = {}

    def record(owner_names, param, value):
        defaults.setdefault(param, set()).add(value)
        for owner in owner_names:
            by_owner.setdefault(owner, {}).setdefault(
                param, set()).add(value)

    def visit_fn(node, owners):
        args = node.args
        pos = args.posonlyargs + args.args
        for arg, dflt in zip(pos[len(pos) - len(args.defaults):],
                             args.defaults):
            if isinstance(dflt, ast.Constant):
                record(owners, arg.arg, repr(dflt.value))
        for arg, dflt in zip(args.kwonlyargs, args.kw_defaults):
            if dflt is not None and isinstance(dflt, ast.Constant):
                record(owners, arg.arg, repr(dflt.value))

    pkg = os.path.join(REPO, "brainiak_tpu")
    for root, dirs, files in os.walk(pkg):
        dirs[:] = [d for d in dirs if d not in SKIP_DIRS]
        for f in files:
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            with open(path, encoding="utf-8") as fh:
                try:
                    tree = ast.parse(fh.read(), filename=path)
                except SyntaxError:
                    continue
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            visit_fn(sub, (node.name, sub.name))
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    visit_fn(node, (node.name,))
    return defaults, by_owner


def check_doc_defaults(findings):
    """Docs-vs-code default drift gate: every ``**`name=`** (default X)``
    claim in docs/*.md must match at least one signature default for a
    parameter of that name somewhere in the package (the round-2
    ``svm_iters`` 20-vs-10 drift is the motivating case)."""
    import re
    pattern = re.compile(
        r"`(?P<name>[A-Za-z_][A-Za-z0-9_]*)=?`\*{0,2}\s*"
        r"\(\s*(?:`)?default(?:s to)?[\s:`]+(?P<value>[^)`\s,;]+)")
    docs_dir = os.path.join(REPO, "docs")
    if not os.path.isdir(docs_dir):
        return
    token_re = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")
    defaults = by_owner = None
    for root, dirs, files in os.walk(docs_dir):
        dirs[:] = [d for d in dirs if d not in SKIP_DIRS]
        for f in sorted(files):
            if not f.endswith(".md"):
                continue
            path = os.path.join(root, f)
            heading = ""
            in_fence = False
            with open(path, encoding="utf-8") as fh:
                for i, line in enumerate(fh, 1):
                    if line.lstrip().startswith("```"):
                        in_fence = not in_fence
                    # markdown heading, not a comment inside a fenced
                    # code example
                    if not in_fence and re.match(r"^#{1,6} ", line):
                        heading = line
                    if "# noqa" in line:
                        continue
                    for m in pattern.finditer(line):
                        if defaults is None:
                            defaults, by_owner = _code_defaults()
                        name = m.group("name")
                        doc_val = m.group("value").strip("'\"")
                        code_vals = defaults.get(name)
                        if not code_vals:
                            continue  # not a signature param (knob alias)
                        # Scope to the owning symbol when the line or
                        # the nearest heading names one that defines
                        # this parameter — a claim must not be
                        # "confirmed" by an unrelated function's
                        # coincidentally matching default.
                        owners = [t for t in token_re.findall(
                                      line + " " + heading)
                                  if t != name and name in
                                  by_owner.get(t, ())]
                        if owners:
                            code_vals = set().union(
                                *(by_owner[o][name] for o in owners))
                        elif len(code_vals) > 1:
                            findings.append(
                                f"{path}:{i}: documented default "
                                f"`{name}={doc_val}` is ambiguous — "
                                f"{len(code_vals)} distinct signature "
                                f"defaults ({', '.join(sorted(code_vals))})"
                                " exist; name the owning function/class"
                                " on the line or heading, or # noqa")
                            continue
                        normalized = {v.strip("'\"") for v in code_vals}
                        if doc_val not in normalized:
                            opts = ", ".join(sorted(code_vals))
                            findings.append(
                                f"{path}:{i}: documented default "
                                f"`{name}={doc_val}` does not match "
                                f"a signature default of "
                                f"{'/'.join(owners) or name} ({opts})")


# Public iterative estimators required to honor the resilience
# contract: fit() accepts checkpoint_dir, and the module either drives
# its loop through resilience.run_resilient_loop (which applies the
# non-finite guard) or delegates by forwarding checkpoint_dir= to
# another estimator's fit (FastSRM -> reduced-space DetSRM).
RESILIENT_FITS = {
    "brainiak_tpu/funcalign/srm.py": ("SRM", "DetSRM"),
    "brainiak_tpu/funcalign/rsrm.py": ("RSRM",),
    "brainiak_tpu/funcalign/fastsrm.py": ("FastSRM",),
    "brainiak_tpu/factoranalysis/tfa.py": ("TFA",),
    "brainiak_tpu/factoranalysis/htfa.py": ("HTFA",),
    "brainiak_tpu/reprsimil/brsa.py": ("BRSA",),
    "brainiak_tpu/eventseg/event.py": ("EventSegment",),
}


def check_resilient_fits(findings):
    """Static resilience gate: every public iterative ``fit`` must
    accept ``checkpoint_dir`` and run its loop under the non-finite
    guard (via ``run_resilient_loop``) or forward the contract to a
    guarded estimator."""
    for relpath, classes in sorted(RESILIENT_FITS.items()):
        path = os.path.join(REPO, *relpath.split("/"))
        try:
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            findings.append(f"{path}: unparseable (resilience gate)")
            continue
        uses_driver = any(
            (isinstance(n, ast.Name) and n.id == "run_resilient_loop")
            or (isinstance(n, ast.Attribute)
                and n.attr == "run_resilient_loop")
            for n in ast.walk(tree))
        delegates = any(
            isinstance(n, ast.Call) and any(
                kw.arg == "checkpoint_dir" for kw in n.keywords)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "fit"
            for n in ast.walk(tree))
        if not (uses_driver or delegates):
            findings.append(
                f"{path}: no run_resilient_loop use (or checkpointed "
                "fit delegation); iterative fits must run under the "
                "resilience guard")
        class_fits = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef) \
                            and sub.name == "fit":
                        class_fits[node.name] = sub
        for cls in classes:
            fit = class_fits.get(cls)
            if fit is None:
                findings.append(
                    f"{path}: class {cls} defines no fit() "
                    "(resilience gate)")
                continue
            args = [a.arg for a in (fit.args.posonlyargs + fit.args.args
                                    + fit.args.kwonlyargs)]
            for required in ("checkpoint_dir", "checkpoint_every"):
                if required not in args:
                    findings.append(
                        f"{path}:{fit.lineno}: {cls}.fit() does not "
                        f"accept {required}= (resilience contract)")


def run_external(findings):
    """Run ruff/flake8 + mypy when available (full CI environments)."""
    ran = []
    if shutil.which("ruff"):
        ran.append("ruff")
        r = subprocess.run(["ruff", "check", REPO],
                           capture_output=True, text=True)
        if r.returncode:
            findings.append(r.stdout.strip())
    elif shutil.which("flake8"):
        ran.append("flake8")
        r = subprocess.run(
            ["flake8", os.path.join(REPO, "brainiak_tpu")],
            capture_output=True, text=True)
        if r.returncode:
            findings.append(r.stdout.strip())
    if shutil.which("mypy"):
        ran.append("mypy")
        r = subprocess.run(
            ["mypy", os.path.join(REPO, "brainiak_tpu")],
            capture_output=True, text=True)
        if r.returncode:
            findings.append(r.stdout.strip())
    return ran


def main(argv=None):
    findings = []
    ran = run_external(findings)
    check_doc_defaults(findings)
    check_resilient_fits(findings)
    n = 0
    for path in python_sources():
        n += 1
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
        noqa = _noqa_lines(lines)
        source = "".join(lines)
        check_syntax(path, source, findings)
        check_line_length(path, lines, noqa, findings)
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue  # already reported by check_syntax
        check_unused_imports(path, tree, noqa, findings)
    label = "+".join(["stdlib"] + ran)
    if findings:
        print(f"run_checks [{label}]: {len(findings)} finding(s) "
              f"over {n} files")
        for item in findings:
            print(" ", item)
        return 1
    print(f"run_checks [{label}]: OK ({n} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
