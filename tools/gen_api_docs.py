#!/usr/bin/env python3
"""Generate the markdown API reference under docs/api/.

The analog of the reference's Sphinx autodoc pipeline (docs/api.rst +
conf.py): walks every public module of :mod:`brainiak_tpu`, introspects
the public surface (``__all__`` when declared, else non-underscore
top-level names defined in the module), and writes one markdown page
per subpackage with signatures and full docstrings.

Run from the repo root:  python tools/gen_api_docs.py
The output is committed, so the docs stay greppable/browsable without a
docs build step; CI-style freshness is enforced by re-running this
script (it is deterministic).
"""

import importlib
import inspect
import os
import pkgutil
import re
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "docs", "api")

# Modules whose import would initialize a heavyweight backend get their
# docstrings read but members introspected lazily like any other; jax
# imports are fine on CPU.
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def public_names(mod):
    if hasattr(mod, "__all__"):
        return list(mod.__all__)
    names = []
    for name, obj in vars(mod).items():
        if name.startswith("_"):
            continue
        if inspect.ismodule(obj):
            continue
        defined_in = getattr(obj, "__module__", None)
        if defined_in != mod.__name__:
            continue
        names.append(name)
    return sorted(names)


_SET_REPR_RE = re.compile(r"\{('[^'{}]*'(?:, '[^'{}]*')+)\}")


def _sort_set_reprs(text):
    """Sort the elements of string-set reprs: set iteration order is
    hash-randomized per process, so an unsorted repr (e.g. a
    ``skip_dirs={...}`` default) churns on every regeneration.

    Elements are re-extracted as quoted units (not split on ', '),
    so a string member that itself contains a comma survives."""
    return _SET_REPR_RE.sub(
        lambda m: "{" + ", ".join(
            sorted(re.findall(r"'[^'{}]*'", m.group(1)))) + "}",
        text)


def _mask_addrs(text):
    """Strip run-specific id() addresses from reprs (functions, bound
    methods, object instances) so regeneration is deterministic."""
    return re.sub(r"<([^<>]*?) at 0x[\da-f]+>", r"<\1>", text)


def fmt_signature(name, obj):
    try:
        sig = str(inspect.signature(obj))
    except (ValueError, TypeError):
        sig = "(...)"
    # default-value reprs embed addresses; sort set-literal reprs
    # too — both for deterministic regeneration
    return f"{name}{_sort_set_reprs(_mask_addrs(sig))}"


def fmt_doc(obj, indent=""):
    doc = inspect.getdoc(obj)
    if not doc:
        return f"{indent}*(undocumented)*\n"
    return "\n".join(indent + line for line in doc.splitlines()) + "\n"


def emit_member(lines, name, obj):
    if inspect.isclass(obj):
        lines.append(f"### class `{fmt_signature(name, obj)}`\n")
        lines.append(fmt_doc(obj))
        for mname, meth in sorted(vars(obj).items()):
            # public methods, plus __init__ when it carries its own
            # parameter documentation
            if mname.startswith("_") and mname != "__init__":
                continue
            if not (inspect.isfunction(meth)
                    or isinstance(meth, (staticmethod, classmethod,
                                         property))):
                continue
            if isinstance(meth, property):
                lines.append(f"- **property `{mname}`** — "
                             + (inspect.getdoc(meth) or "").split("\n")[0])
                continue
            if isinstance(meth, (staticmethod, classmethod)):
                meth = meth.__func__
            if not inspect.getdoc(meth):
                continue
            lines.append(f"#### `{fmt_signature(mname, meth)}`\n")
            lines.append(fmt_doc(meth))
    elif callable(obj):
        lines.append(f"### `{fmt_signature(name, obj)}`\n")
        lines.append(fmt_doc(obj))
    else:
        lines.append(f"### `{name}`\n")
        lines.append(
            f"Constant: `{_sort_set_reprs(_mask_addrs(repr(obj)))}`\n")


def emit_module(lines, modname):
    try:
        mod = importlib.import_module(modname)
    except Exception as exc:  # pragma: no cover - import guard
        lines.append(f"## `{modname}`\n\n*(import failed: {exc})*\n")
        return
    lines.append(f"## `{modname}`\n")
    doc = inspect.getdoc(mod)
    if doc:
        lines.append(doc + "\n")
    for name in public_names(mod):
        obj = getattr(mod, name, None)
        if obj is None or inspect.ismodule(obj):
            continue
        emit_member(lines, name, obj)


def main():
    import brainiak_tpu

    groups = {}
    pkgpath = os.path.dirname(brainiak_tpu.__file__)
    for info in sorted(pkgutil.walk_packages([pkgpath], "brainiak_tpu."),
                       key=lambda i: i.name):
        if any(part.startswith("_") for part in info.name.split(".")):
            continue
        parts = info.name.split(".")
        group = parts[1]
        groups.setdefault(group, []).append(info.name)

    if os.path.isdir(OUT):
        shutil.rmtree(OUT)
    os.makedirs(OUT)

    index = ["# API reference\n",
             "Generated by `tools/gen_api_docs.py` — one page per "
             "subpackage, full public surface with signatures and "
             "docstrings.\n"]
    for group in sorted(groups):
        modnames = groups[group]
        # A package page covers the package module plus its submodules.
        lines = [f"# `brainiak_tpu.{group}`\n"]
        for modname in modnames:
            emit_module(lines, modname)
        fname = f"{group}.md"
        with open(os.path.join(OUT, fname), "w") as f:
            f.write("\n".join(lines))
        n_entries = sum(1 for line in lines if line.startswith("### "))
        index.append(f"- [`brainiak_tpu.{group}`]({fname}) — "
                     f"{n_entries} public entries")
    with open(os.path.join(OUT, "index.md"), "w") as f:
        f.write("\n".join(index) + "\n")
    print(f"wrote {len(groups) + 1} pages to {OUT}")


if __name__ == "__main__":
    main()
