#!/usr/bin/env python3
"""Hermetic line-coverage runner (the environment ships no coverage.py).

The analog of the reference's ``coverage run -m pytest`` gate
(reference run-tests.sh:31, pyproject ``fail_under = 90``), built on
CPython 3.12's ``sys.monitoring``: LINE events are recorded for files
under ``brainiak_tpu/`` and each (code, line) location is DISABLE'd
after its first hit, so steady-state overhead is near zero.  The
denominator is the set of executable lines from compiling every package
source and walking its nested code objects — the same notion
coverage.py uses (module/def/docstring bookkeeping differs slightly, so
percentages are comparable, not bit-identical; branch coverage is not
measured).

Lines (or whole defs/classes) marked ``# pragma: no cover`` are
excluded, as are ``if TYPE_CHECKING:`` bodies.

Usage:
    python tools/coverage_lite.py [--fail-under PCT] [--json OUT] \
        -m pytest tests/ -q
    python tools/coverage_lite.py report   # report from last run's json
"""

import argparse
import ast
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "brainiak_tpu")
DEFAULT_JSON = os.path.join(REPO, "benchmarks", "coverage_lite.json")

_hits = {}


def _line_cb(code, lineno):
    fn = code.co_filename
    if fn.startswith(PKG):
        _hits.setdefault(fn, set()).add(lineno)
    return sys.monitoring.DISABLE


def _start_monitoring():
    mon = sys.monitoring
    mon.use_tool_id(mon.COVERAGE_ID, "coverage_lite")
    mon.register_callback(mon.COVERAGE_ID, mon.events.LINE, _line_cb)
    mon.set_events(mon.COVERAGE_ID, mon.events.LINE)


def _stop_monitoring():
    mon = sys.monitoring
    mon.set_events(mon.COVERAGE_ID, 0)
    mon.register_callback(mon.COVERAGE_ID, mon.events.LINE, None)
    mon.free_tool_id(mon.COVERAGE_ID)


def _excluded_lines(tree, source_lines):
    """Line numbers excluded by ``# pragma: no cover`` (on the line, or
    covering a whole def/class when on its header) and
    ``if TYPE_CHECKING:`` bodies."""
    excluded = set()
    pragma = {i for i, line in enumerate(source_lines, 1)
              if "pragma: no cover" in line}
    excluded |= pragma
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.lineno in pragma or any(
                    d.lineno in pragma for d in node.decorator_list):
                excluded.update(range(node.lineno, node.end_lineno + 1))
        elif isinstance(node, ast.If):
            test = node.test
            if isinstance(test, ast.Name) and test.id == "TYPE_CHECKING":
                excluded.update(range(node.lineno, node.end_lineno + 1))
    return excluded


def _executable_lines(path):
    with open(path, encoding="utf-8") as f:
        source = f.read()
    lines = source.splitlines()
    try:
        tree = ast.parse(source, filename=path)
        code = compile(source, path, "exec")
    except SyntaxError:
        return set()
    excluded = _excluded_lines(tree, lines)

    linenos = set()
    stack = [code]
    while stack:
        co = stack.pop()
        for _start, _end, lineno in co.co_lines():
            # lineno 0 is the synthetic RESUME location — never a real
            # source line, never hit
            if lineno:
                linenos.add(lineno)
        for const in co.co_consts:
            if hasattr(const, "co_lines"):
                stack.append(const)
    # a bare docstring statement registers one line; drop it like
    # coverage.py does (it is the module/def's first string constant)
    for node in ast.walk(tree):
        body = getattr(node, "body", None)
        if body and isinstance(node, (ast.Module, ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)):
            first = body[0]
            if isinstance(first, ast.Expr) and isinstance(
                    first.value, ast.Constant) and isinstance(
                    first.value.value, str):
                linenos -= set(range(first.lineno,
                                     first.end_lineno + 1))
    return {n for n in linenos if n not in excluded}


def _package_sources():
    for root, dirs, files in os.walk(PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


def collect_report(hits):
    per_file = {}
    total_exec = total_hit = 0
    for path in _package_sources():
        executable = _executable_lines(path)
        hit = hits.get(path, set()) & executable
        total_exec += len(executable)
        total_hit += len(hit)
        pct = 100.0 * len(hit) / len(executable) if executable else 100.0
        per_file[os.path.relpath(path, REPO)] = {
            "executable": len(executable),
            "hit": len(hit),
            "pct": round(pct, 1),
            "missing": sorted(executable - hit),
        }
    total_pct = 100.0 * total_hit / total_exec if total_exec else 100.0
    return {"total_pct": round(total_pct, 2), "total_exec": total_exec,
            "total_hit": total_hit, "files": per_file}


def print_report(report, show_missing=False):
    width = max(len(p) for p in report["files"])
    print(f"{'file'.ljust(width)}  lines   hit    pct")
    for path, st in sorted(report["files"].items()):
        print(f"{path.ljust(width)}  {st['executable']:5d} "
              f"{st['hit']:5d}  {st['pct']:5.1f}%")
        if show_missing and st["missing"]:
            print(f"{' ' * width}  missing: "
                  f"{_ranges(st['missing'])}")
    print(f"{'TOTAL'.ljust(width)}  {report['total_exec']:5d} "
          f"{report['total_hit']:5d}  {report['total_pct']:5.1f}%")


def _ranges(nums):
    out, start, prev = [], None, None
    for n in nums + [None]:
        if start is None:
            start = prev = n
        elif n is not None and n == prev + 1:
            prev = n
        else:
            out.append(f"{start}-{prev}" if prev != start else f"{start}")
            start = prev = n
    return ",".join(out)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--fail-under", type=float, default=90.0)
    ap.add_argument("--json", default=DEFAULT_JSON)
    ap.add_argument("--show-missing", action="store_true")
    ap.add_argument("-m", dest="module")
    ap.add_argument("rest", nargs=argparse.REMAINDER)
    args = ap.parse_args()

    if args.module is None and args.rest[:1] == ["report"]:
        with open(args.json, encoding="utf-8") as f:
            report = json.load(f)
        print_report(report, show_missing=args.show_missing)
        return 0 if report["total_pct"] >= args.fail_under else 1

    sys.argv = [args.module] + args.rest
    _start_monitoring()
    import runpy
    code = 0
    try:
        try:
            runpy.run_module(args.module, run_name="__main__",
                             alter_sys=True)
        except SystemExit as exc:
            code = exc.code if isinstance(exc.code, int) else \
                (0 if exc.code is None else 1)
    finally:
        _stop_monitoring()
    report = collect_report(_hits)
    os.makedirs(os.path.dirname(args.json), exist_ok=True)
    with open(args.json, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
    print_report(report, show_missing=args.show_missing)
    if code:
        return code
    return 0 if report["total_pct"] >= args.fail_under else 1


if __name__ == "__main__":
    sys.exit(main())
