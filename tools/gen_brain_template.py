#!/usr/bin/env python3
"""Regenerate the packaged brain template for ``fmrisim.mask_brain``.

The reference ships an MNI152 grey-matter atlas as package data and
``mask_brain(mask_self=False)`` zooms it to the requested volume
(reference fmrisim.py:2230-2366).  This repo's analog is a PACKAGED,
fixed template with the same loading pipeline: generated ONCE by the
procedural model in ``fmrisim._synthetic_brain_template`` on the
MNI152-like 91 x 109 x 91 grid, quantized to uint8 (1/255 ~ 0.004 of
the [0, 1] range — far below the atlas's own probabilistic resolution)
and stored deflate-compressed.  Provenance is therefore reproducible:
running this script must regenerate the packaged file bit-for-bit
(pinned by tests/utils/test_fmrisim.py::test_packaged_brain_template).
"""

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "brainiak_tpu", "utils", "sim_parameters",
                   "brain_template.npz")
GRID = (91, 109, 91)  # MNI152 2 mm grid, like the reference's atlas


def main():
    from brainiak_tpu.utils.fmrisim import _synthetic_brain_template
    template = _synthetic_brain_template(GRID)
    quantized = np.round(template * 255.0).astype(np.uint8)
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    np.savez_compressed(OUT, template=quantized)
    print(f"wrote {OUT}: shape={quantized.shape} "
          f"size={os.path.getsize(OUT)} bytes")


if __name__ == "__main__":
    main()
