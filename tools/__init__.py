"""Repo tooling (``python -m tools.run_checks`` and friends)."""
