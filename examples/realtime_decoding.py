"""Real-time fMRI simulation + incremental decoding.

TPU-native counterpart of the reference's real-time example family
(reference docs/examples/real-time/, fmrisim_real_time_generator CLI):
stream simulated TR volumes to disk with
:mod:`brainiak_tpu.utils.fmrisim_real_time_generator`, then play the
"real-time analysis" side — watch the directory, ingest volumes TR by TR,
and after each block re-train an incremental two-condition decoder on the
accumulated ROI data, exactly the loop an rtcloud-style experiment runs
(minus the scanner).

Usage:
    python examples/realtime_decoding.py [--num-trs 120] [--keep DIR]
"""

import argparse
import glob
import os
import shutil
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-trs", type=int, default=120)
    ap.add_argument("--event-duration", type=int, default=10)
    ap.add_argument("--isi", type=int, default=6)
    ap.add_argument("--keep", default=None,
                    help="directory to keep generated volumes in "
                         "(default: a temp dir, deleted afterwards)")
    ap.add_argument("--backend", default=None,
                    help="jax platform override (e.g. cpu)")
    args = ap.parse_args()
    if args.backend:
        import jax
        jax.config.update("jax_platforms", args.backend)

    from brainiak_tpu.utils.fmrisim_real_time_generator import \
        generate_data

    out_dir = args.keep or tempfile.mkdtemp(prefix="rtsim_")
    np.random.seed(0)

    # -- "scanner" side: stream simulated volumes ------------------------
    generate_data(out_dir, {
        "numTRs": args.num_trs,
        "event_duration": args.event_duration,
        "isi": args.isi,
        "multivariate_pattern": True,
        "save_realtime": False,     # write as fast as possible
    })
    # decode from the stimulated ROI (the generator writes the ROI
    # geometry next to the stream, as the reference ships its ROI files)
    roi = np.load(os.path.join(out_dir, "roi_a.npy")).astype(bool)
    # stimulus labels at the generator's temporal resolution of one
    # sample per TR (0 = rest, 1 = condition A, 2 = condition B)
    labels_tr = np.load(os.path.join(out_dir, "labels.npy")).ravel()

    # -- "analysis" side: ingest TR by TR, decode incrementally ----------
    vol_files = sorted(
        glob.glob(os.path.join(out_dir, "rt_*.npy")),
        key=lambda f: int(os.path.basename(f)[3:-4]))
    # A reused --keep directory may hold stale volumes from an earlier,
    # longer run; this run's labels only describe the first num_trs.
    if len(vol_files) != len(labels_tr):
        raise SystemExit(
            f"{out_dir} holds {len(vol_files)} volumes but this run "
            f"generated {len(labels_tr)} TRs — remove stale rt_*.npy "
            "files (reused --keep directory?)")
    print(f"streaming {len(vol_files)} TR volumes from {out_dir}")

    series, cond = [], []
    accuracies = []
    for tr, f in enumerate(vol_files):
        vol = np.load(f)
        series.append(vol[roi])
        cond.append(int(labels_tr[tr]))

        # every 20 TRs, re-train on what has arrived so far (shifting
        # labels ~2 TRs for the hemodynamic lag) and report leave-one-
        # block-out accuracy of condition A vs B
        if (tr + 1) % 20 == 0 and tr > 40:
            x = np.asarray(series)
            # hemodynamic lag: shift labels 2 TRs later, zero-padded
            # (a wrapped roll would pin tail labels onto burn-in rest)
            y = np.concatenate([[0, 0], np.asarray(cond)[:-2]])
            keep = y > 0
            if np.unique(y[keep]).size < 2:
                continue
            acc = _block_cv_accuracy(x[keep], y[keep])
            accuracies.append(acc)
            print(f"  TR {tr + 1:3d}: {keep.sum():3d} task TRs, "
                  f"incremental decoder accuracy {acc:.2f}")

    if not args.keep:
        shutil.rmtree(out_dir)
    print("final accuracy trajectory:",
          " ".join(f"{a:.2f}" for a in accuracies))
    assert accuracies and accuracies[-1] > 0.55, \
        "decoder should beat chance once enough TRs have streamed"
    print("OK")


def _block_cv_accuracy(x, y):
    """2-fold (first/second half) CV with an on-device linear SVM dual
    on the voxel Gram — the same solver FCMA voxel selection uses."""
    import jax.numpy as jnp

    from brainiak_tpu.ops.svm import svm_cv_accuracy

    x = (x - x.mean(0)) / (x.std(0) + 1e-6)
    kernel = jnp.asarray((x @ x.T)[None])  # one "voxel": the whole ROI
    return float(svm_cv_accuracy(kernel, (y == 1).astype(int),
                                 num_folds=2)[0])


if __name__ == "__main__":
    main()
