"""Matrix-normal models: RSA and regression with structured noise.

TPU-native counterpart of the reference's `docs/examples/matnormal/`
walkthrough: simulate data whose rows (time) carry AR(1) noise and whose
columns (space) share variance, then (a) recover a condition covariance
with MNRSA and (b) fit a matrix-normal regression, both by autodiff
L-BFGS over the structured-covariance marginal likelihood.

Usage:
    python examples/matnormal_rsa.py [--backend cpu]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default=None)
    ap.add_argument("--trs", type=int, default=150)
    ap.add_argument("--voxels", type=int, default=40)
    args = ap.parse_args()
    import jax
    if args.backend:
        jax.config.update("jax_platforms", args.backend)

    from brainiak_tpu.matnormal.covs import (
        CovAR1,
        CovIdentity,
        CovIsotropic,
    )
    from brainiak_tpu.matnormal.mnrsa import MNRSA
    from brainiak_tpu.matnormal.regression import MatnormalRegression

    rng = np.random.RandomState(0)
    n_t, n_v, n_c = args.trs, args.voxels, 4
    U = np.array([[1.0, 0.7, 0.0, 0.0],
                  [0.7, 1.0, 0.0, 0.0],
                  [0.0, 0.0, 1.0, 0.7],
                  [0.0, 0.0, 0.7, 1.0]])
    X = rng.randn(n_t, n_c)
    W = np.linalg.cholesky(U) @ rng.randn(n_c, n_v)
    # AR(1) noise over time
    noise = np.zeros((n_t, n_v))
    e = rng.randn(n_t, n_v)
    noise[0] = e[0]
    for t in range(1, n_t):
        noise[t] = 0.5 * noise[t - 1] + np.sqrt(1 - 0.25) * e[t]
    Y = X @ W + 0.7 * noise

    model = MNRSA(time_cov=CovAR1(n_t), space_cov=CovIsotropic(n_v),
                  n_nureg=2)
    model.fit(Y, X)
    iu = np.triu_indices(n_c, 1)
    c = np.corrcoef(model.C_[iu], U[iu])[0, 1]
    print("MNRSA similarity recovery (off-diag corr):",
          round(float(c), 3))

    reg = MatnormalRegression(time_cov=CovAR1(n_t),
                              space_cov=CovIdentity(n_v))
    reg.fit(X, Y)
    w_corr = np.corrcoef(reg.beta_.ravel(), W.ravel())[0, 1]
    print("matnormal regression weight recovery:",
          round(float(w_corr), 3))


if __name__ == "__main__":
    main()
