"""End-to-end FCMA: voxel selection then correlation-based classification.

The TPU-native counterpart of the reference's
examples/fcma/voxel_selection.py + classification.py, which are launched
under ``mpirun -np N``; here there is no launcher — the same script runs
single-chip or, with a mesh, across a slice.

Usage:
    python examples/fcma_voxel_selection_and_classification.py \
        [--data-dir DIR] [--top 50] [--backend cpu]

Without --data-dir, simulated data from fmrisim is used (the reference's
test strategy).  With it, expects NIfTI images (suffix bet.nii.gz), a
mask.nii.gz, and an epoch_labels.npy, as in the reference example data.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_real(data_dir):
    from brainiak_tpu import io
    from brainiak_tpu.fcma.preprocessing import prepare_fcma_data

    images = io.load_images_from_dir(data_dir, suffix="bet.nii.gz")
    mask = io.load_boolean_mask(os.path.join(data_dir, "mask.nii.gz"))
    conditions = io.load_labels(os.path.join(data_dir,
                                             "epoch_labels.npy"))
    raw, _, labels = prepare_fcma_data(images, conditions, mask)
    epochs_per_subj = len(labels) // len(conditions)
    return raw, labels, epochs_per_subj


def simulate(n_subjects=4, epochs_per_subj=4, voxels=200, epoch_len=20):
    """Two conditions whose correlation STRUCTURE differs in the first
    voxels (FCMA's signal of interest is connectivity, not activity)."""
    import math

    rng = np.random.RandomState(0)
    raw, labels = [], []
    informative = voxels // 10
    for _ in range(n_subjects):
        for e in range(epochs_per_subj):
            cond = e % 2
            mat = rng.randn(epoch_len, voxels)
            shared = rng.randn(epoch_len)
            if cond == 0:  # condition 0: informative voxels co-fluctuate
                mat[:, :informative] += shared[:, None] * 2
            mat = (mat - mat.mean(0)) / (mat.std(0)
                                         * math.sqrt(epoch_len))
            raw.append(mat.astype(np.float32))
            labels.append(cond)
    return raw, labels, epochs_per_subj


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--backend", default=None)
    args = ap.parse_args()
    import jax
    if args.backend:
        jax.config.update("jax_platforms", args.backend)

    from sklearn import svm

    from brainiak_tpu.fcma.classifier import Classifier
    from brainiak_tpu.fcma.voxelselector import VoxelSelector

    if args.data_dir:
        raw, labels, eps = load_real(args.data_dir)
    else:
        raw, labels, eps = simulate()
    print(f"{len(raw)} epochs, {raw[0].shape[1]} voxels, "
          f"{eps} epochs/subject")

    # Stage 1: rank voxels by correlation-pattern classifiability.
    vs = VoxelSelector(labels, eps, 2, raw)
    results = vs.run('svm')
    top = [vid for vid, _ in results[:args.top]]
    print("top voxel accuracies:",
          [round(acc, 2) for _, acc in results[:5]])

    # Stage 2: classify held-out epochs on the selected submatrix.
    # The train split must respect subject boundaries: within-subject
    # normalization groups epochs in blocks of epochs_per_subj.
    sub = [d[:, top] for d in raw]
    n_train = max((len(sub) * 3 // 4) // eps * eps, eps)
    clf = Classifier(svm.SVC(kernel='precomputed', shrinking=False, C=1),
                     epochs_per_subj=eps)
    clf.fit(list(zip(sub[:n_train], sub[:n_train])), labels[:n_train])
    test = sub[n_train:] if n_train < len(sub) else sub[:n_train]
    test_labels = labels[n_train:] if n_train < len(sub) \
        else labels[:n_train]
    which = "held-out" if n_train < len(sub) else "training"
    score = clf.score(list(zip(test, test)), test_labels)
    print(f"{which} classification accuracy on top-{args.top} voxels: "
          f"{score:.2f}")


if __name__ == "__main__":
    main()
