"""Hyperparameter optimization of the Branin-Hoo function.

TPU-native counterpart of the reference's
``examples/hyperparamopt/hpo_example.py``: minimize the modified
2-variable Branin function (one global minimum of ~-16.6 at
(-3.7, 13.7)) with the TPE-style ``fmin`` and compare against a grid
search of the same evaluation budget.

Usage:
    python examples/hpo_branin.py [--max-evals 120]
"""

import argparse
import os
import sys

import numpy as np
import scipy.stats as st

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def branin(x1, x2):
    """Modified Branin-Hoo (the reference example's objective)."""
    b = 5.1 / (4 * np.pi * np.pi)
    c = 5.0 / np.pi
    t = 1.0 / (8 * np.pi)
    return ((x2 - b * x1 * x1 + c * x1 - 6.0) ** 2
            + 10.0 * (1 - t) * np.cos(x1) + 10.0 + 5 * x1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-evals", type=int, default=120)
    ap.add_argument("--backend", default=None)  # accepted for harness
    args = ap.parse_args()

    from brainiak_tpu.hyperparamopt.hpo import fmin

    np.random.seed(0)
    space = {
        "x1": {"dist": st.uniform(-5.0, 15.0), "lo": -5.0, "hi": 10.0},
        "x2": {"dist": st.uniform(0.0, 15.0), "lo": 0.0, "hi": 15.0},
    }
    trials = []
    best = fmin(lambda kw: float(branin(kw["x1"], kw["x2"])),
                space, max_evals=args.max_evals, trials=trials,
                init_random_evals=30)
    print(f"hpo best: f({best['x1']:.2f}, {best['x2']:.2f}) = "
          f"{best['loss']:.2f} in {len(trials)} evaluations")

    # grid search with the same budget
    n = int(np.sqrt(args.max_evals))
    g1 = np.linspace(-5, 10, n)
    g2 = np.linspace(0, 15, n)
    vals = branin(g1[:, None], g2[None, :])
    gi = np.unravel_index(np.argmin(vals), vals.shape)
    print(f"grid best ({n * n} evaluations): "
          f"f({g1[gi[0]]:.2f}, {g2[gi[1]]:.2f}) = {vals[gi]:.2f}")
    print(f"global minimum: -16.6 at (-3.7, 13.7)")
    assert best["loss"] < vals[gi] + 5.0  # hpo is competitive with grid


if __name__ == "__main__":
    main()
