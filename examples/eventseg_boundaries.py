"""HMM event segmentation: find event boundaries in continuous data.

TPU-native counterpart of the reference's `docs/examples/eventseg/`
walkthrough: simulate a timeseries that passes through a sequence of
stable activity patterns, fit EventSegment (forward-backward as
lax.scan), recover the boundaries, and transfer the learned event
patterns to held-out data.

Usage:
    python examples/eventseg_boundaries.py [--backend cpu]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def simulate(pat, lengths, noise, rng):
    """A noisy pass through the same event patterns (held-out data share
    the patterns, not the noise)."""
    ev = np.concatenate([[e] * n for e, n in enumerate(lengths)])
    data = pat[ev] + noise * rng.rand(len(ev), pat.shape[1])
    return data, ev


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default=None)
    ap.add_argument("--voxels", type=int, default=20)
    ap.add_argument("--events", type=int, default=6)
    ap.add_argument("--noise", type=float, default=0.15)
    args = ap.parse_args()
    import jax
    if args.backend:
        jax.config.update("jax_platforms", args.backend)

    from brainiak_tpu.eventseg.event import EventSegment

    rng = np.random.RandomState(0)
    lengths = rng.randint(8, 20, size=args.events)
    pat = rng.rand(args.events, args.voxels)
    train, ev = simulate(pat, lengths, args.noise, rng)
    test, _ = simulate(pat, lengths, args.noise, rng)

    es = EventSegment(args.events, split_merge=True)
    es.fit(train)
    recovered = np.argmax(es.segments_[0], axis=1)
    true_bounds = np.where(np.diff(ev))[0]
    est_bounds = np.where(np.diff(recovered))[0]
    err = [int(np.min(np.abs(est_bounds - b))) if len(est_bounds)
           else -1 for b in true_bounds]
    print("true boundaries:", true_bounds.tolist())
    print("estimated boundaries:", est_bounds.tolist())
    print("max boundary error (TRs):", max(err))

    segments, test_ll = es.find_events(test)
    print("held-out segmentation LL:", round(float(test_ll), 2))
    transfer = np.argmax(segments, axis=1)
    agree = float(np.mean(transfer == ev))
    print("held-out event agreement:", round(agree, 3))


if __name__ == "__main__":
    main()
