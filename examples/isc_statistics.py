"""Intersubject correlation with resampling statistics.

TPU-native counterpart of the reference's isc examples: simulate
multi-subject data with fmrisim-style shared signal, compute leave-one-out
ISC and ISFC, and assess significance with on-device bootstrap and
phase-randomization nulls.

Usage:
    python examples/isc_statistics.py [--backend cpu]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default=None)
    ap.add_argument("--subjects", type=int, default=15)
    ap.add_argument("--trs", type=int, default=200)
    ap.add_argument("--voxels", type=int, default=30)
    ap.add_argument("--n-resamples", type=int, default=500)
    args = ap.parse_args()
    import jax
    if args.backend:
        jax.config.update("jax_platforms", args.backend)

    from brainiak_tpu.isc import bootstrap_isc, isc, isfc, phaseshift_isc

    rng = np.random.RandomState(0)
    # half the voxels carry a shared signal, half are idiosyncratic noise
    n_sig = args.voxels // 2
    signal = rng.randn(args.trs, n_sig)
    data = np.zeros((args.trs, args.voxels, args.subjects),
                    dtype=np.float32)
    for s in range(args.subjects):
        data[:, :n_sig, s] = signal + rng.randn(args.trs, n_sig)
        data[:, n_sig:, s] = rng.randn(args.trs,
                                       args.voxels - n_sig) * 1.5

    iscs = isc(data)
    print("mean ISC (signal voxels):",
          round(float(iscs[:, :n_sig].mean()), 3))
    print("mean ISC (noise voxels):",
          round(float(iscs[:, n_sig:].mean()), 3))

    observed, ci, p, _ = bootstrap_isc(iscs,
                                       n_bootstraps=args.n_resamples,
                                       random_state=0)
    sig = np.where(np.asarray(p) < 0.05)[0]
    print(f"bootstrap: {len(sig)}/{args.voxels} voxels significant "
          f"(expected ~{n_sig})")

    _, p_phase, _ = phaseshift_isc(data, n_shifts=args.n_resamples // 2,
                                   random_state=0)
    sig_p = np.where(np.asarray(p_phase) < 0.05)[0]
    print(f"phase-shift null: {len(sig_p)}/{args.voxels} significant")

    isfcs, iscs_diag = isfc(data)
    print("ISFC matrix (condensed):", isfcs.shape,
          "mean within-signal ISFC:",
          round(float(np.nanmean(isfcs[:, :n_sig])), 3))


if __name__ == "__main__":
    main()
