"""The functional-alignment family beyond SRM: RSRM, SSSRM, FastSRM.

Counterpart of the reference's remaining funcalign examples
(``rsrm_synthetic_reconstruction.ipynb``,
``sssrm_image_prediction_example.py``, ``FastSRM_encoding_experiment``):
one synthetic multi-subject dataset, three alignment variants —

- **RSRM**: shared response + per-subject sparse residual; recovers an
  injected idiosyncratic component;
- **SSSRM**: semi-supervised alignment — labeled epochs sharpen a
  shared space used for cross-subject classification;
- **FastSRM**: atlas-reduced SRM for datasets that do not fit memory,
  fit from per-subject arrays with a deterministic atlas.

Usage:
    python examples/funcalign_variants.py [--backend cpu]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def make_aligned_subjects(rng, n_subj, v, t, k):
    shared = rng.randn(k, t)
    data, bases = [], []
    for _ in range(n_subj):
        w, _ = np.linalg.qr(rng.randn(v, k))
        data.append(w @ shared + 0.1 * rng.randn(v, t))
        bases.append(w)
    return data, bases, shared


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default=None)
    ap.add_argument("--subjects", type=int, default=5)
    ap.add_argument("--voxels", type=int, default=150)
    ap.add_argument("--trs", type=int, default=100)
    ap.add_argument("--features", type=int, default=4)
    args = ap.parse_args()
    import jax
    if args.backend:
        jax.config.update("jax_platforms", args.backend)

    from brainiak_tpu.funcalign.fastsrm import FastSRM
    from brainiak_tpu.funcalign.rsrm import RSRM
    from brainiak_tpu.funcalign.sssrm import SSSRM

    rng = np.random.RandomState(0)
    S, V, T, K = args.subjects, args.voxels, args.trs, args.features
    data, bases, shared = make_aligned_subjects(rng, S, V, T, K)

    # --- RSRM: inject a sparse idiosyncratic pattern into subject 0
    spike_rows = rng.choice(V, 10, replace=False)
    corrupted = [d.copy() for d in data]
    corrupted[0][spike_rows] += 3.0
    rsrm = RSRM(n_iter=10, features=K, rand_seed=0)
    rsrm.fit(corrupted)
    s0 = np.asarray(rsrm.s_[0])
    spike_energy = np.abs(s0[spike_rows]).mean()
    other_energy = np.abs(np.delete(s0, spike_rows, axis=0)).mean()
    print(f"RSRM: sparse-term energy on injected rows "
          f"{spike_energy:.2f} vs elsewhere {other_energy:.2f}")
    assert spike_energy > 5 * other_energy

    # --- SSSRM: labeled epochs sharpen the shared space; the fitted
    # MLR then classifies NEW epochs of the same subjects
    n_lab, n_test = 40, 20
    labels = (np.arange(n_lab) % 2)
    test_labels = (np.arange(n_test) % 2)
    prototypes = rng.randn(2, K) * 2.0
    Z, y, Z_test = [], [], []
    for s in range(S):
        z = prototypes[labels].T + 0.3 * rng.randn(K, n_lab)
        Z.append(bases[s] @ z + 0.1 * rng.randn(V, n_lab))
        y.append(labels.astype(float))
        zt = prototypes[test_labels].T + 0.3 * rng.randn(K, n_test)
        Z_test.append(bases[s] @ zt + 0.1 * rng.randn(V, n_test))
    sssrm = SSSRM(n_iter=4, features=K, gamma=1.0, alpha=0.2,
                  rand_seed=0)
    sssrm.fit(data, y, Z)
    preds = sssrm.predict(Z_test)
    acc = float(np.mean([np.mean(np.asarray(p) == test_labels)
                         for p in preds]))
    print(f"SSSRM: new-epoch classification accuracy over subjects "
          f"{acc:.2f}")
    assert acc > 0.8

    # --- FastSRM: atlas-reduced fit
    atlas = rng.randint(0, 20, size=V)  # deterministic parcellation
    fast = FastSRM(atlas=atlas, n_components=K, n_iter=10,
                   aggregate="mean")
    fast.fit([d for d in data])
    sr = fast.transform([d for d in data])
    qa, _ = np.linalg.qr(np.asarray(sr).T)
    qb, _ = np.linalg.qr(shared.T)
    cosines = np.linalg.svd(qa.T @ qb, compute_uv=False)
    print(f"FastSRM: shared-subspace principal cosines "
          f"{np.round(cosines, 3).tolist()}")
    assert cosines.min() > 0.8


if __name__ == "__main__":
    main()
