"""fmrisim: simulate realistic fMRI data with matched noise.

TPU-native counterpart of the reference's `docs/examples/fmrisim/`
walkthrough: build a task signal (stimfunction -> HRF convolution),
estimate noise properties from a (here: synthetic) "real" volume with
calc_noise, regenerate matched noise with generate_noise, and verify the
round-trip reproduces the target noise statistics.

Usage:
    python examples/fmrisim_noise_simulation.py [--backend cpu]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default=None)
    ap.add_argument("--dim", type=int, default=18,
                    help="volume edge length")
    ap.add_argument("--trs", type=int, default=80)
    args = ap.parse_args()
    import jax
    if args.backend:
        jax.config.update("jax_platforms", args.backend)

    from brainiak_tpu.utils import fmrisim

    np.random.seed(0)
    dims = [args.dim, args.dim, args.dim]
    tr = 2.0

    # --- task signal: two event types -> stimfunction -> HRF ---
    onsets = np.arange(10, args.trs * tr - 20, 20.0)
    stimfunction = fmrisim.generate_stimfunction(
        onsets=list(onsets), event_durations=[4.0],
        total_time=int(args.trs * tr))
    signal_function = fmrisim.convolve_hrf(stimfunction, tr_duration=tr)

    c = args.dim // 2
    volume_signal = fmrisim.generate_signal(
        dimensions=np.array(dims),
        feature_coordinates=np.array([[c, c, c]]),
        feature_size=[2], feature_type=['cube'],
        signal_magnitude=[1.0])
    signal = fmrisim.apply_signal(signal_function, volume_signal)

    # --- a synthetic "measured" volume to estimate noise from ---
    # brain occupies the interior; the wide border is non-brain (the SNR
    # estimate contrasts brain against background OUTSIDE a 5-voxel
    # dilation of the mask, so the border must be deeper than that)
    b = max(args.dim // 3, 6)
    template = np.zeros(dims)
    template[b:-b, b:-b, b:-b] = 0.8
    mask = (template > 0.5).astype(float)
    target_dict = {'sfnr': 60.0, 'snr': 30.0, 'auto_reg_rho': [0.5],
                   'voxel_size': [1.0, 1.0, 1.0], 'matched': 0}
    stim_tr = stimfunction[::int(tr * 100)]
    measured = fmrisim.generate_noise(
        dims, stim_tr, tr, template, mask=mask,
        noise_dict=dict(target_dict))

    est = fmrisim.calc_noise(measured, mask, template)
    print("estimated SFNR:", round(float(est['sfnr']), 1))
    print("estimated AR(1) rho:", round(float(est['auto_reg_rho'][0]), 3))

    # --- regenerate matched noise and combine with the signal ---
    est['matched'] = 0
    noise = fmrisim.generate_noise(dims, stim_tr, tr, template,
                                   mask=mask, noise_dict=est)
    brain = signal * 10.0 + noise
    print("simulated 4-D volume:", brain.shape)
    est2 = fmrisim.calc_noise(noise, mask, template)
    print("round-trip SFNR:", round(float(est2['sfnr']), 1),
          "(target", round(float(est['sfnr']), 1), ")")


if __name__ == "__main__":
    main()
