"""Hierarchical topographic factor analysis across subjects.

TPU-native counterpart of the reference's factoranalysis examples
(launched under mpirun there): estimate a global template of RBF factor
centers/widths across subjects whose individual factor locations jitter
around it.

Usage:
    python examples/htfa_template.py [--backend cpu]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default=None)
    ap.add_argument("--subjects", type=int, default=3)
    ap.add_argument("--factors", type=int, default=2)
    args = ap.parse_args()
    import jax
    if args.backend:
        jax.config.update("jax_platforms", args.backend)

    from brainiak_tpu.factoranalysis.htfa import HTFA

    rng = np.random.RandomState(0)
    grid = np.array(np.meshgrid(*[np.arange(8)] * 3)) \
        .reshape(3, -1).T.astype(float)
    template_centers = np.array([[2.0, 2.0, 2.0], [6.0, 6.0, 5.0]])
    widths = np.array([[3.0], [4.0]])

    X, R = [], []
    for s in range(args.subjects):
        jitter = 0.3 * rng.randn(*template_centers.shape)
        centers = template_centers + jitter
        F = np.exp(-((grid[:, None, :] - centers[None]) ** 2).sum(-1)
                   / widths.T)
        W = rng.randn(args.factors, 60)
        X.append(F @ W + 0.05 * rng.randn(grid.shape[0], 60))
        R.append(grid)

    htfa = HTFA(K=args.factors, n_subj=args.subjects, max_global_iter=3,
                max_local_iter=3, threshold=0.5, voxel_ratio=1.0,
                tr_ratio=1.0, max_voxel=512, max_tr=60)
    htfa.fit(X, R)

    est = htfa.get_centers(htfa.global_posterior_)
    order = np.argsort(est[:, 0])
    torder = np.argsort(template_centers[:, 0])
    print("true template centers:\n", template_centers[torder])
    print("estimated template centers:\n", np.round(est[order], 2))
    err = np.abs(est[order] - template_centers[torder]).max()
    print("max center error:", round(float(err), 2))


if __name__ == "__main__":
    main()
