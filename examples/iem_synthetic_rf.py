"""Inverted encoding model on synthetic receptive-field data (circular).

TPU-native analog of the reference's `docs/examples/iem_synthetic_RF/`
notebook: stimuli are motion-direction patches spanning a CIRCULAR
360-degree feature space; voxels are simulated with Gaussian receptive
fields tiling that space (fmrisim RF helpers, reference
fmrisim.py:3273-3388); a 6-channel inverted encoding model is fit, the
channel basis is inspected, held-out directions are predicted, the
model-based reconstruction curves are summarized, and an R^2-vs-voxel-
count sweep closes the walkthrough (the notebook's sanity check).

Usage:
    python examples/iem_synthetic_rf.py [--backend cpu]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def simulate(n_voxels, n_trials, noise, rng):
    from brainiak_tpu.utils.fmrisim import (
        generate_1d_gaussian_rfs,
        generate_1d_rf_responses,
    )

    feature_resolution = 360
    rfs, tuning = generate_1d_gaussian_rfs(
        n_voxels, feature_resolution, (0, 359), rf_size=40)
    stimuli = rng.randint(0, 360, size=n_trials).astype(float)
    responses = generate_1d_rf_responses(
        rfs, stimuli, feature_resolution, (0, 359),
        trial_noise=noise).T  # [trials, voxels]
    return responses, stimuli, tuning


def fit_and_score(responses, stimuli, n_train):
    from brainiak_tpu.reconstruct.iem import InvertedEncoding1D

    model = InvertedEncoding1D(n_channels=6, channel_exp=5,
                               stimulus_mode='circular',
                               range_start=0., range_stop=360.)
    model.fit(responses[:n_train], stimuli[:n_train])
    r2 = float(model.score(responses[n_train:], stimuli[n_train:]))
    return model, r2


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default=None)
    ap.add_argument("--voxels", type=int, default=100)
    ap.add_argument("--trials", type=int, default=200)
    ap.add_argument("--noise", type=float, default=0.25)
    args = ap.parse_args()
    import jax
    if args.backend:
        jax.config.update("jax_platforms", args.backend)

    np.random.seed(0)  # RF helpers use the global RNG, as the reference
    rng = np.random.RandomState(1)
    responses, stimuli, tuning = simulate(
        args.voxels, args.trials, args.noise, rng)
    n_train = args.trials * 3 // 4

    model, r2 = fit_and_score(responses, stimuli, n_train)

    # the fitted basis: 6 half-cosine^5 channels tiling the circle
    channels, centers = model._define_channels()
    peaks = np.asarray(model.channel_domain)[np.argmax(channels, axis=1)]
    print("channel peaks (deg):",
          np.round(np.sort(peaks)).astype(int).tolist())

    # held-out prediction quality (circular error)
    pred = np.asarray(model.predict(responses[n_train:]),
                      dtype=np.float64)
    true = stimuli[n_train:]
    err = np.abs(pred - true)
    err = np.minimum(err, 360.0 - err)
    print("median circular error (deg):",
          round(float(np.median(err)), 2))
    print("R^2 score:", round(r2, 3))

    # model-based reconstructions: each held-out trial yields a curve
    # over the feature domain that should peak near the true direction
    recon = np.asarray(model._predict_feature_responses(
        responses[n_train:]))  # [features, trials]
    recon_peak = np.asarray(model.channel_domain)[np.argmax(recon,
                                                            axis=0)]
    peak_err = np.abs(recon_peak - true)
    peak_err = np.minimum(peak_err, 360.0 - peak_err)
    print("median reconstruction-peak error (deg):",
          round(float(np.median(peak_err)), 2))

    # the notebook's sanity sweep: R^2 grows with voxel count
    print("R^2 by voxel count:")
    for n_vox in (10, 30, args.voxels):
        np.random.seed(2)
        resp_i, stim_i, _ = simulate(n_vox, args.trials, args.noise,
                                     np.random.RandomState(3))
        _, r2_i = fit_and_score(resp_i, stim_i, n_train)
        print(f"  {n_vox:4d} voxels: R^2 = {r2_i:.3f}")


if __name__ == "__main__":
    main()
