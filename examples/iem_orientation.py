"""Inverted encoding model: reconstruct a continuous stimulus feature.

TPU-native counterpart of the reference's `docs/examples/reconstruct/`
(iem / iem_synthetic_RF) walkthroughs: simulate orientation-tuned voxel
responses with 1-D Gaussian receptive fields (fmrisim helpers), fit the
1-D inverted encoding model, and predict held-out orientations.

Usage:
    python examples/iem_orientation.py [--backend cpu]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default=None)
    ap.add_argument("--voxels", type=int, default=50)
    ap.add_argument("--trials", type=int, default=120)
    ap.add_argument("--noise", type=float, default=0.3)
    args = ap.parse_args()
    import jax
    if args.backend:
        jax.config.update("jax_platforms", args.backend)

    from brainiak_tpu.reconstruct.iem import InvertedEncoding1D
    from brainiak_tpu.utils.fmrisim import (
        generate_1d_gaussian_rfs,
        generate_1d_rf_responses,
    )

    np.random.seed(0)  # RF helpers use the global RNG, as the reference
    rng = np.random.RandomState(1)
    feature_resolution = 180
    rfs, tuning = generate_1d_gaussian_rfs(
        args.voxels, feature_resolution, (0, 179), rf_size=30)
    stimuli = rng.randint(0, 180, size=args.trials).astype(float)
    responses = generate_1d_rf_responses(
        rfs, stimuli, feature_resolution, (0, 179),
        trial_noise=args.noise).T  # [trials, voxels]

    n_train = args.trials * 3 // 4
    model = InvertedEncoding1D(n_channels=6, channel_exp=5,
                               stimulus_mode='halfcircular',
                               range_start=0., range_stop=180.)
    model.fit(responses[:n_train], stimuli[:n_train])
    pred = model.predict(responses[n_train:])
    true = stimuli[n_train:]
    circ_err = np.minimum(np.abs(pred - true), 180 - np.abs(pred - true))
    print("median circular error (deg):",
          round(float(np.median(circ_err)), 2))
    print("R^2 score:", round(float(model.score(responses[n_train:],
                                                true)), 3))


if __name__ == "__main__":
    main()
