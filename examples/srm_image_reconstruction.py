"""Multi-subject functional alignment with SRM.

TPU-native counterpart of the reference's funcalign examples: fit a shared
response across subjects on one half of the data, then show that a held-out
subject's second-half data can be mapped into the shared space where
patterns transfer across subjects.

Usage:
    python examples/srm_image_reconstruction.py [--backend cpu]
        [--subjects 6] [--voxels 500] [--mesh]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default=None)
    ap.add_argument("--subjects", type=int, default=6)
    ap.add_argument("--voxels", type=int, default=500)
    ap.add_argument("--trs", type=int, default=200)
    ap.add_argument("--features", type=int, default=20)
    ap.add_argument("--mesh", action="store_true",
                    help="shard subjects over all available devices")
    args = ap.parse_args()
    import jax
    if args.backend:
        jax.config.update("jax_platforms", args.backend)

    from brainiak_tpu.funcalign.srm import SRM
    from brainiak_tpu.parallel import make_mesh, max_divisible_shards

    rng = np.random.RandomState(0)
    S = rng.randn(args.features, args.trs)
    X = []
    for _ in range(args.subjects):
        q, _ = np.linalg.qr(rng.randn(args.voxels, args.features))
        X.append((q @ S + 0.3 * rng.randn(args.voxels, args.trs))
                 .astype(np.float32))

    half = args.trs // 2
    train = [x[:, :half] for x in X]
    test = [x[:, half:] for x in X]

    mesh = None
    if args.mesh:
        shards = max_divisible_shards(args.subjects)
        mesh = make_mesh(("subject",), (shards,))
        print(f"sharding subjects over {shards} of "
              f"{len(jax.devices())} devices")

    model = SRM(n_iter=15, features=args.features, mesh=mesh)
    model.fit(train)
    print(f"fit done; logprob {model.logprob_:.1f}")

    # project each subject's held-out data into shared space
    shared_test = model.transform(test)
    corrs = []
    for i in range(1, len(shared_test)):
        corrs.append(np.corrcoef(shared_test[0].ravel(),
                                 shared_test[i].ravel())[0, 1])
    print("held-out shared-space correlation with subject 0:",
          [round(c, 3) for c in corrs])


if __name__ == "__main__":
    main()
