"""Bayesian RSA: unbiased similarity structure under correlated noise.

TPU-native counterpart of the reference's `docs/examples/reprsimil/`
walkthrough: simulate multi-condition data whose point-estimate RSA is
biased by shared noise, then recover the true condition-by-condition
correlation structure with BRSA's marginal likelihood, optionally with a
Gaussian-Process prior over log-SNR (learned length scales).

Usage:
    python examples/brsa_representational_analysis.py [--backend cpu]
        [--gp]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def simulate(n_t=300, n_v=40, n_runs=2, seed=0):
    rng = np.random.RandomState(seed)
    n_c = 4
    design = np.zeros((n_t, n_c))
    for c in range(n_c):
        for o in rng.choice(n_t - 12, size=8, replace=False):
            design[o:o + 6, c] += 1.0
    from scipy.ndimage import gaussian_filter1d
    design = gaussian_filter1d(design, 2, axis=0)

    # two clusters of conditions (1,2) and (3,4)
    U = np.array([[1.0, 0.8, 0.0, 0.0],
                  [0.8, 1.0, 0.0, 0.0],
                  [0.0, 0.0, 1.0, 0.8],
                  [0.0, 0.0, 0.8, 1.0]])
    L = np.linalg.cholesky(U + 1e-9 * np.eye(n_c))
    coords = np.column_stack([np.linspace(0, 20, n_v),
                              np.zeros(n_v), np.zeros(n_v)])
    # spatially smooth SNR profile (what the GP prior models)
    log_snr = 1.0 * np.exp(-0.5 * (coords[:, 0] - 10.0) ** 2 / 9.0)
    snr = np.exp(log_snr - log_snr.mean())
    beta = (L @ rng.randn(n_c, n_v)) * snr
    onsets = np.arange(0, n_t, n_t // n_runs)[:n_runs]
    noise = rng.randn(n_t, n_v) + \
        0.8 * rng.randn(n_t, 1)  # shared noise biases naive RSA
    return design @ beta + noise, design, U, coords, onsets


def offdiag_corr(a, b):
    iu = np.triu_indices(a.shape[0], k=1)
    return float(np.corrcoef(a[iu], b[iu])[0, 1])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default=None)
    ap.add_argument("--voxels", type=int, default=40)
    ap.add_argument("--trs", type=int, default=300)
    ap.add_argument("--gp", action="store_true",
                    help="learn a GP prior over log-SNR")
    args = ap.parse_args()
    import jax
    if args.backend:
        jax.config.update("jax_platforms", args.backend)

    from brainiak_tpu.reprsimil.brsa import BRSA

    data, design, U, coords, onsets = simulate(n_t=args.trs,
                                               n_v=args.voxels)

    # naive RSA: correlation of least-squares beta estimates
    beta_hat = np.linalg.lstsq(design, data, rcond=None)[0]
    naive_c = np.corrcoef(beta_hat)

    model = BRSA(n_iter=1, auto_nuisance=True, n_nureg=2,
                 GP_space=args.gp, lbfgs_iters=150, random_state=0)
    model.fit(data, design, scan_onsets=onsets,
              coords=coords if args.gp else None)

    print("true-vs-naive RSA correlation:",
          round(offdiag_corr(naive_c, U), 3))
    print("true-vs-BRSA correlation:",
          round(offdiag_corr(model.C_, U), 3))
    if args.gp:
        print("learned GP spatial length scale:",
              round(float(model.lGPspace_), 2))
    ll, ll_null = model.score(data, design, scan_onsets=onsets)
    print("cross-validated log-likelihood margin:",
          round(float(ll - ll_null), 2))


if __name__ == "__main__":
    main()
