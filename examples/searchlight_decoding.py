"""Searchlight decoding: find a predictive region in a random volume.

TPU-native counterpart of the reference's
``examples/searchlight/example_searchlight.py`` (launched there under
``mpirun -n 4``): a Gaussian-kernel predictive pattern is injected at a
known point inside random data, and a searchlight sweep recovers it.
Both execution tiers run:

- the TRACED tier (``run_searchlight_jax``): a JAX-traceable
  correlation statistic compiled into one sweep over every active
  center, optionally sharded over a device mesh (the analog of the MPI
  block scatter);
- the HOST tier (``run_searchlight``): an arbitrary Python
  ``voxel_fn`` — here an sklearn SVM cross-validation, the reference
  example's workload.

Usage:
    python examples/searchlight_decoding.py [--backend cpu] [--mesh]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def make_data(dim, ntr, point, kernel_dim, rng):
    """Random data + labels with a predictive Gaussian kernel injected
    at ``point`` (the reference example's construction)."""
    data = rng.random_sample((dim, dim, dim, ntr)).astype(np.float32)
    labels = rng.choice([0.0, 1.0], (ntr,))
    kd = kernel_dim // 2
    grid = np.mgrid[-kd:kd + 1, -kd:kd + 1, -kd:kd + 1]
    kernel = np.exp(-(grid ** 2).sum(0).astype(np.float32))
    sl = tuple(slice(p - kd, p + kd + 1) for p in point)
    data[sl] += np.multiply.outer(kernel, labels)
    mask = np.zeros((dim, dim, dim), dtype=bool)
    center = (dim - 1) / 2.0
    xx, yy, zz = np.mgrid[:dim, :dim, :dim]
    mask[np.sqrt((xx - center) ** 2 + (yy - center) ** 2
                 + (zz - center) ** 2) < dim * 0.45] = True
    return data, labels, mask


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default=None)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--ntr", type=int, default=120)
    ap.add_argument("--rad", type=int, default=1)
    ap.add_argument("--mesh", action="store_true")
    args = ap.parse_args()
    import jax
    import jax.numpy as jnp
    if args.backend:
        jax.config.update("jax_platforms", args.backend)

    from brainiak_tpu.parallel.mesh import make_mesh
    from brainiak_tpu.searchlight.searchlight import Ball, Searchlight

    rng = np.random.RandomState(0)
    point = (args.dim // 2,) * 3
    data, labels, mask = make_data(args.dim, args.ntr, point, 5, rng)

    mesh = None
    if args.mesh:
        n = min(8, len(jax.devices()))
        mesh = make_mesh(("voxel",), (n,))
        print(f"mesh: {n} devices over the center sweep")

    # --- traced tier: label correlation statistic, one compiled sweep
    sl = Searchlight(sl_rad=args.rad, shape=Ball, mesh=mesh)
    sl.distribute([data], mask)
    sl.broadcast(jnp.asarray(labels))

    def corr_stat(patches, mask_patch, rad, bcast):
        x = patches[0] * mask_patch[..., None]
        ts = x.reshape(-1, x.shape[-1]).mean(0)
        ts = ts - ts.mean()
        y = bcast - bcast.mean()
        denom = jnp.sqrt(jnp.sum(ts ** 2) * jnp.sum(y ** 2)) + 1e-12
        return jnp.abs(jnp.sum(ts * y) / denom)

    vol = np.asarray(sl.run_searchlight_jax(corr_stat), dtype=np.float64)
    vol = np.where(np.isfinite(vol), vol, 0.0)
    best = np.unravel_index(np.argmax(vol), vol.shape)
    err = np.linalg.norm(np.subtract(best, point))
    print(f"traced tier: peak |corr| {vol.max():.3f} at {best}, "
          f"distance from injected point: {err:.1f}")
    assert err <= 2.0

    # --- host tier: the reference example's sklearn SVM workload
    from sklearn import model_selection, svm

    def svm_acc(subjects, sl_mask, rad, bcast):
        x = subjects[0][sl_mask, :].T  # [ntr, voxels_in_light]
        clf = svm.SVC(kernel="linear")
        return model_selection.cross_val_score(
            clf, x, np.asarray(bcast), cv=3, n_jobs=1).mean()

    host_sl = Searchlight(sl_rad=args.rad, shape=Ball)
    # keep the host tier quick: a thin slab around the injected point
    slab = np.zeros_like(mask)
    slab[:, :, point[2]] = mask[:, :, point[2]]
    host_sl.distribute([data], slab)
    host_sl.broadcast(labels)
    host_vol = host_sl.run_searchlight(svm_acc, pool_size=1)
    accs = np.array([[v if v is not None else 0.0 for v in row]
                     for row in host_vol[:, :, point[2]]])
    best2 = np.unravel_index(np.argmax(accs), accs.shape)
    print(f"host tier (SVM CV on one slab): peak accuracy "
          f"{accs.max():.3f} at {best2 + (point[2],)}")
    assert accs.max() > 0.6


if __name__ == "__main__":
    main()
