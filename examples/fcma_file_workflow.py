"""Dataset-shaped FCMA workflow: simulate -> files on disk -> analyze.

The reference's FCMA examples operate on a DIRECTORY of per-subject
NIfTI images plus an epoch-spec ``.npy`` and a mask (the layout its
``docs/examples/download_data.sh`` fetches).  Real downloads are not
possible here, so this walkthrough builds that exact dataset shape with
the simulator and then runs the same file-based pipeline a reference
user would:

1. fmrisim: per-subject 4-D volumes where the two task conditions
   differ in ROI CONNECTIVITY (FCMA's signal), written with
   ``io.save_as_nifti_file`` (suffix ``bet.nii.gz``), plus
   ``mask.nii.gz`` and an epoch file from
   ``fmrisim.export_epoch_file``;
2. ``io.load_images_from_dir`` / ``load_boolean_mask`` /
   ``load_labels`` -> ``prepare_fcma_data`` (epoch z-scoring);
3. ``VoxelSelector.run('svm')`` stage-1 screening, then a
   ``Classifier`` fit on the top voxels with held-out accuracy.

Usage:
    python examples/fcma_file_workflow.py [--backend cpu] [--keep DIR]
"""

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_dataset(out_dir, n_subjects, epochs_per_cond, epoch_len_trs,
                  dim, tr_duration=2.0):
    """Write <sub>_bet.nii.gz per subject + mask.nii.gz + epoch file."""
    from brainiak_tpu import io
    from brainiak_tpu.utils import fmrisim as sim

    rng = np.random.RandomState(0)
    n_epochs = 2 * epochs_per_cond
    trs = n_epochs * epoch_len_trs
    affine = np.diag([3.0, 3.0, 3.0, 1.0])

    # two ROIs; condition 1 couples them, condition 0 leaves them
    # independent — an activity-matched connectivity difference
    coords = np.transpose(np.nonzero(np.ones((dim, dim, dim))))
    roi_a = coords[(coords ** 2).sum(1) < (dim * 0.3) ** 2]
    corner = coords - np.array([dim - 1, dim - 1, dim - 1])
    roi_b = coords[(corner ** 2).sum(1) < (dim * 0.3) ** 2]

    stimfunctions = []
    for s in range(n_subjects):
        vol = np.zeros((dim, dim, dim, trs), dtype=np.float32)
        vol += rng.randn(dim, dim, dim, trs).astype(np.float32)
        for e in range(n_epochs):
            cond = e % 2
            t0, t1 = e * epoch_len_trs, (e + 1) * epoch_len_trs
            driver = rng.randn(epoch_len_trs).astype(np.float32)
            for vx, vy, vz in roi_a:
                vol[vx, vy, vz, t0:t1] += 1.5 * driver
            if cond == 1:
                for vx, vy, vz in roi_b:
                    vol[vx, vy, vz, t0:t1] += 1.5 * driver
            else:
                other = rng.randn(epoch_len_trs).astype(np.float32)
                for vx, vy, vz in roi_b:
                    vol[vx, vy, vz, t0:t1] += 1.5 * other
        io.save_as_nifti_file(
            vol, affine,
            os.path.join(out_dir, f"sub{s:02d}_bet.nii.gz"))

        # per-condition boxcar stimfunctions for the epoch file
        total_time = int(trs * tr_duration)
        onsets = {0: [], 1: []}
        for e in range(n_epochs):
            onsets[e % 2].append(e * epoch_len_trs * tr_duration)
        stim = np.hstack([
            sim.generate_stimfunction(
                onsets=onsets[c],
                event_durations=[epoch_len_trs * tr_duration],
                total_time=total_time)
            for c in (0, 1)])
        stimfunctions.append(stim)

    mask = np.ones((dim, dim, dim), dtype=np.int8)
    io.save_as_nifti_file(mask, affine,
                          os.path.join(out_dir, "mask.nii.gz"))
    sim.export_epoch_file(stimfunctions,
                          os.path.join(out_dir, "epoch_labels.npy"),
                          tr_duration)
    return roi_a, roi_b


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default=None)
    ap.add_argument("--subjects", type=int, default=4)
    ap.add_argument("--epochs-per-cond", type=int, default=4)
    ap.add_argument("--epoch-len", type=int, default=16)
    ap.add_argument("--dim", type=int, default=7)
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--keep", default=None,
                    help="write the dataset here instead of a tempdir")
    args = ap.parse_args()
    import jax
    if args.backend:
        jax.config.update("jax_platforms", args.backend)

    from brainiak_tpu import io
    from brainiak_tpu.fcma.classifier import Classifier
    from brainiak_tpu.fcma.preprocessing import prepare_fcma_data
    from brainiak_tpu.fcma.voxelselector import VoxelSelector

    work = args.keep or tempfile.mkdtemp(prefix="fcma_dataset_")
    os.makedirs(work, exist_ok=True)
    print(f"dataset directory: {work}")
    build_dataset(work, args.subjects, args.epochs_per_cond,
                  args.epoch_len, args.dim)
    files = sorted(os.listdir(work))
    print(f"files on disk: {files}")

    # --- the file-based pipeline a reference user runs -------------
    images = io.load_images_from_dir(work, suffix="bet.nii.gz")
    mask = io.load_boolean_mask(os.path.join(work, "mask.nii.gz"))
    conditions = io.load_labels(os.path.join(work, "epoch_labels.npy"))
    raw, _, labels = prepare_fcma_data(images, conditions, mask)
    n_epochs = len(labels)
    epochs_per_subj = n_epochs // args.subjects
    print(f"epochs: {n_epochs} ({epochs_per_subj}/subject), "
          f"voxels: {raw[0].shape[1]}")

    # hold one subject out of EVERYTHING (selection included): voxels
    # chosen using the test subject would leak into the held-out score
    test_subj = args.subjects - 1
    test_idx = [i for i in range(n_epochs)
                if i // epochs_per_subj == test_subj]
    train_idx = [i for i in range(n_epochs) if i not in test_idx]

    vs = VoxelSelector([labels[i] for i in train_idx], epochs_per_subj,
                       args.subjects - 1, [raw[i] for i in train_idx],
                       voxel_unit=64)
    results = vs.run("svm")
    top = [vid for vid, _ in results[:args.top]]
    print(f"top-{args.top} voxel mean CV accuracy: "
          f"{np.mean([acc for _, acc in results[:args.top]]):.3f}")
    from sklearn.svm import SVC

    sub = [(raw[i][:, top], raw[i]) for i in range(n_epochs)]
    clf = Classifier(SVC(kernel="precomputed"),
                     epochs_per_subj=epochs_per_subj)
    clf.fit([sub[i] for i in train_idx],
            [labels[i] for i in train_idx])
    acc = clf.score([sub[i] for i in test_idx],
                    [labels[i] for i in test_idx])
    print(f"held-out-subject classification accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
